"""The scheduling cycle.

Behavioral surface: reference pkg/scheduler/scheduler.go — one cycle =
Heads -> Snapshot -> nominate (flavor assignment + preemption targets) ->
ordered iteration (classical sort or fair-sharing tournament) ->
admit / preempt / skip -> requeue.

This host driver is exact and fully general. The batched TPU cycle
(kueue_tpu/models/batch_scheduler.py) executes the same decision procedure
for the dense common case and is differential-tested against this one.
"""

from __future__ import annotations

import enum
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from kueue_tpu.api.constants import (
    COND_ADMITTED,
    COND_EVICTED,
    COND_PREEMPTED,
    COND_QUOTA_RESERVED,
    EVICTED_BY_PREEMPTION,
    CheckState,
    REASON_PENDING,
    REASON_WAITING_FOR_QUOTA,
    RequeueReason,
)
from kueue_tpu.api.types import Admission, AdmissionCheckState, PodSetAssignment
from kueue_tpu.cache.cache import Cache
from kueue_tpu.cache.resource_node import compare_drs, dominant_resource_share
from kueue_tpu.cache.snapshot import ClusterQueueSnapshot, Snapshot
from kueue_tpu.core.resources import FlavorResource
from kueue_tpu.core.workload_info import (
    WorkloadInfo,
    has_quota_reservation,
    queue_order_timestamp,
    set_condition,
)
from kueue_tpu.metrics import tracing
from kueue_tpu.queue.manager import QueueManager
from kueue_tpu.scheduler.flavorassigner import (
    Assignment,
    FlavorAssigner,
    Mode,
)
from kueue_tpu.scheduler.preemption import (
    PreemptedWorkloads,
    Preemptor,
    Target,
    make_oracle,
)
from kueue_tpu.utils import features


class EntryStatus(str, enum.Enum):
    NOT_NOMINATED = "notNominated"
    NOMINATED = "nominated"
    SKIPPED = "skipped"
    ASSUMED = "assumed"
    EVICTED = "evicted"
    PREEMPTING = "preempting"


@dataclass
class Entry:
    """reference scheduler.go entry."""

    info: WorkloadInfo
    cq_snapshot: Optional[ClusterQueueSnapshot] = None
    assignment: Optional[Assignment] = None
    preemption_targets: List[Target] = field(default_factory=list)
    status: EntryStatus = EntryStatus.NOT_NOMINATED
    inadmissible_msg: str = ""
    requeue_reason: RequeueReason = RequeueReason.GENERIC
    quota_reserved_reason: str = ""


@dataclass
class CycleResult:
    admitted: List[str] = field(default_factory=list)
    preempting: List[str] = field(default_factory=list)
    preempted: List[str] = field(default_factory=list)
    skipped: List[str] = field(default_factory=list)
    inadmissible: List[str] = field(default_factory=list)
    # Per-CQ count of entries skipped because their preemption targets
    # overlapped or no longer fit (reference admission_cycle_preemption_skips).
    preemption_skips: Dict[str, int] = field(default_factory=dict)
    head_keys: frozenset = frozenset()
    duration_s: float = 0.0
    # Per-phase timings (reference scheduler.go:305-372 structured logs).
    snapshot_s: float = 0.0
    nominate_s: float = 0.0
    process_s: float = 0.0

    @property
    def success(self) -> bool:
        return bool(self.admitted)


class Scheduler:
    """reference scheduler.go:180."""

    def __init__(
        self,
        cache: Cache,
        queues: QueueManager,
        fair_sharing: bool = False,
        fair_strategies: Optional[List[str]] = None,
        clock: Callable[[], float] = time.monotonic,
        # Called for each preemption victim; controllers use this to drive
        # the eviction lifecycle. Default applies it inline.
        evict_fn: Optional[Callable[[WorkloadInfo, str, str], None]] = None,
    ) -> None:
        self.cache = cache
        self.queues = queues
        self.fair_sharing = fair_sharing
        self.preemptor = Preemptor(
            enable_fair_sharing=fair_sharing, fair_strategies=fair_strategies
        )
        self.clock = clock
        self.evict_fn = evict_fn or self._default_evict
        self.scheduling_cycle = 0

    # ------------------------------------------------------------------
    # cycle
    # ------------------------------------------------------------------

    def schedule(self) -> CycleResult:
        """One scheduling cycle (reference scheduler.go:300)."""
        self.scheduling_cycle += 1
        start = self.clock()
        result = CycleResult()

        with tracing.span("scheduler/cycle", cycle=self.scheduling_cycle):
            heads = self.queues.heads()
            result.head_keys = frozenset(h.key for h in heads)
            if not heads:
                result.duration_s = self.clock() - start
                return result

            t0 = self.clock()
            with tracing.span("scheduler/snapshot"):
                snapshot = self.cache.snapshot()
            result.snapshot_s = self.clock() - t0

            t0 = self.clock()
            with tracing.span("scheduler/nominate", heads=len(heads)):
                self._cycle_oracle = make_oracle(self.preemptor, snapshot)
                entries, inadmissible = self._nominate(heads, snapshot)
            result.nominate_s = self.clock() - t0

            iterator = self._make_iterator(entries, snapshot)

            t0 = self.clock()
            with tracing.span("scheduler/process", entries=len(entries)):
                preempted_workloads = PreemptedWorkloads()
                skipped_preemptions: Dict[str, int] = {}
                for e in iterator:
                    self._process_entry(
                        e, snapshot, preempted_workloads,
                        skipped_preemptions, result
                    )
            result.preemption_skips = skipped_preemptions
            result.process_s = self.clock() - t0

            # Requeue everything not assumed/evicted.
            with tracing.span("scheduler/requeue"):
                for e in entries:
                    if e.status == EntryStatus.ASSUMED:
                        result.admitted.append(e.info.key)
                    elif e.status == EntryStatus.PREEMPTING:
                        result.preempting.append(e.info.key)
                        # reference scheduler.go:287: the preemptor returns
                        # immediately and stays pinned at the head while its
                        # victims' evictions land.
                        e.requeue_reason = RequeueReason.PENDING_PREEMPTION
                        self._requeue_and_update(e)
                    elif e.status != EntryStatus.EVICTED:
                        result.skipped.append(e.info.key)
                        self._requeue_and_update(e)
                for e in inadmissible:
                    result.inadmissible.append(e.info.key)
                    self._requeue_and_update(e)

            result.duration_s = self.clock() - start
            if tracing.ENABLED:
                self._emit_cycle_metrics(result, len(entries))
        return result

    @staticmethod
    def _emit_cycle_metrics(result: CycleResult, n_entries: int) -> None:
        """Per-phase cycle histograms (reference scheduler.go:305-372
        structured per-phase logs; series follow the
        admission_attempt_duration_seconds family shape)."""
        tracing.observe(
            "scheduler_admission_cycle_duration_seconds", result.duration_s
        )
        for stage, dur in (
            ("snapshot", result.snapshot_s),
            ("nominate", result.nominate_s),
            ("process", result.process_s),
        ):
            tracing.observe(
                "scheduler_admission_cycle_stage_seconds", dur,
                {"stage": stage},
            )
        tracing.set_gauge("scheduler_admission_cycle_entries", n_entries)

    def schedule_all(self, max_cycles: int = 100000) -> int:
        """Run cycles until no progress is possible. Returns cycle count."""
        cycles = 0
        prev_no_progress_heads: Optional[frozenset] = None
        while cycles < max_cycles:
            result = self.schedule()
            cycles += 1
            if result.admitted or result.preempted:
                prev_no_progress_heads = None
                continue
            # No admission and no eviction: no capacity event happened. Stop
            # once the head set repeats (e.g. a StrictFIFO head that will
            # never fit) — the system is stable.
            if not result.head_keys or result.head_keys == prev_no_progress_heads:
                break
            prev_no_progress_heads = result.head_keys
        return cycles

    # ------------------------------------------------------------------
    # nomination
    # ------------------------------------------------------------------

    def _nominate(
        self, heads: Sequence[WorkloadInfo], snapshot: Snapshot
    ) -> Tuple[List[Entry], List[Entry]]:
        """reference scheduler.go:629."""
        entries: List[Entry] = []
        inadmissible: List[Entry] = []
        for info in heads:
            e = Entry(info=info)
            cqs = snapshot.cluster_queues.get(info.cluster_queue)
            e.cq_snapshot = cqs
            if self.cache.is_added(info.key) and not has_second_pass(info):
                continue
            if any(
                acs.state in (CheckState.RETRY, CheckState.REJECTED)
                for acs in info.obj.status.admission_checks
            ):
                e.inadmissible_msg = "The workload has failed admission checks"
                inadmissible.append(e)
            elif info.cluster_queue in snapshot.inactive_cluster_queues:
                e.inadmissible_msg = (
                    f"ClusterQueue {info.cluster_queue} is inactive"
                )
                inadmissible.append(e)
            elif self._local_queue_stopped(info):
                e.inadmissible_msg = (
                    f"LocalQueue {info.obj.queue_name} is stopped"
                )
                inadmissible.append(e)
            elif cqs is None:
                e.inadmissible_msg = (
                    f"ClusterQueue {info.cluster_queue} not found"
                )
                inadmissible.append(e)
            elif not self._namespace_allowed(cqs, info):
                e.inadmissible_msg = "Workload namespace doesn't match ClusterQueue selector"
                e.requeue_reason = RequeueReason.NAMESPACE_MISMATCH
                inadmissible.append(e)
            else:
                assignment, targets = self._get_assignments(info, snapshot)
                e.assignment = assignment
                e.preemption_targets = targets
                # Carry fungibility resume state on the Info so a requeued
                # workload retries from NextFlavorToTry (reference
                # recordAssignment).
                info.last_assignment = assignment.last_state
                entries.append(e)
        return entries, inadmissible

    def _local_queue_stopped(self, info: WorkloadInfo) -> bool:
        from kueue_tpu.api.constants import StopPolicy

        lq = self.cache.local_queues.get(
            f"{info.obj.namespace}/{info.obj.queue_name}"
        )
        return lq is not None and lq.stop_policy != StopPolicy.NONE

    def _namespace_allowed(
        self, cqs: ClusterQueueSnapshot, info: WorkloadInfo
    ) -> bool:
        """namespaceSelector evaluation (reference nominate
        ValidateAdmissibility): selects on namespace labels; the
        kubernetes.io/metadata.name label is always implied."""
        sel = cqs.spec.namespace_selector
        if sel is None:
            return True
        ns = self.cache.namespaces.get(info.obj.namespace)
        labels = dict(getattr(ns, "labels", {}) or {})
        labels.setdefault(
            "kubernetes.io/metadata.name", info.obj.namespace
        )
        from kueue_tpu.api.types import LabelSelector

        if isinstance(sel, LabelSelector):
            return sel.matches(labels)
        return all(labels.get(k) == v for k, v in sel.items())

    def _get_assignments(
        self, info: WorkloadInfo, snapshot: Snapshot
    ) -> Tuple[Assignment, List[Target]]:
        """reference scheduler.go:750,779."""
        cq = snapshot.cluster_queue(info.cluster_queue)
        oracle = getattr(self, "_cycle_oracle", None) or make_oracle(
            self.preemptor, snapshot
        )
        assigner = FlavorAssigner(
            info, cq, snapshot.resource_flavors, oracle=oracle,
            enable_fair_sharing=self.fair_sharing,
            tas_flavors=snapshot.tas_flavors,
            allow_delayed_tas=self._has_multikueue_check(cq),
            delay_tas=self._delay_tas(cq, info),
        )
        with tracing.span("scheduler/flavor_assignment",
                          workload=info.key):
            full = assigner.assign()
        mode = full.representative_mode()
        if tracing.ENABLED:
            tracing.inc("flavor_assignment_total", {"mode": mode.name})

        def tas_fits() -> bool:
            # TAS feasibility probe used by the preemptor's workloadFits
            # (reference preemption.go:637): placements must exist under the
            # snapshot's current (simulated) topology usage.
            return assigner.update_for_tas(full, simulate_empty=False,
                                           attach=False)

        has_tas = any(
            ps.topology_request is not None for ps in info.obj.pod_sets
        )
        if mode == Mode.FIT:
            return full, []
        if mode == Mode.PREEMPT:
            targets = self.preemptor.get_targets(
                info, full, snapshot,
                tas_fits=tas_fits if has_tas else None,
            )
            if targets:
                return full, targets

        if features.enabled("PartialAdmission") and can_be_partially_admitted(info):
            found = self._search_partial(info, snapshot, assigner)
            if found is not None:
                return found
        return full, []

    def _search_partial(
        self, info: WorkloadInfo, snapshot: Snapshot, assigner: FlavorAssigner
    ) -> Optional[Tuple[Assignment, List[Target]]]:
        """PodSetReducer.Search (reference
        flavorassigner/podset_reducer.go:67): binary search over a single
        scale axis shrinking every reducible podset proportionally."""
        pod_sets = info.obj.pod_sets
        full_counts = [ps.count for ps in pod_sets]
        deltas = [
            ps.count - (ps.min_count if ps.min_count is not None else ps.count)
            for ps in pod_sets
        ]
        total_delta = sum(deltas)
        if total_delta == 0:
            return None

        def counts_at(i: int) -> List[int]:
            return [
                full_counts[j] - (deltas[j] * i // total_delta)
                for j in range(len(pod_sets))
            ]

        def fits(counts: List[int]) -> Optional[Tuple[Assignment, List[Target]]]:
            assignment = assigner.assign(counts)
            mode = assignment.representative_mode()
            if mode == Mode.FIT:
                return assignment, []
            if mode == Mode.PREEMPT:
                targets = self.preemptor.get_targets(
                    info, assignment, snapshot
                )
                if targets:
                    return assignment, targets
            return None

        # sort.Search semantics: find smallest i in [0, total_delta] passing.
        lo, hi = 0, total_delta
        best: Optional[Tuple[Assignment, List[Target]]] = None
        while lo < hi:
            mid = (lo + hi) // 2
            r = fits(counts_at(mid))
            if r is not None:
                best = r
                hi = mid
            else:
                lo = mid + 1
        if best is None and lo <= total_delta:
            best = fits(counts_at(lo))
        return best

    # ------------------------------------------------------------------
    # iteration order
    # ------------------------------------------------------------------

    def _make_iterator(self, entries: List[Entry], snapshot: Snapshot):
        if self.fair_sharing:
            return self._fair_iterator(entries, snapshot)
        return self._classical_iterator(entries)

    def _classical_iterator(self, entries: List[Entry]):
        """reference scheduler.go:1005: quota-reserved first, fewest borrows,
        priority desc, FIFO."""

        def key(e: Entry):
            return (
                not has_quota_reservation(e.info.obj),
                e.assignment.borrows() if e.assignment else 0,
                -e.info.priority()
                if features.enabled("PrioritySortingWithinCohort")
                else 0,
                queue_order_timestamp(e.info.obj),
            )

        return iter(sorted(entries, key=key))

    def _fair_iterator(self, entries: List[Entry], snapshot: Snapshot):
        """Fair-sharing tournament (reference fair_sharing_iterator.go).

        computeDRS is incremental: the DRS chain of an entry is a pure
        function of the usage of the nodes on its CQ→root path (plus its
        own simulated usage), so each chain is cached and revalidated
        against those nodes' ``usage_gen`` counters — a winner's
        admission only invalidates chains that share path nodes with it,
        instead of recomputing every remaining entry per pop."""
        cq_to_entry: Dict[str, Entry] = {
            e.info.cluster_queue: e for e in entries
        }

        def assignment_usage(e: Entry):
            return e.assignment.usage if e.assignment else {}

        # Cohort-less CQs pop first, in entry order (no tournament).
        cohortless: List[str] = [
            name for name, e in cq_to_entry.items()
            if not snapshot.cluster_queues[name].has_parent()
        ]

        # Per-root buckets so one pop only touches its own cohort tree
        # (the reference's computeDRS is also root-scoped); `order` + a
        # skip pointer preserve the original pick-first-remaining order.
        order: List[str] = list(cq_to_entry)
        pos = 0
        buckets: Dict[int, Dict[str, Entry]] = {}
        root_of: Dict[str, object] = {}
        for name, e in cq_to_entry.items():
            cqs = snapshot.cluster_queues[name]
            if cqs.has_parent():
                r = cqs.node.root()
                root_of[name] = r
                buckets.setdefault(id(r), {})[name] = e

        # cq_name -> (dep_nodes, dep_gens, {id(ancestor): DRS}) where the
        # DRS stored at an ancestor is the share of the path node just
        # below it (with the entry's usage simulated in).
        chain_cache: Dict[str, tuple] = {}

        def chain_for(cq_name: str, e: Entry) -> Dict[int, object]:
            cqs = snapshot.cluster_queues[cq_name]
            hit = chain_cache.get(cq_name)
            if hit is not None:
                dep_nodes, dep_gens, chain = hit
                if all(
                    n.usage_gen == g for n, g in zip(dep_nodes, dep_gens)
                ):
                    return chain
            revert = cqs.simulate_usage_addition(assignment_usage(e))
            try:
                drs = dominant_resource_share(cqs.node, {})
                chain: Dict[int, object] = {}
                dep_nodes = [cqs.node]
                for anc in cqs.path_parent_to_root():
                    chain[id(anc)] = drs
                    if anc.parent is not None:
                        dep_nodes.append(anc)
                        drs = dominant_resource_share(anc, {})
            finally:
                revert()
            chain_cache[cq_name] = (
                dep_nodes, [n.usage_gen for n in dep_nodes], chain
            )
            return chain

        def pop_one() -> Entry:
            nonlocal pos
            while cohortless:
                name = cohortless.pop(0)
                e = cq_to_entry.pop(name, None)
                if e is not None:
                    return e
            while order[pos] not in cq_to_entry:
                pos += 1
            some_cq = order[pos]
            root = root_of[some_cq]
            bucket = buckets[id(root)]

            chains: Dict[str, Dict[int, object]] = {}
            for cq_name, e in bucket.items():
                chains[cq_name] = chain_for(cq_name, e)

            def less(a: Entry, b: Entry, parent_id: int) -> bool:
                a_drs = chains[a.info.cluster_queue][parent_id]
                b_drs = chains[b.info.cluster_queue][parent_id]
                c = compare_drs(a_drs, b_drs)
                if c != 0:
                    return c < 0
                if features.enabled("PrioritySortingWithinCohort"):
                    if a.info.priority() != b.info.priority():
                        return a.info.priority() > b.info.priority()
                return queue_order_timestamp(a.info.obj) < queue_order_timestamp(
                    b.info.obj
                )

            def tournament(cohort) -> Optional[Entry]:
                candidates: List[Entry] = []
                for child in cohort.children:
                    if child.is_cq:
                        e = cq_to_entry.get(child.name)
                        if e is not None:
                            candidates.append(e)
                    else:
                        c = tournament(child)
                        if c is not None:
                            candidates.append(c)
                if not candidates:
                    return None
                best = candidates[0]
                for cur in candidates[1:]:
                    if less(cur, best, id(cohort)):
                        best = cur
                return best

            winner = tournament(root)
            assert winner is not None
            wname = winner.info.cluster_queue
            del cq_to_entry[wname]
            del bucket[wname]
            chain_cache.pop(wname, None)
            return winner

        def gen():
            while cq_to_entry:
                yield pop_one()

        return gen()

    # ------------------------------------------------------------------
    # per-entry processing
    # ------------------------------------------------------------------

    def _process_entry(
        self,
        e: Entry,
        snapshot: Snapshot,
        preempted_workloads: PreemptedWorkloads,
        skipped_preemptions: Dict[str, int],
        result: CycleResult,
    ) -> None:
        """reference scheduler.go:385."""
        if not tracing.ENABLED:
            return self._process_entry_impl(
                e, snapshot, preempted_workloads, skipped_preemptions, result
            )
        with tracing.span("scheduler/process_entry", workload=e.info.key) as s:
            self._process_entry_impl(
                e, snapshot, preempted_workloads, skipped_preemptions, result
            )
            s.set_arg("status", e.status.value)

    def _process_entry_impl(
        self,
        e: Entry,
        snapshot: Snapshot,
        preempted_workloads: PreemptedWorkloads,
        skipped_preemptions: Dict[str, int],
        result: CycleResult,
    ) -> None:
        cq = snapshot.cluster_queue(e.info.cluster_queue)
        assert e.assignment is not None
        usage = dict(e.assignment.usage)
        fits = self._fits(snapshot, cq, usage, preempted_workloads,
                          e.preemption_targets)
        mode = e.assignment.representative_mode()

        if mode == Mode.NO_FIT:
            e.requeue_reason = RequeueReason.NO_FIT
            e.quota_reserved_reason = e.assignment.no_fit_reason or REASON_WAITING_FOR_QUOTA
            e.inadmissible_msg = "; ".join(
                r for ps in e.assignment.pod_sets for r in ps.status_reasons
            ) or "Workload didn't fit"
            return

        if mode == Mode.PREEMPT and e.info.obj.preemption_gates:
            # reference scheduler.go:436: preemption required but gated.
            e.status = EntryStatus.SKIPPED
            e.quota_reserved_reason = "AdmissionGated"
            e.inadmissible_msg = (
                "Workload requires preemption, but it's gated: "
                + ",".join(e.info.obj.preemption_gates)
            )
            return

        if mode == Mode.PREEMPT and not e.preemption_targets:
            e.requeue_reason = RequeueReason.PREEMPTION_NO_CANDIDATES
            e.quota_reserved_reason = REASON_WAITING_FOR_QUOTA
            e.inadmissible_msg = (
                "Workload requires preemption but no candidate targets found"
            )
            # reserveCapacityForUnreclaimablePreempt (scheduler.go:513).
            if not can_always_reclaim(cq):
                cq.add_usage(self._quota_resources_to_reserve(e, cq))
            return

        if preempted_workloads.has_any(e.preemption_targets):
            e.status = EntryStatus.SKIPPED
            e.inadmissible_msg = (
                "Workload has overlapping preemption targets with another workload"
            )
            e.quota_reserved_reason = REASON_WAITING_FOR_QUOTA
            skipped_preemptions[cq.name] = skipped_preemptions.get(cq.name, 0) + 1
            return

        if not fits:
            e.status = EntryStatus.SKIPPED
            e.inadmissible_msg = (
                "Workload no longer fits after processing another workload"
            )
            e.quota_reserved_reason = REASON_WAITING_FOR_QUOTA
            if mode == Mode.PREEMPT:
                skipped_preemptions[cq.name] = (
                    skipped_preemptions.get(cq.name, 0) + 1
                )
            return

        # TAS recompute: placements were chosen against cycle-start usage;
        # earlier entries may have taken the domains
        # (reference scheduler.go:409-414 updateAssignmentIfNeeded).
        if mode == Mode.FIT and self._has_tas_podsets(e):
            assigner = FlavorAssigner(
                e.info, cq, snapshot.resource_flavors,
                tas_flavors=snapshot.tas_flavors,
                allow_delayed_tas=self._has_multikueue_check(cq),
                delay_tas=self._delay_tas(cq, e.info),
            )
            if not assigner.update_for_tas(
                e.assignment, simulate_empty=False, attach=True
            ):
                e.status = EntryStatus.SKIPPED
                e.inadmissible_msg = (
                    "Topology placement no longer feasible after processing"
                    " another workload"
                )
                e.quota_reserved_reason = REASON_WAITING_FOR_QUOTA
                return

        preempted_workloads.insert(e.preemption_targets)
        cq.add_usage(usage)
        self._add_tas_usage(e, snapshot)

        if mode == Mode.PREEMPT:
            e.status = EntryStatus.PREEMPTING
            e.quota_reserved_reason = REASON_WAITING_FOR_QUOTA
            e.inadmissible_msg = (
                f"Waiting for {len(e.preemption_targets)} preempted workloads"
            )
            self._issue_preemptions(e, result)
            return

        e.status = EntryStatus.NOMINATED
        self._admit(e, cq)

    def _has_multikueue_check(self, cq: ClusterQueueSnapshot) -> bool:
        for ac_name in cq.spec.admission_checks:
            ac = self.cache.admission_checks.get(ac_name)
            if ac is not None and ac.controller_name == \
                    "kueue.x-k8s.io/multikueue":
                return True
        return False

    def _delay_tas(self, cq: ClusterQueueSnapshot, info: WorkloadInfo) -> bool:
        """reference tas_flavorassigner.go:106: topology placement is
        delayed outright for MultiKueue (the worker assigns), and on the
        FIRST pass when a ProvisioningRequest check gates admission (the
        nodes may not exist yet; the second pass assigns after
        provisioning)."""
        if self._has_multikueue_check(cq):
            return True
        if has_quota_reservation(info.obj):
            return False
        for ac_name in cq.spec.admission_checks:
            ac = self.cache.admission_checks.get(ac_name)
            if ac is not None and ac.controller_name == \
                    "kueue.x-k8s.io/provisioning-request":
                return True
        return False

    def _has_tas_podsets(self, e: Entry) -> bool:
        return any(
            ps.topology_request is not None for ps in e.info.obj.pod_sets
        )

    def _add_tas_usage(self, e: Entry, snapshot: Snapshot) -> None:
        """Reserve the chosen topology domains in the snapshot so later
        entries in this cycle see them taken."""
        assert e.assignment is not None
        for i, psa in enumerate(e.assignment.pod_sets):
            ta = psa.topology_assignment
            if ta is None or i >= len(e.info.obj.pod_sets):
                continue
            ps_spec = e.info.obj.pod_sets[i]
            flavor = next(iter(psa.flavors.values())).name if psa.flavors \
                else None
            tas = snapshot.tas_flavors.get(flavor)
            if tas is None:
                continue
            for values, count in ta.domains:
                leaf_id = "/".join(values)
                tas.add_usage(
                    leaf_id,
                    {r: v * count for r, v in ps_spec.requests.items()},
                )

    def _fits(
        self,
        snapshot: Snapshot,
        cq: ClusterQueueSnapshot,
        usage,
        preempted_workloads: PreemptedWorkloads,
        targets: List[Target],
    ) -> bool:
        """reference scheduler.go fits(): simulate removal of ALL victims
        designated earlier in this cycle plus this entry's targets, then
        check quota (victims stay in the snapshot until their async
        evictions land)."""
        by_key = {info.key: info for info in preempted_workloads.infos()}
        for t in targets:
            by_key[t.info.key] = t.info
        # Only remove victims still present in the snapshot (the inline
        # eviction path may already have removed cache state, but the
        # snapshot copy retains them).
        infos = []
        for info in by_key.values():
            cqs = snapshot.cluster_queues.get(info.cluster_queue)
            if cqs is not None and info.key in cqs.workloads:
                infos.append(info)
        revert = snapshot.simulate_workload_removal(infos)
        try:
            return cq.fits(usage)
        finally:
            revert()

    def _quota_resources_to_reserve(self, e: Entry, cq: ClusterQueueSnapshot):
        """reference scheduler.go:738 quotaResourcesToReserve."""
        assert e.assignment is not None
        if e.assignment.representative_mode() != Mode.PREEMPT:
            return e.assignment.usage
        reserved = {}
        for fr, usage in e.assignment.usage.items():
            cell = cq.quota_for(fr)
            node_usage = cq.node.usage.get(fr, 0)
            if e.assignment.borrowing > 0:
                if cell.borrowing_limit is None:
                    reserved[fr] = usage
                else:
                    reserved[fr] = min(
                        usage,
                        cell.nominal + cell.borrowing_limit - node_usage,
                    )
            else:
                reserved[fr] = max(0, min(usage, cell.nominal - node_usage))
        return reserved

    # ------------------------------------------------------------------
    # admission / preemption application
    # ------------------------------------------------------------------

    def _admit(self, e: Entry, cq: ClusterQueueSnapshot) -> None:
        """reference scheduler.go:890 admit + :954 assumeWorkload."""
        assert e.assignment is not None
        with tracing.span("scheduler/admit", workload=e.info.key):
            self._admit_impl(e, cq)

    def _admit_impl(self, e: Entry, cq: ClusterQueueSnapshot) -> None:
        assert e.assignment is not None
        now = self.clock()
        admission = Admission(
            cluster_queue=e.info.cluster_queue,
            pod_set_assignments=[
                PodSetAssignment(
                    name=psa.name,
                    flavors={r: fa.name for r, fa in psa.flavors.items()},
                    resource_usage=dict(psa.requests),
                    count=psa.count,
                    topology_assignment=psa.topology_assignment,
                    delayed_topology_request=psa.delayed_topology_request,
                )
                for psa in e.assignment.pod_sets
            ],
        )
        wl = e.info.obj
        wl.status.admission = admission
        set_condition(
            wl, COND_QUOTA_RESERVED, True, "QuotaReserved",
            f"Quota reserved in ClusterQueue {cq.name}", now,
        )
        # Apply assignment into the info's podset flavors for usage tracking.
        for ps, psa in zip(e.info.total_requests, e.assignment.pod_sets):
            if psa.count != ps.count:
                scaled = ps.scaled_to(psa.count)
                ps.requests = scaled.requests
                ps.count = psa.count
            ps.flavors = {r: fa.name for r, fa in psa.flavors.items()}
        e.info.last_assignment = e.assignment.last_state

        checks = cq.spec.admission_checks
        if checks:
            wl.status.admission_checks = [
                AdmissionCheckState(name=c, state=CheckState.PENDING)
                for c in checks
            ]
        else:
            set_condition(
                wl, COND_ADMITTED, True, "Admitted",
                "The workload is admitted", now,
            )
        self.cache.assume_workload(e.info)
        e.status = EntryStatus.ASSUMED

    def _issue_preemptions(self, e: Entry, result: CycleResult) -> None:
        """reference preemption.go:198 IssuePreemptions."""
        for t in e.preemption_targets:
            self.evict_fn(t.info, EVICTED_BY_PREEMPTION, t.reason)
            result.preempted.append(t.info.key)

    def _default_evict(
        self, victim: WorkloadInfo, eviction_reason: str, preemption_reason: str
    ) -> None:
        """Inline eviction: conditions + cache removal + requeue (the
        controllers module performs this asynchronously in the full stack;
        reference pkg/workload/evict)."""
        now = self.clock()
        wl = victim.obj
        set_condition(wl, COND_EVICTED, True, eviction_reason,
                      "Preempted to accommodate a workload", now)
        set_condition(wl, COND_PREEMPTED, True, preemption_reason,
                      "Preempted", now)
        set_condition(wl, COND_QUOTA_RESERVED, False, "Pending",
                      "Evicted by preemption", now)
        set_condition(wl, COND_ADMITTED, False, "NoReservation",
                      "The workload has no reservation", now)
        wl.status.admission = None
        wl.status.admission_checks = []
        self.cache.delete_workload(victim.key)
        # Re-enter the queues with eviction-time ordering.
        fresh = WorkloadInfo(wl, victim.cluster_queue)
        self.queues.requeue_workload(fresh, RequeueReason.GENERIC)
        self.queues.queue_inadmissible_workloads()

    def _requeue_and_update(self, e: Entry) -> None:
        """reference scheduler.go:1050."""
        if (
            e.status != EntryStatus.NOT_NOMINATED
            and e.requeue_reason == RequeueReason.GENERIC
        ):
            e.requeue_reason = RequeueReason.FAILED_AFTER_NOMINATION
        self.queues.requeue_workload(e.info, e.requeue_reason)
        if e.status in (EntryStatus.NOT_NOMINATED, EntryStatus.SKIPPED):
            now = self.clock()
            wl = e.info.obj
            set_condition(
                wl, COND_QUOTA_RESERVED, False,
                e.quota_reserved_reason or REASON_PENDING,
                e.inadmissible_msg, now,
            )


def can_be_partially_admitted(info: WorkloadInfo) -> bool:
    return any(
        ps.min_count is not None and ps.min_count < ps.count
        for ps in info.obj.pod_sets
    )


def can_always_reclaim(cq: ClusterQueueSnapshot) -> bool:
    """reference preemption CanAlwaysReclaim: with ReclaimWithinCohort=Any
    the CQ can always take back its nominal quota."""
    from kueue_tpu.api.constants import PreemptionPolicy

    return cq.spec.preemption.reclaim_within_cohort == PreemptionPolicy.ANY


def has_second_pass(info: WorkloadInfo) -> bool:
    """reference workload.go:889 NeedsSecondPass. Here the second pass is
    tick-driven (Manager._second_pass_assign resolves delayed topology
    requests; controllers/tas_failure.py handles the node-failure case),
    so reserved workloads never re-enter the quota cycle."""
    return False
