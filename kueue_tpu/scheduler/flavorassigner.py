"""Host-exact flavor assigner.

Behavioral surface: reference pkg/scheduler/flavorassigner/flavorassigner.go
— per (podset-group × resource-group) search over the ClusterQueue's flavor
list, yielding per-resource FlavorAssignments with a Fit/Preempt/NoFit mode,
borrow level (cohort-subtree height), flavor-fungibility stop rules, and the
preemption-oracle probe for Preempt mode.

This is the general/fallback path and the differential-test oracle for the
vectorized device assigner (`nominate` in
kueue_tpu/models/batch_scheduler.py, which handles the dense common case:
single-podset workloads, one resource group).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from kueue_tpu.api.constants import (
    BorrowWithinCohortPolicy,
    FlavorFungibilityPolicy,
    FlavorFungibilityPreference,
    PreemptionPolicy,
    REASON_EXCEEDS_MAX_QUOTA,
    REASON_NO_MATCHING_FLAVOR,
    REASON_WAITING_FOR_QUOTA,
)
from kueue_tpu.api.types import FlavorFungibility, PodSet, ResourceFlavor
from kueue_tpu.cache.resource_node import find_height_of_lowest_subtree_that_fits
from kueue_tpu.cache.snapshot import ClusterQueueSnapshot
from kueue_tpu.core.resources import FlavorResource, FlavorResourceQuantities, sat_add
from kueue_tpu.core.workload_info import (
    AssignmentClusterQueueState,
    PodSetResources,
    WorkloadInfo,
)
from kueue_tpu.metrics import tracing


class Mode(enum.IntEnum):
    """FlavorAssignmentMode, ordered worst to best
    (flavorassigner.go:408-423)."""

    NO_FIT = 0
    PREEMPT = 1
    FIT = 2


class PMode(enum.IntEnum):
    """granular preemptionMode (flavorassigner.go:472-482)."""

    NO_FIT = 0
    NO_CANDIDATES = 1  # preemption possible but no targets found
    PREEMPT = 2
    RECLAIM = 3
    FIT = 4

    def to_mode(self) -> Mode:
        if self == PMode.NO_FIT:
            return Mode.NO_FIT
        if self == PMode.FIT:
            return Mode.FIT
        return Mode.PREEMPT


@dataclass
class GranularMode:
    """(preemptionMode, borrowingLevel) (flavorassigner.go:459)."""

    pmode: PMode = PMode.NO_FIT
    borrowing: int = 1 << 30

    def is_preempt_mode(self) -> bool:
        return self.pmode in (PMode.PREEMPT, PMode.RECLAIM)


def is_lws_group(pod_sets) -> bool:
    """The whole workload is ONE two-podset LWS group: both podsets carry
    a topology_request with a podset_group_name (webhook-validated shape,
    utils/validation.py). The ONE copy of the group-membership test —
    the device encoder, the driver decoder and the compatibility gate
    all key off it, so leader tensors and leader decode stay in step."""
    return len(pod_sets) == 2 and all(
        p.topology_request is not None
        and p.topology_request.podset_group_name for p in pod_sets
    )


def find_leader_and_workers(pod_sets, members):
    """Two-podset group: leader = the smaller-count member, members[1]
    on ties (reference findLeaderAndWorkers :726-737). Returns
    (leader_i or None, worker_i). The ONE copy of this rule — the
    device encode and driver decode both key worker/leader roles off it,
    so the worker TA and leader TA attach to the right podsets."""
    leader_i = None
    worker_i = members[0]
    if len(members) > 1:
        leader_i = members[1]
        if pod_sets[leader_i].count > pod_sets[worker_i].count:
            leader_i, worker_i = worker_i, leader_i
    return leader_i, worker_i


def worst_mode() -> GranularMode:
    return GranularMode(PMode.NO_FIT, 1 << 30)


def best_mode() -> GranularMode:
    return GranularMode(PMode.FIT, 0)


def is_preferred(a: GranularMode, b: GranularMode, fungibility: FlavorFungibility) -> bool:
    """True if a is better than b under the fungibility preference
    (flavorassigner.go:485-516)."""
    if a.pmode == PMode.NO_FIT:
        return False
    if b.pmode == PMode.NO_FIT:
        return True

    def borrowing_over_preemption() -> bool:
        if a.pmode != b.pmode:
            return a.pmode > b.pmode
        return a.borrowing < b.borrowing

    def preemption_over_borrowing() -> bool:
        if a.borrowing != b.borrowing:
            return a.borrowing < b.borrowing
        return a.pmode > b.pmode

    if fungibility.preference == FlavorFungibilityPreference.PREEMPTION_OVER_BORROWING:
        return preemption_over_borrowing()
    return borrowing_over_preemption()


def should_try_next_flavor(
    mode: GranularMode, fungibility: FlavorFungibility
) -> bool:
    """flavorassigner.go:1142-1159."""
    if mode.pmode in (PMode.NO_FIT, PMode.NO_CANDIDATES):
        return True
    if mode.is_preempt_mode() and (
        fungibility.when_can_preempt == FlavorFungibilityPolicy.TRY_NEXT_FLAVOR
    ):
        return True
    if mode.borrowing > 0 and (
        fungibility.when_can_borrow == FlavorFungibilityPolicy.TRY_NEXT_FLAVOR
    ):
        return True
    return False


@dataclass
class FlavorAssignment:
    name: str
    mode: Mode
    tried_flavor_idx: int = -1
    borrow: int = 0


@dataclass
class PodSetAssignmentResult:
    name: str
    flavors: Dict[str, FlavorAssignment] = field(default_factory=dict)
    requests: Dict[str, int] = field(default_factory=dict)
    count: int = 0
    status_reasons: List[str] = field(default_factory=list)
    no_fit_reason: str = ""
    topology_assignment: object = None  # api.types.TopologyAssignment
    delayed_topology_request: bool = False

    def representative_mode(self) -> Mode:
        if not self.flavors:
            return Mode.NO_FIT if self.requests else Mode.FIT
        return Mode(min(fa.mode for fa in self.flavors.values()))


@dataclass
class Assignment:
    """reference flavorassigner.go Assignment struct."""

    pod_sets: List[PodSetAssignmentResult] = field(default_factory=list)
    borrowing: int = 0
    usage: FlavorResourceQuantities = field(default_factory=dict)
    last_state: AssignmentClusterQueueState = field(
        default_factory=AssignmentClusterQueueState
    )
    no_fit_reason: str = ""

    def representative_mode(self) -> Mode:
        if not self.pod_sets:
            return Mode.NO_FIT
        return Mode(min(ps.representative_mode() for ps in self.pod_sets))

    def borrows(self) -> int:
        return self.borrowing

    def total_requests_for(self, wl: WorkloadInfo) -> FlavorResourceQuantities:
        return dict(self.usage)


# Oracle callback: (cq, wl, fr, quantity) ->
#   (possibility: Optional[str in {"Preempt","Reclaim","NoCandidates"}], borrow)
PreemptionOracleFn = Callable[
    [ClusterQueueSnapshot, WorkloadInfo, FlavorResource, int],
    Tuple[str, int],
]


class FlavorAssigner:
    """reference flavorassigner.go:584."""

    def __init__(
        self,
        wl: WorkloadInfo,
        cq: ClusterQueueSnapshot,
        resource_flavors: Dict[str, ResourceFlavor],
        oracle: Optional[PreemptionOracleFn] = None,
        enable_fair_sharing: bool = False,
        tas_flavors: Optional[Dict[str, object]] = None,
        allow_delayed_tas: bool = False,
        delay_tas: bool = False,
    ) -> None:
        self.wl = wl
        self.cq = cq
        self.resource_flavors = resource_flavors
        self.oracle = oracle
        self.enable_fair_sharing = enable_fair_sharing
        self.tas_flavors = tas_flavors or {}
        # MultiKueue: topology placement happens on the target cluster
        # (reference delayedTopologyRequest).
        self.allow_delayed_tas = allow_delayed_tas
        # reference tas_flavorassigner.go:106: delay placement outright —
        # MultiKueue (worker assigns) or first pass with a
        # ProvisioningRequest check (topology assigned after provisioning,
        # in the scheduler's second pass).
        self.delay_tas = delay_tas

    # -- public entry -------------------------------------------------------

    def assign(self, counts: Optional[Sequence[int]] = None) -> Assignment:
        if (
            self.wl.last_assignment is not None
            and self.cq.allocatable_generation
            > self.wl.last_assignment.cluster_queue_generation
        ):
            self.wl.last_assignment = None
        return self._assign_flavors(counts)

    # -- core ---------------------------------------------------------------

    def _assign_flavors(self, counts: Optional[Sequence[int]]) -> Assignment:
        if counts is None:
            requests = [ps for ps in self.wl.total_requests]
        else:
            requests = [
                ps.scaled_to(counts[i])
                for i, ps in enumerate(self.wl.total_requests)
            ]

        assignment = Assignment(
            last_state=AssignmentClusterQueueState(
                cluster_queue_generation=self.cq.allocatable_generation
            )
        )

        # Group podsets (TAS podset-groups collapse to one joint request;
        # default: one group per podset). reference flavorassigner.go:712-718.
        groups: Dict[str, List[Tuple[int, PodSetResources]]] = {}
        order: List[str] = []
        for i, ps in enumerate(requests):
            key = str(i)
            tr = self.wl.obj.pod_sets[i].topology_request
            if tr is not None and tr.podset_group_name:
                key = tr.podset_group_name
            if key not in groups:
                groups[key] = []
                order.append(key)
            groups[key].append((i, ps))

        for key in order:
            group = groups[key]
            group_requests: Dict[str, int] = {}
            ps_ids = [i for i, _ in group]
            for _, ps in group:
                for res, v in ps.requests.items():
                    group_requests[res] = group_requests.get(res, 0) + v

            group_flavors: Dict[str, FlavorAssignment] = {}
            group_reasons: List[str] = []
            group_no_fit_reason = ""
            failed = False
            for res in sorted(group_requests):
                if self.cq.rg_by_resource(res) is None:
                    if group_requests[res] == 0:
                        continue
                if res in group_flavors:
                    continue  # already assigned with its resource group
                flavors, reasons, nf_reason = self._find_flavor_for_podsets(
                    ps_ids, group_requests, res, assignment.usage
                )
                group_reasons.extend(reasons)
                group_no_fit_reason = nf_reason or group_no_fit_reason
                if not flavors and group_requests:
                    # The whole group's flavors are dropped so the podset
                    # reads NoFit — a partial assignment must not mask the
                    # failed resource (flavorassigner.go:757 groupFlavors
                    # = nil).
                    group_flavors = {}
                    failed = True
                    break
                group_flavors.update(flavors)

            for i, ps in group:
                psa = PodSetAssignmentResult(
                    name=ps.name,
                    flavors={
                        r: group_flavors[r]
                        for r in ps.requests
                        if r in group_flavors
                    },
                    requests=dict(ps.requests),
                    count=ps.count,
                    status_reasons=list(group_reasons),
                    no_fit_reason=group_no_fit_reason,
                )
                self._append(assignment, ps, psa)
            if failed:
                return assignment

        # TAS hook (reference flavorassigner.go:796-835): try the topology
        # placement for Fit assignments; downgrade to Preempt on failure;
        # for Preempt assignments verify feasibility on an empty cluster,
        # else NoFit.
        if self.tas_flavors and assignment.representative_mode() == Mode.FIT:
            if not self.update_for_tas(assignment, simulate_empty=False,
                                       attach=True):
                for psa in assignment.pod_sets:
                    for fa in psa.flavors.values():
                        if fa.mode == Mode.FIT:
                            fa.mode = Mode.PREEMPT
        if self.tas_flavors and assignment.representative_mode() == Mode.PREEMPT:
            if not self.update_for_tas(assignment, simulate_empty=True,
                                       attach=False):
                for psa in assignment.pod_sets:
                    for fa in psa.flavors.values():
                        fa.mode = Mode.NO_FIT
        return assignment

    def update_for_tas(
        self, assignment: "Assignment", simulate_empty: bool,
        attach: bool,
    ) -> bool:
        """Find topology placements for every TAS podset of the
        assignment. Podsets sharing a podset_group_name place as ONE
        request: for a two-podset group the smaller-count podset is the
        LWS leader whose single pod must land with the workers
        (reference tas_flavor_snapshot.go:651-737 findLeaderAndWorkers;
        leaderRequests = leader pod requests + OnePodRequest :963-965).
        Accumulates assumed usage across groups so sibling podsets of one
        workload don't double-book domains. Returns False if any TAS
        podset has no placement."""
        if not tracing.ENABLED:
            return self._update_for_tas_impl(
                assignment, simulate_empty, attach
            )
        with tracing.span(
            "scheduler/tas_placement", workload=self.wl.key,
            simulate_empty=simulate_empty,
        ) as s:
            ok = self._update_for_tas_impl(assignment, simulate_empty, attach)
            s.set_arg("ok", ok)
            tracing.inc("tas_placement_total", {"ok": str(ok).lower()})
            return ok

    def _update_for_tas_impl(
        self, assignment: "Assignment", simulate_empty: bool,
        attach: bool,
    ) -> bool:
        from kueue_tpu.tas.snapshot import PlacementRequest

        # Group TAS podsets (reference :651: index-keyed unless a
        # podset_group_name joins them).
        groups: List[List[int]] = []
        group_of: Dict[str, int] = {}
        for i, psa in enumerate(assignment.pod_sets):
            if i >= len(self.wl.obj.pod_sets):
                continue
            ps = self.wl.obj.pod_sets[i]
            tr = ps.topology_request
            if tr is None or not psa.flavors:
                continue
            gname = getattr(tr, "podset_group_name", None)
            if gname and gname in group_of:
                groups[group_of[gname]].append(i)
                continue
            if gname:
                group_of[gname] = len(groups)
            groups.append([i])

        assumed: Dict[str, Dict[str, Dict[str, int]]] = {}
        for members in groups:
            leader_i, worker_i = find_leader_and_workers(
                self.wl.obj.pod_sets, members
            )
            ps = self.wl.obj.pod_sets[worker_i]
            psa = assignment.pod_sets[worker_i]
            tr = ps.topology_request
            if self.delay_tas:
                for i in members:
                    assignment.pod_sets[i].delayed_topology_request = True
                continue
            flavor_name = next(iter(psa.flavors.values())).name
            tas = self.tas_flavors.get(flavor_name)
            if tas is None:
                if self.allow_delayed_tas:
                    for i in members:
                        assignment.pod_sets[i].delayed_topology_request = \
                            True
                    continue
                return False
            leader_requests = None
            if leader_i is not None:
                lr = dict(self.wl.obj.pod_sets[leader_i].requests)
                # OnePodRequest analog (reference :965): the leader
                # occupies one pod slot — only meaningful on fleets that
                # track a "pods" node capacity (k8s nodes always do; a
                # bare TPU fleet may not, and an unbacked request would
                # zero the leader's fit count).
                if "pods" in tas._res_index:
                    lr["pods"] = lr.get("pods", 0) + 1
                leader_requests = lr
            req = PlacementRequest(
                count=psa.count,
                single_pod_requests=dict(ps.requests),
                required_level=tr.required_level,
                preferred_level=tr.preferred_level,
                unconstrained=tr.unconstrained,
                slice_size=tr.slice_size or 1,
                slice_required_level=tr.slice_required_level,
                slice_layers=list(getattr(tr, "slice_layers", [])),
                node_selector=dict(ps.node_selector),
                tolerations=list(ps.tolerations),
                balanced=getattr(tr, "balanced", False),
                leader_requests=leader_requests,
            )
            ta, leader_ta, reason = tas.find_topology_assignment(
                req, simulate_empty=simulate_empty,
                assumed_usage=assumed.get(flavor_name),
            )
            if reason:
                psa.status_reasons.append(reason)
                return False
            if attach:
                psa.topology_assignment = ta
                if leader_i is not None:
                    assignment.pod_sets[leader_i].topology_assignment = \
                        leader_ta
            # Track assumed usage for subsequent groups.
            dst_f = assumed.setdefault(flavor_name, {})
            for values, count in ta.domains:
                leaf_id = "/".join(values)
                dst = dst_f.setdefault(leaf_id, {})
                for res, v in ps.requests.items():
                    dst[res] = dst.get(res, 0) + v * count
            if leader_i is not None and leader_ta is not None:
                lreq = self.wl.obj.pod_sets[leader_i].requests
                for values, count in leader_ta.domains:
                    leaf_id = "/".join(values)
                    dst = dst_f.setdefault(leaf_id, {})
                    for res, v in lreq.items():
                        dst[res] = dst.get(res, 0) + v * count
        return True

    def _append(
        self,
        assignment: Assignment,
        ps: PodSetResources,
        psa: PodSetAssignmentResult,
    ) -> None:
        """reference flavorassigner.go:901-922."""
        flavor_idx: Dict[str, int] = {}
        assignment.pod_sets.append(psa)
        for res, fa in psa.flavors.items():
            if fa.borrow > assignment.borrowing:
                assignment.borrowing = fa.borrow
            fr = FlavorResource(fa.name, res)
            assignment.usage[fr] = sat_add(
                assignment.usage.get(fr, 0), psa.requests.get(res, 0)
            )
            flavor_idx[res] = fa.tried_flavor_idx
        assignment.last_state.last_tried_flavor_idx.append(flavor_idx)

    # -- per-resource-group flavor search -----------------------------------

    def _find_flavor_for_podsets(
        self,
        ps_ids: List[int],
        requests: Dict[str, int],
        res_name: str,
        assignment_usage: FlavorResourceQuantities,
    ) -> Tuple[Dict[str, FlavorAssignment], List[str], str]:
        """reference flavorassigner.go:946-1089. Returns
        (assignments, reasons, no_fit_reason)."""
        rg = self.cq.rg_by_resource(res_name)
        if rg is None:
            return {}, [f"resource {res_name} unavailable in ClusterQueue"], (
                REASON_NO_MATCHING_FLAVOR
            )
        reasons: List[str] = []
        no_fit_reason = ""
        covered = {
            r: v for r, v in requests.items() if r in rg.covered_resources
        }

        pod_sets = [self.wl.obj.pod_sets[i] for i in ps_ids]
        best: Dict[str, FlavorAssignment] = {}
        best_mode = worst_mode()
        fungibility = self.cq.spec.flavor_fungibility

        flavor_names = [fq.name for fq in rg.flavors]
        attempted_idx = -1
        start = 0
        if self.wl.last_assignment is not None:
            start = self.wl.last_assignment.next_flavor_to_try(
                ps_ids[0], res_name
            )
        allowed = self.wl.obj.labels.get(
            "kueue.x-k8s.io/allowed-resource-flavor"
        )
        for idx in range(start, len(flavor_names)):
            attempted_idx = idx
            f_name = flavor_names[idx]
            # ConcurrentAdmission variants race one flavor each
            # (reference flavorassigner.go:981).
            if allowed is not None and f_name != allowed:
                reasons.append(
                    f"skipping flavor {f_name}: variant restricted to"
                    f" {allowed}"
                )
                continue
            flavor_ok, why = self._check_flavor_for_podsets(f_name, pod_sets)
            if not flavor_ok:
                reasons.append(why)
                no_fit_reason = no_fit_reason or REASON_NO_MATCHING_FLAVOR
                continue

            assignments: Dict[str, FlavorAssignment] = {}
            representative = best_mode_const()
            for r_name in sorted(covered):
                val = covered[r_name]
                fr = FlavorResource(f_name, r_name)
                pmode, borrow, r_reasons, r_nf = self._fits_resource_quota(
                    fr, assignment_usage.get(fr, 0), val
                )
                reasons.extend(r_reasons)
                if r_nf:
                    no_fit_reason = _most_severe(no_fit_reason, r_nf)
                mode = GranularMode(pmode, borrow)
                if is_preferred(representative, mode, fungibility):
                    representative = mode
                if representative.pmode == PMode.NO_FIT:
                    break
                assignments[r_name] = FlavorAssignment(
                    name=f_name, mode=pmode.to_mode(), borrow=borrow
                )

            if not should_try_next_flavor(representative, fungibility):
                best = assignments
                best_mode = representative
                break
            if is_preferred(representative, best_mode, fungibility):
                best = assignments
                best_mode = representative

        for fa in best.values():
            fa.tried_flavor_idx = (
                -1 if attempted_idx == len(flavor_names) - 1 else attempted_idx
            )
        if best_mode.pmode == PMode.FIT:
            return best, [], ""
        return best, reasons, no_fit_reason

    def _check_flavor_for_podsets(
        self, flavor_name: str, pod_sets: List[PodSet]
    ) -> Tuple[bool, str]:
        """Taints/tolerations + node-affinity gate
        (flavorassigner.go:1091-1140)."""
        flavor = self.resource_flavors.get(flavor_name)
        if flavor is None:
            return False, f"flavor {flavor_name} not found"
        label_keys = set(flavor.node_labels)
        for ps in pod_sets:
            # checkPodSetAndFlavorMatchForTAS (reference
            # tas_flavorassigner.go): a podset explicitly requesting TAS
            # needs a flavor with a topology.
            if ps.topology_request is not None and not flavor.topology_name:
                if self.allow_delayed_tas:
                    continue  # placement deferred to the target cluster
                return False, (
                    f"flavor {flavor_name} does not support "
                    "TopologyAwareScheduling"
                )
            for taint in flavor.node_taints:
                if taint.effect not in ("NoSchedule", "NoExecute"):
                    continue
                tolerations = list(ps.tolerations) + list(flavor.tolerations)
                if not any(t.tolerates(taint) for t in tolerations):
                    return False, (
                        f"untolerated taint {taint.key} in flavor {flavor_name}"
                    )
            # nodeSelector terms restricted to this flavor's own label keys.
            for k, v in ps.node_selector.items():
                if k in label_keys and flavor.node_labels.get(k) != v:
                    return False, (
                        f"flavor {flavor_name} doesn't match node affinity"
                    )
            # Affinity expressions referencing keys other flavors define are
            # ignored for this flavor; a term emptied this way matches all.
            for expr in ps.required_affinity:
                if expr.key in label_keys and not expr.matches(
                    flavor.node_labels
                ):
                    return False, (
                        f"flavor {flavor_name} doesn't match node affinity"
                    )
        return True, ""

    def _fits_resource_quota(
        self, fr: FlavorResource, assumed_usage: int, request: int
    ) -> Tuple[PMode, int, List[str], str]:
        """flavorassigner.go:1213-1263."""
        reasons: List[str] = []
        available = self.cq.available(fr)
        max_capacity = self.cq.potential_available(fr)
        val = sat_add(assumed_usage, request)

        if val > max_capacity:
            reasons.append(
                f"insufficient quota for {fr.resource} in flavor {fr.flavor},"
                f" request {val} > maximum capacity {max_capacity}"
            )
            return PMode.NO_FIT, 0, reasons, REASON_EXCEEDS_MAX_QUOTA

        borrow, may_reclaim = find_height_of_lowest_subtree_that_fits(
            self.cq.node, fr, val
        )
        if val <= available:
            return PMode.FIT, borrow, [], ""

        reasons.append(
            f"insufficient unused quota for {fr.resource} in flavor"
            f" {fr.flavor}, {val - available} more needed"
        )
        nominal = self.cq.quota_for(fr).nominal
        if nominal >= val or may_reclaim or self._can_preempt_while_borrowing():
            if self.oracle is None:
                return PMode.NO_CANDIDATES, borrow, reasons, ""
            possibility, borrow_after = self.oracle(
                self.cq, self.wl, fr, val
            )
            pmode = {
                "Preempt": PMode.PREEMPT,
                "Reclaim": PMode.RECLAIM,
                "NoCandidates": PMode.NO_CANDIDATES,
            }[possibility]
            return pmode, borrow_after, reasons, ""
        return PMode.NO_FIT, borrow, reasons, REASON_WAITING_FOR_QUOTA

    def _can_preempt_while_borrowing(self) -> bool:
        """flavorassigner.go:1265."""
        p = self.cq.spec.preemption
        return (
            p.borrow_within_cohort.policy != BorrowWithinCohortPolicy.NEVER
        ) or (
            self.enable_fair_sharing
            and p.reclaim_within_cohort != PreemptionPolicy.NEVER
        )


def best_mode_const() -> GranularMode:
    return GranularMode(PMode.FIT, 0)


_SEVERITY = {
    "": 0,
    REASON_WAITING_FOR_QUOTA: 1,
    REASON_NO_MATCHING_FLAVOR: 2,
    REASON_EXCEEDS_MAX_QUOTA: 3,
}


def _most_severe(a: str, b: str) -> str:
    return a if _SEVERITY.get(a, 0) >= _SEVERITY.get(b, 0) else b
