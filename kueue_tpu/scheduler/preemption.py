"""Preemption: classical (hierarchical reclaim + priority) path.

Behavioral surface: reference pkg/scheduler/preemption/preemption.go,
preemption/classical/{candidate_generator,hierarchical_preemption}.go and
preemption/common/{ordering,preemption_policy}.go.

The classical heuristic removes candidates in order while the incoming
workload doesn't fit, then fills back in reverse order (minimization), over
up to two runs (allowBorrowing true/false). All snapshot mutation happens
through Snapshot.add/remove_workload so it is transactional.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Set, Tuple

from kueue_tpu.api.constants import (
    BorrowWithinCohortPolicy,
    IN_CLUSTER_QUEUE_REASON,
    IN_COHORT_RECLAIM_WHILE_BORROWING_REASON,
    IN_COHORT_RECLAMATION_REASON,
    PreemptionPolicy,
)
from kueue_tpu.cache.resource_node import QuotaNode
from kueue_tpu.cache.snapshot import ClusterQueueSnapshot, Snapshot
from kueue_tpu.core.resources import FlavorResource, FlavorResourceQuantities
from kueue_tpu.core.workload_info import (
    WorkloadInfo,
    is_evicted,
    quota_reservation_time,
    queue_order_timestamp,
)
from kueue_tpu.metrics import tracing
from kueue_tpu.scheduler.flavorassigner import Assignment, Mode


class Variant(enum.IntEnum):
    """preemptionVariant (reference classical/hierarchical_preemption.go:31)."""

    NEVER = 0
    WITHIN_CQ = 1
    HIERARCHICAL_RECLAIM = 2
    RECLAIM_WITHOUT_BORROWING = 3
    RECLAIM_WHILE_BORROWING = 4

    def reason(self) -> str:
        if self == Variant.WITHIN_CQ:
            return IN_CLUSTER_QUEUE_REASON
        if self == Variant.RECLAIM_WHILE_BORROWING:
            return IN_COHORT_RECLAIM_WHILE_BORROWING_REASON
        return IN_COHORT_RECLAMATION_REASON


@dataclass
class Target:
    """A workload to evict to make room (reference preemption.go:115)."""

    info: WorkloadInfo
    reason: str


class PreemptedWorkloads:
    """Victims designated so far in one cycle, keyed by workload
    (reference preempted_workloads.go:1-38 — a map, so the cycle's fit
    checks can simulate removal of every earlier victim)."""

    def __init__(self) -> None:
        self._by_key: Dict[str, WorkloadInfo] = {}

    def has_any(self, targets: Sequence[Target]) -> bool:
        return any(t.info.key in self._by_key for t in targets)

    def insert(self, targets: Sequence[Target]) -> None:
        for t in targets:
            self._by_key[t.info.key] = t.info

    def infos(self):
        return self._by_key.values()


def satisfies_preemption_policy(
    preemptor: WorkloadInfo, candidate: WorkloadInfo, policy: PreemptionPolicy
) -> bool:
    """reference preemption/common/preemption_policy.go."""
    lower = preemptor.priority() > candidate.priority()
    if policy == PreemptionPolicy.LOWER_PRIORITY:
        return lower
    if policy == PreemptionPolicy.LOWER_OR_NEWER_EQUAL_PRIORITY:
        newer_equal = (
            preemptor.priority() == candidate.priority()
            and queue_order_timestamp(preemptor.obj)
            < queue_order_timestamp(candidate.obj)
        )
        return lower or newer_equal
    return policy == PreemptionPolicy.ANY


def candidates_ordering_key(
    c: WorkloadInfo, cq_name: str, now: float
) -> Tuple:
    """Sort key replicating CandidatesOrdering (reference
    preemption/common/ordering.go:42): evicted first, other-CQ first, lower
    priority first, later quota-reservation first, UID tiebreak."""
    return (
        not is_evicted(c.obj),
        c.cluster_queue == cq_name,
        c.priority(),
        -quota_reservation_time(c.obj, now),
        c.obj.uid,
    )


def workload_uses_frs(
    wl: WorkloadInfo, frs: Set[FlavorResource]
) -> bool:
    for ps in wl.total_requests:
        for res, flv in ps.flavors.items():
            if FlavorResource(flv, res) in frs:
                return True
    return False


@dataclass
class _CandidateElem:
    wl: WorkloadInfo
    lca: Optional[QuotaNode]
    variant: Variant


@dataclass
class PreemptionCtx:
    preemptor: WorkloadInfo
    preemptor_cq: ClusterQueueSnapshot
    snapshot: Snapshot
    frs_need_preemption: Set[FlavorResource]
    requests: FlavorResourceQuantities  # full workload usage
    now: float = 0.0
    # TAS feasibility probe evaluated inside workload_fits (None = no TAS).
    tas_fits: Optional[Callable[[], bool]] = None


class Preemptor:
    """reference preemption.go Preemptor (classical path; the fair-sharing
    path lives in kueue_tpu/scheduler/fair_preemption.py)."""

    def __init__(
        self,
        enable_fair_sharing: bool = False,
        fair_strategies: Optional[List[str]] = None,
    ) -> None:
        self.enable_fair_sharing = enable_fair_sharing
        self.fair_strategies = fair_strategies or [
            "LessThanOrEqualToFinalShare",
            "LessThanInitialShare",
        ]

    # -- public -------------------------------------------------------------

    def get_targets(
        self,
        wl: WorkloadInfo,
        assignment: Assignment,
        snapshot: Snapshot,
        now: float = 0.0,
        tas_fits: Optional[Callable[[], bool]] = None,
    ) -> List[Target]:
        cq = snapshot.cluster_queue(wl.cluster_queue)
        ctx = PreemptionCtx(
            preemptor=wl,
            preemptor_cq=cq,
            snapshot=snapshot,
            frs_need_preemption=flavor_resources_need_preemption(assignment),
            requests=assignment.total_requests_for(wl),
            now=now,
            tas_fits=tas_fits,
        )
        if not tracing.ENABLED:
            if self.enable_fair_sharing:
                from kueue_tpu.scheduler.fair_preemption import (
                    fair_preemptions,
                )

                return fair_preemptions(ctx, self.fair_strategies)
            return self.classical_preemptions(ctx)
        with tracing.span(
            "scheduler/preemption_search", workload=wl.key,
            fair=self.enable_fair_sharing,
        ) as s:
            if self.enable_fair_sharing:
                from kueue_tpu.scheduler.fair_preemption import (
                    fair_preemptions,
                )

                targets = fair_preemptions(ctx, self.fair_strategies)
            else:
                targets = self.classical_preemptions(ctx)
            s.set_arg("targets", len(targets))
            tracing.inc("preemption_search_total",
                        {"found": str(bool(targets)).lower()})
            tracing.observe("preemption_search_targets", len(targets))
            return targets

    # -- candidate generation ----------------------------------------------

    def _classify(
        self,
        ctx: PreemptionCtx,
        wl: WorkloadInfo,
        hierarchical_advantage: bool,
    ) -> Variant:
        """reference classical/hierarchical_preemption.go:83."""
        if not workload_uses_frs(wl, ctx.frs_need_preemption):
            return Variant.NEVER
        p = ctx.preemptor_cq.spec.preemption
        if wl.cluster_queue == ctx.preemptor_cq.name:
            policy = p.within_cluster_queue
        else:
            policy = p.reclaim_within_cohort
        if not satisfies_preemption_policy(ctx.preemptor, wl, policy):
            return Variant.NEVER
        if wl.cluster_queue == ctx.preemptor_cq.name:
            return Variant.WITHIN_CQ
        if hierarchical_advantage:
            return Variant.HIERARCHICAL_RECLAIM
        bwc = p.borrow_within_cohort
        if bwc.policy == BorrowWithinCohortPolicy.NEVER:
            return Variant.RECLAIM_WITHOUT_BORROWING
        if wl.priority() >= ctx.preemptor.priority() or (
            bwc.max_priority_threshold is not None
            and wl.priority() > bwc.max_priority_threshold
        ):
            return Variant.RECLAIM_WITHOUT_BORROWING
        return Variant.RECLAIM_WHILE_BORROWING

    def _candidates_from_cq(
        self,
        ctx: PreemptionCtx,
        cq: ClusterQueueSnapshot,
        lca: Optional[QuotaNode],
        hierarchical_advantage: bool,
    ) -> List[_CandidateElem]:
        out = []
        for wl in cq.workloads.values():
            variant = self._classify(ctx, wl, hierarchical_advantage)
            if variant != Variant.NEVER:
                out.append(_CandidateElem(wl, lca, variant))
        return out

    def _collect_candidates(
        self, ctx: PreemptionCtx
    ) -> Tuple[List[_CandidateElem], List[_CandidateElem], List[_CandidateElem]]:
        """Returns (hierarchy, priority, same_queue) candidate classes
        (reference hierarchical_preemption.go:129-206)."""
        same_queue: List[_CandidateElem] = []
        if ctx.preemptor_cq.spec.preemption.within_cluster_queue != PreemptionPolicy.NEVER:
            same_queue = self._candidates_from_cq(
                ctx, ctx.preemptor_cq, None, False
            )

        hierarchy: List[_CandidateElem] = []
        priority_c: List[_CandidateElem] = []
        if (
            not ctx.preemptor_cq.has_parent()
            or ctx.preemptor_cq.spec.preemption.reclaim_within_cohort
            == PreemptionPolicy.NEVER
        ):
            return hierarchy, priority_c, same_queue

        cq_by_node: Dict[str, ClusterQueueSnapshot] = \
            ctx.snapshot.cq_by_node()

        def collect_in_subtree(
            cohort: QuotaNode,
            subtree_root: QuotaNode,
            skip: Optional[QuotaNode],
            advantage: bool,
            out: List[_CandidateElem],
        ) -> None:
            for child in cohort.children:
                if child is skip:
                    continue
                if child.is_cq:
                    if child.name == ctx.preemptor_cq.name:
                        continue
                    if not child.is_within_nominal_in(ctx.frs_need_preemption):
                        out.extend(
                            self._candidates_from_cq(
                                ctx, cq_by_node[child.name], subtree_root,
                                advantage,
                            )
                        )
                else:
                    if not child.is_within_nominal_in(ctx.frs_need_preemption):
                        collect_in_subtree(
                            child, subtree_root, skip, advantage, out
                        )

        advantage, remaining = ctx.preemptor_cq.node.quantities_fit_in_quota(
            ctx.requests
        )
        previous: Optional[QuotaNode] = ctx.preemptor_cq.node
        for subtree_root in ctx.preemptor_cq.path_parent_to_root():
            out = hierarchy if advantage else priority_c
            collect_in_subtree(subtree_root, subtree_root, previous, advantage, out)
            fits, remaining = subtree_root.quantities_fit_in_quota(remaining)
            # Once a subtree fits the requests, the preemptor has hierarchical
            # advantage over everything above it.
            advantage = advantage or fits
            previous = subtree_root
        return hierarchy, priority_c, same_queue

    # -- classical algorithm -------------------------------------------------

    def classical_preemptions(self, ctx: PreemptionCtx) -> List[Target]:
        """reference preemption.go:281-336."""
        hierarchy, priority_c, same_queue = self._collect_candidates(ctx)

        def sort(lst: List[_CandidateElem]) -> List[_CandidateElem]:
            return sorted(
                lst,
                key=lambda c: candidates_ordering_key(
                    c.wl, ctx.preemptor_cq.name, ctx.now
                ),
            )

        hierarchy, priority_c, same_queue = (
            sort(hierarchy), sort(priority_c), sort(same_queue),
        )

        def split_evicted(lst):
            ev = [c for c in lst if is_evicted(c.wl.obj)]
            nev = [c for c in lst if not is_evicted(c.wl.obj)]
            return ev, nev

        ev_h, nev_h = split_evicted(hierarchy)
        ev_p, nev_p = split_evicted(priority_c)
        ev_s, nev_s = split_evicted(same_queue)
        all_candidates = ev_h + ev_p + ev_s + nev_h + nev_p + nev_s

        no_other_queue_candidates = not hierarchy and not priority_c
        no_hierarchy_candidates = not hierarchy
        borrow_forbidden = (
            ctx.preemptor_cq.spec.preemption.borrow_within_cohort.policy
            == BorrowWithinCohortPolicy.NEVER
        )

        if no_other_queue_candidates or (
            borrow_forbidden and not self._queue_under_nominal(ctx)
        ):
            attempts = [True]
        elif borrow_forbidden and no_hierarchy_candidates:
            attempts = [False, True]
        else:
            attempts = [True, False]

        for allow_borrowing in attempts:
            targets: List[Target] = []
            for cand in all_candidates:
                if not self._candidate_is_valid(ctx, cand, allow_borrowing):
                    continue
                ctx.snapshot.remove_workload(cand.wl)
                targets.append(Target(cand.wl, cand.variant.reason()))
                if self._workload_fits(ctx, allow_borrowing):
                    targets = self._fill_back(ctx, targets, allow_borrowing)
                    self._restore(ctx, targets)
                    return targets
            self._restore(ctx, targets)
        return []

    def _candidate_is_valid(
        self, ctx: PreemptionCtx, cand: _CandidateElem, borrow: bool
    ) -> bool:
        """reference candidate_generator.go:137-158."""
        if ctx.preemptor_cq.name == cand.wl.cluster_queue:
            return True
        if borrow and cand.variant == Variant.RECLAIM_WITHOUT_BORROWING:
            return False
        cq = ctx.snapshot.cluster_queue(cand.wl.cluster_queue)
        if cq.node.is_within_nominal_in(ctx.frs_need_preemption):
            return False
        node = cq.node.parent
        while node is not None and node is not cand.lca:
            if node.is_within_nominal_in(ctx.frs_need_preemption):
                return False
            node = node.parent
        return True

    def _workload_fits(self, ctx: PreemptionCtx, allow_borrowing: bool) -> bool:
        """reference preemption.go:628."""
        for fr, v in ctx.requests.items():
            if not allow_borrowing and ctx.preemptor_cq.borrowing_with(fr, v):
                return False
            if v > ctx.preemptor_cq.available(fr):
                return False
        if ctx.tas_fits is not None:
            return ctx.tas_fits()
        return True

    def _fill_back(
        self, ctx: PreemptionCtx, targets: List[Target], allow_borrowing: bool
    ) -> List[Target]:
        """reference preemption.go:338-351."""
        i = len(targets) - 2
        while i >= 0:
            ctx.snapshot.add_workload(targets[i].info)
            if self._workload_fits(ctx, allow_borrowing):
                targets[i] = targets[-1]
                targets.pop()
            else:
                ctx.snapshot.remove_workload(targets[i].info)
            i -= 1
        return targets

    def _restore(self, ctx: PreemptionCtx, targets: List[Target]) -> None:
        for t in targets:
            ctx.snapshot.add_workload(t.info)

    def _queue_under_nominal(self, ctx: PreemptionCtx) -> bool:
        """usage strictly below nominal for all contested frs
        (preemption.go:659)."""
        node = ctx.preemptor_cq.node
        return all(
            ctx.preemptor_cq.quota_for(fr).nominal > node.usage.get(fr, 0)
            for fr in ctx.frs_need_preemption
        )


def flavor_resources_need_preemption(
    assignment: Assignment,
) -> Set[FlavorResource]:
    """reference preemption.go:550."""
    out: Set[FlavorResource] = set()
    for ps in assignment.pod_sets:
        for res, fa in ps.flavors.items():
            if fa.mode == Mode.PREEMPT:
                out.add(FlavorResource(fa.name, res))
    return out


def make_oracle(
    preemptor: Preemptor, snapshot: Snapshot, now: float = 0.0
):
    """SimulatePreemption (reference preemption_oracle.go): run the
    preemption search for a single contested FlavorResource and report
    whether targets exist and the borrow height after preemptions.

    Memoized per cycle: all nomination-phase probes see the same snapshot
    state, and the outcome depends only on (cq, fr, amount, preemptor
    priority, preemptor order timestamp)."""
    memo: dict = {}

    def simulate(
        cq: ClusterQueueSnapshot, wl: WorkloadInfo, fr: FlavorResource, val: int
    ) -> Tuple[str, int]:
        # Timestamps only influence candidate sets under
        # LowerOrNewerEqualPriority; otherwise identical (cq, fr, amount,
        # priority) probes share one result.
        p = cq.spec.preemption
        ts_sensitive = PreemptionPolicy.LOWER_OR_NEWER_EQUAL_PRIORITY in (
            p.within_cluster_queue, p.reclaim_within_cohort
        )
        key = (
            cq.name, fr, val, wl.priority(),
            queue_order_timestamp(wl.obj) if ts_sensitive else None,
        )
        hit = memo.get(key)
        if hit is not None:
            return hit
        out = _simulate_uncached(cq, wl, fr, val)
        memo[key] = out
        return out

    def _candidates_possible(
        cq: ClusterQueueSnapshot, wl: WorkloadInfo, fr: FlavorResource
    ) -> bool:
        """Sound existence prefilter: when no admitted workload can
        satisfy any preemption policy on fr, the full search is guaranteed
        to return no targets (candidates are a subset of this check)."""
        p = cq.spec.preemption

        def policy_matches(policy, cand: WorkloadInfo) -> bool:
            if policy == PreemptionPolicy.NEVER:
                return False
            if policy == PreemptionPolicy.ANY:
                return True
            if policy == PreemptionPolicy.LOWER_PRIORITY:
                return cand.priority() < wl.priority()
            return cand.priority() <= wl.priority()  # LowerOrNewer superset

        if p.within_cluster_queue != PreemptionPolicy.NEVER:
            for cand in cq.workloads.values():
                if policy_matches(p.within_cluster_queue, cand) and \
                        workload_uses_frs(cand, {fr}):
                    return True
        if cq.has_parent() and \
                p.reclaim_within_cohort != PreemptionPolicy.NEVER:
            root = cq.node.root()
            for other in snapshot.cqs_under_root(root):
                if other.name == cq.name:
                    continue
                if other.node.is_within_nominal_in({fr}):
                    continue
                for cand in other.workloads.values():
                    if policy_matches(p.reclaim_within_cohort, cand) and \
                            workload_uses_frs(cand, {fr}):
                        return True
        return False

    def _simulate_uncached(
        cq: ClusterQueueSnapshot, wl: WorkloadInfo, fr: FlavorResource, val: int
    ) -> Tuple[str, int]:
        from kueue_tpu.cache.resource_node import (
            find_height_of_lowest_subtree_that_fits,
        )

        if not _candidates_possible(cq, wl, fr):
            borrow, _ = find_height_of_lowest_subtree_that_fits(
                cq.node, fr, val
            )
            return "NoCandidates", borrow
        ctx = PreemptionCtx(
            preemptor=wl,
            preemptor_cq=snapshot.cluster_queue(wl.cluster_queue),
            snapshot=snapshot,
            frs_need_preemption={fr},
            requests={fr: val},
            now=now,
        )
        if preemptor.enable_fair_sharing:
            from kueue_tpu.scheduler.fair_preemption import fair_preemptions

            candidates = fair_preemptions(ctx, preemptor.fair_strategies)
        else:
            candidates = preemptor.classical_preemptions(ctx)
        if not candidates:
            borrow, _ = find_height_of_lowest_subtree_that_fits(cq.node, fr, val)
            return "NoCandidates", borrow
        revert = snapshot.simulate_workload_removal(
            [t.info for t in candidates]
        )
        borrow_after, _ = find_height_of_lowest_subtree_that_fits(
            cq.node, fr, val
        )
        revert()
        if any(t.info.cluster_queue == cq.name for t in candidates):
            return "Preempt", borrow_after
        return "Reclaim", borrow_after

    return simulate
