"""The controller manager: one object wiring the whole control plane.

Behavioral surface: reference cmd/kueue/main.go — cache + queue wiring,
core controllers, scheduler, admission-check controllers — reshaped as a
call-driven facade (kueue_tpu is standalone; there is no kube-apiserver to
watch, so "events" are method calls and `tick()` drives clock-based
reconciliation).

Typical use:

    mgr = Manager()
    mgr.apply(flavor, topology, cohort, cq, lq)
    mgr.submit_job(my_train_job)          # or mgr.create_workload(wl)
    mgr.schedule()                        # one scheduling cycle
    mgr.tick()                            # timeouts, checks, backoffs
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Union

from kueue_tpu.api.constants import (
    COND_FINISHED,
    CheckState,
    StopPolicy,
)
from kueue_tpu.utils.validation import (
    validate_cluster_queue,
    validate_cohort,
    validate_resource_flavor,
    validate_workload,
    validate_workload_update,
)
from kueue_tpu.api.types import (
    AdmissionCheck,
    ClusterQueue,
    Cohort,
    LimitRange,
    LocalQueue,
    Namespace,
    ResourceFlavor,
    RuntimeClass,
    Topology,
    Workload,
    WorkloadPriorityClass,
)
from kueue_tpu.cache.cache import Cache
from kueue_tpu.controllers.jobframework import GenericJob, JobReconciler
from kueue_tpu.controllers.workload_controller import (
    RetentionConfig,
    WaitForPodsReadyConfig,
    WorkloadController,
)
from kueue_tpu.core.workload_info import (
    WorkloadInfo,
    is_finished,
    set_condition,
)
from kueue_tpu.queue.manager import QueueManager
from kueue_tpu.scheduler.scheduler import CycleResult, Scheduler
from kueue_tpu.tas.snapshot import Node
from kueue_tpu.metrics.registry import Metrics

ApplyObject = Union[
    ClusterQueue, Cohort, LocalQueue, Namespace, ResourceFlavor, Topology,
    AdmissionCheck, Node, WorkloadPriorityClass,
]


class AdmissionCheckController:
    """Plugin seam for two-phase admission (reference
    pkg/controller/admissionchecks): the manager calls ``sync`` for every
    workload with a pending check owned by this controller."""

    controller_name = "base"

    def sync(self, manager: "Manager", wl: Workload, check_name: str) -> None:
        raise NotImplementedError


class Manager:
    def __init__(
        self,
        fair_sharing: bool = False,
        pods_ready: Optional[WaitForPodsReadyConfig] = None,
        retention: Optional[RetentionConfig] = None,
        clock: Callable[[], float] = time.monotonic,
        use_device_scheduler: bool = False,
        admission_fair_sharing=None,
        device_kernel: str = "scan",
        auto_cpu_kernel: str = "scan",
        pipeline_cycles: str = "auto",
        tile_width="auto",
    ) -> None:
        self.clock = clock
        self.cache = Cache()
        self.queues = QueueManager()
        self.metrics = Metrics()
        self.fair_sharing = fair_sharing
        if use_device_scheduler:
            from kueue_tpu.models.driver import DeviceScheduler

            self.scheduler = DeviceScheduler(
                self.cache, self.queues, fair_sharing=fair_sharing,
                device_kernel=device_kernel,
                auto_cpu_kernel=auto_cpu_kernel,
                pipeline_cycles=pipeline_cycles,
                tile_width=tile_width,
            )
        else:
            self.scheduler = Scheduler(
                self.cache, self.queues, fair_sharing=fair_sharing,
                clock=clock,
            )
        self.workloads: Dict[str, Workload] = {}
        self.priority_classes: Dict[str, WorkloadPriorityClass] = {}
        # Resource preprocessing (reference config resources section).
        self.exclude_resource_prefixes: list = []
        self.resource_transformations: list = []
        # reference configuration_types.go:634 DRA deviceClassMappings.
        self.device_class_mappings: list = []
        # reference configuration_types.go manageJobsWithoutQueueName.
        self.manage_jobs_without_queue_name = False
        self.job_reconciler = JobReconciler(self)
        self.workload_controller = WorkloadController(
            self, pods_ready=pods_ready, retention=retention
        )
        self.check_controllers: Dict[str, AdmissionCheckController] = {}
        if admission_fair_sharing is not None:
            from kueue_tpu.queue.afs import AfsTracker

            self.queues.afs_tracker = AfsTracker(admission_fair_sharing)
        from kueue_tpu.controllers.tas_failure import TASNodeFailureController

        self.tas_failure = TASNodeFailureController(self)
        self._whatif = None
        self._explainer = None
        self._slo = None
        self._service = None
        self._readplane = None

    def whatif(self):
        """Lazily built what-if forecasting engine over this manager's
        cache and queues (docs/whatif.md). Read-only: forecasts never
        mutate scheduler state."""
        if self._whatif is None:
            from kueue_tpu.whatif import WhatIfEngine

            self._whatif = WhatIfEngine(
                self.cache, self.queues, clock=self.clock,
                kernel=(
                    "fair_fixedpoint" if self.fair_sharing
                    else "fixedpoint"
                ),
            )
        return self._whatif

    def explainer(self):
        """Lazily built explain facade (docs/observability.md): live
        status + flight-recorder provenance + what-if forecast, joined
        per workload. Served as ``cli explain`` and ``/explain/<wl>``."""
        if self._explainer is None:
            from kueue_tpu.obs import Explainer
            from kueue_tpu.obs import recorder as flight

            self._explainer = Explainer(
                self.cache, self.queues, workloads=self.workloads,
                recorder_fn=flight.get, whatif_fn=self.whatif,
                clock=self.clock,
            )
        return self._explainer

    def explain(self, name: str, **kwargs) -> dict:
        """Why is this workload (not) running? See Explainer.explain."""
        return self.explainer().explain(name, **kwargs)

    def slo(self, objectives=None):
        """Lazily built burn-rate SLO engine over this manager's metric
        registry (docs/observability.md). Once built, every
        ``_update_gauges`` pass re-evaluates it so the ``slo_*`` gauges
        stay live on /metrics."""
        if self._slo is None:
            from kueue_tpu.obs import SLOEngine

            self._slo = SLOEngine(
                self.metrics, objectives=objectives, clock=self.clock
            )
        return self._slo

    def service(self, **kwargs):
        """Lazily built streaming service loop over this manager
        (docs/observability.md, "Service loop & live health"): async
        ingestion, admission cycles + ticks on a loop thread, watermark
        gauges + continuous SLO burn on a telemetry thread, and the
        lock-free ``health()`` document behind ``/healthz``. Constructor
        kwargs are honored only on first build."""
        if self._service is None:
            from kueue_tpu.obs import ServiceLoop

            self._service = ServiceLoop(self, **kwargs)
            if self._readplane is not None:
                self._service.attach_readplane(self._readplane)
        elif kwargs:
            raise ValueError(
                "service loop already built; configure it on first call"
            )
        return self._service

    def readplane(self, **kwargs):
        """Lazily built multi-tenant read plane (docs/whatif.md,
        "Multi-tenant read plane"): coalesced what-if serving off
        double-buffered cycle-boundary snapshots. Shares the live
        what-if engine's jit caches, registers the read-plane SLO
        objectives, and — when the service loop exists (before or
        after) — wires its cycle-boundary publish hook. Constructor
        kwargs are honored only on first build."""
        if self._readplane is None:
            from kueue_tpu.readplane import ReadPlane

            self._readplane = ReadPlane(
                self.cache, self.queues, metrics=self.metrics,
                clock=self.clock, template=self.whatif(), **kwargs,
            )
            self.slo().add_objectives(self._readplane.slo_objectives())
            if self._service is not None:
                self._service.attach_readplane(self._readplane)
        elif kwargs:
            raise ValueError(
                "read plane already built; configure it on first call"
            )
        return self._readplane

    def prewarm(self, max_heads: int = 16, background: bool = False,
                aot: bool = True):
        """Compile the device solver's bucket ladder up front so the
        first admission cycles hit warm executables (docs/perf.md, "Cold
        start & compile cache"). Also wires the persistent compile cache
        from ``KUEUE_TPU_COMPILE_CACHE`` when set, so the compiles
        persist across processes. No-op (returns ``{}``) on the
        host-only scheduler; call after registering flavors and
        ClusterQueues — the warmup encodes the live snapshot's shapes."""
        from kueue_tpu.perf import compile_cache

        compile_cache.configure()
        prewarm_fn = getattr(self.scheduler, "prewarm", None)
        out = {}
        if prewarm_fn is not None:
            out = prewarm_fn(
                max_heads=max_heads, background=background, aot=aot
            ) or {}
        # Fleet rung: any check controller carrying a FleetDispatcher
        # (MultiKueue joint placement) compiles its cycle_fleet_assign
        # ladder here too, so the first joint dispatch is warm.
        for ctrl in self.check_controllers.values():
            fleet = getattr(ctrl, "fleet", None)
            if fleet is not None and hasattr(fleet, "prewarm"):
                out = dict(out)
                out["fleet"] = fleet.prewarm(max_heads=max_heads, aot=aot)
        return out

    def warm_workload_columns(self) -> int:
        """Bulk-fill the columnar workload plane (cache/columns.py) for
        every pending workload against one fresh snapshot. Called after a
        failover restore so the first post-takeover cycle encodes off warm
        columns instead of paying the O(W) cold row walk; no-op when the
        columnar plane is disabled or nothing is pending. Returns the
        number of rows filled."""
        from kueue_tpu.models.encode import columns_mode

        if columns_mode() == "off":
            return 0
        snapshot = self.cache.snapshot()
        store = snapshot.workload_columns
        if store is None:
            return 0
        infos: list = []
        for name in self.queues.cluster_queues:
            infos.extend(self.queues.pending_workloads(name))
        if not infos:
            return 0
        return store.warm(infos, snapshot, snapshot.resource_flavors)

    # ------------------------------------------------------------------
    # configuration objects
    # ------------------------------------------------------------------

    def _custom_metric_labels(self, kind: str, obj) -> Dict[str, str]:
        """KEP 7066 custom metric labels: configured entries for ``kind``
        resolve against the source object's labels/annotations (label key
        defaulting to the entry name); missing sources emit ""."""
        out: Dict[str, str] = {}
        for entry in getattr(self, "metrics_custom_labels", []) or []:
            if entry.get("source_kind", "Workload") != kind:
                continue
            labels = getattr(obj, "labels", {}) or {}
            annotations = getattr(obj, "annotations", {}) or {}
            if entry.get("source_annotation_key"):
                val = annotations.get(entry["source_annotation_key"], "")
            else:
                key = entry.get("source_label_key") or entry.get("name", "")
                val = labels.get(key, "")
            out[entry.get("name", "")] = val
        return out

    def apply(self, *objects: ApplyObject) -> None:

        for obj in objects:
            if isinstance(obj, ClusterQueue):
                validate_cluster_queue(obj)
                self.cache.add_or_update_cluster_queue(obj)
                self.queues.add_cluster_queue(obj)
                if obj.stop_policy == StopPolicy.HOLD_AND_DRAIN:
                    # Drain: evict every admitted workload of this CQ
                    # (reference stopPolicy semantics).
                    for info in list(self.cache.workloads.values()):
                        if info.cluster_queue == obj.name:
                            wl = self.workloads.get(info.key)
                            if wl is not None:
                                self.workload_controller.evict(
                                    wl, "ClusterQueueStopped",
                                    "The ClusterQueue is stopped and "
                                    "draining", self.clock(),
                                )
            elif isinstance(obj, Cohort):
                validate_cohort(obj)
                self.cache.add_or_update_cohort(obj)
            elif isinstance(obj, LocalQueue):
                self.cache.add_or_update_local_queue(obj)
                self.queues.add_local_queue(obj)
            elif isinstance(obj, ResourceFlavor):
                validate_resource_flavor(obj)
                self.cache.add_or_update_resource_flavor(obj)
            elif isinstance(obj, Topology):
                self.cache.add_or_update_topology(obj)
            elif isinstance(obj, AdmissionCheck):
                self.cache.add_or_update_admission_check(obj)
            elif isinstance(obj, Node):
                self.cache.add_or_update_node(obj)
            elif type(obj).__name__ == "ResourceSlice":
                self.cache.device_class_mappings = self.device_class_mappings
                self.cache.add_or_update_resource_slice(obj)
            elif isinstance(obj, Namespace):
                self.cache.namespaces[obj.name] = obj
            elif isinstance(obj, WorkloadPriorityClass):
                self.priority_classes[obj.name] = obj
            elif isinstance(obj, LimitRange):
                self.cache.limit_ranges[obj.key] = obj
            elif isinstance(obj, RuntimeClass):
                self.cache.runtime_classes[obj.name] = obj
            else:
                raise TypeError(f"unsupported object {type(obj)!r}")
        self.queues.queue_inadmissible_workloads()

    def delete(self, obj: ApplyObject) -> None:
        if isinstance(obj, ClusterQueue):
            self.cache.delete_cluster_queue(obj.name)
            self.queues.delete_cluster_queue(obj.name)
        elif isinstance(obj, Cohort):
            self.cache.delete_cohort(obj.name)
        elif isinstance(obj, LocalQueue):
            self.cache.delete_local_queue(obj.key)
            self.queues.delete_local_queue(obj.key)
        elif isinstance(obj, ResourceFlavor):
            self.cache.delete_resource_flavor(obj.name)
        elif isinstance(obj, Node):
            self.cache.delete_node(obj.name)
        self.queues.queue_inadmissible_workloads()

    def register_check_controller(
        self, ctrl: AdmissionCheckController
    ) -> None:
        self.check_controllers[ctrl.controller_name] = ctrl

    # ------------------------------------------------------------------
    # workload / job lifecycle
    # ------------------------------------------------------------------

    def create_workload(self, wl: Workload) -> None:
        """Validating-webhook equivalent + queue entry
        (reference pkg/webhooks/workload_webhook.go)."""
        if wl.key in self.workloads:
            raise ValueError(f"workload {wl.key} already exists")
        validate_workload(wl)
        if any(ps.containers or ps.init_containers for ps in wl.pod_sets):
            # Pod-spec-shaped podsets: derive effective requests (pod
            # overhead, LimitRange defaults, limits-as-missing-requests,
            # init-container max rule — reference
            # pkg/workload/resources.go AdjustResources) and enforce the
            # namespace bounds. The reference surfaces violations as
            # inadmissibility; the standalone analog rejects at the
            # webhook seam.
            from kueue_tpu.utils import limitrange as _lr

            ranges = [
                lr for lr in self.cache.limit_ranges.values()
                if lr.namespace == wl.namespace
            ]
            _lr.adjust_resources(wl, ranges, self.cache.runtime_classes)
            errs = _lr.validate_resources(wl)
            errs += _lr.validate_limit_ranges(wl, ranges)
            if errs:
                raise ValueError("; ".join(errs))
        if wl.creation_time == 0.0:
            wl.creation_time = self.clock()
        if wl.priority_class and wl.priority_class in self.priority_classes:
            wl.priority = self.priority_classes[wl.priority_class].value
        if self.exclude_resource_prefixes or self.resource_transformations:
            from kueue_tpu.utils.resource_transform import transform_requests

            for ps in wl.pod_sets:
                ps.requests = transform_requests(
                    ps.requests,
                    self.exclude_resource_prefixes,
                    self.resource_transformations,
                )
        # DRA: count device-class requests against the mapped logical
        # resource (reference configuration_types.go:634 deviceClassMappings;
        # unmapped device classes make the workload inadmissible — here,
        # rejected at creation).
        if any(ps.device_requests for ps in wl.pod_sets):
            from kueue_tpu.dra import charges_for_request
            from kueue_tpu.utils import features

            if not features.enabled("KueueDRAIntegration"):
                if features.enabled(
                    "KueueDRARejectWorkloadsWhenDRADisabled"
                ):
                    raise ValueError(
                        f"workload {wl.key}: DRA device requests present"
                        " but KueueDRAIntegration is disabled"
                    )
                for ps in wl.pod_sets:
                    ps.device_requests = {}
            by_class = {
                dc: m
                for m in self.device_class_mappings
                for dc in m.device_class_names
            }
            slices = list(self.cache.resource_slices.values())
            for ps in wl.pod_sets:
                for dc, n in ps.device_requests.items():
                    m = by_class.get(dc)
                    if m is None:
                        raise ValueError(
                            f"workload {wl.key}: device class {dc!r} has no "
                            f"deviceClassMappings entry"
                        )
                    try:
                        charge = charges_for_request(slices, m, n)
                    except ValueError as exc:
                        raise ValueError(
                            f"workload {wl.key}: {exc}"
                        ) from exc
                    ps.requests[m.name] = ps.requests.get(m.name, 0) + charge
                # Folded into requests; cleared so a checkpoint restore
                # through create_workload cannot double-count.
                ps.device_requests = {}
        self.workloads[wl.key] = wl
        self.metrics.inc("workloads_created_total")
        self.queues.add_or_update_workload(wl)

    def update_workload(self, wl: Workload, elastic: bool = False) -> None:
        """Spec/status update with webhook-grade invariants (reference
        workload_webhook.go ValidateWorkloadUpdate): podSets frozen under
        quota reservation (elastic scale-down exempt), admission immutable
        once set, reclaimable counts monotone, clusterName write-once."""
        old = self.workloads.get(wl.key)
        if old is None:
            raise ValueError(f"workload {wl.key} does not exist")
        validate_workload_update(wl, old, elastic=elastic)
        self.workloads[wl.key] = wl
        if wl.key not in self.cache.workloads:
            self.queues.add_or_update_workload(wl)

    def submit_job(self, job: GenericJob) -> Optional[Workload]:
        """Returns the managed Workload, or None when the job is outside
        kueue's management (no queue name and
        manageJobsWithoutQueueName=False)."""
        return self.job_reconciler.reconcile(job)

    def reconcile_job(self, job: GenericJob) -> None:
        self.job_reconciler.reconcile(job)

    def finish_workload(self, wl: Workload, success: bool = True) -> None:
        now = self.clock()
        if not is_finished(wl):
            set_condition(wl, COND_FINISHED, True,
                          "Succeeded" if success else "Failed", "", now)
        self.cache.delete_workload(wl.key)
        self.queues.delete_workload(wl)
        self.metrics.inc("workloads_finished_total")
        cq = self.queues.cluster_queue_for(wl) or ""
        self.metrics.inc("finished_workloads_total", {"cluster_queue": cq})
        self.queues.queue_inadmissible_workloads()

    def reclaim_pods(self, wl: Workload, counts: Dict[str, int]) -> None:
        """Mark pods of an admitted workload as finished early; their
        resources are released without waiting for the whole gang
        (reference workload ReclaimablePods; jobframework reclaimable-pods
        capability). counts: podset name -> total finished pods."""
        def apply_counts() -> None:
            for name, c in counts.items():
                prev = wl.status.reclaimable_pods.get(name, 0)
                # Reclaimable counts only grow (reference validation).
                wl.status.reclaimable_pods[name] = max(prev, c)

        self.cache.reaccount_workload(wl.key, apply_counts)
        self.metrics.inc("reclaimed_pods_total")
        self.queues.queue_inadmissible_workloads()

    def delete_workload(self, wl: Workload) -> None:
        self.cache.delete_workload(wl.key)
        self.queues.delete_workload(wl)
        self.workloads.pop(wl.key, None)
        self.job_reconciler.job_of_workload.pop(wl.key, None)
        self.queues.queue_inadmissible_workloads()

    # ------------------------------------------------------------------
    # control loops
    # ------------------------------------------------------------------

    def export_state(self) -> str:
        """Serialize the whole control plane (specs + workloads incl.
        admissions) as a multi-doc YAML checkpoint — the analog of the
        reference's etcd-is-the-journal model."""
        import yaml as _yaml

        from kueue_tpu.api.serialization import encode

        docs = []
        for topo in self.cache.topologies.values():
            docs.append(encode(topo))
        for rf in self.cache.resource_flavors.values():
            docs.append(encode(rf))
        for node in self.cache.nodes.values():
            docs.append(encode(node))
        for cohort in self.cache.cohorts.values():
            docs.append(encode(cohort))
        for ac in self.cache.admission_checks.values():
            docs.append(encode(ac))
        for lrange in self.cache.limit_ranges.values():
            docs.append(encode(lrange))
        for rc in self.cache.runtime_classes.values():
            docs.append(encode(rc))
        for cq in self.cache.cluster_queues.values():
            docs.append(encode(cq))
        for lq in self.cache.local_queues.values():
            docs.append(encode(lq))
        for wl in self.workloads.values():
            docs.append(encode(wl))
        return _yaml.safe_dump_all(docs, sort_keys=False)

    @classmethod
    def restore_state(cls, text: str, **kw) -> "Manager":
        """Rebuild a Manager from an export_state checkpoint: specs are
        re-applied, admitted workloads re-enter the cache with their
        admissions, pending ones re-enter the queues."""
        from kueue_tpu.api.serialization import load_manifests
        from kueue_tpu.core.workload_info import (
            WorkloadInfo,
            is_admitted as _adm,
            has_quota_reservation as _qr,
        )

        mgr = cls(**kw)
        workloads = []
        for obj in load_manifests(text):
            if isinstance(obj, Workload):
                workloads.append(obj)
            else:
                mgr.apply(obj)
        for wl in workloads:
            if _adm(wl) or _qr(wl):
                mgr.workloads[wl.key] = wl
                cq_name = (
                    wl.status.admission.cluster_queue
                    if wl.status.admission
                    else mgr.queues.cluster_queue_for(wl)
                )
                info = WorkloadInfo(wl, cq_name or "")
                info.sync_assignment_from_admission()
                mgr.cache.add_or_update_workload(info)
            else:
                mgr.create_workload(wl)
        return mgr

    def schedule(self) -> CycleResult:
        if self._admission_blocked():
            # waitForPodsReady.blockAdmission (reference
            # scheduler.go:545 waitForPodsReadyIfBlocked): hold new
            # admissions until every admitted workload has PodsReady.
            return CycleResult()
        result = self.scheduler.schedule()
        self.metrics.observe(
            "admission_attempt_duration_seconds", result.duration_s
        )
        self.metrics.observe("scheduler_snapshot_duration_seconds",
                             result.snapshot_s)
        self.metrics.observe("scheduler_nomination_duration_seconds",
                             result.nominate_s)
        self.metrics.inc("admission_attempts_total")
        tracker = self.queues.afs_tracker
        now = self.clock()
        for key in result.admitted:
            self.metrics.inc("quota_reserved_workloads_total")
            wl0 = self.workloads.get(key)
            if wl0 is not None:
                # quota_reserved_wait_time_seconds (metrics.go:497):
                # creation -> QuotaReserved. Admitted-side series emit on
                # the Admitted transition in the workload controller.
                self.metrics.observe(
                    "quota_reserved_wait_time_seconds",
                    max(0.0, now - wl0.creation_time),
                )
            if tracker is not None:
                wl = self.workloads.get(key)
                if wl is not None:
                    tracker.add_entry_penalty(
                        f"{wl.namespace}/{wl.queue_name}",
                        {
                            r: v * ps.count
                            for ps in wl.pod_sets
                            for r, v in ps.requests.items()
                        },
                    )
        for key in result.preempted:
            self.metrics.inc("preempted_workloads_total")
        for cq_name, skips in result.preemption_skips.items():
            self.metrics.set_gauge(
                "admission_cycle_preemption_skips", skips,
                {"cluster_queue": cq_name},
            )
        # Sync jobs whose workload state changed.
        self._reconcile_touched_jobs(result)
        return result

    def schedule_all(self, max_cycles: int = 100000) -> int:
        cycles = 0
        prev_no_progress_heads = None
        while cycles < max_cycles:
            result = self.schedule()
            cycles += 1
            if result.admitted or result.preempted:
                prev_no_progress_heads = None
                continue
            if not result.head_keys or result.head_keys == prev_no_progress_heads:
                break
            prev_no_progress_heads = result.head_keys
        for key, job in list(self.job_reconciler.job_of_workload.items()):
            self.job_reconciler.reconcile(job)
        self.tick()
        return cycles

    def tick(self) -> None:
        """Clock-driven reconciliation: admission checks, timeouts,
        backoffs, retention, job sync."""
        tracker = self.queues.afs_tracker
        if tracker is not None:
            from kueue_tpu.core.workload_info import is_admitted as _adm

            now = self.clock()
            running: Dict[str, Dict[str, int]] = {}
            for wl in self.workloads.values():
                lq_key = f"{wl.namespace}/{wl.queue_name}"
                running.setdefault(lq_key, {})
                if _adm(wl):
                    for ps in wl.pod_sets:
                        for r, v in ps.requests.items():
                            running[lq_key][r] = (
                                running[lq_key].get(r, 0) + v * ps.count
                            )
            for lq_key, usage in running.items():
                lq = self.cache.local_queues.get(lq_key)
                if lq is not None and lq.fair_sharing is not None:
                    # nil weight defaults to 1 (reference FairSharing
                    # semantics) — and must RESET a previously set
                    # weight in the persistent tracker.
                    tracker.set_lq_weight(
                        lq_key,
                        1.0 if lq.fair_sharing.weight is None
                        else lq.fair_sharing.weight,
                    )
                tracker.sample(lq_key, usage, now)
        self.tas_failure.reconcile()
        for wl in list(self.workloads.values()):
            self._sync_admission_checks(wl)
            self._sync_remote_status(wl)
            self._second_pass_assign(wl)
            self.workload_controller.reconcile(wl)
        self.workload_controller.requeue_ready_backoffs()
        self._update_gauges()

    def _second_pass_assign(self, wl: Workload) -> None:
        """The scheduler's second pass for delayed topology requests
        (reference workload.go:889 NeedsSecondPass + scheduler second
        pass): once quota is reserved and every admission check is Ready,
        compute the topology placement that was deferred on the first pass
        (ProvisioningRequest: the nodes exist only after provisioning).
        MultiKueue-delayed assignments are resolved by the worker mirror
        instead; podsets whose flavor has no local topology stay pending."""
        from kueue_tpu.core.workload_info import (
            all_checks_ready,
            has_quota_reservation,
            has_topology_assignments_pending,
            is_admitted,
            is_finished,
        )
        from kueue_tpu.tas.snapshot import PlacementRequest

        if (
            is_finished(wl)
            or is_admitted(wl)
            or not wl.active
            or not has_quota_reservation(wl)
            or not wl.status.admission_checks
            or not all_checks_ready(wl)
            or not has_topology_assignments_pending(wl)
        ):
            return
        snapshot = self.cache.snapshot()
        info = self.cache.workloads.get(wl.key)
        changed = False
        for i, psa in enumerate(wl.status.admission.pod_set_assignments):
            if not psa.delayed_topology_request \
                    or psa.topology_assignment is not None \
                    or i >= len(wl.pod_sets):
                continue
            ps = wl.pod_sets[i]
            tr = ps.topology_request
            flavor = next(iter(psa.flavors.values()), None)
            tas = snapshot.tas_flavors.get(flavor)
            if tas is None or tr is None:
                continue  # no local topology: stays pending (MultiKueue)
            req = PlacementRequest(
                count=psa.count or ps.count,
                single_pod_requests=dict(ps.requests),
                required_level=tr.required_level,
                preferred_level=tr.preferred_level,
                unconstrained=tr.unconstrained,
                slice_size=tr.slice_size or 1,
                slice_required_level=tr.slice_required_level,
                slice_layers=list(getattr(tr, "slice_layers", [])),
                node_selector=dict(ps.node_selector),
                tolerations=list(ps.tolerations),
            )
            assignment, _, reason = tas.find_topology_assignment(req)
            if reason:
                continue  # retried on the next tick
            psa.topology_assignment = assignment
            changed = True
        if changed and info is not None:
            info.sync_assignment_from_admission()
            self.cache.add_or_update_workload(info)
            self.metrics.inc("second_pass_assignments_total")

    def _update_gauges(self) -> None:
        """Gauge series (reference pkg/metrics/metrics.go:414,831,896):
        pending_workloads, cluster_queue_resource_usage,
        cluster_queue_weighted_share / cohort_weighted_share."""
        from kueue_tpu.core.resources import FlavorResource

        # One snapshot serves the cohort-subtree aggregates and the
        # weighted shares; a cohort-hierarchy cycle (ValueError) degrades
        # those series gracefully instead of killing the tick.
        try:
            snapshot = self.cache.snapshot()
        except ValueError:
            snapshot = None
        # Re-evaluate SLO burn rates (once built) so the slo_* gauges on
        # /metrics track the same tick cadence as every other gauge.
        if self._slo is not None:
            self._slo.evaluate()
        self.metrics.set_gauge("build_info", 1, {"framework": "kueue_tpu"})
        for name, cq_spec in self.cache.cluster_queues.items():
            self.metrics.set_gauge(
                "pending_workloads", self.queues.pending_count(name),
                {"cluster_queue": name, "status": "active"},
            )
            active = self.cache.cluster_queue_active(cq_spec)
            self.metrics.set_gauge(
                "cluster_queue_status", 1.0 if active else 0.0,
                {"cluster_queue": name, "status": "active"},
            )
            self.metrics.set_gauge(
                "cluster_queue_info", 1,
                {"cluster_queue": name, "cohort": cq_spec.cohort or "",
                 **self._custom_metric_labels("ClusterQueue", cq_spec)},
            )
            # Spec quota series (metrics.go cluster_queue_nominal_quota /
            # borrowing_limit / lending_limit).
            for rg in cq_spec.resource_groups:
                for fq in rg.flavors:
                    for res, q in fq.resources.items():
                        lbl = {"cluster_queue": name, "flavor": fq.name,
                               "resource": res}
                        self.metrics.set_gauge(
                            "cluster_queue_nominal_quota", q.nominal, lbl
                        )
                        if q.borrowing_limit is not None:
                            self.metrics.set_gauge(
                                "cluster_queue_borrowing_limit",
                                q.borrowing_limit, lbl,
                            )
                        if q.lending_limit is not None:
                            self.metrics.set_gauge(
                                "cluster_queue_lending_limit",
                                q.lending_limit, lbl,
                            )
        for co_name, co in self.cache.cohorts.items():
            self.metrics.set_gauge(
                "cohort_info", 1,
                {"cohort": co_name, "parent": co.parent or "",
                 **self._custom_metric_labels("Cohort", co)},
            )
        # Cohort subtree aggregates (reference metrics.go:919
        # cohort_subtree_quota / _resource_reservations /
        # _admitted_active_workloads): the quota tree's cohort nodes
        # already carry subtree-rolled quota and usage.
        cohort_nodes = []
        stack = list(snapshot.roots) if snapshot is not None else []
        while stack:
            node = stack.pop()
            if not node.is_cq:
                cohort_nodes.append(node)
                stack.extend(node.children)
        for node in cohort_nodes:
            co_obj = self.cache.cohorts.get(node.name)
            extra = self._custom_metric_labels("Cohort", co_obj) \
                if co_obj is not None else {}
            # Iterate the union of quota and usage cells so a cell whose
            # reservations dropped to zero RESETS its gauge instead of
            # exporting the last nonzero value forever.
            for fr in set(node.subtree_quota) | set(node.usage):
                lbl = {"cohort": node.name, "flavor": fr.flavor,
                       "resource": fr.resource, **extra}
                self.metrics.set_gauge(
                    "cohort_subtree_quota",
                    node.subtree_quota.get(fr, 0), lbl,
                )
                self.metrics.set_gauge(
                    "cohort_subtree_resource_reservations",
                    node.usage.get(fr, 0), lbl,
                )
        # Active admitted / reserving counts (metrics.go
        # admitted_active_workloads, reserving_active_workloads).
        admitted_n: Dict[str, int] = {}
        reserving_n: Dict[str, int] = {}
        from kueue_tpu.core.workload_info import is_admitted as _is_adm

        for key3, info3 in self.cache.workloads.items():
            wl3 = self.workloads.get(key3)
            reserving_n[info3.cluster_queue] = (
                reserving_n.get(info3.cluster_queue, 0) + 1
            )
            if wl3 is not None and _is_adm(wl3):
                admitted_n[info3.cluster_queue] = (
                    admitted_n.get(info3.cluster_queue, 0) + 1
                )
        for name in self.cache.cluster_queues:
            self.metrics.set_gauge(
                "admitted_active_workloads", admitted_n.get(name, 0),
                {"cluster_queue": name},
            )
            self.metrics.set_gauge(
                "reserving_active_workloads", reserving_n.get(name, 0),
                {"cluster_queue": name},
            )
        # Per-subtree admitted-active rollup (reference metrics.go:946).
        subtree_admitted: Dict[str, int] = {}
        cq_snaps = snapshot.cluster_queues if snapshot is not None else {}
        for name, cqs in cq_snaps.items():
            node = cqs.node.parent
            while node is not None:
                subtree_admitted[node.name] = (
                    subtree_admitted.get(node.name, 0)
                    + admitted_n.get(name, 0)
                )
                node = node.parent
        for node in cohort_nodes:
            co_obj = self.cache.cohorts.get(node.name)
            extra = self._custom_metric_labels("Cohort", co_obj) \
                if co_obj is not None else {}
            self.metrics.set_gauge(
                "cohort_subtree_admitted_active_workloads",
                subtree_admitted.get(node.name, 0),
                {"cohort": node.name, **extra},
            )
        usage_by_cq: Dict[str, Dict] = {}
        for info in self.cache.workloads.values():
            dst = usage_by_cq.setdefault(info.cluster_queue, {})
            for fr, v in info.usage().items():
                dst[fr] = dst.get(fr, 0) + v
        for cq_name, frs in usage_by_cq.items():
            for fr, v in frs.items():
                self.metrics.set_gauge(
                    "cluster_queue_resource_usage", v,
                    {"cluster_queue": cq_name, "flavor": fr.flavor,
                     "resource": fr.resource},
                )
        # Per-LocalQueue series behind the LocalQueueMetrics gate
        # (reference metrics local_queue_* variants, kube_features
        # LocalQueueMetrics).
        from kueue_tpu.utils import features as _features

        if _features.enabled("LocalQueueMetrics"):
            lq_pending: Dict[str, int] = {}
            lq_admitted: Dict[str, int] = {}
            for cq_name2 in self.cache.cluster_queues:
                for info2 in self.queues.pending_workloads(cq_name2):
                    k2 = f"{info2.obj.namespace}/{info2.obj.queue_name}"
                    lq_pending[k2] = lq_pending.get(k2, 0) + 1
            for key2 in self.cache.workloads:
                wl2 = self.workloads.get(key2)
                if wl2 is not None:
                    k2 = f"{wl2.namespace}/{wl2.queue_name}"
                    lq_admitted[k2] = lq_admitted.get(k2, 0) + 1
            for lq_key2 in self.cache.local_queues:
                self.metrics.set_gauge(
                    "local_queue_pending_workloads",
                    lq_pending.get(lq_key2, 0),
                    {"local_queue": lq_key2},
                )
                self.metrics.set_gauge(
                    "local_queue_admitted_workloads",
                    lq_admitted.get(lq_key2, 0),
                    {"local_queue": lq_key2},
                )

        # Weighted shares need the snapshot's quota tree.
        if snapshot is None:
            return
        for name, cqs in snapshot.cluster_queues.items():
            drs = cqs.dominant_resource_share()
            share = drs.precise_weighted_share()
            if share != float("inf"):
                self.metrics.set_gauge(
                    "cluster_queue_weighted_share", share,
                    {"cluster_queue": name},
                )
        for name, node in snapshot.cohorts.items():
            from kueue_tpu.cache.resource_node import (
                dominant_resource_share,
            )

            drs = dominant_resource_share(node, {})
            share = drs.precise_weighted_share()
            if share != float("inf"):
                self.metrics.set_gauge(
                    "cohort_weighted_share", share, {"cohort": name},
                )

    def run_forever(
        self,
        tick_interval_s: float = 1.0,
        stop_event=None,
    ) -> None:
        """Deprecated daemon mode. The service loop
        (``mgr.service().run_blocking()`` / ``.start()``) is the one
        long-running entry point: same cycles + ticks, plus async
        ingestion, live-health telemetry, and /healthz liveness. This
        shim delegates so existing callers keep working."""
        import warnings

        warnings.warn(
            "Manager.run_forever is deprecated; use "
            "Manager.service(...).run_blocking() (or .start()) instead",
            DeprecationWarning, stacklevel=2,
        )
        if self._service is None:
            self.service(
                tick_interval_s=tick_interval_s,
                idle_sleep_s=min(0.05, tick_interval_s),
            )
        self._service.run_blocking(stop_event=stop_event)

    def run_until_settled(self, max_rounds: int = 1000) -> None:
        """Drive schedule + tick until no more progress."""
        for _ in range(max_rounds):
            result = self.schedule()
            self.tick()
            if not result.admitted and not result.preempted:
                if not result.head_keys:
                    break

    # ------------------------------------------------------------------

    def _admission_blocked(self) -> bool:
        cfg = self.workload_controller.pods_ready
        if not (cfg.enable and cfg.block_admission):
            return False
        from kueue_tpu.core.workload_info import is_admitted as _adm

        for key in self.cache.workloads:
            wl = self.workloads.get(key)
            if wl is None or not _adm(wl):
                continue
            job = self.job_reconciler.job_of_workload.get(key)
            if job is not None and not job.pods_ready():
                return True
        return False

    def _sync_admission_checks(self, wl: Workload) -> None:
        for acs in wl.status.admission_checks:
            if acs.state != CheckState.PENDING:
                continue
            ac = self.cache.admission_checks.get(acs.name)
            if ac is None:
                continue
            ctrl = self.check_controllers.get(ac.controller_name)
            if ctrl is not None:
                ctrl.sync(self, wl, acs.name)

    def _sync_remote_status(self, wl: Workload) -> None:
        """Clock-driven remote mirroring for controllers that track a
        workload on another cluster (MultiKueue: completion/eviction
        mirror-back, worker-lost redispatch)."""
        seen = set()
        for acs in wl.status.admission_checks:
            ac = self.cache.admission_checks.get(acs.name)
            if ac is None or ac.controller_name in seen:
                continue
            seen.add(ac.controller_name)
            ctrl = self.check_controllers.get(ac.controller_name)
            hook = getattr(ctrl, "sync_remote_status", None)
            if hook is not None:
                hook(self, wl)

    def _reconcile_touched_jobs(self, result: CycleResult) -> None:
        touched = set(result.admitted) | set(result.preempted) | set(
            result.preempting
        )
        for key in touched:
            job = self.job_reconciler.job_of_workload.get(key)
            if job is not None:
                self.job_reconciler.reconcile(job)
