"""Perf regression ledger: append-only JSONL history of bench probes.

Every ``bench.py --probe ...`` run appends one schema-versioned record
(probe name, config fingerprint, headline metrics, git rev, jax/jaxlib
versions, host info) to ``PERF_LEDGER.jsonl`` at the repo root. The
checker (tools/check_perf_ledger.py) compares the newest record per
(probe, fingerprint) group against the rolling median of its priors and
fails on regressions past a threshold — a drift alarm that works from
plain files, no metrics backend required.

The fingerprint hashes everything that legitimately changes the numbers
(probe, scale, platform, extra config) so records are only ever compared
against runs of the same shape; a record from a different machine is
still the same fingerprint — host drift is part of what the rolling
median is for (one noisy host won't trip it, a fleet move will).

Writes are best-effort: a read-only checkout or full disk must never
fail the probe itself.
"""

from __future__ import annotations

import hashlib
import json
import os
import platform as _platform
import subprocess
import time
from pathlib import Path
from typing import Dict, List, Optional

SCHEMA_VERSION = 1

#: Headline metrics per probe: metric name -> direction. "higher" means
#: bigger is better (throughput); "lower" means smaller is better
#: (latency). The checker only compares these — the full stats dict is
#: stored for forensics but not gated on.
HEADLINE: Dict[str, Dict[str, str]] = {
    "steady": {
        "admissions_per_s": "higher",
        "cycle_p50_ms": "lower",
        "cycle_p99_ms": "lower",
        "ingest_lag_p99_ms": "lower",
        # v3 (pipelined vs serialized in one invocation): occupancy of
        # the device-dispatch window by speculative host encode, total
        # abandoned speculations, and pipelined-minus-serialized deltas.
        "pipeline_overlap_occupancy_pct": "higher",
        "pipeline_abort_total": "lower",
        "admissions_per_s_delta_pct": "higher",
        "cycle_p99_delta_ms": "lower",
    },
    "sim": {"admissions_per_s": "higher"},
    "fair": {
        "admissions_per_s": "higher",
        "device_wall_s": "lower",
    },
    "whatif": {
        "scenarios_per_s": "higher",
        "batched_wall_s": "lower",
    },
    "incremental": {
        "encode_ms": "lower",
        "full_encode_ms": "lower",
    },
    "coldstart": {
        "speedup_x": "higher",
        "warm_first_admission_s": "lower",
    },
    "fleet": {
        "fleet_joint_speedup": "higher",
        "fleet_dispatch_p99_ms": "lower",
    },
    "scanfloor": {
        "fp_speedup": "higher",
        "rounds_max": "lower",
        # v2: the fair DRS tournament vs its fixed-point rounds.
        "fair_fp_speedup": "higher",
        "fair_rounds_max": "lower",
    },
    "tas": {
        "tas_slot_speedup": "higher",
        "tas_compile_s_delta": "lower",
    },
    # Tiled streaming admission: the bounded-arena peak plane (the
    # memory story) and the live tiled-vs-monolithic wall delta (the
    # honest CPU-box overhead of dispatching per tile).
    "tiled": {
        "tiled_peak_plane_mb": "lower",
        "tiled_vs_mono_delta_pct": "lower",
    },
    # Warm failover (docs/failover.md): takeover latency plus the
    # correctness headliners — the differential vs the unkilled twin run
    # must find nothing lost or duplicated, and the AOT-warm takeover
    # window must pay zero backend compiles (all hard-gated by ``ok``).
    "failover": {
        "failover_takeover_ms": "lower",
        "failover_lost_admissions": "lower",
        "failover_dup_admissions": "lower",
        "failover_takeover_compiles": "lower",
    },
    # Multi-tenant read plane (docs/whatif.md): coalesced-vs-sequential
    # serving speedup at K>=64 equivalent load, query latency under
    # concurrent traffic, snapshot staleness at dispatch, the bounded
    # scenario-plane peak (tiled K, the memory story), and the
    # admission-cycle p99 delta of a read-loaded vs read-idle window
    # (recorded as a headline; the ok gate bounds it inside the probe).
    "readplane": {
        "readplane_coalesced_speedup": "higher",
        "readplane_query_p99_ms": "lower",
        "readplane_staleness_p99_ms": "lower",
        "readplane_cycle_p99_delta_ms": "lower",
        "readplane_peak_plane_mb": "lower",
    },
    # Columnar workload plane (docs/perf.md "Columnar workload plane"):
    # warm-columns full encode vs the row-wise oracle at W=50k, the
    # absolute columnar encode wall, and the per-tile gather slice cost.
    # The probe hard-gates (``ok``) on the 3-seed columns-vs-oracle
    # bit-identity differential before timing anything.
    "encode": {
        "encode_cold_speedup": "higher",
        "encode_50k_ms": "lower",
        "encode_tile_slice_ms": "lower",
    },
}

_REQUIRED_KEYS = (
    "schema_version", "probe", "fingerprint", "ts", "ok",
    "headline", "stats",
)


def default_ledger_path() -> Path:
    """``$KUEUE_TPU_PERF_LEDGER`` or ``PERF_LEDGER.jsonl`` at repo root."""
    env = os.environ.get("KUEUE_TPU_PERF_LEDGER")
    if env:
        return Path(env)
    return Path(__file__).resolve().parents[2] / "PERF_LEDGER.jsonl"


def config_fingerprint(probe: str, scale: float,
                       platform: Optional[str] = None,
                       extra: Optional[dict] = None) -> str:
    """Stable 12-hex digest of the knobs that define a comparable run."""
    doc = {
        "probe": probe,
        "scale": scale,
        "platform": platform or "",
        "extra": extra or {},
    }
    blob = json.dumps(doc, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:12]


def headline_metrics(probe: str, stats: dict) -> Dict[str, dict]:
    """Extract {name: {"value", "direction"}} for the probe's headline
    set; metrics absent from (or null in) the stats are skipped."""
    out: Dict[str, dict] = {}
    for name, direction in HEADLINE.get(probe, {}).items():
        v = stats.get(name)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            out[name] = {"value": float(v), "direction": direction}
    return out


def _git_rev() -> Optional[str]:
    try:
        return subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=Path(__file__).resolve().parents[2],
            capture_output=True, text=True, timeout=5,
        ).stdout.strip() or None
    except Exception:  # noqa: BLE001 - no git, no rev
        return None


def _dist_version(name: str) -> Optional[str]:
    try:
        from importlib.metadata import version

        return version(name)
    except Exception:  # noqa: BLE001 - not installed
        return None


def make_record(probe: str, stats: dict, scale: float = 1.0,
                platform: Optional[str] = None,
                extra_config: Optional[dict] = None) -> dict:
    """Build one ledger record from a probe's final stats dict."""
    return {
        "schema_version": SCHEMA_VERSION,
        "probe": probe,
        "fingerprint": config_fingerprint(
            probe, scale, platform=platform, extra=extra_config
        ),
        "ts": time.time(),
        "ok": bool(stats.get("ok")),
        "headline": headline_metrics(probe, stats),
        "stats": stats,
        "config": {
            "scale": scale,
            "platform": platform,
            "extra": extra_config or {},
        },
        "env": {
            "git_rev": _git_rev(),
            "jax": _dist_version("jax"),
            "jaxlib": _dist_version("jaxlib"),
            "python": _platform.python_version(),
            "host": _platform.node(),
            "machine": _platform.machine(),
            "cpus": os.cpu_count(),
        },
    }


def validate_record(rec: dict) -> List[str]:
    """Schema check; returns a list of problems (empty == valid)."""
    errs: List[str] = []
    if not isinstance(rec, dict):
        return ["record is not an object"]
    for k in _REQUIRED_KEYS:
        if k not in rec:
            errs.append(f"missing key {k!r}")
    if rec.get("schema_version") not in (SCHEMA_VERSION,):
        errs.append(
            f"unknown schema_version {rec.get('schema_version')!r}"
        )
    if not isinstance(rec.get("headline", {}), dict):
        errs.append("headline is not an object")
    else:
        for name, h in rec.get("headline", {}).items():
            if not isinstance(h, dict) or "value" not in h \
                    or h.get("direction") not in ("higher", "lower"):
                errs.append(f"malformed headline entry {name!r}")
    if not isinstance(rec.get("stats", {}), dict):
        errs.append("stats is not an object")
    return errs


def append_record(rec: dict, path: Optional[Path] = None) -> bool:
    """Append one JSON line; best-effort (False on any I/O failure).

    Crash-consistent: the whole line goes down as a single O_APPEND
    ``os.write`` followed by fsync, so a kill mid-append can at worst
    leave one torn final line — which ``load_records`` (and the
    check_perf_ledger.py gate) already skip — never interleave with a
    concurrent writer or poison earlier records."""
    p = Path(path) if path is not None else default_ledger_path()
    try:
        line = json.dumps(rec, sort_keys=True, separators=(",", ":"))
        data = (line + "\n").encode()
        fd = os.open(p, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        try:
            os.write(fd, data)
            os.fsync(fd)
        finally:
            os.close(fd)
        return True
    except Exception:  # noqa: BLE001 - ledger must never fail the probe
        return False


def load_records(path: Optional[Path] = None) -> List[dict]:
    """All parseable records in file order; malformed lines skipped."""
    p = Path(path) if path is not None else default_ledger_path()
    out: List[dict] = []
    try:
        text = p.read_text()
    except OSError:
        return out
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict):
            out.append(rec)
    return out
