"""MultiKueue dispatch benchmark (BASELINE.json config #5 shape).

N workloads dispatched from a manager cluster across K worker clusters
(each its own Manager — the in-process analog of the reference's envtest
multi-cluster suite, test/integration/multikueue/suite_test.go:100, scaled
up). Measures end-to-end dispatch throughput: local quota reservation ->
mirror to workers -> first QuotaReserved wins -> losers cleaned up.
"""

from __future__ import annotations

import time
from typing import Dict, List

from kueue_tpu.api.types import (
    AdmissionCheck,
    ClusterQueue,
    FlavorQuotas,
    LocalQueue,
    ResourceFlavor,
    ResourceGroup,
    ResourceQuota,
)
from kueue_tpu.controllers.jobs import BatchJob
from kueue_tpu.controllers.multikueue import MultiKueueController
from kueue_tpu.core.workload_info import is_admitted
from kueue_tpu.manager import Manager


def _cluster(cpu_quota_m: int) -> Manager:
    mgr = Manager()
    mgr.apply(
        ResourceFlavor(name="default"),
        ClusterQueue(
            name="cq",
            resource_groups=[ResourceGroup(
                covered_resources=["cpu"],
                flavors=[FlavorQuotas(
                    name="default",
                    resources={"cpu": ResourceQuota(nominal=cpu_quota_m)},
                )],
            )],
        ),
        LocalQueue(name="lq", cluster_queue="cq"),
    )
    return mgr


def run(
    n_workloads: int = 2000,
    n_workers: int = 8,
    dispatcher: str = "AllAtOnce",
) -> Dict:
    # Manager cluster holds ample local quota; workers bound the real
    # placement capacity.
    mgr = _cluster(cpu_quota_m=n_workloads * 1000)
    mgr.cache.cluster_queues["cq"].admission_checks = ["mk"]
    mgr.apply(AdmissionCheck(
        name="mk", controller_name="kueue.x-k8s.io/multikueue",
    ))
    mk = MultiKueueController()
    mk.config.dispatcher = dispatcher
    per_worker = (n_workloads * 1000) // n_workers + 1000
    for i in range(n_workers):
        mk.add_worker(f"worker-{i}", _cluster(per_worker))
    mgr.register_check_controller(mk)

    jobs: List[BatchJob] = []
    for i in range(n_workloads):
        job = BatchJob(f"job-{i}", queue="lq", requests={"cpu": 1000})
        mgr.submit_job(job)
        jobs.append(job)

    t0 = time.monotonic()
    rounds = 0
    while rounds < 200:
        mgr.schedule_all()
        dispatched = sum(
            1 for wl in mgr.workloads.values()
            if wl.status.cluster_name is not None
        )
        if dispatched >= n_workloads:
            break
        rounds += 1
    wall = time.monotonic() - t0

    placed: Dict[str, int] = {}
    for wl in mgr.workloads.values():
        if wl.status.cluster_name:
            placed[wl.status.cluster_name] = (
                placed.get(wl.status.cluster_name, 0) + 1
            )
    admitted = sum(1 for wl in mgr.workloads.values() if is_admitted(wl))
    return {
        "n": n_workloads,
        "workers": n_workers,
        "dispatched": sum(placed.values()),
        "admitted": admitted,
        "wall_s": wall,
        "throughput": sum(placed.values()) / wall if wall else 0.0,
        "placement": placed,
    }


def run_joint(
    n_workloads: int = 2000,
    n_workers: int = 8,
    device: bool = True,
    prewarm: bool = True,
) -> Dict:
    """Same fleet shape, admitted through the joint FleetDispatcher:
    one batched solve places the whole pending set, one mirror +
    ``schedule_all`` per cluster lane applies it."""
    from kueue_tpu.fleet import FleetDispatcher

    mgr = _cluster(cpu_quota_m=n_workloads * 1000)
    mgr.cache.cluster_queues["cq"].admission_checks = ["mk"]
    mgr.apply(AdmissionCheck(
        name="mk", controller_name="kueue.x-k8s.io/multikueue",
    ))
    mk = MultiKueueController(fleet=FleetDispatcher(device=device))
    per_worker = (n_workloads * 1000) // n_workers + 1000
    for i in range(n_workers):
        mk.add_worker(f"worker-{i}", _cluster(per_worker))
    mgr.register_check_controller(mk)
    if prewarm and device:
        mgr.prewarm(max_heads=n_workloads, aot=False)

    jobs: List[BatchJob] = []
    for i in range(n_workloads):
        job = BatchJob(f"job-{i}", queue="lq", requests={"cpu": 1000})
        mgr.submit_job(job)
        jobs.append(job)

    t0 = time.monotonic()
    rounds = 0
    while rounds < 200:
        mgr.schedule_all()
        dispatched = sum(
            1 for wl in mgr.workloads.values()
            if wl.status.cluster_name is not None
        )
        if dispatched >= n_workloads:
            break
        rounds += 1
    wall = time.monotonic() - t0

    placed: Dict[str, int] = {}
    for wl in mgr.workloads.values():
        if wl.status.cluster_name:
            placed[wl.status.cluster_name] = (
                placed.get(wl.status.cluster_name, 0) + 1
            )
    admitted = sum(1 for wl in mgr.workloads.values() if is_admitted(wl))
    p99 = mgr.metrics.histogram_quantile("fleet_dispatch_seconds", 0.99)
    return {
        "n": n_workloads,
        "workers": n_workers,
        "dispatched": sum(placed.values()),
        "admitted": admitted,
        "wall_s": wall,
        "throughput": sum(placed.values()) / wall if wall else 0.0,
        "placement": placed,
        "dispatch_p99_ms": (p99 or 0.0) * 1000.0,
        "device_solves": mgr.metrics.get(
            "fleet_dispatches_total", {"path": "device"}
        ),
        "host_solves": mgr.metrics.get(
            "fleet_dispatches_total", {"path": "host"}
        ),
    }


if __name__ == "__main__":
    import json
    import sys

    stats = run(
        n_workloads=int(sys.argv[1]) if len(sys.argv) > 1 else 2000,
    )
    print(json.dumps(stats, indent=2))
