"""Compile-performance subsystem: persistent compile cache + AOT store.

A fresh admission sidecar must serve <100ms cycles immediately, but
every new process used to re-jit every solver variant from scratch —
multi-second time-to-first-admission per entry point. This module kills
the cold start in three layers:

1. **Persistent compilation cache** — :func:`configure` points JAX's
   on-disk compilation cache at a directory (``KUEUE_TPU_COMPILE_CACHE``
   env or explicit argument), so a backend compile in one process is a
   disk hit in the next. The threshold knobs are forced to cache *every*
   executable (the default minimums skip exactly the small solver
   programs this service runs).
2. **Compile observability** — :func:`install_listeners` bridges
   ``jax.monitoring`` events into the metrics registry
   (``solver_compile_seconds``, ``solver_compile_cache_hits_total``,
   ``solver_compile_cache_misses_total``) and a process-local
   :func:`stats` counter block that the compile-count regression tests
   assert against.
3. **AOT executable store** — :func:`prewarm_entry` lowers, compiles and
   serializes a solver entry point for one bucket shape
   (``jax.experimental.serialize_executable``); :func:`dispatch` loads
   the stored executable on the next cold start and calls it directly,
   skipping even the persistent-cache compile round-trip. Entries are
   keyed by (entry point, argument shape signature, static config,
   device kind, jax/jaxlib version) and carry a sha256 integrity
   digest; any mismatch, deserialize failure, or injected
   ``compile.deserialize`` fault falls back to the plain jitted call —
   behind a circuit breaker so a corrupt store cannot stall admission
   with repeated load attempts.

CAUTION — serialization writes: this jaxlib intermittently segfaults
inside PJRT ``executable.serialize()`` under heavy cumulative compile
load (the reason tests/conftest.py disables the persistent cache by
default and tools/run_isolated.py exists). AOT stores therefore happen
ONLY inside explicit prewarm calls — never on the admission hot path —
and the persistent cache stays opt-in for the test suite.

Zero-cost when disabled, same pattern as ``tracing.ENABLED`` /
``faults.ENABLED``: :func:`dispatch` is a straight passthrough call
until an AOT store is configured.
"""

from __future__ import annotations

import hashlib
import os
import pickle
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

from kueue_tpu.metrics import tracing
from kueue_tpu.utils import faults
from kueue_tpu.utils.breaker import CircuitBreaker

# Fast flags, mutated only under _lock by configure()/enable_aot()/reset().
ENABLED = False  # persistent compilation cache configured
AOT_ENABLED = False  # AOT executable store configured

ENV_VAR = "KUEUE_TPU_COMPILE_CACHE"
_AOT_SUBDIR = "aot"

_lock = threading.Lock()
_cache_dir: Optional[str] = None
_aot: Optional["AOTCache"] = None
_listeners_installed = False

# Process-local counters (see stats()): the compile-count regression
# tests assert on backend_compiles, the coldstart probe reports the rest.
_stats = {
    "cache_hits": 0,  # persistent-cache disk hits
    "cache_misses": 0,  # persistent-cache misses (real backend compiles)
    "backend_compiles": 0,  # backend compile requests (hits + misses)
    "compile_seconds": 0.0,
    "aot_hits": 0,  # dispatches served by a deserialized executable
    "aot_load_failures": 0,  # integrity/deserialize failures (contained)
    "prewarmed": 0,  # entries compiled by prewarm_entry
}

_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_MISS_EVENT = "/jax/compilation_cache/cache_misses"
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


def stats() -> Dict[str, Any]:
    return dict(_stats)


def reset_stats() -> None:
    for k in _stats:
        _stats[k] = 0.0 if k == "compile_seconds" else 0


def _on_event(event: str, *args, **kwargs) -> None:
    if event == _HIT_EVENT:
        _stats["cache_hits"] += 1
        if tracing.ENABLED:
            tracing.inc("solver_compile_cache_hits_total")
    elif event == _MISS_EVENT:
        _stats["cache_misses"] += 1
        if tracing.ENABLED:
            tracing.inc("solver_compile_cache_misses_total")


def _on_duration(event: str, duration: float, *args, **kwargs) -> None:
    if event == _COMPILE_EVENT:
        _stats["backend_compiles"] += 1
        _stats["compile_seconds"] += duration
        if tracing.ENABLED:
            tracing.observe("solver_compile_seconds", duration)


def install_listeners() -> None:
    """Bridge jax.monitoring compile/cache events into stats() and the
    metrics registry. Idempotent; listener registration has no public
    removal API, so the bridge stays for the process lifetime."""
    global _listeners_installed
    with _lock:
        if _listeners_installed:
            return
        from jax._src import monitoring

        monitoring.register_event_listener(_on_event)
        monitoring.register_event_duration_secs_listener(_on_duration)
        _listeners_installed = True


def configure(cache_dir: Optional[str] = None,
              enable_aot: bool = True) -> Optional[str]:
    """Enable the persistent compilation cache (and, by default, the AOT
    executable store under ``<dir>/aot``). ``cache_dir`` defaults to the
    ``KUEUE_TPU_COMPILE_CACHE`` environment variable; returns the
    configured directory, or None when neither is set. Idempotent."""
    global ENABLED, _cache_dir
    cache_dir = cache_dir or os.environ.get(ENV_VAR) or None
    if not cache_dir:
        return None
    import jax

    cache_dir = os.path.abspath(cache_dir)
    os.makedirs(cache_dir, exist_ok=True)
    with _lock:
        jax.config.update("jax_enable_compilation_cache", True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # The defaults skip small/fast programs — exactly the solver
        # executables this service runs. Cache everything.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        ENABLED = True
        _cache_dir = cache_dir
    install_listeners()
    if enable_aot:
        activate_aot(os.path.join(cache_dir, _AOT_SUBDIR))
    return cache_dir


def activate_aot(aot_dir: str) -> "AOTCache":
    """Point :func:`dispatch` / :func:`prewarm_entry` at an on-disk AOT
    executable store (normally called via :func:`configure`)."""
    global AOT_ENABLED, _aot
    with _lock:
        if _aot is None or _aot.root != os.path.abspath(aot_dir):
            _aot = AOTCache(aot_dir)
        AOT_ENABLED = True
    install_listeners()
    return _aot


def reset() -> None:
    """Drop the AOT store binding and counters (tests). The persistent
    jax cache config is left as-is — flipping it mid-process would
    invalidate nothing and confuse everything."""
    global AOT_ENABLED, _aot, ENABLED, _cache_dir
    with _lock:
        AOT_ENABLED = False
        _aot = None
        ENABLED = False
        _cache_dir = None
    reset_stats()


def cache_dir() -> Optional[str]:
    return _cache_dir


def _device_fingerprint() -> str:
    import jax

    dev = jax.devices()[0]
    return f"{dev.platform}/{dev.device_kind}"


def _versions() -> str:
    import jax
    import jaxlib

    return f"jax={jax.__version__};jaxlib={jaxlib.__version__}"


def signature(args: Tuple[Any, ...], static: Tuple[Any, ...] = ()) -> str:
    """Stable shape/dtype/pytree signature of a call — the part of the
    AOT key that varies per bucket. Static (closure-baked) parameters
    must be passed explicitly: they select a different compiled program
    without appearing in the argument avals."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(args)
    parts = []
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            parts.append(f"py:{type(leaf).__name__}:{leaf!r}")
        else:
            parts.append(f"{dtype}{tuple(shape)}")
    return f"{treedef}|{';'.join(parts)}|static={static!r}"


class AOTCache:
    """On-disk store of serialized solver executables.

    File layout: ``<root>/<entry>-<digest16>.aot`` where the digest is
    sha256 over (entry, signature, device kind, versions). Payload
    format: 64 ascii hex chars (sha256 of the body) + ``\\n`` + pickled
    ``(serialized_executable, in_tree, out_tree)``. Loads verify the
    digest before unpickling; every failure mode (missing file, bad
    digest, unpickle error, deserialize error, injected
    ``compile.deserialize`` fault) returns None and lets the caller fall
    back to the plain jit path. A circuit breaker stops repeated load
    attempts against a persistently corrupt store."""

    def __init__(self, root: str,
                 breaker: Optional[CircuitBreaker] = None) -> None:
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self.breaker = breaker or CircuitBreaker(
            threshold=3, backoff_s=60.0, max_backoff_s=600.0
        )
        self._loaded: Dict[str, Any] = {}

    # -- keying --------------------------------------------------------

    def key(self, entry: str, sig: str) -> str:
        blob = "\x00".join(
            (entry, sig, _device_fingerprint(), _versions())
        ).encode()
        return hashlib.sha256(blob).hexdigest()

    def path_for(self, entry: str, sig: str) -> str:
        safe = "".join(c if c.isalnum() or c in "._-" else "_"
                       for c in entry)
        return os.path.join(
            self.root, f"{safe}-{self.key(entry, sig)[:16]}.aot"
        )

    # -- store / load --------------------------------------------------

    def store(self, entry: str, sig: str, compiled) -> str:
        """Serialize a Compiled executable to disk (atomic rename).
        ONLY call from prewarm paths — see the module caution on the
        jaxlib serialize() hazard."""
        from jax.experimental import serialize_executable as se

        payload = pickle.dumps(se.serialize(compiled))
        digest = hashlib.sha256(payload).hexdigest().encode()
        path = self.path_for(entry, sig)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(digest + b"\n" + payload)
        os.replace(tmp, path)
        return path

    def load(self, entry: str, sig: str):
        """Deserialize-and-load the stored executable for (entry, sig),
        or None. Never raises: corruption is this store's threat model,
        not its failure mode."""
        path = self.path_for(entry, sig)
        if not os.path.exists(path):
            return None
        if not self.breaker.allow():
            return None
        try:
            if faults.ENABLED:
                faults.fire(faults.COMPILE_DESERIALIZE)
            with open(path, "rb") as f:
                blob = f.read()
            digest, sep, payload = blob.partition(b"\n")
            if not sep or hashlib.sha256(payload).hexdigest() != \
                    digest.decode("ascii", "replace"):
                raise ValueError(f"integrity digest mismatch in {path}")
            from jax.experimental import serialize_executable as se

            exe = se.deserialize_and_load(*pickle.loads(payload))
            self.breaker.record_success()
            return exe
        except Exception:
            _stats["aot_load_failures"] += 1
            self.breaker.record_failure()
            return None


_PROBE = object()  # sentinel: "not probed yet" vs "probed, absent"

# Most recent (fn, args, static) per entry, recorded by dispatch() while
# the AOT store is active, so an explicit prewarm (store_recorded) can
# serialize executables whose call shapes only exist at dispatch time
# (the whatif rollout). Holds device-array references — bounded by the
# number of distinct entry points, and only when AOT is opted in.
_recorded: Dict[str, Tuple[Callable, Tuple, Tuple]] = {}


def dispatch(entry: str, fn: Callable, *args, static: Tuple = ()):
    """Call a jitted solver entry point through the AOT store.

    Passthrough (one module-flag read) when no store is configured. With
    a store: the first call per (entry, shape signature, static) probes
    the store; a loaded executable serves this and every later matching
    call with zero compiles, anything else falls back to ``fn(*args)``
    (which compiles once through the persistent cache). ``static`` must
    carry closure-baked parameters (fair s_max, rollout kernel/horizon)
    that select a different program without changing argument shapes."""
    aot = _aot
    if not AOT_ENABLED or aot is None:
        return fn(*args)
    _recorded[entry] = (fn, args, static)
    sig = signature(args, static)
    ck = f"{entry}|{sig}"
    exe = aot._loaded.get(ck, _PROBE)
    if exe is _PROBE:
        exe = aot.load(entry, sig)
        aot._loaded[ck] = exe
    if exe is not None:
        try:
            out = exe(*args)
            _stats["aot_hits"] += 1
            return out
        except Exception:
            # Aval/layout drift between store time and now: disable this
            # entry for the process and take the jit path.
            aot._loaded[ck] = None
    return fn(*args)


def prewarm_entry(entry: str, fn: Callable, args: Tuple,
                  static: Tuple = (), aot: bool = True) -> float:
    """Compile one solver entry point for one bucket shape: call the
    jitted ``fn`` (seeding the in-process jit cache and, when enabled,
    the persistent cache), then — if an AOT store is configured and the
    executable is not already on disk — lower/compile/serialize it.
    Returns wall seconds."""
    import jax

    t0 = time.monotonic()
    out = fn(*args)
    jax.block_until_ready(out)
    _stats["prewarmed"] += 1
    store = _aot
    if aot and AOT_ENABLED and store is not None:
        sig = signature(args, static)
        if not os.path.exists(store.path_for(entry, sig)):
            # With the persistent cache warm this backend compile is a
            # disk hit; the serialize cost is the real work here.
            compiled = fn.lower(*args).compile()
            store.store(entry, sig, compiled)
        store._loaded.pop(f"{entry}|{sig}", None)
    return time.monotonic() - t0


def store_recorded(entries: Optional[Tuple[str, ...]] = None
                   ) -> Dict[str, str]:
    """Serialize the most recently dispatched call of each recorded
    entry point into the AOT store (skipping ones already on disk).
    Prewarm-only, same serialize() hazard as :meth:`AOTCache.store` —
    callers are explicit warmup paths like ``WhatIfEngine.prewarm``,
    never the admission loop. Returns {entry: path} for what's now
    stored."""
    out: Dict[str, str] = {}
    store = _aot
    if not AOT_ENABLED or store is None:
        return out
    for entry, (fn, args, static) in list(_recorded.items()):
        if entries is not None and entry not in entries:
            continue
        sig = signature(args, static)
        path = store.path_for(entry, sig)
        if not os.path.exists(path):
            compiled = fn.lower(*args).compile()
            store.store(entry, sig, compiled)
        store._loaded.pop(f"{entry}|{sig}", None)
        out[entry] = path
    return out
