"""Performance/scalability harness.

Behavioral surface: reference test/performance/scheduler — the generator
(configs/*/generator.yaml: cohorts x queuesSets x workloadsSets with
creation intervals, runtimes, priorities), the runner (mimics workload
execution by completing after runtimeMs — no real pods), and the checker
(rangespec.yaml expectation bands: maxWallMs, per-CQ-class min utilization,
per-workload-class max avg time-to-admission).

Time model: a virtual clock drives creation intervals and runtimes, so the
recorded per-class admission latencies are directly comparable with the
reference's calibrated rangespecs; the wall-clock spent scheduling is
reported separately (the TPU-native speed metric).
"""

from __future__ import annotations

import heapq
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import yaml

from kueue_tpu.api.constants import PreemptionPolicy
from kueue_tpu.api.types import (
    ClusterQueue,
    ClusterQueuePreemption,
    Cohort,
    FlavorQuotas,
    LocalQueue,
    PodSet,
    ResourceFlavor,
    ResourceGroup,
    ResourceQuota,
    Topology,
    TopologyRequest,
    Workload,
)
from kueue_tpu.tas.snapshot import Node
from kueue_tpu.core.workload_info import get_condition
from kueue_tpu.manager import Manager
from kueue_tpu.metrics import tracing

CREATE, COMPLETE = 0, 1


def _parse_q(v, resource: str) -> int:
    from kueue_tpu.api.serialization import parse_quantity

    return parse_quantity(v, resource)


def _wl_cpu(wl) -> int:
    return sum(ps.requests.get("cpu", 0) * ps.count for ps in wl.pod_sets)


@dataclass
class GeneratedWorkload:
    wl: Workload
    klass: str
    cq_name: str
    cq_class: str
    create_at: float
    runtime_s: float
    admitted_at: Optional[float] = None
    completed_at: Optional[float] = None
    running: bool = False
    expected_completion: Optional[float] = None


@dataclass
class RunResult:
    total_workloads: int = 0
    admitted: int = 0
    virtual_wall_s: float = 0.0
    scheduling_wall_s: float = 0.0
    cycles: int = 0
    # workload class -> average time-to-admission (virtual seconds)
    avg_time_to_admission_s: Dict[str, float] = field(default_factory=dict)
    # CQ class -> minimum average utilization %
    cq_class_min_usage_pct: Dict[str, float] = field(default_factory=dict)
    # Populated only when run(..., trace=True): span name -> total seconds,
    # and the full Chrome trace_event document (Perfetto-loadable).
    phase_breakdown: Optional[Dict[str, float]] = None
    trace: Optional[dict] = None
    # Prometheus text exposition of the run's Manager registry.
    metrics_text: Optional[str] = None

    def throughput(self) -> float:
        if self.scheduling_wall_s <= 0:
            return 0.0
        return self.admitted / self.scheduling_wall_s


def generate(config: dict) -> Tuple[Manager, List[GeneratedWorkload]]:
    """Build the control plane + workload stream from a generator config
    (reference test/performance/scheduler generator.yaml schema)."""
    mgr = Manager(fair_sharing=bool(
        (config.get("fairSharing") or {}).get("enable")
    ))
    flavor_name = "default"
    # Optional topology section (reference configs/tas/generator.yaml).
    topo_cfg = config.get("topology")
    if topo_cfg:
        levels = [lv["nodeLabel"] for lv in topo_cfg.get("levels", [])]
        mgr.apply(Topology(name=topo_cfg.get("name", "topo"), levels=levels))
        rf_cfg = config.get("resourceFlavor", {})
        flavor_name = rf_cfg.get("name", "tas-flavor")
        mgr.apply(ResourceFlavor(
            name=flavor_name,
            topology_name=topo_cfg.get("name", "topo"),
        ))
        # Materialize the node fleet from the per-level counts.
        counts = [lv.get("count", 1) for lv in topo_cfg.get("levels", [])]
        leaf_cfg = topo_cfg.get("levels", [])[-1] if topo_cfg.get("levels") \
            else {}
        cap = {
            r: _parse_q(v, r)
            for r, v in (leaf_cfg.get("capacity") or {"cpu": "96"}).items()
        }

        def emit(prefix, values, level):
            if level == len(counts) - 1:
                for i in range(counts[level]):
                    name = "-".join(values + [str(i)]) or f"n{i}"
                    labels = {
                        levels[d]: "-".join(values[: d + 1])
                        for d in range(len(values))
                    }
                    mgr.apply(Node(name=f"node-{name}", labels=labels,
                                   capacity=dict(cap)))
                return
            for i in range(counts[level]):
                emit(prefix, values + [f"{prefix}{level}x{i}"], level + 1)

        emit("l", [], 0)
    else:
        mgr.apply(ResourceFlavor(name="default"))
    out: List[GeneratedWorkload] = []

    for cohort_set in config.get("cohorts", []):
        cohort_class = cohort_set.get("className", "cohort")
        for ci in range(cohort_set.get("count", 1)):
            cohort_name = f"{cohort_class}-{ci}"
            mgr.apply(Cohort(name=cohort_name))
            for queue_set in cohort_set.get("queuesSets", []):
                cq_class = queue_set.get("className", "cq")
                nominal = _parse_q(queue_set.get("nominalQuota", 10), "cpu")
                borrowing = queue_set.get("borrowingLimit")
                for qi in range(queue_set.get("count", 1)):
                    cq_name = f"{cohort_name}-{cq_class}-{qi}"
                    cq = ClusterQueue(
                        name=cq_name,
                        cohort=cohort_name,
                        resource_groups=[
                            ResourceGroup(
                                covered_resources=["cpu"],
                                flavors=[FlavorQuotas(
                                    name=flavor_name,
                                    resources={"cpu": ResourceQuota(
                                        nominal=nominal,
                                        borrowing_limit=(
                                            _parse_q(borrowing, "cpu")
                                            if borrowing is not None
                                            else None
                                        ),
                                    )},
                                )],
                            )
                        ],
                        preemption=ClusterQueuePreemption(
                            reclaim_within_cohort=PreemptionPolicy(
                                queue_set.get("reclaimWithinCohort", "Never")
                            ),
                            within_cluster_queue=PreemptionPolicy(
                                queue_set.get("withinClusterQueue", "Never")
                            ),
                        ),
                    )
                    mgr.apply(cq)
                    lq = LocalQueue(name=f"lq-{cq_name}",
                                    cluster_queue=cq_name)
                    mgr.apply(lq)
                    for ws in queue_set.get("workloadsSets", []):
                        interval_s = ws.get("creationIntervalMs", 0) / 1000.0
                        t = 0.0
                        n = ws.get("count", 0)
                        specs = ws.get("workloads", [])
                        for i in range(n):
                            spec = specs[i % len(specs)]
                            t += interval_s
                            tr = None
                            constraint = spec.get("tasConstraint")
                            if constraint:
                                level = spec.get("tasLevel")
                                tr = TopologyRequest(
                                    required_level=(
                                        level if constraint == "required"
                                        else None
                                    ),
                                    preferred_level=(
                                        level if constraint in
                                        ("preferred", "balanced") else None
                                    ),
                                    balanced=(constraint == "balanced"),
                                )
                            wl = Workload(
                                name=(
                                    f"{cq_name}-{spec.get('className', 'wl')}"
                                    f"-{i}"
                                ),
                                queue_name=lq.name,
                                priority=spec.get("priority", 0),
                                pod_sets=[PodSet(
                                    name="main",
                                    count=spec.get("podCount", 1),
                                    requests={
                                        "cpu": _parse_q(
                                            spec.get("request", 1), "cpu"
                                        )
                                    },
                                    topology_request=tr,
                                )],
                            )
                            out.append(GeneratedWorkload(
                                wl=wl,
                                klass=spec.get("className", "wl"),
                                cq_name=cq_name,
                                cq_class=cq_class,
                                create_at=t,
                                runtime_s=(
                                    spec.get("runtimeMs", 100) / 1000.0
                                ),
                            ))
    return mgr, out


def _remote_trace_probe() -> None:
    """One traced gRPC round-trip against an in-process worker, so the
    exported trace contains a worker-side span carrying the caller's
    trace id (the cross-boundary propagation proof)."""
    try:
        from kueue_tpu.remote.grpc_transport import (
            GrpcWorkerClient,
            serve_worker_grpc,
        )
    except Exception:  # pragma: no cover - grpc not installed
        return
    worker_mgr = Manager()
    server, bound = serve_worker_grpc(worker_mgr, in_thread=True)
    try:
        client = GrpcWorkerClient(bound)
        with tracing.span("harness/remote_probe"):
            client.schedule()
        client.close()
    finally:
        server.stop(0)


def run(config: dict, trace: bool = False,
        trace_remote: bool = False) -> RunResult:
    """Event-driven virtual-time simulation (reference runner/main.go:118
    'mimic workload execution').

    With ``trace=True`` the run executes under the admission-cycle tracer:
    the result carries the per-phase wall breakdown, the Chrome trace JSON
    and the /metrics exposition. ``trace_remote=True`` additionally drives
    one traced gRPC round-trip against an in-process worker so the trace
    demonstrates cross-boundary trace-id propagation."""
    mgr, gens = generate(config)
    if not trace:
        return _run_sim(mgr, gens)
    was_enabled = tracing.enabled()
    tracer = tracing.enable(mgr.metrics)
    tracer.clear()
    try:
        result = _run_sim(mgr, gens)
        if trace_remote:
            _remote_trace_probe()
        result.phase_breakdown = tracing.phase_breakdown()
        result.trace = tracer.export_chrome_trace()
        result.metrics_text = mgr.metrics.expose()
        return result
    finally:
        if not was_enabled:
            tracing.disable()


def _run_sim(mgr: Manager, gens: List[GeneratedWorkload]) -> RunResult:
    by_key = {g.wl.key: g for g in gens}
    nominal_of: Dict[str, int] = {}
    class_of_cq: Dict[str, str] = {}
    for g in gens:
        class_of_cq[g.cq_name] = g.cq_class
    for name, cq in mgr.cache.cluster_queues.items():
        nominal_of[name] = sum(
            q.nominal
            for rg in cq.resource_groups
            for fq in rg.flavors
            for q in fq.resources.values()
        )

    events: List[Tuple[float, int, int, str]] = []  # (t, kind, seq, key)
    for i, g in enumerate(gens):
        heapq.heappush(events, (g.create_at, CREATE, i, g.wl.key))

    vclock = 0.0
    usage_now: Dict[str, int] = {name: 0 for name in nominal_of}
    usage_integral: Dict[str, float] = {name: 0.0 for name in nominal_of}
    last_sample = 0.0
    sched_wall = 0.0
    cycles = 0
    result = RunResult(total_workloads=len(gens))
    seq = len(gens)
    finished = 0

    def advance_to(t: float) -> None:
        nonlocal last_sample, vclock
        dt = t - last_sample
        if dt > 0:
            for name, u in usage_now.items():
                usage_integral[name] += u * dt
        last_sample = t
        vclock = t

    def handle_event(kind: int, key: str) -> None:
        nonlocal finished
        g = by_key[key]
        if kind == CREATE:
            mgr.create_workload(g.wl)
            return
        # COMPLETE: valid only if still running and this is the live
        # completion (preemption reschedules a fresh one on re-admission).
        if g.running and g.completed_at is None and \
                g.expected_completion is not None and \
                abs(g.expected_completion - vclock) < 1e-9:
            g.completed_at = vclock
            g.running = False
            usage_now[g.cq_name] -= _wl_cpu(g.wl)
            finished += 1
            mgr.finish_workload(g.wl)

    def drain_scheduler() -> None:
        """Run cycles until quiescent: on every admission schedule the run
        (possibly a re-run after preemption); on every preemption release
        the victim's simulated usage."""
        nonlocal cycles, seq, sched_wall
        t0 = time.monotonic()
        for _ in range(1000):  # safety cap per event batch
            r = mgr.schedule()
            cycles += 1
            for pkey in r.preempted:
                pg = by_key.get(pkey)
                if pg is not None and pg.running:
                    pg.running = False
                    pg.expected_completion = None
                    usage_now[pg.cq_name] -= _wl_cpu(pg.wl)
            for akey in r.admitted:
                ag = by_key.get(akey)
                if ag is None or ag.running:
                    continue
                if ag.admitted_at is None:
                    ag.admitted_at = vclock
                ag.running = True
                ag.expected_completion = vclock + ag.runtime_s
                usage_now[ag.cq_name] += _wl_cpu(ag.wl)
                seq += 1
                heapq.heappush(
                    events,
                    (ag.expected_completion, COMPLETE, seq, akey),
                )
            if not r.admitted and not r.preempted:
                break
        sched_wall += time.monotonic() - t0

    while events:
        t, kind, _seq, key = heapq.heappop(events)
        advance_to(t)
        handle_event(kind, key)
        while events and events[0][0] <= vclock + 1e-9:
            _t2, kind2, _s2, key2 = heapq.heappop(events)
            handle_event(kind2, key2)
        drain_scheduler()

    advance_to(vclock)
    result.virtual_wall_s = vclock
    result.scheduling_wall_s = sched_wall
    result.cycles = cycles
    result.admitted = sum(1 for g in gens if g.admitted_at is not None)

    sums: Dict[str, List[float]] = {}
    for g in gens:
        if g.admitted_at is not None:
            sums.setdefault(g.klass, []).append(g.admitted_at - g.create_at)
    result.avg_time_to_admission_s = {
        k: sum(v) / len(v) for k, v in sums.items()
    }

    per_class_util: Dict[str, List[float]] = {}
    for name, integral in usage_integral.items():
        if vclock <= 0 or nominal_of.get(name, 0) <= 0:
            continue
        util = 100.0 * integral / (vclock * nominal_of[name])
        per_class_util.setdefault(class_of_cq.get(name, "cq"), []).append(util)
    result.cq_class_min_usage_pct = {
        k: min(v) for k, v in per_class_util.items()
    }
    return result


def check(result: RunResult, rangespec: dict) -> List[str]:
    """Compare against a rangespec (reference checker). Returns violations;
    empty list = pass."""
    violations: List[str] = []
    cmd = rangespec.get("cmd", {})
    max_wall_ms = cmd.get("maxWallMs")
    if max_wall_ms is not None and result.virtual_wall_s * 1000 > max_wall_ms:
        violations.append(
            f"virtual wall {result.virtual_wall_s*1000:.0f}ms > "
            f"maxWallMs {max_wall_ms}"
        )
    # Real scheduling-compute wall bound (reference rangespecs bound the
    # actual run wall, configs/baseline/rangespec.yaml:7-9 — the virtual
    # clock alone would hide a slow scheduler).
    max_sched_ms = cmd.get("maxSchedulingWallMs")
    if max_sched_ms is not None and \
            result.scheduling_wall_s * 1000 > max_sched_ms:
        violations.append(
            f"scheduling wall {result.scheduling_wall_s*1000:.0f}ms > "
            f"maxSchedulingWallMs {max_sched_ms}"
        )
    for cq_class, floor in (
        rangespec.get("clusterQueueClassesMinUsage") or {}
    ).items():
        got = result.cq_class_min_usage_pct.get(cq_class, 0.0)
        if got < floor:
            violations.append(
                f"cq class {cq_class} min usage {got:.1f}% < floor {floor}%"
            )
    for klass, limit_ms in (
        rangespec.get("wlClassesMaxAvgTimeToAdmissionMs") or {}
    ).items():
        got = result.avg_time_to_admission_s.get(klass)
        if got is None:
            violations.append(f"no admissions for class {klass}")
        elif got * 1000 > limit_ms:
            violations.append(
                f"class {klass} avg time-to-admission {got*1000:.0f}ms > "
                f"{limit_ms}ms"
            )
    return violations


def run_config_files(generator_path: str, rangespec_path: Optional[str] = None,
                     trace: bool = False, trace_remote: bool = False):
    with open(generator_path) as f:
        config = yaml.safe_load(f)
    result = run(config, trace=trace, trace_remote=trace_remote)
    violations = []
    if rangespec_path:
        with open(rangespec_path) as f:
            rangespec = yaml.safe_load(f)
        violations = check(result, rangespec)
    return result, violations
