"""High-availability manager replication: leader lease + warm standby.

Behavioral analog of the reference's HA story: the scheduler only runs on
the elected leader (reference pkg/scheduler/scheduler.go:230
NeedLeaderElection), while non-leader replicas keep their caches warm by
read-only reconciliation so failover is fast (reference
pkg/controller/core/leader_aware_reconciler.go:60 — non-leader replicas
reconcile reads; roletracker labels lead/follow transitions).

The reference delegates durability to etcd (CRD status is the journal) and
leases to the kube leader-election API. Standalone, the same contract is:

  * ``LeaseStore`` — the lease + journal backend (in-process here; the
    same interface maps onto any CAS-capable store).
  * the leader publishes ``Manager.export_state()`` checkpoints and
    appends every accepted client object to an event journal; the
    checkpoint truncates the journal (etcd-compaction analog);
  * followers continuously fold checkpoint+journal into a local standby
    Manager (read-reconcile) WITHOUT scheduling — admissions are the
    leader's exclusive write;
  * on lease expiry a follower promotes: it re-applies the journal tail
    and starts scheduling from the recovered state.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from kueue_tpu.manager import Manager


@dataclass
class Lease:
    """One leader lease record (kube coordination.k8s.io Lease analog)."""

    holder: Optional[str] = None
    term: int = 0
    expires_at: float = 0.0


class LeaseStore:
    """Shared lease + checkpoint + journal. In-process reference backend;
    every mutation is synchronous and linearizable (the CAS the kube
    leader-election client gets from the apiserver)."""

    def __init__(self, lease_duration_s: float = 15.0) -> None:
        self.lease = Lease()
        self.lease_duration_s = lease_duration_s
        self.checkpoint: Optional[str] = None
        self.checkpoint_term: int = 0
        # Journal of (seq, yaml-doc) accepted since the last checkpoint.
        self.journal: List[Tuple[int, str]] = []
        self._seq = itertools.count(1)

    # -- lease ---------------------------------------------------------

    def try_acquire(self, identity: str, now: float) -> bool:
        """Acquire or renew: holder renews unconditionally; others win
        only after expiry (a new term)."""
        if self.lease.holder == identity:
            self.lease.expires_at = now + self.lease_duration_s
            return True
        if self.lease.holder is None or now >= self.lease.expires_at:
            self.lease.holder = identity
            self.lease.term += 1
            self.lease.expires_at = now + self.lease_duration_s
            return True
        return False

    def is_leader(self, identity: str, now: float) -> bool:
        return self.lease.holder == identity and now < self.lease.expires_at

    # -- durable state -------------------------------------------------

    def publish_checkpoint(self, state: str, term: int) -> None:
        self.checkpoint = state
        self.checkpoint_term = term
        self.journal = []

    def append_event(self, doc: str) -> int:
        seq = next(self._seq)
        self.journal.append((seq, doc))
        return seq


@dataclass
class RoleTracker:
    """Lead/follow transition log (reference pkg/util/roletracker)."""

    transitions: List[str] = field(default_factory=list)
    role: str = "follow"

    def observe(self, leading: bool) -> None:
        role = "lead" if leading else "follow"
        if role != self.role:
            self.role = role
            self.transitions.append(role)


class HAReplica:
    """One manager replica participating in leader election.

    Drive it with ``tick(now)``; submit client objects with ``submit``
    (accepted only by the leader — the apiserver would route writes).
    """

    def __init__(self, identity: str, store: LeaseStore,
                 manager_kw: Optional[dict] = None,
                 checkpoint_every: int = 1) -> None:
        self.identity = identity
        self.store = store
        self.manager_kw = dict(manager_kw or {})
        self.manager = Manager(**self.manager_kw)
        self.roletracker = RoleTracker()
        self.checkpoint_every = checkpoint_every
        self._cycles_since_checkpoint = 0
        self._applied_seq = 0
        self._restored_term = 0

    # -- client surface ------------------------------------------------

    def submit(self, obj, now: float) -> bool:
        """Leader-only write: apply the object and journal it. Returns
        False when this replica is not the leader (client retries against
        the current leader)."""
        if not self.store.is_leader(self.identity, now):
            return False
        from kueue_tpu.api.serialization import encode
        import yaml as _yaml

        from kueue_tpu.api.types import Workload

        if isinstance(obj, Workload):
            self.manager.create_workload(obj)
        else:
            self.manager.apply(obj)
        self.store.append_event(_yaml.safe_dump(encode(obj),
                                                sort_keys=False))
        return True

    # -- replication ---------------------------------------------------

    def _read_reconcile(self) -> None:
        """Follower: fold the shared checkpoint + journal into the local
        standby manager (read-only — never schedules, never writes
        admissions; leader_aware_reconciler.go:60 semantics)."""
        store = self.store
        if store.checkpoint is not None and \
                store.checkpoint_term > self._restored_term:
            self.manager = Manager.restore_state(
                store.checkpoint, **self.manager_kw
            )
            self._restored_term = store.checkpoint_term
            self._applied_seq = 0
        from kueue_tpu.api.serialization import load_manifests
        from kueue_tpu.api.types import Workload

        for seq, doc in store.journal:
            if seq <= self._applied_seq:
                continue
            for obj in load_manifests(doc):
                if isinstance(obj, Workload):
                    # Pending client submissions re-enter the queues; the
                    # leader's admission outcomes arrive via checkpoints.
                    if obj.key not in self.manager.workloads:
                        self.manager.create_workload(obj)
                else:
                    self.manager.apply(obj)
            self._applied_seq = seq

    def tick(self, now: float, max_cycles: int = 10) -> dict:
        """One control-loop beat: renew/contend the lease, then act the
        role. Returns {"role", "admitted": [...]} for observability."""
        leading = self.store.try_acquire(self.identity, now)
        admitted: List[str] = []
        if leading and self.roletracker.role != "lead":
            # Fresh promotion: recover the latest durable state first.
            self._read_reconcile()
        self.roletracker.observe(leading)
        if leading:
            for _ in range(max_cycles):
                result = self.manager.schedule()
                admitted.extend(result.admitted)
                if not result.admitted and not result.preempted:
                    break
            self._cycles_since_checkpoint += 1
            if self._cycles_since_checkpoint >= self.checkpoint_every:
                self.store.publish_checkpoint(
                    self.manager.export_state(), self.store.lease.term
                )
                self._cycles_since_checkpoint = 0
        else:
            self._read_reconcile()
        return {"role": self.roletracker.role, "admitted": admitted}

    def stop(self) -> None:
        """Crash/drain this replica: it simply stops ticking; the lease
        expires on its own (no explicit release — the crash path)."""
