"""High-availability admission serving: leader lease, crash-consistent
replication stream, and a warm standby that takes over mid-churn.

Behavioral analog of the reference's HA story: the scheduler only runs on
the elected leader (reference pkg/scheduler/scheduler.go:230
NeedLeaderElection), while non-leader replicas keep their caches warm by
read-only reconciliation so failover is fast (reference
pkg/controller/core/leader_aware_reconciler.go:60 — non-leader replicas
reconcile reads; roletracker labels lead/follow transitions).

The reference delegates durability to etcd (CRD status is the journal) and
leases to the kube leader-election API. Standalone, the same contract is:

  * ``LeaseStore`` — the lease + journal backend (in-process here; the
    same interface maps onto any CAS-capable store). With ``dir=`` it
    also carries a durable :class:`RecordLog` replication stream.
  * the leader publishes ``Manager.export_state()`` checkpoints and
    appends every accepted client object to an event journal; the
    checkpoint truncates the journal (etcd-compaction analog);
  * followers continuously fold checkpoint+journal into a local standby
    Manager (read-reconcile) WITHOUT scheduling — admissions are the
    leader's exclusive write;
  * on lease expiry a follower promotes: it re-applies the journal tail
    and starts scheduling from the recovered state.

Two serving layers share that store:

``HAReplica``
    The original coarse replica: full ``export_state`` checkpoints plus
    a client-object journal, recovered wholesale at promotion. Simple,
    correct, O(state) per checkpoint.

``Replicator`` + ``WarmStandby`` (docs/failover.md)
    The streaming path the service loop uses. The primary's
    :class:`Replicator` hooks ``ServiceLoop.step()`` (obs/service.py)
    under the service lock and appends ONE record per step to the
    store's :class:`RecordLog`: the step's ingested ops, the cache
    workload events drained through the ``workload_events_since``
    cursor, and a compact admitted-set fingerprint. Records are
    length-prefixed, CRC-checked and fsync'd — a torn write at crash is
    detected by framing and truncated at promotion, never replayed. The
    :class:`WarmStandby` prewarms its bucket ladder from the shared AOT
    store (perf/compile_cache.py), tails the stream applying records
    idempotently, and on lease expiry promotes with its arenas already
    generation-consistent — zero backend compiles at takeover.

Every HA state mutation runs inside a ``_contained(...)`` scope: the
named fault points (``ha.checkpoint_write`` / ``ha.event_tail`` /
``ha.takeover``, utils/faults.py) fire at the top of the scope and any
failure lands in the scope's circuit breaker instead of the caller
(docs/fault_containment.md). tools/check_ha_containment.py enforces the
invariant statically.
"""

from __future__ import annotations

import contextlib
import itertools
import json
import os
import struct
import time
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from kueue_tpu.manager import Manager
from kueue_tpu.utils import faults
from kueue_tpu.utils.breaker import CircuitBreaker


@dataclass
class Lease:
    """One leader lease record (kube coordination.k8s.io Lease analog)."""

    holder: Optional[str] = None
    term: int = 0
    expires_at: float = 0.0


# ----------------------------------------------------------------------
# replication stream: length-prefixed, checksummed, fsync'd records
# ----------------------------------------------------------------------

#: Record framing: big-endian (payload length, CRC32 of payload).
_HEADER = struct.Struct(">II")


class RecordLog:
    """Append-only log of JSON records with torn-write detection.

    Each record is ``_HEADER(len, crc32)`` + the JSON payload, written as
    one buffer and fsync'd, so a crash mid-append leaves a tail that
    fails either the length or the checksum — :meth:`scan` stops there
    and the promoting standby calls :meth:`truncate_to` to drop it. A
    *live* tailer must NOT truncate: the primary may legitimately be
    mid-write; torn bytes are final only once the lease has expired.
    """

    def __init__(self, path: str) -> None:
        self.path = path
        self._fd: Optional[int] = None
        self.bytes_written = (
            os.path.getsize(path) if os.path.exists(path) else 0
        )

    def _ensure_fd(self) -> int:
        if self._fd is None:
            self._fd = os.open(
                self.path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644
            )
        return self._fd

    def append(self, doc: dict) -> int:
        """Append one record (single write + fsync); returns the end
        byte offset. On a failed write the file is rolled back to the
        pre-append length so a *live* stream never grows torn bytes —
        only a crash can leave them."""
        payload = json.dumps(doc, separators=(",", ":")).encode()
        buf = _HEADER.pack(len(payload), zlib.crc32(payload)) + payload
        fd = self._ensure_fd()
        pos = self.bytes_written
        try:
            os.write(fd, buf)
            os.fsync(fd)
        except Exception:
            with contextlib.suppress(OSError):
                os.ftruncate(fd, pos)
            raise
        self.bytes_written = pos + len(buf)
        return self.bytes_written

    def scan(self, offset: int) -> Tuple[List[Tuple[dict, int]], bool]:
        """Decode complete records from byte ``offset``; returns
        ``([(doc, end_offset), ...], torn)`` where ``torn`` reports
        undecodable trailing bytes (incomplete header/payload or CRC
        mismatch). Never mutates the file."""
        try:
            with open(self.path, "rb") as f:
                f.seek(offset)
                data = f.read()
        except FileNotFoundError:
            return [], False
        out: List[Tuple[dict, int]] = []
        pos = 0
        while True:
            if pos + _HEADER.size > len(data):
                return out, pos < len(data)
            ln, crc = _HEADER.unpack_from(data, pos)
            end = pos + _HEADER.size + ln
            if end > len(data):
                return out, True
            payload = data[pos + _HEADER.size:end]
            if zlib.crc32(payload) != crc:
                return out, True
            try:
                doc = json.loads(payload)
            except ValueError:
                return out, True
            out.append((doc, offset + end))
            pos = end

    def size(self) -> int:
        try:
            return os.path.getsize(self.path)
        except OSError:
            return 0

    def truncate_to(self, offset: int) -> int:
        """Drop everything past ``offset`` (the promote-time torn-tail
        cut); returns the number of bytes removed."""
        size = self.size()
        if size <= offset:
            return 0
        with open(self.path, "rb+") as f:
            f.truncate(offset)
            f.flush()
            os.fsync(f.fileno())
        self.bytes_written = offset
        return size - offset

    def close(self) -> None:
        if self._fd is not None:
            with contextlib.suppress(OSError):
                os.close(self._fd)
            self._fd = None


class MemoryLog:
    """In-process :class:`RecordLog` twin for stores without a ``dir``
    (offsets are record indices; writes cannot tear)."""

    def __init__(self) -> None:
        self.records: List[dict] = []
        self.bytes_written = 0

    def append(self, doc: dict) -> int:
        self.records.append(doc)
        self.bytes_written += len(json.dumps(doc, separators=(",", ":")))
        return len(self.records)

    def scan(self, offset: int) -> Tuple[List[Tuple[dict, int]], bool]:
        return (
            [(doc, offset + i + 1)
             for i, doc in enumerate(self.records[offset:])],
            False,
        )

    def size(self) -> int:
        return len(self.records)

    def truncate_to(self, offset: int) -> int:
        removed = max(0, len(self.records) - offset)
        del self.records[offset:]
        return removed

    def close(self) -> None:
        pass


class LeaseStore:
    """Shared lease + checkpoint + journal + replication stream.
    In-process reference backend; every mutation is synchronous and
    linearizable (the CAS the kube leader-election client gets from the
    apiserver). With ``dir=`` the replication stream is a durable
    :class:`RecordLog` a fresh process can recover from; without it the
    stream is in-memory (same interface, same tests)."""

    def __init__(self, lease_duration_s: float = 15.0,
                 dir: Optional[str] = None) -> None:
        self.lease = Lease()
        self.lease_duration_s = lease_duration_s
        self.checkpoint: Optional[str] = None
        self.checkpoint_term: int = 0
        # Journal of (seq, yaml-doc) accepted since the last checkpoint.
        self.journal: List[Tuple[int, str]] = []
        self._seq = itertools.count(1)
        self.dir = dir
        if dir:
            os.makedirs(dir, exist_ok=True)
            self.stream = RecordLog(os.path.join(dir, "replication.log"))
        else:
            self.stream = MemoryLog()

    # -- lease ---------------------------------------------------------

    def try_acquire(self, identity: str, now: float) -> bool:
        """Acquire or renew: holder renews unconditionally; others win
        only after expiry (a new term)."""
        if self.lease.holder == identity:
            self.lease.expires_at = now + self.lease_duration_s
            return True
        if self.lease.holder is None or now >= self.lease.expires_at:
            self.lease.holder = identity
            self.lease.term += 1
            self.lease.expires_at = now + self.lease_duration_s
            return True
        return False

    def is_leader(self, identity: str, now: float) -> bool:
        return self.lease.holder == identity and now < self.lease.expires_at

    # -- durable state -------------------------------------------------

    def publish_checkpoint(self, state: str, term: int) -> None:
        self.checkpoint = state
        self.checkpoint_term = term
        self.journal = []

    def append_event(self, doc: str) -> int:
        seq = next(self._seq)
        self.journal.append((seq, doc))
        return seq


@dataclass
class RoleTracker:
    """Lead/follow transition log (reference pkg/util/roletracker)."""

    transitions: List[str] = field(default_factory=list)
    role: str = "follow"

    def observe(self, leading: bool) -> None:
        role = "lead" if leading else "follow"
        if role != self.role:
            self.role = role
            self.transitions.append(role)


# ----------------------------------------------------------------------
# containment
# ----------------------------------------------------------------------


class _Containment:
    """Breaker-guarded fault containment shared by every HA actor. Each
    state-mutation scope fires its named fault point up front and books
    any failure (breaker trip + ``ha_replication_errors_total``) before
    letting it propagate; the call site decides whether to absorb it —
    a generator context manager cannot skip its body, so a fault fired
    on entry must raise, and callers that survive the failure wrap the
    scope in try/except and leave their state un-advanced."""

    breaker: CircuitBreaker

    def _init_containment(self) -> None:
        self.breaker = CircuitBreaker(
            threshold=3, backoff_s=0.05, max_backoff_s=5.0
        )

    def _containment_metrics(self):
        mgr = getattr(self, "manager", None)
        return getattr(mgr, "metrics", None)

    @contextlib.contextmanager
    def _contained(self, point: str):
        try:
            if faults.ENABLED:
                faults.fire(point)
            yield
        except Exception:
            self.breaker.record_failure()
            m = self._containment_metrics()
            if m is not None:
                m.inc("ha_replication_errors_total", {"point": point})
            raise
        else:
            self.breaker.record_success()


# ----------------------------------------------------------------------
# fingerprints / digests
# ----------------------------------------------------------------------


def admitted_fingerprint(manager) -> dict:
    """Compact admitted-set fingerprint streamed with every step record:
    the CRC32 of the sorted admitted keys plus the count. Cheap enough
    for every step; a mismatch on the standby means the replicas have
    diverged and the next full checkpoint must resync."""
    keys = sorted(manager.cache.workloads)
    return {
        "crc": zlib.crc32("\n".join(keys).encode()) & 0xFFFFFFFF,
        "n": len(keys),
    }


def state_digest(manager) -> dict:
    """Canonical, order-independent digest of the control-plane state
    the failover differential gates on: the admitted set with per-key
    admission assignments and usage, aggregate usage per CQ, and the
    pending/finished sets. Condition timestamps are excluded — a standby
    re-deciding an unacked admission does so at a later clock."""
    from kueue_tpu.api.serialization import encode
    from kueue_tpu.core.workload_info import is_finished

    admitted: Dict[str, dict] = {}
    usage: Dict[str, Dict[str, float]] = {}
    for key in sorted(manager.cache.workloads):
        info = manager.cache.workloads[key]
        doc = encode(info.obj)
        admitted[key] = {
            "cq": info.cluster_queue,
            "admission": (doc.get("status") or {}).get("admission"),
            "usage": sorted(
                (str(fr), float(v)) for fr, v in info.usage().items()
            ),
        }
        cq_usage = usage.setdefault(info.cluster_queue, {})
        for fr, v in info.usage().items():
            cq_usage[str(fr)] = cq_usage.get(str(fr), 0.0) + float(v)
    pending = sorted(
        key for key, wl in manager.workloads.items()
        if key not in manager.cache.workloads and not is_finished(wl)
    )
    finished = sorted(
        key for key, wl in manager.workloads.items() if is_finished(wl)
    )
    return {
        "admitted": admitted,
        "usage": {cq: sorted(m.items()) for cq, m in usage.items()},
        "pending": pending,
        "finished": finished,
    }


# ----------------------------------------------------------------------
# primary side: the service-loop replicator
# ----------------------------------------------------------------------


def _encode_ops(batch) -> Tuple[List[dict], int]:
    """Serialize one step's ingested op tuples (obs/service.py post
    format) into stream docs. Returns (ops, opaque_count): ``call`` ops
    carry arbitrary closures and cannot be replayed — the caller must
    follow with a full checkpoint."""
    import yaml as _yaml

    from kueue_tpu.api.serialization import encode

    ops: List[dict] = []
    opaque = 0
    for op in batch:
        kind = op[0]
        if kind == "submit":
            ops.append({
                "op": "submit",
                "doc": _yaml.safe_dump(encode(op[1]), sort_keys=False),
            })
        elif kind == "finish":
            ops.append({
                "op": "finish", "key": op[1], "success": bool(op[2]),
            })
        elif kind == "apply":
            ops.append({
                "op": "apply",
                "docs": [
                    _yaml.safe_dump(encode(o), sort_keys=False)
                    for o in op[1]
                ],
            })
        elif kind == "delete":
            ops.append({
                "op": "delete",
                "doc": _yaml.safe_dump(encode(op[1]), sort_keys=False),
            })
        else:
            ops.append({"op": "opaque", "kind": str(kind)})
            opaque += 1
    return ops, opaque


class Replicator(_Containment):
    """Primary-side stream producer, attached to a ``ServiceLoop`` via
    :meth:`attach`. ``on_step`` runs INSIDE ``step()`` under the service
    lock, after cycles and before telemetry — so every record is durable
    (fsync'd) before any observer sees the step's results: an acked
    admission is always recoverable (write-ahead of the ack).

    Per step it appends one ``step`` record: the batch's ops, the cache
    workload events drained through ``workload_events_since`` (a
    ``CursorLost`` — the cap trimmed past our cursor — forces a full
    checkpoint instead of a gapped stream), and the admitted-set
    fingerprint. Failures trip the breaker; while it is open steps are
    skipped (counted) and the stream is marked dirty so the first
    successful write re-publishes a full checkpoint."""

    def __init__(self, store: LeaseStore, full_every: int = 0) -> None:
        self.store = store
        #: 0 = full checkpoints only on demand (first step, opaque ops,
        #: cursor loss, breaker recovery); N > 0 also every N steps.
        self.full_every = full_every
        self.manager = None
        self._init_containment()
        self._cursor = 0
        self._steps = 0
        self._dirty_full = True
        self.records_written = 0

    def attach(self, service) -> "Replicator":
        service.replicator = self
        self.manager = service.manager
        return self

    def on_step(self, manager, batch) -> None:
        self.manager = manager
        m = manager.metrics
        self._steps += 1
        if not self.breaker.allow():
            self._dirty_full = True
            m.inc("ha_replication_skipped_total")
            return
        try:
            self._write_step(manager, batch, m)
        except Exception:
            # Contained: the step completes regardless; the failed
            # append was rolled back to the previous record boundary,
            # and the first successful write re-publishes a full
            # checkpoint covering the gap.
            self._dirty_full = True

    def _write_step(self, manager, batch, m) -> None:
        from kueue_tpu.cache.cache import CursorLost

        with self._contained(faults.HA_CHECKPOINT_WRITE):
            ops, opaque = _encode_ops(batch)
            if opaque:
                self._dirty_full = True
            try:
                events, cursor = manager.cache.workload_events_since(
                    self._cursor
                )
            except CursorLost as exc:
                # The event-log cap dropped entries we never streamed:
                # resync from the end and ship a full checkpoint rather
                # than a gapped stream.
                self._dirty_full = True
                events, cursor = [], exc.end
            evs: List[dict] = []
            wl_docs: Dict[str, str] = {}
            if events:
                import yaml as _yaml

                from kueue_tpu.api.serialization import encode

                for kind, key, cq, _items, _prio, _uid, info in events:
                    evs.append({"e": int(kind), "key": key, "cq": cq})
                    # Event-time usage was captured in the tuple, but the
                    # workload object is shared and mutable — serialized
                    # here, it carries the step-final status, which is
                    # what the standby must converge to.
                    wl_docs[key] = _yaml.safe_dump(
                        encode(info.obj), sort_keys=False
                    )
            term = self.store.lease.term
            need_full = self._dirty_full or (
                self.full_every > 0
                and self._steps % self.full_every == 0
            )
            if not (ops or evs or need_full):
                return
            b0 = self.store.stream.bytes_written
            if ops or evs:
                self.store.stream.append({
                    "k": "step", "t": term, "ops": ops, "evs": evs,
                    "wl": wl_docs, "cur": cursor,
                    "fp": admitted_fingerprint(manager),
                })
                self.records_written += 1
            if need_full:
                state = manager.export_state()
                self.store.stream.append({
                    "k": "full", "t": term, "state": state,
                    "cur": cursor,
                })
                self.records_written += 1
                self.store.publish_checkpoint(state, term)
                self._dirty_full = False
            m.inc("ha_checkpoint_writes_total")
            m.inc(
                "ha_checkpoint_bytes_total",
                value=float(self.store.stream.bytes_written - b0),
            )
            self._cursor = cursor


# ----------------------------------------------------------------------
# standby side: tail, apply, promote
# ----------------------------------------------------------------------


class WarmStandby(_Containment):
    """A follower that tails the replication stream into its own Manager
    and promotes on lease expiry.

    Record application is idempotent (at-least-once delivery: a failed
    apply never advances the stream offset, so the record is retried),
    and the standby prewarms its device bucket ladder from the shared
    AOT executable store up front — takeover schedules on warm
    executables, zero backend compiles."""

    def __init__(self, identity: str, store: LeaseStore,
                 manager_kw: Optional[dict] = None) -> None:
        self.identity = identity
        self.store = store
        self.manager_kw = dict(manager_kw or {})
        self.manager = Manager(**self.manager_kw)
        self.roletracker = RoleTracker()
        self._init_containment()
        self._offset = 0
        self._cursor = 0
        self._restored_term = 0
        self._prewarm_kw: Optional[dict] = None
        self._opaque_ops = 0
        self.promoted = False
        self.records_applied = 0
        self.fingerprint_mismatches = 0
        self.truncated_bytes = 0
        self.takeover_seconds: Optional[float] = None

    # -- warm-up -------------------------------------------------------

    def prewarm(self, max_heads: int = 16, aot: bool = True) -> dict:
        """Compile/load the standby's bucket ladder now (from the shared
        AOT store when ``aot``), and remember the shape so a full-state
        restore — which rebuilds the Manager — re-warms automatically."""
        self._prewarm_kw = {"max_heads": max_heads, "aot": aot}
        return self.manager.prewarm(max_heads=max_heads, aot=aot)

    # -- stream application --------------------------------------------

    def tail(self, strict: bool = False) -> Tuple[int, bool]:
        """Apply every complete record past our offset; returns
        ``(applied, torn)``. A record that fails to apply stops the scan
        WITHOUT advancing past it (retried next poll); with ``strict``
        the failure propagates (the promote path must not silently skip
        tail state). Torn trailing bytes are reported, never truncated
        here — only :meth:`promote` may cut them, once the primary's
        lease is dead."""
        m = self.manager.metrics
        if not self.breaker.allow():
            m.inc("ha_replication_skipped_total")
            return 0, False
        applied = 0
        entries, torn = self.store.stream.scan(self._offset)
        try:
            with self._contained(faults.HA_EVENT_TAIL):
                for doc, end_offset in entries:
                    self._apply_record(doc)
                    self._offset = end_offset
                    applied += 1
                    self.records_applied += 1
        except Exception:
            # Contained: the offset never advanced past the failed
            # record — at-least-once delivery, retried next poll.
            if strict:
                raise
        m = self.manager.metrics  # a full record replaces the manager
        m.set_gauge(
            "ha_replication_lag_records", float(len(entries) - applied)
        )
        return applied, torn

    def _apply_record(self, doc: dict) -> None:
        if doc.get("k") == "full":
            self._apply_full(doc)
        else:
            self._apply_step(doc)

    def _apply_full(self, doc: dict) -> None:
        with self._contained(faults.HA_EVENT_TAIL):
            self.manager = Manager.restore_state(
                doc["state"], **self.manager_kw
            )
            self._restored_term = int(doc.get("t", 0))
            self._cursor = int(doc.get("cur", 0))
        if self._prewarm_kw is not None:
            # restore_state built a fresh Manager (cold scheduler); the
            # shared AOT store makes this a load, not a compile.
            self.manager.prewarm(**self._prewarm_kw)
        # Rebuild the columnar workload plane in one pass so the first
        # post-takeover cycle gathers instead of cold row-walking.
        self.manager.warm_workload_columns()

    def _apply_step(self, doc: dict) -> None:
        from kueue_tpu.api.serialization import load_manifests
        from kueue_tpu.api.types import Workload
        from kueue_tpu.core.workload_info import (
            WorkloadInfo,
            has_quota_reservation,
            is_admitted,
            is_finished,
        )

        mgr = self.manager
        applied_events = 0
        with self._contained(faults.HA_EVENT_TAIL):
            for op in doc.get("ops", ()):
                kind = op.get("op")
                if kind == "submit":
                    for obj in load_manifests(op["doc"]):
                        if not isinstance(obj, Workload) \
                                or obj.key in mgr.workloads:
                            continue
                        if is_admitted(obj) or has_quota_reservation(obj):
                            # Admitted by the time the primary streamed
                            # it; the cache add arrives as an ev below.
                            mgr.workloads[obj.key] = obj
                        else:
                            mgr.create_workload(obj)
                elif kind == "finish":
                    wl = mgr.workloads.get(op.get("key"))
                    if wl is not None and not is_finished(wl):
                        mgr.finish_workload(
                            wl, success=bool(op.get("success", True))
                        )
                elif kind == "apply":
                    for text in op.get("docs", ()):
                        for obj in load_manifests(text):
                            mgr.apply(obj)
                elif kind == "delete":
                    for obj in load_manifests(op["doc"]):
                        mgr.delete(obj)
                else:
                    # A ``call`` escape-hatch op: not replayable; the
                    # primary marked the stream dirty and a full
                    # checkpoint follows.
                    self._opaque_ops += 1
            decoded: Dict[str, Workload] = {}
            for key, text in (doc.get("wl") or {}).items():
                objs = load_manifests(text)
                if objs:
                    decoded[key] = objs[0]
            for ev in doc.get("evs", ()):
                key = ev.get("key")
                wl = decoded.get(key)
                if int(ev.get("e", 0)) > 0:
                    if wl is None:
                        continue
                    mgr.workloads[key] = wl
                    info = WorkloadInfo(wl, ev.get("cq") or "")
                    info.sync_assignment_from_admission()
                    mgr.cache.add_or_update_workload(info)
                    mgr.queues.delete_workload(wl)
                else:
                    mgr.cache.delete_workload(key)
                    if wl is not None and key in mgr.workloads:
                        mgr.workloads[key] = wl
                        mgr.queues.delete_workload(wl)
                        if not is_finished(wl) and not (
                            is_admitted(wl) or has_quota_reservation(wl)
                        ):
                            # Evicted/requeued on the primary — back to
                            # pending here too.
                            mgr.queues.add_or_update_workload(wl)
                applied_events += 1
        if applied_events:
            mgr.metrics.inc(
                "ha_events_applied_total", value=float(applied_events)
            )
        self._cursor = int(doc.get("cur", self._cursor))
        fp = doc.get("fp")
        if fp:
            mine = admitted_fingerprint(mgr)
            if (fp.get("crc"), fp.get("n")) != (mine["crc"], mine["n"]):
                self.fingerprint_mismatches += 1
                mgr.metrics.inc("ha_fingerprint_mismatch_total")

    # -- control loop --------------------------------------------------

    def poll(self, now: float) -> str:
        """One standby beat: tail the stream; when the lease is
        winnable, promote. Returns the current role."""
        m = self.manager.metrics
        if self.promoted:
            self.store.try_acquire(self.identity, now)
            m.set_gauge("ha_role", 1.0)
            return "lead"
        self.tail()
        lease = self.store.lease
        if lease.holder in (None, self.identity) \
                or now >= lease.expires_at:
            self.promote(now)
        m = self.manager.metrics
        m.set_gauge("ha_role", 1.0 if self.promoted else 0.0)
        self.roletracker.observe(self.promoted)
        return "lead" if self.promoted else "follow"

    def promote(self, now: float) -> bool:
        """Take over: the primary's lease is dead, so the torn tail (if
        any) is final — apply the last complete records, cut the torn
        bytes, acquire the lease. A failure anywhere aborts the
        promotion (retried on the next poll) — the lease is never left
        half-claimed."""
        t0 = time.perf_counter()
        try:
            with self._contained(faults.HA_TAKEOVER):
                replayed, torn = self.tail(strict=True)
                if torn:
                    cut = self.store.stream.truncate_to(self._offset)
                    self.truncated_bytes += cut
                    self.manager.metrics.inc(
                        "failover_truncated_bytes", value=float(cut)
                    )
                if not self.store.try_acquire(self.identity, now):
                    return False
                self.promoted = True
                self.roletracker.observe(True)
                self.takeover_seconds = time.perf_counter() - t0
                m = self.manager.metrics
                m.inc("failover_takeovers_total")
                m.observe("failover_takeover_seconds",
                          self.takeover_seconds)
                m.set_gauge("failover_replayed_records", float(replayed))
        except Exception:
            # Contained: promotion aborts whole — the lease was never
            # claimed; retried on the next poll.
            return False
        return self.promoted


# ----------------------------------------------------------------------
# coarse replica (checkpoint + client-object journal)
# ----------------------------------------------------------------------


class HAReplica(_Containment):
    """One manager replica participating in leader election.

    Drive it with ``tick(now)``; submit client objects with ``submit``
    (accepted only by the leader — the apiserver would route writes).
    """

    def __init__(self, identity: str, store: LeaseStore,
                 manager_kw: Optional[dict] = None,
                 checkpoint_every: int = 1) -> None:
        self.identity = identity
        self.store = store
        self.manager_kw = dict(manager_kw or {})
        self.manager = Manager(**self.manager_kw)
        self.roletracker = RoleTracker()
        self._init_containment()
        self.checkpoint_every = checkpoint_every
        self._cycles_since_checkpoint = 0
        self._applied_seq = 0
        self._restored_term = 0

    # -- client surface ------------------------------------------------

    def submit(self, obj, now: float) -> bool:
        """Leader-only write: apply the object and journal it. Returns
        False when this replica is not the leader (client retries against
        the current leader)."""
        if not self.store.is_leader(self.identity, now):
            return False
        import yaml as _yaml

        from kueue_tpu.api.serialization import encode
        from kueue_tpu.api.types import Workload

        with self._contained(faults.HA_CHECKPOINT_WRITE):
            if isinstance(obj, Workload):
                self.manager.create_workload(obj)
            else:
                self.manager.apply(obj)
            self.store.append_event(_yaml.safe_dump(encode(obj),
                                                    sort_keys=False))
        return True

    # -- replication ---------------------------------------------------

    def _read_reconcile(self) -> None:
        """Follower: fold the shared checkpoint + journal into the local
        standby manager (read-only — never schedules, never writes
        admissions; leader_aware_reconciler.go:60 semantics)."""
        store = self.store
        from kueue_tpu.api.serialization import load_manifests
        from kueue_tpu.api.types import Workload

        with self._contained(faults.HA_EVENT_TAIL):
            if store.checkpoint is not None and \
                    store.checkpoint_term > self._restored_term:
                self.manager = Manager.restore_state(
                    store.checkpoint, **self.manager_kw
                )
                self._restored_term = store.checkpoint_term
                self._applied_seq = 0
            for seq, doc in store.journal:
                if seq <= self._applied_seq:
                    continue
                for obj in load_manifests(doc):
                    if isinstance(obj, Workload):
                        # Pending client submissions re-enter the queues;
                        # the leader's admission outcomes arrive via
                        # checkpoints.
                        if obj.key not in self.manager.workloads:
                            self.manager.create_workload(obj)
                    else:
                        self.manager.apply(obj)
                self._applied_seq = seq

    def tick(self, now: float, max_cycles: int = 10) -> dict:
        """One control-loop beat: renew/contend the lease, then act the
        role. Returns {"role", "admitted": [...]} for observability."""
        leading = self.store.try_acquire(self.identity, now)
        admitted: List[str] = []
        if leading and self.roletracker.role != "lead":
            # Fresh promotion: recover the latest durable state first. A
            # failed recovery aborts the promotion for this tick (never
            # lead on unrecovered state); holding the lease, the replica
            # retries on its next beat.
            try:
                with self._contained(faults.HA_TAKEOVER):
                    self._read_reconcile()
            except Exception:
                return {"role": self.roletracker.role,
                        "admitted": admitted}
        self.roletracker.observe(leading)
        self.manager.metrics.set_gauge(
            "ha_role", 1.0 if leading else 0.0
        )
        if leading:
            for _ in range(max_cycles):
                result = self.manager.schedule()
                admitted.extend(result.admitted)
                if not result.admitted and not result.preempted:
                    break
            self._cycles_since_checkpoint += 1
            if self._cycles_since_checkpoint >= self.checkpoint_every:
                try:
                    with self._contained(faults.HA_CHECKPOINT_WRITE):
                        self.store.publish_checkpoint(
                            self.manager.export_state(),
                            self.store.lease.term,
                        )
                        self._cycles_since_checkpoint = 0
                except Exception:
                    # Contained: the leader keeps serving; the next tick
                    # retries the checkpoint publish.
                    pass
        else:
            self._read_reconcile()
        return {"role": self.roletracker.role, "admitted": admitted}

    def stop(self) -> None:
        """Crash/drain this replica: it simply stops ticking; the lease
        expires on its own (no explicit release — the crash path)."""
