"""MultiKueue: multi-cluster dispatch.

Behavioral surface: reference pkg/controller/admissionchecks/multikueue +
pkg/controller/workloaddispatcher — the manager cluster reserves quota
locally, then mirrors the workload to nominated worker clusters (the
incremental dispatcher nominates up to 3 new workers per round); the first
worker to reserve quota wins, the copies on other workers are deleted, and
the check flips Ready with the winning cluster recorded.

In kueue_tpu a "worker cluster" is another Manager instance (in-process or
remote behind the same interface) — for TPU fleets these are independent
slices/pools, the DCN tier of the placement hierarchy.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from kueue_tpu.api.constants import CheckState
from kueue_tpu.api.types import Workload
from kueue_tpu.core.workload_info import (
    has_quota_reservation,
    is_evicted,
    is_finished,
)
from kueue_tpu.manager import AdmissionCheckController, Manager

INCREMENTAL_DISPATCHER_ROUND_SIZE = 3  # reference incrementaldispatcher.go:56


@dataclass
class MultiKueueConfig:
    """reference multikueue_types.go MultiKueueConfig."""

    name: str
    clusters: List[str] = field(default_factory=list)
    # "AllAtOnce" | "Incremental" (reference config multiKueue dispatcher).
    dispatcher: str = "AllAtOnce"


@dataclass
class _GroupState:
    nominated: List[str] = field(default_factory=list)
    round_started_at: float = 0.0
    winner: Optional[str] = None
    winner_lost_since: Optional[float] = None
    # Remote-status-mirror retry backoff: when the winner's transport is
    # unreachable (breaker open -> fast-fail WorkerUnreachable), the next
    # mirror attempt is deferred to next_sync_at instead of hammering the
    # dead transport every tick.
    sync_backoff_s: float = 0.0
    next_sync_at: float = 0.0


class MultiKueueController(AdmissionCheckController):
    """reference multikueue admissioncheck.go + workload.go wlReconciler."""

    controller_name = "kueue.x-k8s.io/multikueue"

    def __init__(
        self,
        workers: Optional[Dict[str, Manager]] = None,
        config: Optional[MultiKueueConfig] = None,
        nomination_round_seconds: float = 300.0,
        worker_lost_timeout_seconds: float = 900.0,
        remote_sync_backoff_seconds: float = 1.0,
        remote_sync_backoff_max_seconds: float = 60.0,
        fleet=None,
    ) -> None:
        self.workers: Dict[str, Manager] = workers or {}
        self.config = config or MultiKueueConfig(name="default")
        self.nomination_round_seconds = nomination_round_seconds
        # reference config multiKueue.workerLostTimeout: grace before a
        # workload on an unreachable worker is redispatched.
        self.worker_lost_timeout_seconds = worker_lost_timeout_seconds
        self.remote_sync_backoff_seconds = remote_sync_backoff_seconds
        self.remote_sync_backoff_max_seconds = remote_sync_backoff_max_seconds
        self.state: Dict[str, _GroupState] = {}
        # Joint fleet placement (fleet/dispatcher.py): when attached,
        # sync() hands the whole pending batch to one joint solve and
        # only falls back to the sequential race below when the fleet
        # declines (unsupported quota shapes, no reachable workers).
        self.fleet = None
        if fleet is not None:
            self.attach_fleet(fleet)

    def add_worker(self, name: str, manager: Manager) -> None:
        self.workers[name] = manager
        if name not in self.config.clusters:
            self.config.clusters.append(name)

    def attach_fleet(self, fleet) -> "MultiKueueController":
        """Bind a :class:`~kueue_tpu.fleet.FleetDispatcher` to this
        controller (docs/multikueue.md)."""
        self.fleet = fleet.bind(self)
        return self

    # ------------------------------------------------------------------

    def sync(self, manager: Manager, wl: Workload, check_name: str) -> None:
        """reference workload.go:185 Reconcile / :364 reconcileGroup."""
        now = manager.clock()
        st = self.state.setdefault(wl.key, _GroupState())
        acs = next(
            (a for a in wl.status.admission_checks if a.name == check_name),
            None,
        )
        if acs is None:
            return

        clusters = [c for c in self.config.clusters if c in self.workers]
        if not clusters:
            return

        # Joint fleet placement: one batched solve admits every pending
        # candidate across all clusters at once; the sequential race
        # below only runs when the fleet declines the problem.
        if self.fleet is not None and self.fleet.sync(
            manager, wl, check_name
        ):
            return

        # Nominate workers (incremental: rounds of 3; reference
        # incrementaldispatcher.go:92).
        if self.config.dispatcher == "Incremental":
            if not st.nominated or (
                now - st.round_started_at > self.nomination_round_seconds
                and st.winner is None
            ):
                remaining = [c for c in clusters if c not in st.nominated]
                st.nominated.extend(
                    remaining[:INCREMENTAL_DISPATCHER_ROUND_SIZE]
                )
                st.round_started_at = now
        else:
            st.nominated = list(clusters)

        # Mirror the workload to nominated workers (readGroup/createRemote).
        # Unreachable workers are skipped; the reconnect/backoff lives in
        # the transport client (reference multikueuecluster.go).
        for cluster in st.nominated:
            worker = self.workers[cluster]
            try:
                if wl.key not in worker.workloads:
                    copy = wl.clone()
                    copy.status = type(copy.status)()  # fresh remote status
                    worker.create_workload(copy)
            except ValueError:
                continue
            except ConnectionError:
                continue

        # Let the remote schedulers make progress, then look for a winner.
        for cluster in st.nominated:
            worker = self.workers[cluster]
            try:
                worker.schedule()
            except ConnectionError:
                continue

        winner = st.winner
        if winner is None:
            for cluster in st.nominated:
                try:
                    remote = self.workers[cluster].workloads.get(wl.key)
                except ConnectionError:
                    continue
                if remote is not None and has_quota_reservation(remote):
                    winner = cluster
                    break
        if winner is None:
            acs.message = (
                f"No worker cluster reserved quota yet "
                f"(nominated: {st.nominated})"
            )
            return

        # First worker with QuotaReserved wins; delete the other copies
        # (reference workload.go:364).
        st.winner = winner
        for cluster in st.nominated:
            if cluster == winner:
                continue
            worker = self.workers[cluster]
            try:
                remote = worker.workloads.get(wl.key)
                if remote is not None:
                    worker.delete_workload(remote)
            except ConnectionError:
                continue  # retried on the next sync
        wl.status.cluster_name = winner
        self._mirror_topology(wl, self.workers[winner].workloads.get(wl.key))
        acs.state = CheckState.READY
        acs.message = f'The workload got reservation on "{winner}"'
        acs.last_transition_time = now
        manager.metrics.inc(
            "multikueue_dispatches_total", {"cluster": winner}
        )

    # ------------------------------------------------------------------

    def sync_remote_status(self, manager: Manager, wl: Workload) -> None:
        """Mirror remote completion/eviction back (reference workload.go
        remote status sync + failurerecovery redispatch)."""
        st = self.state.get(wl.key)
        if st is None or st.winner is None:
            # Controller state is in-memory only; after a checkpoint restore
            # rebuild it from the persisted placement (status.clusterName) so
            # worker-lost redispatch and remote status mirroring keep working
            # for previously dispatched workloads.
            if wl.status.cluster_name:
                st = self.state.setdefault(wl.key, _GroupState())
                st.winner = wl.status.cluster_name
                if st.winner not in st.nominated:
                    st.nominated.append(st.winner)
            else:
                return
        now = manager.clock()
        if st.next_sync_at and now < st.next_sync_at:
            # Backing off after an unreachable mirror attempt: don't
            # hammer a transport whose breaker is open. The worker-lost
            # clock keeps running underneath, so redispatch still fires
            # after workerLostTimeout even while backing off.
            if st.winner_lost_since is not None and (
                now - st.winner_lost_since
                >= self.worker_lost_timeout_seconds
            ):
                self._redispatch(manager, wl)
            return
        worker = self.workers.get(st.winner)
        unreachable = False
        try:
            remote = (
                worker.workloads.get(wl.key) if worker is not None else None
            )
        except ConnectionError:
            # Transport down (incl. fast-failed WorkerUnreachable from an
            # open breaker): requeue the mirror with exponential backoff
            # and keep the workerLostTimeout clock running.
            remote = None
            unreachable = True
        if unreachable:
            manager.metrics.inc(
                "multikueue_remote_sync_retries_total",
                {"cluster": st.winner},
            )
            st.sync_backoff_s = min(
                max(self.remote_sync_backoff_seconds,
                    st.sync_backoff_s * 2),
                self.remote_sync_backoff_max_seconds,
            )
            st.next_sync_at = now + st.sync_backoff_s
        if worker is None or remote is None:
            # Worker unreachable/lost the workload: wait out the grace
            # period before redispatching (workerLostTimeout).
            if st.winner_lost_since is None:
                st.winner_lost_since = now
                return
            if now - st.winner_lost_since >= self.worker_lost_timeout_seconds:
                self._redispatch(manager, wl)
            return
        st.winner_lost_since = None
        st.sync_backoff_s = 0.0
        st.next_sync_at = 0.0
        self._mirror_topology(wl, remote)
        if is_finished(remote):
            manager.finish_workload(wl)
        elif is_evicted(remote) and not has_quota_reservation(remote):
            self._redispatch(manager, wl)

    @staticmethod
    def _mirror_topology(wl: Workload, remote: Optional[Workload]) -> None:
        """Copy the worker's topology assignments back onto the manager's
        delayed pod-set assignments (resolves the reference's
        DelayedTopologyRequest Pending -> Ready transition so the manager
        workload can become Admitted)."""
        if (
            remote is None
            or remote.status.admission is None
            or wl.status.admission is None
        ):
            return
        remote_by_name = {
            psa.name: psa
            for psa in remote.status.admission.pod_set_assignments
        }
        for psa in wl.status.admission.pod_set_assignments:
            if not psa.delayed_topology_request \
                    or psa.topology_assignment is not None:
                continue
            rpsa = remote_by_name.get(psa.name)
            if rpsa is not None and rpsa.topology_assignment is not None:
                psa.topology_assignment = rpsa.topology_assignment

    def _redispatch(self, manager: Manager, wl: Workload) -> None:
        """Worker lost the workload (eviction / cluster gone): reset the
        check and dispatch again (reference failurerecovery/)."""
        st = self.state.setdefault(wl.key, _GroupState())
        st.winner = None
        st.nominated = []
        st.winner_lost_since = None
        st.sync_backoff_s = 0.0
        st.next_sync_at = 0.0
        wl.status.cluster_name = None
        for acs in wl.status.admission_checks:
            ac = manager.cache.admission_checks.get(acs.name)
            if ac is not None and ac.controller_name == self.controller_name:
                acs.state = CheckState.PENDING
                acs.message = "Redispatching after worker loss"
