"""TAS node-failure detection and recovery.

Behavioral surface: reference pkg/controller/tas/node_controller.go
(unhealthy-node detection -> Workload.Status.UnhealthyNodes) +
tas_flavor_snapshot.go:743 findReplacementAssignment (replace only the
failed node's share of the gang, keeping the rest in place) +
scheduler.go:417 fail-fast eviction when no replacement exists
(TASFailedNodeReplacement / TASFailedNodeReplacementFailFast gates).

For TPU fleets this is the host-failure path: a dead host inside an ICI
domain gets its pods re-placed onto a healthy host — same rack first —
without restarting the rest of the gang when possible.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from kueue_tpu.api.constants import EVICTED_BY_NODE_FAILURE
from kueue_tpu.api.types import TopologyAssignment, Workload
from kueue_tpu.core.workload_info import is_admitted
from kueue_tpu.tas.snapshot import PlacementRequest
from kueue_tpu.utils import features


class TASNodeFailureController:
    """Drives detection + recovery; the manager calls ``node_unhealthy`` on
    node events and ``reconcile`` from tick()."""

    def __init__(self, manager) -> None:
        self.manager = manager

    # -- detection ----------------------------------------------------------

    def node_unhealthy(self, node_name: str) -> List[str]:
        """Mark the node unhealthy and flag every admitted workload whose
        topology assignment uses it. Returns affected workload keys."""
        node = self.manager.cache.nodes.get(node_name)
        if node is not None:
            node.ready = False
            self.manager.cache.generation += 1
        affected = []
        for key, wl in self.manager.workloads.items():
            if not is_admitted(wl) or wl.status.admission is None:
                continue
            for psa in wl.status.admission.pod_set_assignments:
                ta = psa.topology_assignment
                if ta is None:
                    continue
                if any(values[-1] == node_name for values, _ in ta.domains):
                    if node_name not in wl.status.unhealthy_nodes:
                        wl.status.unhealthy_nodes.append(node_name)
                    affected.append(key)
                    break
        return affected

    def node_recovered(self, node_name: str) -> None:
        node = self.manager.cache.nodes.get(node_name)
        if node is not None:
            node.ready = True
            self.manager.cache.generation += 1

    # -- recovery -----------------------------------------------------------

    def reconcile(self) -> None:
        if not features.enabled("TASFailedNodeReplacement"):
            return
        for wl in list(self.manager.workloads.values()):
            if wl.status.unhealthy_nodes and is_admitted(wl):
                self._recover(wl)

    def _recover(self, wl: Workload) -> None:
        """Find replacement nodes for the failed share of each affected
        podset; evict fail-fast when impossible."""
        mgr = self.manager
        snapshot = mgr.cache.snapshot()  # unhealthy nodes already excluded
        failed = set(wl.status.unhealthy_nodes)
        ok = True
        info = mgr.cache.workloads.get(wl.key)
        for i, psa in enumerate(wl.status.admission.pod_set_assignments):
            ta = psa.topology_assignment
            if ta is None or i >= len(wl.pod_sets):
                continue
            lost = [(v, c) for v, c in ta.domains if v[-1] in failed]
            if not lost:
                continue
            keep = [(v, c) for v, c in ta.domains if v[-1] not in failed]
            lost_count = sum(c for _, c in lost)
            ps = wl.pod_sets[i]
            flavor = next(iter(psa.flavors.values()), None)
            tas = snapshot.tas_flavors.get(flavor)
            if tas is None:
                ok = False
                break
            tr = ps.topology_request
            req = PlacementRequest(
                count=lost_count,
                single_pod_requests=dict(ps.requests),
                # The replacement must stay within the original constraint
                # level; reference keeps the existing domain when possible.
                required_level=tr.required_level if tr else None,
                preferred_level=tr.preferred_level if tr else None,
                unconstrained=tr.unconstrained if tr else True,
                node_selector=dict(ps.node_selector),
                tolerations=list(ps.tolerations),
            )
            # The workload's own surviving usage stays; its lost usage was
            # on the dead node whose capacity is excluded, so plain
            # placement against current usage is correct.
            replacement, _, reason = tas.find_topology_assignment(req)
            if reason:
                ok = False
                break
            merged: Dict[Tuple[str, ...], int] = {}
            for v, c in keep + list(replacement.domains):
                merged[v] = merged.get(v, 0) + c
            psa.topology_assignment = TopologyAssignment(
                levels=replacement.levels or ta.levels,
                domains=sorted(merged.items()),
            )
        if ok:
            wl.status.unhealthy_nodes = []
            if info is not None:
                info.sync_assignment_from_admission()
                mgr.cache.add_or_update_workload(info)
            mgr.metrics.inc("tas_node_replacements_total")
        elif features.enabled("TASFailedNodeReplacementFailFast"):
            mgr.workload_controller.evict(
                wl, EVICTED_BY_NODE_FAILURE,
                "No replacement for unhealthy node(s): "
                + ",".join(sorted(failed)),
                mgr.clock(),
            )
            wl.status.unhealthy_nodes = []
            mgr.metrics.inc("tas_node_replacement_failures_total")
