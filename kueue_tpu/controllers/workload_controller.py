"""Workload lifecycle controller.

Behavioral surface: reference pkg/controller/core/workload_controller.go —
eviction on deactivation / maximumExecutionTime / PodsReady timeout with
requeue backoff, admission-check retry/rejection handling, Admitted-state
sync, finished-workload retention GC, and requeue into the queue manager.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, List, Optional

from kueue_tpu.api.constants import (
    COND_ADMITTED,
    COND_EVICTED,
    COND_PODS_READY,
    COND_QUOTA_RESERVED,
    COND_REQUEUED,
    EVICTED_BY_ADMISSION_CHECK,
    EVICTED_BY_DEACTIVATION,
    EVICTED_BY_PODS_READY_TIMEOUT,
    CheckState,
    RequeueReason,
)
from kueue_tpu.api.types import RequeueState, Workload
from kueue_tpu.utils import features
from kueue_tpu.core.workload_info import (
    WorkloadInfo,
    all_checks_ready,
    get_condition,
    has_quota_reservation,
    has_topology_assignments_pending,
    is_admitted,
    is_evicted,
    is_finished,
    set_condition,
)


@dataclass
class WaitForPodsReadyConfig:
    """reference config v1beta2 configuration_types.go:304."""

    enable: bool = False
    timeout_seconds: float = 300.0
    block_admission: bool = False
    requeuing_backoff_base_seconds: float = 60.0
    requeuing_backoff_limit_count: Optional[int] = None
    requeuing_backoff_max_seconds: float = 3600.0


@dataclass
class RetentionConfig:
    """reference objectRetentionPolicies (configuration_types.go:774)."""

    retain_finished_seconds: Optional[float] = None  # None = keep forever
    retain_deactivated_seconds: Optional[float] = None


class WorkloadController:
    """One reconcile pass = reconcile(workload). The manager calls it on
    events and periodically (clock-driven timeouts)."""

    def __init__(
        self,
        manager,
        pods_ready: Optional[WaitForPodsReadyConfig] = None,
        retention: Optional[RetentionConfig] = None,
    ) -> None:
        self.manager = manager
        self.pods_ready = pods_ready or WaitForPodsReadyConfig()
        self.retention = retention or RetentionConfig()
        # workload key -> admission time (for PodsReady/maxExecutionTime).
        self.admitted_at: Dict[str, float] = {}

    # ------------------------------------------------------------------

    def reconcile(self, wl: Workload) -> None:
        now = self.manager.clock()
        key = wl.key

        if is_finished(wl):
            self._maybe_gc(wl, now)
            return

        if not wl.active:
            keep = self.retention.retain_deactivated_seconds
            if keep is not None:
                cond = get_condition(wl, COND_EVICTED)
                if cond is not None and cond.status and \
                        now - cond.last_transition_time > keep:
                    self.manager.delete_workload(wl)
                    return

        # Deactivation (spec.active=False) evicts and deactivates
        # (reference workload_controller.go DeactivationTarget path).
        if not wl.active and (is_admitted(wl) or has_quota_reservation(wl)):
            self.evict(wl, EVICTED_BY_DEACTIVATION,
                       "The workload is deactivated", now)
            return

        # Admission-check state machine (reference :322 area):
        if has_quota_reservation(wl) and wl.status.admission_checks:
            states = {acs.state for acs in wl.status.admission_checks}
            if CheckState.REJECTED in states:
                wl.active = False
                self.evict(
                    wl, EVICTED_BY_ADMISSION_CHECK,
                    "At least one admission check was rejected", now,
                )
                return
            if CheckState.RETRY in states:
                self.evict(
                    wl, EVICTED_BY_ADMISSION_CHECK,
                    "At least one admission check requests retry", now,
                )
                # Reset check states for the next attempt.
                for acs in wl.status.admission_checks:
                    acs.state = CheckState.PENDING
                return
            if all_checks_ready(wl) and not is_admitted(wl) \
                    and not has_topology_assignments_pending(wl):
                # reference admissionchecks.go:39 SyncAdmittedCondition:
                # Admitted requires all delayed topology requests resolved
                # (the scheduler's second pass assigns them).
                set_condition(wl, COND_ADMITTED, True, "Admitted",
                              "The workload is admitted", now)

        if is_admitted(wl):
            if key not in self.admitted_at:
                # First Admitted observation: admission lifecycle series
                # (reference metrics.go admitted_workloads_total,
                # admission_wait_time_seconds,
                # admission_checks_wait_time_seconds).
                m = self.manager.metrics
                cq = self.manager.queues.cluster_queue_for(wl) or ""
                wl_extra = self.manager._custom_metric_labels(
                    "Workload", wl
                )
                m.inc("admitted_workloads_total",
                      {"cluster_queue": cq, **wl_extra})
                # Per-subtree admission counters (reference metrics.go
                # cohort_subtree_admitted_workloads_total): every ancestor
                # cohort of the admitting CQ counts the admission.
                co_name = None
                cq_spec = self.manager.cache.cluster_queues.get(cq)
                if cq_spec is not None:
                    co_name = cq_spec.cohort
                seen_cohorts = set()
                while co_name and co_name not in seen_cohorts:
                    seen_cohorts.add(co_name)
                    co_obj = self.manager.cache.cohorts.get(co_name)
                    m.inc(
                        "cohort_subtree_admitted_workloads_total",
                        {"cohort": co_name,
                         "priority_class": wl.priority_class or "",
                         **(self.manager._custom_metric_labels(
                             "Cohort", co_obj)
                            if co_obj is not None else {})},
                    )
                    co_name = (
                        co_obj.parent if co_obj is not None else None
                    )
                m.observe("admission_wait_time_seconds",
                          max(0.0, now - wl.creation_time),
                          {"cluster_queue": cq})
                qr = get_condition(wl, COND_QUOTA_RESERVED)
                if qr is not None and qr.status:
                    m.observe(
                        "admission_checks_wait_time_seconds",
                        max(0.0, now - qr.last_transition_time),
                        {"cluster_queue": cq},
                    )
            self.admitted_at.setdefault(key, now)
            # maximumExecutionTime (reference evictions by
            # MaximumExecutionTimeExceeded).
            met = wl.maximum_execution_time_seconds
            if met is not None and now - self.admitted_at[key] > met:
                wl.active = False
                self.evict(wl, EVICTED_BY_DEACTIVATION,
                           "Exceeded the maximum execution time", now)
                return
            # WaitForPodsReady timeout (DisableWaitForPodsReady gate turns
            # the whole mechanism off regardless of configuration).
            if self.pods_ready.enable and not features.enabled(
                "DisableWaitForPodsReady"
            ):
                job = self.manager.job_reconciler.job_of_workload.get(key)
                ready = job.pods_ready() if job is not None else True
                if ready:
                    set_condition(wl, COND_PODS_READY, True, "PodsReady",
                                  "All pods are ready", now)
                elif now - self.admitted_at[key] > self.pods_ready.timeout_seconds:
                    self._requeue_with_backoff(wl, now)
                    self.evict(
                        wl, EVICTED_BY_PODS_READY_TIMEOUT,
                        f"Exceeded the PodsReady timeout {key}", now,
                    )
                    return
        else:
            self.admitted_at.pop(key, None)

    # ------------------------------------------------------------------

    def evict(self, wl: Workload, reason: str, message: str, now: float) -> None:
        """pkg/workload/evict.Evict equivalent: conditions + quota release +
        requeue."""
        set_condition(wl, COND_EVICTED, True, reason, message, now)
        set_condition(wl, COND_QUOTA_RESERVED, False, "Pending", message, now)
        set_condition(wl, COND_ADMITTED, False, "NoReservation", message, now)
        self.manager.metrics.inc(
            "evicted_workloads_total", {"reason": reason}
        )
        # First-ever eviction of this workload (reference
        # evicted_workloads_once_total) + time from PodsReady to eviction.
        if not getattr(wl, "_evicted_once", False):
            wl._evicted_once = True
            self.manager.metrics.inc(
                "evicted_workloads_once_total", {"reason": reason}
            )
        pr = get_condition(wl, COND_PODS_READY)
        if pr is not None and pr.status:
            self.manager.metrics.observe(
                "pods_ready_to_evicted_time_seconds",
                max(0.0, now - pr.last_transition_time),
                {"reason": reason},
            )
        wl.status.admission = None
        wl.status.admission_checks = []
        self.manager.cache.delete_workload(wl.key)
        self.admitted_at.pop(wl.key, None)
        if wl.active:
            info = WorkloadInfo(wl, self.manager.queues.cluster_queue_for(wl))
            rs = wl.status.requeue_state
            if rs is None or rs.requeue_at is None or rs.requeue_at <= now:
                set_condition(wl, COND_REQUEUED, True, reason, message, now)
                self.manager.queues.requeue_workload(
                    info, RequeueReason.GENERIC
                )
        self.manager.queues.queue_inadmissible_workloads()
        # The job must stop (suspend) — handled by job reconciliation.
        job = self.manager.job_reconciler.job_of_workload.get(wl.key)
        if job is not None:
            self.manager.job_reconciler.reconcile(job)

    def _requeue_with_backoff(self, wl: Workload, now: float) -> None:
        """reference workload_controller.go requeuing backoff: exponential
        per eviction count, capped; deactivate past the limit."""
        rs = wl.status.requeue_state or RequeueState()
        rs.count += 1
        limit = self.pods_ready.requeuing_backoff_limit_count
        if limit is not None and rs.count > limit:
            wl.active = False
            rs.requeue_at = None
        else:
            delay = min(
                self.pods_ready.requeuing_backoff_base_seconds
                * (2 ** (rs.count - 1)),
                self.pods_ready.requeuing_backoff_max_seconds,
            )
            rs.requeue_at = now + delay
        wl.status.requeue_state = rs

    def requeue_ready_backoffs(self) -> int:
        """Move workloads whose backoff expired back into the queues.
        Returns how many were requeued."""
        now = self.manager.clock()
        n = 0
        for wl in list(self.manager.workloads.values()):
            rs = wl.status.requeue_state
            if (
                rs is not None
                and rs.requeue_at is not None
                and rs.requeue_at <= now
                and wl.active
                and not is_finished(wl)
                and not has_quota_reservation(wl)
            ):
                rs.requeue_at = None
                set_condition(wl, COND_REQUEUED, True, "BackoffFinished",
                              "Requeued after backoff", now)
                # Straight into the active heap — the backoff already served
                # as the penalty (reference workload_controller.go requeues
                # via an immediate queue add once RequeueAt passes).
                if self.manager.queues.add_or_update_workload(wl):
                    n += 1
        return n

    def _maybe_gc(self, wl: Workload, now: float) -> None:
        keep = self.retention.retain_finished_seconds
        if keep is None:
            return
        cond = get_condition(wl, "Finished")
        if cond is not None and now - cond.last_transition_time > keep:
            self.manager.delete_workload(wl)
