"""Concurrent admission: per-flavor workload variants racing for admission.

Behavioral surface: reference pkg/controller/concurrentadmission — for a
ClusterQueue with ConcurrentAdmission enabled, a workload is expanded into
one variant per candidate flavor; each variant may only use its own flavor
(reference flavorassigner.go:981 IsFlavorAllowedForVariant). The first
variant admitted wins; less-preferred admitted variants are migrated to a
more-preferred flavor when it becomes available (controller.go:307); the
losing variants are deactivated once the winner runs.

For TPU fleets: the same training job races for "reserved v5e" and "spot
v5e" capacity simultaneously, and migrates back to reserved when it frees.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from kueue_tpu.api.types import Workload
from kueue_tpu.core.workload_info import is_admitted, is_evicted
from kueue_tpu.scheduler.flavorassigner import FlavorAssigner, Mode

VARIANT_OF_LABEL = "kueue.x-k8s.io/variant-of"
ALLOWED_FLAVOR_LABEL = "kueue.x-k8s.io/allowed-resource-flavor"


def is_variant(wl: Workload) -> bool:
    return VARIANT_OF_LABEL in wl.labels


def allowed_flavor(wl: Workload) -> Optional[str]:
    return wl.labels.get(ALLOWED_FLAVOR_LABEL)


class ConcurrentAdmissionController:
    """reference concurrentadmission/controller.go:70."""

    def __init__(self, manager) -> None:
        self.manager = manager
        # group key (original wl key) -> ordered variant keys (flavor pref)
        self.groups: Dict[str, List[str]] = {}

    # ------------------------------------------------------------------

    def ensure_variants(self, wl: Workload) -> List[Workload]:
        """Expand a workload into per-flavor variants (controller.go:188).
        Returns the variants (creating them on first call). The original
        workload is withdrawn from the queues and acts as the group
        anchor."""
        mgr = self.manager
        cq_name = mgr.queues.cluster_queue_for(wl)
        cq = mgr.cache.cluster_queues.get(cq_name) if cq_name else None
        if cq is None or cq.concurrent_admission_policy != "Enabled":
            return []
        if wl.key in self.groups:
            return [
                mgr.workloads[k] for k in self.groups[wl.key]
                if k in mgr.workloads
            ]
        flavors: List[str] = []
        for rg in cq.resource_groups:
            for fq in rg.flavors:
                if fq.name not in flavors:
                    flavors.append(fq.name)
        if len(flavors) < 2:
            return []
        mgr.queues.delete_workload(wl)  # anchor no longer queued itself
        variants = []
        for flavor in flavors:
            v = wl.clone()
            v.name = f"{wl.name}-fl-{flavor}"
            v.labels = dict(wl.labels)
            v.labels[VARIANT_OF_LABEL] = wl.key
            v.labels[ALLOWED_FLAVOR_LABEL] = flavor
            v.status = type(v.status)()
            mgr.create_workload(v)
            variants.append(v)
        self.groups[wl.key] = [v.key for v in variants]
        return variants

    # ------------------------------------------------------------------

    def reconcile(self) -> None:
        """Winner selection + loser deactivation + migration
        (controller.go:70,307)."""
        mgr = self.manager
        for anchor_key, variant_keys in list(self.groups.items()):
            variants = [
                mgr.workloads[k] for k in variant_keys if k in mgr.workloads
            ]
            admitted = [v for v in variants if is_admitted(v)]
            if not admitted:
                continue
            anchor = mgr.workloads.get(anchor_key)
            # Preference order = flavor order; keep the most preferred
            # admitted variant, deactivate the rest.
            order = {k: i for i, k in enumerate(variant_keys)}
            admitted.sort(key=lambda v: order[v.key])
            winner = admitted[0]
            for v in variants:
                if v is winner:
                    continue
                if is_admitted(v):
                    # Less favorable sibling admitted: migration — evict it
                    # in favor of the winner (scheduler issueMigration).
                    mgr.workload_controller.evict(
                        v, "FlavorMigration",
                        f"Migrated to more favorable variant {winner.name}",
                        mgr.clock(),
                    )
                v.active = False
                mgr.queues.delete_workload(v)
            # Mirror the winning admission onto the anchor for observers.
            if anchor is not None:
                anchor.status = winner.status

    def try_migration(self) -> None:
        """Periodic: if a more-preferred variant would now Fit, evict the
        currently admitted less-preferred one and re-race
        (controller.go:307 migration-to-preferred-flavor)."""
        mgr = self.manager
        snapshot = mgr.cache.snapshot()
        for anchor_key, variant_keys in list(self.groups.items()):
            admitted = [
                mgr.workloads[k] for k in variant_keys
                if k in mgr.workloads and is_admitted(mgr.workloads[k])
            ]
            if not admitted:
                continue
            order = {k: i for i, k in enumerate(variant_keys)}
            current = min(admitted, key=lambda v: order[v.key])
            cur_rank = order[current.key]
            if cur_rank == 0:
                continue
            for k in variant_keys[:cur_rank]:
                preferred = mgr.workloads.get(k)
                if preferred is None:
                    continue
                from kueue_tpu.core.workload_info import WorkloadInfo

                cq_name = current.status.admission.cluster_queue
                cqs = snapshot.cluster_queues.get(cq_name)
                if cqs is None:
                    continue
                info = WorkloadInfo(preferred, cq_name)
                assigner = FlavorAssigner(
                    info, cqs, snapshot.resource_flavors,
                    tas_flavors=snapshot.tas_flavors,
                )
                assignment = assigner.assign()
                fits_preferred = (
                    assignment.representative_mode() == Mode.FIT
                    and all(
                        next(iter(psa.flavors.values())).name
                        == allowed_flavor(preferred)
                        for psa in assignment.pod_sets if psa.flavors
                    )
                )
                if fits_preferred:
                    preferred.active = True
                    mgr.workload_controller.evict(
                        current, "FlavorMigration",
                        f"Migrating to preferred flavor variant "
                        f"{preferred.name}",
                        mgr.clock(),
                    )
                    mgr.queues.add_or_update_workload(preferred)
                    break
