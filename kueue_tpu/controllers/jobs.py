"""Built-in job integrations.

Behavioral surface: reference pkg/controller/jobs/* — adapters implementing
GenericJob for each workload framework. kueue_tpu ships TPU-native
equivalents of the reference families:

  BatchJob          <- batch/job            (single podset, completions)
  TrainJob          <- kubeflow TFJob/PyTorchJob/JAXJob/TrainJob (role
                       replicas, e.g. one podset per jax process group)
  LeaderWorkerSet   <- leaderworkerset      (leader + workers gang)
  PodGroup          <- pod-group integration (plain pods admitted together)
  ServingGroup      <- Deployment/StatefulSet (long-running replicas)
  MPIJob            <- mpijob               (launcher + workers)
  RayCluster        <- raycluster           (head + worker groups)

Adapters are plain Python state machines — "suspended" means the framework
must not run processes; run_with_podsets_info delivers node selectors and
topology domains (for TPU fleets: which hosts of which ICI domain to use).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from kueue_tpu.api.types import PodSet, Toleration, TopologyRequest
from kueue_tpu.controllers.jobframework import (
    GenericJob,
    PodSetInfo,
    PodSetInfoConflict,
    registry,
)


@dataclass
class PodTemplate:
    """The mutable scheduling fields of one role's pod template — the
    part of a job spec RunWithPodSetsInfo customizes on start and
    RestorePodSetsInfo puts back on stop (reference pkg/podset
    podset.go FromAssignment/Merge + reconciler.go:1326-1424)."""

    count: int
    node_selector: Dict[str, str] = field(default_factory=dict)
    tolerations: List[Toleration] = field(default_factory=list)


class _BaseJob(GenericJob):
    def __init__(
        self,
        name: str,
        queue: str,
        namespace: str = "default",
        priority: int = 0,
        priority_class: Optional[str] = None,
    ) -> None:
        self._name = name
        self._queue = queue
        self._namespace = namespace
        self._priority = priority
        self._priority_class = priority_class
        self._suspended = True
        self._finished = False
        self._success = True
        self._message = ""
        self._pods_ready = False
        self.started_with: List[PodSetInfo] = []
        # Live pod templates by podset name while running; None when the
        # job has never started or was restored (reference: a suspended
        # job's spec carries the original template).
        self.templates: Optional[Dict[str, PodTemplate]] = None
        # Last startJob failure (PodSetInfoConflict message); cleared by
        # the reconciler on a successful start.
        self.start_error: Optional[str] = None

    @property
    def name(self) -> str:
        return self._name

    @property
    def namespace(self) -> str:
        return self._namespace

    @property
    def queue_name(self) -> str:
        return self._queue

    def is_suspended(self) -> bool:
        return self._suspended

    def suspend(self) -> None:
        self._suspended = True
        self._pods_ready = False

    def run_with_podsets_info(self, infos: List[PodSetInfo]) -> None:
        """Start the job with the admission's scheduling attributes
        applied to its pod templates (reference reconciler.go:1326
        startJob -> job.RunWithPodSetsInfo): flavor node labels merge
        into each role's node selector (conflicting keys are an error,
        podset.go Merge), tolerations append, and the admitted count
        (partial admission) replaces the role's count."""
        base = {ps.name: ps for ps in self.pod_sets()}
        templates: Dict[str, PodTemplate] = {}
        for info in infos:
            ps = base.get(info.name)
            own_sel = dict(ps.node_selector or {}) if ps is not None else {}
            for k, v in info.node_selector.items():
                if k in own_sel and own_sel[k] != v:
                    raise PodSetInfoConflict(
                        f"podset {info.name!r}: node selector {k}="
                        f"{own_sel[k]!r} conflicts with admitted {v!r}"
                    )
                own_sel[k] = v
            tols = list(ps.tolerations or []) if ps is not None else []
            seen = set(tols)  # Toleration is a frozen dataclass
            for t in info.tolerations:
                if t not in seen:
                    tols.append(t)
                    seen.add(t)
            templates[info.name] = PodTemplate(
                count=info.count, node_selector=own_sel, tolerations=tols
            )
        self.templates = templates
        self._apply_counts({n: t.count for n, t in templates.items()})
        self._suspended = False
        self.started_with = infos
        self._pods_ready = True

    def restore_podsets_info(self, infos: List[PodSetInfo]) -> None:
        """Undo every start-time customization (reference stopJob ->
        RestorePodSetsInfo): templates revert to the job's own spec."""
        self.templates = None
        self._restore_counts()
        self.started_with = []

    # Frameworks with a live scalar mirroring the template count (the
    # reference mutates the actual spec field, e.g. job.Spec.Parallelism)
    # override these two.
    def _apply_counts(self, counts: Dict[str, int]) -> None:
        pass

    def _restore_counts(self) -> None:
        pass

    def finished(self) -> Tuple[bool, bool, str]:
        return self._finished, self._success, self._message

    def pods_ready(self) -> bool:
        return not self._suspended and self._pods_ready

    def priority(self) -> int:
        return self._priority

    def priority_class(self) -> Optional[str]:
        return self._priority_class

    # test/ops helpers
    def mark_finished(self, success: bool = True, message: str = "") -> None:
        self._finished = True
        self._success = success
        self._message = message

    def set_pods_ready(self, ready: bool) -> None:
        self._pods_ready = ready


class BatchJob(_BaseJob):
    """reference pkg/controller/jobs/job."""

    def __init__(self, name: str, queue: str, parallelism: int = 1,
                 requests: Optional[Dict[str, int]] = None,
                 min_parallelism: Optional[int] = None,
                 topology: Optional[TopologyRequest] = None,
                 **kw) -> None:
        super().__init__(name, queue, **kw)
        self.parallelism = parallelism
        self._spec_parallelism: Optional[int] = None
        self.min_parallelism = min_parallelism
        self.requests = requests or {"cpu": 1000}
        self.topology = topology

    def pod_sets(self) -> List[PodSet]:
        # While running, parallelism mirrors the admitted count; the
        # spec's own value (restored on stop) is the snapshot taken at
        # start. Suspended jobs read the live public field, so callers
        # may mutate it freely before submit.
        count = (
            self._spec_parallelism
            if self._spec_parallelism is not None else self.parallelism
        )
        return [
            PodSet(
                name="main",
                count=count,
                requests=dict(self.requests),
                min_count=self.min_parallelism,
                topology_request=self.topology,
            )
        ]

    def _apply_counts(self, counts: Dict[str, int]) -> None:
        # reference jobs/job RunWithPodSetsInfo: the live spec's
        # parallelism becomes the admitted (possibly reduced) count;
        # the original is snapshotted for RestorePodSetsInfo. An
        # unpaired restart (suspend without restore) must not clobber
        # the snapshot with the already-reduced value.
        if self._spec_parallelism is None:
            self._spec_parallelism = self.parallelism
        self.parallelism = counts.get("main", self.parallelism)

    def _restore_counts(self) -> None:
        if self._spec_parallelism is not None:
            self.parallelism = self._spec_parallelism
            self._spec_parallelism = None


class TrainJob(_BaseJob):
    """Multi-role training job (reference kubeflow jobs / trainjob): each
    role (e.g. "trainer" process group) is one podset. For TPU training a
    role maps onto a set of hosts driving one slice."""

    def __init__(self, name: str, queue: str,
                 roles: Dict[str, Tuple[int, Dict[str, int]]],
                 topology: Optional[TopologyRequest] = None,
                 **kw) -> None:
        super().__init__(name, queue, **kw)
        self.roles = roles
        self.topology = topology

    def pod_sets(self) -> List[PodSet]:
        return [
            PodSet(
                name=role,
                count=count,
                requests=dict(reqs),
                topology_request=self.topology,
            )
            for role, (count, reqs) in self.roles.items()
        ]


class LeaderWorkerSet(_BaseJob):
    """reference pkg/controller/jobs/leaderworkerset: a leader podset and a
    workers podset admitted as one gang."""

    def __init__(self, name: str, queue: str, workers: int,
                 worker_requests: Dict[str, int],
                 leader_requests: Optional[Dict[str, int]] = None,
                 topology: Optional[TopologyRequest] = None, **kw) -> None:
        super().__init__(name, queue, **kw)
        self.workers = workers
        self.worker_requests = worker_requests
        self.leader_requests = leader_requests or {"cpu": 100}
        self.topology = topology

    def pod_sets(self) -> List[PodSet]:
        return [
            PodSet(name="leader", count=1,
                   requests=dict(self.leader_requests)),
            PodSet(name="workers", count=self.workers,
                   requests=dict(self.worker_requests),
                   topology_request=self.topology),
        ]


class MPIJob(_BaseJob):
    """reference pkg/controller/jobs/mpijob: launcher + workers."""

    def __init__(self, name: str, queue: str, workers: int,
                 worker_requests: Dict[str, int],
                 launcher_requests: Optional[Dict[str, int]] = None,
                 **kw) -> None:
        super().__init__(name, queue, **kw)
        self.workers = workers
        self.worker_requests = worker_requests
        self.launcher_requests = launcher_requests or {"cpu": 500}

    def pod_sets(self) -> List[PodSet]:
        return [
            PodSet(name="launcher", count=1,
                   requests=dict(self.launcher_requests)),
            PodSet(name="worker", count=self.workers,
                   requests=dict(self.worker_requests)),
        ]


class RayCluster(_BaseJob):
    """reference pkg/controller/jobs/raycluster: head + worker groups."""

    def __init__(self, name: str, queue: str,
                 head_requests: Dict[str, int],
                 worker_groups: Dict[str, Tuple[int, Dict[str, int]]],
                 **kw) -> None:
        super().__init__(name, queue, **kw)
        self.head_requests = head_requests
        self.worker_groups = worker_groups

    def pod_sets(self) -> List[PodSet]:
        out = [PodSet(name="head", count=1, requests=dict(self.head_requests))]
        for g, (count, reqs) in self.worker_groups.items():
            out.append(PodSet(name=g, count=count, requests=dict(reqs)))
        return out


class PodGroup(_BaseJob):
    """reference pkg/controller/jobs/pod (pod groups): N identical pods
    admitted all-or-nothing via scheduling gates."""

    def __init__(self, name: str, queue: str, count: int,
                 requests: Dict[str, int], **kw) -> None:
        super().__init__(name, queue, **kw)
        self.count = count
        self.requests = requests

    def pod_sets(self) -> List[PodSet]:
        return [PodSet(name="pods", count=self.count,
                       requests=dict(self.requests))]


class ServingGroup(_BaseJob):
    """reference pkg/controller/jobs/{deployment,statefulset}: long-running
    replicas; scale via replace-and-resubmit (elastic slices in a later
    phase)."""

    def __init__(self, name: str, queue: str, replicas: int,
                 requests: Dict[str, int], **kw) -> None:
        super().__init__(name, queue, **kw)
        self.replicas = replicas
        self.requests = requests

    def pod_sets(self) -> List[PodSet]:
        return [PodSet(name="replicas", count=self.replicas,
                       requests=dict(self.requests))]


for _name, _cls in [
    ("batch/job", BatchJob),
    ("trainjob", TrainJob),
    ("leaderworkerset", LeaderWorkerSet),
    ("mpijob", MPIJob),
    ("raycluster", RayCluster),
    ("pod", PodGroup),
    ("serving", ServingGroup),
]:
    registry.register(_name, _cls)


class JobSet(_BaseJob):
    """reference pkg/controller/jobs/jobset: a set of replicated jobs, each
    replicated job -> one podset (count = replicas x parallelism)."""

    def __init__(self, name: str, queue: str,
                 replicated_jobs: Dict[str, Tuple[int, int, Dict[str, int]]],
                 topology: Optional[TopologyRequest] = None, **kw) -> None:
        """replicated_jobs: name -> (replicas, parallelism, per-pod requests)."""
        super().__init__(name, queue, **kw)
        self.replicated_jobs = replicated_jobs
        self.topology = topology

    def pod_sets(self) -> List[PodSet]:
        return [
            PodSet(
                name=rj_name,
                count=replicas * parallelism,
                requests=dict(reqs),
                topology_request=self.topology,
            )
            for rj_name, (replicas, parallelism, reqs)
            in self.replicated_jobs.items()
        ]


class AppWrapper(_BaseJob):
    """reference pkg/controller/jobs/appwrapper: an arbitrary bundle of
    components, each contributing podsets."""

    def __init__(self, name: str, queue: str,
                 components: List[Tuple[str, int, Dict[str, int]]],
                 **kw) -> None:
        super().__init__(name, queue, **kw)
        self.components = components

    def pod_sets(self) -> List[PodSet]:
        return [
            PodSet(name=cname, count=count, requests=dict(reqs))
            for cname, count, reqs in self.components
        ]


class SparkApplication(_BaseJob):
    """reference pkg/controller/jobs/sparkapplication: driver + executors."""

    def __init__(self, name: str, queue: str, executors: int,
                 executor_requests: Dict[str, int],
                 driver_requests: Optional[Dict[str, int]] = None,
                 **kw) -> None:
        super().__init__(name, queue, **kw)
        self.executors = executors
        self.executor_requests = executor_requests
        self.driver_requests = driver_requests or {"cpu": 1000}

    def pod_sets(self) -> List[PodSet]:
        return [
            PodSet(name="driver", count=1, requests=dict(self.driver_requests)),
            PodSet(name="executor", count=self.executors,
                   requests=dict(self.executor_requests)),
        ]


# ---------------------------------------------------------------------------
# Kubeflow training job family — distinct adapters with each framework's
# canonical replica roles, ordering and structural validation (reference
# pkg/controller/jobs/kubeflow/jobs/{tfjob,pytorchjob,xgboostjob,paddlejob,
# jaxjob}: podsets are emitted in the framework's replica-type order and
# the per-framework invariants are enforced at construction).
# ---------------------------------------------------------------------------


class _KubeflowJob(_BaseJob):
    """Common kubeflow ReplicaSpec handling: ordered roles, single-master
    invariants, per-role podsets (reference kubeflowjob.go)."""

    ROLE_ORDER: Tuple[str, ...] = ()
    SINGLETON_ROLES: Tuple[str, ...] = ()

    def __init__(self, name: str, queue: str,
                 replicas: Dict[str, Tuple[int, Dict[str, int]]],
                 topology: Optional[TopologyRequest] = None, **kw) -> None:
        super().__init__(name, queue, **kw)
        unknown = set(replicas) - set(self.ROLE_ORDER)
        if unknown:
            raise ValueError(
                f"{type(self).__name__} does not support replica types"
                f" {sorted(unknown)}; valid: {list(self.ROLE_ORDER)}"
            )
        for role in self.SINGLETON_ROLES:
            if role in replicas and replicas[role][0] > 1:
                raise ValueError(
                    f"{type(self).__name__} allows at most one {role}"
                )
        self.replicas = replicas
        self.topology = topology

    def pod_sets(self) -> List[PodSet]:
        out = []
        for role in self.ROLE_ORDER:
            if role not in self.replicas:
                continue
            count, reqs = self.replicas[role]
            out.append(PodSet(
                name=role.lower(), count=count, requests=dict(reqs),
                topology_request=self.topology,
            ))
        return out


class TFJob(_KubeflowJob):
    """reference kubeflow/jobs/tfjob: Chief/Master, PS, Worker, Evaluator
    replica order (tfjob_multikueue_adapter order)."""

    ROLE_ORDER = ("Chief", "Master", "PS", "Worker", "Evaluator")
    SINGLETON_ROLES = ("Chief", "Master")


class PyTorchJob(_KubeflowJob):
    """reference kubeflow/jobs/pytorchjob: one Master + Workers."""

    ROLE_ORDER = ("Master", "Worker")
    SINGLETON_ROLES = ("Master",)


class XGBoostJob(_KubeflowJob):
    """reference kubeflow/jobs/xgboostjob: one Master + Workers."""

    ROLE_ORDER = ("Master", "Worker")
    SINGLETON_ROLES = ("Master",)


class PaddleJob(_KubeflowJob):
    """reference kubeflow/jobs/paddlejob: Master + Workers."""

    ROLE_ORDER = ("Master", "Worker")
    SINGLETON_ROLES = ("Master",)


class JAXJob(_KubeflowJob):
    """reference kubeflow/jobs/jaxjob: a single Worker replica set — one
    process per host of a TPU slice."""

    ROLE_ORDER = ("Worker",)


class RayJob(_BaseJob):
    """reference pkg/controller/jobs/rayjob: head + worker groups, plus the
    submitter pod when the job is submitted via a Kubernetes Job
    (rayjob spec.submissionMode == K8sJobMode)."""

    def __init__(self, name: str, queue: str,
                 head_requests: Dict[str, int],
                 worker_groups: Dict[str, Tuple[int, Dict[str, int]]],
                 submission_mode: str = "K8sJobMode",
                 submitter_requests: Optional[Dict[str, int]] = None,
                 **kw) -> None:
        super().__init__(name, queue, **kw)
        self.head_requests = head_requests
        self.worker_groups = worker_groups
        self.submission_mode = submission_mode
        self.submitter_requests = submitter_requests or {"cpu": 500}

    def pod_sets(self) -> List[PodSet]:
        out = [PodSet(name="head", count=1,
                      requests=dict(self.head_requests))]
        for g, (count, reqs) in self.worker_groups.items():
            out.append(PodSet(name=g, count=count, requests=dict(reqs)))
        if self.submission_mode == "K8sJobMode":
            out.append(PodSet(name="submitter", count=1,
                              requests=dict(self.submitter_requests)))
        return out


class RayService(_BaseJob):
    """reference pkg/controller/jobs/rayservice: a long-running serve
    deployment on a Ray cluster — head + worker groups, never 'finished'
    on its own (torn down by deletion, like serving workloads)."""

    def __init__(self, name: str, queue: str,
                 head_requests: Dict[str, int],
                 worker_groups: Dict[str, Tuple[int, Dict[str, int]]],
                 **kw) -> None:
        super().__init__(name, queue, **kw)
        self.head_requests = head_requests
        self.worker_groups = worker_groups

    def pod_sets(self) -> List[PodSet]:
        out = [PodSet(name="head", count=1,
                      requests=dict(self.head_requests))]
        for g, (count, reqs) in self.worker_groups.items():
            out.append(PodSet(name=g, count=count, requests=dict(reqs)))
        return out

    def finished(self) -> Tuple[bool, bool, str]:
        # A serve deployment never self-terminates.
        return self._finished, self._success, self._message


class Deployment(ServingGroup):
    """reference pkg/controller/jobs/deployment: stateless replicas; the
    replica count may change at runtime — scale-down is always safe,
    scale-up re-enters admission via elastic workload slices."""

    def scale(self, replicas: int) -> None:
        self.replicas = replicas


class StatefulSet(ServingGroup):
    """reference pkg/controller/jobs/statefulset: ordered, identity-bearing
    replicas admitted as one group."""


for _name, _cls in [
    ("jobset", JobSet),
    ("appwrapper", AppWrapper),
    ("sparkapplication", SparkApplication),
    ("kubeflow/tfjob", TFJob),
    ("kubeflow/pytorchjob", PyTorchJob),
    ("kubeflow/xgboostjob", XGBoostJob),
    ("kubeflow/paddlejob", PaddleJob),
    ("kubeflow/jaxjob", JAXJob),
    ("rayjob", RayJob),
    ("rayservice", RayService),
    ("deployment", Deployment),
    ("statefulset", StatefulSet),
]:
    registry.register(_name, _cls)
