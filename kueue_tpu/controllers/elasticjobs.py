"""Elastic jobs: scale running workloads via workload slices.

Behavioral surface: reference pkg/workloadslicing + pkg/controller/
elasticjobs — a scale-up admits a *replacement slice*: a new workload
carrying the new counts that treats the old slice as a preemptible
replacement target, so the job keeps its current allocation until the
larger one is granted atomically. Scale-down releases the delta
immediately.

The admission transaction here: simulate removal of the old slice, run the
scheduler's assignment for the new slice, and only commit the swap when the
new slice fits (otherwise the old allocation is untouched and the request
stays pending for retry).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from kueue_tpu.api.constants import COND_ADMITTED, COND_QUOTA_RESERVED
from kueue_tpu.api.types import Admission, PodSetAssignment, Workload
from kueue_tpu.core.workload_info import (
    WorkloadInfo,
    is_admitted,
    set_condition,
)
from kueue_tpu.scheduler.flavorassigner import FlavorAssigner, Mode

REPLACED_SLICE_LABEL = "kueue.x-k8s.io/replaced-workload-slice"


def scale(manager, wl: Workload, new_counts: Dict[str, int]) -> Tuple[bool, str]:
    """Scale an admitted workload's podsets to ``new_counts`` (podset name
    -> count). Returns (applied, message).

    Scale-down applies immediately (usage delta released). Scale-up runs
    the replacement-slice admission: old usage is treated as reclaimable
    during the fit check (reference workloadslicing.go:165
    EnsureWorkloadSlices / :344 ReplacedWorkloadSlice)."""
    if not is_admitted(wl):
        return False, "workload is not admitted; edit the spec and resubmit"
    info = manager.cache.workloads.get(wl.key)
    if info is None:
        return False, "workload not found in cache"

    old_counts = {ps.name: ps.count for ps in wl.pod_sets}
    if new_counts == old_counts:
        return True, "no change"
    scale_up = any(
        new_counts.get(name, c) > c for name, c in old_counts.items()
    )

    # Build the new slice (same spec, new counts).
    new_pod_sets = []
    for ps in wl.pod_sets:
        count = new_counts.get(ps.name, ps.count)
        if count < 0:
            return False, f"invalid count {count} for podset {ps.name}"
        new_pod_sets.append(dataclasses.replace(ps, count=count))

    if not scale_up:
        _apply_counts(manager, wl, info, new_pod_sets)
        return True, "scaled down"

    # Scale-up: fit the new slice with the old slice's usage removed
    # (the old slice is the replacement target).
    snapshot = manager.cache.snapshot()
    old_info = snapshot.cluster_queues[info.cluster_queue].workloads.get(
        wl.key
    )
    if old_info is not None:
        revert = snapshot.simulate_workload_removal([old_info])
    else:
        revert = lambda: None
    try:
        candidate = wl.clone()
        candidate.pod_sets = new_pod_sets
        cand_info = WorkloadInfo(candidate, info.cluster_queue)
        assigner = FlavorAssigner(
            cand_info,
            snapshot.cluster_queues[info.cluster_queue],
            snapshot.resource_flavors,
            tas_flavors=snapshot.tas_flavors,
        )
        assignment = assigner.assign()
        if assignment.representative_mode() != Mode.FIT:
            return False, (
                "insufficient quota for the scaled slice; keeping current "
                "allocation"
            )
    finally:
        revert()

    # Commit the swap atomically: new admission replaces the old slice.
    now = manager.clock()
    wl.pod_sets = new_pod_sets
    wl.status.admission = Admission(
        cluster_queue=info.cluster_queue,
        pod_set_assignments=[
            PodSetAssignment(
                name=psa.name,
                flavors={r: fa.name for r, fa in psa.flavors.items()},
                resource_usage=dict(psa.requests),
                count=psa.count,
                topology_assignment=psa.topology_assignment,
            )
            for psa in assignment.pod_sets
        ],
    )
    set_condition(wl, COND_QUOTA_RESERVED, True, "SliceReplaced",
                  "Quota reserved for the scaled slice", now)
    set_condition(wl, COND_ADMITTED, True, "SliceReplaced",
                  "Scaled slice admitted", now)
    fresh = WorkloadInfo(wl, info.cluster_queue)
    fresh.sync_assignment_from_admission()
    manager.cache.add_or_update_workload(fresh)
    manager.queues.queue_inadmissible_workloads()
    return True, "scaled up via replacement slice"


def _apply_counts(manager, wl: Workload, info: WorkloadInfo, new_pod_sets) -> None:
    now = manager.clock()
    wl.pod_sets = new_pod_sets
    adm = wl.status.admission
    per_pod = {ps.name: ps.requests for ps in new_pod_sets}
    for psa in adm.pod_set_assignments:
        count = next(
            (ps.count for ps in new_pod_sets if ps.name == psa.name),
            psa.count,
        )
        psa.count = count
        psa.resource_usage = {
            r: v * count for r, v in per_pod.get(psa.name, {}).items()
        }
    fresh = WorkloadInfo(wl, info.cluster_queue)
    fresh.sync_assignment_from_admission()
    manager.cache.add_or_update_workload(fresh)
    manager.queues.queue_inadmissible_workloads()
    set_condition(wl, COND_ADMITTED, True, "SliceScaledDown",
                  "Scaled down in place", now)
