"""Job integration SDK.

Behavioral surface: reference pkg/controller/jobframework — the GenericJob
interface (interface.go:37-71), the JobReconciler lifecycle
(reconciler.go:296: ensure-one-workload, construct workload from podsets,
start/stop with podset info injection) and the integration registry
(integrationmanager.go).

kueue_tpu is standalone (no kube-apiserver), so "reconcile" is call-driven:
the manager invokes reconcile_job on job events (submit, finish, suspend)
and on workload events (admitted, evicted). Job adapters translate between
a framework's job object and the Workload admission currency.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Protocol, Tuple

from kueue_tpu.api.constants import (
    COND_ADMITTED,
    COND_FINISHED,
)
from kueue_tpu.api.types import (
    Admission,
    PodSet,
    PodSetAssignment,
    Workload,
)
from kueue_tpu.core.workload_info import (
    get_condition,
    is_admitted,
    set_condition,
)


@dataclass
class PodSetInfo:
    """Scheduling attributes injected into a started job's podset
    (reference pkg/podset PodSetInfo)."""

    name: str
    count: int
    node_selector: Dict[str, str] = field(default_factory=dict)
    tolerations: list = field(default_factory=list)
    topology_domains: List[Tuple[Tuple[str, ...], int]] = field(
        default_factory=list
    )


class PodSetInfoConflict(ValueError):
    """A podset info's node selector contradicts the job's own template
    (reference podset.go Merge: conflicting keys are an error — the job
    author pinned a node label the admitted flavor disagrees with)."""


class GenericJob(abc.ABC):
    """reference jobframework/interface.go:37 GenericJob."""

    # -- identity --
    @property
    @abc.abstractmethod
    def name(self) -> str: ...

    @property
    def namespace(self) -> str:
        return "default"

    @property
    @abc.abstractmethod
    def queue_name(self) -> str:
        """Target LocalQueue."""

    # -- suspension --
    @abc.abstractmethod
    def is_suspended(self) -> bool: ...

    @abc.abstractmethod
    def suspend(self) -> None: ...

    @abc.abstractmethod
    def run_with_podsets_info(self, infos: List[PodSetInfo]) -> None:
        """Unsuspend, injecting node selectors / topology domains."""

    def restore_podsets_info(self, infos: List[PodSetInfo]) -> None:
        """Undo run_with_podsets_info customizations on stop."""

    # -- shape --
    @abc.abstractmethod
    def pod_sets(self) -> List[PodSet]: ...

    # -- completion --
    @abc.abstractmethod
    def finished(self) -> Tuple[bool, bool, str]:
        """(finished, success, message)."""

    def pods_ready(self) -> bool:
        """All expected pods are running (WaitForPodsReady input)."""
        return True

    # -- optional capabilities (reference interface.go:76-228) --
    def priority(self) -> int:
        return 0

    def priority_class(self) -> Optional[str]:
        return None

    def active(self) -> bool:
        return True

    def max_execution_time_seconds(self) -> Optional[int]:
        return None

    def reclaimable_pods(self) -> Dict[str, int]:
        """podset name -> pods whose resources can be reclaimed early."""
        return {}


class IntegrationRegistry:
    """reference integrationmanager.go: frameworks register adapters."""

    def __init__(self) -> None:
        self._integrations: Dict[str, Callable[..., GenericJob]] = {}
        self._enabled: Dict[str, bool] = {}

    def register(
        self, framework_name: str, factory: Callable[..., GenericJob],
        enabled: bool = True,
    ) -> None:
        self._integrations[framework_name] = factory
        self._enabled[framework_name] = enabled

    def enabled(self, framework_name: str) -> bool:
        return self._enabled.get(framework_name, False)

    def set_enabled(self, framework_name: str, value: bool) -> None:
        if framework_name in self._integrations:
            self._enabled[framework_name] = value

    def factory(self, framework_name: str):
        return self._integrations.get(framework_name)

    def names(self) -> List[str]:
        return sorted(self._integrations)


registry = IntegrationRegistry()


def workload_name_for(job: GenericJob) -> str:
    return f"{type(job).__name__.lower()}-{job.name}"


def construct_workload(job: GenericJob, now: float) -> Workload:
    """reference reconciler.go:1424 constructWorkload."""
    return Workload(
        name=workload_name_for(job),
        namespace=job.namespace,
        queue_name=job.queue_name,
        pod_sets=[ps for ps in job.pod_sets()],
        priority=job.priority(),
        priority_class=job.priority_class(),
        active=job.active(),
        creation_time=now,
        maximum_execution_time_seconds=job.max_execution_time_seconds(),
    )


def podset_infos_from_admission(
    wl: Workload, admission: Admission
) -> List[PodSetInfo]:
    """Build start-time podset infos from the admission: flavors' node
    labels become node selectors; topology domains pin the gang
    (reference reconciler.go startJob + podset.go Merge)."""
    infos: List[PodSetInfo] = []
    for i, psa in enumerate(admission.pod_set_assignments):
        info = PodSetInfo(name=psa.name, count=psa.count)
        if psa.topology_assignment is not None:
            info.topology_domains = list(psa.topology_assignment.domains)
        infos.append(info)
    return infos


class JobReconciler:
    """reference reconciler.go:296 ReconcileGenericJob, call-driven.

    The manager owns one instance; it keeps the job <-> workload link and
    drives suspend/unsuspend according to workload admission state.
    """

    def __init__(self, manager) -> None:
        self.manager = manager
        self.job_of_workload: Dict[str, GenericJob] = {}
        self.workload_of_job: Dict[str, str] = {}

    def _job_key(self, job: GenericJob) -> str:
        return f"{job.namespace}/{job.name}"

    def reconcile(self, job: GenericJob) -> Optional[Workload]:
        """ensureOneWorkload + lifecycle step for one job. Returns the
        workload (created if needed)."""
        now = self.manager.clock()
        jkey = self._job_key(job)
        wl_key = self.workload_of_job.get(jkey)
        wl = self.manager.workloads.get(wl_key) if wl_key else None

        if wl is None:
            if not job.queue_name and not getattr(
                self.manager, "manage_jobs_without_queue_name", False
            ):
                # Unmanaged (reference manageJobsWithoutQueueName=false):
                # kueue ignores the job; it may run on its own.
                return None
            # Webhook-equivalent: jobs are created suspended
            # (reference base_webhook.go Default).
            if not job.is_suspended():
                job.suspend()
            wl = construct_workload(job, now)
            self.manager.create_workload(wl)
            self.workload_of_job[jkey] = wl.key
            self.job_of_workload[wl.key] = job
            return wl

        finished, success, msg = job.finished()
        if finished and get_condition(wl, COND_FINISHED) is None:
            set_condition(wl, COND_FINISHED, True,
                          "Succeeded" if success else "Failed", msg, now)
            self.manager.finish_workload(wl)
            return wl

        # Reclaimable-pods capability: jobs report early-finished pods
        # (reference interface.go ReclaimablePods).
        reclaimable = job.reclaimable_pods()
        if reclaimable and is_admitted(wl):
            self.manager.reclaim_pods(wl, reclaimable)

        if is_admitted(wl) and job.is_suspended():
            # startJob (reference reconciler.go:1326).
            infos = podset_infos_from_admission(wl, wl.status.admission)
            # Flavor node labels -> node selectors.
            for i, psa in enumerate(wl.status.admission.pod_set_assignments):
                for flavor_name in psa.flavors.values():
                    rf = self.manager.cache.resource_flavors.get(flavor_name)
                    if rf is not None:
                        infos[i].node_selector.update(rf.node_labels)
                        infos[i].tolerations.extend(rf.tolerations)
            try:
                job.run_with_podsets_info(infos)
                job.start_error = None
            except PodSetInfoConflict as e:
                # Per-job start error, not a controller crash (reference
                # startJob returns the Merge error and the reconciler
                # retries): the job stays suspended; the next reconcile
                # retries the start.
                job.start_error = str(e)
        elif not is_admitted(wl) and not job.is_suspended():
            # stopJob (reference reconciler.go:1368): evicted/not admitted.
            job.suspend()
            job.restore_podsets_info([])
        return wl
