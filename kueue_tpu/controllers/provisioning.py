"""Provisioning admission check: capacity provisioning before admission.

Behavioral surface: reference pkg/controller/admissionchecks/provisioning —
per admitted-pending workload, create a ProvisioningRequest from the
check's ProvisioningRequestConfig, mirror its Provisioned/Failed state into
the AdmissionCheckState, and retry with backoff per the retry strategy.

The cluster-autoscaler seam becomes a pluggable CapacityProvider — for TPU
fleets: a reservation system, a GKE/TPU provisioner, or the test fake.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, Optional, Protocol

from kueue_tpu.api.constants import CheckState
from kueue_tpu.api.types import Workload
from kueue_tpu.manager import AdmissionCheckController, Manager


class ProvisioningState(str, enum.Enum):
    PENDING = "Pending"
    PROVISIONED = "Provisioned"
    FAILED = "Failed"


@dataclass
class ProvisioningRequestConfig:
    """reference apis provisioningrequestconfig_types.go."""

    name: str
    provisioning_class: str = "queued-provisioning.gke.io"
    parameters: Dict[str, str] = field(default_factory=dict)
    max_retries: int = 3
    retry_backoff_seconds: float = 60.0
    # reference podSetMergePolicy: IdenticalPodTemplates merges podsets
    # with identical per-pod requests into one entry.
    pod_set_merge_policy: Optional[str] = "IdenticalPodTemplates"


@dataclass
class ProvisioningRequest:
    """The capacity request handed to the provider."""

    name: str
    workload_key: str
    provisioning_class: str
    parameters: Dict[str, str]
    pod_sets: list
    attempt: int = 1
    state: ProvisioningState = ProvisioningState.PENDING
    message: str = ""
    retry_at: Optional[float] = None


class CapacityProvider(Protocol):
    def poll(self, request: ProvisioningRequest) -> ProvisioningState: ...


class AlwaysProvisioned:
    def poll(self, request: ProvisioningRequest) -> ProvisioningState:
        return ProvisioningState.PROVISIONED


class ProvisioningController(AdmissionCheckController):
    """reference provisioning/controller.go:83."""

    controller_name = "kueue.x-k8s.io/provisioning-request"

    def __init__(
        self,
        provider: Optional[CapacityProvider] = None,
        configs: Optional[Dict[str, ProvisioningRequestConfig]] = None,
    ) -> None:
        self.provider = provider or AlwaysProvisioned()
        # admission-check name -> config
        self.configs = configs or {}
        self.requests: Dict[str, ProvisioningRequest] = {}

    def config_for(self, check_name: str) -> ProvisioningRequestConfig:
        return self.configs.get(
            check_name, ProvisioningRequestConfig(name="default")
        )

    def sync(self, manager: Manager, wl: Workload, check_name: str) -> None:
        now = manager.clock()
        cfg = self.config_for(check_name)
        key = f"{wl.key}/{check_name}"
        req = self.requests.get(key)
        if req is None:
            pod_sets = list(wl.pod_sets)
            if cfg.pod_set_merge_policy == "IdenticalPodTemplates":
                import dataclasses as _dc

                merged = {}
                for ps in pod_sets:
                    key2 = tuple(sorted(ps.requests.items()))
                    if key2 in merged:
                        merged[key2].count += ps.count
                    else:
                        merged[key2] = _dc.replace(ps)
                pod_sets = list(merged.values())
            req = ProvisioningRequest(
                name=f"{wl.name}-{check_name}-1",
                workload_key=wl.key,
                provisioning_class=cfg.provisioning_class,
                parameters=dict(cfg.parameters),
                pod_sets=pod_sets,
            )
            self.requests[key] = req
        if req.retry_at is not None:
            if now < req.retry_at:
                return
            req.retry_at = None
            req.state = ProvisioningState.PENDING
            req.attempt += 1
            req.name = f"{wl.name}-{check_name}-{req.attempt}"

        if req.state == ProvisioningState.PENDING:
            req.state = self.provider.poll(req)

        acs = next(
            (a for a in wl.status.admission_checks if a.name == check_name),
            None,
        )
        if acs is None:
            return
        if req.state == ProvisioningState.PROVISIONED:
            acs.state = CheckState.READY
            acs.message = f"Provisioned by request {req.name}"
            acs.last_transition_time = now
            manager.metrics.inc("provisioning_requests_provisioned_total")
        elif req.state == ProvisioningState.FAILED:
            if req.attempt >= cfg.max_retries + 1:
                acs.state = CheckState.REJECTED
                acs.message = (
                    f"Provisioning failed after {req.attempt} attempts"
                )
                acs.last_transition_time = now
                self.requests.pop(key, None)
            else:
                # Backoff then re-create the request (reference
                # admissioncheck_reconciler.go retry path).
                req.retry_at = now + cfg.retry_backoff_seconds * (
                    2 ** (req.attempt - 1)
                )
                acs.message = f"Provisioning attempt {req.attempt} failed"
            manager.metrics.inc("provisioning_requests_failed_total")
