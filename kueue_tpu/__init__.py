"""kueue_tpu: a TPU-native job-queueing and quota-admission framework.

Capabilities of kubernetes-sigs/kueue — Workload/ClusterQueue/LocalQueue/
Cohort quota semantics, hierarchical borrowing, StrictFIFO/BestEffortFIFO,
flavor fungibility, classical + fair-sharing (DRF) preemption, two-phase
admission checks, multi-cluster dispatch, topology-aware gang placement —
with the admission hot loop reformulated as a batched tensor program solved
with JAX/XLA on TPU.

Public surface:

    from kueue_tpu import Manager
    from kueue_tpu.api.types import ClusterQueue, LocalQueue, ...
    from kueue_tpu.controllers.jobs import TrainJob, BatchJob, ...
"""

__version__ = "0.1.0"


def __getattr__(name):
    # Lazy exports: importing kueue_tpu stays lightweight (no JAX import
    # until the device path is actually used).
    if name == "Manager":
        from kueue_tpu.manager import Manager

        return Manager
    if name == "load_config":
        from kueue_tpu.config.configuration import load

        return load
    if name == "build_manager":
        from kueue_tpu.config.configuration import build_manager

        return build_manager
    raise AttributeError(name)
