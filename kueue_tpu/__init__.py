"""kueue_tpu: a TPU-native job-queueing and quota-admission framework.

Capabilities of kubernetes-sigs/kueue — Workload/ClusterQueue/LocalQueue/
Cohort quota semantics, hierarchical borrowing, StrictFIFO/BestEffortFIFO,
flavor fungibility, classical + fair-sharing (DRF) preemption, two-phase
admission checks, multi-cluster dispatch, topology-aware gang placement —
with the admission hot loop reformulated as a batched tensor program solved
with JAX/XLA on TPU.
"""

__version__ = "0.1.0"
