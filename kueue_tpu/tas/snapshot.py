"""Topology-Aware Scheduling: the gang-placement engine.

Behavioral surface: reference pkg/cache/scheduler/tas_flavor_snapshot.go —
the per-flavor topology tree (datacenter levels -> domains -> leaf nodes),
phase-1 capacity fill (per-leaf free capacity -> per-domain pod/slice
counts, bottom-up), phase-2a best-fit level search, phase-2b greedy descent
minimizing domains per level, and phase-3 assignment building.

For a TPU fleet the topology levels map onto interconnect domains (e.g.
("pod", "superpod", "host")): a required "superpod" constraint keeps a
model-parallel gang inside one ICI domain; slice constraints pin
sequence/tensor-parallel subgroups under a level.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

import numpy as np
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from kueue_tpu.api.types import (
    PodSet,
    Taint,
    Toleration,
    Topology,
    TopologyAssignment,
    TopologyRequest,
)

INF = 1 << 30


@dataclass
class Node:
    """A schedulable host (for TPU fleets: one TPU VM / host)."""

    name: str
    labels: Dict[str, str] = field(default_factory=dict)
    capacity: Dict[str, int] = field(default_factory=dict)
    taints: List[Taint] = field(default_factory=list)
    ready: bool = True


class Domain:
    """reference tas_flavor_snapshot.go:54."""

    __slots__ = (
        "id", "level_values", "parent", "children",
        "state", "state_with_leader", "slice_state",
        "slice_state_with_leader", "leader_state",
        "free_capacity",
    )

    def __init__(self, level_values: Tuple[str, ...]):
        self.id = "/".join(level_values)
        self.level_values = level_values
        self.parent: Optional["Domain"] = None
        self.children: List["Domain"] = []
        self.state = 0
        self.state_with_leader = 0
        self.slice_state = 0
        self.slice_state_with_leader = 0
        self.leader_state = 0
        self.free_capacity: Dict[str, int] = {}


def count_fits(requests: Dict[str, int], capacity: Dict[str, int]) -> int:
    """How many pods with ``requests`` fit in ``capacity``
    (reference resources.Requests.CountIn). A "pods" capacity on the node
    bounds the count even when not requested (the reference's OnePodRequest
    per pod)."""
    fits = INF
    for res, v in requests.items():
        if v <= 0:
            continue
        fits = min(fits, capacity.get(res, 0) // v)
    if "pods" in capacity and "pods" not in requests:
        fits = min(fits, capacity["pods"])
    return 0 if fits >= INF else max(0, fits)


@dataclass
class PlacementRequest:
    """One podset's topology placement request."""

    count: int
    single_pod_requests: Dict[str, int]
    required_level: Optional[str] = None
    preferred_level: Optional[str] = None
    unconstrained: bool = False
    slice_size: int = 1
    slice_required_level: Optional[str] = None
    node_selector: Dict[str, str] = field(default_factory=dict)
    tolerations: List[Toleration] = field(default_factory=list)
    leader_requests: Optional[Dict[str, int]] = None  # LWS leader pod
    balanced: bool = False
    # Inner slice layers: [(level, size)] below the outer slice layer.
    slice_layers: List[Tuple[str, int]] = field(default_factory=list)


class TASFlavorSnapshot:
    """Per-flavor topology tree with free capacities
    (reference tas_flavor_snapshot.go)."""

    def __init__(
        self,
        topology: Topology,
        nodes: Iterable[Node],
        usage: Optional[Dict[str, Dict[str, int]]] = None,
        flavor_taints: Sequence[Taint] = (),
        flavor_tolerations: Sequence[Toleration] = (),
    ) -> None:
        self.topology = topology
        self.level_keys = list(topology.levels)
        self.lowest_is_node = (
            bool(self.level_keys)
            and self.level_keys[-1] == "kubernetes.io/hostname"
        )
        self.flavor_taints = list(flavor_taints)
        self.flavor_tolerations = list(flavor_tolerations)
        # usage: leaf domain id -> resource -> used amount (from admitted
        # TAS workloads + non-TAS pods; reference tas_cache.go).
        self.usage = usage or {}

        self.domains: Dict[str, Domain] = {}
        self.leaves: List[Domain] = []
        self.roots: List[Domain] = []
        self._leaf_alias: Dict[str, str] = {}  # hostname -> full leaf id
        self._match_cache: Dict = {}
        self.domains_per_level: List[List[Domain]] = [
            [] for _ in self.level_keys
        ]
        self.nodes_by_leaf: Dict[str, List[Node]] = {}
        for node in nodes:
            if not node.ready:
                continue
            values = []
            ok = True
            for key in self.level_keys:
                if key == "kubernetes.io/hostname":
                    values.append(node.name)
                elif key in node.labels:
                    values.append(node.labels[key])
                else:
                    ok = False
                    break
            if not ok:
                continue
            leaf = self._ensure_domain(tuple(values))
            self.nodes_by_leaf.setdefault(leaf.id, []).append(node)
            if self.lowest_is_node:
                self._leaf_alias[values[-1]] = leaf.id
        self._build_static_arrays()

    def _build_static_arrays(self) -> None:
        """Dense per-leaf capacity arrays for the vectorized phase-1 fill
        (the Python per-leaf loop dominates at fleet scale otherwise)."""
        res: set = set()
        for nodes in self.nodes_by_leaf.values():
            for node in nodes:
                res.update(node.capacity)
        self._res_names = sorted(res)
        self._res_index = {r: i for i, r in enumerate(self._res_names)}
        ln = len(self.leaves)
        rn = max(len(self._res_names), 1)
        self._leaf_cap = np.zeros((ln, rn), dtype=np.int64)
        self._leaf_index = {leaf.id: i for i, leaf in enumerate(self.leaves)}
        for i, leaf in enumerate(self.leaves):
            for node in self.nodes_by_leaf.get(leaf.id, []):
                for r, v in node.capacity.items():
                    self._leaf_cap[i, self._res_index[r]] += v
        # Per-level parent index vectors for the vectorized roll-up: for
        # each domain at level l, the position of its parent at level l-1.
        self._level_parent_idx: List[Optional[np.ndarray]] = [None]
        for l in range(1, len(self.level_keys)):
            pos = {id(d): i for i, d in enumerate(self.domains_per_level[l - 1])}
            self._level_parent_idx.append(
                np.asarray(
                    [pos[id(d.parent)] for d in self.domains_per_level[l]],
                    dtype=np.int64,
                )
            )

    def share_structure(self) -> "TASFlavorSnapshot":
        """Cheap per-cycle snapshot: shares the immutable domain tree and
        capacity arrays, with fresh usage (reference rebuilds the whole
        snapshot per cycle; structure only changes on node/topology
        events)."""
        clone = object.__new__(TASFlavorSnapshot)
        clone.__dict__.update(self.__dict__)
        clone.usage = {}
        clone._match_cache = self._match_cache
        return clone

    def _ensure_domain(self, values: Tuple[str, ...]) -> Domain:
        did = "/".join(values)
        if did in self.domains:
            return self.domains[did]
        dom = Domain(values)
        self.domains[did] = dom
        level_idx = len(values) - 1
        self.domains_per_level[level_idx].append(dom)
        if level_idx == len(self.level_keys) - 1:
            self.leaves.append(dom)
        if level_idx == 0:
            self.roots.append(dom)
        else:
            parent = self._ensure_domain(values[:-1])
            dom.parent = parent
            parent.children.append(dom)
        return dom

    # -- usage bookkeeping (reference tas_cache.go) -------------------------

    def _canonical_leaf_id(self, leaf_id: str) -> str:
        """TopologyAssignments emitted with hostname-only levels (lowest
        level is the node) reference leaves by hostname; map those back to
        the full domain path."""
        if leaf_id in self.domains:
            return leaf_id
        return self._leaf_alias.get(leaf_id, leaf_id)

    def add_usage(self, leaf_id: str, requests: Dict[str, int]) -> None:
        leaf_id = self._canonical_leaf_id(leaf_id)
        dst = self.usage.setdefault(leaf_id, {})
        for res, v in requests.items():
            dst[res] = dst.get(res, 0) + v

    def remove_usage(self, leaf_id: str, requests: Dict[str, int]) -> None:
        leaf_id = self._canonical_leaf_id(leaf_id)
        dst = self.usage.setdefault(leaf_id, {})
        for res, v in requests.items():
            dst[res] = dst.get(res, 0) - v

    def clone_usage(self) -> Dict[str, Dict[str, int]]:
        return {k: dict(v) for k, v in self.usage.items()}

    # -- phase 1: capacity fill ---------------------------------------------

    def _leaf_free_capacity(
        self, leaf: Domain, simulate_empty: bool
    ) -> Dict[str, int]:
        cap: Dict[str, int] = {}
        for node in self.nodes_by_leaf.get(leaf.id, []):
            for res, v in node.capacity.items():
                cap[res] = cap.get(res, 0) + v
        if not simulate_empty:
            for res, used in self.usage.get(leaf.id, {}).items():
                cap[res] = cap.get(res, 0) - used
        return cap

    @property
    def has_tainted_nodes(self) -> bool:
        """Any node in the fleet carries taints — the single definition
        used by the host fast path, the device-compat gate and the cycle
        encoder (memoized; node sets only change via snapshot rebuild)."""
        cached = getattr(self, "_has_tainted", None)
        if cached is None:
            cached = any(
                n.taints
                for nodes in self.nodes_by_leaf.values()
                for n in nodes
            )
            self._has_tainted = cached
        return cached

    def _matching_capacity(self, req: PlacementRequest) -> np.ndarray:
        """Per-leaf capacity restricted to nodes passing the request's
        selector/tolerations; memoized per distinct (selector, tolerations)
        — workload specs repeat heavily in practice."""
        key = (
            tuple(sorted(req.node_selector.items())),
            tuple(req.tolerations),
        )
        cached = self._match_cache.get(key)
        if cached is not None:
            return cached
        if not req.node_selector and not self.has_tainted_nodes:
            cap = self._leaf_cap
        else:
            cap = np.zeros_like(self._leaf_cap)
            for i, leaf in enumerate(self.leaves):
                for node in self.nodes_by_leaf.get(leaf.id, []):
                    if self._node_matches(node, req):
                        for r, v in node.capacity.items():
                            cap[i, self._res_index[r]] += v
        self._match_cache[key] = cap
        return cap

    def _node_matches(self, node: Node, req: PlacementRequest) -> bool:
        for k, v in req.node_selector.items():
            if node.labels.get(k) != v:
                return False
        tolerations = list(req.tolerations) + self.flavor_tolerations
        for taint in list(node.taints) + self.flavor_taints:
            if taint.effect not in ("NoSchedule", "NoExecute"):
                continue
            if not any(t.tolerates(taint) for t in tolerations):
                return False
        return True

    def _fill_in_counts(
        self,
        req: PlacementRequest,
        slice_size: int,
        slice_level_idx: int,
        simulate_empty: bool,
        assumed_usage: Optional[Dict[str, Dict[str, int]]],
        required_replacement_domain: Optional[str] = None,
        sizes_at_level: Optional[Dict[int, int]] = None,
    ) -> None:
        """reference fillInCounts :1760 + fillLeafCounts :1863."""
        for dom in self.domains.values():
            dom.state = dom.state_with_leader = 0
            dom.slice_state = dom.slice_state_with_leader = 0
            dom.leader_state = 0
        # Vectorized leaf fill: free = static capacity - usage - assumed,
        # restricted to selector/taint-matching nodes; per-pod fit counts by
        # integer division over the resource axis.
        requests = dict(req.single_pod_requests)
        ln = len(self.leaves)
        rn = self._leaf_cap.shape[1]
        cap_arr = self._matching_capacity(req)
        if simulate_empty:
            free = cap_arr.copy()
        else:
            free = cap_arr.copy()
            for leaf_id, used in self.usage.items():
                i = self._leaf_index.get(leaf_id)
                if i is None:
                    continue
                for r, v in used.items():
                    ri = self._res_index.get(r)
                    if ri is not None:
                        free[i, ri] -= v
        if assumed_usage:
            for leaf_id, used in assumed_usage.items():
                i = self._leaf_index.get(self._canonical_leaf_id(leaf_id))
                if i is None:
                    continue
                for r, v in used.items():
                    ri = self._res_index.get(r)
                    if ri is not None:
                        free[i, ri] -= v

        fits = np.full(ln, INF, dtype=np.int64)
        for r, v in requests.items():
            if v <= 0:
                continue
            ri = self._res_index.get(r)
            col = free[:, ri] if ri is not None else np.zeros(ln, np.int64)
            fits = np.minimum(fits, np.maximum(col, 0) // v)
        if "pods" in self._res_index and "pods" not in requests:
            fits = np.minimum(
                fits, np.maximum(free[:, self._res_index["pods"]], 0)
            )
        fits = np.where(fits >= INF, 0, fits)
        if required_replacement_domain:
            for i, leaf in enumerate(self.leaves):
                if not leaf.id.startswith(required_replacement_domain):
                    fits[i] = 0
                    free[i] = 0

        for i, leaf in enumerate(self.leaves):
            leaf.state = int(fits[i])
            leaf.leader_state = 0
            leaf.state_with_leader = leaf.state
        if req.leader_requests is not None:
            for i, leaf in enumerate(self.leaves):
                cap = {
                    r: int(free[i, self._res_index[r]])
                    for r in self._res_names
                }
                leaf.free_capacity = cap
                if count_fits(req.leader_requests, cap) > 0:
                    leaf.leader_state = 1
                    cap2 = {
                        r: cap.get(r, 0) - req.leader_requests.get(r, 0)
                        for r in set(cap) | set(req.leader_requests)
                    }
                    leaf.state_with_leader = count_fits(requests, cap2)
                else:
                    leaf.state_with_leader = count_fits(requests, cap)

        leader_required = req.leader_requests is not None
        self._roll_up_counts(slice_size, slice_level_idx, leader_required,
                             sizes_at_level)

    def _roll_up_counts(
        self, slice_size: int, slice_level_idx: int, leader_required: bool,
        sizes_at_level: Optional[Dict[int, int]] = None,
    ) -> None:
        """Vectorized bottom-up accumulation (fillInCountsHelper :1902) as
        per-level segment reductions over parent-index vectors."""
        n_levels = len(self.level_keys)
        last = n_levels - 1
        doms = self.domains_per_level[last]
        state = np.asarray([d.state for d in doms], dtype=np.int64)
        swl = np.asarray([d.state_with_leader for d in doms], dtype=np.int64)
        leader = np.asarray([d.leader_state for d in doms], dtype=np.int64)
        if last == slice_level_idx:
            sl = state // slice_size
            sl_wl = swl // slice_size
        else:
            sl = np.zeros_like(state)
            sl_wl = np.zeros_like(state)
        for i, d in enumerate(doms):
            d.slice_state = int(sl[i])
            d.slice_state_with_leader = int(sl_wl[i])

        for l in range(last - 1, -1, -1):
            pidx = self._level_parent_idx[l + 1]
            n_parent = len(self.domains_per_level[l])
            # Multi-layer inner constraint at the child level: a child can
            # only contribute pods in multiples of the inner slice size
            # (reference fillInCountsHelper :1926 rounds childState down).
            inner = (sizes_at_level or {}).get(l + 1, 1)
            c_state = (state // inner) * inner if inner > 1 else state
            c_swl = (swl // inner) * inner if inner > 1 else swl
            p_state = np.zeros(n_parent, dtype=np.int64)
            np.add.at(p_state, pidx, c_state)
            p_slice = np.zeros(n_parent, dtype=np.int64)
            np.add.at(p_slice, pidx, sl)
            p_leader = np.zeros(n_parent, dtype=np.int64)
            np.maximum.at(p_leader, pidx, leader)

            contributes = (
                np.ones_like(leader, dtype=bool)
                if not leader_required else (leader > 0)
            )
            diff = np.where(contributes, c_state - c_swl, INF)
            sdiff = np.where(contributes, sl - sl_wl, INF)
            min_diff = np.full(n_parent, INF, dtype=np.int64)
            np.minimum.at(min_diff, pidx, diff)
            min_sdiff = np.full(n_parent, INF, dtype=np.int64)
            np.minimum.at(min_sdiff, pidx, sdiff)
            has_contrib = np.zeros(n_parent, dtype=bool)
            np.logical_or.at(has_contrib, pidx, contributes)

            p_swl = np.where(has_contrib, p_state - min_diff, 0)
            p_slice_wl = np.where(has_contrib, p_slice - min_sdiff, 0)

            if l == slice_level_idx:
                p_slice = p_state // slice_size
                p_slice_wl = p_swl // slice_size
            elif l > slice_level_idx:
                p_slice = np.zeros_like(p_state)
                p_slice_wl = np.zeros_like(p_state)

            pdoms = self.domains_per_level[l]
            for i, d in enumerate(pdoms):
                d.state = int(p_state[i])
                d.state_with_leader = int(p_swl[i])
                d.leader_state = int(p_leader[i])
                d.slice_state = int(p_slice[i])
                d.slice_state_with_leader = int(p_slice_wl[i])
            state, swl, leader, sl, sl_wl = (
                p_state, p_swl, p_leader, p_slice, p_slice_wl
            )

    def _fill_counts_helper(
        self, dom: Domain, slice_size: int, slice_level_idx: int, level: int,
        leader_required: bool,
    ) -> None:
        """reference fillInCountsHelper :1902."""
        if not dom.children:
            if level == slice_level_idx:
                dom.slice_state = dom.state // slice_size
                dom.slice_state_with_leader = (
                    dom.state_with_leader // slice_size
                )
            return
        children_capacity = 0
        slice_capacity = 0
        has_leader_contributor = False
        min_swl_diff = INF
        min_slice_swl_diff = INF
        leader_state = 0
        for child in dom.children:
            self._fill_counts_helper(
                child, slice_size, slice_level_idx, level + 1, leader_required
            )
            children_capacity += child.state
            slice_capacity += child.slice_state
            if not leader_required or child.leader_state > 0:
                has_leader_contributor = True
                min_swl_diff = min(
                    child.state - child.state_with_leader, min_swl_diff
                )
                min_slice_swl_diff = min(
                    child.slice_state - child.slice_state_with_leader,
                    min_slice_swl_diff,
                )
            leader_state = max(child.leader_state, leader_state)
        dom.state = children_capacity
        if has_leader_contributor:
            dom.state_with_leader = children_capacity - min_swl_diff
            slice_swl = slice_capacity - min_slice_swl_diff
        else:
            dom.state_with_leader = 0
            slice_swl = 0
        dom.leader_state = leader_state
        if level == slice_level_idx:
            dom.slice_state = dom.state // slice_size
            dom.slice_state_with_leader = dom.state_with_leader // slice_size
        elif level < slice_level_idx:
            dom.slice_state = slice_capacity
            dom.slice_state_with_leader = slice_swl

    # -- sorting / best fit --------------------------------------------------

    def _sorted_domains(self, domains: List[Domain]) -> List[Domain]:
        """BestFit order: slice_state desc, state asc, levelValues asc
        (reference sortedDomains :1731)."""
        return sorted(
            domains,
            key=lambda d: (-d.slice_state, d.state, d.level_values),
        )

    def _sorted_domains_with_leader(self, domains: List[Domain]) -> List[Domain]:
        return sorted(
            domains,
            key=lambda d: (
                -d.leader_state, -d.slice_state_with_leader,
                d.state_with_leader, d.level_values,
            ),
        )

    @staticmethod
    def _best_fit_for_slices(
        domains: List[Domain], slice_count: int, leader_count: int
    ) -> Domain:
        """First domain with the lowest sufficient capacity
        (reference findBestFitDomainBy)."""
        get = (
            (lambda d: d.slice_state_with_leader)
            if leader_count > 0
            else (lambda d: d.slice_state)
        )
        best = domains[0]
        for d in domains:
            if get(d) >= slice_count and (
                get(d) < get(best) or get(best) < slice_count
            ):
                best = d
        return best

    # -- phase 2a: level search ----------------------------------------------

    def _find_level_with_fit(
        self, search_level_idx: int, req: PlacementRequest, slice_size: int,
        required: bool, unconstrained: bool, leader_count: int,
    ) -> Tuple[int, List[Domain], str]:
        """reference findLevelWithFitDomains :1380 (BestFit profile)."""
        domains = self.domains_per_level[search_level_idx]
        if not domains:
            return 0, [], (
                f"no topology domains at level: "
                f"{self.level_keys[search_level_idx]}"
            )
        sorted_domains = self._sorted_domains_with_leader(list(domains))
        top = sorted_domains[0]
        slice_count = req.count // slice_size
        if (
            top.slice_state_with_leader >= slice_count
            and top.leader_state >= leader_count
        ):
            top = self._best_fit_for_slices(
                sorted_domains, slice_count, leader_count
            )
            return search_level_idx, [top], ""

        if required:
            return 0, [], (
                f"topology {self.level_keys[search_level_idx]} doesn't fit:"
                f" requested {slice_count} slice(s), fits {top.slice_state}"
            )
        if search_level_idx > 0 and not unconstrained:
            return self._find_level_with_fit(
                search_level_idx - 1, req, slice_size, required,
                unconstrained, leader_count,
            )
        # Top level (or unconstrained): gather multiple domains greedily.
        results: List[Domain] = []
        remaining = slice_count
        remaining_leaders = leader_count
        idx = 0
        while (
            remaining_leaders > 0
            and idx < len(sorted_domains)
            and sorted_domains[idx].leader_state > 0
        ):
            dom = sorted_domains[idx]
            if sorted_domains[idx].slice_state_with_leader >= remaining:
                dom = self._best_fit_for_slices(
                    sorted_domains[idx:], remaining, remaining_leaders
                )
            results.append(dom)
            remaining_leaders -= dom.leader_state
            remaining -= dom.slice_state_with_leader
            idx += 1
        if remaining_leaders > 0:
            return 0, [], "not enough leader capacity"
        rest = self._sorted_domains(
            [d for d in sorted_domains[idx:] if d not in results]
        )
        j = 0
        while remaining > 0 and j < len(rest):
            dom = rest[j]
            if dom.slice_state >= remaining:
                dom = self._best_fit_for_slices(rest[j:], remaining, 0)
            results.append(dom)
            remaining -= dom.slice_state
            j += 1
        if remaining > 0:
            return 0, [], (
                f"topology doesn't fit: requested {slice_count} slice(s),"
                f" fits {slice_count - remaining}"
            )
        return search_level_idx, results, ""

    # -- phase 2b: minimize counts -------------------------------------------

    def _update_counts_to_minimum(
        self, domains: List[Domain], count: int, leader_count: int,
        slice_size: int, slices: bool,
    ) -> List[Domain]:
        """reference updateCountsToMinimumGeneric :1578 (BestFit)."""
        result: List[Domain] = []
        remaining = count // slice_size if slices else count
        remaining_leaders = leader_count

        i = 0
        while i < len(domains):
            dom = domains[i]
            if remaining_leaders > 0 and dom.leader_state > 0:
                # Consume a leader-hosting domain.
                if slices:
                    take = min(dom.slice_state_with_leader, remaining)
                    dom.state = take * slice_size
                    dom.slice_state = take
                else:
                    take = min(dom.state_with_leader, remaining)
                    dom.state = take
                dom.leader_state = min(dom.leader_state, remaining_leaders)
                remaining_leaders -= dom.leader_state
                remaining -= take
                result.append(dom)
                if remaining <= 0 and remaining_leaders <= 0:
                    return result
                i += 1
                continue
            if slices:
                if dom.slice_state >= remaining:
                    dom = self._best_fit_for_slices(
                        domains[i:], remaining, 0
                    )
                    dom.leader_state = 0
                    dom.state = remaining * slice_size
                    dom.slice_state = remaining
                    result.append(dom)
                    return result
                dom.leader_state = 0
                dom.state = dom.slice_state * slice_size
                remaining -= dom.slice_state
                result.append(dom)
            else:
                if dom.state >= remaining:
                    get = lambda d: d.state
                    best = dom
                    for d in domains[i:]:
                        if get(d) >= remaining and (
                            get(d) < get(best) or get(best) < remaining
                        ):
                            best = d
                    dom = best
                    dom.leader_state = 0
                    dom.state = remaining
                    result.append(dom)
                    return result
                dom.leader_state = 0
                remaining -= dom.state
                result.append(dom)
            i += 1
        return result if remaining <= 0 else []

    # -- balanced placement (reference tas_balanced_placement.go) ------------

    def _evaluate_greedy(
        self, domains: List[Domain], slice_count: int, leader_count: int
    ) -> Tuple[bool, int, Optional[Domain], Optional[Domain]]:
        """evaluateGreedyAssignment :28: does the request fit, how many
        domains the greedy takes, and the last domain used (with/without a
        leader)."""
        selected = 0
        last_dom = None
        last_dom_leader = None
        remaining = slice_count
        remaining_leaders = leader_count
        rest = list(domains)
        if leader_count > 0:
            with_leader = self._sorted_domains_with_leader(rest)
            idx = 0
            while (
                remaining_leaders > 0
                and idx < len(with_leader)
                and with_leader[idx].leader_state > 0
            ):
                selected += 1
                last_dom_leader = with_leader[idx]
                remaining_leaders -= with_leader[idx].leader_state
                remaining -= with_leader[idx].slice_state_with_leader
                idx += 1
            rest = with_leader[idx:]
        if remaining_leaders > 0:
            return False, 0, None, None
        ordered = self._sorted_domains(rest)
        idx = 0
        while remaining > 0 and idx < len(ordered) and \
                ordered[idx].slice_state > 0:
            selected += 1
            last_dom = ordered[idx]
            remaining -= ordered[idx].slice_state
            idx += 1
        if remaining > 0:
            return False, 0, None, None
        return True, selected, last_dom_leader, last_dom

    @staticmethod
    def _balance_threshold(
        slice_count: int, selected: int,
        last_leader: Optional[Domain], last: Optional[Domain],
    ) -> int:
        """balanceThresholdValue :66."""
        threshold = slice_count // selected
        if last_leader is not None:
            threshold = min(threshold, last_leader.slice_state_with_leader)
        if last is not None:
            threshold = min(threshold, last.slice_state)
        return threshold

    @staticmethod
    def _domains_entropy(domains: List[Domain]) -> float:
        total = sum(d.state for d in domains)
        if not domains or total == 0:
            return 0.0
        entropy = 0.0
        for d in domains:
            if d.state > 0:
                p = d.state / total
                entropy += -p * math.log2(p)
        return entropy

    def _select_optimal_domain_set(
        self, domains: List[Domain], slice_count: int, leader_count: int,
        slice_size: int, prioritize_by_entropy: bool,
    ) -> Optional[List[Domain]]:
        """selectOptimalDomainSetToFit :82: DP over (domains-used,
        leaders-left, capacity-left) finding a minimal-domain-count set
        with minimal total capacity."""
        fits, optimal_n, _, _ = self._evaluate_greedy(
            domains, slice_count, leader_count
        )
        if not fits:
            return None
        ordered = list(domains)
        if prioritize_by_entropy:
            ordered.sort(key=lambda d: (
                -d.leader_state, -d.slice_state_with_leader,
                -self._domains_entropy(d.children), d.level_values,
            ))
        else:
            ordered.sort(key=lambda d: d.level_values)

        # placements[i][leaders_left][state_left] -> domain list.
        placements: List[Dict[int, Dict[int, List[Domain]]]] = [
            {} for _ in range(optimal_n + 1)
        ]
        placements[0][leader_count] = {slice_count * slice_size: []}
        for d in ordered:
            for i in range(optimal_n, 0, -1):
                for before_leader in sorted(placements[i - 1]):
                    for before_state in sorted(
                        placements[i - 1][before_leader]
                    ):
                        if before_leader <= 0 and before_state <= 0:
                            continue
                        before = placements[i - 1][before_leader][
                            before_state]
                        new_placement = before + [d]
                        if before_leader > 0 and d.leader_state > 0:
                            after_l = before_leader - d.leader_state
                            after_s = before_state - d.state_with_leader
                            placements[i].setdefault(
                                after_l, {}
                            ).setdefault(after_s, new_placement)
                        if d.slice_state > 0:
                            after_s = before_state - d.state
                            placements[i].setdefault(
                                before_leader, {}
                            ).setdefault(after_s, new_placement)

        best_by_state = placements[optimal_n].get(0, {})
        best_slice = None
        best_placement = None
        for slices_left in sorted(best_by_state):
            if slices_left <= 0 and (
                best_slice is None or slices_left > best_slice
            ):
                best_slice = slices_left
                best_placement = best_by_state[slices_left]
        return best_placement

    def _place_slices_balanced(
        self, domains: List[Domain], slice_count: int, leader_count: int,
        slice_size: int, threshold: int,
    ) -> Tuple[Optional[List[Domain]], str]:
        """placeSlicesOnDomainsBalanced :150: give every selected domain
        ``threshold`` slices, then distribute the extras front-to-back."""
        result = self._select_optimal_domain_set(
            domains, slice_count, leader_count, slice_size, False
        )
        if result is None:
            return None, ("TAS Balanced Placement: Cannot find optimal"
                          " domain set to fit the request")
        if slice_count < len(result) * threshold:
            return None, ("TAS Balanced Placement: Not enough slices to"
                          " meet the threshold")
        result = self._sorted_domains_with_leader(result)
        extra_left = slice_count - len(result) * threshold
        leaders_left = leader_count
        for dom in result:
            if leaders_left > 0:
                take = min(dom.slice_state_with_leader - threshold,
                           extra_left)
                dom.leader_state = 1
                leaders_left -= 1
            elif extra_left > 0:
                take = min(dom.slice_state - threshold, extra_left)
                dom.leader_state = 0
            else:
                dom.leader_state = 0
                take = 0
            dom.state = (threshold + take) * slice_size
            dom.slice_state = threshold + take
            dom.slice_state_with_leader = dom.slice_state
            dom.state_with_leader = dom.state - dom.leader_state
            extra_left -= take
        if extra_left > 0 or leaders_left > 0:
            return None, ("TAS Balanced Placement: Not all slices or"
                          " leaders could be placed")
        return result, ""

    def _clone_domain(self, d: Domain, parent: Optional[Domain]) -> Domain:
        clone = Domain(d.level_values)
        clone.parent = parent
        clone.state = d.state
        clone.state_with_leader = d.state_with_leader
        clone.slice_state = d.slice_state
        clone.slice_state_with_leader = d.slice_state_with_leader
        clone.leader_state = d.leader_state
        clone.free_capacity = dict(d.free_capacity)
        clone.children = [
            self._clone_domain(c, clone) for c in d.children
        ]
        return clone

    @staticmethod
    def _clear_state(d: Domain) -> None:
        d.state = d.slice_state = 0
        d.state_with_leader = d.slice_state_with_leader = 0
        d.leader_state = 0
        for c in d.children:
            TASFlavorSnapshot._clear_state(c)

    @staticmethod
    def _clear_leader_capacity(d: Domain) -> None:
        d.state_with_leader = d.slice_state_with_leader = 0
        d.leader_state = 0
        for c in d.children:
            TASFlavorSnapshot._clear_leader_capacity(c)

    @classmethod
    def _prune_node_below_threshold(
        cls, d: Domain, threshold: int, leader_required: bool
    ) -> None:
        if d.slice_state < threshold:
            cls._clear_state(d)
            return
        if leader_required and d.leader_state > 0 and \
                d.slice_state_with_leader < threshold:
            cls._clear_leader_capacity(d)

    def _prune_below_threshold(
        self, domains: List[Domain], threshold: int, slice_size: int,
        slice_level_idx: int, level: int, leader_required: bool,
    ) -> None:
        """pruneDomainsBelowThreshold :363."""
        for d in domains:
            for c in d.children:
                self._prune_node_below_threshold(
                    c, threshold, leader_required
                )
        for d in domains:
            self._fill_counts_helper(
                d, slice_size, slice_level_idx, level, leader_required
            )
            self._prune_node_below_threshold(d, threshold, leader_required)

    def _lower_level_domains(self, domains: List[Domain]) -> List[Domain]:
        return [c for d in domains for c in d.children]

    def _find_best_domains_balanced(
        self, slice_count: int, leader_count: int, slice_size: int,
        slice_level_idx: int, requested_level_idx: int,
    ) -> Tuple[Optional[List[Domain]], int]:
        """findBestDomainsForBalancedPlacement :232: evaluate each
        requested-level sibling group, maximizing the balance threshold."""
        if requested_level_idx == 0:
            groups = [list(self.domains_per_level[0])]
        else:
            uppers = sorted(
                self.domains_per_level[requested_level_idx - 1],
                key=lambda d: d.level_values,
            )
            groups = [list(u.children) for u in uppers]

        best_threshold = 0
        best_count = 0
        best_fit: Optional[List[Domain]] = None
        for group in groups:
            candidates = [self._clone_domain(d, None) for d in group]
            lower = (
                self._lower_level_domains(candidates)
                if requested_level_idx < slice_level_idx else candidates
            )
            fits, selected, last_leader, last = self._evaluate_greedy(
                lower, slice_count, leader_count
            )
            if not fits:
                continue
            threshold = self._balance_threshold(
                slice_count, selected, last_leader, last
            )
            threshold_with_reserve = threshold
            if leader_count > 0 and last is not None:
                threshold_with_reserve = min(
                    threshold, last.slice_state_with_leader
                )
            if threshold < best_threshold:
                continue
            self._prune_below_threshold(
                candidates, threshold, slice_size, slice_level_idx,
                requested_level_idx, leader_count > 0,
            )
            fits2, count2, _, _ = self._evaluate_greedy(
                candidates, slice_count, leader_count
            )
            if not fits2 and threshold_with_reserve < threshold:
                if threshold_with_reserve <= 0 or \
                        threshold_with_reserve < best_threshold:
                    continue
                threshold = threshold_with_reserve
                candidates = [self._clone_domain(d, None) for d in group]
                self._prune_below_threshold(
                    candidates, threshold, slice_size, slice_level_idx,
                    requested_level_idx, leader_count > 0,
                )
                fits2, count2, _, _ = self._evaluate_greedy(
                    candidates, slice_count, leader_count
                )
            if not fits2:
                continue
            if threshold > best_threshold or (
                threshold == best_threshold and count2 < best_count
            ):
                best_threshold = threshold
                best_count = count2
                best_fit = candidates
        return best_fit, best_threshold

    def _apply_balanced_placement(
        self, curr_fit: List[Domain], best_threshold: int,
        slice_count: int, leader_count: int, slice_size: int,
        slice_level_idx: int, requested_level_idx: int,
    ) -> Tuple[Optional[List[Domain]], int, str]:
        """applyBalancedPlacementAlgorithm :293."""
        if requested_level_idx < slice_level_idx:
            result = self._select_optimal_domain_set(
                curr_fit, slice_count, leader_count, slice_size, True
            )
            if result is None:
                return None, 0, ("TAS Balanced Placement: Cannot find"
                                 " optimal domain set to fit the request")
            curr_fit = self._lower_level_domains(result)
            fit_level_idx = requested_level_idx + 1
        else:
            fit_level_idx = requested_level_idx
        placed, reason = self._place_slices_balanced(
            curr_fit, slice_count, leader_count, slice_size, best_threshold
        )
        if reason:
            return None, 0, reason
        return placed, fit_level_idx, ""

    # -- main entry ------------------------------------------------------------

    def find_topology_assignment(
        self,
        req: PlacementRequest,
        simulate_empty: bool = False,
        assumed_usage: Optional[Dict[str, Dict[str, int]]] = None,
        required_replacement_domain: Optional[str] = None,
    ) -> Tuple[Optional[TopologyAssignment], Optional[TopologyAssignment], str]:
        """Returns (worker_assignment, leader_assignment, failure_reason).
        reference findTopologyAssignment :943."""
        required = req.required_level is not None
        unconstrained = req.unconstrained or (
            req.required_level is None and req.preferred_level is None
        )
        level_key = req.required_level or req.preferred_level
        if unconstrained and level_key is None:
            level_key = self.level_keys[-1] if self.level_keys else None
        if level_key is None or level_key not in self.level_keys:
            return None, None, f"no requested topology level: {level_key}"
        requested_level_idx = self.level_keys.index(level_key)

        slice_size = req.slice_size or 1
        if req.slice_required_level is not None:
            if req.slice_required_level not in self.level_keys:
                return None, None, (
                    f"no requested topology level for slices:"
                    f" {req.slice_required_level}"
                )
            slice_level_idx = self.level_keys.index(req.slice_required_level)
        else:
            slice_level_idx = len(self.level_keys) - 1
            slice_size = 1
        if requested_level_idx > slice_level_idx:
            return None, None, (
                "podset slice topology is above the podset topology"
            )
        if slice_size > 0 and req.count % slice_size != 0:
            return None, None, (
                f"pod count {req.count} not divisible by slice size"
                f" {slice_size}"
            )
        # Multi-layer slice sizes (reference buildSliceSizeAtLevel): each
        # inner layer must be strictly deeper and divide the previous size;
        # intermediate levels inherit the inner layer's size.
        slice_size_at_level: Dict[int, int] = {}
        prev_idx, prev_size = slice_level_idx, slice_size
        if req.slice_layers:
            from kueue_tpu.utils import features as _features

            if not _features.enabled("TASMultiLayerTopology"):
                return None, None, (
                    "multi-layer slice topologies are disabled"
                    " (TASMultiLayerTopology feature gate)"
                )
        for layer_level, layer_size in req.slice_layers:
            if layer_level not in self.level_keys:
                return None, None, (
                    f"no topology level for slice layer: {layer_level}"
                )
            idx2 = self.level_keys.index(layer_level)
            if idx2 <= prev_idx:
                return None, None, (
                    "slice layers must be strictly finer-grained"
                )
            if layer_size <= 0 or prev_size % layer_size != 0:
                return None, None, (
                    f"slice layer size {layer_size} must divide the outer"
                    f" layer size {prev_size}"
                )
            for lvl in range(prev_idx + 1, idx2 + 1):
                slice_size_at_level[lvl] = layer_size
            prev_idx, prev_size = idx2, layer_size

        leader_count = 1 if req.leader_requests is not None else 0

        # phase 1
        self._fill_in_counts(
            req, slice_size, slice_level_idx, simulate_empty, assumed_usage,
            required_replacement_domain,
            sizes_at_level=slice_size_at_level or None,
        )

        # Balanced placement (reference tas_balanced_placement.go +
        # tas_flavor_snapshot.go:1068): find the sibling group with the
        # highest balance threshold, pick a minimal optimal domain set via
        # DP, give every selected domain the threshold, distribute the
        # extras; fall back to BestFit on any failure.
        from kueue_tpu.utils import features

        slice_count = req.count // slice_size
        use_balanced = False
        curr: List[Domain] = []
        fit_level_idx = 0
        balanced_on = req.balanced or features.enabled("TASBalancedPlacement")
        if balanced_on and not required and not unconstrained:
            best_fit, best_threshold = self._find_best_domains_balanced(
                slice_count, leader_count, slice_size, slice_level_idx,
                requested_level_idx,
            )
            if best_threshold > 0 and best_fit is not None:
                placed, fl, reason_b = self._apply_balanced_placement(
                    best_fit, best_threshold, slice_count, leader_count,
                    slice_size, slice_level_idx, requested_level_idx,
                )
                if not reason_b and placed is not None:
                    use_balanced = True
                    curr = placed
                    fit_level_idx = fl

        # phase 2a
        if not use_balanced:
            fit_level_idx, curr, reason = self._find_level_with_fit(
                requested_level_idx, req, slice_size, required,
                unconstrained, leader_count,
            )
            if reason:
                return None, None, reason

            # phase 2b: descend, minimizing domains per level.
            curr = self._update_counts_to_minimum(
                curr, req.count, leader_count, slice_size, True
            )
        level_idx = fit_level_idx
        while level_idx < min(len(self.level_keys) - 1, slice_level_idx) \
                and not use_balanced:
            # Above the slice level: slices may be re-distributed freely
            # across all lower domains (reference :1092-1099); balanced
            # placement skips this loop — its per-domain counts are final.
            lower = self._sorted_domains(
                [c for d in curr for c in d.children]
            )
            curr = self._update_counts_to_minimum(
                lower, req.count, leader_count, slice_size, True
            )
            level_idx += 1
        while level_idx < len(self.level_keys) - 1:
            # At/below the slice level: per-parent assignment; an inner
            # slice layer constrains child distributions to multiples of
            # its size (reference :1100-1132). Above the slice level —
            # reachable only on the balanced path, whose fit level may sit
            # above it — distribution runs in OUTER slice units so slices
            # never split across sub-slice domains (reference :1104:
            # sliceSizeOnLevel = sliceSize when currentLevel <
            # sliceLevelIdx).
            if level_idx < slice_level_idx:
                inner = slice_size
            else:
                inner = slice_size_at_level.get(level_idx + 1, 1)
            new_curr: List[Domain] = []
            for dom in curr:
                lower = self._sorted_domains(list(dom.children))
                if inner > 1:
                    for d in lower:
                        d.slice_state = d.state // inner
                        d.slice_state_with_leader = (
                            d.state_with_leader // inner
                        )
                    taken = self._update_counts_to_minimum(
                        lower, dom.state, dom.leader_state, inner, True
                    )
                else:
                    taken = self._update_counts_to_minimum(
                        lower, dom.state, dom.leader_state, 1, False
                    )
                new_curr.extend(taken)
            curr = new_curr
            level_idx += 1

        # Safety net (deliberate deviation): the reference's balanced
        # descent recomputes sliceState = state // sliceSize above the
        # slice level (:1113), which over-counts fragmented subtrees and
        # can silently emit an assignment with FEWER pods than requested
        # (updateCountsToMinimum absorbs the shortage). We keep the
        # reference's counting bit-for-bit but refuse to admit a short
        # gang: surface a placement failure instead.
        placed_total = sum(d.state for d in curr)
        if placed_total != req.count:
            return None, None, (
                f"topology assignment under-placed: {placed_total} of"
                f" {req.count} pods (fragmented capacity at an"
                " intermediate level)"
            )

        # phase 3
        leader_assignment: Optional[TopologyAssignment] = None
        if leader_count:
            leader_domains = []
            worker_domains = []
            for dom in curr:
                if dom.leader_state > 0:
                    ld = Domain(dom.level_values)
                    ld.state = dom.leader_state
                    leader_domains.append(ld)
                if dom.state > 0:
                    worker_domains.append(dom)
            leader_assignment = self._build_assignment(leader_domains)
            curr = worker_domains
        return self._build_assignment(curr), leader_assignment, ""

    def _build_assignment(self, domains: List[Domain]) -> TopologyAssignment:
        """reference buildAssignment :1663."""
        domains = sorted(domains, key=lambda d: d.level_values)
        level_idx = len(self.level_keys) - 1 if self.lowest_is_node else 0
        ta = TopologyAssignment(levels=self.level_keys[level_idx:])
        for dom in domains:
            if dom.state == 0:
                continue
            ta.domains.append((dom.level_values[level_idx:], dom.state))
        return ta
