"""Batched counterfactual rollout: K scenarios, one device dispatch.

A scenario is a *quota perturbation* plus an *activity mask* over the
shared workload plane: ``nominal`` replaces the quota tree's nominal
capacities (expressing quota deltas and node drains) and ``active``
selects which rows start pending (expressing hypothetical submissions —
extra encoded rows that only one scenario switches on).

Everything else — the encoded cycle arrays, group layout, per-row
runtimes, and the already-running seed state — is shared across the
batch and closed over by vmap, so XLA keeps one copy of the heavy
tensors and batches only the [K, ...] planes. ``subtree_quota`` depends
solely on nominal capacities and lending limits (compute_subtree with
zero usage), so it is recomputed per scenario inside the vmapped
closure; the simulator re-derives usage roll-ups from the running set
every round regardless.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from kueue_tpu.models.sim_loop import SimInit, SimOutputs, make_sim_loop
from kueue_tpu.ops import quota_ops


class ScenarioTensors(NamedTuple):
    """Per-scenario planes; leading axis K is the batch."""

    nominal: jnp.ndarray  # i64[K,N,F,R] counterfactual nominal quotas
    active: jnp.ndarray  # bool[K,W] rows that start pending


def make_batched_rollout(s_max: int, kernel: str = "grouped",
                         n_levels: int = quota_ops.MAX_DEPTH + 1,
                         max_rounds: int = 512,
                         per_cq_heads: bool = True):
    """Build ``rollout(arrays, ga, runtime_ms, init, scen) -> SimOutputs``
    where every output field gains a leading K axis. The caller jits the
    returned function once per shape bucket.

    ``per_cq_heads`` defaults ON here (unlike :func:`make_sim_loop`):
    forecasts are promises about what the live scheduler will do, so each
    simulated round must pop one head per CQ and stage failed heads
    inadmissible exactly like ``QueueManager.heads()`` — the differential
    suite (tests/test_whatif.py) pins the trajectories bit-identical."""
    sim = make_sim_loop(
        s_max, max_rounds=max_rounds, kernel=kernel, n_levels=n_levels,
        per_cq_heads=per_cq_heads,
    )

    def one(arrays, ga, runtime_ms, init: SimInit,
            nominal: jnp.ndarray, active: jnp.ndarray) -> SimOutputs:
        tree = arrays.tree._replace(nominal=nominal)
        is_parent = jnp.zeros(tree.n_nodes, bool).at[tree.parent].max(
            tree.parent >= 0, mode="drop"
        )
        is_cq = tree.active & ~is_parent
        subtree, _usage = quota_ops.compute_subtree(
            tree, jnp.zeros_like(nominal), is_cq
        )
        tree = tree._replace(subtree_quota=subtree)
        # Rows a scenario leaves inactive must not start pending either;
        # running seed rows are scenario-independent.
        init = init._replace(pending=init.pending & active)
        return sim(
            arrays._replace(tree=tree, w_active=active), ga, runtime_ms,
            init,
        )

    def rollout(arrays, ga, runtime_ms, init: SimInit,
                scen: ScenarioTensors) -> SimOutputs:
        return jax.vmap(
            lambda nom, act: one(arrays, ga, runtime_ms, init, nom, act)
        )(scen.nominal, scen.active)

    return rollout
