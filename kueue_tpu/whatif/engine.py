"""WhatIfEngine: counterfactual admission forecasting over a live fork.

The engine answers three questions without ever touching scheduler
state:

* **ETA** — for every pending workload, how many virtual milliseconds
  until admission, and on which flavor? (``eta``)
* **capacity probes** — how do those answers move under a quota delta
  or a node drain? (``eta`` with scenarios)
* **preemption preview** — if this hypothetical workload were
  submitted right now, would it admit, and who would it evict?
  (``preview``)

Mechanically: fork the live state (``cache.snapshot()`` plus cloned
pending queue entries), encode it host-side with ``encode_cycle``,
seed the currently admitted workloads as already-running simulator
rows, and run K counterfactual scenarios through one batched device
dispatch of the vmapped virtual-time simulator
(whatif/batched.make_batched_rollout). The live arena, cache and
queues are never written — the only shared objects are immutable specs.

Containment: the dispatch path runs behind the ``whatif.dispatch``
fault-injection point and a dedicated circuit breaker. When the
breaker is open (or the rollout faults), forecasts degrade to the
queue-position heuristic — position in head order per ClusterQueue —
flagged with ``basis="queue_position"`` so callers can tell a real
rollout from the fallback.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from kueue_tpu.api.types import Workload
from kueue_tpu.core.workload_info import WorkloadInfo
from kueue_tpu.metrics import tracing
from kueue_tpu.obs import costs
from kueue_tpu.utils import faults
from kueue_tpu.utils.breaker import CircuitBreaker

# Per-workload expected runtime override (virtual milliseconds). Without
# it the engine falls back to maximum_execution_time_seconds, then to
# the engine-wide default — forecasts are only as good as the runtime
# model, so callers that know their job durations should annotate.
RUNTIME_ANNOTATION = "kueue.x-k8s.io/whatif-expected-runtime-ms"


class ForecastUnsupported(RuntimeError):
    """The snapshot is structurally outside the rollout model (e.g. TAS
    topologies). Not a fault: does not trip the breaker."""


@dataclass(frozen=True)
class QuotaDelta:
    """Additive change to one nominal quota cell. ``node`` may name a
    ClusterQueue or a Cohort."""

    node: str
    flavor: str
    resource: str
    delta: int


@dataclass(frozen=True)
class Scenario:
    """One counterfactual world. ``kind`` is the metrics label:
    "base", "quota", "drain" or "submit"."""

    kind: str
    label: str = ""
    quota_deltas: Tuple[QuotaDelta, ...] = ()
    drain_node: Optional[str] = None
    workload: Optional[Workload] = None
    cluster_queue: Optional[str] = None  # for ``workload`` resolution


@dataclass
class WorkloadForecast:
    key: str
    cluster_queue: str
    basis: str  # "rollout" | "queue_position"
    eta_ms: Optional[int] = None  # None = not admitted within horizon
    completed_ms: Optional[int] = None
    flavor: Optional[str] = None
    position: Optional[int] = None  # queue-position heuristic only

    def to_dict(self) -> dict:
        return {
            "key": self.key,
            "clusterQueue": self.cluster_queue,
            "basis": self.basis,
            "etaMs": self.eta_ms,
            "completedMs": self.completed_ms,
            "flavor": self.flavor,
            "position": self.position,
        }


@dataclass
class ScenarioForecast:
    kind: str
    label: str
    ok: bool = True
    reason: str = ""
    truncated: bool = False  # rollout hit the round horizon
    rounds: int = 0
    makespan_ms: int = 0
    admitted_within_horizon: int = 0
    pending_after: int = 0
    workloads: List[WorkloadForecast] = field(default_factory=list)
    # Aggregate deltas vs the base scenario (absent on base itself).
    vs_base: Optional[dict] = None

    def to_dict(self) -> dict:
        return {
            "kind": self.kind,
            "label": self.label,
            "ok": self.ok,
            "reason": self.reason,
            "truncated": self.truncated,
            "rounds": self.rounds,
            "makespanMs": self.makespan_ms,
            "admittedWithinHorizon": self.admitted_within_horizon,
            "pendingAfter": self.pending_after,
            "vsBase": self.vs_base,
            "workloads": [w.to_dict() for w in self.workloads],
        }


@dataclass
class WhatIfReport:
    basis: str  # "rollout" | "queue_position"
    scenarios: List[ScenarioForecast] = field(default_factory=list)
    reason: str = ""  # why the fallback basis was used
    wall_s: float = 0.0
    horizon_rounds: int = 0
    modeled_running: int = 0  # admitted rows seeded into the simulator
    unmodeled_running: int = 0  # admitted left as static base usage

    @property
    def base(self) -> ScenarioForecast:
        return self.scenarios[0]

    def to_dict(self) -> dict:
        return {
            "basis": self.basis,
            "reason": self.reason,
            "wallS": self.wall_s,
            "horizonRounds": self.horizon_rounds,
            "modeledRunning": self.modeled_running,
            "unmodeledRunning": self.unmodeled_running,
            "scenarios": [s.to_dict() for s in self.scenarios],
        }


@dataclass
class PreviewVictim:
    key: str
    cluster_queue: str
    priority: int

    def to_dict(self) -> dict:
        return {
            "key": self.key,
            "clusterQueue": self.cluster_queue,
            "priority": self.priority,
        }


@dataclass
class PreviewReport:
    basis: str  # "rollout" | "queue_position"
    outcome: str = ""
    ok: bool = True
    reason: str = ""
    flavor: Optional[str] = None
    borrowing: bool = False
    victims: List[PreviewVictim] = field(default_factory=list)
    position: Optional[int] = None  # queue-position fallback only
    wall_s: float = 0.0

    def to_dict(self) -> dict:
        return {
            "basis": self.basis,
            "outcome": self.outcome,
            "ok": self.ok,
            "reason": self.reason,
            "flavor": self.flavor,
            "borrowing": self.borrowing,
            "victims": [v.to_dict() for v in self.victims],
            "position": self.position,
            "wallS": self.wall_s,
        }


_OUTCOME_NAMES = {
    0: "NoFit",
    1: "NoCandidates",
    2: "NeedsHost",
    3: "FitSkipped",
    4: "Admitted",
    5: "Preempting",
    6: "Shadowed",
}


# Compile-shape buckets for the forecast W axis and the scan depth: the
# SAME ladder the admission driver pads with (models/buckets.py), so a
# forecast over the shapes the live scheduler runs reuses the driver's
# compiled executables instead of compiling near-duplicates.
from kueue_tpu.models.buckets import bucket_for as _w_bucket
from kueue_tpu.models.buckets import pow2_bucket as _pow2


class WhatIfEngine:
    """Read-only forecasting facade over a (cache, queues) pair.

    Thread-safe: a lock serializes forecasts (they share jit caches and
    the breaker), and nothing here mutates the cache or the queues.
    """

    def __init__(
        self,
        cache,
        queues,
        default_runtime_ms: int = 300_000,
        horizon_rounds: int = 512,
        runtime_ms_fn: Optional[Callable[[WorkloadInfo], int]] = None,
        breaker: Optional[CircuitBreaker] = None,
        clock: Callable[[], float] = time.monotonic,
        kernel: str = "fixedpoint",
    ) -> None:
        self.cache = cache
        self.queues = queues
        self.default_runtime_ms = int(default_runtime_ms)
        self.horizon_rounds = int(horizon_rounds)
        # Per-round admission pass for rollouts (make_sim_loop kernels).
        # Fair-sharing managers pass "fair_fixedpoint" so forecasts rank
        # contenders with the same DRS tournament the live cycles use.
        self.kernel = str(kernel)
        self._runtime_ms_fn = runtime_ms_fn
        self.breaker = breaker or CircuitBreaker(
            threshold=3, backoff_s=5.0, max_backoff_s=60.0, clock=clock
        )
        self._clock = clock
        # RLock: maybe_refresh() holds it across its refresh decision AND
        # the eta() call it triggers (which re-acquires), so a concurrent
        # preview() can never interleave with the refresh's jit-cache
        # bucket swap between the decision and the compile.
        self._lock = threading.RLock()
        self._rollout_fns: Dict[tuple, Callable] = {}
        # Spare-time refresh state (driver hook).
        self.last_report: Optional[WhatIfReport] = None
        self._last_refresh = -float("inf")

    # ------------------------------------------------------------------
    # runtime model
    # ------------------------------------------------------------------

    def runtime_ms(self, info: WorkloadInfo) -> int:
        if self._runtime_ms_fn is not None:
            return max(1, int(self._runtime_ms_fn(info)))
        ann = info.obj.annotations.get(RUNTIME_ANNOTATION)
        if ann is not None:
            try:
                return max(1, int(ann))
            except ValueError:
                pass
        if info.obj.maximum_execution_time_seconds:
            return max(1, int(info.obj.maximum_execution_time_seconds) * 1000)
        return self.default_runtime_ms

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------

    def eta(
        self,
        scenarios: Sequence[Scenario] = (),
        cluster_queue: Optional[str] = None,
        include_inadmissible: bool = True,
    ) -> WhatIfReport:
        """Forecast admission ETAs for every pending workload under the
        base world plus each extra scenario (one batched dispatch)."""
        with self._lock:
            t0 = self._clock()
            scens = [Scenario(kind="base", label="base")] + list(scenarios)
            for s in scens:
                tracing.inc("whatif_scenarios_total", {"kind": s.kind})
            reason = None
            if self.breaker.allow():
                try:
                    if faults.ENABLED:
                        faults.fire(faults.WHATIF_DISPATCH)
                    report = self._rollout(scens, cluster_queue,
                                           include_inadmissible)
                    self.breaker.record_success()
                    report.wall_s = self._clock() - t0
                    tracing.observe("whatif_rollout_seconds", report.wall_s)
                    return report
                except ForecastUnsupported as exc:
                    # Structural, not a device fault: resolve any
                    # half-open probe as success, degrade to heuristic.
                    self.breaker.record_success()
                    reason = f"unsupported: {exc}"
                except AssertionError:
                    raise
                except Exception as exc:  # contained: degrade, count
                    self.breaker.record_failure()
                    reason = f"{type(exc).__name__}: {exc}"
            else:
                reason = "breaker_open"
            tracing.inc("whatif_fallback_total")
            report = self._fallback(scens, cluster_queue, reason)
            report.wall_s = self._clock() - t0
            return report

    def preview(
        self,
        workload: Workload,
        cluster_queue: Optional[str] = None,
    ) -> PreviewReport:
        """One-cycle preemption preview: would this hypothetical
        workload admit right now, and which admitted workloads would it
        evict? Runs the device preemption cycle against the forked
        snapshot; never executes the preemption."""
        with self._lock:
            t0 = self._clock()
            tracing.inc("whatif_scenarios_total", {"kind": "preview"})
            reason = None
            if self.breaker.allow():
                try:
                    if faults.ENABLED:
                        faults.fire(faults.WHATIF_DISPATCH)
                    report = self._preview(workload, cluster_queue)
                    self.breaker.record_success()
                    report.wall_s = self._clock() - t0
                    tracing.observe("whatif_rollout_seconds", report.wall_s)
                    return report
                except ForecastUnsupported as exc:
                    self.breaker.record_success()
                    reason = f"unsupported: {exc}"
                except AssertionError:
                    raise
                except Exception as exc:
                    self.breaker.record_failure()
                    reason = f"{type(exc).__name__}: {exc}"
            else:
                reason = "breaker_open"
            tracing.inc("whatif_fallback_total")
            report = self._preview_fallback(workload, cluster_queue, reason)
            report.wall_s = self._clock() - t0
            return report

    def prewarm(self, aot: bool = True) -> Optional[WhatIfReport]:
        """Compile the rollout program for the current snapshot's shapes
        by running one base forecast; with the AOT store configured
        (perf/compile_cache), additionally serialize the compiled
        rollout executable for the next process. An explicit warmup
        path — the serialize hazard never rides an admission cycle."""
        report = self.eta()
        if aot:
            from kueue_tpu.perf import compile_cache

            compile_cache.store_recorded(("whatif_rollout",))
        return report

    def maybe_refresh(self, interval_s: float = 30.0) -> Optional[WhatIfReport]:
        """Driver spare-time hook: refresh the cached base ETA forecast
        at most every ``interval_s``. Never raises.

        Runs entirely under the engine lock (reentrant, so the inner
        ``eta()`` re-acquires safely): the unlocked version raced a
        concurrent ``preview()`` on ``_last_refresh`` / ``last_report``
        and on the jit-cache bucket swap between the refresh decision
        and the compile (tests/test_whatif.py hammer test)."""
        with self._lock:
            now = self._clock()
            if now - self._last_refresh < interval_s:
                return None
            self._last_refresh = now
            try:
                self.last_report = self.eta()
            except Exception:  # pragma: no cover - eta() already contains
                return None
            return self.last_report

    # ------------------------------------------------------------------
    # rollout path
    # ------------------------------------------------------------------

    def _resolve_cq(self, wl: Workload,
                    cluster_queue: Optional[str]) -> str:
        cq = cluster_queue or self.queues.cluster_queue_for(wl)
        if not cq:
            raise ForecastUnsupported(
                f"workload {wl.namespace}/{wl.name}: no LocalQueue "
                f"{wl.queue_name!r} / ClusterQueue mapping"
            )
        return cq

    def _collect_pending(self, include_inadmissible: bool
                         ) -> List[WorkloadInfo]:
        """Cloned pending entries across every CQ (they compete for
        shared cohort quota, so the rollout always covers the fleet;
        reports are filtered per-CQ at decode time)."""
        out: List[WorkloadInfo] = []
        getter = (self.queues.pending_workloads_all if include_inadmissible
                  else self.queues.pending_workloads)
        for name in sorted(self.queues.cluster_queues):
            out.extend(info.clone() for info in getter(name))
        return out

    @staticmethod
    def _model_admitted(info: WorkloadInfo, tidx, covered,
                        remaining: np.ndarray):
        """If ``info``'s quota usage maps exactly onto the device model
        (single flavor, covered resources, consistent snapshot usage),
        return (ni, fi, {ri: qty}); else None — the workload then stays
        as static base usage and never completes, a conservative
        (pessimistic-ETA) approximation."""
        ni = tidx.node_of.get(info.cluster_queue)
        if ni is None:
            return None
        try:
            u = info.usage()
        except Exception:
            return None
        if not u:
            return None
        flavors = {fr.flavor for fr, v in u.items() if v > 0}
        if len(flavors) != 1:
            return None
        fi = tidx.flavor_of.get(next(iter(flavors)))
        if fi is None:
            return None
        cells: Dict[int, int] = {}
        for fr, v in u.items():
            if v <= 0:
                continue
            ri = tidx.resource_of.get(fr.resource)
            if ri is None or not covered[ni, ri]:
                return None
            cells[ri] = cells.get(ri, 0) + int(v)
        if not cells:
            return None
        # The subtraction must not drive base usage negative (stale or
        # reconstructed snapshots): verify against what is left.
        for ri, v in cells.items():
            if remaining[ni, fi, ri] < v:
                return None
        for ri, v in cells.items():
            remaining[ni, fi, ri] -= v
        return ni, fi, cells

    def _next_timestamp(self, pending: Sequence[WorkloadInfo]) -> float:
        ts = [i.obj.creation_time for i in pending]
        return (max(ts) + 1.0) if ts else 1.0

    @staticmethod
    def _hypo(wl: Workload, ts: float) -> Workload:
        """Shallow-copy a hypothetical workload so the caller's object is
        never mutated; a fresh submission sorts after every real pending
        entry at equal priority."""
        import copy

        wl2 = copy.copy(wl)
        if wl2.creation_time == 0.0:
            wl2.creation_time = ts
        return wl2

    def _rollout(self, scens: List[Scenario],
                 cluster_queue: Optional[str],
                 include_inadmissible: bool) -> WhatIfReport:
        import jax
        import jax.numpy as jnp

        from kueue_tpu.models.encode import encode_cycle
        from kueue_tpu.models.sim_loop import SimInit
        from kueue_tpu.whatif.batched import ScenarioTensors

        snap = self.cache.snapshot()
        if snap.tas_flavors:
            raise ForecastUnsupported(
                "TAS topologies present; rollout forecasting does not "
                "model topology placement"
            )

        pending = self._collect_pending(include_inadmissible)
        # Hypothetical submissions ride as extra encoded head rows that
        # only their scenario activates. A fresh submission sorts after
        # every real pending entry at equal priority.
        next_ts = self._next_timestamp(pending)
        hypo_of_scen: Dict[int, WorkloadInfo] = {}
        heads = list(pending)
        for k, s in enumerate(scens):
            if s.workload is None:
                continue
            wl = self._hypo(s.workload, next_ts)
            next_ts += 1.0
            info = WorkloadInfo(wl, self._resolve_cq(wl, s.cluster_queue))
            hypo_of_scen[k] = info
            heads.append(info)

        # Upper-bound the W axis up front (pending + hypothetical heads
        # plus every admitted workload that may seed a running row) so
        # the modeled-admitted pass below never forces a re-encode.
        n_admitted = sum(
            len(cq.workloads) for cq in snap.cluster_queues.values()
        )
        arrays, idx = encode_cycle(
            snap, heads, snap.resource_flavors,
            w_pad=_w_bucket(len(heads) + n_admitted), device_put=False,
            fair_sharing=self.kernel.startswith("fair"),
        )
        tidx = idx.tree_index
        covered = np.asarray(arrays.covered)

        # Admitted workloads that the device model can represent become
        # already-running simulator rows: their usage moves from the
        # static base into dynamic (completing) usage.
        remaining = np.array(arrays.usage)
        modeled: List[Tuple[WorkloadInfo, int, int, Dict[int, int]]] = []
        unmodeled = 0
        for name in sorted(snap.cluster_queues):
            for info in snap.cluster_queues[name].workloads.values():
                m = self._model_admitted(info, tidx, covered, remaining)
                if m is None:
                    unmodeled += 1
                else:
                    modeled.append((info, m[0], m[1], m[2]))

        p_dev = len(idx.workloads)
        w_have = int(arrays.w_cq.shape[0])
        need = p_dev + len(modeled)
        if need > w_have:
            arrays, idx = encode_cycle(
                snap, heads, snap.resource_flavors,
                w_pad=_w_bucket(need), device_put=False,
                fair_sharing=self.kernel.startswith("fair"),
            )
            tidx = idx.tree_index
            covered = np.asarray(arrays.covered)
            remaining = np.array(arrays.usage)
            modeled2 = []
            for info, _ni, _fi, _cells in modeled:
                m = self._model_admitted(info, tidx, covered, remaining)
                if m is not None:
                    modeled2.append((info, m[0], m[1], m[2]))
            modeled = modeled2
            p_dev = len(idx.workloads)
            w_have = int(arrays.w_cq.shape[0])

        w_n = w_have
        w_cq = np.array(arrays.w_cq)
        w_req = np.array(arrays.w_req)
        base_usage = np.array(arrays.usage)
        running = np.zeros(w_n, bool)
        admitted_at0 = np.full(w_n, -1, np.int64)
        chosen0 = np.full(w_n, -1, np.int32)
        runtime = np.ones(w_n, np.int64)
        for j, (info, ni, fi, cells) in enumerate(modeled):
            row = p_dev + j
            w_cq[row] = ni
            w_req[row, :] = 0
            for ri, v in cells.items():
                w_req[row, ri] = v
                base_usage[ni, fi, ri] -= v
            running[row] = True
            admitted_at0[row] = 0
            chosen0[row] = fi
            runtime[row] = self.runtime_ms(info)
        arrays = arrays._replace(
            w_cq=w_cq, w_req=w_req, usage=base_usage,
        )

        # Per-scenario planes.
        hypo_rows: Dict[int, int] = {}  # scenario -> device row
        row_of = {id(info): i for i, info in enumerate(idx.workloads)}
        for k, info in hypo_of_scen.items():
            r = row_of.get(id(info))
            if r is None:
                raise ForecastUnsupported(
                    f"scenario {k} ({scens[k].label or scens[k].kind}): "
                    "hypothetical workload needs host-side scheduling"
                )
            hypo_rows[k] = r
        hypo_mask = np.zeros(w_n, bool)
        for r in hypo_rows.values():
            hypo_mask[r] = True
        base_active = np.array(arrays.w_active) & ~hypo_mask

        base_nom = np.array(arrays.tree.nominal)
        K = len(scens)
        # K-lane padding: bucket the scenario axis on the pow2 ladder so
        # nearby scenario counts share one compiled rollout instead of
        # recompiling per K. Pad lanes replay the base world (base
        # nominal + base active); vmap lanes are independent, so they
        # cannot perturb the real lanes, and decode reads only [:K].
        k_pad = _pow2(K, floor=1)
        nominal = np.broadcast_to(base_nom, (k_pad,) + base_nom.shape).copy()
        active = np.broadcast_to(base_active, (k_pad, w_n)).copy()
        scen_ok = [True] * K
        scen_reason = [""] * K
        for k, s in enumerate(scens):
            try:
                deltas = list(s.quota_deltas)
                if s.drain_node is not None:
                    deltas.extend(self._drain_deltas(s.drain_node, snap))
                for d in deltas:
                    ni = tidx.node_of.get(d.node)
                    fi = tidx.flavor_of.get(d.flavor)
                    ri = tidx.resource_of.get(d.resource)
                    if ni is None or fi is None or ri is None:
                        raise ForecastUnsupported(
                            f"unknown quota cell {d.node}/{d.flavor}/"
                            f"{d.resource}"
                        )
                    nominal[k, ni, fi, ri] = max(
                        0, int(nominal[k, ni, fi, ri]) + int(d.delta)
                    )
            except ForecastUnsupported as exc:
                scen_ok[k] = False
                scen_reason[k] = str(exc)
                nominal[k] = base_nom  # run the base world instead
            if k in hypo_rows and scen_ok[k]:
                active[k, hypo_rows[k]] = True

        for i, info in enumerate(idx.workloads):
            if not hypo_mask[i] and base_active[i]:
                runtime[i] = self.runtime_ms(info)
        for k, r in hypo_rows.items():
            runtime[r] = self.runtime_ms(hypo_of_scen[k])

        init = SimInit(
            pending=jnp.asarray(np.array(arrays.w_active)),
            running=jnp.asarray(running),
            admitted_at=jnp.asarray(admitted_at0),
            chosen_flavor=jnp.asarray(chosen0),
        )
        scen_t = ScenarioTensors(
            nominal=jnp.asarray(nominal), active=jnp.asarray(active)
        )

        # The fixed-point pass is exact for lending-limit trees too (its
        # chain walk mirrors the scan's cohort-lending bookkeeping), so
        # every forecast shares one rollout executable per s_max bucket.
        # Fair-sharing managers swap in the fair rounds via self.kernel.
        kernel = self.kernel
        s_max = _pow2(int(base_active.sum()) + len(hypo_rows), floor=8)
        fn = self._rollout_fn(s_max, kernel)
        arrays_d, ga_d = jax.device_put((arrays, idx.group_arrays))
        from kueue_tpu.perf import compile_cache

        t_disp = self._clock()
        out = compile_cache.dispatch(
            "whatif_rollout", fn,
            arrays_d, ga_d, jnp.asarray(runtime), init, scen_t,
            static=("s_max", s_max, "kernel", kernel,
                    "horizon", self.horizon_rounds),
        )
        adm = np.asarray(out.admitted_at)
        comp = np.asarray(out.completed_at)
        chosen = np.asarray(out.chosen_flavor)
        rounds = np.asarray(out.rounds)
        vclock = np.asarray(out.final_vclock)
        disp_s = self._clock() - t_disp
        # Honest padding gauges for the batched rollout (the PR 2 driver
        # idiom, extended to the scenario planes): real vs padded lanes
        # on both the K (scenario) and W (workload-row) axes.
        w_real = p_dev + len(modeled)
        if tracing.ENABLED:
            tracing.set_gauge(
                "padding_waste_lane_fraction", 1.0 - (K / k_pad),
                {"entry": "whatif_rollout", "axis": "K"},
            )
            tracing.set_gauge(
                "padding_waste_lane_fraction",
                1.0 - (w_real / w_n) if w_n else 0.0,
                {"entry": "whatif_rollout", "axis": "W"},
            )
        if costs.ENABLED:
            costs.charge(
                "whatif_rollout", w_n, disp_s,
                lanes={"K": (K, k_pad), "W": (w_real, w_n)},
            )

        # Decode. Per-scenario aggregates are vector math over the [K, W]
        # planes; the per-workload forecast list (10k dataclass rows at
        # production scale) is materialized once for the base scenario —
        # counterfactual scenarios carry aggregates plus, for submit
        # scenarios, the hypothetical workload's own forecast row.
        report = WhatIfReport(
            basis="rollout", horizon_rounds=self.horizon_rounds,
            modeled_running=len(modeled), unmodeled_running=unmodeled,
        )
        fallback_heads = [
            info for info in idx.host_fallback
            if cluster_queue in (None, info.cluster_queue)
        ]
        admitted = adm >= 0  # bool [K, W]
        n_adm_k = (admitted & active).sum(axis=1)
        n_pend_k = (active & ~admitted).sum(axis=1)
        # ETA deltas vs base over rows admitted in both worlds (own
        # hypothetical rows have no base counterpart and are excluded).
        both = active & admitted & active[0:1] & admitted[0:1] & ~hypo_mask
        for k, s in enumerate(scens):
            sf = ScenarioForecast(
                kind=s.kind, label=s.label or s.kind,
                ok=scen_ok[k], reason=scen_reason[k],
                rounds=int(rounds[k]),
                truncated=bool(rounds[k] >= self.horizon_rounds),
                makespan_ms=int(vclock[k]),
            )
            sf.admitted_within_horizon = int(n_adm_k[k])
            sf.pending_after = int(n_pend_k[k]) + len(fallback_heads)
            if k == 0:
                adm0, comp0, fl0 = adm[0], comp[0], chosen[0]
                for i, info in enumerate(idx.workloads):
                    if not active[0, i]:
                        continue
                    if cluster_queue not in (None, info.cluster_queue):
                        continue
                    fl = int(fl0[i])
                    sf.workloads.append(WorkloadForecast(
                        key=info.key, cluster_queue=info.cluster_queue,
                        basis="rollout",
                        eta_ms=int(adm0[i]) if adm0[i] >= 0 else None,
                        completed_ms=(int(comp0[i]) if comp0[i] >= 0
                                      else None),
                        flavor=(idx.flavors[fl]
                                if 0 <= fl < len(idx.flavors) else None),
                    ))
                # Device-incompatible pending entries degrade one by one.
                for pos, info in enumerate(fallback_heads):
                    sf.workloads.append(WorkloadForecast(
                        key=info.key, cluster_queue=info.cluster_queue,
                        basis="queue_position", position=pos,
                    ))
                sf.workloads.sort(
                    key=lambda w: (w.eta_ms is None,
                                   w.eta_ms or 0, w.key)
                )
            else:
                r = hypo_rows.get(k)
                if r is not None and scen_ok[k]:
                    info = idx.workloads[r]
                    fl = int(chosen[k, r])
                    sf.workloads.append(WorkloadForecast(
                        key=info.key, cluster_queue=info.cluster_queue,
                        basis="rollout",
                        eta_ms=int(adm[k, r]) if adm[k, r] >= 0 else None,
                        completed_ms=(int(comp[k, r]) if comp[k, r] >= 0
                                      else None),
                        flavor=(idx.flavors[fl]
                                if 0 <= fl < len(idx.flavors) else None),
                    ))
                deltas = (adm[k] - adm[0])[both[k]]
                sf.vs_base = {
                    "admitted_delta": int(n_adm_k[k]) - int(n_adm_k[0]),
                    "mean_eta_delta_ms":
                        (float(deltas.mean()) if deltas.size else None),
                    "makespan_delta_ms":
                        int(vclock[k]) - int(vclock[0]),
                }
            report.scenarios.append(sf)
        return report

    def _drain_deltas(self, node_name: str, snap) -> List[QuotaDelta]:
        """Approximate a node drain as nominal-quota reductions spread
        proportionally across the ClusterQueues of every ResourceFlavor
        whose node_labels select the node (docs/whatif.md#node-drain)."""
        node = self.cache.nodes.get(node_name)
        if node is None:
            raise ForecastUnsupported(f"unknown node {node_name!r}")
        matched = [
            rf for rf in snap.resource_flavors.values()
            if rf.node_labels and all(
                node.labels.get(k) == v for k, v in rf.node_labels.items()
            )
        ]
        if not matched:
            raise ForecastUnsupported(
                f"node {node_name!r} matches no ResourceFlavor node_labels"
            )
        out: List[QuotaDelta] = []
        for rf in matched:
            for res, cap in node.capacity.items():
                holders = []
                for cq in snap.cluster_queues.values():
                    q = 0
                    for fr, cell in cq.node.quotas.items():
                        if fr.flavor == rf.name and fr.resource == res:
                            q += cell.nominal
                    if q > 0:
                        holders.append((cq.name, q))
                total = sum(q for _n, q in holders)
                if total <= 0:
                    continue
                for cq_name, q in holders:
                    cut = min(q, (cap * q + total - 1) // total)
                    if cut > 0:
                        out.append(QuotaDelta(
                            node=cq_name, flavor=rf.name,
                            resource=res, delta=-cut,
                        ))
        return out

    def _rollout_fn(self, s_max: int, kernel: str):
        import jax

        from kueue_tpu.whatif.batched import make_batched_rollout

        key = (s_max, kernel, self.horizon_rounds)
        fn = self._rollout_fns.get(key)
        if fn is None:
            fn = jax.jit(make_batched_rollout(
                s_max, kernel=kernel, max_rounds=self.horizon_rounds
            ))
            self._rollout_fns[key] = fn
        return fn

    # ------------------------------------------------------------------
    # preview path
    # ------------------------------------------------------------------

    def _preview(self, workload: Workload,
                 cluster_queue: Optional[str]) -> PreviewReport:
        import jax
        import jax.numpy as jnp

        from kueue_tpu.models import batch_scheduler as bs
        from kueue_tpu.models.encode import encode_cycle

        snap = self.cache.snapshot()
        if snap.tas_flavors:
            raise ForecastUnsupported(
                "TAS topologies present; preview does not model "
                "topology placement"
            )
        cq = self._resolve_cq(workload, cluster_queue)
        workload = self._hypo(
            workload, self._next_timestamp(self._collect_pending(True))
        )
        info = WorkloadInfo(workload, cq)
        # Pad to the ladder's rung for one head: when the live driver's
        # bucket sits at the same rung, the preview reuses the
        # scheduler's own compiled cycle executable instead of jitting
        # a duplicate (the old dedicated _preview_fn always compiled
        # its own copy of the grouped-preempt program).
        arrays, idx = encode_cycle(
            snap, [info], snap.resource_flavors, preempt=True,
            w_pad=_w_bucket(1), device_put=False,
        )
        if any(h is info for h in idx.host_fallback) or not idx.workloads:
            raise ForecastUnsupported(
                "hypothetical workload needs host-side scheduling"
            )
        arrays_d, ga_d, adm_d = jax.device_put(
            (arrays, idx.group_arrays, idx.admitted_arrays)
        )
        from kueue_tpu.perf import compile_cache

        out = compile_cache.dispatch(
            "cycle_grouped_preempt", bs.cycle_grouped_preempt,
            arrays_d, ga_d, adm_d,
        )
        row = next(i for i, h in enumerate(idx.workloads) if h is info)
        outcome = int(np.asarray(out.outcome)[row])
        fl = int(np.asarray(out.chosen_flavor)[row])
        report = PreviewReport(
            basis="rollout",
            outcome=_OUTCOME_NAMES.get(outcome, str(outcome)),
            flavor=(idx.flavors[fl] if 0 <= fl < len(idx.flavors)
                    else None),
            borrowing=bool(np.asarray(out.borrow)[row] > 0),
        )
        if out.victims is not None and outcome == bs.OUT_PREEMPTING:
            vrow = np.asarray(out.victims)[row]
            for a, victim in enumerate(idx.admitted):
                if a < vrow.shape[0] and vrow[a]:
                    report.victims.append(PreviewVictim(
                        key=victim.key,
                        cluster_queue=victim.cluster_queue,
                        priority=victim.priority(),
                    ))
        return report

    # ------------------------------------------------------------------
    # queue-position fallback
    # ------------------------------------------------------------------

    def _heuristic_workloads(self, cluster_queue: Optional[str]
                             ) -> List[WorkloadForecast]:
        out: List[WorkloadForecast] = []
        names = ([cluster_queue] if cluster_queue
                 else sorted(self.queues.cluster_queues))
        for name in names:
            for pos, info in enumerate(
                self.queues.pending_workloads_all(name)
            ):
                out.append(WorkloadForecast(
                    key=info.key, cluster_queue=info.cluster_queue or name,
                    basis="queue_position", position=pos,
                ))
        return out

    def _fallback(self, scens: List[Scenario],
                  cluster_queue: Optional[str],
                  reason: str) -> WhatIfReport:
        report = WhatIfReport(
            basis="queue_position", reason=reason or "",
            horizon_rounds=self.horizon_rounds,
        )
        wls = self._heuristic_workloads(cluster_queue)
        for k, s in enumerate(scens):
            sf = ScenarioForecast(
                kind=s.kind, label=s.label or s.kind,
                ok=(k == 0), reason="" if k == 0 else (reason or ""),
                pending_after=len(wls),
            )
            if k == 0:
                sf.workloads = wls
            report.scenarios.append(sf)
        return report

    def _preview_fallback(self, workload: Workload,
                          cluster_queue: Optional[str],
                          reason: str) -> PreviewReport:
        try:
            cq = self._resolve_cq(workload, cluster_queue)
        except ForecastUnsupported as exc:
            return PreviewReport(
                basis="queue_position", ok=False,
                reason=f"{reason}; {exc}" if reason else str(exc),
            )
        prio = workload.priority
        ahead = sum(
            1 for i in self.queues.pending_workloads_all(cq)
            if i.priority() >= prio
        )
        return PreviewReport(
            basis="queue_position", ok=False, reason=reason or "",
            position=ahead,
        )
