"""What-if engine: batched counterfactual admission forecasting.

Read-only forecasting over a fork of the live snapshot — virtual-time
rollouts answering "when will my job start, where will it land, and who
would it preempt?" without ever mutating scheduler state. See
docs/whatif.md for the API and scenario semantics.
"""

from kueue_tpu.whatif.engine import (
    ForecastUnsupported,
    PreviewReport,
    QuotaDelta,
    Scenario,
    ScenarioForecast,
    WhatIfEngine,
    WhatIfReport,
    WorkloadForecast,
)
from kueue_tpu.whatif.batched import ScenarioTensors, make_batched_rollout

__all__ = [
    "ForecastUnsupported",
    "PreviewReport",
    "QuotaDelta",
    "Scenario",
    "ScenarioForecast",
    "ScenarioTensors",
    "WhatIfEngine",
    "WhatIfReport",
    "WorkloadForecast",
    "make_batched_rollout",
]
