"""Resource request preprocessing.

Behavioral surface: reference pkg/config resources section
(configuration_types.go:589-731): excludeResourcePrefixes strips matching
resources from scheduling; transformations map an input resource into
output scheduling resources (Retain keeps the input alongside, Replace
swaps it) — the DRA/device-class seam: e.g. one "tpu-v5e-slice" request
becomes 4 "tpu" chips.
"""

from __future__ import annotations

from typing import Dict, Iterable, List


def transform_requests(
    requests: Dict[str, int],
    exclude_prefixes: Iterable[str] = (),
    transformations: Iterable = (),
) -> Dict[str, int]:
    out: Dict[str, int] = {}
    tf_by_input = {t.input: t for t in transformations}
    for res, v in requests.items():
        if any(res.startswith(p) for p in exclude_prefixes):
            continue
        t = tf_by_input.get(res)
        if t is None:
            out[res] = out.get(res, 0) + v
            continue
        if t.strategy == "Retain":
            out[res] = out.get(res, 0) + v
        for o_res, per_unit in t.outputs.items():
            out[o_res] = out.get(o_res, 0) + per_unit * v
    return out
