"""State dumper (reference pkg/debugger: SIGUSR2 -> dump caches/queues).

register_signal_dump(manager) installs the same SIGUSR2 behavior; dump()
returns the text for programmatic use.
"""

from __future__ import annotations

import signal
import sys
from typing import TextIO


def dump(manager, out: TextIO = sys.stderr) -> None:
    cache = manager.cache
    queues = manager.queues
    print("=== kueue_tpu cache dump ===", file=out)
    print(f"ClusterQueues: {sorted(cache.cluster_queues)}", file=out)
    print(f"Cohorts: {sorted(cache.cohorts)}", file=out)
    print(f"Flavors: {sorted(cache.resource_flavors)}", file=out)
    print(f"Nodes: {len(cache.nodes)}", file=out)
    print("--- admitted workloads ---", file=out)
    for key, info in sorted(cache.workloads.items()):
        flag = " (assumed)" if key in cache.assumed else ""
        print(f"  {key} cq={info.cluster_queue}{flag} "
              f"usage={dict(info.usage())}", file=out)
    print("--- pending queues ---", file=out)
    for name, cqh in sorted(queues.cluster_queues.items()):
        heads = [i.obj.name for i in cqh.snapshot_sorted()]
        print(f"  {name}: active={heads} "
              f"inadmissible={sorted(cqh.inadmissible)}", file=out)
    print("=== end dump ===", file=out)


def register_signal_dump(manager) -> None:
    """SIGUSR2 -> dump, like the reference's pkg/debugger/debugger.go:31."""
    signal.signal(signal.SIGUSR2, lambda *_: dump(manager))
