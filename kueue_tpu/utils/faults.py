"""Fault-injection framework for the device solver and the remote seams.

The ROADMAP north star is a production system, and the reference Kueue
survives component failures by construction (controller-runtime requeue +
backoff around every reconcile). The JAX/TPU hot path this rebuild runs is
far more fragile — an XLA failure, a corrupted readback, or a dead device
tunnel used to take out the whole admission loop. This module is the
*test half* of the containment story: named injection points at every
device/remote seam let tests (and soak rigs) drive raises, corrupted
result planes, and delays through the real code paths, so the containment
layer in ``models/driver.py`` and the transport breakers in ``remote/``
are exercised against the exact failure classes they must absorb.

Zero-cost when disabled, same pattern as ``tracing.ENABLED``: every call
site guards with ``if faults.ENABLED:`` so the production path pays one
module-attribute read and nothing else. ``ENABLED`` is mutated only by
:func:`install` / :func:`clear`.

Injection points (the complete set — :meth:`FaultPlan.add` rejects
anything else so a typo'd point never silently no-ops):

- ``solver.dispatch``   — the batched kernel call in the driver
- ``arena.delta_apply`` — the CycleArena incremental scatter path
- ``device.readback``   — blocking device->host plane transfers (also the
  hook for *corrupt* rules: planes pass through :func:`corrupt_plane`)
- ``remote.transport``  — client-side socket/gRPC call attempts
- ``remote.dispatch``   — worker-side op dispatch (slow/failing worker)
- ``cache.snapshot``    — the device path's snapshot acquisition
- ``whatif.dispatch``   — the what-if engine's batched forecast dispatch
  (whatif/engine.py; degrades to the queue-position heuristic)
- ``readplane.dispatch`` — the read plane's coalesced batch dispatch
  (readplane/coalescer.py; a ``raise`` rule poisons exactly one
  coalescing window — every query in that window resolves with a
  structured error, later windows re-coalesce cleanly — and repeated
  failures open the per-coalescer breaker)
- ``compile.deserialize`` — AOT executable loads from the on-disk
  compile cache (perf/compile_cache.py; a corrupt or poisoned store
  falls back to the plain jit path behind a breaker)
- ``service.cycle``     — the top of one service-loop iteration
  (obs/service.py; a ``delay`` rule stalls the loop so ``/healthz``
  staleness detection can be drilled, a ``raise`` rule is contained by
  the loop and counted in ``service_loop_errors_total``)
- ``fleet.dispatch``    — the joint multi-cluster placement dispatch
  (fleet/dispatcher.py; a ``raise`` rule is contained by the host
  oracle fallback, counted ``solver_fallback_cycles_total{reason="fleet"}``)
- ``fleet.apply``       — one cluster lane's placement apply (delete
  victims / mirror / schedule_all on the worker; a failing lane leaves
  its placements PENDING — counted ``fleet_apply_failures_total`` — and
  never corrupts manager state or other lanes)
- ``pipeline.patch``    — the CycleArena speculative-encode patch step
  (models/arena.py; consuming a pipelined speculation buffer into the
  next cycle's W build. A ``raise`` rule aborts the speculation —
  counted in ``solver_pipeline_abort_total{reason="fault"}`` — and the
  cycle falls back to a fresh encode, never a corrupted one)
- ``ha.checkpoint_write`` — the primary's replication-stream write
  (controllers/ha.py; a ``raise`` rule is contained by the replicator
  breaker — the step completes, the stream marks itself dirty and
  re-publishes a full checkpoint once the breaker closes; counted in
  ``ha_replication_errors_total``)
- ``ha.event_tail``     — the standby's stream tail/apply step
  (controllers/ha.py; a failing tail never advances the cursor — the
  standby retries, or falls back to the latest full checkpoint)
- ``ha.takeover``       — the standby's promotion sequence (torn-tail
  truncation + final replay + lease acquisition; a ``raise`` rule
  aborts the promotion, which is retried on the next poll — the lease
  stays unclaimed rather than half-claimed)

Rule modes:

- ``raise``   — raise ``exc(point)`` (default :class:`InjectedFault`);
  pass ``exc=ConnectionError`` to model a transport drop that the
  client's retry/backoff machinery must absorb.
- ``delay``   — ``time.sleep(delay_s)`` (deadline / slow-worker tests).
- ``corrupt`` — mutate a readback plane via :func:`corrupt_plane`. The
  default corrupter writes *out-of-domain garbage* (NaN for floats,
  huge/negative values for ints, an all-zero wipe for bool planes): the
  threat model is a trashed or truncated readback buffer, which the
  driver's result-plane validation is specified to catch. A corruption
  that produces a semantically plausible but wrong answer is out of
  scope here — that class is covered by the arena verify mode and the
  device-vs-host differential suites.

Typical use::

    from kueue_tpu.utils import faults
    plan = faults.FaultPlan(seed=7)
    plan.add(faults.SOLVER_DISPATCH, mode="raise", rate=0.2)
    plan.add(faults.DEVICE_READBACK, mode="corrupt", rate=0.2,
             planes=("victims", "partial"))
    faults.install(plan)
    try:
        scheduler.schedule_all()
    finally:
        faults.clear()
"""

from __future__ import annotations

import random
import threading
import time
from collections import Counter
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

# Module-level fast flag: hot loops read this attribute directly. Mutate
# only through install()/clear().
ENABLED = False

SOLVER_DISPATCH = "solver.dispatch"
ARENA_DELTA_APPLY = "arena.delta_apply"
DEVICE_READBACK = "device.readback"
REMOTE_TRANSPORT = "remote.transport"
REMOTE_DISPATCH = "remote.dispatch"
CACHE_SNAPSHOT = "cache.snapshot"
WHATIF_DISPATCH = "whatif.dispatch"
READPLANE_DISPATCH = "readplane.dispatch"
COMPILE_DESERIALIZE = "compile.deserialize"
SERVICE_CYCLE = "service.cycle"
PIPELINE_PATCH = "pipeline.patch"
FLEET_DISPATCH = "fleet.dispatch"
FLEET_APPLY = "fleet.apply"
HA_CHECKPOINT_WRITE = "ha.checkpoint_write"
HA_EVENT_TAIL = "ha.event_tail"
HA_TAKEOVER = "ha.takeover"

POINTS = frozenset({
    SOLVER_DISPATCH,
    ARENA_DELTA_APPLY,
    DEVICE_READBACK,
    REMOTE_TRANSPORT,
    REMOTE_DISPATCH,
    CACHE_SNAPSHOT,
    WHATIF_DISPATCH,
    READPLANE_DISPATCH,
    COMPILE_DESERIALIZE,
    SERVICE_CYCLE,
    PIPELINE_PATCH,
    FLEET_DISPATCH,
    FLEET_APPLY,
    HA_CHECKPOINT_WRITE,
    HA_EVENT_TAIL,
    HA_TAKEOVER,
})

_MODES = ("raise", "delay", "corrupt")


class InjectedFault(RuntimeError):
    """Default exception raised by a ``raise``-mode rule."""


def default_corrupt(rng: random.Random, plane: str,
                    a: np.ndarray) -> np.ndarray:
    """Out-of-domain garbage per dtype (see module docstring for the
    threat model). Operates on a copy provided by :func:`corrupt_plane`."""
    if a.size == 0:
        return a
    if np.issubdtype(a.dtype, np.floating):
        k = max(1, a.size // 8)
        idxs = [rng.randrange(a.size) for _ in range(k)]
        a.flat[idxs] = np.nan
    elif a.dtype == np.bool_:
        # A truncated/dropped transfer reads back as zeros.
        a[...] = False
    else:
        k = max(1, a.size // 8)
        garbage = rng.choice([-(1 << 20), 1 << 28])
        idxs = [rng.randrange(a.size) for _ in range(k)]
        a.flat[idxs] = garbage
    return a


class _Rule:
    __slots__ = ("point", "mode", "rate", "delay_s", "exc", "corrupt_fn",
                 "times", "planes", "fired")

    def __init__(self, point: str, mode: str, rate: float, delay_s: float,
                 exc: Optional[Callable[[str], BaseException]],
                 corrupt_fn: Optional[Callable], times: Optional[int],
                 planes: Optional[Tuple[str, ...]]) -> None:
        self.point = point
        self.mode = mode
        self.rate = rate
        self.delay_s = delay_s
        self.exc = exc
        self.corrupt_fn = corrupt_fn
        self.times = times
        self.planes = planes
        self.fired = 0

    def spent(self) -> bool:
        return self.times is not None and self.fired >= self.times


class FaultPlan:
    """A deterministic (seeded) schedule of fault rules by point."""

    def __init__(self, seed: int = 0) -> None:
        self.rng = random.Random(seed)
        self.rules: Dict[str, List[_Rule]] = {}
        # (point, mode) -> times a rule actually fired.
        self.counts: Counter = Counter()
        # point -> times the point was consulted while installed.
        self.evaluated: Counter = Counter()

    def add(
        self,
        point: str,
        mode: str = "raise",
        rate: float = 1.0,
        delay_s: float = 0.0,
        exc: Optional[Callable[[str], BaseException]] = None,
        corrupt: Optional[Callable] = None,
        times: Optional[int] = None,
        planes: Optional[Sequence[str]] = None,
    ) -> "FaultPlan":
        if point not in POINTS:
            raise ValueError(f"unknown injection point {point!r}")
        if mode not in _MODES:
            raise ValueError(f"unknown fault mode {mode!r}")
        self.rules.setdefault(point, []).append(_Rule(
            point, mode, rate, delay_s, exc, corrupt, times,
            tuple(planes) if planes is not None else None,
        ))
        return self

    def fired(self, point: str, mode: Optional[str] = None) -> int:
        if mode is not None:
            return self.counts[(point, mode)]
        return sum(v for (p, _m), v in self.counts.items() if p == point)


_plan: Optional[FaultPlan] = None
_lock = threading.Lock()


def install(plan: FaultPlan) -> FaultPlan:
    """Install ``plan`` and flip the fast flag on."""
    global ENABLED, _plan
    with _lock:
        _plan = plan
        ENABLED = True
    return plan


def clear() -> None:
    global ENABLED, _plan
    with _lock:
        _plan = None
        ENABLED = False


def active_plan() -> Optional[FaultPlan]:
    return _plan


def fire(point: str) -> None:
    """Evaluate the ``raise``/``delay`` rules at ``point``. Call sites
    guard with ``if faults.ENABLED:`` — never call unconditionally from a
    hot loop."""
    plan = _plan
    if plan is None:
        return
    plan.evaluated[point] += 1
    for rule in plan.rules.get(point, ()):
        if rule.mode == "corrupt" or rule.spent():
            continue
        if rule.rate < 1.0 and plan.rng.random() >= rule.rate:
            continue
        rule.fired += 1
        plan.counts[(point, rule.mode)] += 1
        if rule.mode == "delay":
            time.sleep(rule.delay_s)
        else:
            exc = rule.exc or InjectedFault
            raise exc(f"injected fault at {point}")


def corrupt_plane(point: str, plane: str, array):
    """Return ``array``, possibly corrupted by a ``corrupt`` rule at
    ``point``. The input is copied before mutation — callers' arrays are
    never aliased. ``None`` passes through (absent optional planes)."""
    plan = _plan
    if plan is None or array is None:
        return array
    for rule in plan.rules.get(point, ()):
        if rule.mode != "corrupt" or rule.spent():
            continue
        if rule.planes is not None and plane not in rule.planes:
            continue
        if rule.rate < 1.0 and plan.rng.random() >= rule.rate:
            continue
        rule.fired += 1
        plan.counts[(point, "corrupt")] += 1
        fn = rule.corrupt_fn or default_corrupt
        array = fn(plan.rng, plane, np.array(array, copy=True))
    return array
