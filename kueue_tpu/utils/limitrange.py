"""Pod-spec-level request derivation: LimitRange defaulting, the
init-container max rule, sidecar accumulation and pod overhead.

Behavioral surface:
  * reference pkg/util/limitrange/limitrange.go — Summarize (keep-min of
    Max/MaxLimitRequestRatio, keep-max of Min, keep-first of defaults) and
    ValidatePodSpec (per-container and per-pod bound checks);
  * reference pkg/workload/resources.go AdjustResources — RuntimeClass
    overhead, LimitRange container defaults, limits-as-missing-requests;
  * k8s resourcehelpers.PodRequests — effective pod requests =
    max(sum of app containers + accumulated sidecars, running init peak)
    + overhead, with restartable (sidecar) init containers adding to the
    running base.

A migrating user's effective requests therefore match the reference for
pod-spec-shaped podsets; podsets that state ``requests`` directly (the
abstract shape) are taken as given.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from kueue_tpu.api.types import (
    Container,
    LimitRange,
    LimitRangeItem,
    PodSet,
    RuntimeClass,
    Workload,
)

REQUESTS_ABOVE_LIMITRANGE_MAX = "requests must not be above the limitRange max"
REQUESTS_BELOW_LIMITRANGE_MIN = "requests must not be below the limitRange min"
REQUESTS_EXCEED_LIMITS = "resource requests must not exceed limits"


def _keep_min(dst: Dict[str, int], src: Dict[str, int]) -> Dict[str, int]:
    out = dict(dst)
    for k, v in src.items():
        out[k] = min(out[k], v) if k in out else v
    return out


def _keep_max(dst: Dict[str, int], src: Dict[str, int]) -> Dict[str, int]:
    out = dict(dst)
    for k, v in src.items():
        out[k] = max(out[k], v) if k in out else v
    return out


def _keep_first(dst: Dict[str, int], src: Dict[str, int]) -> Dict[str, int]:
    out = dict(src)
    out.update(dst)
    return out


def summarize(ranges: List[LimitRange]) -> Dict[str, LimitRangeItem]:
    """limitrange.go:38 Summarize: one LimitRangeItem per type with the
    tightest bounds and first-encountered defaults."""
    out: Dict[str, LimitRangeItem] = {}
    for lr in ranges:
        for item in lr.items:
            cur = out.get(item.type)
            if cur is None:
                cur = LimitRangeItem(type=item.type)
                out[item.type] = cur
            cur.max = _keep_min(cur.max, item.max)
            cur.min = _keep_max(cur.min, item.min)
            cur.default = _keep_first(cur.default, item.default)
            cur.default_request = _keep_first(
                cur.default_request, item.default_request
            )
            cur.max_limit_request_ratio = _keep_min(
                cur.max_limit_request_ratio, item.max_limit_request_ratio
            )
    return out


def _is_sidecar(c: Container) -> bool:
    return c.restart_policy == "Always"


def pod_requests(ps: PodSet) -> Dict[str, int]:
    """Effective per-pod requests (k8s resourcehelpers.PodRequests):
    max(sum of app containers + accumulated sidecars, init peak) with
    sidecars folding into the running base, plus overhead; pod-level
    resources (KEP-2837) override the aggregate for resources they name."""
    reqs: Dict[str, int] = {}
    for c in ps.containers:
        for k, v in c.requests.items():
            reqs[k] = reqs.get(k, 0) + v
    restartable: Dict[str, int] = {}
    init_peak: Dict[str, int] = {}
    for c in ps.init_containers:
        if _is_sidecar(c):
            for k, v in c.requests.items():
                restartable[k] = restartable.get(k, 0) + v
            step = dict(restartable)
        else:
            step = dict(c.requests)
            for k, v in restartable.items():
                step[k] = step.get(k, 0) + v
        init_peak = _keep_max(init_peak, step)
    for k, v in restartable.items():
        reqs[k] = reqs.get(k, 0) + v
    reqs = _keep_max(reqs, init_peak)
    if ps.pod_requests:
        # Pod-level resources take precedence for the resources they name.
        reqs.update(ps.pod_requests)
    for k, v in ps.overhead.items():
        reqs[k] = reqs.get(k, 0) + v
    return reqs


def _apply_container_defaults(c: Container, item: LimitRangeItem) -> None:
    c.limits = _keep_first(c.limits, item.default)
    c.requests = _keep_first(c.requests, item.default_request)


def adjust_resources(
    wl: Workload,
    limit_ranges: List[LimitRange],
    runtime_classes: Optional[Dict[str, RuntimeClass]] = None,
) -> None:
    """reference resources.go AdjustResources: pod overhead from the
    RuntimeClass (when unset), LimitRange container defaults,
    limits-as-missing-requests — then derive each podset's effective
    ``requests`` for podsets that carry containers."""
    summary = summarize(limit_ranges)
    container_item = summary.get("Container")
    pod_item = summary.get("Pod")
    for ps in wl.pod_sets:
        if not ps.containers and not ps.init_containers:
            continue
        if ps.runtime_class_name and not ps.overhead:
            rc = (runtime_classes or {}).get(ps.runtime_class_name)
            if rc is not None:
                ps.overhead = dict(rc.overhead)
        if container_item is not None:
            for c in ps.init_containers:
                _apply_container_defaults(c, container_item)
            for c in ps.containers:
                _apply_container_defaults(c, container_item)
        if pod_item is not None and (ps.pod_requests or ps.pod_limits):
            ps.pod_limits = _keep_first(ps.pod_limits, pod_item.default)
            ps.pod_requests = _keep_first(
                ps.pod_requests, pod_item.default_request
            )
        # UseLimitsAsMissingRequestsInPod (resources.go:124).
        for c in list(ps.init_containers) + list(ps.containers):
            c.requests = _keep_first(c.requests, c.limits)
        if ps.pod_limits:
            ps.pod_requests = _keep_first(ps.pod_requests, ps.pod_limits)
        if not ps.requests_explicit:
            # Explicitly-stated requests (the abstract shorthand) win over
            # the container-derived totals.
            ps.requests = pod_requests(ps)


def _greater_keys(a: Dict[str, int], b: Dict[str, int]) -> List[str]:
    """Resources where a > b (only for keys present in both — reference
    resources.GreaterKeys semantics on typed lists)."""
    return sorted(k for k, v in a.items() if k in b and v > b[k])


def validate_resources(wl: Workload) -> List[str]:
    """resources.go ValidateResources: requests must not exceed limits."""
    errs: List[str] = []
    for i, ps in enumerate(wl.pod_sets):
        for c in list(ps.init_containers) + list(ps.containers):
            over = _greater_keys(c.requests, c.limits)
            if over:
                errs.append(
                    f"podSets[{i}] container {c.name or '?'} {over}: "
                    + REQUESTS_EXCEED_LIMITS
                )
        over = _greater_keys(ps.pod_requests, ps.pod_limits)
        if over:
            errs.append(
                f"podSets[{i}] pod resources {over}: "
                + REQUESTS_EXCEED_LIMITS
            )
    return errs


def validate_limit_ranges(
    wl: Workload, limit_ranges: List[LimitRange]
) -> List[str]:
    """limitrange.go ValidatePodSpec over every podset with containers."""
    if not limit_ranges:
        return []
    summary = summarize(limit_ranges)
    errs: List[str] = []
    container_item = summary.get("Container")
    pod_item = summary.get("Pod")
    for i, ps in enumerate(wl.pod_sets):
        if not ps.containers and not ps.init_containers:
            continue
        if container_item is not None:
            for c in list(ps.init_containers) + list(ps.containers):
                c_min = _keep_min(c.requests, c.limits)
                c_max = _keep_max(c.requests, c.limits)
                over = _greater_keys(c_max, container_item.max)
                if over:
                    errs.append(
                        f"podSets[{i}] container {c.name or '?'} {over}: "
                        + REQUESTS_ABOVE_LIMITRANGE_MAX
                    )
                under = _greater_keys(container_item.min, c_min)
                if under:
                    errs.append(
                        f"podSets[{i}] container {c.name or '?'} {under}: "
                        + REQUESTS_BELOW_LIMITRANGE_MIN
                    )
                for res, max_ratio in (
                    container_item.max_limit_request_ratio or {}
                ).items():
                    req_v = c.requests.get(res, 0)
                    lim_v = c.limits.get(res)
                    if req_v > 0 and lim_v is not None \
                            and lim_v / req_v > max_ratio:
                        errs.append(
                            f"podSets[{i}] container {c.name or '?'} "
                            f"{res}: limit/request ratio "
                            f"{lim_v / req_v:g} exceeds "
                            f"maxLimitRequestRatio {max_ratio:g}"
                        )
        if pod_item is not None:
            total = pod_requests(ps)
            over = _greater_keys(total, pod_item.max)
            if over:
                errs.append(
                    f"podSets[{i}] {over}: "
                    + REQUESTS_ABOVE_LIMITRANGE_MAX
                )
            under = _greater_keys(pod_item.min, total)
            if under:
                errs.append(
                    f"podSets[{i}] {under}: "
                    + REQUESTS_BELOW_LIMITRANGE_MIN
                )
    return errs
