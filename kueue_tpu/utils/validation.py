"""Object validation (webhook equivalents).

Behavioral surface: reference pkg/webhooks/{clusterqueue,cohort,
resourceflavor,workload}_webhook.go — structural invariants enforced at
apply/create time.
"""

from __future__ import annotations

from kueue_tpu.api.constants import BorrowWithinCohortPolicy, PreemptionPolicy
from kueue_tpu.api.types import ClusterQueue, Cohort, Workload


def validate_cluster_queue(cq: ClusterQueue) -> None:
    """reference clusterqueue_webhook.go:62-96."""
    if len(cq.resource_groups) > 16:
        raise ValueError("a ClusterQueue supports at most 16 resourceGroups")
    total_flavors = sum(len(rg.flavors) for rg in cq.resource_groups)
    if total_flavors > 256:
        raise ValueError("a ClusterQueue supports at most 256 flavors")
    seen_resources = set()
    for rg in cq.resource_groups:
        if not rg.covered_resources:
            raise ValueError("resourceGroup needs coveredResources")
        for res in rg.covered_resources:
            if res in seen_resources:
                raise ValueError(
                    f"resource {res} appears in multiple resourceGroups"
                )
            seen_resources.add(res)
        for fq in rg.flavors:
            for res, q in fq.resources.items():
                if res not in rg.covered_resources:
                    raise ValueError(
                        f"flavor {fq.name} defines quota for uncovered"
                        f" resource {res}"
                    )
                if q.nominal < 0:
                    raise ValueError("nominalQuota must be >= 0")
                if q.borrowing_limit is not None and q.borrowing_limit < 0:
                    raise ValueError("borrowingLimit must be >= 0")
                if q.lending_limit is not None and q.lending_limit < 0:
                    raise ValueError("lendingLimit must be >= 0")
                if q.lending_limit is not None and not cq.cohort:
                    raise ValueError(
                        "lendingLimit requires the ClusterQueue to be in a"
                        " cohort"
                    )
    bwc = cq.preemption.borrow_within_cohort
    if (
        bwc.policy == BorrowWithinCohortPolicy.NEVER
        and bwc.max_priority_threshold is not None
    ):
        raise ValueError(
            "maxPriorityThreshold requires borrowWithinCohort policy"
            " != Never"
        )


def validate_cohort(cohort: Cohort) -> None:
    if cohort.parent == cohort.name:
        raise ValueError("a Cohort cannot be its own parent")


def validate_workload(wl: Workload) -> None:
    """reference workload_webhook.go."""
    if not wl.pod_sets:
        raise ValueError("workload needs at least one podset")
    if len(wl.pod_sets) > 18:
        raise ValueError("workload supports at most 18 podsets")
    names = set()
    for ps in wl.pod_sets:
        if ps.name in names:
            raise ValueError(f"duplicate podset name {ps.name}")
        names.add(ps.name)
        if ps.count < 0:
            raise ValueError("podset count must be >= 0")
        if ps.min_count is not None and not (
            0 < ps.min_count <= ps.count
        ):
            raise ValueError("minCount must be in (0, count]")
        tr = ps.topology_request
        if tr is not None and tr.required_level and tr.preferred_level:
            raise ValueError(
                "topologyRequest cannot set both required and preferred"
            )
