"""Object validation (webhook equivalents).

Behavioral surface: reference pkg/webhooks/{clusterqueue,cohort,
resourceflavor,workload}_webhook.go — structural invariants enforced at
apply/create time, plus the update-path invariants (podSets immutability
under quota reservation, admission immutability, reclaimablePods
monotonicity, clusterName transitions).
"""

from __future__ import annotations

from typing import Dict, Optional

from kueue_tpu.api.constants import BorrowWithinCohortPolicy, PreemptionPolicy
from kueue_tpu.api.types import ClusterQueue, Cohort, ResourceFlavor, Workload

_VALID_TAINT_EFFECTS = {"NoSchedule", "PreferNoSchedule", "NoExecute"}


def validate_cluster_queue(cq: ClusterQueue) -> None:
    """reference clusterqueue_webhook.go:96-400."""
    if len(cq.resource_groups) > 16:
        raise ValueError("a ClusterQueue supports at most 16 resourceGroups")
    total_flavors = sum(len(rg.flavors) for rg in cq.resource_groups)
    if total_flavors > 256:
        raise ValueError("a ClusterQueue supports at most 256 flavors")
    seen_resources = set()
    for rg in cq.resource_groups:
        if not rg.covered_resources:
            raise ValueError("resourceGroup needs coveredResources")
        covered = set(rg.covered_resources)
        if len(covered) != len(rg.covered_resources):
            raise ValueError("coveredResources must not repeat")
        for res in rg.covered_resources:
            if res in seen_resources:
                raise ValueError(
                    f"resource {res} appears in multiple resourceGroups"
                )
            seen_resources.add(res)
        seen_flavors = set()
        for fq in rg.flavors:
            if fq.name in seen_flavors:
                raise ValueError(
                    f"flavor {fq.name} appears twice in one resourceGroup"
                )
            seen_flavors.add(fq.name)
            # validateFlavorQuotas: the flavor's resources must match the
            # group's covered resources exactly (:331).
            if set(fq.resources) != covered:
                raise ValueError(
                    f"flavor {fq.name} must define quota for exactly the"
                    f" coveredResources {sorted(covered)}"
                )
            for res, q in fq.resources.items():
                if q.nominal < 0:
                    raise ValueError("nominalQuota must be >= 0")
                if q.borrowing_limit is not None:
                    if q.borrowing_limit < 0:
                        raise ValueError("borrowingLimit must be >= 0")
                    if not cq.cohort:
                        raise ValueError(
                            "borrowingLimit requires the ClusterQueue to"
                            " be in a cohort"
                        )
                if q.lending_limit is not None:
                    if q.lending_limit < 0:
                        raise ValueError("lendingLimit must be >= 0")
                    if not cq.cohort:
                        raise ValueError(
                            "lendingLimit requires the ClusterQueue to be"
                            " in a cohort"
                        )
                    if q.lending_limit > q.nominal:
                        raise ValueError(
                            "lendingLimit must not exceed nominalQuota"
                            " (clusterqueue_webhook.go:383)"
                        )
    bwc = cq.preemption.borrow_within_cohort
    if (
        bwc.policy == BorrowWithinCohortPolicy.NEVER
        and bwc.max_priority_threshold is not None
    ):
        raise ValueError(
            "maxPriorityThreshold requires borrowWithinCohort policy"
            " != Never"
        )
    if (
        bwc.policy != BorrowWithinCohortPolicy.NEVER
        and cq.preemption.reclaim_within_cohort == PreemptionPolicy.NEVER
    ):
        # clusterqueue_webhook.go:278 validatePreemption.
        raise ValueError(
            "borrowWithinCohort requires reclaimWithinCohort != Never"
        )


def validate_cohort(cohort: Cohort) -> None:
    if cohort.parent == cohort.name:
        raise ValueError("a Cohort cannot be its own parent")


def validate_resource_flavor(rf: ResourceFlavor) -> None:
    """reference resourceflavor_webhook.go:84-110."""
    for taint in rf.node_taints:
        if not taint.key:
            raise ValueError("flavor taint key must not be empty")
        if taint.effect not in _VALID_TAINT_EFFECTS:
            raise ValueError(
                f"invalid taint effect {taint.effect!r}; must be one of"
                f" {sorted(_VALID_TAINT_EFFECTS)}"
            )


def validate_workload(wl: Workload) -> None:
    """reference workload_webhook.go:119 ValidateWorkload (create path)."""
    if not wl.pod_sets:
        raise ValueError("workload needs at least one podset")
    # KEP-7990: the priority-boost annotation must be a valid signed
    # integer when set (reference workload_webhook.go:153).
    from kueue_tpu.core.workload_info import PRIORITY_BOOST_ANNOTATION

    boost = wl.annotations.get(PRIORITY_BOOST_ANNOTATION)
    if boost is not None:
        try:
            int(boost)
        except ValueError:
            raise ValueError(
                f"metadata.annotations[{PRIORITY_BOOST_ANNOTATION}] must "
                f"be a valid signed integer, got {boost!r}"
            )
    if len(wl.pod_sets) > 18:
        raise ValueError("workload supports at most 18 podsets")
    names = set()
    variable_count = 0
    for ps in wl.pod_sets:
        if not ps.name:
            raise ValueError("podset name must not be empty")
        if ps.name in names:
            raise ValueError(f"duplicate podset name {ps.name}")
        names.add(ps.name)
        if ps.count < 0:
            raise ValueError("podset count must be >= 0")
        if ps.min_count is not None:
            variable_count += 1
            if not (0 < ps.min_count <= ps.count):
                raise ValueError("minCount must be in (0, count]")
        from kueue_tpu.utils import features

        if features.enabled("WorkloadValidateResourcesAreNonNegative"):
            for res, v in ps.requests.items():
                if v < 0:
                    raise ValueError(
                        f"podset {ps.name} request {res} must be >= 0"
                    )
        tr = ps.topology_request
        if tr is not None:
            if tr.required_level and tr.preferred_level:
                raise ValueError(
                    "topologyRequest cannot set both required and preferred"
                )
            if tr.slice_required_level is not None and (
                tr.slice_size is None or tr.slice_size <= 0
            ):
                raise ValueError(
                    "podSetSliceRequiredTopology requires a positive"
                    " podSetSliceSize"
                )
            if tr.slice_size is not None and tr.slice_size <= 0:
                raise ValueError("podSetSliceSize must be > 0")
    if variable_count > 1:
        raise ValueError("at most one podSet can use minCount")

    # Podset-group shape (reference jobframework/tas_validation.go:213
    # ValidatePodSetGroupingTopology): exactly 2 podsets per group, at
    # least one with a single replica (the LWS leader); grouping is
    # incompatible with slice constraints (:77-81).
    group_members: Dict[str, list] = {}
    for ps in wl.pod_sets:
        tr = ps.topology_request
        if tr is not None and getattr(tr, "podset_group_name", None):
            if tr.slice_required_level is not None:
                raise ValueError(
                    "podSetGroupName may not be combined with"
                    " podSetSliceRequiredTopology"
                )
            group_members.setdefault(tr.podset_group_name, []).append(ps)
    for gname, members in group_members.items():
        if len(members) != 2:
            raise ValueError(
                f"podset group {gname!r} can only define groups of exactly"
                f" 2 pod sets, got: {len(members)}"
            )
        if all(ps.count != 1 for ps in members):
            raise ValueError(
                f"podset group {gname!r} needs at least one pod set with"
                " only 1 replica"
            )

    # Status-side invariants (validateAdmission / validateAdmissionChecks).
    adm = wl.status.admission
    if adm is not None:
        psa_names = [psa.name for psa in adm.pod_set_assignments]
        if len(set(psa_names)) != len(psa_names):
            raise ValueError("podSetAssignments names must be unique")
        unknown = set(psa_names) - names
        if unknown:
            raise ValueError(
                f"podSetAssignments reference unknown podsets: "
                f"{sorted(unknown)}"
            )
    acs_names = [a.name for a in wl.status.admission_checks]
    if len(set(acs_names)) != len(acs_names):
        raise ValueError("admissionChecks names must be unique")
    for psn, count in wl.status.reclaimable_pods.items():
        if psn not in names:
            raise ValueError(
                f"reclaimablePods references unknown podset {psn}"
            )
        if count < 0:
            raise ValueError("reclaimablePods count must be >= 0")


def _podset_immutable_eq(new_ps, old_ps, allow_scale_down: bool) -> bool:
    """validateImmutablePodSet :448: every field but count is frozen;
    elastic jobs may scale count down."""
    count_ok = new_ps.count == old_ps.count or (
        allow_scale_down and new_ps.count < old_ps.count
    )
    return (
        count_ok
        and new_ps.name == old_ps.name
        and new_ps.requests == old_ps.requests
        and new_ps.min_count == old_ps.min_count
        and new_ps.node_selector == old_ps.node_selector
        and new_ps.tolerations == old_ps.tolerations
        and new_ps.topology_request == old_ps.topology_request
    )


def validate_workload_update(
    new: Workload, old: Workload, elastic: bool = False
) -> None:
    """reference workload_webhook.go:343 ValidateWorkloadUpdate."""
    from kueue_tpu.core.workload_info import has_quota_reservation

    validate_workload(new)

    if has_quota_reservation(old):
        if len(new.pod_sets) != len(old.pod_sets):
            raise ValueError(
                "podSets are immutable while quota is reserved"
            )
        for nps, ops in zip(new.pod_sets, old.pod_sets):
            if not _podset_immutable_eq(nps, ops, elastic):
                raise ValueError(
                    f"podSet {ops.name} is immutable while quota is"
                    " reserved (workload_webhook.go:448)"
                )

    # Admission may be set or cleared, but not changed (topology
    # assignments may be attached later — the delayed-TAS second pass).
    new_adm, old_adm = new.status.admission, old.status.admission
    if new_adm is not None and old_adm is not None:
        if len(new_adm.pod_set_assignments) != \
                len(old_adm.pod_set_assignments):
            raise ValueError("admission is immutable once set")
        for npsa, opsa in zip(new_adm.pod_set_assignments,
                              old_adm.pod_set_assignments):
            if (
                npsa.name != opsa.name
                or npsa.flavors != opsa.flavors
                or npsa.count != opsa.count
            ):
                raise ValueError(
                    "admission is immutable once set"
                    " (workload_webhook.go:368)"
                )

    # Reclaimable counts must not decrease while quota is reserved
    # (workload_webhook.go:387); scaled-down podsets are exempt.
    if has_quota_reservation(new) and has_quota_reservation(old):
        scaled_down = set()
        if elastic and new.status.admission is not None:
            current = {ps.name: ps.count for ps in new.pod_sets}
            for psa in new.status.admission.pod_set_assignments:
                if psa.count > current.get(psa.name, psa.count):
                    scaled_down.add(psa.name)
        for name, old_count in old.status.reclaimable_pods.items():
            if name in scaled_down:
                continue
            new_count = new.status.reclaimable_pods.get(name)
            if new_count is None:
                raise ValueError(
                    f"reclaimablePods for {name} cannot be removed"
                )
            if new_count < old_count:
                raise ValueError(
                    f"reclaimablePods for {name} cannot decrease"
                    f" ({new_count} < {old_count})"
                )

    # clusterName may be set once and cleared on eviction, never rewritten
    # (workload_webhook.go:470).
    if (
        old.status.cluster_name
        and new.status.cluster_name
        and new.status.cluster_name != old.status.cluster_name
    ):
        raise ValueError("status.clusterName cannot change once set")