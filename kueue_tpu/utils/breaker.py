"""Host-fallback circuit breaker for the device solver and remote seams.

The reference survives a persistently failing component by backing off the
reconcile that drives it (controller-runtime rate limiters); the analog
here is a classic three-state breaker shared by the device scheduler
(``models/driver.py``) and the remote worker clients (``remote/``):

- **closed** — the protected path runs normally; consecutive failures are
  counted and reset on any success.
- **open** — after ``threshold`` consecutive failures the breaker trips:
  ``allow()`` answers False until an exponential-backoff deadline passes,
  so every cycle/call degrades instantly (all-host scheduling, fast-fail
  dispatch) instead of paying the failure latency again.
- **half_open** — the first ``allow()`` past the deadline admits exactly
  one probe. A recorded success fully closes the breaker and resets the
  backoff; a failure re-opens it with the backoff doubled (capped).

The breaker is policy-free about *what* a failure is: the driver records
one per contained device cycle, the transport clients one per logical
call that exhausted its retries. Thread-safe (the remote clients are
driven from controller threads); the driver's use is single-threaded.

VERDICT round 5 motivation: the TPU tunnel was down for 18 consecutive
probes — without a breaker every one of those cycles re-paid the full
device dispatch + failure path.
"""

from __future__ import annotations

import threading
import time
from typing import Callable

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

# Gauge encoding for the solver_breaker_state metric.
STATE_GAUGE = {CLOSED: 0, OPEN: 1, HALF_OPEN: 2}


class CircuitBreaker:
    def __init__(
        self,
        threshold: int = 3,
        backoff_s: float = 1.0,
        max_backoff_s: float = 60.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        if threshold < 1:
            raise ValueError("threshold must be >= 1")
        self.threshold = threshold
        self.base_backoff_s = backoff_s
        self.max_backoff_s = max_backoff_s
        self.clock = clock
        self._lock = threading.Lock()
        self.state = CLOSED
        self.failures = 0  # consecutive failures while closed
        self.trips = 0  # consecutive trips since the last close
        self.last_backoff_s = 0.0
        self._retry_at = 0.0
        self._probing = False

    # ------------------------------------------------------------------

    def allow(self) -> bool:
        """May the protected path run now? Transitions open -> half_open
        when the backoff deadline has passed, admitting a single probe."""
        with self._lock:
            if self.state == CLOSED:
                return True
            if self.state == OPEN:
                if self.clock() >= self._retry_at:
                    self.state = HALF_OPEN
                    self._probing = True
                    return True
                return False
            # half_open: one probe in flight at a time.
            if self._probing:
                return False
            self._probing = True
            return True

    def record_success(self) -> None:
        with self._lock:
            self.failures = 0
            self._probing = False
            if self.state != CLOSED:
                self.state = CLOSED
                self.trips = 0
                self.last_backoff_s = 0.0

    def record_failure(self) -> None:
        with self._lock:
            self._probing = False
            if self.state == HALF_OPEN:
                self._trip_locked()
                return
            self.failures += 1
            if self.state == CLOSED and self.failures >= self.threshold:
                self._trip_locked()

    def _trip_locked(self) -> None:
        self.trips += 1
        backoff = min(
            self.base_backoff_s * (2 ** (self.trips - 1)),
            self.max_backoff_s,
        )
        self.last_backoff_s = backoff
        self.state = OPEN
        self.failures = 0
        self._retry_at = self.clock() + backoff

    # ------------------------------------------------------------------

    @property
    def gauge_value(self) -> int:
        return STATE_GAUGE[self.state]

    def __repr__(self) -> str:  # debugging aid
        return (f"CircuitBreaker(state={self.state}, failures="
                f"{self.failures}, trips={self.trips}, "
                f"backoff={self.last_backoff_s})")
