"""Feature gates (reference pkg/features/kube_features.go).

A small mutable registry with the reference's defaults. Gates not yet wired
into behavior are still registered so user configs carry over unchanged;
they're marked below as they become load-bearing.
"""

from __future__ import annotations

from typing import Dict

_DEFAULTS: Dict[str, bool] = {
    # -- load-bearing in kueue_tpu --
    "FlavorFungibility": True,
    "PrioritySortingWithinCohort": True,
    "FairSharingPreemptWithinNominal": True,
    "TopologyAwareScheduling": True,
    "PartialAdmission": True,
    "WaitForPodsReady": True,
    "LocalQueueMetrics": False,
    "ElasticJobsViaWorkloadSlices": False,
    "ConcurrentAdmission": False,
    "AdmissionFairSharing": False,
    "MultiKueue": True,
    "MultiKueueBatchJobWithManagedBy": False,
    "HierarchicalCohorts": True,
    "TASFailedNodeReplacement": True,
    "TASFailedNodeReplacementFailFast": True,
    "TASReplaceNodeOnPodTermination": True,
    "WorkloadRequestUseMergePatch": False,
    "ObjectRetentionPolicies": True,
    "SchedulerTimestampPreemptionBuffer": False,
    "DynamicResourceAllocation": False,
    "ProvisioningACC": True,
    "VisibilityOnDemand": True,
    "QueueVisibility": False,
    "PodIntegrationAutoEnable": True,
    "ConfigurableResourceTransformations": True,
    "ManagedJobsNamespaceSelectorAlwaysRespected": True,
    "PrioritizedAccessToFlavors": False,
    "FairSharingPrioritizeNonBorrowing": False,
}

_overrides: Dict[str, bool] = {}


def enabled(name: str) -> bool:
    if name in _overrides:
        return _overrides[name]
    return _DEFAULTS.get(name, False)


def set_enabled(name: str, value: bool) -> None:
    _overrides[name] = value


def reset() -> None:
    _overrides.clear()


def all_gates() -> Dict[str, bool]:
    out = dict(_DEFAULTS)
    out.update(_overrides)
    return out
