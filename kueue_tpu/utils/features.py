"""Feature gates (reference pkg/features/kube_features.go).

The complete reference gate registry (78 gates) with each gate's latest
versioned default. ``LOAD_BEARING`` lists the gates that change behavior in
kueue_tpu today; the rest are registered so user configs carry over
unchanged and flips become observable as they are wired in.
"""

from __future__ import annotations

from typing import Dict

# Every gate from kube_features.go:35-536, defaults = the newest
# VersionedSpecs entry's Default.
_DEFAULTS: Dict[str, bool] = {
    "PartialAdmission": True,
    "FlavorFungibility": True,
    "VisibilityOnDemand": True,
    "DisableWaitForPodsReady": False,
    "PrioritySortingWithinCohort": True,
    "FairSharingPreemptWithinNominal": True,
    "FairSharingPrioritizeNonBorrowing": True,
    "MultiKueue": True,
    "TopologyAwareScheduling": True,
    "LocalQueueMetrics": True,
    "TASProfileMixed": True,
    "HierarchicalCohorts": True,
    "AdmissionFairSharing": True,
    "ObjectRetentionPolicies": True,
    "TASFailedNodeReplacement": True,
    "ElasticJobsViaWorkloadSlices": True,
    "ElasticJobsViaWorkloadSlicesWithTAS": False,
    "TASFailedNodeReplacementFailFast": True,
    "TASReplaceNodeOnPodTermination": True,
    "SkipReassignmentForPodOwnedWorkloads": True,
    "TASReplaceNodeDueToNotReadyOverFixedTime": False,
    "ManagedJobsNamespaceSelectorAlwaysRespected": True,
    "TASBalancedPlacement": False,
    "TASAssignmentsEncodingByHostnamePrefix": True,
    "KueueDRAIntegration": True,
    "KueueDRAIntegrationExtendedResource": True,
    "KueueDRARejectWorkloadsWhenDRADisabled": True,
    "KueueDRAIntegrationPartitionableDevices": True,
    "KueueDRAIntegrationConsumableCapacity": False,
    "MultiKueueAdaptersForCustomJobs": True,
    "WorkloadRequestUseMergePatch": False,
    "MultiKueueAllowInsecureKubeconfigs": True,
    "MultiKueueKubeConfigPathValidation": False,
    "ReclaimablePods": True,
    "PropagateBatchJobLabelsToWorkload": True,
    "MultiKueueClusterProfile": False,
    "FailureRecoveryPolicy": False,
    "SkipFinalizersForPodsSuspendedByParent": True,
    "MultiKueueWaitForWorkloadAdmitted": True,
    "MultiKueueRedoAdmissionOnEvictionInWorker": True,
    "TLSOptions": True,
    "RemoveFinalizersWithStrictPatch": True,
    "TASReplaceNodeOnNodeTaints": True,
    "AssignQueueLabelsForPods": True,
    "TASMultiLayerTopology": True,
    "SchedulingEquivalenceHashing": True,
    "SchedulerLongRequeueInterval": False,
    "SchedulerTimestampPreemptionBuffer": False,
    "CustomMetricLabels": False,
    "SparkApplicationIntegration": False,
    "MultiKueueOrchestratedPreemption": False,
    "PriorityBoost": False,
    "AdmissionGatedBy": True,
    "ShortWorkloadNames": False,
    "FastQuotaReleaseInPodIntegration": False,
    "RejectUpdatesToCQWithInvalidOnFlavors": False,
    "FinishOrphanedWorkloads": True,
    "MultiKueueIncrementalDispatcherConfig": True,
    "MultiKueueIncrementalDispatcherRespectConfigOrder": True,
    "ConcurrentAdmission": False,
    "QuotaCheckStrategy": True,
    "MetricForWorkloadCreationLatency": True,
    "TASRespectNodeAffinityPreferred": False,
    "MultiKueueManagerQuotaAutomation": False,
    "WorkloadIdentifierAnnotations": True,
    "WorkloadPriorityClassDefaulting": False,
    "MetricsForCohorts": True,
    "CleanupProvisioningRequestsOnEviction": True,
    "TASHandleOverlappingFlavors": True,
    "UnadmittedWorkloadsObservability": False,
    "TASRecomputeAssignmentWithinSchedulingCycle": True,
    "UnadmittedWorkloadsExplicitStatus": False,
    "DeferRayServiceFinalizationForRedisCleanup": True,
    "TASCacheNodeMatchResults": True,
    "TASCachingRemainingResources": True,
    "SchedulerLibraryIntegration": False,
    "VectorizedResourceRequests": True,
    "WorkloadValidateResourcesAreNonNegative": True,
}

# Gates that flip observable behavior in kueue_tpu today.
LOAD_BEARING = frozenset({
    "PartialAdmission",            # scheduler partial-admission search
    "PrioritySortingWithinCohort",  # admission order + fair tournament key
    "FairSharingPreemptWithinNominal",  # fair preemption rule S1 shortcut
    "TASFailedNodeReplacement",    # node-health replacement pipeline
    "TASFailedNodeReplacementFailFast",  # evict instead of waiting
    "TASBalancedPlacement",        # balanced placement for preferred gangs
    "TASMultiLayerTopology",       # inner slice layers
    "KueueDRAIntegration",         # device-class request mapping
    "KueueDRARejectWorkloadsWhenDRADisabled",  # reject vs ignore when off
    "WorkloadValidateResourcesAreNonNegative",  # webhook request check
    "DisableWaitForPodsReady",     # turn PodsReady gating off globally
    "ElasticJobsViaWorkloadSlices",  # workload-slice scale paths
})

_overrides: Dict[str, bool] = {}


def enabled(name: str) -> bool:
    if name in _overrides:
        return _overrides[name]
    return _DEFAULTS.get(name, False)


def set_enabled(name: str, value: bool) -> None:
    _overrides[name] = value


def reset() -> None:
    _overrides.clear()


def all_gates() -> Dict[str, bool]:
    out = dict(_DEFAULTS)
    out.update(_overrides)
    return out
