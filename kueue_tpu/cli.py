"""kueuectl-equivalent CLI.

Behavioral surface: reference cmd/kueuectl — create/list/delete/stop/resume
for ClusterQueues, LocalQueues and Workloads, pending-workload listing via
the visibility server, plus `schedule` (run cycles) and `import` (bulk
import). Operates on a manifest-defined in-process control plane:

    python -m kueue_tpu.cli --manifests cluster.yaml list clusterqueue
    python -m kueue_tpu.cli --manifests cluster.yaml schedule
    python -m kueue_tpu.cli --manifests cluster.yaml \
        list pendingworkloads --cluster-queue cq-a
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from kueue_tpu.api.constants import StopPolicy
from kueue_tpu.api.serialization import load_manifests
from kueue_tpu.api.types import ClusterQueue, LocalQueue, Workload
from kueue_tpu.core.workload_info import is_admitted
from kueue_tpu.manager import Manager
from kueue_tpu.visibility.server import VisibilityServer


def build_manager(manifest_paths: List[str]) -> Manager:
    mgr = Manager()
    for path in manifest_paths:
        for obj in load_manifests(path):
            if isinstance(obj, Workload):
                mgr.create_workload(obj)
            else:
                mgr.apply(obj)
    return mgr


def _print_table(rows: List[List[str]], headers: List[str]) -> None:
    widths = [
        max(len(str(r[i])) for r in [headers] + rows)
        for i in range(len(headers))
    ]
    for r in [headers] + rows:
        print("  ".join(str(v).ljust(w) for v, w in zip(r, widths)))


def cmd_list(mgr: Manager, args) -> int:
    kind = args.resource.lower()
    if kind in ("clusterqueue", "cq", "clusterqueues"):
        rows = []
        for name, cq in sorted(mgr.cache.cluster_queues.items()):
            pending = mgr.queues.pending_count(name)
            admitted = sum(
                1 for info in mgr.cache.workloads.values()
                if info.cluster_queue == name
            )
            rows.append([name, cq.cohort or "", cq.queueing_strategy.value,
                         pending, admitted, cq.stop_policy.value])
        _print_table(rows, ["NAME", "COHORT", "STRATEGY", "PENDING",
                            "ADMITTED", "STOP"])
    elif kind in ("localqueue", "lq", "localqueues"):
        rows = [
            [lq.namespace, lq.name, lq.cluster_queue]
            for lq in sorted(mgr.cache.local_queues.values(),
                             key=lambda q: q.key)
        ]
        _print_table(rows, ["NAMESPACE", "NAME", "CLUSTERQUEUE"])
    elif kind in ("workload", "workloads", "wl"):
        rows = []
        for key, wl in sorted(mgr.workloads.items()):
            status = "Admitted" if is_admitted(wl) else "Pending"
            cq = mgr.queues.cluster_queue_for(wl) or ""
            rows.append([wl.namespace, wl.name, wl.queue_name, cq,
                         wl.priority, status])
        _print_table(rows, ["NAMESPACE", "NAME", "QUEUE", "CLUSTERQUEUE",
                            "PRIORITY", "STATUS"])
    elif kind in ("pendingworkloads", "pending"):
        vis = VisibilityServer(mgr.queues)
        summary = vis.pending_workloads_cq(args.cluster_queue)
        rows = [
            [w.name, w.local_queue, w.priority,
             w.position_in_cluster_queue, w.position_in_local_queue]
            for w in summary.items
        ]
        _print_table(rows, ["NAME", "LOCALQUEUE", "PRIORITY", "POS(CQ)",
                            "POS(LQ)"])
        print(f"inadmissible: {summary.inadmissible}")
    elif kind in ("resourceflavor", "resourceflavors", "rf"):
        rows = [
            [rf.name, json.dumps(rf.node_labels), rf.topology_name or ""]
            for rf in sorted(mgr.cache.resource_flavors.values(),
                             key=lambda r: r.name)
        ]
        _print_table(rows, ["NAME", "NODELABELS", "TOPOLOGY"])
    else:
        print(f"unknown resource {args.resource}", file=sys.stderr)
        return 1
    return 0


def _set_stop_policy(mgr: Manager, args, policy: StopPolicy) -> int:
    kind = args.resource.lower()
    if kind in ("clusterqueue", "cq"):
        cq = mgr.cache.cluster_queues.get(args.name)
        if cq is None:
            print(f"ClusterQueue {args.name} not found", file=sys.stderr)
            return 1
        cq.stop_policy = policy
        mgr.apply(cq)
    elif kind in ("localqueue", "lq"):
        lq = mgr.cache.local_queues.get(f"default/{args.name}")
        if lq is None:
            print(f"LocalQueue {args.name} not found", file=sys.stderr)
            return 1
        lq.stop_policy = policy
    elif kind in ("workload", "wl"):
        wl = mgr.workloads.get(f"default/{args.name}")
        if wl is None:
            print(f"Workload {args.name} not found", file=sys.stderr)
            return 1
        wl.active = policy == StopPolicy.NONE
        mgr.tick()
    else:
        print(f"unknown resource {args.resource}", file=sys.stderr)
        return 1
    print(f"{args.resource}/{args.name} -> {policy.value}")
    return 0


def _parse_flavor_quotas(specs: List[str], field: str) -> dict:
    """Parse repeatable ``<flavor>:<res>=<qty>[,<res>=<qty>...]`` flags
    (reference kueuectl create clusterqueue --nominal-quota format,
    cmd/kueuectl/app/create/create_clusterqueue.go). Returns
    {flavor: {resource: int}}."""
    from kueue_tpu.api.serialization import parse_quantity

    out: dict = {}
    for spec in specs:
        flavor, sep, rest = spec.partition(":")
        if not sep or not flavor:
            raise ValueError(
                f"--{field} must look like flavor:res=qty[,res=qty]; "
                f"got {spec!r}"
            )
        cells = out.setdefault(flavor, {})
        for pair in rest.split(","):
            res, sep2, qty = pair.partition("=")
            if not sep2:
                raise ValueError(f"bad quantity {pair!r} in --{field}")
            cells[res.strip()] = parse_quantity(qty.strip(), res.strip())
    return out


def cmd_create(mgr: Manager, args) -> int:
    """kueuectl create clusterqueue/localqueue/resourceflavor
    (reference cmd/kueuectl/app/create/create.go)."""
    from kueue_tpu.api.constants import (
        PreemptionPolicy,
        QueueingStrategy,
    )
    from kueue_tpu.api.serialization import encode
    from kueue_tpu.api.types import (
        ClusterQueuePreemption,
        FlavorQuotas,
        ResourceFlavor,
        ResourceGroup,
        ResourceQuota,
        Taint,
    )
    import yaml

    kind = args.resource.lower()
    if kind in ("clusterqueue", "cq"):
        if args.name in mgr.cache.cluster_queues:
            print(f"ClusterQueue {args.name} already exists",
                  file=sys.stderr)
            return 1
        nominal = _parse_flavor_quotas(args.nominal_quota, "nominal-quota")
        borrow = _parse_flavor_quotas(
            args.borrowing_limit, "borrowing-limit"
        )
        lend = _parse_flavor_quotas(args.lending_limit, "lending-limit")
        if not nominal:
            print("--nominal-quota is required", file=sys.stderr)
            return 1
        for flag, cells_by_flavor in (("borrowing-limit", borrow),
                                      ("lending-limit", lend)):
            for fname, cells in cells_by_flavor.items():
                for res in cells:
                    if res not in nominal.get(fname, {}):
                        # A silently-dropped limit would mean UNBOUNDED
                        # borrowing — the opposite of what was asked.
                        print(
                            f"--{flag} {fname}:{res} has no matching "
                            "--nominal-quota entry",
                            file=sys.stderr,
                        )
                        return 1
        covered: List[str] = []
        flavors = []
        for fname, cells in nominal.items():
            for res in cells:
                if res not in covered:
                    covered.append(res)
            flavors.append(FlavorQuotas(
                name=fname,
                resources={
                    res: ResourceQuota(
                        nominal=qty,
                        borrowing_limit=borrow.get(fname, {}).get(res),
                        lending_limit=lend.get(fname, {}).get(res),
                    )
                    for res, qty in cells.items()
                },
            ))
        pol = {
            "Never": PreemptionPolicy.NEVER,
            "LowerPriority": PreemptionPolicy.LOWER_PRIORITY,
            "LowerOrNewerEqualPriority":
                PreemptionPolicy.LOWER_OR_NEWER_EQUAL_PRIORITY,
            "Any": PreemptionPolicy.ANY,
        }
        obj = ClusterQueue(
            name=args.name,
            cohort=args.cohort or None,
            resource_groups=[ResourceGroup(
                covered_resources=covered, flavors=flavors
            )],
            queueing_strategy=(
                QueueingStrategy.STRICT_FIFO
                if args.queuing_strategy == "StrictFIFO"
                else QueueingStrategy.BEST_EFFORT_FIFO
            ),
            preemption=ClusterQueuePreemption(
                reclaim_within_cohort=pol[args.reclaim_within_cohort],
                within_cluster_queue=pol[args.preemption_within_cq],
            ),
        )
    elif kind in ("localqueue", "lq"):
        key = f"{args.namespace}/{args.name}"
        if key in mgr.cache.local_queues:
            print(f"LocalQueue {key} already exists", file=sys.stderr)
            return 1
        if (args.clusterqueue not in mgr.cache.cluster_queues
                and not args.ignore_unknown_cq):
            print(
                f"ClusterQueue {args.clusterqueue} not found "
                "(use --ignore-unknown-cq to create anyway)",
                file=sys.stderr,
            )
            return 1
        obj = LocalQueue(
            name=args.name, namespace=args.namespace,
            cluster_queue=args.clusterqueue,
        )
    elif kind in ("resourceflavor", "rf"):
        if args.name in mgr.cache.resource_flavors:
            print(f"ResourceFlavor {args.name} already exists",
                  file=sys.stderr)
            return 1
        labels = {}
        for pair in (args.node_labels or "").split(","):
            if not pair:
                continue
            k, _, v = pair.partition("=")
            labels[k.strip()] = v.strip()
        taints = []
        for spec in args.node_taints or []:
            kv, _, effect = spec.partition(":")
            k, _, v = kv.partition("=")
            taints.append(Taint(key=k, value=v,
                                effect=effect or "NoSchedule"))
        obj = ResourceFlavor(
            name=args.name, node_labels=labels, node_taints=taints,
            topology_name=args.topology or None,
        )
    else:
        print(f"unknown resource {args.resource}", file=sys.stderr)
        return 1
    mgr.apply(obj)
    print(yaml.safe_dump(encode(obj), sort_keys=False), end="")
    _maybe_save(mgr, args)
    return 0


def cmd_delete(mgr: Manager, args) -> int:
    """kueuectl delete clusterqueue/localqueue/workload/resourceflavor."""
    kind = args.resource.lower()
    if kind in ("clusterqueue", "cq"):
        obj = mgr.cache.cluster_queues.get(args.name)
    elif kind in ("localqueue", "lq"):
        obj = mgr.cache.local_queues.get(f"{args.namespace}/{args.name}")
    elif kind in ("resourceflavor", "rf"):
        obj = mgr.cache.resource_flavors.get(args.name)
    elif kind in ("workload", "wl"):
        obj = mgr.workloads.get(f"{args.namespace}/{args.name}")
    else:
        print(f"unknown resource {args.resource}", file=sys.stderr)
        return 1
    if obj is None:
        print(f"{args.resource}/{args.name} not found", file=sys.stderr)
        return 1
    if isinstance(obj, Workload):
        mgr.delete_workload(obj)
    else:
        mgr.delete(obj)
    print(f"{args.resource}/{args.name} deleted")
    _maybe_save(mgr, args)
    return 0


def cmd_apply(mgr: Manager, args) -> int:
    """Manifest passthrough (the kubectl-delegation analog of reference
    kueuectl's passthrough verbs): apply every object in the file."""
    n = 0
    for obj in load_manifests(args.file):
        if isinstance(obj, Workload):
            mgr.create_workload(obj)
        else:
            mgr.apply(obj)
        n += 1
    print(f"applied {n} object(s)")
    _maybe_save(mgr, args)
    return 0


def _maybe_save(mgr: Manager, args) -> None:
    """Persist the control plane back to YAML (--save): the standalone
    analog of kueuectl's writes landing in the apiserver. Uses the full
    checkpoint serializer so nodes, limit ranges, admission checks and
    workloads survive the round trip."""
    path = getattr(args, "save", None)
    if not path:
        return
    state = mgr.export_state()
    with open(path, "w") as f:
        f.write(state)
    n = sum(1 for doc in state.split("\n---") if doc.strip())
    print(f"saved {n} object(s) to {path}")


def cmd_schedule(mgr: Manager, args) -> int:
    cycles = mgr.schedule_all(max_cycles=args.cycles)
    admitted = sum(
        1 for wl in mgr.workloads.values() if is_admitted(wl)
    )
    print(f"cycles={cycles} admitted={admitted} "
          f"total={len(mgr.workloads)}")
    return 0


def cmd_import(mgr: Manager, args) -> int:
    from kueue_tpu.importer import import_workloads

    report = import_workloads(mgr, args.file, check_only=args.check)
    print(json.dumps(report, indent=2))
    return 0


def _parse_requests(spec: str) -> dict:
    """``res=qty[,res=qty]`` -> canonical ints."""
    from kueue_tpu.api.serialization import parse_quantity

    out = {}
    for pair in spec.split(","):
        res, sep, qty = pair.partition("=")
        if not sep:
            raise ValueError(f"bad quantity {pair!r} in --requests")
        out[res.strip()] = parse_quantity(qty.strip(), res.strip())
    return out


def _parse_quota_delta(spec: str):
    """``node:flavor:res=+qty`` / ``...=-qty`` -> QuotaDelta. ``node``
    may name a ClusterQueue or a Cohort."""
    from kueue_tpu.api.serialization import parse_quantity
    from kueue_tpu.whatif import QuotaDelta

    head, sep, qty = spec.partition("=")
    parts = head.split(":")
    if not sep or len(parts) != 3 or not all(parts):
        raise ValueError(
            f"--quota-delta must look like node:flavor:res=+qty; "
            f"got {spec!r}"
        )
    qty = qty.strip()
    sign = -1 if qty.startswith("-") else 1
    mag = parse_quantity(qty.lstrip("+-"), parts[2])
    return QuotaDelta(
        node=parts[0], flavor=parts[1], resource=parts[2],
        delta=sign * mag,
    )


def cmd_whatif(mgr: Manager, args) -> int:
    """Counterfactual forecasts from the what-if engine (docs/whatif.md):
    admission ETAs, capacity probes, preemption previews."""
    from kueue_tpu.whatif import Scenario

    engine = mgr.whatif()
    if args.whatif_cmd == "eta":
        report = engine.eta(cluster_queue=args.cluster_queue or None)
        if args.json:
            print(json.dumps(report.to_dict(), indent=2))
            return 0
        base = report.base
        rows = [
            [w.key, w.cluster_queue, w.basis,
             "-" if w.eta_ms is None else w.eta_ms,
             w.flavor or "-",
             "-" if w.position is None else w.position]
            for w in base.workloads
        ]
        _print_table(rows, ["WORKLOAD", "CLUSTERQUEUE", "BASIS",
                            "ETA(MS)", "FLAVOR", "POS"])
        print(f"basis={report.basis} "
              f"admitted_within_horizon={base.admitted_within_horizon} "
              f"pending_after={base.pending_after}"
              + (f" fallback_reason={report.reason}" if report.reason
                 else ""))
        return 0
    if args.whatif_cmd == "capacity":
        scens = []
        for spec in args.quota_delta:
            scens.append(Scenario(
                kind="quota", label=spec,
                quota_deltas=(_parse_quota_delta(spec),),
            ))
        for node in args.drain_node:
            scens.append(Scenario(
                kind="drain", label=f"drain:{node}", drain_node=node,
            ))
        if not scens:
            print("capacity needs --quota-delta and/or --drain-node",
                  file=sys.stderr)
            return 1
        report = engine.eta(
            scenarios=scens, cluster_queue=args.cluster_queue or None
        )
        print(json.dumps(report.to_dict(), indent=2))
        return 0
    if args.whatif_cmd == "preview":
        from kueue_tpu.api.types import PodSet, Workload

        wl = Workload(
            name=args.name,
            namespace=args.namespace,
            queue_name=args.queue,
            priority=args.priority,
            pod_sets=[PodSet(
                name="main", count=args.count,
                requests=_parse_requests(args.requests),
            )],
        )
        report = engine.preview(
            wl, cluster_queue=args.cluster_queue or None
        )
        print(json.dumps(report.to_dict(), indent=2))
        return 0
    return 1


def cmd_explain(mgr: Manager, args) -> int:
    """Why is this workload (not) running? Joins live status with the
    flight recorder's provenance and the what-if forecast
    (docs/observability.md)."""
    name = args.name if "/" in args.name else \
        f"{args.namespace}/{args.name}"
    doc = mgr.explain(
        name,
        include_forecast=not args.no_forecast,
        include_preview=args.victims,
    )
    if args.json:
        print(json.dumps(doc, indent=2))
        return 0 if doc.get("found") else 1
    if not doc.get("found"):
        print(f"workload {doc['workload']} not found", file=sys.stderr)
        return 1
    print(f"Workload: {doc['workload']}")
    print(f"State: {doc['state']}"
          + (f" (queue position {doc['queuePosition']})"
             if "queuePosition" in doc else ""))
    print(f"ClusterQueue: {doc.get('clusterQueue')}"
          f"  LocalQueue: {doc.get('localQueue')}"
          f"  Priority: {doc.get('priority')}")
    for c in doc.get("conditions") or []:
        print(f"  condition {c['type']}={c['status']} "
              f"({c['reason']}) {c['message']}")
    if doc.get("lastEviction"):
        ev = doc["lastEviction"]
        print(f"Last eviction: {ev['reason']} — {ev['message']}")
    adm = doc.get("admission")
    if adm:
        for ps in adm["podSets"]:
            print(f"  podset {ps['name']} x{ps['count']} "
                  f"flavors={ps['flavors']}")
    attempts = doc.get("attempts")
    if attempts is None:
        print(f"Attempts: n/a ({doc.get('attemptsReason')})")
    else:
        print(f"Attempts ({len(attempts)} recorded):")
        for a in attempts:
            extra = ""
            if a.get("flavor"):
                extra += f" flavor={a['flavor']}"
            if a.get("victims"):
                extra += " victims=" + ",".join(
                    f"{k}({r})" for k, r in a["victims"]
                )
            if a.get("eviction_reason"):
                extra += f" reason={a['eviction_reason']}"
            print(f"  cycle {a['cycle']}: {a['outcome']} "
                  f"[{a['condition_reason']}] via {a['path']}{extra}")
    for ev in doc.get("evictions") or []:
        by = f" by {ev['preempted_by']}" if ev.get("preempted_by") else ""
        print(f"  evicted cycle {ev['cycle']}: "
              f"{ev.get('eviction_reason')}{by}")
    fc = doc.get("forecast")
    if fc is not None:
        eta = fc.get("etaMs")
        print(f"Forecast: eta_ms={'-' if eta is None else eta} "
              f"flavor={fc.get('flavor') or '-'} "
              f"basis={doc.get('forecastBasis')}")
    elif "forecastReason" in doc:
        print(f"Forecast: n/a ({doc['forecastReason']})")
    blockers = doc.get("blockingQuota")
    if blockers:
        for b in blockers:
            print(f"Blocking quota: {b['resource']} requested="
                  f"{b['requested']} best={b['bestFlavor']} "
                  f"available={b['available']}")
    if doc.get("preview") is not None:
        print("Preemption preview:")
        print(json.dumps(doc["preview"], indent=2))
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="kueuectl-tpu")
    ap.add_argument("--manifests", action="append", default=[],
                    help="YAML manifest file(s) defining the control plane")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p_list = sub.add_parser("list")
    p_list.add_argument("resource")
    p_list.add_argument("--cluster-queue", default="")

    p_create = sub.add_parser("create")
    p_create.add_argument("resource")
    p_create.add_argument("name")
    p_create.add_argument("--cohort", default="")
    p_create.add_argument("--queuing-strategy", default="BestEffortFIFO",
                          choices=["BestEffortFIFO", "StrictFIFO"])
    p_create.add_argument("--nominal-quota", action="append", default=[],
                          help="flavor:res=qty[,res=qty] (repeatable)")
    p_create.add_argument("--borrowing-limit", action="append", default=[])
    p_create.add_argument("--lending-limit", action="append", default=[])
    p_create.add_argument("--reclaim-within-cohort", default="Never")
    p_create.add_argument("--preemption-within-cq",
                          "--preemption-within-cluster-queue",
                          dest="preemption_within_cq", default="Never")
    p_create.add_argument("-c", "--clusterqueue", default="")
    p_create.add_argument("-i", "--ignore-unknown-cq", action="store_true")
    p_create.add_argument("--namespace", default="default")
    p_create.add_argument("--node-labels", default="")
    p_create.add_argument("--node-taints", action="append", default=[],
                          help="key=value:Effect (repeatable)")
    p_create.add_argument("--topology", default="")
    p_create.add_argument("--save", default=None,
                          help="write the control-plane spec back to YAML")

    p_del = sub.add_parser("delete")
    p_del.add_argument("resource")
    p_del.add_argument("name")
    p_del.add_argument("--namespace", default="default")
    p_del.add_argument("--save", default=None)

    p_apply = sub.add_parser("apply")
    p_apply.add_argument("file")
    p_apply.add_argument("--save", default=None)

    p_stop = sub.add_parser("stop")
    p_stop.add_argument("resource")
    p_stop.add_argument("name")

    p_resume = sub.add_parser("resume")
    p_resume.add_argument("resource")
    p_resume.add_argument("name")

    p_sched = sub.add_parser("schedule")
    p_sched.add_argument("--cycles", type=int, default=100000)

    p_imp = sub.add_parser("import")
    p_imp.add_argument("file")
    p_imp.add_argument("--check", action="store_true")

    sub.add_parser("dump")

    p_desc = sub.add_parser("describe")
    p_desc.add_argument("resource")
    p_desc.add_argument("name")

    p_perf = sub.add_parser("perf")
    p_perf.add_argument("generator")
    p_perf.add_argument("--rangespec", default=None)

    p_whatif = sub.add_parser(
        "whatif", help="counterfactual forecasts (docs/whatif.md)"
    )
    whatif_sub = p_whatif.add_subparsers(dest="whatif_cmd", required=True)
    w_eta = whatif_sub.add_parser("eta")
    w_eta.add_argument("--cluster-queue", default="")
    w_eta.add_argument("--json", action="store_true")
    w_cap = whatif_sub.add_parser("capacity")
    w_cap.add_argument("--quota-delta", action="append", default=[],
                       help="node:flavor:res=+qty (repeatable)")
    w_cap.add_argument("--drain-node", action="append", default=[])
    w_cap.add_argument("--cluster-queue", default="")
    w_prev = whatif_sub.add_parser("preview")
    w_prev.add_argument("name")
    w_prev.add_argument("--queue", default="")
    w_prev.add_argument("--cluster-queue", default="")
    w_prev.add_argument("--namespace", default="default")
    w_prev.add_argument("--priority", type=int, default=0)
    w_prev.add_argument("--count", type=int, default=1)
    w_prev.add_argument("--requests", default="cpu=1",
                        help="res=qty[,res=qty]")

    p_explain = sub.add_parser(
        "explain",
        help="admission provenance + forecast (docs/observability.md)",
    )
    p_explain.add_argument("name", help="workload name or ns/name key")
    p_explain.add_argument("--namespace", default="default")
    p_explain.add_argument("--json", action="store_true")
    p_explain.add_argument("--no-forecast", action="store_true",
                           help="skip the what-if admission forecast")
    p_explain.add_argument("--victims", action="store_true",
                           help="include the preemption preview")

    args = ap.parse_args(argv)
    mgr = build_manager(args.manifests)

    if args.cmd == "list":
        return cmd_list(mgr, args)
    if args.cmd == "create":
        try:
            return cmd_create(mgr, args)
        except ValueError as exc:
            print(str(exc), file=sys.stderr)
            return 1
    if args.cmd == "delete":
        try:
            return cmd_delete(mgr, args)
        except ValueError as exc:
            print(str(exc), file=sys.stderr)
            return 1
    if args.cmd == "apply":
        try:
            return cmd_apply(mgr, args)
        except ValueError as exc:
            # e.g. a Workload violating a namespace LimitRange, or a
            # duplicate create — clean stderr, not a traceback.
            print(str(exc), file=sys.stderr)
            return 1
    if args.cmd == "stop":
        return _set_stop_policy(mgr, args, StopPolicy.HOLD)
    if args.cmd == "resume":
        return _set_stop_policy(mgr, args, StopPolicy.NONE)
    if args.cmd == "schedule":
        return cmd_schedule(mgr, args)
    if args.cmd == "import":
        return cmd_import(mgr, args)
    if args.cmd == "whatif":
        try:
            return cmd_whatif(mgr, args)
        except ValueError as exc:
            print(str(exc), file=sys.stderr)
            return 1
    if args.cmd == "explain":
        return cmd_explain(mgr, args)
    if args.cmd == "describe":
        kind = args.resource.lower()
        if kind in ("workload", "wl"):
            wl = mgr.workloads.get(f"default/{args.name}")
            if wl is None:
                print("not found", file=sys.stderr)
                return 1
            print(f"Name: {wl.name}\nQueue: {wl.queue_name}"
                  f"\nPriority: {wl.priority}\nActive: {wl.active}")
            for c in wl.status.conditions:
                print(f"  condition {c.type}={c.status} ({c.reason})")
            if wl.status.admission:
                print(f"  admitted to {wl.status.admission.cluster_queue}")
                for psa in wl.status.admission.pod_set_assignments:
                    print(f"    podset {psa.name} x{psa.count} "
                          f"flavors={psa.flavors}")
        elif kind in ("clusterqueue", "cq"):
            from kueue_tpu.visibility.server import VisibilityServer

            cq = mgr.cache.cluster_queues.get(args.name)
            if cq is None:
                print("not found", file=sys.stderr)
                return 1
            print(f"Name: {cq.name}\nCohort: {cq.cohort}"
                  f"\nStrategy: {cq.queueing_strategy.value}")
            for rg in cq.resource_groups:
                for fq in rg.flavors:
                    for res, q in fq.resources.items():
                        print(f"  {fq.name}/{res}: nominal={q.nominal} "
                              f"borrow={q.borrowing_limit} "
                              f"lend={q.lending_limit}")
            vis = VisibilityServer(mgr.queues)
            print(f"Pending: {mgr.queues.pending_count(cq.name)}")
        else:
            print(f"unknown resource {args.resource}", file=sys.stderr)
            return 1
        return 0
    if args.cmd == "dump":
        from kueue_tpu.utils.debugger import dump

        dump(mgr, sys.stdout)
        return 0
    if args.cmd == "perf":
        from kueue_tpu.perf.harness import run_config_files

        result, violations = run_config_files(args.generator, args.rangespec)
        print(json.dumps({
            "virtual_wall_s": round(result.virtual_wall_s, 2),
            "scheduling_wall_s": round(result.scheduling_wall_s, 2),
            "admitted": result.admitted,
            "total": result.total_workloads,
            "cycles": result.cycles,
            "avg_time_to_admission_s": {
                k: round(v, 2)
                for k, v in result.avg_time_to_admission_s.items()
            },
            "cq_class_min_usage_pct": {
                k: round(v, 1)
                for k, v in result.cq_class_min_usage_pct.items()
            },
            "violations": violations,
        }, indent=2))
        return 0 if not violations else 1
    return 1


if __name__ == "__main__":
    raise SystemExit(main())
