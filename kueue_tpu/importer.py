"""Bulk importer.

Behavioral surface: reference cmd/importer — adopt pre-existing running
jobs into Workloads with admission already granted (check mode validates,
import mode applies), so a live fleet can be brought under kueue_tpu
management without restarting anything.
"""

from __future__ import annotations

from typing import Dict, List

from kueue_tpu.api.serialization import load_manifests
from kueue_tpu.api.types import Admission, PodSetAssignment, Workload
from kueue_tpu.core.workload_info import WorkloadInfo, set_condition
from kueue_tpu.api.constants import COND_ADMITTED, COND_QUOTA_RESERVED


def import_workloads(manager, manifest_path: str, check_only: bool = False) -> Dict:
    """Each Workload manifest is admitted in place against its LocalQueue's
    ClusterQueue using the first flavor that fits the declared requests
    (reference importer check/import modes)."""
    report = {"checked": 0, "imported": 0, "failed": []}
    objs = load_manifests(manifest_path)
    for obj in objs:
        if not isinstance(obj, Workload):
            continue
        report["checked"] += 1
        cq_name = manager.queues.cluster_queue_for(obj)
        if cq_name is None:
            report["failed"].append(
                {"workload": obj.key, "reason": "no LocalQueue route"}
            )
            continue
        cq = manager.cache.cluster_queues.get(cq_name)
        if cq is None:
            report["failed"].append(
                {"workload": obj.key, "reason": f"no ClusterQueue {cq_name}"}
            )
            continue
        assignments: List[PodSetAssignment] = []
        ok = True
        for ps in obj.pod_sets:
            flavors = {}
            for res in ps.requests:
                flist = cq.flavors_for(res)
                if not flist:
                    ok = False
                    report["failed"].append({
                        "workload": obj.key,
                        "reason": f"no flavor covers resource {res}",
                    })
                    break
                flavors[res] = flist[0]
            if not ok:
                break
            assignments.append(
                PodSetAssignment(
                    name=ps.name,
                    flavors=flavors,
                    resource_usage={
                        r: v * ps.count for r, v in ps.requests.items()
                    },
                    count=ps.count,
                )
            )
        if not ok:
            continue
        if check_only:
            continue
        now = manager.clock()
        obj.status.admission = Admission(
            cluster_queue=cq_name, pod_set_assignments=assignments
        )
        set_condition(obj, COND_QUOTA_RESERVED, True, "Imported",
                      "Imported with quota reservation", now)
        set_condition(obj, COND_ADMITTED, True, "Imported",
                      "Imported as admitted", now)
        manager.workloads[obj.key] = obj
        info = WorkloadInfo(obj, cq_name)
        info.sync_assignment_from_admission()
        manager.cache.add_or_update_workload(info)
        report["imported"] += 1
    return report
