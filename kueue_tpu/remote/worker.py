"""MultiKueue worker endpoint: a Manager served over a local socket.

One JSON object per request/response, newline-delimited, over a Unix
domain socket (or TCP for cross-host). Workloads cross the boundary as
manifest documents (api/serialization), never as Python objects — the
same serialized-snapshot seam a multi-host deployment would use over
gRPC/DCN.

Run standalone:
    python -m kueue_tpu.remote.worker --manifests cluster.yaml \
        --socket /tmp/worker.sock
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import socketserver
import threading
from typing import Optional

from kueue_tpu.api.serialization import decode, encode
from kueue_tpu.manager import Manager
from kueue_tpu.metrics import tracing
from kueue_tpu.utils import faults


class _Handler(socketserver.StreamRequestHandler):
    def handle(self) -> None:  # one connection, many requests
        mgr: Manager = self.server.manager  # type: ignore[attr-defined]
        lock: threading.Lock = self.server.lock  # type: ignore[attr-defined]
        for line in self.rfile:
            line = line.strip()
            if not line:
                continue
            try:
                req = json.loads(line)
                with lock:
                    resp = dispatch(mgr, req)
            except Exception as exc:  # noqa: BLE001 - wire errors back
                resp = {"ok": False, "error": repr(exc)[:500]}
            self.wfile.write(json.dumps(resp).encode() + b"\n")
            self.wfile.flush()


def dispatch(mgr: Manager, req: dict) -> dict:
    """Worker-side op dispatch, shared by every transport (socket JSON
    lines, gRPC) — the op surface IS the seam.

    Requests may carry a caller ``trace`` id; it is re-entered here so
    worker-side spans land in the same logical trace as the caller's."""
    caller_trace = req.pop("trace", None)
    if faults.ENABLED:
        # Slow-worker / failing-worker injection: a delay rule here
        # exercises the clients' op deadlines; a raise rule surfaces as an
        # error response (application failure at the client — it must NOT
        # trip the transport breaker).
        faults.fire(faults.REMOTE_DISPATCH)
    if not tracing.ENABLED:
        return _dispatch_impl(mgr, req)
    trace_id = caller_trace or tracing.current_trace_id()
    with tracing.trace_context(trace_id):
        with tracing.span("remote/dispatch", op=req.get("op")):
            resp = _dispatch_impl(mgr, req)
    # Trace fan-in: ship this trace's finished worker spans back in the
    # response (bounded, best-effort) so the client's Chrome export
    # renders one merged client+worker timeline. Collected AFTER the
    # dispatch span closed so the span covering this very call travels
    # too. Never fails the op.
    if caller_trace and isinstance(resp, dict):
        try:
            tracing.attach_remote_spans(resp, caller_trace)
        except Exception:  # noqa: BLE001 - observability must not break ops
            pass
    return resp


def _dispatch_impl(mgr: Manager, req: dict) -> dict:
    op = req.get("op")
    if op == "ping":
        return {"ok": True, "pong": True}
    if op == "create_workload":
        wl = decode(req["workload"])
        if wl.key in mgr.workloads:
            return {"ok": False, "error": "exists"}
        mgr.create_workload(wl)
        return {"ok": True}
    if op == "delete_workload":
        wl = mgr.workloads.get(req["key"])
        if wl is not None:
            mgr.delete_workload(wl)
        return {"ok": True}
    if op == "get_workload":
        wl = mgr.workloads.get(req["key"])
        return {"ok": True,
                "workload": encode(wl) if wl is not None else None}
    if op == "schedule":
        result = mgr.schedule_all()
        mgr.tick()
        return {"ok": True, "cycles": result}
    if op == "schedule_all":
        # One drive of the whole worker queue: the fleet applier's
        # per-lane batch replaces per-workload schedule round-trips.
        result = mgr.schedule_all()
        return {"ok": True, "cycles": result}
    if op == "capacity":
        # Flat capacity doc for the fleet encoder's lane planes.
        from kueue_tpu.fleet.encode import local_capacity

        return {"ok": True, "capacity": local_capacity(mgr)}
    if op == "finish_workload":
        wl = mgr.workloads.get(req["key"])
        if wl is not None:
            mgr.finish_workload(wl)
        return {"ok": True}
    return {"ok": False, "error": f"unknown op {op!r}"}


class _Server(socketserver.ThreadingUnixStreamServer):
    daemon_threads = True
    allow_reuse_address = True


def serve_worker(
    manager: Manager, socket_path: str, in_thread: bool = True
):
    """Serve ``manager`` on a unix socket. Returns the server (call
    ``shutdown()`` to stop) when ``in_thread``."""
    if os.path.exists(socket_path):
        os.unlink(socket_path)
    server = _Server(socket_path, _Handler)
    server.manager = manager  # type: ignore[attr-defined]
    server.lock = threading.Lock()  # type: ignore[attr-defined]
    if in_thread:
        t = threading.Thread(target=server.serve_forever, daemon=True)
        t.start()
        return server
    server.serve_forever()
    return server


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--manifests", required=False)
    ap.add_argument("--socket", required=True)
    args = ap.parse_args(argv)
    mgr = Manager()
    if args.manifests:
        from kueue_tpu.api.serialization import load_manifests

        for obj in load_manifests(open(args.manifests).read()):
            mgr.apply(obj)
    serve_worker(mgr, args.socket, in_thread=False)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
