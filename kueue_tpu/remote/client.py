"""Remote worker client: the MultiKueue-facing worker interface over the
socket protocol, with reconnect + backoff.

Implements exactly the surface MultiKueueController drives on a worker
(`workloads` lookup, create/delete, schedule) so an in-process Manager and
a remote cluster are interchangeable (reference remote_client.go keeps the
same shape behind a kubeconfig client; multikueuecluster.go owns the
reconnect loop)."""

from __future__ import annotations

import json
import socket
import time
from typing import Optional

from kueue_tpu.api.serialization import decode, encode
from kueue_tpu.api.types import Workload
from kueue_tpu.metrics import tracing
from kueue_tpu.utils import faults
from kueue_tpu.utils.breaker import CircuitBreaker


class WorkerUnreachable(ConnectionError):
    pass


class _WorkloadView:
    """Mapping-ish facade: each access is an RPC (the remote state IS the
    source of truth; nothing is cached across calls)."""

    def __init__(self, client: "RemoteWorkerClient") -> None:
        self._client = client

    def get(self, key: str) -> Optional[Workload]:
        doc = self._client._call({"op": "get_workload", "key": key}).get(
            "workload"
        )
        return decode(doc) if doc else None

    def __contains__(self, key: str) -> bool:
        return self.get(key) is not None

    def __getitem__(self, key: str) -> Workload:
        wl = self.get(key)
        if wl is None:
            raise KeyError(key)
        return wl


class RemoteWorkerClient:
    """A MultiKueue worker behind the socket seam."""

    def __init__(
        self,
        socket_path: str,
        connect_timeout: float = 2.0,
        retries: int = 2,
        backoff_s: float = 0.05,
        op_timeout: float = 30.0,
        breaker: Optional[CircuitBreaker] = None,
    ) -> None:
        self.socket_path = socket_path
        # connect_timeout bounds connection establishment; op_timeout is
        # the per-op deadline on the established socket — without it a
        # worker that accepts but never answers wedges the MultiKueue
        # dispatch loop forever.
        self.connect_timeout = connect_timeout
        self.op_timeout = max(op_timeout, connect_timeout)
        self.retries = retries
        self.backoff_s = backoff_s
        # Transport breaker: a worker that exhausted its retries trips
        # after `threshold` consecutive logical failures, and later calls
        # fast-fail WorkerUnreachable (which MultiKueueController already
        # treats as "skip this cluster") instead of re-paying the full
        # connect + retry + backoff latency per call.
        self.breaker = breaker or CircuitBreaker()
        self._sock: Optional[socket.socket] = None
        self._file = None
        self.workloads = _WorkloadView(self)

    # -- transport ---------------------------------------------------------

    def _connect(self) -> None:
        self.close()
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.settimeout(self.connect_timeout)
        s.connect(self.socket_path)
        s.settimeout(self.op_timeout)
        self._sock = s
        self._file = s.makefile("rwb")

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        self._sock = None
        self._file = None

    def _call(self, req: dict) -> dict:
        if not tracing.ENABLED:
            return self._call_impl(req)
        op = req.get("op")
        with tracing.span("remote/call", op=op, transport="socket"):
            t0 = time.perf_counter()
            try:
                resp = self._call_impl(req)
                tracing.inc("remote_calls_total",
                            {"op": op, "transport": "socket", "ok": "true"})
                return resp
            except Exception:
                tracing.inc("remote_calls_total",
                            {"op": op, "transport": "socket", "ok": "false"})
                raise
            finally:
                tracing.observe(
                    "remote_call_duration_seconds",
                    time.perf_counter() - t0,
                    {"op": op, "transport": "socket"},
                )

    def _call_impl(self, req: dict) -> dict:
        """One RPC with reconnect + backoff on transport failure
        (multikueuecluster.go reconnect loop)."""
        if tracing.ENABLED:
            req = dict(req,
                       trace=tracing.current_trace_id()
                       or tracing.new_trace_id())
        if not self.breaker.allow():
            raise WorkerUnreachable(
                f"worker at {self.socket_path} unreachable: breaker open "
                f"(retry in {self.breaker.last_backoff_s:.1f}s)"
            )
        last_exc: Optional[Exception] = None
        for attempt in range(self.retries + 1):
            try:
                if faults.ENABLED:
                    faults.fire(faults.REMOTE_TRANSPORT)
                if self._file is None:
                    self._connect()
                t_send = (
                    time.perf_counter() - tracing.get_tracer().epoch
                    if tracing.ENABLED else 0.0
                )
                self._file.write(json.dumps(req).encode() + b"\n")
                self._file.flush()
                line = self._file.readline()
                if not line:
                    raise ConnectionError("worker closed the connection")
                resp = json.loads(line)
                # A transport round-trip completed: the worker is healthy
                # even if the op itself errors (RuntimeError below is an
                # application failure, not a reachability one).
                self.breaker.record_success()
                if tracing.ENABLED and isinstance(resp, dict):
                    # Merge the worker's finished spans into this trace
                    # (best-effort; the response stays clean either way).
                    try:
                        tracing.ingest_remote_spans(
                            resp, worker=self.socket_path,
                            t_send=t_send,
                            t_recv=(time.perf_counter()
                                    - tracing.get_tracer().epoch),
                            trace_id=req.get("trace"),
                        )
                    except Exception:  # noqa: BLE001
                        pass
                if not resp.get("ok"):
                    raise RuntimeError(resp.get("error", "remote error"))
                return resp
            except socket.timeout as exc:
                last_exc = exc
                if tracing.ENABLED:
                    tracing.inc("remote_deadline_exceeded_total",
                                {"transport": "socket"})
                self.close()
                if attempt < self.retries:
                    time.sleep(self.backoff_s * (2 ** attempt))
            except (OSError, ConnectionError, json.JSONDecodeError) as exc:
                last_exc = exc
                self.close()
                if attempt < self.retries:
                    time.sleep(self.backoff_s * (2 ** attempt))
        self.breaker.record_failure()
        raise WorkerUnreachable(
            f"worker at {self.socket_path} unreachable: {last_exc!r}"
        )

    # -- worker interface --------------------------------------------------

    def ping(self) -> bool:
        try:
            return bool(self._call({"op": "ping"}).get("pong"))
        except WorkerUnreachable:
            return False

    def create_workload(self, wl: Workload) -> None:
        try:
            self._call({"op": "create_workload", "workload": encode(wl)})
        except RuntimeError as exc:
            if "exists" in str(exc):
                raise ValueError(str(exc)) from exc
            raise

    def delete_workload(self, wl: Workload) -> None:
        self._call({"op": "delete_workload", "key": wl.key})

    def schedule(self) -> None:
        self._call({"op": "schedule"})

    def schedule_all(self) -> None:
        self._call({"op": "schedule_all"})

    def capacity(self) -> dict:
        """Flat capacity doc for the fleet encoder (one RPC per lane
        per joint solve, vs one schedule round-trip per workload on the
        sequential path)."""
        return self._call({"op": "capacity"}).get("capacity") or {}

    def finish_workload(self, wl: Workload) -> None:
        self._call({"op": "finish_workload", "key": wl.key})
