"""Process-boundary transport for MultiKueue worker clusters.

The manager talks to worker clusters through a serialized-snapshot seam
(SURVEY §5/§7): workloads cross the boundary as manifest documents over a
length-delimited JSON protocol on a local socket — the idiomatic analog of
the reference's per-cluster kubeconfig clients with reconnect/watch
(pkg/controller/admissionchecks/multikueue/remote_client.go,
multikueuecluster.go).
"""

from kueue_tpu.remote.client import RemoteWorkerClient
from kueue_tpu.remote.worker import serve_worker


def __getattr__(name):
    # grpc transport imported lazily so environments without grpcio can
    # still use the socket seam.
    if name in ("GrpcWorkerClient", "serve_worker_grpc"):
        from kueue_tpu.remote import grpc_transport

        return getattr(grpc_transport, name)
    raise AttributeError(name)


__all__ = [
    "RemoteWorkerClient",
    "serve_worker",
    "GrpcWorkerClient",
    "serve_worker_grpc",
]
