"""gRPC transport for the MultiKueue worker seam — the DCN tier.

The unix-socket JSON protocol (remote/worker.py) is the local-process
boundary; this module carries the SAME op surface over gRPC/HTTP2 TCP,
which is how a real multi-cluster deployment crosses the data-center
network (reference pkg/controller/admissionchecks/multikueue talks to
worker clusters through kubeconfig REST clients; the seam here is the
serialized-manifest analog, SURVEY §5/§7).

No .proto codegen: requests/responses are JSON payloads over a generic
unary method (``/kueue.tpu.MultiKueueWorker/Call``). The wire contract is
the ``remote.worker.dispatch`` op table, so the socket worker, the gRPC
worker, and an in-process Manager remain interchangeable behind the
controller's worker interface.

Run standalone:
    python -m kueue_tpu.remote.grpc_transport --manifests cluster.yaml \
        --listen 127.0.0.1:50061
"""

from __future__ import annotations

import argparse
import json
import threading
import time
import uuid
from collections import deque
from concurrent import futures
from typing import Optional

import grpc

from kueue_tpu.api.serialization import decode, encode
from kueue_tpu.api.types import Workload
from kueue_tpu.manager import Manager
from kueue_tpu.metrics import tracing
from kueue_tpu.remote.client import WorkerUnreachable, _WorkloadView
from kueue_tpu.remote.worker import dispatch
from kueue_tpu.utils import faults
from kueue_tpu.utils.breaker import CircuitBreaker

_SERVICE = "kueue.tpu.MultiKueueWorker"
_METHOD = f"/{_SERVICE}/Call"


def _identity(b: bytes) -> bytes:
    return b


def serve_worker_grpc(
    manager: Manager, address: str = "127.0.0.1:0", in_thread: bool = True
):
    """Serve ``manager`` over gRPC. Returns (server, bound_address);
    call ``server.stop(0)`` to kill it.

    Requests carrying a client ``rid`` are deduplicated: a retry of an
    already-executed call (client deadline fired after the op applied)
    replays the recorded response instead of re-executing non-idempotent
    ops like ``schedule`` (double virtual-clock tick) or
    ``create_workload`` (spurious 'exists')."""
    lock = threading.Lock()
    seen: dict = {}
    seen_order: deque = deque()

    # Only mutating ops need replay protection; caching reads would churn
    # useful entries and pin response payloads for no benefit.
    _MUTATING = {"create_workload", "delete_workload", "schedule",
                 "finish_workload"}

    def call(request: bytes, context) -> bytes:
        rid = None
        try:
            req = json.loads(request)
            rid = req.pop("rid", None)
            if req.get("op") not in _MUTATING:
                rid = None
            with lock:
                if rid is not None and rid in seen:
                    return seen[rid]
                # The op may have mutated state even when it raises, so
                # the error response is recorded under the rid too —
                # otherwise a retry would re-execute the half-applied op.
                try:
                    resp = dispatch(manager, req)
                except Exception as exc:  # noqa: BLE001
                    resp = {"ok": False, "error": repr(exc)[:500]}
                out = json.dumps(resp).encode()
                if rid is not None:
                    seen[rid] = out
                    seen_order.append(rid)
                    while len(seen_order) > 1024:
                        seen.pop(seen_order.popleft(), None)
                return out
        except Exception as exc:  # noqa: BLE001 - wire errors back
            resp = {"ok": False, "error": repr(exc)[:500]}
        return json.dumps(resp).encode()

    handler = grpc.method_handlers_generic_handler(
        _SERVICE,
        {
            "Call": grpc.unary_unary_rpc_method_handler(
                call,
                request_deserializer=_identity,
                response_serializer=_identity,
            )
        },
    )
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
    server.add_generic_rpc_handlers((handler,))
    port = server.add_insecure_port(address)
    host = address.rsplit(":", 1)[0]
    bound = f"{host}:{port}"
    server.start()
    if not in_thread:
        server.wait_for_termination()
    return server, bound


class GrpcWorkerClient:
    """A MultiKueue worker behind the gRPC seam. Same surface as
    ``RemoteWorkerClient`` (workloads view, create/delete, schedule,
    finish, ping) with reconnect + backoff on transport failure."""

    def __init__(
        self,
        address: str,
        connect_timeout: float = 2.0,
        retries: int = 2,
        backoff_s: float = 0.05,
        op_timeout: float = 30.0,
        breaker: Optional[CircuitBreaker] = None,
    ) -> None:
        self.address = address
        # connect_timeout bounds cheap control ops (ping); op_timeout
        # bounds real work — a schedule cycle at DCN scale can legally
        # exceed 2 s, and timing it out mid-execution would leave the
        # retry racing an op that completes server-side.
        self.connect_timeout = connect_timeout
        self.op_timeout = max(op_timeout, connect_timeout)
        self.retries = retries
        self.backoff_s = backoff_s
        # Same transport breaker as RemoteWorkerClient: consecutive
        # retry-exhausted calls trip to fast-fail WorkerUnreachable, so an
        # unreachable worker degrades MultiKueue dispatch (cluster is
        # skipped) instead of stalling it for the full deadline per call.
        self.breaker = breaker or CircuitBreaker()
        self._channel: Optional[grpc.Channel] = None
        self._call_fn = None
        self.workloads = _WorkloadView(self)

    # -- transport ---------------------------------------------------------

    def _connect(self) -> None:
        self.close()
        self._channel = grpc.insecure_channel(self.address)
        self._call_fn = self._channel.unary_unary(
            _METHOD,
            request_serializer=_identity,
            response_deserializer=_identity,
        )

    def close(self) -> None:
        if self._channel is not None:
            try:
                self._channel.close()
            except Exception:  # noqa: BLE001
                pass
        self._channel = None
        self._call_fn = None

    def _call(self, req: dict, timeout: Optional[float] = None) -> dict:
        if not tracing.ENABLED:
            return self._call_impl(req, timeout)
        op = req.get("op")
        with tracing.span("remote/call", op=op, transport="grpc"):
            t0 = time.perf_counter()
            try:
                resp = self._call_impl(req, timeout)
                tracing.inc("remote_calls_total",
                            {"op": op, "transport": "grpc", "ok": "true"})
                return resp
            except Exception:
                tracing.inc("remote_calls_total",
                            {"op": op, "transport": "grpc", "ok": "false"})
                raise
            finally:
                tracing.observe(
                    "remote_call_duration_seconds",
                    time.perf_counter() - t0,
                    {"op": op, "transport": "grpc"},
                )

    def _call_impl(self, req: dict, timeout: Optional[float] = None) -> dict:
        # One request id across all attempts of this logical call: the
        # server dedupes replays, so retrying after an ambiguous failure
        # (deadline fired after the op applied) cannot re-execute it.
        req = dict(req, rid=uuid.uuid4().hex)
        if tracing.ENABLED:
            # Propagate the caller's trace id so worker-side spans join
            # this trace (mint one if the caller has no active trace).
            req["trace"] = tracing.current_trace_id() or tracing.new_trace_id()
        if not self.breaker.allow():
            raise WorkerUnreachable(
                f"worker at {self.address} unreachable: breaker open "
                f"(retry in {self.breaker.last_backoff_s:.1f}s)"
            )
        last_exc: Optional[Exception] = None
        for attempt in range(self.retries + 1):
            try:
                if faults.ENABLED:
                    faults.fire(faults.REMOTE_TRANSPORT)
                if self._call_fn is None:
                    self._connect()
                t_send = (
                    time.perf_counter() - tracing.get_tracer().epoch
                    if tracing.ENABLED else 0.0
                )
                raw = self._call_fn(
                    json.dumps(req).encode(),
                    timeout=timeout or self.op_timeout,
                )
                resp = json.loads(raw)
                # See RemoteWorkerClient: a completed round-trip is a
                # transport success even when the op itself failed.
                self.breaker.record_success()
                if tracing.ENABLED and isinstance(resp, dict):
                    # Merge the worker's finished spans into this trace
                    # (best-effort; the response stays clean either way).
                    try:
                        tracing.ingest_remote_spans(
                            resp, worker=self.address,
                            t_send=t_send,
                            t_recv=(time.perf_counter()
                                    - tracing.get_tracer().epoch),
                            trace_id=req.get("trace"),
                        )
                    except Exception:  # noqa: BLE001
                        pass
                if not resp.get("ok"):
                    raise RuntimeError(resp.get("error", "remote error"))
                return resp
            except (grpc.RpcError, ConnectionError,
                    json.JSONDecodeError) as exc:
                last_exc = exc
                if tracing.ENABLED and isinstance(exc, grpc.RpcError) \
                        and hasattr(exc, "code") \
                        and exc.code() == grpc.StatusCode.DEADLINE_EXCEEDED:
                    tracing.inc("remote_deadline_exceeded_total",
                                {"transport": "grpc"})
                self.close()
                # Retry connection-establishment failures; a DEADLINE or
                # INTERNAL mid-call is retried too, but the rid dedupe
                # makes the replay safe.
                if attempt < self.retries:
                    time.sleep(self.backoff_s * (2 ** attempt))
        self.breaker.record_failure()
        raise WorkerUnreachable(
            f"worker at {self.address} unreachable: {last_exc!r}"
        )

    # -- worker interface --------------------------------------------------

    def ping(self) -> bool:
        try:
            return bool(
                self._call({"op": "ping"}, timeout=self.connect_timeout)
                .get("pong")
            )
        except WorkerUnreachable:
            return False

    def create_workload(self, wl: Workload) -> None:
        try:
            self._call({"op": "create_workload", "workload": encode(wl)})
        except RuntimeError as exc:
            if "exists" in str(exc):
                raise ValueError(str(exc)) from exc
            raise

    def delete_workload(self, wl: Workload) -> None:
        self._call({"op": "delete_workload", "key": wl.key})

    def schedule(self) -> None:
        self._call({"op": "schedule"})

    def schedule_all(self) -> None:
        self._call({"op": "schedule_all"})

    def capacity(self) -> dict:
        """Flat capacity doc for the fleet encoder's lane planes."""
        return self._call({"op": "capacity"}).get("capacity") or {}

    def finish_workload(self, wl: Workload) -> None:
        self._call({"op": "finish_workload", "key": wl.key})


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--manifests", required=False)
    ap.add_argument("--listen", required=True)
    args = ap.parse_args(argv)
    mgr = Manager()
    if args.manifests:
        from kueue_tpu.api.serialization import load_manifests

        for obj in load_manifests(open(args.manifests).read()):
            mgr.apply(obj)
    server, bound = serve_worker_grpc(mgr, args.listen, in_thread=True)
    print(bound, flush=True)
    server.wait_for_termination()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
