"""YAML/dict (de)serialization for the API objects.

Manifest kinds mirror the reference CRDs (kind: ClusterQueue, LocalQueue,
ResourceFlavor, Cohort, Topology, AdmissionCheck, WorkloadPriorityClass,
Workload, Node) so users migrating from the reference can carry their specs
over with a mechanical field mapping.
"""

from __future__ import annotations

from typing import Any, Dict, List, Union

import yaml

from kueue_tpu.api.constants import (
    BorrowWithinCohortPolicy,
    FlavorFungibilityPolicy,
    FlavorFungibilityPreference,
    PreemptionPolicy,
    QueueingStrategy,
    StopPolicy,
)
from kueue_tpu.api.types import (
    AdmissionCheck,
    LabelSelector,
    Namespace,
    BorrowWithinCohort,
    ClusterQueue,
    ClusterQueuePreemption,
    Cohort,
    FairSharing,
    FlavorFungibility,
    FlavorQuotas,
    LocalQueue,
    MatchExpression,
    PodSet,
    ResourceFlavor,
    ResourceGroup,
    ResourceQuota,
    Taint,
    Toleration,
    Topology,
    TopologyRequest,
    Workload,
    WorkloadPriorityClass,
)
from kueue_tpu.tas.snapshot import Node


def parse_quantity(v: Union[int, float, str], resource: str = "") -> int:
    """Canonical integers matching the reference's int64 canonicalization
    (pkg/resources/amount.go AmountFromQuantity): cpu in milli-units,
    memory/storage in bytes, everything else in plain counts."""
    if isinstance(v, bool):
        raise ValueError("quantity cannot be bool")
    is_cpu = resource == "cpu"
    if isinstance(v, (int, float)):
        return int(v * 1000) if is_cpu else int(v)
    s = str(v).strip()
    suffixes = {
        "Ki": 1024, "Mi": 1024 ** 2, "Gi": 1024 ** 3, "Ti": 1024 ** 4,
        "k": 1000, "M": 10 ** 6, "G": 10 ** 9, "T": 10 ** 12,
    }
    if s.endswith("m"):
        return int(float(s[:-1]))
    for suf, mult in suffixes.items():
        if s.endswith(suf):
            return int(float(s[: -len(suf)]) * mult)
    return int(float(s) * 1000) if is_cpu else int(float(s))


def _quota(d: Dict[str, Any]) -> ResourceQuota:
    res = d.get("name", "")
    return ResourceQuota(
        nominal=parse_quantity(
            d.get("nominalQuota", d.get("nominal", 0)), res
        ),
        borrowing_limit=(
            parse_quantity(d["borrowingLimit"], res)
            if d.get("borrowingLimit") is not None else None
        ),
        lending_limit=(
            parse_quantity(d["lendingLimit"], res)
            if d.get("lendingLimit") is not None else None
        ),
    )


def _toleration(d) -> Toleration:
    return Toleration(
        key=d.get("key", ""), operator=d.get("operator", "Equal"),
        value=d.get("value", ""), effect=d.get("effect", ""),
    )


def _taint(d) -> Taint:
    return Taint(key=d["key"], value=d.get("value", ""),
                 effect=d.get("effect", "NoSchedule"))


def decode(doc: Dict[str, Any]):
    """Decode one manifest document by `kind`."""
    kind = doc.get("kind")
    meta = doc.get("metadata", {})
    spec = doc.get("spec", {})
    name = meta.get("name", doc.get("name"))
    if kind == "ResourceFlavor":
        return ResourceFlavor(
            name=name,
            node_labels=spec.get("nodeLabels", {}),
            node_taints=[_taint(t) for t in spec.get("nodeTaints", [])],
            tolerations=[_toleration(t) for t in spec.get("tolerations", [])],
            topology_name=spec.get("topologyName"),
        )
    if kind == "Topology":
        levels = spec.get("levels", [])
        keys = [
            lv["nodeLabel"] if isinstance(lv, dict) else lv for lv in levels
        ]
        return Topology(name=name, levels=keys)
    if kind == "Cohort":
        return Cohort(
            name=name,
            parent=spec.get("parentName", spec.get("parent")),
            quotas=[
                FlavorQuotas(
                    name=fq["name"],
                    resources={
                        r["name"]: _quota(r) for r in fq.get("resources", [])
                    },
                )
                for rg in spec.get("resourceGroups", [])
                for fq in rg.get("flavors", [])
            ],
            fair_sharing=_fair_sharing(spec),
        )
    if kind == "ClusterQueue":
        preemption = spec.get("preemption", {})
        bwc = preemption.get("borrowWithinCohort", {}) or {}
        fung = spec.get("flavorFungibility", {}) or {}
        return ClusterQueue(
            name=name,
            cohort=spec.get("cohortName", spec.get("cohort")),
            resource_groups=[
                ResourceGroup(
                    covered_resources=rg.get("coveredResources", []),
                    flavors=[
                        FlavorQuotas(
                            name=fq["name"],
                            resources={
                                r["name"]: _quota(r)
                                for r in fq.get("resources", [])
                            },
                        )
                        for fq in rg.get("flavors", [])
                    ],
                )
                for rg in spec.get("resourceGroups", [])
            ],
            queueing_strategy=QueueingStrategy(
                spec.get("queueingStrategy", "BestEffortFIFO")
            ),
            preemption=ClusterQueuePreemption(
                within_cluster_queue=PreemptionPolicy(
                    preemption.get("withinClusterQueue", "Never")
                ),
                reclaim_within_cohort=PreemptionPolicy(
                    preemption.get("reclaimWithinCohort", "Never")
                ),
                borrow_within_cohort=BorrowWithinCohort(
                    policy=BorrowWithinCohortPolicy(
                        bwc.get("policy", "Never")
                    ),
                    max_priority_threshold=bwc.get("maxPriorityThreshold"),
                ),
            ),
            flavor_fungibility=FlavorFungibility(
                when_can_borrow=FlavorFungibilityPolicy(
                    fung.get("whenCanBorrow", "Borrow")
                ),
                when_can_preempt=FlavorFungibilityPolicy(
                    fung.get("whenCanPreempt", "TryNextFlavor")
                ),
                preference=(
                    FlavorFungibilityPreference(fung["preference"])
                    if fung.get("preference") else None
                ),
            ),
            namespace_selector=_selector(spec.get("namespaceSelector")),
            stop_policy=StopPolicy(spec.get("stopPolicy", "None")),
            fair_sharing=_fair_sharing(spec),
            admission_checks=spec.get("admissionChecks", []),
        )
    if kind == "LocalQueue":
        return LocalQueue(
            name=name,
            namespace=meta.get("namespace", "default"),
            cluster_queue=spec.get("clusterQueue", ""),
            stop_policy=StopPolicy(spec.get("stopPolicy", "None")),
        )
    if kind == "AdmissionCheck":
        return AdmissionCheck(
            name=name,
            controller_name=spec.get("controllerName", ""),
            parameters=spec.get("parameters"),
        )
    if kind == "WorkloadPriorityClass":
        return WorkloadPriorityClass(name=name, value=doc.get("value", 0))
    if kind == "Node":
        return Node(
            name=name,
            labels=meta.get("labels", {}),
            capacity={
                r: parse_quantity(v, r)
                for r, v in (doc.get("status", {}).get("capacity")
                             or doc.get("capacity", {})).items()
            },
            taints=[_taint(t) for t in spec.get("taints", [])],
            ready=doc.get("ready", True),
        )
    if kind == "Namespace":
        return Namespace(name=name, labels=meta.get("labels", {}))
    if kind == "Workload":
        return Workload(
            name=name,
            namespace=meta.get("namespace", "default"),
            queue_name=spec.get("queueName", ""),
            priority=spec.get("priority", 0),
            priority_class=spec.get("priorityClassName"),
            active=spec.get("active", True),
            pod_sets=[_podset(ps) for ps in spec.get("podSets", [])],
        )
    raise ValueError(f"unknown kind: {kind}")


def _podset(d: Dict[str, Any]) -> PodSet:
    template = d.get("template", {}).get("spec", {})
    containers = template.get("containers", [])
    requests: Dict[str, int] = {}
    for c in containers:
        for r, v in (c.get("resources", {}).get("requests") or {}).items():
            requests[r] = requests.get(r, 0) + parse_quantity(v, r)
    requests.update({
        r: parse_quantity(v, r) for r, v in d.get("requests", {}).items()
    })
    tr = d.get("topologyRequest")
    topology_request = None
    if tr:
        topology_request = TopologyRequest(
            required_level=tr.get("required"),
            preferred_level=tr.get("preferred"),
            unconstrained=tr.get("unconstrained", False),
            podset_group_name=tr.get("podSetGroupName"),
            slice_required_level=tr.get("podSetSliceRequiredTopology"),
            slice_size=tr.get("podSetSliceSize"),
        )
    return PodSet(
        name=d.get("name", "main"),
        count=d.get("count", 1),
        requests=requests,
        min_count=d.get("minCount"),
        node_selector=template.get("nodeSelector", {}),
        tolerations=[_toleration(t) for t in template.get("tolerations", [])],
        topology_request=topology_request,
    )


def _selector(d):
    if d is None:
        return None
    if "matchLabels" in d or "matchExpressions" in d:
        return LabelSelector(
            match_labels=d.get("matchLabels", {}),
            match_expressions=[
                MatchExpression(
                    key=e["key"], operator=e["operator"],
                    values=tuple(e.get("values", [])),
                )
                for e in d.get("matchExpressions", [])
            ],
        )
    return d


def _fair_sharing(spec):
    fs = spec.get("fairSharing")
    if not fs:
        return None
    return FairSharing(weight=float(fs.get("weight", 1)))


def load_manifests(text_or_path: str) -> List[Any]:
    text = text_or_path
    if "\n" not in text_or_path:
        try:
            with open(text_or_path) as f:
                text = f.read()
        except OSError:
            pass
    return [decode(doc) for doc in yaml.safe_load_all(text) if doc]
