"""YAML/dict (de)serialization for the API objects.

Manifest kinds mirror the reference CRDs (kind: ClusterQueue, LocalQueue,
ResourceFlavor, Cohort, Topology, AdmissionCheck, WorkloadPriorityClass,
Workload, Node) so users migrating from the reference can carry their specs
over with a mechanical field mapping.
"""

from __future__ import annotations

from typing import Any, Dict, List, Union

import yaml

from kueue_tpu.api.constants import (
    BorrowWithinCohortPolicy,
    FlavorFungibilityPolicy,
    FlavorFungibilityPreference,
    PreemptionPolicy,
    QueueingStrategy,
    StopPolicy,
)
from kueue_tpu.api.types import (
    AdmissionCheck,
    LabelSelector,
    Namespace,
    BorrowWithinCohort,
    ClusterQueue,
    ClusterQueuePreemption,
    Cohort,
    FairSharing,
    FlavorFungibility,
    FlavorQuotas,
    LocalQueue,
    MatchExpression,
    PodSet,
    ResourceFlavor,
    ResourceGroup,
    ResourceQuota,
    Taint,
    Toleration,
    Topology,
    TopologyRequest,
    Workload,
    WorkloadPriorityClass,
)
from kueue_tpu.tas.snapshot import Node


def parse_quantity(v: Union[int, float, str], resource: str = "") -> int:
    """Canonical integers matching the reference's int64 canonicalization
    (pkg/resources/amount.go AmountFromQuantity): cpu in milli-units,
    memory/storage in bytes, everything else in plain counts."""
    if isinstance(v, bool):
        raise ValueError("quantity cannot be bool")
    is_cpu = resource == "cpu"
    if isinstance(v, (int, float)):
        return int(v * 1000) if is_cpu else int(v)
    s = str(v).strip()
    suffixes = {
        "Ki": 1024, "Mi": 1024 ** 2, "Gi": 1024 ** 3, "Ti": 1024 ** 4,
        "k": 1000, "M": 10 ** 6, "G": 10 ** 9, "T": 10 ** 12,
    }
    if s.endswith("m"):
        return int(float(s[:-1]))
    for suf, mult in suffixes.items():
        if s.endswith(suf):
            return int(float(s[: -len(suf)]) * mult)
    return int(float(s) * 1000) if is_cpu else int(float(s))


def _quota(d: Dict[str, Any]) -> ResourceQuota:
    res = d.get("name", "")
    return ResourceQuota(
        nominal=parse_quantity(
            d.get("nominalQuota", d.get("nominal", 0)), res
        ),
        borrowing_limit=(
            parse_quantity(d["borrowingLimit"], res)
            if d.get("borrowingLimit") is not None else None
        ),
        lending_limit=(
            parse_quantity(d["lendingLimit"], res)
            if d.get("lendingLimit") is not None else None
        ),
    )


def _toleration(d) -> Toleration:
    return Toleration(
        key=d.get("key", ""), operator=d.get("operator", "Equal"),
        value=d.get("value", ""), effect=d.get("effect", ""),
    )


def _taint(d) -> Taint:
    return Taint(key=d["key"], value=d.get("value", ""),
                 effect=d.get("effect", "NoSchedule"))


def decode(doc: Dict[str, Any]):
    """Decode one manifest document by `kind`."""
    kind = doc.get("kind")
    meta = doc.get("metadata", {})
    spec = doc.get("spec", {})
    name = meta.get("name", doc.get("name"))
    if kind == "ResourceFlavor":
        return ResourceFlavor(
            name=name,
            node_labels=spec.get("nodeLabels", {}),
            node_taints=[_taint(t) for t in spec.get("nodeTaints", [])],
            tolerations=[_toleration(t) for t in spec.get("tolerations", [])],
            topology_name=spec.get("topologyName"),
        )
    if kind == "Topology":
        levels = spec.get("levels", [])
        keys = [
            lv["nodeLabel"] if isinstance(lv, dict) else lv for lv in levels
        ]
        return Topology(name=name, levels=keys)
    if kind == "Cohort":
        return Cohort(
            name=name,
            parent=spec.get("parentName", spec.get("parent")),
            quotas=[
                FlavorQuotas(
                    name=fq["name"],
                    resources={
                        r["name"]: _quota(r) for r in fq.get("resources", [])
                    },
                )
                for rg in spec.get("resourceGroups", [])
                for fq in rg.get("flavors", [])
            ],
            fair_sharing=_fair_sharing(spec),
            labels=meta.get("labels", {}),
            annotations=meta.get("annotations", {}),
        )
    if kind == "ClusterQueue":
        preemption = spec.get("preemption", {})
        bwc = preemption.get("borrowWithinCohort", {}) or {}
        fung = spec.get("flavorFungibility", {}) or {}
        return ClusterQueue(
            name=name,
            cohort=spec.get("cohortName", spec.get("cohort")),
            resource_groups=[
                ResourceGroup(
                    covered_resources=rg.get("coveredResources", []),
                    flavors=[
                        FlavorQuotas(
                            name=fq["name"],
                            resources={
                                r["name"]: _quota(r)
                                for r in fq.get("resources", [])
                            },
                        )
                        for fq in rg.get("flavors", [])
                    ],
                )
                for rg in spec.get("resourceGroups", [])
            ],
            queueing_strategy=QueueingStrategy(
                spec.get("queueingStrategy", "BestEffortFIFO")
            ),
            preemption=ClusterQueuePreemption(
                within_cluster_queue=PreemptionPolicy(
                    preemption.get("withinClusterQueue", "Never")
                ),
                reclaim_within_cohort=PreemptionPolicy(
                    preemption.get("reclaimWithinCohort", "Never")
                ),
                borrow_within_cohort=BorrowWithinCohort(
                    policy=BorrowWithinCohortPolicy(
                        bwc.get("policy", "Never")
                    ),
                    max_priority_threshold=bwc.get("maxPriorityThreshold"),
                ),
            ),
            flavor_fungibility=FlavorFungibility(
                when_can_borrow=FlavorFungibilityPolicy(
                    fung.get("whenCanBorrow", "Borrow")
                ),
                when_can_preempt=FlavorFungibilityPolicy(
                    fung.get("whenCanPreempt", "TryNextFlavor")
                ),
                preference=(
                    FlavorFungibilityPreference(fung["preference"])
                    if fung.get("preference") else None
                ),
            ),
            namespace_selector=_selector(spec.get("namespaceSelector")),
            stop_policy=StopPolicy(spec.get("stopPolicy", "None")),
            fair_sharing=_fair_sharing(spec),
            admission_checks=spec.get("admissionChecks", []),
            labels=meta.get("labels", {}),
            annotations=meta.get("annotations", {}),
        )
    if kind == "LocalQueue":
        return LocalQueue(
            name=name,
            namespace=meta.get("namespace", "default"),
            cluster_queue=spec.get("clusterQueue", ""),
            stop_policy=StopPolicy(spec.get("stopPolicy", "None")),
            labels=meta.get("labels", {}),
        )
    if kind == "AdmissionCheck":
        return AdmissionCheck(
            name=name,
            controller_name=spec.get("controllerName", ""),
            parameters=spec.get("parameters"),
        )
    if kind == "WorkloadPriorityClass":
        return WorkloadPriorityClass(name=name, value=doc.get("value", 0))
    if kind == "Node":
        return Node(
            name=name,
            labels=meta.get("labels", {}),
            capacity={
                r: parse_quantity(v, r)
                for r, v in (doc.get("status", {}).get("capacity")
                             or doc.get("capacity", {})).items()
            },
            taints=[_taint(t) for t in spec.get("taints", [])],
            ready=doc.get("ready", True),
        )
    if kind == "Namespace":
        return Namespace(name=name, labels=meta.get("labels", {}))
    if kind == "LimitRange":
        from kueue_tpu.api.types import LimitRange, LimitRangeItem

        def _qmap(d):
            return {
                r: parse_quantity(v, r) for r, v in (d or {}).items()
            }

        return LimitRange(
            name=name,
            namespace=meta.get("namespace", "default"),
            items=[
                LimitRangeItem(
                    type=it.get("type", "Container"),
                    max=_qmap(it.get("max")),
                    min=_qmap(it.get("min")),
                    default=_qmap(it.get("default")),
                    default_request=_qmap(it.get("defaultRequest")),
                    max_limit_request_ratio={
                        # k8s Quantities; ratios may be fractional.
                        r: float(v) for r, v in
                        (it.get("maxLimitRequestRatio") or {}).items()
                    },
                )
                for it in spec.get("limits", [])
            ],
        )
    if kind == "RuntimeClass":
        from kueue_tpu.api.types import RuntimeClass

        pod_fixed = (doc.get("overhead") or {}).get("podFixed", {})
        return RuntimeClass(
            name=name,
            overhead={
                r: parse_quantity(v, r) for r, v in pod_fixed.items()
            },
        )
    if kind == "ResourceSlice":
        from kueue_tpu.dra import Device, ResourceSlice

        return ResourceSlice(
            name=name,
            driver=spec.get("driver", ""),
            pool=(spec.get("pool") or {}).get("name", spec.get("pool", ""))
            if isinstance(spec.get("pool"), dict) else spec.get("pool", ""),
            devices=[
                Device(
                    name=d.get("name", ""),
                    attributes=dict(d.get("attributes", {})),
                    capacity={
                        r: parse_quantity(v, r)
                        for r, v in d.get("capacity", {}).items()
                    },
                    counters=dict(d.get("counters", {})),
                )
                for d in spec.get("devices", [])
            ],
        )
    if kind == "Workload":
        wl = Workload(
            name=name,
            namespace=meta.get("namespace", "default"),
            queue_name=spec.get("queueName", ""),
            priority=spec.get("priority", 0),
            priority_class=spec.get("priorityClassName"),
            active=spec.get("active", True),
            pod_sets=[_podset(ps) for ps in spec.get("podSets", [])],
            labels=meta.get("labels", {}),
            annotations=meta.get("annotations", {}),
        )
        status = doc.get("status") or {}
        adm = status.get("admission")
        if adm:
            from kueue_tpu.api.types import (
                Admission,
                PodSetAssignment,
                TopologyAssignment,
            )

            psas = []
            for d in adm.get("podSetAssignments", []):
                ta = None
                if d.get("topologyAssignment"):
                    tad = d["topologyAssignment"]
                    domains = [
                        (tuple(e["values"]), e["count"])
                        for e in tad.get("domains", [])
                    ]
                    for grp in tad.get("slicedDomains", []):
                        domains.extend(
                            (tuple(vals), grp["count"])
                            for vals in grp.get("values", [])
                        )
                    ta = TopologyAssignment(
                        levels=list(tad.get("levels", [])),
                        domains=sorted(domains),
                    )
                by_name = {ps.name: ps for ps in wl.pod_sets}
                src = by_name.get(d.get("name"))
                psas.append(PodSetAssignment(
                    name=d.get("name", ""),
                    flavors=dict(d.get("flavors", {})),
                    resource_usage={
                        r: v * d.get("count", 1)
                        for r, v in (src.requests if src else {}).items()
                    },
                    count=d.get("count", 0),
                    topology_assignment=ta,
                    delayed_topology_request=bool(
                        d.get("delayedTopologyRequest", False)
                    ),
                ))
            wl.status.admission = Admission(
                cluster_queue=adm.get("clusterQueue", ""),
                pod_set_assignments=psas,
            )
        if status.get("conditions"):
            from kueue_tpu.core.workload_info import set_condition

            for c in status.get("conditions", []):
                # Reference-style manifests encode condition status as the
                # strings "True"/"False"; our own round-trips use bools.
                set_condition(wl, c["type"], c["status"] in (True, "True"),
                              c.get("reason", ""))
        if status.get("admissionChecks"):
            from kueue_tpu.api.constants import CheckState
            from kueue_tpu.api.types import AdmissionCheckState

            wl.status.admission_checks = [
                AdmissionCheckState(
                    name=acd["name"],
                    state=CheckState(acd.get("state", "Pending")),
                    message=acd.get("message", ""),
                )
                for acd in status["admissionChecks"]
            ]
        if status.get("requeueState"):
            from kueue_tpu.api.types import RequeueState

            rsd = status["requeueState"]
            wl.status.requeue_state = RequeueState(
                count=rsd.get("count", 0),
                requeue_at=rsd.get("requeueAt"),
            )
        if status.get("clusterName"):
            wl.status.cluster_name = status["clusterName"]
        return wl
    raise ValueError(f"unknown kind: {kind}")


def _container(c: Dict[str, Any]):
    from kueue_tpu.api.types import Container

    res = c.get("resources", {}) or {}
    return Container(
        name=c.get("name", ""),
        requests={
            r: parse_quantity(v, r)
            for r, v in (res.get("requests") or {}).items()
        },
        limits={
            r: parse_quantity(v, r)
            for r, v in (res.get("limits") or {}).items()
        },
        restart_policy=c.get("restartPolicy"),
    )


def _podset(d: Dict[str, Any]) -> PodSet:
    template = d.get("template", {}).get("spec", {})
    containers = [_container(c) for c in template.get("containers", [])]
    init_containers = [
        _container(c) for c in template.get("initContainers", [])
    ]
    overhead = {
        r: parse_quantity(v, r)
        for r, v in (template.get("overhead") or {}).items()
    }
    pod_res = template.get("resources") or {}
    pod_requests = {
        r: parse_quantity(v, r)
        for r, v in (pod_res.get("requests") or {}).items()
    }
    pod_limits = {
        r: parse_quantity(v, r)
        for r, v in (pod_res.get("limits") or {}).items()
    }
    requests: Dict[str, int] = {}
    if containers or init_containers:
        # Initial derivation without LimitRange context (the Manager
        # re-derives with namespace LimitRanges at workload creation):
        # k8s PodRequests semantics incl. the init-container max rule,
        # sidecars and overhead (utils/limitrange.pod_requests).
        from kueue_tpu.utils.limitrange import pod_requests as _pr

        requests = _pr(PodSet(
            name="", count=1, containers=containers,
            init_containers=init_containers, overhead=overhead,
            pod_requests=pod_requests, pod_limits=pod_limits,
        ))
    explicit = d.get("requests", {})
    requests.update({
        r: parse_quantity(v, r) for r, v in explicit.items()
    })
    tr = d.get("topologyRequest")
    topology_request = None
    if tr:
        topology_request = TopologyRequest(
            required_level=tr.get("required"),
            preferred_level=tr.get("preferred"),
            unconstrained=tr.get("unconstrained", False),
            balanced=tr.get("balanced", False),
            podset_group_name=tr.get("podSetGroupName"),
            slice_required_level=tr.get("podSetSliceRequiredTopology"),
            slice_size=tr.get("podSetSliceSize"),
            slice_layers=[
                (layer["topology"], layer["size"])
                for layer in tr.get("sliceLayers", [])
            ],
        )
    return PodSet(
        name=d.get("name", "main"),
        count=d.get("count", 1),
        requests=requests,
        device_requests={
            r: int(v) for r, v in d.get("deviceRequests", {}).items()
        },
        min_count=d.get("minCount"),
        node_selector=template.get("nodeSelector", {}),
        tolerations=[_toleration(t) for t in template.get("tolerations", [])],
        topology_request=topology_request,
        containers=containers,
        init_containers=init_containers,
        overhead=overhead,
        runtime_class_name=template.get("runtimeClassName"),
        pod_requests=pod_requests,
        pod_limits=pod_limits,
        requests_explicit=bool(explicit),
    )


def _selector(d):
    if d is None:
        return None
    if "matchLabels" in d or "matchExpressions" in d:
        return LabelSelector(
            match_labels=d.get("matchLabels", {}),
            match_expressions=[
                MatchExpression(
                    key=e["key"], operator=e["operator"],
                    values=tuple(e.get("values", [])),
                )
                for e in d.get("matchExpressions", [])
            ],
        )
    return d


def _fair_sharing(spec):
    fs = spec.get("fairSharing")
    if not fs:
        return None
    return FairSharing(weight=float(fs.get("weight", 1)))


def load_manifests(text_or_path: str) -> List[Any]:
    text = text_or_path
    if "\n" not in text_or_path:
        try:
            with open(text_or_path) as f:
                text = f.read()
        except OSError:
            pass
    return [decode(doc) for doc in yaml.safe_load_all(text) if doc]


# ---------------------------------------------------------------------------
# Encoding (state export / checkpoint)
# ---------------------------------------------------------------------------


def _encode_toleration(t) -> Dict[str, Any]:
    return {
        "key": t.key, "operator": t.operator,
        **({"value": t.value} if t.value else {}),
        **({"effect": t.effect} if t.effect else {}),
    }


def _encode_podset(ps) -> Dict[str, Any]:
    """Inverse of _podset: round-trips every field _podset reads (requests,
    deviceRequests, minCount, template.spec nodeSelector/tolerations,
    topologyRequest incl. slice layers)."""
    d: Dict[str, Any] = {
        "name": ps.name,
        "count": ps.count,
        "requests": {r: _emit_q(r, v) for r, v in ps.requests.items()},
    }
    if ps.device_requests:
        d["deviceRequests"] = dict(ps.device_requests)
    if ps.min_count is not None:
        d["minCount"] = ps.min_count
    template_spec: Dict[str, Any] = {}
    if ps.node_selector:
        template_spec["nodeSelector"] = dict(ps.node_selector)
    if ps.tolerations:
        template_spec["tolerations"] = [
            _encode_toleration(t) for t in ps.tolerations
        ]
    if template_spec:
        d["template"] = {"spec": template_spec}
    tr = ps.topology_request
    if tr is not None:
        trd: Dict[str, Any] = {}
        if tr.required_level is not None:
            trd["required"] = tr.required_level
        if tr.preferred_level is not None:
            trd["preferred"] = tr.preferred_level
        if tr.unconstrained:
            trd["unconstrained"] = True
        if tr.balanced:
            trd["balanced"] = True
        if tr.podset_group_name is not None:
            trd["podSetGroupName"] = tr.podset_group_name
        if tr.slice_required_level is not None:
            trd["podSetSliceRequiredTopology"] = tr.slice_required_level
        if tr.slice_size is not None:
            trd["podSetSliceSize"] = tr.slice_size
        if tr.slice_layers:
            trd["sliceLayers"] = [
                {"topology": lv, "size": sz} for lv, sz in tr.slice_layers
            ]
        d["topologyRequest"] = trd
    return d


def _encode_ta(ta) -> Dict[str, Any]:
    """TopologyAssignment encoding. Large assignments use the sliced form
    (reference workload_types.go:479-537 sliced encodings): domains grouped
    by identical per-domain count — e.g. 512 hosts x 4 pods each becomes
    one group instead of 512 entries."""
    if len(ta.domains) > 16:
        groups: Dict[int, list] = {}
        for v, c in ta.domains:
            groups.setdefault(c, []).append(list(v))
        return {
            "levels": list(ta.levels),
            "slicedDomains": [
                {"count": c, "values": vals}
                for c, vals in sorted(groups.items())
            ],
        }
    return {
        "levels": list(ta.levels),
        "domains": [
            {"values": list(v), "count": c} for v, c in ta.domains
        ],
    }


def _emit_q(res: str, v: int):
    """Emit a canonical integer so decode round-trips exactly: cpu is
    stored in milli-units, so it serializes with the "m" suffix."""
    return f"{v}m" if res == "cpu" else v


def _encode_quota(res: str, q: ResourceQuota) -> Dict[str, Any]:
    out = {"name": res, "nominalQuota": _emit_q(res, q.nominal)}
    if q.borrowing_limit is not None:
        out["borrowingLimit"] = _emit_q(res, q.borrowing_limit)
    if q.lending_limit is not None:
        out["lendingLimit"] = _emit_q(res, q.lending_limit)
    return out


def encode(obj) -> Dict[str, Any]:
    """Encode an API object back into its manifest form. Quantities are
    emitted as canonical integers (decode accepts them unchanged), so
    encode/decode round-trips exactly."""
    from kueue_tpu.tas.snapshot import Node as _Node

    if isinstance(obj, ResourceFlavor):
        return {
            "kind": "ResourceFlavor",
            "metadata": {"name": obj.name},
            "spec": {
                "nodeLabels": dict(obj.node_labels),
                "nodeTaints": [
                    {"key": t.key, "value": t.value, "effect": t.effect}
                    for t in obj.node_taints
                ],
                "tolerations": [
                    {"key": t.key, "operator": t.operator,
                     "value": t.value, "effect": t.effect}
                    for t in obj.tolerations
                ],
                **({"topologyName": obj.topology_name}
                   if obj.topology_name else {}),
            },
        }
    if isinstance(obj, Topology):
        return {
            "kind": "Topology",
            "metadata": {"name": obj.name},
            "spec": {"levels": [{"nodeLabel": lv} for lv in obj.levels]},
        }
    if isinstance(obj, Cohort):
        return {
            "kind": "Cohort",
            "metadata": {"name": obj.name},
            "spec": {
                **({"parentName": obj.parent} if obj.parent else {}),
                "resourceGroups": [{
                    "flavors": [{
                        "name": fq.name,
                        "resources": [
                            _encode_quota(r, q)
                            for r, q in fq.resources.items()
                        ],
                    } for fq in obj.quotas],
                }] if obj.quotas else [],
            },
        }
    if isinstance(obj, ClusterQueue):
        spec: Dict[str, Any] = {
            "queueingStrategy": obj.queueing_strategy.value,
            "resourceGroups": [{
                "coveredResources": list(rg.covered_resources),
                "flavors": [{
                    "name": fq.name,
                    "resources": [
                        _encode_quota(r, q) for r, q in fq.resources.items()
                    ],
                } for fq in rg.flavors],
            } for rg in obj.resource_groups],
            "preemption": {
                "withinClusterQueue":
                    obj.preemption.within_cluster_queue.value,
                "reclaimWithinCohort":
                    obj.preemption.reclaim_within_cohort.value,
                "borrowWithinCohort": {
                    "policy": obj.preemption.borrow_within_cohort.policy.value,
                    **({"maxPriorityThreshold":
                        obj.preemption.borrow_within_cohort
                        .max_priority_threshold}
                       if obj.preemption.borrow_within_cohort
                       .max_priority_threshold is not None else {}),
                },
            },
        }
        if obj.cohort:
            spec["cohortName"] = obj.cohort
        if obj.admission_checks:
            spec["admissionChecks"] = list(obj.admission_checks)
        if obj.stop_policy.value != "None":
            spec["stopPolicy"] = obj.stop_policy.value
        return {"kind": "ClusterQueue", "metadata": {"name": obj.name},
                "spec": spec}
    if isinstance(obj, LocalQueue):
        from kueue_tpu.api.constants import StopPolicy as _SP

        return {
            "kind": "LocalQueue",
            "metadata": {
                "name": obj.name,
                "namespace": obj.namespace,
                **({"labels": dict(obj.labels)} if obj.labels else {}),
            },
            "spec": {
                "clusterQueue": obj.cluster_queue,
                **({"stopPolicy": obj.stop_policy.value}
                   if obj.stop_policy != _SP.NONE else {}),
            },
        }
    if isinstance(obj, AdmissionCheck):
        return {
            "kind": "AdmissionCheck",
            "metadata": {"name": obj.name},
            "spec": {"controllerName": obj.controller_name},
        }
    if isinstance(obj, _Node):
        return {
            "kind": "Node",
            "metadata": {"name": obj.name, "labels": dict(obj.labels)},
            "capacity": {
                r: _emit_q(r, v) for r, v in obj.capacity.items()
            },
            "ready": obj.ready,
        }
    if isinstance(obj, Workload):
        doc: Dict[str, Any] = {
            "kind": "Workload",
            "metadata": {
                "name": obj.name,
                "namespace": obj.namespace,
                **({"labels": dict(obj.labels)} if obj.labels else {}),
                **({"annotations": dict(obj.annotations)}
                   if obj.annotations else {}),
            },
            "spec": {
                "queueName": obj.queue_name,
                "priority": obj.priority,
                "active": obj.active,
                "podSets": [_encode_podset(ps) for ps in obj.pod_sets],
            },
        }
        # Status export enables checkpoint/restore of admissions, pending
        # admission-check state machines, requeue backoff, and MultiKueue
        # placement.
        status: Dict[str, Any] = {}
        if obj.status.admission is not None:
            status["admission"] = {
                "clusterQueue": obj.status.admission.cluster_queue,
                "podSetAssignments": [{
                    "name": psa.name,
                    "flavors": dict(psa.flavors),
                    "count": psa.count,
                    **({"topologyAssignment": _encode_ta(
                        psa.topology_assignment
                    )} if psa.topology_assignment else {}),
                    **({"delayedTopologyRequest": True}
                       if psa.delayed_topology_request else {}),
                } for psa in obj.status.admission.pod_set_assignments],
            }
        if obj.status.conditions:
            status["conditions"] = [
                {"type": c.type, "status": c.status, "reason": c.reason}
                for c in obj.status.conditions
            ]
        if obj.status.admission_checks:
            status["admissionChecks"] = [{
                "name": acs.name,
                "state": acs.state.value,
                **({"message": acs.message} if acs.message else {}),
            } for acs in obj.status.admission_checks]
        if obj.status.requeue_state is not None:
            rs = obj.status.requeue_state
            status["requeueState"] = {
                "count": rs.count,
                **({"requeueAt": rs.requeue_at}
                   if rs.requeue_at is not None else {}),
            }
        if obj.status.cluster_name:
            status["clusterName"] = obj.status.cluster_name
        if status:
            doc["status"] = status
        return doc
    if type(obj).__name__ == "LimitRange":
        return {
            "kind": "LimitRange",
            "metadata": {"name": obj.name, "namespace": obj.namespace},
            "spec": {"limits": [{
                "type": it.type,
                **({"max": dict(it.max)} if it.max else {}),
                **({"min": dict(it.min)} if it.min else {}),
                **({"default": dict(it.default)} if it.default else {}),
                **({"defaultRequest": dict(it.default_request)}
                   if it.default_request else {}),
                **({"maxLimitRequestRatio":
                    dict(it.max_limit_request_ratio)}
                   if it.max_limit_request_ratio else {}),
            } for it in obj.items]},
        }
    if type(obj).__name__ == "RuntimeClass":
        return {
            "kind": "RuntimeClass",
            "metadata": {"name": obj.name},
            "overhead": {"podFixed": dict(obj.overhead)},
        }
    raise TypeError(f"cannot encode {type(obj)!r}")
