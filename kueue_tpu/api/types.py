"""Core API object model: Workload, ClusterQueue, Cohort, LocalQueue,
ResourceFlavor, AdmissionCheck, Topology, WorkloadPriorityClass.

These are idiomatic Python dataclasses carrying the behaviorally relevant
fields of the reference CRDs (reference: apis/kueue/v1beta2/). They are the
host-side object model; the scheduler hot loop operates on dense tensor
encodings derived from them (kueue_tpu/ops, kueue_tpu/models).

Resource quantities are canonical integers: milliCPU for "cpu", bytes for
"memory", plain counts otherwise — matching the reference's int64
canonicalization (reference pkg/resources/amount.go AmountFromQuantity).
"""

from __future__ import annotations

import dataclasses
import itertools
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from kueue_tpu.api.constants import (
    AdmissionScope,
    BorrowWithinCohortPolicy,
    CheckState,
    FlavorFungibilityPolicy,
    FlavorFungibilityPreference,
    PreemptionPolicy,
    QueueingStrategy,
    StopPolicy,
)
from kueue_tpu.core.resources import UNLIMITED

_uid_counter = itertools.count(1)


def _new_uid() -> str:
    return f"uid-{next(_uid_counter)}"


# --------------------------------------------------------------------------
# Shared scheduling primitives
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class Toleration:
    """Subset of corev1.Toleration the admission path evaluates."""

    key: str = ""
    operator: str = "Equal"  # Equal | Exists
    value: str = ""
    effect: str = ""  # "" tolerates all effects

    def tolerates(self, taint: "Taint") -> bool:
        if self.effect and self.effect != taint.effect:
            return False
        if self.operator == "Exists":
            return self.key == "" or self.key == taint.key
        return self.key == taint.key and self.value == taint.value


@dataclass(frozen=True)
class Taint:
    key: str
    value: str = ""
    effect: str = "NoSchedule"  # NoSchedule | NoExecute | PreferNoSchedule


@dataclass(frozen=True)
class MatchExpression:
    """Node-affinity requirement (corev1.NodeSelectorRequirement subset)."""

    key: str
    operator: str  # In | NotIn | Exists | DoesNotExist
    values: Tuple[str, ...] = ()

    def matches(self, labels: Dict[str, str]) -> bool:
        present = self.key in labels
        if self.operator == "Exists":
            return present
        if self.operator == "DoesNotExist":
            return not present
        if self.operator == "In":
            return present and labels[self.key] in self.values
        if self.operator == "NotIn":
            return not present or labels[self.key] not in self.values
        raise ValueError(f"unknown operator {self.operator}")


# --------------------------------------------------------------------------
# ResourceFlavor / Topology
# --------------------------------------------------------------------------


@dataclass
class ResourceFlavor:
    """Hardware variant (reference resourceflavor_types.go:31-121)."""

    name: str
    node_labels: Dict[str, str] = field(default_factory=dict)
    node_taints: List[Taint] = field(default_factory=list)
    tolerations: List[Toleration] = field(default_factory=list)
    topology_name: Optional[str] = None


@dataclass
class Topology:
    """Ordered node-label levels defining the datacenter tree
    (reference topology_types.go:108-162). For TPU fleets the levels map onto
    ICI domains: e.g. ("pod", "superpod", "host")."""

    name: str
    levels: List[str] = field(default_factory=list)  # ordered, top first


# --------------------------------------------------------------------------
# ClusterQueue / Cohort / LocalQueue
# --------------------------------------------------------------------------


@dataclass
class ResourceQuota:
    """Per (flavor, resource) quota cell (reference clusterqueue_types.go:300).

    ``borrowing_limit``/``lending_limit`` of None mean unlimited borrowing /
    everything lendable, as in the reference (nil pointers)."""

    nominal: int = 0
    borrowing_limit: Optional[int] = None
    lending_limit: Optional[int] = None


@dataclass
class FlavorQuotas:
    name: str  # ResourceFlavor reference
    resources: Dict[str, ResourceQuota] = field(default_factory=dict)


@dataclass
class ResourceGroup:
    """Flavors ordered by preference covering a set of resources
    (reference clusterqueue_types.go:255)."""

    covered_resources: List[str] = field(default_factory=list)
    flavors: List[FlavorQuotas] = field(default_factory=list)


@dataclass
class FlavorFungibility:
    """reference clusterqueue_types.go:456."""

    when_can_borrow: FlavorFungibilityPolicy = FlavorFungibilityPolicy.BORROW
    when_can_preempt: FlavorFungibilityPolicy = (
        FlavorFungibilityPolicy.TRY_NEXT_FLAVOR
    )
    preference: Optional[FlavorFungibilityPreference] = None


@dataclass
class BorrowWithinCohort:
    policy: BorrowWithinCohortPolicy = BorrowWithinCohortPolicy.NEVER
    max_priority_threshold: Optional[int] = None


@dataclass
class ClusterQueuePreemption:
    """reference clusterqueue_types.go:517."""

    within_cluster_queue: PreemptionPolicy = PreemptionPolicy.NEVER
    reclaim_within_cohort: PreemptionPolicy = PreemptionPolicy.NEVER
    borrow_within_cohort: BorrowWithinCohort = field(
        default_factory=BorrowWithinCohort
    )


@dataclass
class FairSharing:
    """Weight used by DRF ordering (reference fairsharing_types.go:25-39).

    Weight is a non-negative float; 0 means "borrow last, preempt first"."""

    weight: float = 1.0


@dataclass
class ClusterQueue:
    """Quota pool + admission policies (reference clusterqueue_types.go:67)."""

    name: str
    cohort: Optional[str] = None
    resource_groups: List[ResourceGroup] = field(default_factory=list)
    queueing_strategy: QueueingStrategy = QueueingStrategy.BEST_EFFORT_FIFO
    preemption: ClusterQueuePreemption = field(
        default_factory=ClusterQueuePreemption
    )
    flavor_fungibility: FlavorFungibility = field(default_factory=FlavorFungibility)
    # None selects all; a dict is treated as matchLabels; a LabelSelector
    # supports matchExpressions too.
    namespace_selector: Optional[object] = None
    stop_policy: StopPolicy = StopPolicy.NONE
    fair_sharing: Optional[FairSharing] = None
    admission_checks: List[str] = field(default_factory=list)
    admission_scope: Optional[AdmissionScope] = None
    # ConcurrentAdmission (reference clusterqueue_types.go:204): when
    # "Enabled", workloads race one variant per candidate flavor.
    concurrent_admission_policy: Optional[str] = None
    # Object metadata (custom metric label sources, KEP 7066).
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)

    def flavors_for(self, resource: str) -> List[str]:
        for rg in self.resource_groups:
            if resource in rg.covered_resources:
                return [f.name for f in rg.flavors]
        return []


@dataclass
class Cohort:
    """Node in the borrowing hierarchy (reference cohort_types.go:24-72)."""

    name: str
    parent: Optional[str] = None
    quotas: List[FlavorQuotas] = field(default_factory=list)
    fair_sharing: Optional[FairSharing] = None
    # Object metadata (custom metric label sources, KEP 7066).
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)


@dataclass
class Namespace:
    """Namespace with labels, for ClusterQueue namespaceSelector
    evaluation (reference uses corev1.Namespace labels)."""

    name: str
    labels: Dict[str, str] = field(default_factory=dict)


@dataclass
class LabelSelector:
    """metav1.LabelSelector subset: matchLabels AND matchExpressions."""

    match_labels: Dict[str, str] = field(default_factory=dict)
    match_expressions: List[MatchExpression] = field(default_factory=list)

    def matches(self, labels: Dict[str, str]) -> bool:
        for k, v in self.match_labels.items():
            if labels.get(k) != v:
                return False
        return all(e.matches(labels) for e in self.match_expressions)


@dataclass
class LocalQueue:
    """Namespaced tenant queue -> ClusterQueue
    (reference localqueue_types.go:33)."""

    name: str
    namespace: str = "default"
    cluster_queue: str = ""
    stop_policy: StopPolicy = StopPolicy.NONE
    fair_sharing: Optional[FairSharing] = None
    labels: Dict[str, str] = field(default_factory=dict)

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


# --------------------------------------------------------------------------
# Workload
# --------------------------------------------------------------------------


@dataclass
class TopologyRequest:
    """Per-podset topology constraint (reference workload_types.go podset
    topology request): admit only if the gang fits under one domain at
    ``level`` (required) or prefer to (preferred)."""

    required_level: Optional[str] = None
    preferred_level: Optional[str] = None
    unconstrained: bool = False
    # Balanced placement (reference TASBalancedPlacement): spread slices
    # evenly over the minimal domain set instead of packing best-fit.
    balanced: bool = False
    podset_group_name: Optional[str] = None
    # Gang subdivided into slices pinned under a topology level
    # (reference workload_types.go:252 PodsetSliceRequiredTopologyConstraint).
    slice_required_level: Optional[str] = None
    slice_size: Optional[int] = None
    # Additional inner slice layers (reference TASMultiLayerTopology):
    # [(level, size), ...] strictly deeper than the outer layer; each size
    # must divide the previous layer's size.
    slice_layers: List[Tuple[str, int]] = field(default_factory=list)


@dataclass
class Container:
    """Container resource spec for pod-spec-level request derivation
    (corev1.Container subset; reference pkg/workload/resources.go applies
    LimitRange defaults and limits-as-missing-requests to these before
    totaling)."""

    name: str = ""
    requests: Dict[str, int] = field(default_factory=dict)
    limits: Dict[str, int] = field(default_factory=dict)
    # "Always" on an init container marks a sidecar (restartable): its
    # requests add to the running base instead of the init peak.
    restart_policy: Optional[str] = None


@dataclass
class LimitRangeItem:
    """One constraint row of a LimitRange (corev1.LimitRangeItem)."""

    type: str = "Container"  # "Container" | "Pod"
    max: Dict[str, int] = field(default_factory=dict)
    min: Dict[str, int] = field(default_factory=dict)
    default: Dict[str, int] = field(default_factory=dict)  # limits default
    default_request: Dict[str, int] = field(default_factory=dict)
    # Quantity ratios (may be fractional, e.g. "1.5").
    max_limit_request_ratio: Dict[str, float] = field(default_factory=dict)


@dataclass
class LimitRange:
    """Namespace resource bounds/defaults (corev1.LimitRange; consumed by
    the request-derivation pipeline, reference pkg/util/limitrange)."""

    name: str
    namespace: str = "default"
    items: List[LimitRangeItem] = field(default_factory=list)

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass
class RuntimeClass:
    """nodev1.RuntimeClass subset: pod overhead source (reference
    pkg/workload/resources.go handlePodOverhead)."""

    name: str
    overhead: Dict[str, int] = field(default_factory=dict)


@dataclass
class PodSet:
    """Homogeneous group of pods (reference workload_types.go:556)."""

    name: str
    count: int
    requests: Dict[str, int] = field(default_factory=dict)  # per-pod
    # DRA: per-pod device requests by DeviceClass name (reference
    # ResourceClaim device requests); translated into ``requests`` via the
    # configured deviceClassMappings at workload creation.
    device_requests: Dict[str, int] = field(default_factory=dict)
    min_count: Optional[int] = None  # enables partial admission
    node_selector: Dict[str, str] = field(default_factory=dict)
    required_affinity: List[MatchExpression] = field(default_factory=list)
    tolerations: List[Toleration] = field(default_factory=list)
    topology_request: Optional[TopologyRequest] = None
    # Optional pod-spec level (reference PodSpec subset): when containers
    # are present, ``requests`` is DERIVED at workload creation — the
    # init-container max rule, sidecar accumulation, pod overhead and
    # LimitRange defaulting (utils/limitrange.py; reference
    # pkg/workload/resources.go AdjustResources + k8s PodRequests).
    containers: List[Container] = field(default_factory=list)
    init_containers: List[Container] = field(default_factory=list)
    overhead: Dict[str, int] = field(default_factory=dict)
    runtime_class_name: Optional[str] = None
    # Pod-level resources (KEP-2837): override totals for named resources.
    pod_requests: Dict[str, int] = field(default_factory=dict)
    pod_limits: Dict[str, int] = field(default_factory=dict)
    # True when the manifest stated ``requests`` directly (the abstract
    # shorthand): derivation must not overwrite the user's numbers.
    requests_explicit: bool = False


@dataclass
class PodSetAssignment:
    """Result of admission for one podset (reference workload_types.go:289)."""

    name: str
    flavors: Dict[str, str] = field(default_factory=dict)  # resource -> flavor
    resource_usage: Dict[str, int] = field(default_factory=dict)  # totals
    count: int = 0
    topology_assignment: Optional["TopologyAssignment"] = None
    # Placement deferred to the target cluster (reference
    # workload_types.go delayedTopologyRequest; the MultiKueue+TAS path).
    delayed_topology_request: bool = False


@dataclass
class TopologyAssignment:
    """Domains assigned to a podset (reference workload_types.go:457)."""

    levels: List[str] = field(default_factory=list)
    # list of (level-values tuple, pod count)
    domains: List[Tuple[Tuple[str, ...], int]] = field(default_factory=list)


@dataclass
class Admission:
    """reference workload_types.go:267."""

    cluster_queue: str = ""
    pod_set_assignments: List[PodSetAssignment] = field(default_factory=list)


@dataclass
class Condition:
    type: str
    status: bool
    reason: str = ""
    message: str = ""
    last_transition_time: float = 0.0


@dataclass
class AdmissionCheckState:
    name: str
    state: CheckState = CheckState.PENDING
    message: str = ""
    last_transition_time: float = 0.0


@dataclass
class RequeueState:
    """Eviction backoff bookkeeping (reference workload_types.go:774)."""

    count: int = 0
    requeue_at: Optional[float] = None


@dataclass
class WorkloadStatus:
    conditions: List[Condition] = field(default_factory=list)
    admission: Optional[Admission] = None
    admission_checks: List[AdmissionCheckState] = field(default_factory=list)
    requeue_state: Optional[RequeueState] = None
    reclaimable_pods: Dict[str, int] = field(default_factory=dict)
    cluster_name: Optional[str] = None  # MultiKueue placement
    unhealthy_nodes: List[str] = field(default_factory=list)


@dataclass
class Workload:
    """The unit of admission (reference workload_types.go:28)."""

    name: str
    namespace: str = "default"
    queue_name: str = ""  # LocalQueue name
    # Open preemption gates hold this workload's preemptions until removed
    # (reference workload_types.go:604 PreemptionGate; used by concurrent
    # admission and MultiKueue orchestrated preemption).
    preemption_gates: List[str] = field(default_factory=list)
    pod_sets: List[PodSet] = field(default_factory=list)
    priority: int = 0
    priority_class: Optional[str] = None
    active: bool = True
    creation_time: float = 0.0
    uid: str = field(default_factory=_new_uid)
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    maximum_execution_time_seconds: Optional[int] = None
    status: WorkloadStatus = field(default_factory=WorkloadStatus)

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def clone(self) -> "Workload":
        return dataclasses.replace(
            self,
            pod_sets=[dataclasses.replace(ps) for ps in self.pod_sets],
            status=dataclasses.replace(
                self.status,
                conditions=list(self.status.conditions),
                admission_checks=list(self.status.admission_checks),
            ),
        )


# --------------------------------------------------------------------------
# AdmissionCheck / WorkloadPriorityClass
# --------------------------------------------------------------------------


@dataclass
class AdmissionCheck:
    """Two-phase admission plugin registration
    (reference admissioncheck_types.go:23-134)."""

    name: str
    controller_name: str = ""
    parameters: Optional[Dict[str, str]] = None
    active: bool = True


@dataclass
class WorkloadPriorityClass:
    """Priority decoupled from pod priority
    (reference workloadpriorityclass_types.go)."""

    name: str
    value: int = 0


def quota(
    nominal: int,
    borrowing_limit: Optional[int] = None,
    lending_limit: Optional[int] = None,
) -> ResourceQuota:
    """Convenience constructor used heavily by tests."""
    return ResourceQuota(nominal, borrowing_limit, lending_limit)


__all__ = [
    "Admission",
    "AdmissionCheck",
    "AdmissionCheckState",
    "BorrowWithinCohort",
    "ClusterQueue",
    "ClusterQueuePreemption",
    "Cohort",
    "Condition",
    "FairSharing",
    "FlavorFungibility",
    "FlavorQuotas",
    "LocalQueue",
    "MatchExpression",
    "PodSet",
    "PodSetAssignment",
    "RequeueState",
    "ResourceFlavor",
    "ResourceGroup",
    "ResourceQuota",
    "Taint",
    "Toleration",
    "Topology",
    "TopologyAssignment",
    "TopologyRequest",
    "Workload",
    "WorkloadPriorityClass",
    "WorkloadStatus",
    "quota",
    "UNLIMITED",
]
