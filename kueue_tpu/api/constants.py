"""API enums and condition constants.

Mirrors the behavioral surface of the reference API types
(reference: apis/kueue/v1beta2/*_types.go). String values follow the
reference so that serialized state is recognizable to users migrating over.
"""

from __future__ import annotations

import enum


class QueueingStrategy(str, enum.Enum):
    """reference clusterqueue_types.go:190."""

    STRICT_FIFO = "StrictFIFO"
    BEST_EFFORT_FIFO = "BestEffortFIFO"


class PreemptionPolicy(str, enum.Enum):
    """withinClusterQueue / reclaimWithinCohort policies
    (reference clusterqueue_types.go:517)."""

    NEVER = "Never"
    LOWER_PRIORITY = "LowerPriority"
    LOWER_OR_NEWER_EQUAL_PRIORITY = "LowerOrNewerEqualPriority"
    ANY = "Any"


class BorrowWithinCohortPolicy(str, enum.Enum):
    """reference clusterqueue_types.go:573."""

    NEVER = "Never"
    LOWER_PRIORITY = "LowerPriority"


class FlavorFungibilityPolicy(str, enum.Enum):
    """whenCanBorrow / whenCanPreempt (reference clusterqueue_types.go:456)."""

    BORROW = "Borrow"
    PREEMPT = "Preempt"
    TRY_NEXT_FLAVOR = "TryNextFlavor"


class FlavorFungibilityPreference(str, enum.Enum):
    """reference clusterqueue_types.go:446."""

    BORROWING_OVER_PREEMPTION = "BorrowingOverPreemption"
    PREEMPTION_OVER_BORROWING = "PreemptionOverBorrowing"


class StopPolicy(str, enum.Enum):
    NONE = "None"
    HOLD = "Hold"
    HOLD_AND_DRAIN = "HoldAndDrain"


class AdmissionScope(str, enum.Enum):
    """reference fairsharing_types.go:55."""

    USAGE_BASED_FAIR_SHARING = "UsageBasedAdmissionFairSharing"
    NO_FAIR_SHARING = "NoAdmissionFairSharing"


# ---- Workload condition types (reference workload_types.go:929-1069) ----

COND_QUOTA_RESERVED = "QuotaReserved"
COND_ADMITTED = "Admitted"
COND_PODS_READY = "PodsReady"
COND_EVICTED = "Evicted"
COND_PREEMPTED = "Preempted"
COND_REQUEUED = "Requeued"
COND_FINISHED = "Finished"
COND_DEACTIVATION_TARGET = "DeactivationTarget"

# ---- Eviction / preemption reasons ----

EVICTED_BY_PREEMPTION = "Preempted"
EVICTED_BY_PODS_READY_TIMEOUT = "PodsReadyTimeout"
EVICTED_BY_ADMISSION_CHECK = "AdmissionCheck"
EVICTED_BY_CLUSTER_QUEUE_STOPPED = "ClusterQueueStopped"
EVICTED_BY_LOCAL_QUEUE_STOPPED = "LocalQueueStopped"
EVICTED_BY_DEACTIVATION = "Deactivated"
EVICTED_BY_NODE_FAILURE = "NodeFailures"

IN_CLUSTER_QUEUE_REASON = "InClusterQueue"
IN_COHORT_RECLAMATION_REASON = "InCohortReclamation"
IN_COHORT_FAIR_SHARING_REASON = "InCohortFairSharing"
IN_COHORT_RECLAIM_WHILE_BORROWING_REASON = "InCohortReclaimWhileBorrowing"

# ---- QuotaReserved "pending" reasons (subset used by the scheduler) ----

REASON_WAITING_FOR_QUOTA = "WaitingForQuota"
REASON_EXCEEDS_MAX_QUOTA = "ExceedsMaxQuota"
REASON_NO_MATCHING_FLAVOR = "NoMatchingFlavor"
REASON_WAITING_FOR_PREEMPTED = "WaitingForPreemptedWorkloads"
REASON_PENDING = "Pending"

# ---- AdmissionCheck states (reference workload_types.go:796) ----


class CheckState(str, enum.Enum):
    PENDING = "Pending"
    READY = "Ready"
    RETRY = "Retry"
    REJECTED = "Rejected"


class RequeueReason(str, enum.Enum):
    """Why a workload went back to the queues
    (reference pkg/cache/queue requeue reasons)."""

    GENERIC = "Generic"
    FAILED_AFTER_NOMINATION = "FailedAfterNomination"
    NO_FIT = "NoFit"
    PREEMPTION_NO_CANDIDATES = "PreemptionNoCandidates"
    NAMESPACE_MISMATCH = "NamespaceMismatch"
    # The entry issued preemptions and waits for its victims' capacity
    # (reference RequeueReasonPendingPreemption): requeued immediately and,
    # under BestEffortFIFO, pinned to the head (stickyWorkload) so other
    # entries cannot steal the freed capacity.
    PENDING_PREEMPTION = "PendingPreemption"
