"""Component configuration.

Behavioral surface: reference apis/config/v1beta2/configuration_types.go +
pkg/config/{config,validation}.go — the single Configuration object with
defaulting, validation, and feature-gate overrides, loadable from YAML.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

import yaml

from kueue_tpu.controllers.workload_controller import (
    RetentionConfig,
    WaitForPodsReadyConfig,
)
from kueue_tpu.utils import features


@dataclass
class FairSharingConfig:
    """reference configuration_types.go:739."""

    enable: bool = False
    preemption_strategies: List[str] = field(
        default_factory=lambda: [
            "LessThanOrEqualToFinalShare", "LessThanInitialShare",
        ]
    )


@dataclass
class MultiKueueSettings:
    """reference configuration_types.go:331."""

    gc_interval_seconds: float = 60.0
    origin: str = "multikueue"
    worker_lost_timeout_seconds: float = 900.0
    dispatcher_name: str = "AllAtOnce"  # or "Incremental" | "Fleet"
    # Joint fleet placement knobs (kueue_tpu/fleet; used when
    # dispatcher_name == "Fleet").
    fleet_device: bool = True
    fleet_preemption: bool = False
    fleet_spread_weight: int = 1
    fleet_preempt_penalty: int = 64
    fleet_affinity_penalty: int = 8
    fleet_dispatch_costs: Dict[str, int] = field(default_factory=dict)


@dataclass
class ResourceTransformation:
    """reference configuration_types.go:612: map an input resource into
    scheduling resources (e.g. tpu-v5e-pod -> tpu chips)."""

    input: str
    strategy: str = "Retain"  # Retain | Replace
    outputs: Dict[str, int] = field(default_factory=dict)  # per input unit


@dataclass
class DeviceClassMapping:
    """DRA seam (reference configuration_types.go:634 DeviceClassMapping):
    ``name`` is the logical resource referenced by ClusterQueue quotas;
    ``device_class_names`` are the DRA device classes it covers. Pod-set
    ``device_requests`` naming one of those classes are counted against
    ``name`` at workload creation. ``sources`` switches from whole-device
    counting to ResourceSlice-derived counter/capacity charges
    (kueue_tpu.dra)."""

    name: str
    device_class_names: List[str] = field(default_factory=list)
    sources: List[object] = field(default_factory=list)


def _parse_dra_sources(raw: List[dict]) -> List[object]:
    """Parse DeviceClassMapping sources (counter / capacity)."""
    from kueue_tpu.dra import CapacitySource, CounterSource

    out: List[object] = []
    for s in raw:
        if "counter" in s:
            c = s["counter"]
            out.append(CounterSource(
                driver=c.get("driver", ""), name=c.get("name", ""),
                selector=dict(c.get("selector", {})),
            ))
        if "capacity" in s:
            c = s["capacity"]
            out.append(CapacitySource(
                driver=c.get("driver", ""),
                resource_name=c.get("resourceName",
                                    c.get("resource_name", "")),
                selector=dict(c.get("selector", {})),
            ))
    return out


@dataclass
class ResourcesConfig:
    """reference configuration_types.go:589."""

    exclude_resource_prefixes: List[str] = field(default_factory=list)
    transformations: List[ResourceTransformation] = field(
        default_factory=list
    )
    device_class_mappings: List[DeviceClassMapping] = field(
        default_factory=list
    )


@dataclass
class Configuration:
    """reference configuration_types.go:35."""

    namespace: str = "kueue-system"
    manage_jobs_without_queue_name: bool = False
    wait_for_pods_ready: WaitForPodsReadyConfig = field(
        default_factory=WaitForPodsReadyConfig
    )
    integrations: List[str] = field(
        default_factory=lambda: ["batch/job", "trainjob", "leaderworkerset",
                                 "mpijob", "raycluster", "pod", "serving"]
    )
    fair_sharing: FairSharingConfig = field(default_factory=FairSharingConfig)
    multi_kueue: MultiKueueSettings = field(default_factory=MultiKueueSettings)
    resources: ResourcesConfig = field(default_factory=ResourcesConfig)
    admission_fair_sharing: Optional[object] = None  # AdmissionFairSharingConfig
    feature_gates: Dict[str, bool] = field(default_factory=dict)
    object_retention_after_finished_seconds: Optional[float] = None
    object_retention_after_deactivated_seconds: Optional[float] = None
    visibility_enabled: bool = True
    use_device_scheduler: bool = False
    # Device admission kernel: "scan" (grouped sequential scan, the
    # conservative default), "fixedpoint" (monotone-bounds rounds wherever
    # exact, host otherwise), "auto" (widest exact kernel per cycle,
    # including the fixed-point + residual-scan preemption hybrid). See
    # docs/perf.md "Fixed-point coverage matrix".
    device_kernel: str = "scan"
    # Which kernel "auto" may pick when the backend is CPU: "scan" (the
    # grouped scan — fixed-point's vectorized rounds are slower than the
    # scan under JAX CPU emulation unless the residual-scan bound is
    # large) or "fixedpoint" (force the accelerator preference anyway).
    # See docs/perf.md "Pipelined cycle" / the scanfloor ledger note.
    auto_cpu_kernel: str = "scan"
    # Pipelined admission cycles: "off" (serialized snapshot -> encode ->
    # dispatch -> apply), "on" (always speculate the next cycle's encode
    # inside the device-dispatch window; requires the arena), "auto"
    # (enabled when driven by the streaming service loop, off for
    # call-per-cycle use). See docs/perf.md "Pipelined cycle".
    pipeline_cycles: str = "auto"
    # Tiled streaming admission: "auto" (stream past-the-flagship cycles
    # through the device in bounded W-tiles; smaller cycles keep the
    # monolithic dispatch), "off" (never tile), or a positive int tile
    # width (tile whenever the head count exceeds it). See docs/perf.md
    # "Scaling beyond 50k".
    tile_width: object = "auto"
    # KEP 7066 custom metric labels: entries of
    # {name, sourceKind: Workload|ClusterQueue|Cohort, sourceLabelKey,
    # sourceAnnotationKey}; values are read from the source object's
    # labels/annotations and appended to that kind's metric series.
    metrics_custom_labels: List[Dict[str, str]] = field(
        default_factory=list
    )


def _pick(d: dict, *names, default=None):
    for n in names:
        if n in d:
            return d[n]
    return default


def load(source) -> Configuration:
    """Load + default + validate a Configuration from a YAML string, file
    path, or dict (reference pkg/config/config.go:219)."""
    if isinstance(source, dict):
        raw = source
    else:
        text = source
        if "\n" not in str(source):
            try:
                with open(source) as f:
                    text = f.read()
            except (OSError, TypeError):
                pass
        raw = yaml.safe_load(text) or {}

    cfg = Configuration()
    cfg.namespace = _pick(raw, "namespace", default=cfg.namespace)
    metrics_raw = _pick(raw, "metrics", default={}) or {}
    for entry in _pick(metrics_raw, "customLabels", "custom_labels",
                       default=[]) or []:
        cfg.metrics_custom_labels.append({
            "name": entry.get("name", ""),
            "source_kind": _pick(entry, "sourceKind", "source_kind",
                                 default="Workload"),
            "source_label_key": _pick(
                entry, "sourceLabelKey", "source_label_key", default=""
            ),
            "source_annotation_key": _pick(
                entry, "sourceAnnotationKey", "source_annotation_key",
                default=""
            ),
        })
    cfg.manage_jobs_without_queue_name = _pick(
        raw, "manageJobsWithoutQueueName", "manage_jobs_without_queue_name",
        default=False,
    )
    wfpr = _pick(raw, "waitForPodsReady", "wait_for_pods_ready", default={})
    if wfpr:
        rq = _pick(wfpr, "requeuingStrategy", "requeuing_strategy",
                   default={}) or {}
        cfg.wait_for_pods_ready = WaitForPodsReadyConfig(
            enable=wfpr.get("enable", False),
            timeout_seconds=_duration(_pick(wfpr, "timeout", default="5m")),
            block_admission=_pick(wfpr, "blockAdmission", "block_admission",
                                  default=False),
            requeuing_backoff_base_seconds=float(
                _pick(rq, "backoffBaseSeconds", default=60)
            ),
            requeuing_backoff_limit_count=_pick(
                rq, "backoffLimitCount", default=None
            ),
            requeuing_backoff_max_seconds=float(
                _pick(rq, "backoffMaxSeconds", default=3600)
            ),
        )
    if "integrations" in raw:
        frameworks = _pick(raw["integrations"] or {}, "frameworks",
                           default=None)
        if frameworks is not None:
            cfg.integrations = list(frameworks)
    fs = _pick(raw, "fairSharing", "fair_sharing", default={}) or {}
    cfg.fair_sharing = FairSharingConfig(
        enable=fs.get("enable", False),
        preemption_strategies=fs.get(
            "preemptionStrategies",
            ["LessThanOrEqualToFinalShare", "LessThanInitialShare"],
        ),
    )
    mk = _pick(raw, "multiKueue", "multi_kueue", default={}) or {}
    cfg.multi_kueue = MultiKueueSettings(
        gc_interval_seconds=_duration(_pick(mk, "gcInterval", default="1m")),
        origin=mk.get("origin", "multikueue"),
        worker_lost_timeout_seconds=_duration(
            _pick(mk, "workerLostTimeout", default="15m")
        ),
        dispatcher_name=mk.get("dispatcherName", "AllAtOnce"),
        fleet_device=bool(_pick(mk, "fleetDevice", "fleet_device",
                                default=True)),
        fleet_preemption=bool(_pick(mk, "fleetPreemption",
                                    "fleet_preemption", default=False)),
        fleet_spread_weight=int(_pick(mk, "fleetSpreadWeight",
                                      "fleet_spread_weight", default=1)),
        fleet_preempt_penalty=int(_pick(
            mk, "fleetPreemptPenalty", "fleet_preempt_penalty", default=64
        )),
        fleet_affinity_penalty=int(_pick(
            mk, "fleetAffinityPenalty", "fleet_affinity_penalty", default=8
        )),
        fleet_dispatch_costs={
            str(k): int(v)
            for k, v in (_pick(mk, "fleetDispatchCosts",
                               "fleet_dispatch_costs", default={})
                         or {}).items()
        },
    )
    res = _pick(raw, "resources", default={}) or {}
    cfg.resources = ResourcesConfig(
        exclude_resource_prefixes=res.get("excludeResourcePrefixes", []),
        transformations=[
            ResourceTransformation(
                input=t["input"],
                strategy=t.get("strategy", "Retain"),
                outputs=t.get("outputs", {}),
            )
            for t in res.get("transformations", [])
        ],
        device_class_mappings=[
            DeviceClassMapping(
                name=m["name"],
                device_class_names=list(
                    m.get("deviceClassNames", m.get("device_class_names", []))
                ),
                sources=_parse_dra_sources(m.get("sources", [])),
            )
            for m in res.get("deviceClassMappings",
                             res.get("device_class_mappings", []))
        ],
    )
    afs = _pick(raw, "admissionFairSharing", default=None)
    if afs:
        from kueue_tpu.queue.afs import AdmissionFairSharingConfig

        cfg.admission_fair_sharing = AdmissionFairSharingConfig(
            usage_half_life_s=_duration(
                afs.get("usageHalfLifeTime", "10m")
            ),
            usage_sampling_interval_s=_duration(
                afs.get("usageSamplingInterval", "5m")
            ),
            resource_weights={
                k: float(v)
                for k, v in (afs.get("resourceWeights") or {}).items()
            },
        )
    cfg.feature_gates = dict(_pick(raw, "featureGates", "feature_gates",
                                   default={}) or {})
    orp = _pick(raw, "objectRetentionPolicies", default={}) or {}
    wl_ret = (orp.get("workloads") or {})
    if wl_ret.get("afterFinished") is not None:
        cfg.object_retention_after_finished_seconds = _duration(
            wl_ret["afterFinished"]
        )
    if wl_ret.get("afterDeactivatedByKueue") is not None:
        cfg.object_retention_after_deactivated_seconds = _duration(
            wl_ret["afterDeactivatedByKueue"]
        )
    cfg.use_device_scheduler = bool(
        _pick(raw, "useDeviceScheduler", "use_device_scheduler",
              default=False)
    )
    cfg.device_kernel = str(
        _pick(raw, "deviceKernel", "device_kernel", default="scan")
    )
    cfg.auto_cpu_kernel = str(
        _pick(raw, "autoCpuKernel", "auto_cpu_kernel", default="scan")
    )
    cfg.pipeline_cycles = str(
        _pick(raw, "pipelineCycles", "pipeline_cycles", default="auto")
    )
    cfg.tile_width = _pick(raw, "tileWidth", "tile_width", default="auto")

    validate(cfg)
    return cfg


def validate(cfg: Configuration) -> None:
    """reference pkg/config/validation.go (subset)."""
    if cfg.wait_for_pods_ready.enable:
        if cfg.wait_for_pods_ready.timeout_seconds <= 0:
            raise ValueError("waitForPodsReady.timeout must be positive")
        if cfg.wait_for_pods_ready.requeuing_backoff_base_seconds < 0:
            raise ValueError("backoffBaseSeconds must be >= 0")
    for strategy in cfg.fair_sharing.preemption_strategies:
        if strategy not in (
            "LessThanOrEqualToFinalShare", "LessThanInitialShare",
        ):
            raise ValueError(f"unknown preemption strategy {strategy}")
    if cfg.multi_kueue.dispatcher_name not in (
        "AllAtOnce", "Incremental", "Fleet",
    ):
        raise ValueError(
            f"unknown dispatcher {cfg.multi_kueue.dispatcher_name}"
        )
    if cfg.multi_kueue.fleet_spread_weight < 0:
        raise ValueError("multiKueue.fleetSpreadWeight must be >= 0")
    if cfg.multi_kueue.fleet_preempt_penalty < 0:
        raise ValueError("multiKueue.fleetPreemptPenalty must be >= 0")
    if cfg.multi_kueue.fleet_affinity_penalty < 0:
        raise ValueError("multiKueue.fleetAffinityPenalty must be >= 0")
    if any(v < 0 for v in cfg.multi_kueue.fleet_dispatch_costs.values()):
        raise ValueError("multiKueue.fleetDispatchCosts must be >= 0")
    for gate in cfg.feature_gates:
        if gate not in features.all_gates():
            raise ValueError(f"unknown feature gate {gate}")
    if cfg.device_kernel not in ("scan", "fixedpoint", "auto"):
        raise ValueError(
            f"unknown deviceKernel {cfg.device_kernel!r} "
            "(expected scan | fixedpoint | auto)"
        )
    if cfg.auto_cpu_kernel not in ("scan", "fixedpoint"):
        raise ValueError(
            f"unknown autoCpuKernel {cfg.auto_cpu_kernel!r} "
            "(expected scan | fixedpoint)"
        )
    if cfg.pipeline_cycles not in ("on", "off", "auto"):
        raise ValueError(
            f"unknown pipelineCycles {cfg.pipeline_cycles!r} "
            "(expected on | off | auto)"
        )
    if cfg.tile_width not in ("auto", "off"):
        try:
            ok = int(cfg.tile_width) > 0 and not isinstance(
                cfg.tile_width, bool
            )
        except (TypeError, ValueError):
            ok = False
        if not ok:
            raise ValueError(
                f"unknown tileWidth {cfg.tile_width!r} "
                "(expected auto | off | positive integer)"
            )


def apply_feature_gates(cfg: Configuration) -> None:
    for gate, value in cfg.feature_gates.items():
        features.set_enabled(gate, value)


def build_manager(cfg: Configuration, **kw):
    """cmd/kueue main.go equivalent: construct a Manager from config."""
    from kueue_tpu.manager import Manager

    apply_feature_gates(cfg)
    retention = None
    if (
        cfg.object_retention_after_finished_seconds is not None
        or cfg.object_retention_after_deactivated_seconds is not None
    ):
        retention = RetentionConfig(
            retain_finished_seconds=(
                cfg.object_retention_after_finished_seconds
            ),
            retain_deactivated_seconds=(
                cfg.object_retention_after_deactivated_seconds
            ),
        )
    mgr = Manager(
        fair_sharing=cfg.fair_sharing.enable,
        pods_ready=cfg.wait_for_pods_ready,
        retention=retention,
        use_device_scheduler=cfg.use_device_scheduler,
        admission_fair_sharing=cfg.admission_fair_sharing,
        device_kernel=cfg.device_kernel,
        auto_cpu_kernel=cfg.auto_cpu_kernel,
        pipeline_cycles=cfg.pipeline_cycles,
        tile_width=cfg.tile_width,
        **kw,
    )
    mgr.exclude_resource_prefixes = list(
        cfg.resources.exclude_resource_prefixes
    )
    mgr.metrics_custom_labels = list(cfg.metrics_custom_labels)
    mgr.resource_transformations = list(cfg.resources.transformations)
    mgr.device_class_mappings = list(cfg.resources.device_class_mappings)
    mgr.cache.device_class_mappings = mgr.device_class_mappings
    mgr.manage_jobs_without_queue_name = cfg.manage_jobs_without_queue_name
    from kueue_tpu.controllers.jobframework import registry

    for name in registry.names():
        registry.set_enabled(name, name in cfg.integrations)
    return mgr


def _duration(v) -> float:
    """Parse '5m', '30s', '1h', numbers, into seconds."""
    if isinstance(v, (int, float)):
        return float(v)
    s = str(v).strip()
    mult = 1.0
    if s.endswith("ms"):
        return float(s[:-2]) / 1000.0
    if s.endswith("h"):
        mult, s = 3600.0, s[:-1]
    elif s.endswith("m"):
        mult, s = 60.0, s[:-1]
    elif s.endswith("s"):
        s = s[:-1]
    return float(s) * mult
