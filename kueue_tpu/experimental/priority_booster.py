"""Time-sharing priority booster
(reference cmd/experimental/kueue-priority-booster).

Once a workload has been Admitted for at least ``time_sharing_interval``,
sets the ``kueue.x-k8s.io/priority-boost`` annotation to a negative value so
same-base-priority pending workloads can preempt it under
withinClusterQueue: LowerPriority — cooperative time slicing on top of the
normal preemption machinery. Behavioral surface:
cmd/experimental/kueue-priority-booster/pkg/controller/controller.go:40-285.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import time

from kueue_tpu.api.constants import COND_ADMITTED
from kueue_tpu.api.types import Workload
from kueue_tpu.core.workload_info import (
    PRIORITY_BOOST_ANNOTATION,
    get_condition,
)


@dataclass
class PriorityBoostController:
    """Call-driven reconciler: ``reconcile(manager)`` sweeps all workloads.

    * admitted for >= ``time_sharing_interval`` seconds and in scope →
      annotation set to ``-negative_boost_value``;
    * out of scope / not admitted → a controller-managed (negative)
      annotation is cleared; zero/positive values are treated as
      manually set and left untouched.
    """

    time_sharing_interval: float = 0.0
    negative_boost_value: int = 100_000
    workload_selector: Optional[Callable[[Workload], bool]] = None
    max_workload_priority: Optional[int] = None
    clock: Callable[[], float] = time.monotonic
    changed: List[str] = field(default_factory=list)

    def _in_scope(self, wl: Workload) -> bool:
        if self.workload_selector is not None and not self.workload_selector(
            wl
        ):
            return False
        if (
            self.max_workload_priority is not None
            and wl.priority > self.max_workload_priority
        ):
            return False
        return True

    def _compute_boost(self, wl: Workload) -> int:
        """(boost, 0) after the time-sharing window; 0 otherwise."""
        if self.time_sharing_interval <= 0:
            return 0
        cond = get_condition(wl, COND_ADMITTED)
        if cond is None or not cond.status:
            return 0
        if self.clock() - cond.last_transition_time \
                < self.time_sharing_interval:
            return 0
        return -self.negative_boost_value

    def reconcile_workload(self, manager, wl: Workload) -> bool:
        """Returns True when the annotation changed (priority re-resolves
        through the queue update)."""
        current = wl.annotations.get(PRIORITY_BOOST_ANNOTATION, "")
        if not self._in_scope(wl):
            # Clear only controller-managed (negative) values.
            try:
                managed = current != "" and int(current) < 0
            except ValueError:
                managed = False
            if not managed:
                return False
            del wl.annotations[PRIORITY_BOOST_ANNOTATION]
            self._requeue(manager, wl)
            return True

        boost = self._compute_boost(wl)
        desired = str(boost) if boost != 0 else ""
        if current == desired:
            return False
        if desired:
            wl.annotations[PRIORITY_BOOST_ANNOTATION] = desired
        else:
            wl.annotations.pop(PRIORITY_BOOST_ANNOTATION, None)
        self._requeue(manager, wl)
        return True

    @staticmethod
    def _requeue(manager, wl: Workload) -> None:
        """Effective priority changed (reference workload.go:1525
        PriorityChanged -> workload_controller.go:1471): re-sort queue
        membership for pending workloads; for admitted ones, wake the
        associated inadmissible workloads so a pending peer can now try to
        preempt the deprioritized workload."""
        from kueue_tpu.core.workload_info import is_admitted

        if is_admitted(wl):
            cq = manager.queues.cluster_queue_for(wl)
            manager.queues.queue_inadmissible_workloads(
                [cq] if cq else None
            )
        else:
            manager.queues.add_or_update_workload(wl)

    def reconcile(self, manager) -> List[str]:
        """Sweep every workload known to the manager's cache + queues."""
        out: List[str] = []
        seen: Dict[str, Workload] = {}
        for info in manager.cache.workloads.values():
            seen[info.obj.key] = info.obj
        for wl in list(getattr(manager, "workloads", {}).values()):
            seen.setdefault(wl.key, wl)
        for wl in seen.values():
            if self.reconcile_workload(manager, wl):
                out.append(wl.key)
        self.changed.extend(out)
        return out
