"""Experimental controllers (reference cmd/experimental): the LocalQueue
populator and the time-sharing priority booster."""

from kueue_tpu.experimental.populator import PopulatorController
from kueue_tpu.experimental.priority_booster import PriorityBoostController

__all__ = ["PopulatorController", "PriorityBoostController"]
