"""LocalQueue populator (reference cmd/experimental/kueue-populator).

Watches namespaces and ClusterQueues; for every (namespace, CQ) pair where
the CQ's namespaceSelector matches the namespace labels (and the namespace
passes the populator's own selector), ensures a LocalQueue pointing at the
CQ exists in that namespace. Behavioral surface:
cmd/experimental/kueue-populator/pkg/controller/controller.go:108-282.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from kueue_tpu.api.types import LabelSelector, LocalQueue

AUTO_GENERATED_LABEL = "kueue.x-k8s.io/auto-generated"

# LocalQueueNameMode (pkg/config/config.go)
NAME_MODE_FIXED = "Fixed"
NAME_MODE_AS_CLUSTER_QUEUE = "AsClusterQueue"


@dataclass
class PopulatorEvent:
    kind: str  # Created | Exists | Skipped
    namespace: str
    local_queue: str
    cluster_queue: str


@dataclass
class PopulatorController:
    """Call-driven reconciler: ``reconcile(manager)`` scans all namespaces
    known to the cache (plus any defaults) against all ClusterQueues."""

    namespace_selector: Optional[LabelSelector] = None
    local_queue_name: str = "default"
    local_queue_name_mode: str = NAME_MODE_AS_CLUSTER_QUEUE
    events: List[PopulatorEvent] = field(default_factory=list)

    def _ns_matches(self, labels: Dict[str, str]) -> bool:
        if self.namespace_selector is None:
            return True
        return self.namespace_selector.matches(labels)

    def _cq_selects(self, cq, labels: Dict[str, str]) -> bool:
        sel = cq.namespace_selector
        if sel is None:
            return True
        if isinstance(sel, LabelSelector):
            return sel.matches(labels)
        return all(labels.get(k) == v for k, v in sel.items())

    def reconcile(self, manager) -> List[PopulatorEvent]:
        """Ensure LocalQueues exist for every matching (namespace, CQ).
        Returns the events of this pass (also appended to ``events``)."""
        cache = manager.cache
        out: List[PopulatorEvent] = []
        namespaces = dict(cache.namespaces)
        # Namespaces referenced by workloads but not registered get the
        # implicit metadata.name label (mirrors the implied label the
        # scheduler's namespaceSelector check uses).
        for ns_name, ns in namespaces.items():
            labels = dict(getattr(ns, "labels", {}) or {})
            labels.setdefault("kubernetes.io/metadata.name", ns_name)
            if not self._ns_matches(labels):
                continue
            for cq_name, cq in cache.cluster_queues.items():
                if not self._cq_selects(cq, labels):
                    continue
                lq_name = (
                    cq_name
                    if self.local_queue_name_mode == NAME_MODE_AS_CLUSTER_QUEUE
                    else self.local_queue_name
                )
                key = f"{ns_name}/{lq_name}"
                existing = cache.local_queues.get(key)
                if existing is not None:
                    kind = (
                        "Exists"
                        if existing.cluster_queue == cq_name
                        else "Skipped"  # name collision with other CQ
                    )
                    out.append(
                        PopulatorEvent(kind, ns_name, lq_name, cq_name)
                    )
                    continue
                lq = LocalQueue(
                    name=lq_name,
                    namespace=ns_name,
                    cluster_queue=cq_name,
                    labels={AUTO_GENERATED_LABEL: "true"},
                )
                manager.apply(lq)
                out.append(
                    PopulatorEvent("Created", ns_name, lq_name, cq_name)
                )
        self.events.extend(out)
        return out
