"""Multi-chip execution of the batched scheduling cycle.

The solver is data-parallel over the workload axis: per-workload nomination
(the FLOP-heavy part — W x F x R fit/borrow tensors) shards across devices
over a 1-D ``('w',)`` mesh, while the quota tree, policy arrays, admitted
candidates and topology state are replicated. XLA inserts the collectives
(an all-gather before the global admission sort/scan, which is sequential
by semantics and tiny by volume).

Every per-workload field of CycleArrays (``w_*`` vectors, the slot-layout
``s_*`` tensors, per-entry TAS rows) shards on its leading axis; everything
else replicates — the spec is derived from the field names, so new encoder
fields inherit the right placement automatically.

On multi-host TPU fleets the same program spans hosts via jax.distributed;
the mesh axis simply grows. No NCCL-analog hand-plumbing: ICI/DCN routing is
XLA's job (the reference's MultiKueue-style cross-cluster dispatch remains a
control-plane concern, kueue_tpu/controllers/multikueue.py).
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kueue_tpu.models import batch_scheduler
from kueue_tpu.models.encode import CycleArrays


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), ("w",))


def arrays_shardings(mesh: Mesh, arrays: CycleArrays) -> CycleArrays:
    """Sharding pytree matching ``arrays``: per-workload tensors (w_*/s_*)
    shard their leading axis over the 'w' mesh axis, everything else
    (tree, per-CQ policy, TAS topology, fair fields) replicates."""
    rep = NamedSharding(mesh, P())
    wsh = NamedSharding(mesh, P("w"))

    def leaf_spec(sharded):
        return lambda leaf: (wsh if sharded else rep)

    out = {}
    for name in CycleArrays._fields:
        val = getattr(arrays, name)
        if val is None:
            out[name] = None
            continue
        sharded = name.startswith("w_") or name.startswith("s_")
        out[name] = jax.tree_util.tree_map(leaf_spec(sharded), val)
    return CycleArrays(**out)


def group_shardings(mesh: Mesh, ga) -> object:
    rep = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(lambda _: rep, ga)


def admitted_shardings(mesh: Mesh, adm) -> object:
    # The admitted-candidate set is consumed by victim searches indexed
    # per pending workload; replicating it keeps the [W,A] interactions
    # local to each shard of W.
    rep = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(lambda _: rep, adm)


def out_shardings(mesh: Mesh) -> object:
    # Outputs are decoded on the host each cycle: replicate (the final
    # all-gather is tiny relative to the nomination FLOPs).
    rep = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(
        lambda _: rep,
        batch_scheduler.CycleOutputs(
            outcome=0, chosen_flavor=0, borrow=0, tried_flavor_idx=0,
            usage=0, order=0,
        ),
    )


def cycle_shardings(mesh: Mesh):
    """Legacy helper for the dense layout (back-compat): builds the specs
    from a minimal CycleArrays prototype."""
    proto = CycleArrays(
        tree=_tree_proto(), usage=0, flavor_at=0, n_flavors=0, covered=0,
        when_can_borrow_try_next=0, when_can_preempt_try_next=0,
        pref_preempt_over_borrow=0, can_preempt_while_borrowing=0,
        never_preempts=0, can_always_reclaim=0, usage_by_prio=0,
        prio_cuts=0, prefilter_valid=0, policy_within=0, policy_reclaim=0,
        nominal_cq=0, w_cq=0, w_req=0, w_elig=0, w_active=0, w_priority=0,
        w_timestamp=0, w_quota_reserved=0, w_start_flavor=0,
    )
    return arrays_shardings(mesh, proto), out_shardings(mesh)


def _tree_proto():
    from kueue_tpu.ops.quota_ops import QuotaTreeArrays

    return QuotaTreeArrays(*([0] * len(QuotaTreeArrays._fields)))


def sharded_cycle(mesh: Mesh):
    """Compile the flat cycle for the mesh (workload axis sharded). The
    workload axis length must divide the mesh size (the encoder pads to a
    multiple of 8)."""
    in_sh, out_sh = cycle_shardings(mesh)
    return jax.jit(
        batch_scheduler.cycle_impl, in_shardings=(in_sh,),
        out_shardings=out_sh,
    )


def sharded_grouped_cycle(mesh: Mesh, arrays: CycleArrays, ga,
                          adm=None, s_max: int = 0,
                          n_levels: Optional[int] = None,
                          unroll: int = 2,
                          shard_scan_by_group: bool = False):
    """Compile the forest-grouped cycle (the production kernel) with the
    workload axis sharded over ``mesh``. With ``adm`` the classical
    device-preemption cycle is compiled (victim search + designated-victim
    scan), matching DeviceScheduler's default kernel.

    ``shard_scan_by_group``: nominate stays data-parallel over W, but the
    sequential admission scan shards over the GROUP axis (independent
    cohort forests) instead of replicating on every device — the
    nominate outputs all-gather once before the scan and the per-step
    scan state stays device-local (the replicated scan was the
    multi-chip bottleneck: cycle 533 ms at 1 device -> 1,877 ms at 8)."""
    from kueue_tpu.ops.quota_ops import MAX_DEPTH

    nl = n_levels if n_levels is not None else MAX_DEPTH + 1
    # ga stays replicated at the boundary even in group mode (G rarely
    # divides the mesh; the internal with_sharding_constraint pads) —
    # the scan's group tensors are re-constrained to P('w') inside.
    in_sh = [arrays_shardings(mesh, arrays), group_shardings(mesh, ga)]
    rep = NamedSharding(mesh, P())
    if adm is not None:
        in_sh.append(admitted_shardings(mesh, adm))
    impl = batch_scheduler.make_grouped_cycle(
        s_max=s_max, preempt=adm is not None, n_levels=nl, unroll=unroll,
        mesh=mesh if shard_scan_by_group else None,
    )
    return jax.jit(
        impl, in_shardings=tuple(in_sh),
        out_shardings=jax.tree_util.tree_map(lambda _: rep, _out_proto(
            preempt=adm is not None, arrays=arrays,
        )),
    )


def sharded_sim_loop(mesh: Mesh, arrays: CycleArrays, ga, s_max: int,
                     kernel: str = "grouped",
                     n_levels: Optional[int] = None,
                     shard_scan_by_group: bool = False):
    """Compile the on-device multi-cycle simulation loop
    (models/sim_loop.py) with the workload axis sharded over ``mesh``:
    per-round nomination fans out across devices, the sequential
    admission state stays replicated (or, with ``shard_scan_by_group``,
    shards over the independent cohort forests), and XLA places the
    collectives."""
    from kueue_tpu.models.sim_loop import make_sim_loop
    from kueue_tpu.ops.quota_ops import MAX_DEPTH

    nl = n_levels if n_levels is not None else MAX_DEPTH + 1
    rep = NamedSharding(mesh, P())
    wsh = NamedSharding(mesh, P("w"))
    sim = make_sim_loop(
        s_max=s_max, kernel=kernel, n_levels=nl,
        mesh=mesh if shard_scan_by_group else None,
    )
    return jax.jit(
        sim,
        in_shardings=(
            arrays_shardings(mesh, arrays),
            group_shardings(mesh, ga),
            wsh,  # runtime_ms[W]
        ),
        out_shardings=jax.tree_util.tree_map(
            lambda _: rep, _sim_out_proto()
        ),
    )


def _sim_out_proto():
    from kueue_tpu.models.sim_loop import SimOutputs

    return SimOutputs(admitted_at=0, completed_at=0, rounds=0,
                      final_vclock=0)


def _out_proto(preempt: bool, arrays: CycleArrays):
    """CycleOutputs prototype with the same None/non-None structure the
    grouped kernel emits for ``arrays`` — out_shardings pytrees must
    match the output tree exactly, so every conditional output plane
    (including the post-PR-15 per-slot TAS takes and the trailing
    ``slot_rounds`` carry) mirrors make_grouped_cycle's with_* gates."""
    has_slots = arrays.s_req is not None
    has_partial = arrays.w_partial is not None
    has_tas = arrays.tas_topo is not None
    has_leader = has_tas and arrays.w_tas_leader_req is not None
    has_stas = has_tas and arrays.s_tas is not None
    return batch_scheduler.CycleOutputs(
        outcome=0, chosen_flavor=0, borrow=0, tried_flavor_idx=0,
        usage=0, order=0,
        victims=0 if preempt else None,
        victim_variant=0 if preempt else None,
        partial_count=0 if has_partial else None,
        s_flavor=0 if has_slots else None,
        s_pmode=0 if has_slots else None,
        s_tried=0 if has_slots else None,
        tas_takes=0 if has_tas else None,
        tas_leader_takes=0 if has_leader else None,
        s_tas_takes=0 if has_stas else None,
        slot_rounds=0 if has_stas else None,
    )
