"""Multi-chip execution of the batched scheduling cycle.

The solver is data-parallel over the workload axis: per-workload nomination
(the FLOP-heavy part — W x F x R fit/borrow tensors) shards across devices
over a 1-D ``('w',)`` mesh, while the quota tree and policy arrays are
replicated. XLA inserts the collectives (an all-gather before the global
admission sort/scan, which is sequential by semantics and tiny by volume).

On multi-host TPU fleets the same program spans hosts via jax.distributed;
the mesh axis simply grows. No NCCL-analog hand-plumbing: ICI/DCN routing is
XLA's job (the reference's MultiKueue-style cross-cluster dispatch remains a
control-plane concern, kueue_tpu/controllers/multikueue.py).
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kueue_tpu.models import batch_scheduler
from kueue_tpu.models.encode import CycleArrays


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), ("w",))


def cycle_shardings(mesh: Mesh):
    """(in_shardings, out_shardings) for batch_scheduler.cycle_impl: workload
    axis sharded, tree/policy replicated, outputs replicated."""
    rep = NamedSharding(mesh, P())
    wsh = NamedSharding(mesh, P("w"))
    tree_sh = jax.tree_util.tree_map(lambda _: rep, _tree_proto())
    in_sh = CycleArrays(
        tree=tree_sh,
        usage=rep,
        flavor_at=rep,
        n_flavors=rep,
        covered=rep,
        when_can_borrow_try_next=rep,
        when_can_preempt_try_next=rep,
        pref_preempt_over_borrow=rep,
        can_preempt_while_borrowing=rep,
        never_preempts=rep,
        can_always_reclaim=rep,
        usage_by_prio=rep,
        prio_cuts=rep,
        prefilter_valid=rep,
        policy_within=rep,
        policy_reclaim=rep,
        nominal_cq=rep,
        w_cq=wsh,
        w_req=wsh,
        w_elig=wsh,
        w_active=wsh,
        w_priority=wsh,
        w_timestamp=wsh,
        w_quota_reserved=wsh,
        w_start_flavor=wsh,
    )
    out_sh = batch_scheduler.CycleOutputs(
        outcome=rep, chosen_flavor=rep, borrow=rep, tried_flavor_idx=rep,
        usage=rep, order=rep,
    )
    return in_sh, out_sh


def _tree_proto():
    from kueue_tpu.ops.quota_ops import QuotaTreeArrays

    return QuotaTreeArrays(*([0] * len(QuotaTreeArrays._fields)))


def sharded_cycle(mesh: Mesh):
    """Compile the cycle for the mesh. Workload axis length must divide the
    mesh size (the encoder pads to a multiple of 8)."""
    in_sh, out_sh = cycle_shardings(mesh)
    return jax.jit(
        batch_scheduler.cycle_impl, in_shardings=(in_sh,), out_shardings=out_sh
    )
