"""The streaming admission service loop and its live-health telemetry.

Everything before this module observes one ``Manager.schedule()`` call at
a time; an operator of a long-running control plane asks a different
question — *is the loop keeping up?* :class:`ServiceLoop` converts the
call-driven :class:`~kueue_tpu.manager.Manager` facade into an actual
service: an async ingestion path (submissions, completions, quota edits,
drains) that producer threads feed while cycles run, a loop thread that
drains the ingest queue at cycle boundaries and runs admission cycles +
clock ticks, and a telemetry stage that overlaps against the *next*
cycle on its own thread.

Determinism contract (pinned by tests/test_service.py's randomized
differential): ingested ops are applied FIFO at the top of a loop
iteration, under the service lock, on the loop thread — so the event
sequence the scheduler sees is exactly the sequence a call-per-cycle
driver would produce, and every cycle stays bit-identical to the
synchronous path. The telemetry pipelining is observation-only: stage B
(telemetry export — watermark gauges, continuous SLO burn, observer
callbacks) runs on the telemetry thread and never touches manager
state, so overlapping it with stage A cannot change an admission.

The service loop is also what switches on *compute* pipelining: a
device scheduler configured ``pipeline_cycles="auto"`` gets
``set_pipeline(True)`` at service start, so each admission cycle
speculatively stages the next cycle's W encode inside its own
device-dispatch window (models/driver.py + models/arena.py). Apply
stays FIFO at the cycle boundary and stale speculation rows are
patched or abandoned, so results remain bit-identical to the
serialized loop; the loop feeds a backpressure hint (skip staging
while quota edits / deletes are draining) each iteration.

Live-health surface (docs/observability.md, "Service loop & live
health"):

- queue watermarks: per-CQ depth + oldest-pending-age gauges and the
  p99 admission-wait gauge;
- per-workload latency spans: submit→nominate and submit→admit
  histograms, plus retroactive ``service/admission_wait`` spans on the
  Chrome-trace timeline (:func:`kueue_tpu.metrics.tracing.record_complete_span`);
- backpressure + lag: bounded ingest queue with a rejected-post
  counter, per-op ingestion lag histogram;
- liveness: a lock-free :meth:`health` document (cycle staleness,
  stall flag, breaker state) served as ``/healthz`` + ``/readyz`` on
  the visibility server — lock-free because a stalled loop may be
  holding the service lock, and the health probe must still answer;
- continuous SLO burn: the PR-6 engine re-evaluated on the loop tick
  instead of on demand.

Fault drill: the ``service.cycle`` injection point fires at the top of
every iteration — a ``delay`` rule stalls the loop (``/healthz`` flips
503 once staleness exceeds ``stall_after_s`` and recovers after), a
``raise`` rule is contained by the loop and counted in
``service_loop_errors_total``.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from kueue_tpu.metrics import tracing
from kueue_tpu.utils import faults


class ServiceLoop:
    """Run a Manager as a long-lived admission service.

    Producers call :meth:`submit` / :meth:`finish` / :meth:`apply` /
    :meth:`delete` / :meth:`call` from any thread; the loop thread
    drains them FIFO at the next cycle boundary. ``step()`` is also
    callable synchronously (no threads) — the differential tests and
    simulations drive it that way.

    Parameters:

    - ``tick_interval_s``: cadence of ``manager.tick()`` inside the
      loop; ``None`` disables ticking (differential harnesses).
    - ``slo_interval_s``: cadence of continuous SLO evaluation on the
      telemetry stage (defaults to ``tick_interval_s`` or 1s).
    - ``idle_sleep_s``: sleep between iterations when an iteration did
      no work (no ops, no admissions).
    - ``max_ingest``: ingest queue bound; a full queue rejects the post
      (returns False) and counts ``service_backpressure_total``.
    - ``stall_after_s``: cycle staleness above this flips
      ``health()["healthy"]`` false and ``/healthz`` to 503.
    - ``cycles_per_iter``: max admission cycles per iteration (stops
      early on no progress); 1 = exactly one cycle per step.
    - ``telemetry_async``: export telemetry on a separate thread,
      overlapped with the next cycle (False = inline, deterministic).
    """

    def __init__(
        self,
        manager,
        *,
        tick_interval_s: Optional[float] = 1.0,
        slo_interval_s: Optional[float] = None,
        idle_sleep_s: float = 0.01,
        max_ingest: int = 4096,
        stall_after_s: float = 5.0,
        cycles_per_iter: int = 4,
        telemetry_async: bool = True,
    ) -> None:
        self.manager = manager
        self.tick_interval_s = tick_interval_s
        self.slo_interval_s = (
            slo_interval_s if slo_interval_s is not None
            else (tick_interval_s or 1.0)
        )
        self.idle_sleep_s = idle_sleep_s
        self.max_ingest = max_ingest
        self.stall_after_s = stall_after_s
        self.cycles_per_iter = max(1, cycles_per_iter)
        self.telemetry_async = telemetry_async
        self._clock = manager.clock

        #: The service state lock. The loop holds it while applying ops
        #: and running cycles; visibility handlers that traverse cache /
        #: queue state (explain, what-if, pendingworkloads) serialize
        #: against it. RLock: handler code may re-enter manager helpers
        #: that take it again.
        self.lock = threading.RLock()

        # Ingestion: producers append under their own mutex so a post
        # never blocks on a running cycle.
        self._ingest: deque = deque()
        self._ingest_lock = threading.Lock()

        # submit→nominate→admit latency bookkeeping (loop thread only):
        # key -> [submit_ts, nominate_ts or None].
        self._lat: Dict[str, List[Optional[float]]] = {}

        #: Observer callbacks, invoked with each CycleResult on the
        #: telemetry stage (never on the loop thread's critical path).
        #: Callbacks must not mutate manager state directly — post ops.
        self.on_cycle: List[Callable[[Any], None]] = []

        # Liveness heartbeats — plain float/int writes (atomic under the
        # GIL) read lock-free by health().
        self._started = False
        self._last_cycle_t: Optional[float] = None
        self._last_tick_t: Optional[float] = None
        self._last_slo_t: Optional[float] = None
        self._iterations = 0
        self._errors = 0

        self._stop: Optional[threading.Event] = None
        self._thread: Optional[threading.Thread] = None

        # Pipelined admission cycles: True when the device scheduler is
        # speculating next-cycle encodes inside the dispatch window. A
        # scheduler configured pipeline_cycles="auto" is switched on at
        # service start (_prepare_start) — the service loop is the
        # steady-cycle producer the speculation pays off under.
        self._pipeline = bool(
            getattr(getattr(manager, "scheduler", None),
                    "_pipeline_on", False)
        )

        #: HA replication hook (controllers/ha.py Replicator.attach):
        #: when set, ``on_step(manager, batch)`` runs inside ``step()``
        #: under the service lock AFTER cycles/tick and BEFORE telemetry
        #: — the stream is durable before any observer sees the step's
        #: results (write-ahead of the ack).
        self.replicator = None

        #: when attached, ``publish_cycle(cache, queues, dirty)`` runs at
        #: the end of each step under the service lock, so the read plane
        #: serves crash-consistent cycle-boundary snapshots.
        self._readplane = None

        # Telemetry hand-off: a coalescing one-slot mailbox + seq/done
        # counters so flush_telemetry() can wait for quiescence.
        self._tel_cv = threading.Condition()
        self._tel_payload: Optional[dict] = None
        self._tel_seq = 0
        self._tel_done = 0
        self._tel_thread: Optional[threading.Thread] = None

    # -- ingestion (any thread) -----------------------------------------

    def post(self, op: Tuple) -> bool:
        """Enqueue one raw op tuple; False (+ backpressure counter) when
        the ingest queue is full. Prefer the typed helpers below."""
        with self._ingest_lock:
            if len(self._ingest) >= self.max_ingest:
                full = True
            else:
                self._ingest.append(op)
                full = False
        if full:
            m = self.manager.metrics
            m.inc("service_backpressure_total")
            return False
        return True

    def submit(self, wl) -> bool:
        """Submit one Workload (webhook-validated at apply time)."""
        return self.post(("submit", wl, self._clock()))

    def finish(self, key: str, success: bool = True) -> bool:
        """Mark a workload finished (completion churn)."""
        return self.post(("finish", key, success, self._clock()))

    def apply(self, *objects) -> bool:
        """Apply config objects (quota edits, drains, new queues)."""
        return self.post(("apply", objects, self._clock()))

    def delete(self, obj) -> bool:
        return self.post(("delete", obj, self._clock()))

    def call(self, fn: Callable[[Any], None], kind: str = "call") -> bool:
        """Run ``fn(manager)`` on the loop thread under the lock — the
        escape hatch for ops the typed helpers don't cover."""
        return self.post((kind, fn, self._clock()))

    def ingest_depth(self) -> int:
        with self._ingest_lock:
            return len(self._ingest)

    def attach_readplane(self, readplane) -> None:
        """Wire a ReadPlane so ``step()`` publishes cycle-boundary read
        snapshots (readplane/publisher.py). Idempotent; pass None to
        detach."""
        self._readplane = readplane

    # -- one loop iteration (loop thread) -------------------------------

    def step(self) -> bool:
        """Apply pending ops FIFO, run admission cycles, tick when due,
        publish telemetry. Returns True when the iteration did work.
        Synchronous and deterministic with ``telemetry_async=False``."""
        if faults.ENABLED:
            faults.fire(faults.SERVICE_CYCLE)
        m = self.manager.metrics
        with self._ingest_lock:
            batch = list(self._ingest)
            self._ingest.clear()
        results: List[Any] = []
        with self.lock:
            now = self._clock()
            for op in batch:
                m.observe("service_ingest_lag_seconds", max(0.0, now - op[-1]))
                m.inc("service_ingest_ops_total", {"kind": op[0]})
                self._apply_op(op)
            if self._pipeline:
                # Backpressure hint: config churn (quota edits, queue
                # deletes) invalidates speculation buffers anyway — skip
                # staging the next one while such ops are flowing.
                self.manager.scheduler.pipeline_backpressure_hint(
                    any(op[0] in ("apply", "delete") for op in batch)
                )
            had_pending = bool(batch) or self._any_pending()
            if had_pending:
                prev_heads = None
                for _ in range(self.cycles_per_iter):
                    result = self.manager.schedule()
                    results.append(result)
                    self._track_latency(result)
                    if result.admitted or result.preempted:
                        prev_heads = None
                        continue
                    if not result.head_keys \
                            or result.head_keys == prev_heads:
                        break
                    prev_heads = result.head_keys
            now = self._clock()
            if self.tick_interval_s is not None and (
                self._last_tick_t is None
                or now - self._last_tick_t >= self.tick_interval_s
            ):
                self.manager.tick()
                self._last_tick_t = now
            if self.replicator is not None:
                self.replicator.on_step(self.manager, batch)
            if self._readplane is not None:
                # Cycle-boundary snapshot for the read plane: demand- and
                # fingerprint-gated inside, contained, never raises.
                self._readplane.publish_cycle(
                    self.manager.cache, self.manager.queues,
                    dirty=bool(batch) or any(
                        r.admitted or r.preempted for r in results),
                )
            payload = self._collect_watermarks(results)
        m.inc("service_loop_iterations_total")
        self._iterations += 1
        self._last_cycle_t = self._clock()
        self._publish_telemetry(payload)
        return bool(batch) or any(
            r.admitted or r.preempted for r in results
        )

    def _apply_op(self, op: Tuple) -> None:
        kind = op[0]
        if kind == "submit":
            wl = op[1]
            self.manager.create_workload(wl)
            # Latency clock starts at post time: the operator-visible
            # wait includes time spent queued in the ingest path.
            self._lat[wl.key] = [op[2], None]
        elif kind == "finish":
            key, success = op[1], op[2]
            wl = self.manager.workloads.get(key)
            if wl is not None:
                self.manager.finish_workload(wl, success=success)
            self._lat.pop(key, None)
        elif kind == "apply":
            self.manager.apply(*op[1])
        elif kind == "delete":
            self.manager.delete(op[1])
        else:
            op[1](self.manager)

    def _track_latency(self, result) -> None:
        now = self._clock()
        for key in result.head_keys:
            ent = self._lat.get(key)
            if ent is not None and ent[1] is None:
                ent[1] = now
                self.manager.metrics.observe(
                    "service_submit_to_nominate_seconds",
                    max(0.0, now - ent[0]),
                )
        for key in result.admitted:
            ent = self._lat.pop(key, None)
            if ent is None:
                continue
            wait = max(0.0, now - ent[0])
            self.manager.metrics.observe(
                "service_submit_to_admit_seconds", wait
            )
            if tracing.ENABLED:
                tracing.record_complete_span(
                    "service/admission_wait", wait, workload=key
                )
        # Entries for workloads that left by another door (deleted,
        # evicted then finished) must not pin memory forever.
        if len(self._lat) > 64:
            for key in list(self._lat):
                if key not in self.manager.workloads:
                    self._lat.pop(key, None)

    def _any_pending(self) -> bool:
        q = self.manager.queues
        return any(
            q.pending_count(name)
            for name in list(self.manager.cache.cluster_queues)
        )

    def _collect_watermarks(self, results: List[Any]) -> dict:
        """Plain-data snapshot taken under the service lock; exported by
        the telemetry stage without touching live state."""
        now = self._clock()
        per_cq = {}
        for name in list(self.manager.cache.cluster_queues):
            depth = self.manager.queues.pending_count(name)
            oldest = self.manager.queues.oldest_pending_creation(name)
            per_cq[name] = (
                depth,
                0.0 if oldest is None else max(0.0, now - oldest),
            )
        return {
            "per_cq": per_cq,
            "ingest_depth": self.ingest_depth(),
            "results": results,
        }

    # -- telemetry stage (telemetry thread, or inline) ------------------

    def _publish_telemetry(self, payload: dict) -> None:
        if not self.telemetry_async or self._tel_thread is None:
            self._export_telemetry(payload)
            return
        with self._tel_cv:
            if self._tel_payload is None:
                self._tel_payload = payload
            else:
                # Coalesce: latest watermarks win, cycle results append
                # so observers never miss an admission.
                self._tel_payload["per_cq"] = payload["per_cq"]
                self._tel_payload["ingest_depth"] = payload["ingest_depth"]
                self._tel_payload["results"].extend(payload["results"])
            self._tel_seq += 1
            self._tel_cv.notify_all()

    def _export_telemetry(self, payload: dict) -> None:
        m = self.manager.metrics
        for name, (depth, age) in payload["per_cq"].items():
            lbl = {"cluster_queue": name}
            m.set_gauge("service_queue_depth", depth, lbl)
            m.set_gauge("service_oldest_pending_age_seconds", age, lbl)
        m.set_gauge("service_ingest_queue_depth", payload["ingest_depth"])
        p99 = m.histogram_quantile("service_submit_to_admit_seconds", 0.99)
        if p99 is not None:
            m.set_gauge("service_admission_wait_p99_seconds", p99)
        self._export_staleness()
        now = self._clock()
        if self._last_slo_t is None \
                or now - self._last_slo_t >= self.slo_interval_s:
            self._last_slo_t = now
            self.manager.slo().evaluate()
        for result in payload["results"]:
            for cb in list(self.on_cycle):
                try:
                    cb(result)
                except Exception:
                    self._errors += 1
                    m.inc("service_loop_errors_total")

    def _export_staleness(self) -> None:
        m = self.manager.metrics
        now = self._clock()
        last = self._last_cycle_t
        age = 0.0 if last is None else max(0.0, now - last)
        m.set_gauge("service_cycle_staleness_seconds", age)
        m.set_gauge(
            "service_loop_stalled",
            1.0 if age > self.stall_after_s else 0.0,
        )

    def _telemetry_run(self) -> None:
        stop = self._stop
        while True:
            with self._tel_cv:
                if self._tel_payload is None:
                    if stop is not None and stop.is_set():
                        return
                    # Timed wait so staleness/stalled gauges keep moving
                    # even while the loop itself is wedged.
                    self._tel_cv.wait(
                        timeout=max(0.05, self.stall_after_s / 4.0)
                    )
                payload = self._tel_payload
                self._tel_payload = None
                seq = self._tel_seq
            if payload is None:
                try:
                    self._export_staleness()
                except Exception:
                    self._errors += 1
                continue
            try:
                self._export_telemetry(payload)
            except Exception:
                self._errors += 1
                self.manager.metrics.inc("service_loop_errors_total")
            with self._tel_cv:
                self._tel_done = seq
                self._tel_cv.notify_all()

    def flush_telemetry(self, timeout: float = 5.0) -> None:
        """Block until every published payload has been exported — the
        determinism hook for tests and the steady probe."""
        if not self.telemetry_async or self._tel_thread is None:
            return
        deadline = time.monotonic() + timeout
        with self._tel_cv:
            while self._tel_done < self._tel_seq \
                    or self._tel_payload is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._tel_thread.is_alive():
                    return
                self._tel_cv.wait(timeout=min(0.1, remaining))

    # -- lifecycle ------------------------------------------------------

    def _prepare_start(self, stop_event) -> None:
        if self._started:
            raise RuntimeError("service loop already started")
        self._started = True
        self._stop = stop_event or threading.Event()
        now = self._clock()
        self._last_cycle_t = now
        self._last_tick_t = now
        # Build the SLO engine up front so continuous burn starts on the
        # first telemetry pass, not the first /slo request.
        self.manager.slo()
        # Resolve pipeline_cycles="auto": under a service loop the next
        # cycle is (almost) always coming, so speculation pays for
        # itself; call-per-cycle users keep it off for free.
        sched = getattr(self.manager, "scheduler", None)
        if getattr(sched, "pipeline_cycles", None) == "auto":
            sched.set_pipeline(True)
        self._pipeline = bool(getattr(sched, "_pipeline_on", False))
        if self.telemetry_async:
            self._tel_thread = threading.Thread(
                target=self._telemetry_run,
                name="kueue-service-telemetry", daemon=True,
            )
            self._tel_thread.start()

    def start(self, stop_event: Optional[threading.Event] = None
              ) -> "ServiceLoop":
        """Spawn the loop (and telemetry) threads; returns self."""
        self._prepare_start(stop_event)
        self._thread = threading.Thread(
            target=self._run, name="kueue-service-loop", daemon=True
        )
        self._thread.start()
        return self

    def run_blocking(self, stop_event: Optional[threading.Event] = None
                     ) -> None:
        """Run the loop on the calling thread until ``stop_event`` is
        set (the daemon-mode entry point behind Manager.run_forever)."""
        self._prepare_start(stop_event)
        try:
            self._run()
        finally:
            self._shutdown_telemetry()

    def stop(self, timeout: float = 10.0) -> None:
        if self._stop is not None:
            self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
        self._shutdown_telemetry(timeout=timeout)

    def _shutdown_telemetry(self, timeout: float = 10.0) -> None:
        if self._tel_thread is not None:
            with self._tel_cv:
                self._tel_cv.notify_all()
            self._tel_thread.join(timeout=timeout)

    def _run(self) -> None:
        stop = self._stop
        m = self.manager.metrics
        while stop is not None and not stop.is_set():
            try:
                progressed = self.step()
            except Exception:
                # Contained: one poisoned iteration (including injected
                # service.cycle raises) must not take the service down.
                progressed = False
                self._errors += 1
                m.inc("service_loop_errors_total")
            if not progressed:
                stop.wait(self.idle_sleep_s)

    # -- liveness (any thread, lock-free) -------------------------------

    def health(self) -> dict:
        """Liveness document for ``/healthz`` + ``/readyz``. Reads only
        heartbeat attributes — never the service lock — so a stalled (or
        lock-holding) loop still gets an honest 503."""
        now = self._clock()
        last = self._last_cycle_t
        age = None if last is None else max(0.0, now - last)
        stopping = self._stop is not None and self._stop.is_set()
        stalled = bool(
            self._started and age is not None and age > self.stall_after_s
        )
        healthy = bool(self._started and not stalled and not stopping)
        ready = bool(healthy and self._iterations > 0)
        breaker = getattr(self.manager.scheduler, "breaker_state", None)
        return {
            "healthy": healthy,
            "ready": ready,
            "started": self._started,
            "stopping": stopping,
            "stalled": stalled,
            "lastCycleAgeS": age,
            "stallAfterS": self.stall_after_s,
            "iterations": self._iterations,
            "errors": self._errors,
            "ingestDepth": self.ingest_depth(),
            "breakerState": breaker,
            "pipelineEnabled": self._pipeline,
        }

    def to_doc(self) -> dict:
        """The ``/service`` endpoint body: health + loop configuration."""
        doc = self.health()
        doc["tickIntervalS"] = self.tick_interval_s
        doc["sloIntervalS"] = self.slo_interval_s
        doc["cyclesPerIter"] = self.cycles_per_iter
        doc["maxIngest"] = self.max_ingest
        doc["telemetryAsync"] = self.telemetry_async
        pipeline_health = getattr(
            self.manager.scheduler, "pipeline_health", None
        )
        if pipeline_health is not None:
            doc["pipeline"] = pipeline_health()
        return doc
