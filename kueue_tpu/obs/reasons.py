"""Reason-code vocabulary for admission provenance.

One table maps every decoded device outcome code (models/batch_scheduler
OUT_*) and every preemption victim variant to the kueue-style workload
condition reason it drives — the same strings the reference writes into
workload conditions (QuotaReserved, Preempted, InCohortReclamation, ...).
The flight recorder stamps these onto per-cycle head records, the explain
API surfaces them, and tools/check_metrics_names.py verifies every code
listed here is documented in docs/observability.md.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

from kueue_tpu.api.constants import (
    COND_EVICTED,
    COND_QUOTA_RESERVED,
    EVICTED_BY_PREEMPTION,
    IN_CLUSTER_QUEUE_REASON,
    IN_COHORT_FAIR_SHARING_REASON,
    IN_COHORT_RECLAIM_WHILE_BORROWING_REASON,
    IN_COHORT_RECLAMATION_REASON,
    RequeueReason,
)

# Outcome plane codes, mirrored from models/batch_scheduler.py OUT_* as
# plain literals so this vocabulary (and everything downstream: explain,
# the docs checker, the CLI) imports without the JAX-backed kernel module.
# tests/test_obs.py pins these equal to the kernel's constants.
OUT_NOFIT = 0
OUT_NO_CANDIDATES = 1
OUT_NEEDS_HOST = 2
OUT_FIT_SKIPPED = 3
OUT_ADMITTED = 4
OUT_PREEMPTING = 5
OUT_SHADOWED = 6


@dataclass(frozen=True)
class OutcomeInfo:
    """How one decoded outcome translates to workload status."""

    name: str                    # symbolic outcome (whatif _OUTCOME_NAMES)
    condition: str               # workload condition the outcome drives
    condition_reason: str        # kueue-style condition reason string
    requeue_reason: Optional[str]  # RequeueReason value, None if terminal


# Device outcome plane codes -> provenance info. Names match
# whatif/engine.py _OUTCOME_NAMES; condition semantics match what
# models/driver.py actually writes (QuotaReserved=True "QuotaReserved" on
# admission, QuotaReserved=False "Pending" on every requeue).
DEVICE_OUTCOMES: Dict[int, OutcomeInfo] = {
    OUT_NOFIT: OutcomeInfo(
        "NoFit", COND_QUOTA_RESERVED, "Pending",
        RequeueReason.NO_FIT.value),
    OUT_NO_CANDIDATES: OutcomeInfo(
        "NoCandidates", COND_QUOTA_RESERVED, "Pending",
        RequeueReason.PREEMPTION_NO_CANDIDATES.value),
    OUT_NEEDS_HOST: OutcomeInfo(
        "NeedsHost", COND_QUOTA_RESERVED, "Pending", None),
    OUT_FIT_SKIPPED: OutcomeInfo(
        "FitSkipped", COND_QUOTA_RESERVED, "Pending",
        RequeueReason.FAILED_AFTER_NOMINATION.value),
    OUT_ADMITTED: OutcomeInfo(
        "Admitted", COND_QUOTA_RESERVED, "QuotaReserved", None),
    OUT_PREEMPTING: OutcomeInfo(
        "Preempting", COND_QUOTA_RESERVED, "Pending",
        RequeueReason.PENDING_PREEMPTION.value),
    OUT_SHADOWED: OutcomeInfo(
        "Shadowed", COND_QUOTA_RESERVED, "Pending",
        RequeueReason.FAILED_AFTER_NOMINATION.value),
}

# Victim eviction: Evicted=True with reason "Preempted", qualified by the
# preemption strategy variant the kernel chose (models/driver.py
# _apply_preempting keeps the same map).
VICTIM_OUTCOME = OutcomeInfo(
    "Preempted", COND_EVICTED, EVICTED_BY_PREEMPTION, None
)

VICTIM_VARIANT_REASONS: Dict[int, str] = {
    1: IN_CLUSTER_QUEUE_REASON,
    2: IN_COHORT_RECLAMATION_REASON,
    3: IN_COHORT_RECLAMATION_REASON,
    4: IN_COHORT_RECLAIM_WHILE_BORROWING_REASON,
    # Fair-sharing tournament variants (fair_preempt_kernel).
    5: IN_COHORT_FAIR_SHARING_REASON,
    6: IN_COHORT_RECLAMATION_REASON,
}

# Host-exact path outcomes, keyed by the CycleResult category the entry
# landed in. The host pipeline doesn't expose per-entry assignment codes
# to the driver, so provenance is per category.
HOST_OUTCOMES: Dict[str, OutcomeInfo] = {
    "admitted": OutcomeInfo(
        "Admitted", COND_QUOTA_RESERVED, "QuotaReserved", None),
    "preempting": OutcomeInfo(
        "Preempting", COND_QUOTA_RESERVED, "Pending",
        RequeueReason.PENDING_PREEMPTION.value),
    "preempted": VICTIM_OUTCOME,
    "skipped": OutcomeInfo(
        "Skipped", COND_QUOTA_RESERVED, "Pending",
        RequeueReason.FAILED_AFTER_NOMINATION.value),
    "inadmissible": OutcomeInfo(
        "Inadmissible", COND_QUOTA_RESERVED, "Pending",
        RequeueReason.GENERIC.value),
}


def documented_reason_codes() -> frozenset:
    """Every symbolic outcome / reason string this layer can emit; the
    docs-coverage check requires each to appear in docs/observability.md."""
    out = set()
    for info in list(DEVICE_OUTCOMES.values()) + list(HOST_OUTCOMES.values()):
        out.add(info.name)
        out.add(info.condition_reason)
        if info.requeue_reason:
            out.add(info.requeue_reason)
    out.add(VICTIM_OUTCOME.condition_reason)
    out.update(VICTIM_VARIANT_REASONS.values())
    return frozenset(out)
