"""Admission provenance + SLO layer (docs/observability.md).

- ``obs.recorder`` — cycle flight recorder: bounded ring of structured
  per-cycle records captured by the device driver, zero-cost when off.
- ``obs.explain`` — the /explain answer: recorder history (what
  happened) joined with the what-if forecast (what will happen).
- ``obs.slo`` — declarative burn-rate SLOs over the metric histograms.
- ``obs.reasons`` — the outcome-code -> kueue condition reason tables.
- ``obs.costs`` — device cost attribution per solver entry point and
  shape bucket, plus the breaker-guarded on-demand profiler.
- ``obs.service`` — the streaming admission service loop: async
  ingestion, pipelined telemetry, queue-age watermarks, /healthz
  liveness, continuous SLO burn.
"""

from kueue_tpu.obs.costs import CostCell, CostLedger
from kueue_tpu.obs.explain import Explainer
from kueue_tpu.obs.recorder import CycleRecord, FlightRecorder, HeadAttempt
from kueue_tpu.obs.service import ServiceLoop
from kueue_tpu.obs.slo import (
    DEFAULT_OBJECTIVES,
    SLObjective,
    SLOEngine,
    SLOStatus,
)

__all__ = [
    "CostCell",
    "CostLedger",
    "CycleRecord",
    "DEFAULT_OBJECTIVES",
    "Explainer",
    "FlightRecorder",
    "HeadAttempt",
    "ServiceLoop",
    "SLObjective",
    "SLOEngine",
    "SLOStatus",
]
