"""Device cost attribution: where do the device milliseconds go?

Always-on-capable, cheap accounting per solver entry point and shape
bucket. The driver already measures wall time around every device
dispatch + readback (models/driver.py ``dt``) — this module books those
numbers into a thread-safe ledger keyed by ``(entry, bucket)`` so an
operator can answer, per executable shape:

* device wall seconds and dispatch counts (executable occupancy: which
  bucket rungs actually run, and for how long);
* padding waste per axis — real heads/W/K lanes vs the padded bucket,
  as a wasted-lane fraction.

Zero-cost when off: same module-flag idiom as ``utils.faults`` /
``obs.recorder`` — every call site in the driver / what-if engine is
guarded by ``if costs.ENABLED`` so the disabled hot path pays one
module-attribute read and allocates nothing (tests/test_costs.py pins
the guard discipline by scanning the source).

On-demand profiling: :func:`profile_start` / :func:`profile_stop` wrap
``jax.profiler`` behind a breaker-style guard (utils/breaker.py) so a
capture that wedges or raises can never take the admission loop with it
— after ``_PROFILE_BREAKER.threshold`` consecutive failures the
endpoints fast-fail until the backoff expires. Profiling is host-gated:
nothing in the hot path ever touches the profiler; captures start only
from an explicit operator request (``/profile/start`` on the visibility
server).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

from kueue_tpu.metrics import tracing
from kueue_tpu.utils.breaker import CircuitBreaker

ENABLED = False
_ledger: Optional["CostLedger"] = None


def enable() -> "CostLedger":
    """Switch cost accounting on (idempotent); returns the live ledger."""
    global ENABLED, _ledger
    if _ledger is None:
        _ledger = CostLedger()
    ENABLED = True
    return _ledger


def disable() -> None:
    global ENABLED
    ENABLED = False


def get() -> Optional["CostLedger"]:
    """The live ledger, or None when accounting is off."""
    return _ledger if ENABLED else None


def charge(entry: str, bucket: int, device_s: float,
           lanes: Optional[Dict[str, Tuple[int, int]]] = None) -> None:
    """Module-level charge shim for call sites (driver / what-if):
    no-ops safely if the flag was flipped without :func:`enable`."""
    led = get()
    if led is not None:
        led.charge(entry, bucket, device_s, lanes=lanes)


def charge_tenant(tenant: str, bucket: int, device_s: float,
                  lanes: Optional[Dict[str, Tuple[int, int]]] = None,
                  entry: str = "readplane") -> None:
    """Per-tenant attribution shim (read plane): books the tenant's
    share of a coalesced dispatch against an ``entry[tenant]`` cell, so
    ``/costs`` breaks read traffic down by who asked. Call sites guard
    with ``if costs.ENABLED:`` like every other charge site."""
    charge(f"{entry}[{tenant}]", bucket, device_s, lanes=lanes)


@dataclass
class CostCell:
    """Accumulated cost for one (entry point, bucket rung)."""

    entry: str
    bucket: int
    dispatches: int = 0
    device_seconds: float = 0.0
    # Padding accounting per axis: axis -> (sum real lanes, sum padded
    # lanes) across dispatches. waste = 1 - real/padded.
    lanes: Dict[str, Tuple[int, int]] = field(default_factory=dict)

    def to_dict(self) -> dict:
        waste = {
            axis: round(1.0 - (real / padded), 6) if padded else 0.0
            for axis, (real, padded) in sorted(self.lanes.items())
        }
        return {
            "entry": self.entry,
            "bucket": self.bucket,
            "dispatches": self.dispatches,
            "device_seconds": self.device_seconds,
            "lanes": {a: list(v) for a, v in sorted(self.lanes.items())},
            "padding_waste": waste,
        }


class CostLedger:
    """Thread-safe accumulator of :class:`CostCell` per (entry, bucket)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._cells: Dict[Tuple[str, int], CostCell] = {}

    def charge(
        self,
        entry: str,
        bucket: int,
        device_s: float,
        lanes: Optional[Dict[str, Tuple[int, int]]] = None,
    ) -> None:
        """Book one dispatch: ``device_s`` wall seconds against the
        ``(entry, bucket)`` cell, plus per-axis (real, padded) lane
        counts. Call sites pass the same wall time they add to their own
        timing totals, so attribution sums reconcile against them."""
        with self._lock:
            cell = self._cells.get((entry, bucket))
            if cell is None:
                cell = self._cells[(entry, bucket)] = CostCell(
                    entry=entry, bucket=int(bucket)
                )
            cell.dispatches += 1
            cell.device_seconds += device_s
            for axis, (real, padded) in (lanes or {}).items():
                r0, p0 = cell.lanes.get(axis, (0, 0))
                cell.lanes[axis] = (r0 + int(real), p0 + int(padded))
        if tracing.ENABLED:
            lab = {"entry": entry, "bucket": str(int(bucket))}
            tracing.inc("solver_cost_dispatch_total", lab)
            tracing.inc("solver_cost_device_seconds_total", lab,
                        value=device_s)
            for axis, (real, padded) in (lanes or {}).items():
                if padded:
                    tracing.set_gauge(
                        "padding_waste_lane_fraction",
                        1.0 - (real / padded),
                        {"entry": entry, "axis": axis},
                    )

    # -- queries ---------------------------------------------------------

    def cells(self) -> Dict[Tuple[str, int], CostCell]:
        """Deep-copied snapshot. The live :class:`CostCell` objects are
        mutated in place by :meth:`charge` (including ``lanes`` dict
        growth), so handing out the shared instances would let a reader
        iterate a dict mid-resize or see dispatches/device_seconds from
        two different instants. Copies are cheap: cell count is bounded
        by (entry points × bucket rungs)."""
        with self._lock:
            return {
                k: CostCell(
                    entry=c.entry, bucket=c.bucket,
                    dispatches=c.dispatches,
                    device_seconds=c.device_seconds,
                    lanes=dict(c.lanes),
                )
                for k, c in self._cells.items()
            }

    def total_device_seconds(self, entry: Optional[str] = None) -> float:
        with self._lock:
            return sum(
                c.device_seconds for c in self._cells.values()
                if entry is None or c.entry == entry
            )

    def total_dispatches(self, entry: Optional[str] = None) -> int:
        with self._lock:
            return sum(
                c.dispatches for c in self._cells.values()
                if entry is None or c.entry == entry
            )

    def waste_fraction(self, entry: str, axis: str) -> Optional[float]:
        """Cumulative wasted-lane fraction for one entry point + axis,
        aggregated across buckets; None when nothing was booked."""
        real = padded = 0
        with self._lock:
            for c in self._cells.values():
                if c.entry != entry or axis not in c.lanes:
                    continue
                r, p = c.lanes[axis]
                real += r
                padded += p
        if padded == 0:
            return None
        return 1.0 - (real / padded)

    def snapshot(self) -> dict:
        """JSON-ready document: per-cell detail plus entry-level totals
        (the ``/costs`` endpoint body)."""
        cells = self.cells()
        by_entry: Dict[str, dict] = {}
        for c in cells.values():
            agg = by_entry.setdefault(c.entry, {
                "dispatches": 0, "device_seconds": 0.0, "buckets": [],
            })
            agg["dispatches"] += c.dispatches
            agg["device_seconds"] += c.device_seconds
            agg["buckets"].append(c.bucket)
        for agg in by_entry.values():
            agg["buckets"] = sorted(set(agg["buckets"]))
            agg["device_seconds"] = round(agg["device_seconds"], 6)
        return {
            "entries": {k: by_entry[k] for k in sorted(by_entry)},
            "cells": [
                cells[k].to_dict() for k in sorted(cells)
            ],
            "total_device_seconds": round(
                sum(c.device_seconds for c in cells.values()), 6
            ),
        }

    def clear(self) -> None:
        with self._lock:
            self._cells.clear()


# ----------------------------------------------------------------------
# On-demand jax.profiler capture (host-gated; /profile/start|stop)
# ----------------------------------------------------------------------

#: 0 = idle, 1 = capturing, 2 = last capture failed, 3 = breaker open.
PROFILE_IDLE, PROFILE_ACTIVE, PROFILE_FAILED, PROFILE_BROKEN = 0, 1, 2, 3

_profile_lock = threading.Lock()
_profile_state = PROFILE_IDLE
_profile_dir: Optional[str] = None
_profile_started_at: Optional[float] = None
# Breaker-style guard: a profiler backend that keeps raising (or a capture
# left dangling by a crash) trips after `threshold` consecutive failures
# and the endpoints fast-fail during the backoff window instead of
# re-poking a wedged profiler from the serving thread.
_PROFILE_BREAKER = CircuitBreaker(threshold=2, backoff_s=30.0,
                                  max_backoff_s=300.0)


def profile_status() -> dict:
    from kueue_tpu.utils.breaker import OPEN

    with _profile_lock:
        return {
            "state": _profile_state,
            "active": _profile_state == PROFILE_ACTIVE,
            "dir": _profile_dir,
            "started_at": _profile_started_at,
            "breaker_open": _PROFILE_BREAKER.state == OPEN,
        }


def profile_start(log_dir: str) -> dict:
    """Start a ``jax.profiler`` trace into ``log_dir``. Contained: any
    profiler failure is recorded against the breaker and reported as an
    error document — it never propagates into the serving thread."""
    global _profile_state, _profile_dir, _profile_started_at
    with _profile_lock:
        if _profile_state == PROFILE_ACTIVE:
            return {"ok": False, "error": "capture already active",
                    "dir": _profile_dir}
        if not _PROFILE_BREAKER.allow():
            _profile_state = PROFILE_BROKEN
            _emit_profile_metric("breaker_open")
            return {"ok": False, "error": "profiler breaker open "
                    f"(retry in {_PROFILE_BREAKER.last_backoff_s:.0f}s)"}
        try:
            import jax

            jax.profiler.start_trace(log_dir)
        except Exception as exc:  # noqa: BLE001 - contained by design
            _PROFILE_BREAKER.record_failure()
            _profile_state = PROFILE_FAILED
            _emit_profile_metric("error")
            return {"ok": False, "error": f"{type(exc).__name__}: {exc}"}
        _PROFILE_BREAKER.record_success()
        _profile_state = PROFILE_ACTIVE
        _profile_dir = log_dir
        _profile_started_at = time.time()
        _emit_profile_metric("start")
        return {"ok": True, "dir": log_dir}


def profile_stop() -> dict:
    """Stop the active capture; contained like :func:`profile_start`."""
    global _profile_state, _profile_dir, _profile_started_at
    with _profile_lock:
        if _profile_state != PROFILE_ACTIVE:
            return {"ok": False, "error": "no active capture"}
        dir_ = _profile_dir
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception as exc:  # noqa: BLE001 - contained by design
            _PROFILE_BREAKER.record_failure()
            _profile_state = PROFILE_FAILED
            _profile_dir = None
            _profile_started_at = None
            _emit_profile_metric("error")
            return {"ok": False, "error": f"{type(exc).__name__}: {exc}"}
        _profile_state = PROFILE_IDLE
        _profile_dir = None
        _profile_started_at = None
        _emit_profile_metric("stop")
        return {"ok": True, "dir": dir_}


def _emit_profile_metric(event: str) -> None:
    if tracing.ENABLED:
        tracing.inc("profile_captures_total", {"event": event})
        tracing.set_gauge("profile_state", float(_profile_state))
