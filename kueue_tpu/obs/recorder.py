"""Cycle flight recorder: bounded ring of structured per-cycle records.

Captured in models/driver.py after readback — generation fingerprints,
bucket + padding shape, per-stage wall times, fallback/breaker state, and
the decoded per-head outcomes (admitted flavor, inadmissible reason code,
preemption victims with strategy reasons). Capture cost is O(heads) host
work over planes the apply loop already read back — no extra device syncs.

Zero-cost when off: this module follows the same module-flag idiom as
``kueue_tpu.utils.faults`` / ``kueue_tpu.metrics.tracing`` — every call
site in the driver is guarded by ``if flight.ENABLED`` so the disabled hot
path executes no recorder code and allocates nothing
(tests/test_obs.py pins the guard discipline by scanning the source).
"""

from __future__ import annotations

import json
import os
import threading
from collections import deque
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional, Tuple

from kueue_tpu.metrics import tracing
from kueue_tpu.obs import reasons

ENABLED = False
_recorder: Optional["FlightRecorder"] = None


def enable(capacity: int = 256) -> "FlightRecorder":
    """Switch recording on (idempotent); returns the live recorder."""
    global ENABLED, _recorder
    if _recorder is None or _recorder.capacity != capacity:
        _recorder = FlightRecorder(capacity=capacity)
    ENABLED = True
    return _recorder


def disable() -> None:
    global ENABLED
    ENABLED = False


def get() -> Optional["FlightRecorder"]:
    """The live recorder, or None when recording is off."""
    return _recorder if ENABLED else None


@dataclass
class HeadAttempt:
    """One workload's outcome in one cycle."""

    key: str
    outcome: str             # symbolic code (obs/reasons.py)
    condition: str           # workload condition the outcome drives
    condition_reason: str    # kueue-style condition reason
    path: str                # "device" | "host"
    requeue_reason: Optional[str] = None
    flavor: Optional[str] = None
    # Preemptor side: designated victims as (key, strategy_reason).
    victims: List[Tuple[str, str]] = field(default_factory=list)
    # Victim side: the strategy reason this eviction was issued under.
    eviction_reason: Optional[str] = None


@dataclass
class CycleRecord:
    """One admission cycle's provenance record."""

    cycle: int
    ts: float
    path: str                # "device" | "fallback" | "breaker_open" | ...
    heads: int
    bucket: int              # W padding bucket (0 = no device dispatch)
    generation: int          # cache quota/topology generation
    workload_generation: int
    arena: bool
    breaker_state: float
    fallback_reason: Optional[str] = None
    # Device kernel entry that decided the cycle ("cycle_grouped_preempt",
    # "cycle_fixedpoint", "cycle_fixedpoint_hybrid", "cycle_fair_preempt");
    # "" when no device readback applied (host / contained / fallback).
    kernel: str = ""
    encode_s: float = 0.0
    dispatch_s: float = 0.0
    readback_s: float = 0.0
    overlap_host_s: float = 0.0
    duration_s: float = 0.0
    attempts: List[HeadAttempt] = field(default_factory=list)

    def to_dict(self) -> dict:
        return asdict(self)


class FlightRecorder:
    """Thread-safe bounded ring of :class:`CycleRecord`."""

    def __init__(self, capacity: int = 256) -> None:
        self.capacity = int(capacity)
        self._ring: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()

    def record(self, rec: CycleRecord) -> None:
        with self._lock:
            self._ring.append(rec)
        if tracing.ENABLED:
            tracing.inc("obs_recorder_cycles_total", {"path": rec.path})

    def records(self) -> List[CycleRecord]:
        with self._lock:
            return list(self._ring)

    def last(self) -> Optional[CycleRecord]:
        with self._lock:
            return self._ring[-1] if self._ring else None

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    # -- provenance queries (explain API) ------------------------------

    def attempts_for(self, key: str, limit: int = 20) -> List[dict]:
        """The workload's attempt history, oldest first, newest last —
        each entry is the per-head outcome dict plus its cycle number."""
        out: List[dict] = []
        for rec in self.records():
            for att in rec.attempts:
                if att.key != key:
                    continue
                d = asdict(att)
                d["cycle"] = rec.cycle
                d["ts"] = rec.ts
                d["kernel"] = rec.kernel
                out.append(d)
        return out[-limit:]

    def evictions_for(self, key: str, limit: int = 20) -> List[dict]:
        """Cycles in which this workload was evicted as a preemption
        victim (outcome "Preempted"), with the strategy reason and — when
        decoded on device — the preemptor that claimed it."""
        out: List[dict] = []
        for rec in self.records():
            # One entry per cycle: the victim-side Preempted attempt
            # wins (it carries the decoded eviction reason); the
            # preemptor's victims list only stands in when the cycle has
            # no direct row for this key. Either way the preemptor, when
            # known, is joined in.
            direct: Optional[dict] = None
            by_victims: Optional[dict] = None
            for att in rec.attempts:
                if att.key == key and att.outcome == "Preempted":
                    direct = asdict(att)
                    direct["cycle"] = rec.cycle
                    direct["ts"] = rec.ts
                    continue
                for vkey, vreason in att.victims:
                    if vkey != key or by_victims is not None:
                        continue
                    by_victims = {
                        "key": key, "cycle": rec.cycle, "ts": rec.ts,
                        "outcome": "Preempted",
                        "condition": reasons.VICTIM_OUTCOME.condition,
                        "condition_reason":
                            reasons.VICTIM_OUTCOME.condition_reason,
                        "eviction_reason": vreason,
                        "preempted_by": att.key,
                        "path": att.path,
                    }
            if direct is not None:
                if by_victims is not None:
                    direct.setdefault(
                        "preempted_by", by_victims["preempted_by"]
                    )
                    if direct.get("eviction_reason") is None:
                        direct["eviction_reason"] = \
                            by_victims["eviction_reason"]
                out.append(direct)
            elif by_victims is not None:
                out.append(by_victims)
        return out[-limit:]

    # -- offline replay -------------------------------------------------

    def dumps_jsonl(self) -> str:
        return "\n".join(
            json.dumps(rec.to_dict()) for rec in self.records()
        )

    def export_jsonl(self, path: str) -> int:
        """Write one JSON object per cycle record; returns record count.

        Crash-consistent: the export lands in a temp file first and is
        fsync'd before an atomic rename, so a kill mid-export leaves
        either the previous file or the complete new one — never a
        half-written line that poisons later readers."""
        recs = self.records()
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            for rec in recs:
                f.write(json.dumps(rec.to_dict()))
                f.write("\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return len(recs)


# ----------------------------------------------------------------------
# capture (called from models/driver.py under ``if flight.ENABLED``)
# ----------------------------------------------------------------------


def capture_cycle(
    *,
    cycle: int,
    ts: float,
    heads: int,
    bucket: int,
    path: str,
    generations: Tuple[int, int],
    arena: bool,
    breaker_state: float,
    result,
    fallback_reason: Optional[str] = None,
    timings: Optional[Dict[str, float]] = None,
    duration_s: float = 0.0,
    idx=None,
    planes=None,
    kernel: str = "",
) -> None:
    """Build and append one CycleRecord from state the cycle already has
    in hand. ``planes`` is the driver's _read_planes tuple (or None when
    the cycle never read back); ``result`` is the cycle's CycleResult
    with host outcomes already merged."""
    rec_to = get()
    if rec_to is None:
        return
    t = timings or {}
    rec = CycleRecord(
        cycle=cycle, ts=ts, path=path, heads=heads, bucket=bucket,
        generation=generations[0], workload_generation=generations[1],
        arena=arena, breaker_state=breaker_state,
        fallback_reason=fallback_reason,
        kernel=kernel,
        encode_s=t.get("encode_s", 0.0),
        dispatch_s=t.get("dispatch_s", 0.0),
        readback_s=t.get("readback_s", 0.0),
        overlap_host_s=t.get("overlap_host_s", 0.0),
        duration_s=duration_s,
    )
    rec.attempts = _decode_attempts(result, idx, planes)
    rec_to.record(rec)


def _device_rows(idx, planes):
    """Per-key device decode: key -> (code, flavor, victims, NeedsHost?).
    Victim map: victim key -> (preemptor key, strategy reason)."""
    rows: Dict[str, Tuple[int, Optional[str], List[Tuple[str, str]]]] = {}
    victim_map: Dict[str, Tuple[str, str]] = {}
    if idx is None or planes is None:
        return rows, victim_map
    import numpy as np

    outcome, chosen = planes[0], planes[1]
    victims, variants = planes[7], planes[8]
    for i, info in enumerate(idx.workloads):
        code = int(outcome[i])
        flavor = None
        vlist: List[Tuple[str, str]] = []
        if code == reasons.OUT_ADMITTED:
            ci = int(chosen[i])
            if 0 <= ci < len(idx.flavors):
                flavor = idx.flavors[ci]
        elif code == reasons.OUT_PREEMPTING and victims is not None:
            for a in np.flatnonzero(victims[i]):
                vkey = idx.admitted[a].key
                vreason = reasons.VICTIM_VARIANT_REASONS.get(
                    int(variants[i][a]) if variants is not None else 0,
                    reasons.VICTIM_VARIANT_REASONS[2],
                )
                vlist.append((vkey, vreason))
                victim_map[vkey] = (info.key, vreason)
        rows[info.key] = (code, flavor, vlist)
    return rows, victim_map


# CycleResult category -> the device outcome code consistent with it. A
# device row whose decoded code disagrees with where the key actually
# landed was discarded (fallback tree / NeedsHost) and host-reprocessed,
# so its provenance is attributed to the host path.
_CATEGORY_CODES = {
    "admitted": (reasons.OUT_ADMITTED,),
    "preempting": (reasons.OUT_PREEMPTING,),
    "skipped": (
        reasons.OUT_NOFIT,
        reasons.OUT_NO_CANDIDATES,
        reasons.OUT_FIT_SKIPPED,
        reasons.OUT_SHADOWED,
    ),
    "inadmissible": (),
    "preempted": (),
}


def _decode_attempts(result, idx, planes) -> List[HeadAttempt]:
    rows, victim_map = _device_rows(idx, planes)
    attempts: List[HeadAttempt] = []
    seen = set()
    for category in (
        "admitted", "preempting", "skipped", "inadmissible", "preempted"
    ):
        for key in getattr(result, category):
            if key in seen:
                continue
            seen.add(key)
            dev = rows.get(key)
            if category == "preempted":
                preemptor = victim_map.get(key)
                attempts.append(HeadAttempt(
                    key=key,
                    outcome=reasons.VICTIM_OUTCOME.name,
                    condition=reasons.VICTIM_OUTCOME.condition,
                    condition_reason=(
                        reasons.VICTIM_OUTCOME.condition_reason
                    ),
                    path="device" if preemptor is not None else "host",
                    eviction_reason=(
                        preemptor[1] if preemptor is not None else None
                    ),
                ))
                continue
            on_device = dev is not None and dev[0] in \
                _CATEGORY_CODES[category]
            if on_device:
                info = reasons.DEVICE_OUTCOMES[dev[0]]
                attempts.append(HeadAttempt(
                    key=key, outcome=info.name, condition=info.condition,
                    condition_reason=info.condition_reason, path="device",
                    requeue_reason=info.requeue_reason,
                    flavor=dev[1], victims=dev[2],
                ))
            else:
                # Routed through the host pipeline — either no device row
                # at all (encode fallback, breaker, contained cycle) or a
                # device row whose tree was discarded. Record the
                # NeedsHost hand-off when the device explicitly deferred.
                if dev is not None and \
                        dev[0] == reasons.OUT_NEEDS_HOST:
                    ninfo = reasons.DEVICE_OUTCOMES[
                        reasons.OUT_NEEDS_HOST
                    ]
                    attempts.append(HeadAttempt(
                        key=key, outcome=ninfo.name,
                        condition=ninfo.condition,
                        condition_reason=ninfo.condition_reason,
                        path="device",
                    ))
                info = reasons.HOST_OUTCOMES[category]
                attempts.append(HeadAttempt(
                    key=key, outcome=info.name, condition=info.condition,
                    condition_reason=info.condition_reason, path="host",
                    requeue_reason=info.requeue_reason,
                ))
    return attempts
