"""Burn-rate SLOs over the existing metric histograms.

Declarative objectives evaluated on rolling windows: the engine snapshots
the raw counter/histogram-bucket totals at every ``evaluate()`` call and
diffs the current totals against the oldest snapshot inside the window,
so only traffic *within* the window counts against the budget. Two
objective kinds:

- ``latency``: fraction of histogram observations above ``threshold_s``
  must stay under ``budget`` (threshold is resolved against bucket upper
  bounds — observations in the bucket containing the threshold count as
  over-threshold, the conservative reading).
- ``ratio``: ``series`` (counter, summed over labels) divided by
  ``den_series`` must stay under ``budget``.

``burn_rate`` is the classic multi-window form: bad-fraction / budget.
1.0 means the error budget is being consumed exactly at the sustainable
rate; >1 means the objective is burning down. Evaluation exports the
``slo_*`` gauges (metrics/names.py OBS_SERIES) so ``/metrics`` scrapes
and the dashboard see the same numbers as the ``/slo`` endpoint.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from kueue_tpu.metrics.registry import Histogram, Metrics


@dataclass(frozen=True)
class SLObjective:
    name: str
    kind: str = "latency"        # "latency" | "ratio"
    series: str = ""             # histogram (latency) / numerator (ratio)
    den_series: str = ""         # ratio denominator counter
    threshold_s: float = 1.0     # latency objective threshold
    budget: float = 0.01         # allowed bad fraction over the window
    window_s: float = 300.0
    description: str = ""


DEFAULT_OBJECTIVES: Tuple[SLObjective, ...] = (
    SLObjective(
        name="cycle_latency",
        kind="latency",
        series="admission_attempt_duration_seconds",
        threshold_s=1.0,
        budget=0.01,
        description="p99 admission-cycle latency: <1% of cycles over 1s",
    ),
    SLObjective(
        name="admission_wait",
        kind="latency",
        series="admission_wait_time_seconds",
        threshold_s=300.0,
        budget=0.05,
        description="admission wait: <5% of workloads wait over 5min",
    ),
    SLObjective(
        name="fallback_cycles",
        kind="ratio",
        series="solver_fallback_cycles_total",
        den_series="admission_attempts_total",
        budget=0.01,
        description="device-solver error budget: <1% contained fallbacks",
    ),
)


# Read-plane serving objectives (readplane/): opted in by
# Manager.readplane() via :meth:`SLOEngine.add_objectives` so
# deployments without a read plane don't evaluate dead series.
READPLANE_OBJECTIVES: Tuple[SLObjective, ...] = (
    SLObjective(
        name="readplane_query_latency",
        kind="latency",
        series="readplane_query_seconds",
        threshold_s=2.0,
        budget=0.05,
        description="read-plane query latency: <5% of queries over 2s",
    ),
    SLObjective(
        name="readplane_staleness",
        kind="latency",
        series="readplane_snapshot_staleness_seconds",
        threshold_s=5.0,
        budget=0.05,
        description="snapshot staleness at dispatch: <5% of batches "
                    "read a snapshot older than 5s",
    ),
)


@dataclass
class SLOStatus:
    name: str
    kind: str
    window_s: float
    budget: float
    samples: int = 0
    bad: int = 0
    bad_fraction: float = 0.0
    burn_rate: float = 0.0
    budget_remaining: float = 1.0
    healthy: bool = True
    # latency objectives: windowed quantiles; ratio: the windowed ratio.
    value: float = 0.0
    p50: Optional[float] = None
    p99: Optional[float] = None
    description: str = ""

    def to_dict(self) -> dict:
        return {
            "name": self.name, "kind": self.kind,
            "windowS": self.window_s, "budget": self.budget,
            "samples": self.samples, "bad": self.bad,
            "badFraction": self.bad_fraction,
            "burnRate": self.burn_rate,
            "budgetRemaining": self.budget_remaining,
            "healthy": self.healthy, "value": self.value,
            "p50": self.p50, "p99": self.p99,
            "description": self.description,
        }


# Raw per-objective snapshot payloads:
#   latency -> (buckets_tuple, counts_list, n)
#   ratio   -> (numerator, denominator)
_Raw = Tuple


class SLOEngine:
    """Evaluates objectives over a Metrics registry and exports gauges."""

    def __init__(
        self,
        metrics: Metrics,
        objectives: Optional[Sequence[SLObjective]] = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.metrics = metrics
        self.objectives: List[SLObjective] = list(
            objectives if objectives is not None else DEFAULT_OBJECTIVES
        )
        self._clock = clock
        # (t, {objective name: raw totals}) — cumulative, diffed per call.
        self._snaps: deque = deque()
        # evaluate() is now called from both the service-loop telemetry
        # thread (continuous burn) and /slo request handlers; the snapshot
        # deque diff/append must be atomic per evaluation.
        self._eval_lock = threading.Lock()
        self.last_statuses: List[SLOStatus] = []

    def add_objectives(self, objectives: Sequence[SLObjective]) -> None:
        """Register extra objectives (e.g. the read plane's) after
        construction. Dedupes by name so repeated wiring is idempotent."""
        with self._eval_lock:
            have = {o.name for o in self.objectives}
            for o in objectives:
                if o.name not in have:
                    self.objectives.append(o)
                    have.add(o.name)

    # -- raw totals -----------------------------------------------------

    def _hist_totals(self, series: str):
        return self.metrics.histogram_totals(series)

    def _counter_total(self, series: str) -> float:
        return self.metrics.counter_total(series)

    def _raw(self, obj: SLObjective) -> _Raw:
        if obj.kind == "latency":
            return self._hist_totals(obj.series)
        return (
            self._counter_total(obj.series),
            self._counter_total(obj.den_series),
        )

    # -- evaluation -----------------------------------------------------

    def evaluate(self) -> List[SLOStatus]:
        with self._eval_lock:
            now = self._clock()
            current = {o.name: self._raw(o) for o in self.objectives}
            baseline = self._baseline(now)
            statuses = [
                self._status(o, baseline.get(o.name), current[o.name])
                for o in self.objectives
            ]
            self._snaps.append((now, current))
            self._trim(now)
            self._export(statuses)
            self.last_statuses = statuses
            return statuses

    def _baseline(self, now: float) -> Dict[str, _Raw]:
        """Oldest snapshot still inside the widest objective window; with
        no history yet, the diff is against zero (process start)."""
        max_window = max(
            (o.window_s for o in self.objectives), default=300.0
        )
        chosen: Dict[str, _Raw] = {}
        for t, snap in self._snaps:
            if now - t <= max_window:
                return chosen or snap
            chosen = snap
        return chosen

    def _trim(self, now: float) -> None:
        max_window = max(
            (o.window_s for o in self.objectives), default=300.0
        )
        # Keep one snapshot older than the window as the diff baseline.
        while len(self._snaps) >= 2 and \
                now - self._snaps[1][0] > max_window:
            self._snaps.popleft()

    def _status(self, obj: SLObjective, base: Optional[_Raw],
                cur: _Raw) -> SLOStatus:
        st = SLOStatus(
            name=obj.name, kind=obj.kind, window_s=obj.window_s,
            budget=obj.budget, description=obj.description,
        )
        if obj.kind == "latency":
            buckets, counts, n = cur
            if base is not None and base[0] == buckets:
                counts = [c - b for c, b in zip(counts, base[1])]
                n = n - base[2]
            if n <= 0 or not buckets:
                return st
            # Observations strictly under the threshold bucket are good;
            # the bucket containing the threshold counts as bad.
            good = sum(
                c for ub, c in zip(buckets, counts) if ub <= obj.threshold_s
            )
            bad = max(0, n - good)
            h = Histogram(buckets=buckets)
            h.counts = list(counts) + [0] * (
                len(buckets) + 1 - len(counts)
            )
            h.n = n
            st.p50 = h.quantile(0.50)
            st.p99 = h.quantile(0.99)
            st.value = st.p99
            st.samples, st.bad = n, bad
            st.bad_fraction = bad / n
        else:
            num, den = cur
            if base is not None:
                num, den = num - base[0], den - base[1]
            if den <= 0:
                return st
            st.samples, st.bad = int(den), int(num)
            st.bad_fraction = num / den
            st.value = st.bad_fraction
        st.burn_rate = (
            st.bad_fraction / obj.budget if obj.budget > 0 else 0.0
        )
        st.budget_remaining = 1.0 - st.burn_rate
        st.healthy = st.burn_rate <= 1.0
        return st

    def _export(self, statuses: List[SLOStatus]) -> None:
        for st in statuses:
            labels = {"slo": st.name}
            self.metrics.set_gauge("slo_burn_rate", st.burn_rate, labels)
            self.metrics.set_gauge(
                "slo_budget_remaining", st.budget_remaining, labels
            )
            self.metrics.set_gauge("slo_objective_value", st.value, labels)
            self.metrics.set_gauge(
                "slo_healthy", 1.0 if st.healthy else 0.0, labels
            )

    # -- reporting ------------------------------------------------------

    def to_doc(self) -> dict:
        """The ``/slo`` endpoint body (evaluates first)."""
        statuses = self.evaluate()
        return {
            "evaluatedAt": self._clock(),
            "objectives": [st.to_dict() for st in statuses],
            "healthy": all(st.healthy for st in statuses),
        }
