"""Explain API: why is this workload (not) running?

Joins three sources into one answer per workload:

- live status (conditions, admission, queue position) from the cache and
  queue manager;
- what happened: the flight recorder's attempt history — per-cycle
  outcome codes mapped to kueue-style condition reasons
  (QuotaReserved / Preempted / InCohortReclamation / ...);
- what will happen: the what-if engine's forward forecast (admission
  ETA, flavor, queue position) plus, on request, a preemption preview
  (candidate victims), and a blocking-quota readout computed from the
  live snapshot headroom.

Served as ``/explain/<workload>`` on the visibility server and as
``cli explain``. Every side lookup is contained: a missing recorder,
a faulted forecast, or a blocked quota probe degrade that one section
to ``None`` with a reason — never the whole answer.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

from kueue_tpu.api.constants import COND_EVICTED


class Explainer:
    """Facade over (cache, queues) plus optional recorder/what-if hooks.

    ``recorder_fn`` / ``whatif_fn`` are zero-arg callables resolved at
    explain time (the recorder may be enabled after construction; the
    manager builds its what-if engine lazily)."""

    def __init__(
        self,
        cache,
        queues,
        workloads: Optional[Dict] = None,
        recorder_fn: Optional[Callable[[], object]] = None,
        whatif_fn: Optional[Callable[[], object]] = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.cache = cache
        self.queues = queues
        self.workloads = workloads if workloads is not None else {}
        self._recorder_fn = recorder_fn or (lambda: None)
        self._whatif_fn = whatif_fn or (lambda: None)
        self._clock = clock

    # -- lookup ---------------------------------------------------------

    def _resolve(self, name: str):
        """Find the workload by full key ("ns/name") or bare name; returns
        (key, Workload-or-None). Searches the manager's registry, admitted
        cache entries, and pending queue entries."""
        candidates = [name] if "/" in name else [f"default/{name}"]
        for key in candidates:
            wl = self.workloads.get(key)
            if wl is not None:
                return key, wl
            info = self.cache.workloads.get(key)
            if info is not None:
                return key, info.obj
        suffix = "/" + name
        for key, wl in self.workloads.items():
            if key.endswith(suffix):
                return key, wl
        for key, info in self.cache.workloads.items():
            if key.endswith(suffix):
                return key, info.obj
        for cq_name in list(self.queues.cluster_queues):
            for info in self._pending(cq_name):
                if info.key == name or info.key.endswith(suffix):
                    return info.key, info.obj
        return name if "/" in name else f"default/{name}", None

    def _pending(self, cq_name: str):
        """All pending entries — the active heap AND the BestEffortFIFO
        inadmissible staging area (a staged workload is still pending;
        it is the case explain answers for most often)."""
        return self.queues.pending_workloads_all(cq_name)

    def _pending_position(self, wl) -> Optional[Dict]:
        cq_name = self.queues.cluster_queue_for(wl)
        if not cq_name:
            return None
        key = f"{wl.namespace}/{wl.name}"
        for pos, info in enumerate(self._pending(cq_name)):
            if info.key == key:
                return {"clusterQueue": cq_name, "position": pos}
        return None

    # -- sections -------------------------------------------------------

    def _blocking_quota(self, wl, cq_name: str) -> List[Dict]:
        """Resources for which no flavor in the workload's CQ currently
        has headroom for the request — the quota standing between a
        pending workload and admission."""
        from kueue_tpu.core.resources import FlavorResource

        snapshot = self.cache.snapshot()
        cqs = snapshot.cluster_queues.get(cq_name)
        if cqs is None:
            return []
        totals: Dict[str, int] = {}
        for ps in wl.pod_sets:
            for res, v in ps.requests.items():
                totals[res] = totals.get(res, 0) + v * ps.count
        blockers: List[Dict] = []
        for res, req in sorted(totals.items()):
            best = None
            for rg in cqs.spec.resource_groups:
                if res not in rg.covered_resources:
                    continue
                for fq in rg.flavors:
                    if res not in fq.resources:
                        continue
                    avail = cqs.available(FlavorResource(fq.name, res))
                    if best is None or avail > best[1]:
                        best = (fq.name, avail)
            if best is not None and req > best[1]:
                blockers.append({
                    "resource": res, "requested": req,
                    "bestFlavor": best[0], "available": int(best[1]),
                })
        return blockers

    def _forecast(self, key: str, cq_name: Optional[str]) -> Dict:
        engine = self._whatif_fn()
        if engine is None:
            return {"forecast": None, "forecastReason": "whatif not attached"}
        try:
            report = engine.eta(cluster_queue=cq_name or None)
        except Exception as exc:  # contained: one section, not the answer
            return {
                "forecast": None,
                "forecastReason": f"{type(exc).__name__}: {exc}",
            }
        for wf in report.base.workloads:
            if wf.key == key:
                return {
                    "forecast": wf.to_dict(),
                    "forecastReason": report.reason or None,
                    "forecastBasis": report.basis,
                }
        return {
            "forecast": None,
            "forecastReason": "not in forecast horizon",
            "forecastBasis": report.basis,
        }

    def _preview(self, wl, cq_name: Optional[str]) -> Dict:
        engine = self._whatif_fn()
        if engine is None:
            return {"preview": None, "previewReason": "whatif not attached"}
        try:
            report = engine.preview(wl, cluster_queue=cq_name or None)
        except Exception as exc:
            return {
                "preview": None,
                "previewReason": f"{type(exc).__name__}: {exc}",
            }
        return {"preview": report.to_dict(), "previewReason": None}

    # -- public ---------------------------------------------------------

    def explain(
        self,
        name: str,
        include_forecast: bool = True,
        include_preview: bool = False,
        attempts_limit: int = 20,
    ) -> dict:
        key, wl = self._resolve(name)
        doc: dict = {
            "workload": key,
            "found": wl is not None,
            "explainedAt": self._clock(),
        }
        if wl is None:
            doc["error"] = "workload not found"
            return doc

        admitted = wl.status.admission is not None
        pending = self._pending_position(wl)
        cq_name = (
            wl.status.admission.cluster_queue if admitted
            else self.queues.cluster_queue_for(wl)
        )
        doc["clusterQueue"] = cq_name
        doc["localQueue"] = wl.queue_name
        doc["priority"] = wl.priority
        doc["conditions"] = [
            {
                "type": c.type, "status": c.status,
                "reason": c.reason, "message": c.message,
            }
            for c in wl.status.conditions
        ]
        evicted = next(
            (c for c in reversed(wl.status.conditions)
             if c.type == COND_EVICTED and c.status), None
        )
        if admitted:
            doc["state"] = "admitted"
            psas = wl.status.admission.pod_set_assignments
            doc["admission"] = {
                "clusterQueue": cq_name,
                "podSets": [
                    {"name": p.name, "count": p.count,
                     "flavors": dict(p.flavors)}
                    for p in psas
                ],
            }
        elif pending is not None:
            doc["state"] = "pending"
            doc["queuePosition"] = pending["position"]
        elif evicted is not None:
            doc["state"] = "evicted"
        else:
            doc["state"] = "unknown"
        if evicted is not None:
            doc["lastEviction"] = {
                "reason": evicted.reason, "message": evicted.message,
            }

        # What happened: the flight recorder's attempt + eviction history.
        rec = self._recorder_fn()
        if rec is not None:
            doc["attempts"] = rec.attempts_for(key, limit=attempts_limit)
            doc["evictions"] = rec.evictions_for(key, limit=attempts_limit)
        else:
            doc["attempts"] = None
            doc["attemptsReason"] = "flight recorder not enabled"

        # What will happen: forecast + blocking quota for pending entries.
        if not admitted:
            if include_forecast:
                doc.update(self._forecast(key, cq_name))
            if cq_name:
                try:
                    doc["blockingQuota"] = self._blocking_quota(wl, cq_name)
                except Exception as exc:
                    doc["blockingQuota"] = None
                    doc["blockingQuotaReason"] = (
                        f"{type(exc).__name__}: {exc}"
                    )
            if include_preview:
                doc.update(self._preview(wl, cq_name))
        return doc
