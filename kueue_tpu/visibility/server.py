"""Visibility API: on-demand pending-workload introspection.

Behavioral surface: reference pkg/visibility (extension API server) —
live pending-workloads summaries with queue positions from the heap order
(storage/pending_workloads_cq.go:63). Exposed as plain Python calls plus an
optional JSON/HTTP server for remote operators.
"""

from __future__ import annotations

import contextlib
import json
import threading
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional

from kueue_tpu.queue.manager import QueueManager


class ServiceUnavailable(RuntimeError):
    """A route's backing subsystem is not attached / not serving.
    ``_guarded`` maps this to a structured 503 with a machine-readable
    ``reason`` — never a 200-shaped error dict."""

    def __init__(self, reason: str) -> None:
        super().__init__(reason)
        self.reason = reason


@dataclass
class PendingWorkload:
    """reference apis/visibility/v1beta2/types.go:66."""

    name: str
    namespace: str
    local_queue: str
    priority: int
    position_in_cluster_queue: int
    position_in_local_queue: int


@dataclass
class PendingWorkloadsSummary:
    """reference apis/visibility types.go:87."""

    cluster_queue: str
    items: List[PendingWorkload] = field(default_factory=list)
    inadmissible: int = 0


class VisibilityServer:
    """reference pkg/visibility/server.go:82.

    With a :class:`kueue_tpu.whatif.WhatIfEngine` attached, also exposes
    the forecasting endpoints ``/whatif/eta`` and ``/whatif/preview``
    (docs/whatif.md) — the reference has no analog; forecasts come from
    the on-device counterfactual rollout.

    With an :class:`kueue_tpu.obs.Explainer` / ``SLOEngine`` attached
    (docs/observability.md), also serves ``/explain/<workload>`` and
    ``/slo``.

    With a :class:`kueue_tpu.obs.ServiceLoop` attached, also serves the
    liveness endpoints ``/healthz`` (503 once the loop is stalled or
    stopped) and ``/readyz`` (503 until the first iteration completes),
    plus ``/service`` (health + loop configuration). These read only
    the loop's lock-free heartbeat — a wedged loop holding the state
    lock still gets an honest 503. The service lock additionally
    serializes the state-traversing handlers (``/explain``,
    ``/whatif/*``) against live cycles, so concurrent scrapes never
    observe a half-applied admission."""

    def __init__(self, queues: QueueManager, whatif=None,
                 explainer=None, slo=None, metrics=None,
                 service=None, lock=None, readplane=None) -> None:
        self.queues = queues
        self.whatif = whatif
        # Optional ReadPlane (docs/whatif.md, "Multi-tenant read
        # plane"): serves /readplane/query, and the /whatif/* routes
        # run coalesced off the admission lock when attached.
        self.readplane = readplane
        self.explainer = explainer
        self.slo = slo
        # Optional Metrics registry: when attached, /metrics serves the
        # Prometheus text exposition and /metrics.json the JSON mirror.
        self.metrics = metrics
        # Optional ServiceLoop: /healthz, /readyz, /service.
        self.service = service
        # State lock shared with the admission loop (defaults to the
        # attached service's lock): handlers that traverse cache/queue
        # state take it so they run only at cycle boundaries.
        self.lock = lock if lock is not None else (
            service.lock if service is not None else None
        )

    def _state_lock(self):
        return self.lock if self.lock is not None \
            else contextlib.nullcontext()

    # -- cost attribution + profiling (docs/observability.md) -----------

    def costs_doc(self) -> Dict:
        from kueue_tpu.obs import costs

        led = costs.get()
        if led is None:
            return {"error": "cost accounting not enabled"}
        doc = led.snapshot()
        doc["profile"] = costs.profile_status()
        return doc

    def profile_start(self, log_dir: Optional[str] = None) -> Dict:
        from kueue_tpu.obs import costs

        if not log_dir:
            import tempfile

            log_dir = tempfile.mkdtemp(prefix="kueue-tpu-profile-")
        return costs.profile_start(log_dir)

    def profile_stop(self) -> Dict:
        from kueue_tpu.obs import costs

        return costs.profile_stop()

    def metrics_text(self) -> str:
        if self.metrics is None:
            raise KeyError("metrics registry not attached")
        return self.metrics.expose()

    def metrics_doc(self) -> Dict:
        if self.metrics is None:
            return {"error": "metrics registry not attached"}
        return self.metrics.to_doc()

    # -- observability (docs/observability.md) --------------------------

    def explain(self, name: str, include_forecast: bool = True,
                include_preview: bool = False) -> Dict:
        if self.explainer is None:
            return {"error": "explainer not attached"}
        with self._state_lock():
            return self.explainer.explain(
                name, include_forecast=include_forecast,
                include_preview=include_preview,
            )

    def slo_doc(self) -> Dict:
        if self.slo is None:
            return {"error": "slo engine not attached"}
        return self.slo.to_doc()

    def pending_workloads_cq(
        self, cq_name: str, offset: int = 0, limit: int = 1000
    ) -> PendingWorkloadsSummary:
        summary = PendingWorkloadsSummary(cluster_queue=cq_name)
        lq_pos: Dict[str, int] = {}
        infos = self.queues.pending_workloads(cq_name)
        for pos, info in enumerate(infos):
            lq = info.obj.queue_name
            lq_idx = lq_pos.get(lq, 0)
            lq_pos[lq] = lq_idx + 1
            if pos < offset or pos >= offset + limit:
                continue
            summary.items.append(
                PendingWorkload(
                    name=info.obj.name,
                    namespace=info.obj.namespace,
                    local_queue=lq,
                    priority=info.priority(),
                    position_in_cluster_queue=pos,
                    position_in_local_queue=lq_idx,
                )
            )
        cqh = self.queues.cluster_queues.get(cq_name)
        if cqh is not None:
            summary.inadmissible = len(cqh.inadmissible)
        return summary

    def pending_workloads_lq(
        self, lq_key: str, offset: int = 0, limit: int = 1000
    ) -> List[PendingWorkload]:
        lq = self.queues.local_queues.get(lq_key)
        if lq is None:
            return []
        summary = self.pending_workloads_cq(lq.cluster_queue)
        items = [
            w for w in summary.items
            if f"{w.namespace}/{w.local_queue}" == lq_key
        ]
        return items[offset:offset + limit]

    def local_queue_status(self, lq_key: str, cache=None) -> Dict:
        """LocalQueue status analog (reference localqueue_types.go:60):
        pending count, head, and admitted flavor usage when the cache is
        provided."""
        items = self.pending_workloads_lq(lq_key)
        out = {
            "local_queue": lq_key,
            "pending_workloads": len(items),
            "head": items[0].name if items else None,
        }
        if cache is not None:
            usage: Dict[str, int] = {}
            admitted = 0
            for info in cache.workloads.values():
                key = f"{info.obj.namespace}/{info.obj.queue_name}"
                if key != lq_key:
                    continue
                admitted += 1
                for fr, v in info.usage().items():
                    label = f"{fr.flavor}/{fr.resource}"
                    usage[label] = usage.get(label, 0) + v
            out["admitted_workloads"] = admitted
            out["flavor_usage"] = usage
        return out

    def to_json(self, cq_name: str) -> str:
        return json.dumps(asdict(self.pending_workloads_cq(cq_name)))

    # -- what-if forecasting (docs/whatif.md) ---------------------------

    def whatif_eta(self, cluster_queue: Optional[str] = None,
                   scenarios: Optional[List[Dict]] = None) -> Dict:
        """Per-pending-workload admission ETA + flavor forecast, plus any
        capacity-probe scenarios (JSON dicts, see _parse_scenario)."""
        scens = [self._parse_scenario(s) for s in (scenarios or [])]
        if self.readplane is not None:
            # Coalesced read path: no admission lock, answers come off
            # the pinned cycle-boundary snapshot.
            from kueue_tpu.readplane import eta_query

            return self.readplane.query(eta_query(
                cluster_queue=cluster_queue, scenarios=tuple(scens),
            ))
        if self.whatif is None:
            raise ServiceUnavailable("whatif_engine_not_attached")
        with self._state_lock():
            report = self.whatif.eta(
                scenarios=scens, cluster_queue=cluster_queue
            )
        return report.to_dict()

    def whatif_preview(self, spec: Dict) -> Dict:
        """Preemption preview for one hypothetical workload. ``spec``:
        {"name", "namespace"?, "queue"?, "clusterQueue"?, "priority"?,
        "count"?, "requests": {resource: canonical int}}."""
        wl = self._parse_workload(spec)
        if self.readplane is not None:
            from kueue_tpu.readplane import preview_query

            return self.readplane.query(preview_query(
                wl, cluster_queue=spec.get("clusterQueue"),
            ))
        if self.whatif is None:
            raise ServiceUnavailable("whatif_engine_not_attached")
        with self._state_lock():
            report = self.whatif.preview(
                wl, cluster_queue=spec.get("clusterQueue")
            )
        return report.to_dict()

    @staticmethod
    def _parse_workload(spec: Dict):
        from kueue_tpu.api.types import PodSet, Workload

        return Workload(
            name=spec.get("name", "whatif-preview"),
            namespace=spec.get("namespace", "default"),
            queue_name=spec.get("queue", ""),
            priority=int(spec.get("priority", 0)),
            pod_sets=[PodSet(
                name="main",
                count=int(spec.get("count", 1)),
                requests={
                    str(r): int(v)
                    for r, v in (spec.get("requests") or {}).items()
                },
            )],
        )

    # -- read plane (docs/whatif.md, "Multi-tenant read plane") ---------

    def readplane_doc(self) -> Dict:
        if self.readplane is None:
            raise ServiceUnavailable("readplane_not_attached")
        return self.readplane.to_doc()

    def readplane_query(self, payload: Dict) -> Dict:
        """Dispatch one read-plane query. ``payload``: {"kind": "eta" |
        "preview" | "sweep" | "drain_matrix" | "starve_search",
        "tenant"?, "timeoutS"?, plus per-kind fields — see
        readplane/queries.py constructor helpers}."""
        if self.readplane is None:
            raise ServiceUnavailable("readplane_not_attached")
        from kueue_tpu.readplane import (
            drain_matrix_query, eta_query, preview_query,
            starve_search_query, sweep_query,
        )

        kind = payload.get("kind")
        tenant = str(payload.get("tenant", "default"))
        if kind == "eta":
            q = eta_query(
                cluster_queue=payload.get("clusterQueue"),
                scenarios=tuple(
                    self._parse_scenario(s)
                    for s in payload.get("scenarios") or []
                ),
                tenant=tenant,
            )
        elif kind == "preview":
            q = preview_query(
                self._parse_workload(payload["workload"]),
                cluster_queue=payload.get("clusterQueue"),
                tenant=tenant,
            )
        elif kind == "sweep":
            q = sweep_query(
                payload["node"], payload["flavor"], payload["resource"],
                tuple(int(d) for d in payload["deltas"]),
                tenant=tenant,
            )
        elif kind == "drain_matrix":
            q = drain_matrix_query(
                tuple(payload["drainNodes"]), tenant=tenant,
            )
        elif kind == "starve_search":
            q = starve_search_query(
                payload["node"], payload["flavor"], payload["resource"],
                max_cut=int(payload["maxCut"]),
                points=int(payload.get("points", 4)),
                rounds=int(payload.get("rounds", 4)),
                tenant=tenant,
            )
        else:
            raise ValueError(f"unknown read-plane query kind {kind!r}")
        return self.readplane.query(
            q, timeout=float(payload.get("timeoutS", 30.0)))

    def _parse_scenario(self, s: Dict):
        from kueue_tpu.whatif.engine import QuotaDelta, Scenario

        deltas = tuple(
            QuotaDelta(
                node=d["node"], flavor=d["flavor"],
                resource=d["resource"], delta=int(d["delta"]),
            )
            for d in s.get("quotaDeltas", [])
        )
        workload = None
        if s.get("workload"):
            workload = self._parse_workload(s["workload"])
        kind = s.get("kind") or (
            "drain" if s.get("drainNode")
            else "submit" if workload is not None else "quota"
        )
        return Scenario(
            kind=kind, label=s.get("label", ""),
            quota_deltas=deltas, drain_node=s.get("drainNode"),
            workload=workload,
            cluster_queue=s.get("clusterQueue"),
        )

    def serve(self, host: str = "127.0.0.1", port: int = 8082):
        """Optional HTTP endpoints:
        GET  /visibility/clusterqueues/<name>/pendingworkloads
        GET  /whatif/eta[?cluster_queue=<name>]
        GET  /explain/<workload>[?forecast=0&preview=1]
        GET  /slo
        GET  /costs
        GET  /healthz          (200 healthy / 503 stalled or stopped)
        GET  /readyz           (200 after the first loop iteration)
        GET  /service          (loop health + configuration)
        GET  /metrics          (Prometheus text exposition)
        GET  /metrics.json     (same registry, JSON document)
        GET  /readplane        (publisher + coalescer status)
        POST /whatif/eta      {"clusterQueue"?: ..., "scenarios": [...]}
        POST /whatif/preview  {workload spec, see whatif_preview}
        POST /readplane/query {"kind": ..., see readplane_query}

        Routes whose backing subsystem is not attached return a
        structured 503 ``{"error": "service unavailable", "reason":
        ...}`` (machine-readable), never a 200-shaped error dict.
        POST /profile/start   {"logDir"?: ...}   (also GET, operator cURL)
        POST /profile/stop                        (also GET).

        Malformed requests (bad JSON, wrong field types, missing keys)
        return structured 400 JSON ``{"error": "bad request", ...}``;
        unknown paths and unknown workloads return structured 404 JSON;
        handler bugs return structured 500 JSON — a client never sees a
        hung connection or a bare HTML error page."""
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        from urllib.parse import parse_qs, urlparse

        server_self = self

        class Handler(BaseHTTPRequestHandler):
            def _send_json(self, obj, code=200):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.end_headers()
                self.wfile.write(body)

            def _read_body(self):
                n = int(self.headers.get("Content-Length") or 0)
                if n <= 0:
                    return {}
                return json.loads(self.rfile.read(n) or b"{}")

            def _send_text(self, body, ctype, code=200):
                body = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.end_headers()
                self.wfile.write(body)

            def _guarded(self, fn):
                """Run one route body; malformed input (the int()/[] /
                KeyError family a bad payload produces) becomes a
                structured 400, anything else a structured 500."""
                try:
                    fn()
                except ServiceUnavailable as exc:
                    self._send_json({
                        "error": "service unavailable",
                        "reason": exc.reason,
                    }, 503)
                except (KeyError, ValueError, TypeError,
                        AttributeError) as exc:
                    self._send_json({
                        "error": "bad request",
                        "detail": f"{type(exc).__name__}: {exc}",
                    }, 400)
                except Exception as exc:  # pragma: no cover - bug guard
                    self._send_json({
                        "error": "internal error",
                        "detail": f"{type(exc).__name__}: {exc}",
                    }, 500)

            def do_GET(self):  # noqa: N802
                url = urlparse(self.path)
                parts = url.path.strip("/").split("/")
                if (
                    len(parts) == 3
                    and parts[0] == "visibility"
                    and parts[1] == "clusterqueues"
                ) or (
                    len(parts) == 4
                    and parts[0] == "visibility"
                    and parts[1] == "clusterqueues"
                    and parts[3] == "pendingworkloads"
                ):
                    self._guarded(lambda: self._send_json(
                        json.loads(server_self.to_json(parts[2]))
                    ))
                elif parts == ["whatif", "eta"]:
                    q = parse_qs(url.query)
                    cq = (q.get("cluster_queue") or [None])[0]
                    self._guarded(lambda: self._send_json(
                        server_self.whatif_eta(cluster_queue=cq)
                    ))
                elif len(parts) >= 2 and parts[0] == "explain":
                    q = parse_qs(url.query)
                    name = "/".join(parts[1:])
                    fc = (q.get("forecast") or ["1"])[0] != "0"
                    pv = (q.get("preview") or ["0"])[0] == "1"

                    def _explain():
                        doc = server_self.explain(
                            name, include_forecast=fc, include_preview=pv
                        )
                        code = 404 if doc.get("found") is False else 200
                        self._send_json(doc, code)

                    self._guarded(_explain)
                elif parts == ["explain"]:
                    self._send_json({
                        "error": "bad request",
                        "detail": "usage: /explain/<workload>",
                    }, 400)
                elif parts == ["slo"]:
                    self._guarded(lambda: self._send_json(
                        server_self.slo_doc()
                    ))
                elif parts == ["healthz"] or parts == ["readyz"]:
                    # Deliberately lock-free: a stalled loop may be
                    # holding the state lock, and the probe must still
                    # answer with a 503 rather than hang.
                    svc = server_self.service
                    if svc is None:
                        self._send_json({
                            "error": "service loop not attached",
                        }, 404)
                    else:
                        def _probe():
                            h = svc.health()
                            key = (
                                "healthy" if parts == ["healthz"]
                                else "ready"
                            )
                            self._send_json(h, 200 if h[key] else 503)

                        self._guarded(_probe)
                elif parts == ["service"]:
                    svc = server_self.service
                    if svc is None:
                        self._send_json({
                            "error": "service loop not attached",
                        }, 404)
                    else:
                        self._guarded(lambda: self._send_json(
                            svc.to_doc()
                        ))
                elif parts == ["readplane"]:
                    self._guarded(lambda: self._send_json(
                        server_self.readplane_doc()
                    ))
                elif parts == ["costs"]:
                    self._guarded(lambda: self._send_json(
                        server_self.costs_doc()
                    ))
                elif parts == ["metrics"]:
                    if server_self.metrics is None:
                        self._send_json({
                            "error": "metrics registry not attached",
                        }, 404)
                    else:
                        # Prometheus text exposition format 0.0.4.
                        self._guarded(lambda: self._send_text(
                            server_self.metrics_text(),
                            "text/plain; version=0.0.4; charset=utf-8",
                        ))
                elif parts == ["metrics.json"]:
                    self._guarded(lambda: self._send_json(
                        server_self.metrics_doc()
                    ))
                elif parts == ["profile", "start"]:
                    q = parse_qs(url.query)
                    log_dir = (q.get("log_dir") or [None])[0]
                    self._guarded(lambda: self._send_json(
                        server_self.profile_start(log_dir)
                    ))
                elif parts == ["profile", "stop"]:
                    self._guarded(lambda: self._send_json(
                        server_self.profile_stop()
                    ))
                elif parts == ["profile", "status"]:
                    def _status():
                        from kueue_tpu.obs import costs

                        self._send_json(costs.profile_status())

                    self._guarded(_status)
                else:
                    self._send_json({
                        "error": "not found", "path": url.path,
                    }, 404)

            def do_POST(self):  # noqa: N802
                parts = urlparse(self.path).path.strip("/").split("/")
                try:
                    payload = self._read_body()
                except (ValueError, json.JSONDecodeError):
                    self._send_json({"error": "invalid JSON body"}, 400)
                    return
                if not isinstance(payload, dict):
                    self._send_json({
                        "error": "bad request",
                        "detail": "JSON body must be an object",
                    }, 400)
                    return
                if parts == ["whatif", "eta"]:
                    self._guarded(lambda: self._send_json(
                        server_self.whatif_eta(
                            cluster_queue=payload.get("clusterQueue"),
                            scenarios=payload.get("scenarios"),
                        )
                    ))
                elif parts == ["whatif", "preview"]:
                    self._guarded(lambda: self._send_json(
                        server_self.whatif_preview(payload)
                    ))
                elif parts == ["readplane", "query"]:
                    self._guarded(lambda: self._send_json(
                        server_self.readplane_query(payload)
                    ))
                elif parts == ["profile", "start"]:
                    self._guarded(lambda: self._send_json(
                        server_self.profile_start(payload.get("logDir"))
                    ))
                elif parts == ["profile", "stop"]:
                    self._guarded(lambda: self._send_json(
                        server_self.profile_stop()
                    ))
                else:
                    self._send_json({
                        "error": "not found", "path": self.path,
                    }, 404)

            def log_message(self, *a):  # quiet
                pass

        httpd = ThreadingHTTPServer((host, port), Handler)
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        return httpd
