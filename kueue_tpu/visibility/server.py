"""Visibility API: on-demand pending-workload introspection.

Behavioral surface: reference pkg/visibility (extension API server) —
live pending-workloads summaries with queue positions from the heap order
(storage/pending_workloads_cq.go:63). Exposed as plain Python calls plus an
optional JSON/HTTP server for remote operators.
"""

from __future__ import annotations

import json
import threading
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional

from kueue_tpu.queue.manager import QueueManager


@dataclass
class PendingWorkload:
    """reference apis/visibility/v1beta2/types.go:66."""

    name: str
    namespace: str
    local_queue: str
    priority: int
    position_in_cluster_queue: int
    position_in_local_queue: int


@dataclass
class PendingWorkloadsSummary:
    """reference apis/visibility types.go:87."""

    cluster_queue: str
    items: List[PendingWorkload] = field(default_factory=list)
    inadmissible: int = 0


class VisibilityServer:
    """reference pkg/visibility/server.go:82."""

    def __init__(self, queues: QueueManager) -> None:
        self.queues = queues

    def pending_workloads_cq(
        self, cq_name: str, offset: int = 0, limit: int = 1000
    ) -> PendingWorkloadsSummary:
        summary = PendingWorkloadsSummary(cluster_queue=cq_name)
        lq_pos: Dict[str, int] = {}
        infos = self.queues.pending_workloads(cq_name)
        for pos, info in enumerate(infos):
            lq = info.obj.queue_name
            lq_idx = lq_pos.get(lq, 0)
            lq_pos[lq] = lq_idx + 1
            if pos < offset or pos >= offset + limit:
                continue
            summary.items.append(
                PendingWorkload(
                    name=info.obj.name,
                    namespace=info.obj.namespace,
                    local_queue=lq,
                    priority=info.priority(),
                    position_in_cluster_queue=pos,
                    position_in_local_queue=lq_idx,
                )
            )
        cqh = self.queues.cluster_queues.get(cq_name)
        if cqh is not None:
            summary.inadmissible = len(cqh.inadmissible)
        return summary

    def pending_workloads_lq(
        self, lq_key: str, offset: int = 0, limit: int = 1000
    ) -> List[PendingWorkload]:
        lq = self.queues.local_queues.get(lq_key)
        if lq is None:
            return []
        summary = self.pending_workloads_cq(lq.cluster_queue)
        items = [
            w for w in summary.items
            if f"{w.namespace}/{w.local_queue}" == lq_key
        ]
        return items[offset:offset + limit]

    def local_queue_status(self, lq_key: str, cache=None) -> Dict:
        """LocalQueue status analog (reference localqueue_types.go:60):
        pending count, head, and admitted flavor usage when the cache is
        provided."""
        items = self.pending_workloads_lq(lq_key)
        out = {
            "local_queue": lq_key,
            "pending_workloads": len(items),
            "head": items[0].name if items else None,
        }
        if cache is not None:
            usage: Dict[str, int] = {}
            admitted = 0
            for info in cache.workloads.values():
                key = f"{info.obj.namespace}/{info.obj.queue_name}"
                if key != lq_key:
                    continue
                admitted += 1
                for fr, v in info.usage().items():
                    label = f"{fr.flavor}/{fr.resource}"
                    usage[label] = usage.get(label, 0) + v
            out["admitted_workloads"] = admitted
            out["flavor_usage"] = usage
        return out

    def to_json(self, cq_name: str) -> str:
        return json.dumps(asdict(self.pending_workloads_cq(cq_name)))

    def serve(self, host: str = "127.0.0.1", port: int = 8082):
        """Optional HTTP endpoint:
        GET /visibility/clusterqueues/<name>/pendingworkloads."""
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        server_self = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802
                parts = self.path.strip("/").split("/")
                if (
                    len(parts) == 3
                    and parts[0] == "visibility"
                    and parts[1] == "clusterqueues"
                ) or (
                    len(parts) == 4
                    and parts[0] == "visibility"
                    and parts[1] == "clusterqueues"
                    and parts[3] == "pendingworkloads"
                ):
                    body = server_self.to_json(parts[2]).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self.send_response(404)
                    self.end_headers()

            def log_message(self, *a):  # quiet
                pass

        httpd = ThreadingHTTPServer((host, port), Handler)
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        return httpd
