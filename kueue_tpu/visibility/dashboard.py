"""Web dashboard (kueueviz equivalent).

Behavioral surface: reference cmd/kueueviz — a live view of ClusterQueues,
pending/admitted workloads and quota usage. Single self-contained HTML page
polling the JSON API; serve with ``serve_dashboard(manager)`` or mount into
the visibility server.
"""

from __future__ import annotations

import json
import threading
from typing import Dict

from kueue_tpu.core.resources import FlavorResource
from kueue_tpu.core.workload_info import is_admitted

_PAGE = """<!DOCTYPE html>
<html><head><title>kueue_tpu</title><style>
body{font-family:monospace;margin:2em;background:#111;color:#ddd}
table{border-collapse:collapse;margin:1em 0}
td,th{border:1px solid #444;padding:4px 10px;text-align:left}
th{background:#222}.bar{background:#333;width:160px;height:12px}
.fill{background:#4a8;height:12px}h2{color:#8cf}
</style></head><body>
<h1>kueue_tpu dashboard</h1>
<div id="content">loading...</div>
<script>
async function refresh(){
  const r = await fetch('/api/state'); const s = await r.json();
  let h = '<h2>ClusterQueues</h2><table><tr><th>name</th><th>cohort</th>'+
    '<th>pending</th><th>admitted</th><th>usage</th></tr>';
  for (const cq of s.cluster_queues){
    h += `<tr><td>${cq.name}</td><td>${cq.cohort||''}</td>`+
      `<td>${cq.pending}</td><td>${cq.admitted}</td><td>`;
    for (const [res, u] of Object.entries(cq.usage)){
      const pct = Math.min(100, u.pct);
      h += `${res}: ${u.used}/${u.nominal} `+
        `<div class=bar><div class=fill style="width:${pct*1.6}px"></div></div>`;
    }
    h += '</td></tr>';
  }
  h += '</table><h2>Workloads</h2><table><tr><th>key</th><th>queue</th>'+
    '<th>priority</th><th>status</th></tr>';
  for (const w of s.workloads){
    h += `<tr><td>${w.key}</td><td>${w.queue}</td><td>${w.priority}</td>`+
      `<td>${w.status}</td></tr>`;
  }
  h += '</table>';
  document.getElementById('content').innerHTML = h;
}
refresh(); setInterval(refresh, 2000);
</script></body></html>"""


def state_json(manager) -> Dict:
    cqs = []
    for name, cq in sorted(manager.cache.cluster_queues.items()):
        usage: Dict[str, Dict] = {}
        nominal: Dict[str, int] = {}
        for rg in cq.resource_groups:
            for fq in rg.flavors:
                for res, q in fq.resources.items():
                    nominal[res] = nominal.get(res, 0) + q.nominal
        used: Dict[str, int] = {}
        for info in manager.cache.workloads.values():
            if info.cluster_queue != name:
                continue
            for fr, v in info.usage().items():
                used[fr.resource] = used.get(fr.resource, 0) + v
        for res, nom in nominal.items():
            u = used.get(res, 0)
            usage[res] = {
                "used": u, "nominal": nom,
                "pct": round(100.0 * u / nom, 1) if nom else 0.0,
            }
        cqs.append({
            "name": name,
            "cohort": cq.cohort,
            "pending": manager.queues.pending_count(name),
            "admitted": sum(
                1 for i in manager.cache.workloads.values()
                if i.cluster_queue == name
            ),
            "usage": usage,
        })
    wls = []
    for key, wl in sorted(manager.workloads.items()):
        wls.append({
            "key": key,
            "queue": wl.queue_name,
            "priority": wl.priority,
            "status": "Admitted" if is_admitted(wl) else "Pending",
        })
    return {"cluster_queues": cqs, "workloads": wls}


def serve_dashboard(manager, host: str = "127.0.0.1", port: int = 8081):
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802
            if self.path == "/api/state":
                body = json.dumps(state_json(manager)).encode()
                ctype = "application/json"
            elif self.path in ("/", "/index.html"):
                body = _PAGE.encode()
                ctype = "text/html"
            else:
                self.send_response(404)
                self.end_headers()
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    httpd = ThreadingHTTPServer((host, port), Handler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd
