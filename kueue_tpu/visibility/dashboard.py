"""Web dashboard (kueueviz equivalent).

Behavioral surface: reference cmd/kueueviz — a live view of ClusterQueues,
cohort topology, pending/admitted workloads, quota utilization and
scheduling activity. Self-contained single page (no external assets):
polls the JSON API and renders utilization bars, a cohort tree, an
activity time-series chart (pending/admitted/preempted) and per-flavor
breakdowns as inline SVG.

Serve with ``serve_dashboard(manager)`` or mount into the visibility
server.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Dict

from kueue_tpu.core.workload_info import is_admitted

_PAGE = """<!DOCTYPE html>
<html><head><title>kueue_tpu</title><style>
body{font-family:monospace;margin:2em;background:#111;color:#ddd}
table{border-collapse:collapse;margin:1em 0}
td,th{border:1px solid #444;padding:4px 10px;text-align:left}
th{background:#222}.bar{background:#333;width:160px;height:12px;display:inline-block}
.fill{background:#4a8;height:12px}.fill.hot{background:#e74}
h2{color:#8cf}.cohort{margin-left:1.5em}.muted{color:#777}
.tile{display:inline-block;border:1px solid #444;margin:4px;padding:8px 16px}
.tile b{font-size:1.6em;color:#8cf;display:block}
svg{background:#181818;border:1px solid #333}
</style></head><body>
<h1>kueue_tpu dashboard</h1>
<div id="tiles"></div>
<h2>Scheduling activity</h2>
<svg id="chart" width="720" height="160"></svg>
<div class="muted">pending <span style="color:#8cf">&#9632;</span>
 admitted <span style="color:#4a8">&#9632;</span>
 preempted-total <span style="color:#e74">&#9632;</span></div>
<div id="content">loading...</div>
<script>
function polyline(points, color, w, h, maxY){
  if (points.length < 2) return '';
  const step = w / Math.max(points.length - 1, 1);
  const pts = points.map((v,i) =>
    `${(i*step).toFixed(1)},${(h - h*(v/Math.max(maxY,1))).toFixed(1)}`
  ).join(' ');
  return `<polyline fill="none" stroke="${color}" stroke-width="1.5" points="${pts}"/>`;
}
function render(s){
  let tiles = '';
  for (const [label, v] of Object.entries(s.totals)){
    tiles += `<div class=tile><b>${v}</b>${label}</div>`;
  }
  document.getElementById('tiles').innerHTML = tiles;

  const hist = s.history;
  const maxY = Math.max(...hist.pending, ...hist.admitted, 1);
  const maxP = Math.max(...hist.preempted_total, 1);
  document.getElementById('chart').innerHTML =
    polyline(hist.pending, '#8cf', 720, 160, maxY) +
    polyline(hist.admitted, '#4a8', 720, 160, maxY) +
    polyline(hist.preempted_total, '#e74', 720, 160, maxP);

  let h = '<h2>Cohort topology</h2>';
  function renderCohort(node, depth){
    let out = `<div class=cohort style="margin-left:${depth*1.5}em">`+
      `&#9656; <b>${node.name}</b> <span class=muted>`+
      `${node.cqs.length} queues</span></div>`;
    for (const cq of node.cqs){
      out += `<div class=cohort style="margin-left:${(depth+1)*1.5}em">`+
        `${cq}</div>`;
    }
    for (const child of node.children) out += renderCohort(child, depth+1);
    return out;
  }
  for (const root of s.cohort_tree) h += renderCohort(root, 0);

  h += '<h2>ClusterQueues</h2><table><tr><th>name</th><th>cohort</th>'+
    '<th>pending</th><th>admitted</th><th>utilization (per flavor)</th></tr>';
  for (const cq of s.cluster_queues){
    h += `<tr><td>${cq.name}</td><td>${cq.cohort||''}</td>`+
      `<td>${cq.pending}</td><td>${cq.admitted}</td><td>`;
    for (const [key, u] of Object.entries(cq.usage)){
      const pct = Math.min(100, u.pct);
      const hot = u.pct > 95 ? ' hot' : '';
      h += `${key}: ${u.used}/${u.nominal} (${u.pct}%)`+
        `<div class=bar><div class="fill${hot}" style="width:${pct*1.6}px">`+
        `</div></div><br>`;
    }
    h += '</td></tr>';
  }
  h += '</table><h2>Workloads</h2><table><tr><th>key</th><th>queue</th>'+
    '<th>priority</th><th>status</th><th>topology</th></tr>';
  for (const w of s.workloads){
    h += `<tr><td>${w.key}</td><td>${w.queue}</td><td>${w.priority}</td>`+
      `<td>${w.status}</td><td class=muted>${w.topology||''}</td></tr>`;
  }
  h += '</table>';
  document.getElementById('content').innerHTML = h;
}
async function refresh(){
  const r = await fetch('/api/state'); render(await r.json());
}
let wsLive = false, wsRetry = null;
function scheduleReconnect(){
  wsLive = false;
  if (wsRetry === null) {
    wsRetry = setTimeout(() => { wsRetry = null; connectWS(); }, 2000);
  }
}
function connectWS(){
  try {
    const ws = new WebSocket(`ws://${location.host}/ws`);
    ws.onopen = () => { wsLive = true; };
    ws.onmessage = (ev) => render(JSON.parse(ev.data));
    ws.onclose = scheduleReconnect;
    ws.onerror = scheduleReconnect;
  } catch (e) { wsLive = false; }
}
connectWS();
refresh(); setInterval(() => { if (!wsLive) refresh(); }, 2000);
</script></body></html>"""

# Activity ring buffer sampled on every /api/state call (kueueviz keeps a
# live websocket stream; polling + history is the self-contained analog).
_HISTORY_LEN = 360


class _History:
    """Ring of dashboard activity samples. Writers (the state-doc cache,
    direct ``state_json`` callers) and readers (every HTTP/websocket
    serialization) run on different handler threads, so both go through
    one internal lock: ``sample`` appends all four rings atomically and
    ``snapshot`` returns a consistent same-length view — a reader can
    never observe one ring longer than another mid-append."""

    def __init__(self) -> None:
        self.pending = deque(maxlen=_HISTORY_LEN)
        self.admitted = deque(maxlen=_HISTORY_LEN)
        self.preempted_total = deque(maxlen=_HISTORY_LEN)
        self.t = deque(maxlen=_HISTORY_LEN)
        self._lock = threading.Lock()

    def sample(self, pending: int, admitted: int, preempted: float) -> None:
        with self._lock:
            self.pending.append(pending)
            self.admitted.append(admitted)
            self.preempted_total.append(preempted)
            self.t.append(time.time())

    def snapshot(self) -> Dict[str, list]:
        with self._lock:
            return {
                "pending": list(self.pending),
                "admitted": list(self.admitted),
                "preempted_total": list(self.preempted_total),
            }


_history = _History()

# Shared state snapshot: every reader (each websocket connection, every
# /api/state poll) goes through one cache + one history sampler, so N
# connected clients cannot record N duplicate history samples per state
# change, and the O(workloads) serialization runs at most once per
# refresh interval regardless of client count.
_state_lock = threading.Lock()
_state_cache = {"ts": 0.0, "core": None, "doc": None, "mgr": None}


def shared_state_doc(manager, max_age_s: float = 0.2):
    """Compute (or reuse) the state document. Returns (doc, core_bytes);
    ``core_bytes`` excludes the history lists so callers can use it for
    change detection. History is sampled exactly once per distinct state
    revision across all callers."""
    now = time.monotonic()
    with _state_lock:
        if (
            _state_cache["doc"] is not None
            and _state_cache["mgr"] is manager
            and now - _state_cache["ts"] < max_age_s
        ):
            return _state_cache["doc"], _state_cache["core"]
        doc = state_json(manager, sample_history=False)
        core = json.dumps(
            {k: v for k, v in doc.items() if k != "history"}
        ).encode()
        if core != _state_cache["core"]:
            t = doc["totals"]
            _history.sample(
                t["pending"], t["admitted"], t["preempted (total)"]
            )
        doc["history"] = _history.snapshot()
        _state_cache.update(ts=now, core=core, doc=doc, mgr=manager)
        return doc, core


def _cohort_tree(manager):
    children: Dict[str, list] = {}
    cq_of: Dict[str, list] = {}
    roots = []
    for name, co in manager.cache.cohorts.items():
        if co.parent:
            children.setdefault(co.parent, []).append(name)
        else:
            roots.append(name)
    for cq_name, cq in manager.cache.cluster_queues.items():
        if cq.cohort:
            cq_of.setdefault(cq.cohort, []).append(cq_name)

    def build(name):
        return {
            "name": name,
            "cqs": sorted(cq_of.get(name, [])),
            "children": [build(c) for c in sorted(children.get(name, []))],
        }

    return [build(r) for r in sorted(roots)]


def state_json(manager, sample_history: bool = True) -> Dict:
    """Serialize live manager state. The scheduler may mutate its dicts
    concurrently (the dashboard handler threads share the process);
    iteration races surface as RuntimeError — retry on a fresh view
    rather than killing the caller's stream."""
    for attempt in range(5):
        try:
            return _state_json_once(manager, sample_history)
        except RuntimeError:
            if attempt == 4:
                raise
            time.sleep(0.005)


def _state_json_once(manager, sample_history: bool = True) -> Dict:
    cqs = []
    total_pending = 0
    total_admitted = 0
    for name, cq in sorted(manager.cache.cluster_queues.items()):
        usage: Dict[str, Dict] = {}
        nominal: Dict[tuple, int] = {}
        for rg in cq.resource_groups:
            for fq in rg.flavors:
                for res, q in fq.resources.items():
                    nominal[(fq.name, res)] = q.nominal
        used: Dict[tuple, int] = {}
        for info in manager.cache.workloads.values():
            if info.cluster_queue != name:
                continue
            for fr, v in info.usage().items():
                used[(fr.flavor, fr.resource)] = (
                    used.get((fr.flavor, fr.resource), 0) + v
                )
        for (flavor, res), nom in nominal.items():
            u = used.get((flavor, res), 0)
            usage[f"{flavor}/{res}"] = {
                "used": u, "nominal": nom,
                "pct": round(100.0 * u / nom, 1) if nom else 0.0,
            }
        pending = manager.queues.pending_count(name)
        admitted = sum(
            1 for i in manager.cache.workloads.values()
            if i.cluster_queue == name
        )
        total_pending += pending
        total_admitted += admitted
        cqs.append({
            "name": name,
            "cohort": cq.cohort,
            "pending": pending,
            "admitted": admitted,
            "usage": usage,
        })
    wls = []
    for key, wl in sorted(manager.workloads.items()):
        topo = ""
        if wl.status.admission is not None:
            for psa in wl.status.admission.pod_set_assignments:
                ta = psa.topology_assignment
                if ta is not None and ta.domains:
                    topo = ", ".join(
                        f"{'/'.join(v)}x{c}" for v, c in ta.domains[:4]
                    )
                    if len(ta.domains) > 4:
                        topo += f" +{len(ta.domains) - 4} more"
        wls.append({
            "key": key,
            "queue": wl.queue_name,
            "priority": wl.priority,
            "status": "Admitted" if is_admitted(wl) else "Pending",
            "topology": topo,
        })
    m = manager.metrics
    preempted_total = sum(
        m.counters.get("preempted_workloads_total", {}).values()
    )
    totals = {
        "pending": total_pending,
        "admitted": total_admitted,
        "preempted (total)": int(preempted_total),
        "evicted (total)": int(sum(
            m.counters.get("evicted_workloads_total", {}).values()
        )),
        "finished (total)": int(sum(
            m.counters.get("workloads_finished_total", {}).values()
        )),
        "cycles": int(sum(
            m.counters.get("admission_attempts_total", {}).values()
        )),
    }
    if sample_history:
        _history.sample(total_pending, total_admitted, preempted_total)
    return {
        "cluster_queues": cqs,
        "workloads": wls,
        "cohort_tree": _cohort_tree(manager),
        "totals": totals,
        "history": _history.snapshot(),
    }


def serve_dashboard(manager, host: str = "127.0.0.1", port: int = 8081,
                    ws_interval_s: float = 0.25):
    """HTTP + WebSocket dashboard server. ``/ws`` upgrades to a live
    stream (kueueviz's websocket analog): the full state document is
    pushed immediately on connect and whenever it changes, checked every
    ``ws_interval_s``; pings are answered, close frames honored."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from kueue_tpu.visibility import ws as wsmod

    class Handler(BaseHTTPRequestHandler):
        def _serve_ws(self):
            key = self.headers.get("Sec-WebSocket-Key")
            if not key or "websocket" not in (
                self.headers.get("Upgrade", "").lower()
            ):
                self.send_response(400)
                self.end_headers()
                return
            self.connection.sendall(wsmod.handshake_response(key))
            self.close_connection = True
            last_core = None
            reader = wsmod.SockReader(self.connection)
            try:
                while True:
                    # Shared snapshot: computed once per tick across all
                    # connections, history sampled once per distinct
                    # revision (shared_state_doc). Change detection
                    # excludes the history lists so the periodic check
                    # itself cannot manufacture a difference.
                    doc, core = shared_state_doc(manager)
                    if core != last_core:
                        self.connection.sendall(wsmod.encode_frame(
                            json.dumps(doc).encode(), wsmod.OP_TEXT
                        ))
                        last_core = core
                    # Handle one client frame per tick (pings, close).
                    # select() only when the reader holds no read-ahead,
                    # so frames coalesced into one TCP segment are not
                    # stranded behind a quiet socket.
                    import select

                    if not reader.has_buffered:
                        ready, _, _ = select.select(
                            [self.connection], [], [], ws_interval_s
                        )
                        if not ready:
                            continue
                    frame = wsmod.read_frame(reader)
                    if frame is None:
                        return
                    op, payload = frame
                    if op == wsmod.OP_CLOSE:
                        self.connection.sendall(
                            wsmod.encode_frame(payload, wsmod.OP_CLOSE)
                        )
                        return
                    if op == wsmod.OP_PING:
                        self.connection.sendall(
                            wsmod.encode_frame(payload, wsmod.OP_PONG)
                        )
            except (BrokenPipeError, ConnectionResetError, OSError):
                return

        def do_GET(self):  # noqa: N802
            if self.path == "/ws":
                self._serve_ws()
                return
            if self.path == "/api/state":
                body = json.dumps(shared_state_doc(manager)[0]).encode()
                ctype = "application/json"
            elif self.path == "/metrics":
                # Conventional Prometheus scrape path: text exposition
                # format with # HELP/# TYPE lines.
                body = manager.metrics.expose().encode()
                ctype = "text/plain; version=0.0.4"
            elif self.path == "/api/metrics":
                # JSON mirror of the same registry for the dashboard's
                # own pollers (strict-JSON: +Inf quantiles become null).
                body = json.dumps(manager.metrics.to_doc()).encode()
                ctype = "application/json"
            elif self.path == "/trace":
                from kueue_tpu.metrics import tracing

                body = json.dumps(
                    tracing.get_tracer().export_chrome_trace()
                ).encode()
                ctype = "application/json"
            elif self.path in ("/", "/index.html"):
                body = _PAGE.encode()
                ctype = "text/html"
            else:
                self.send_response(404)
                self.end_headers()
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    httpd = ThreadingHTTPServer((host, port), Handler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd
