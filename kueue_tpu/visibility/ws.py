"""Minimal RFC 6455 WebSocket server-side plumbing (stdlib only).

kueueviz (reference cmd/kueueviz) streams cluster state to the browser
over websockets; this module provides the handshake and frame codec used
by the dashboard's ``/ws`` endpoint (visibility/dashboard.py). Only the
server side of the protocol is implemented: text pushes, client-masked
frame reads, ping/pong, close.
"""

from __future__ import annotations

import base64
import hashlib
import struct
from typing import Optional, Tuple

_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

OP_CONT = 0x0
OP_TEXT = 0x1
OP_BINARY = 0x2
OP_CLOSE = 0x8
OP_PING = 0x9
OP_PONG = 0xA


def accept_key(client_key: str) -> str:
    """Sec-WebSocket-Accept for a client's Sec-WebSocket-Key."""
    digest = hashlib.sha1((client_key + _GUID).encode()).digest()
    return base64.b64encode(digest).decode()


def handshake_response(client_key: str) -> bytes:
    return (
        "HTTP/1.1 101 Switching Protocols\r\n"
        "Upgrade: websocket\r\n"
        "Connection: Upgrade\r\n"
        f"Sec-WebSocket-Accept: {accept_key(client_key)}\r\n"
        "\r\n"
    ).encode()


def encode_frame(payload: bytes, opcode: int = OP_TEXT,
                 mask: bool = False) -> bytes:
    """One FIN frame. Servers send unmasked; the test client masks."""
    head = bytes([0x80 | opcode])
    mbit = 0x80 if mask else 0
    n = len(payload)
    if n < 126:
        head += bytes([mbit | n])
    elif n < (1 << 16):
        head += bytes([mbit | 126]) + struct.pack(">H", n)
    else:
        head += bytes([mbit | 127]) + struct.pack(">Q", n)
    if mask:
        key = b"\x37\xfa\x21\x3d"  # fixed mask is fine for tests
        masked = bytes(b ^ key[i % 4] for i, b in enumerate(payload))
        return head + key + masked
    return head + payload


class SockReader:
    """Blocking exact-read wrapper over a socket with an inspectable
    buffer — unlike BufferedReader, ``has_buffered`` lets a server poll
    select() only when nothing is already read ahead (so coalesced
    frames are never stranded) and never blocks on a peek."""

    def __init__(self, sock) -> None:
        self.sock = sock
        self.buf = b""

    def read(self, n: int) -> bytes:
        while len(self.buf) < n:
            chunk = self.sock.recv(65536)
            if not chunk:
                break
            self.buf += chunk
        out, self.buf = self.buf[:n], self.buf[n:]
        return out

    @property
    def has_buffered(self) -> bool:
        return bool(self.buf)


# Upper bound on accepted client frames. The dashboard only ever expects
# tiny control/close frames from browsers; a client-declared 64-bit length
# must not drive the reader into buffering gigabytes.
MAX_CLIENT_FRAME = 1 << 20
_CONTROL_OPS = (OP_CLOSE, OP_PING, OP_PONG)


def read_frame(rfile, require_mask: bool = True) -> Optional[Tuple[int, bytes]]:
    """Read one frame from a file-like socket reader. Returns
    (opcode, payload) or None on EOF / protocol violation. Unmasks masked
    payloads. With ``require_mask`` (the server side), unmasked frames
    fail the connection (RFC 6455 5.1); oversized declared lengths always
    do (5.5 bounds control frames; MAX_CLIENT_FRAME bounds the rest)."""
    h = rfile.read(2)
    if len(h) < 2:
        return None
    opcode = h[0] & 0x0F
    masked = bool(h[1] & 0x80)
    n = h[1] & 0x7F
    if n == 126:
        ext = rfile.read(2)
        if len(ext) < 2:
            return None
        n = struct.unpack(">H", ext)[0]
    elif n == 127:
        ext = rfile.read(8)
        if len(ext) < 8:
            return None
        n = struct.unpack(">Q", ext)[0]
    if require_mask and not masked:
        return None  # clients MUST mask; fail the connection
    if opcode in _CONTROL_OPS and n > 125:
        return None  # control frames are bounded by RFC 6455 5.5
    if require_mask and n > MAX_CLIENT_FRAME:
        # The size cap protects the SERVER from client-declared lengths;
        # server->client pushes (state documents) are legitimately large.
        return None
    key = rfile.read(4) if masked else b""
    payload = rfile.read(n) if n else b""
    if masked and payload:
        payload = bytes(b ^ key[i % 4] for i, b in enumerate(payload))
    return opcode, payload
