"""Multi-tenant read plane: coalesced what-if serving off snapshots.

The admission loop answers one what-if caller at a time, serialized on
the service lock, against live state. This package absorbs heavy read
traffic instead (docs/whatif.md, "Multi-tenant read plane"):

- :class:`SnapshotPublisher` — a generation-fingerprinted, double-
  buffered read snapshot the ServiceLoop publishes at cycle boundaries
  (demand-gated: a read-idle deployment pays one attribute read per
  cycle and never captures);
- :class:`QueryCoalescer` / :class:`ReadPlane` — a bounded coalescing
  window that packs concurrent heterogeneous queries (eta, preview,
  quota sweeps, drain matrices, starvation bisection) into shared
  K-padded rollout dispatches against the pinned snapshot, tiling the
  K axis through a bounded lane budget so scenario-plane memory stays
  fixed at any query load;
- :mod:`queries` — the sweep/search compiler: expands high-level
  queries into scenario lanes and folds lane results back into
  per-query answers.

Read traffic overlaps with — and never blocks — admission: dispatches
run off the service lock against frozen views, and answers are
bit-identical to issuing each query alone against the same snapshot
generation (tests/test_readplane.py differential).
"""

from kueue_tpu.readplane.coalescer import QueryCoalescer, ReadPlane
from kueue_tpu.readplane.publisher import ReadSnapshot, SnapshotPublisher
from kueue_tpu.readplane.queries import (
    Query,
    drain_matrix_query,
    eta_query,
    preview_query,
    starve_search_query,
    sweep_query,
)

__all__ = [
    "Query",
    "QueryCoalescer",
    "ReadPlane",
    "ReadSnapshot",
    "SnapshotPublisher",
    "drain_matrix_query",
    "eta_query",
    "preview_query",
    "starve_search_query",
    "sweep_query",
]
