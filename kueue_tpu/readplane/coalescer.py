"""QueryCoalescer: bounded-window batching of read-plane queries.

Concurrent what-if traffic from many tenants lands in a bounded queue;
a worker thread drains it in *coalescing windows*, expands every query
into scenario lanes (readplane/queries.py) and packs the lanes into
shared K-padded rollout dispatches against the publisher's pinned
snapshot generation. Because vmap lanes are independent and every
lane's hypotheticals are inactive in every other lane, coalesced
answers are bit-identical to issuing each query alone against the same
generation — the contract :meth:`QueryCoalescer.query_solo` exists to
check (tests/test_readplane.py differential).

Memory stays bounded at any K: lanes tile through ``lane_budget``-sized
dispatches, so the scenario-plane working set is the tile's pow2 bucket
— never the full batch — and the tile shapes reuse the live engine's
compiled executables via the shared jit-cache dict.

Containment: a poisoned batch (``faults.READPLANE_DISPATCH``, or any
dispatch-path bug) fails only the queries in that window with a
structured error; later windows re-coalesce cleanly, and repeated
failures open the per-coalescer breaker so callers shed fast instead
of queueing behind a broken plane (docs/fault_containment.md).

Fairness: each tenant's lanes per window are capped
(``max_lanes_per_tenant``); surplus queries defer to the next window
(never dropped), and a tenant's first query in a window always admits
so a big sweep cannot be starved out entirely.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Tuple

from kueue_tpu.metrics.registry import Metrics
from kueue_tpu.models.buckets import pow2_bucket as _pow2
from kueue_tpu.obs import costs
from kueue_tpu.readplane.publisher import ReadSnapshot, SnapshotPublisher
from kueue_tpu.readplane.queries import Query, expand, fold, fold_preview
from kueue_tpu.utils import faults
from kueue_tpu.utils.breaker import CircuitBreaker
from kueue_tpu.whatif.engine import WhatIfEngine


class _Ticket:
    """One in-flight query: a waitable slot the coalescer resolves."""

    __slots__ = ("query", "event", "answer", "t0")

    def __init__(self, query: Query, t0: float) -> None:
        self.query = query
        self.event = threading.Event()
        self.answer: Optional[dict] = None
        self.t0 = t0

    def result(self, timeout: Optional[float] = None) -> dict:
        if not self.event.wait(timeout):
            raise TimeoutError(
                f"read-plane query {self.query.kind} not resolved in time")
        return self.answer


class QueryCoalescer:
    """Batches queries into shared dispatches against pinned snapshots."""

    def __init__(
        self,
        publisher: SnapshotPublisher,
        template: Optional[WhatIfEngine] = None,
        metrics: Optional[Metrics] = None,
        clock: Callable[[], float] = time.monotonic,
        window: int = 32,
        coalesce_delay_s: float = 0.005,
        queue_limit: int = 256,
        max_lanes_per_tenant: int = 64,
        lane_budget: int = 127,
        breaker: Optional[CircuitBreaker] = None,
    ) -> None:
        self.publisher = publisher
        self.template = template
        self.metrics = metrics if metrics is not None else Metrics()
        self._clock = clock
        self.window = int(window)
        self.coalesce_delay_s = float(coalesce_delay_s)
        self.queue_limit = int(queue_limit)
        self.max_lanes_per_tenant = int(max_lanes_per_tenant)
        self.lane_budget = int(lane_budget)
        self.breaker = breaker or CircuitBreaker(
            threshold=3, backoff_s=2.0, max_backoff_s=30.0, clock=clock
        )
        self._queue: Deque[_Ticket] = deque()
        self._cv = threading.Condition()
        # Serializes batch execution: the worker thread vs query_solo
        # callers (both swap the shared engine's frozen views).
        self._exec_lock = threading.Lock()
        self._engine: Optional[WhatIfEngine] = None
        self._thread: Optional[threading.Thread] = None
        self._stopping = False
        # Bounded-memory evidence for the perf probe: the largest padded
        # K any single dispatch used, and total lanes ever dispatched.
        self.peak_tile_lanes = 0
        self.total_lanes = 0
        self.batches = 0

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "QueryCoalescer":
        with self._cv:
            if self._thread is None or not self._thread.is_alive():
                self._stopping = False
                self._thread = threading.Thread(
                    target=self._run, name="readplane-coalescer",
                    daemon=True)
                self._thread.start()
        return self

    def stop(self) -> None:
        with self._cv:
            self._stopping = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        # Drain: late submitters get a structured shutdown error.
        with self._cv:
            pending = list(self._queue)
            self._queue.clear()
        for t in pending:
            self._resolve(t, {"ok": False, "error": "stopped"})

    # -- submission (any thread) ----------------------------------------

    def submit(self, query: Query) -> _Ticket:
        t = _Ticket(query, self._clock())
        m = self.metrics
        m.inc("readplane_queries_total", {"kind": query.kind})
        self.publisher.note_demand()
        with self._cv:
            if len(self._queue) >= self.queue_limit:
                m.inc("readplane_rejected_total")
                self._resolve(t, {"ok": False, "error": "queue_full"})
                return t
            self._queue.append(t)
            m.set_gauge("readplane_queue_depth", float(len(self._queue)))
            self._cv.notify()
        return t

    def query(self, query: Query, timeout: Optional[float] = 30.0) -> dict:
        """Coalesced blocking query: submit, wait for the window that
        carries it (and any continuation windows) to resolve."""
        self.start()
        return self.submit(query).result(timeout)

    def query_solo(self, query: Query, max_windows: int = 64) -> dict:
        """The differential reference: run ``query`` alone, one single-
        query window per continuation round, against the same pinned
        snapshot the coalesced path would use."""
        t = _Ticket(query, self._clock())
        self.metrics.inc("readplane_queries_total", {"kind": query.kind})
        self.publisher.note_demand()
        for _ in range(max_windows):
            with self._exec_lock:
                continued = self._execute([t])
            if not continued:
                break
        if not t.event.is_set():  # round budget exhausted mid-bisection
            self._resolve(t, {"ok": False, "error": "unresolved"})
        return t.answer

    # -- worker ---------------------------------------------------------

    def _run(self) -> None:
        while True:
            batch = self._next_window()
            if batch is None:
                return
            if not batch:
                continue
            with self._exec_lock:
                continued = self._execute(batch)
            if continued:
                with self._cv:
                    # Continuations ride the next window, ahead of new
                    # arrivals, so bisections converge under load.
                    for t in reversed(continued):
                        self._queue.appendleft(t)
                    self._cv.notify()

    def _next_window(self) -> Optional[List[_Ticket]]:
        """Block for traffic, linger ``coalesce_delay_s`` to let a burst
        accumulate, then take up to ``window`` queries subject to the
        per-tenant lane cap. Returns None on shutdown."""
        with self._cv:
            while not self._queue:
                if self._stopping:
                    return None
                self._cv.wait(0.1)
            if self._stopping:
                return None
            if len(self._queue) < self.window:
                self._cv.wait(self.coalesce_delay_s)
            batch: List[_Ticket] = []
            deferred: List[_Ticket] = []
            tenant_lanes: Dict[str, int] = {}
            while self._queue and len(batch) < self.window:
                t = self._queue.popleft()
                cost = max(1, len(expand(t.query)))
                tenant = t.query.tenant
                used = tenant_lanes.get(tenant, 0)
                # A tenant's first query always admits; beyond that its
                # lanes are capped per window and the surplus defers.
                if used and used + cost > self.max_lanes_per_tenant:
                    deferred.append(t)
                    continue
                tenant_lanes[tenant] = used + cost
                batch.append(t)
            if deferred:
                self.metrics.inc("readplane_deferred_total",
                                 value=float(len(deferred)))
                for t in reversed(deferred):
                    self._queue.appendleft(t)
            self.metrics.set_gauge("readplane_queue_depth",
                                   float(len(self._queue)))
            return batch

    # -- execution (under _exec_lock) -----------------------------------

    def _engine_for(self, rs: ReadSnapshot) -> WhatIfEngine:
        """One long-lived engine over swapped frozen views: sharing the
        template's jit-cache dict means the read plane reuses the live
        engine's compiled executables instead of recompiling per
        generation (or per coalescer)."""
        eng = self._engine
        if eng is None:
            t = self.template
            if t is not None:
                eng = WhatIfEngine(
                    rs.cache_view, rs.queues_view,
                    default_runtime_ms=t.default_runtime_ms,
                    horizon_rounds=t.horizon_rounds,
                    runtime_ms_fn=t._runtime_ms_fn,
                    clock=self._clock,
                    kernel=t.kernel,
                )
                eng._rollout_fns = t._rollout_fns
            else:
                eng = WhatIfEngine(rs.cache_view, rs.queues_view,
                                   clock=self._clock)
            self._engine = eng
        eng.cache = rs.cache_view
        eng.queues = rs.queues_view
        return eng

    def _resolve(self, t: _Ticket, answer: dict) -> None:
        t.answer = answer
        self.metrics.observe("readplane_query_seconds",
                             max(0.0, self._clock() - t.t0))
        t.event.set()

    def _fail_all(self, batch: List[_Ticket], error: str,
                  reason: str = "") -> None:
        doc = {"ok": False, "error": error}
        if reason:
            doc["reason"] = reason
        for t in batch:
            if not t.event.is_set():
                self._resolve(t, dict(doc))

    def _execute(self, batch: List[_Ticket]) -> List[_Ticket]:
        """Dispatch one window. Returns the continuation tickets (still
        unresolved, to re-enter the queue). Exceptions never escape: a
        poisoned window fails only its own tickets."""
        m = self.metrics
        rs = self.publisher.current()
        if rs is None:
            self._fail_all(batch, "no_snapshot")
            return []
        m.observe("readplane_snapshot_staleness_seconds",
                  max(0.0, self._clock() - rs.published_at))
        if not self.breaker.allow():
            m.set_gauge("readplane_breaker_state",
                        float(self.breaker.gauge_value))
            self._fail_all(batch, "breaker_open")
            return []
        t0 = self._clock()
        continued: List[_Ticket] = []
        try:
            if faults.ENABLED:
                faults.fire(faults.READPLANE_DISPATCH)
            eng = self._engine_for(rs)
            # Expand every query's lanes into one flat plan.
            plans: List[Tuple[_Ticket, int, int]] = []  # (ticket, off, n)
            all_lanes: List = []
            need_rollout = False
            for t in batch:
                if t.query.kind == "preview":
                    plans.append((t, 0, 0))
                    continue
                need_rollout = True
                lanes = expand(t.query)
                plans.append((t, len(all_lanes), len(lanes)))
                all_lanes.extend(lanes)
            # Tiled dispatch: every tile is one K-padded rollout sharing
            # the base lane; the scenario-plane working set is bounded
            # by lane_budget regardless of batch size. A single query's
            # lanes may split across tiles — lanes are independent, so
            # per-lane results are unchanged.
            base_sf = None
            basis = "rollout"
            lane_sfs: List = []
            tiles = 0
            if need_rollout:
                step = max(1, self.lane_budget)
                tile_list = [all_lanes[i:i + step]
                             for i in range(0, len(all_lanes), step)]
                if not tile_list:
                    tile_list = [[]]  # plain eta queries: base lane only
                for tile in tile_list:
                    rep = eng.eta(scenarios=tile, cluster_queue=None)
                    tiles += 1
                    self.peak_tile_lanes = max(
                        self.peak_tile_lanes,
                        _pow2(len(tile) + 1, floor=1))
                    if base_sf is None:
                        base_sf = rep.base
                        basis = rep.basis
                    lane_sfs.extend(rep.scenarios[1:])
            # Fold lane results back into per-query answers.
            tenant_lanes: Dict[str, int] = {}
            for t, off, n in plans:
                q = t.query
                tenant_lanes[q.tenant] = (
                    tenant_lanes.get(q.tenant, 0) + max(1, n))
                if q.kind == "preview":
                    rep = eng.preview(q.workload, q.cluster_queue)
                    ans = fold_preview(q, rep)
                    self._resolve(t, dict(
                        ans, ok=True, generation=rs.generation))
                    continue
                answer, cont = fold(q, base_sf, lane_sfs[off:off + n],
                                    basis)
                if cont is not None:
                    continued.append(t)
                else:
                    self._resolve(t, dict(
                        answer, ok=True, generation=rs.generation))
            wall = max(0.0, self._clock() - t0)
            self.batches += 1
            self.total_lanes += len(all_lanes)
            m.inc("readplane_batches_total")
            if tiles:
                m.inc("readplane_dispatch_tiles_total", value=float(tiles))
            m.set_gauge("readplane_lanes_per_batch", float(len(all_lanes)))
            total = sum(tenant_lanes.values()) or 1
            for tenant, tl in sorted(tenant_lanes.items()):
                m.inc("readplane_tenant_lanes_total", {"tenant": tenant},
                      value=float(tl))
                if costs.ENABLED:
                    costs.charge_tenant(
                        tenant, self.peak_tile_lanes or 1,
                        wall * tl / total, lanes={"K": (tl, tl)})
            self.breaker.record_success()
        except Exception as exc:  # noqa: BLE001 - window containment
            self.breaker.record_failure()
            m.inc("readplane_batch_failures_total")
            self._fail_all(batch, "dispatch_failed",
                           reason=f"{type(exc).__name__}: {exc}")
            continued = []
        m.set_gauge("readplane_breaker_state",
                    float(self.breaker.gauge_value))
        return continued

    def to_doc(self) -> dict:
        with self._cv:
            depth = len(self._queue)
        return {
            "window": self.window,
            "laneBudget": self.lane_budget,
            "maxLanesPerTenant": self.max_lanes_per_tenant,
            "queueDepth": depth,
            "queueLimit": self.queue_limit,
            "batches": self.batches,
            "totalLanes": self.total_lanes,
            "peakTileLanes": self.peak_tile_lanes,
            "breaker": self.breaker.state,
        }


class ReadPlane:
    """Facade wiring a publisher + coalescer over one (cache, queues).

    The ServiceLoop calls :meth:`publish_cycle` at cycle boundaries
    (guarded ``if self._readplane is not None``); clients call
    :meth:`query` / :meth:`submit` from any thread."""

    def __init__(
        self,
        cache,
        queues,
        metrics: Optional[Metrics] = None,
        clock: Callable[[], float] = time.monotonic,
        template: Optional[WhatIfEngine] = None,
        min_interval_s: float = 0.05,
        demand_window_s: float = 5.0,
        **coalescer_kwargs,
    ) -> None:
        self.cache = cache
        self.queues = queues
        self.metrics = metrics if metrics is not None else Metrics()
        self.publisher = SnapshotPublisher(
            metrics=self.metrics, clock=clock,
            min_interval_s=min_interval_s,
            demand_window_s=demand_window_s,
        )
        self.coalescer = QueryCoalescer(
            self.publisher, template=template, metrics=self.metrics,
            clock=clock, **coalescer_kwargs,
        )

    # Publishing (service-loop thread / manual).
    def publish_cycle(self, cache=None, queues=None,
                      dirty: bool = False) -> bool:
        return self.publisher.publish_cycle(
            cache if cache is not None else self.cache,
            queues if queues is not None else self.queues,
            dirty=dirty,
        )

    def publish(self, force: bool = False) -> bool:
        return self.publisher.publish(self.cache, self.queues,
                                      force=force)

    # Serving (any thread).
    def query(self, query: Query, timeout: Optional[float] = 30.0) -> dict:
        return self.coalescer.query(query, timeout)

    def query_solo(self, query: Query) -> dict:
        return self.coalescer.query_solo(query)

    def submit(self, query: Query) -> _Ticket:
        self.coalescer.start()
        return self.coalescer.submit(query)

    def start(self) -> "ReadPlane":
        self.coalescer.start()
        return self

    def stop(self) -> None:
        self.coalescer.stop()

    def slo_objectives(self):
        from kueue_tpu.obs.slo import READPLANE_OBJECTIVES
        return READPLANE_OBJECTIVES

    def to_doc(self) -> dict:
        return {
            "publisher": self.publisher.to_doc(),
            "coalescer": self.coalescer.to_doc(),
        }
