"""SnapshotPublisher: double-buffered read snapshots at cycle boundaries.

The ServiceLoop calls :meth:`SnapshotPublisher.publish_cycle` inside
``step()`` under the service lock (guarded by ``if self._readplane is
not None`` — tools/check_readplane_guards.py pins the idiom), so every
published generation is a crash-consistent cycle-boundary view. Reads
then run against the frozen views with NO lock shared with admission.

Cost discipline (the "never blocks admission" half of the contract):

- **demand gating** — no coalescer traffic inside ``demand_window_s``
  means ``publish_cycle`` returns after two float compares; a read-idle
  deployment pays nothing per cycle;
- **fingerprint gating** — captures only when the cache generation
  counters / pending totals moved (or the step applied ops), so a busy
  read plane over a quiet cluster reuses one generation;
- **min-interval throttling** — bounds capture rate under churn.

Double buffering: the publisher retains at most the two newest
generations (front + back slot); ``current()`` is an atomic reference
read. In-flight batches keep older generations alive only for the
duration of their dispatch.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Tuple


class _FrozenQueues:
    """The slice of the QueueManager surface the WhatIfEngine reads,
    pinned at publish time. Entries were cloned at capture; the engine
    clones again per rollout, so the stored copies are never mutated."""

    def __init__(self, pending: Dict[str, List], pending_all: Dict[str, List],
                 lq_to_cq: Dict[str, str]) -> None:
        self.cluster_queues = {name: None for name in pending_all}
        self._pending = pending
        self._pending_all = pending_all
        self._lq_to_cq = lq_to_cq

    def pending_workloads(self, cq_name: str) -> List:
        return self._pending.get(cq_name, [])

    def pending_workloads_all(self, cq_name: str) -> List:
        return self._pending_all.get(cq_name, [])

    def pending_count(self, cq_name: str) -> int:
        return len(self._pending.get(cq_name, []))

    def cluster_queue_for(self, wl) -> Optional[str]:
        return self._lq_to_cq.get(f"{wl.namespace}/{wl.queue_name}")


class _FrozenCache:
    """The slice of the Cache surface the WhatIfEngine reads."""

    def __init__(self, snap, nodes: Dict) -> None:
        self._snap = snap
        self.nodes = nodes

    def snapshot(self):
        return self._snap


class ReadSnapshot:
    """One published generation: frozen cache/queue views + identity."""

    __slots__ = ("generation", "fingerprint", "published_at",
                 "cache_view", "queues_view", "pending_total")

    def __init__(self, generation: int, fingerprint: Tuple,
                 published_at: float, cache_view: _FrozenCache,
                 queues_view: _FrozenQueues, pending_total: int) -> None:
        self.generation = generation
        self.fingerprint = fingerprint
        self.published_at = published_at
        self.cache_view = cache_view
        self.queues_view = queues_view
        self.pending_total = pending_total

    def to_doc(self) -> dict:
        return {
            "generation": self.generation,
            "publishedAt": self.published_at,
            "pendingTotal": self.pending_total,
        }


class SnapshotPublisher:
    """Publishes double-buffered :class:`ReadSnapshot` generations."""

    def __init__(
        self,
        metrics=None,
        clock: Callable[[], float] = time.monotonic,
        min_interval_s: float = 0.05,
        demand_window_s: float = 5.0,
    ) -> None:
        self.metrics = metrics
        self._clock = clock
        self.min_interval_s = float(min_interval_s)
        self.demand_window_s = float(demand_window_s)
        # Double buffer: only the two newest generations stay referenced
        # here; _front_idx flips after the back slot is fully built.
        self._buffers: List[Optional[ReadSnapshot]] = [None, None]
        self._front_idx = 0
        self._generation = 0
        self._last_fingerprint: Optional[Tuple] = None
        self._last_capture_t = -float("inf")
        # Plain float, written by any submitting thread / read by the
        # loop thread — atomic under the GIL, no lock on the hot path.
        self._last_demand_t = -float("inf")
        self.publish_errors = 0

    # -- demand signal (coalescer threads) ------------------------------

    def note_demand(self) -> None:
        """Called by the coalescer on every submit: read traffic within
        ``demand_window_s`` is what arms ``publish_cycle``."""
        self._last_demand_t = self._clock()

    # -- publishing (service-loop thread, under the service lock) -------

    def publish_cycle(self, cache, queues, dirty: bool = False) -> bool:
        """The ServiceLoop cycle-boundary hook. Contained: a capture
        failure is counted, never raised into the admission loop."""
        try:
            if self._should_capture(cache, queues, dirty):
                self._capture(cache, queues)
                return True
        except Exception:  # noqa: BLE001 - must never poison a cycle
            self.publish_errors += 1
            if self.metrics is not None:
                self.metrics.inc("readplane_publish_errors_total")
        return False

    def publish(self, cache, queues, force: bool = False) -> bool:
        """Manual/test publish path (e.g. before a service loop exists).
        ``force=True`` skips the demand + interval gates but still
        dedupes on an unchanged fingerprint via ``dirty=True`` capture
        semantics."""
        if force or self._should_capture(cache, queues, dirty=True):
            self._capture(cache, queues)
            return True
        return False

    def _should_capture(self, cache, queues, dirty: bool) -> bool:
        now = self._clock()
        if now - self._last_demand_t > self.demand_window_s:
            return False  # read-idle: zero publish cost
        if self._buffers[self._front_idx] is None:
            return True
        if now - self._last_capture_t < self.min_interval_s:
            return False
        if dirty:
            return True
        return self._fingerprint(cache, queues) != self._last_fingerprint

    @staticmethod
    def _fingerprint(cache, queues) -> Tuple:
        """Cheap change detector: the cache's fine-grained generation
        counters plus the pending-backlog total (pending entries live in
        the queues and bump no cache counter)."""
        pending_total = sum(
            queues.pending_count(name) for name in queues.cluster_queues
        )
        return (
            cache.generation, cache.quota_generation,
            cache.node_generation, cache.admitted_generation,
            cache.workload_generation, pending_total,
        )

    def _capture(self, cache, queues) -> None:
        t0 = self._clock()
        fp = self._fingerprint(cache, queues)
        snap = cache.snapshot()
        pending: Dict[str, List] = {}
        pending_all: Dict[str, List] = {}
        for name in sorted(queues.cluster_queues):
            pending[name] = [
                i.clone() for i in queues.pending_workloads(name)
            ]
            pending_all[name] = [
                i.clone() for i in queues.pending_workloads_all(name)
            ]
        lq_to_cq = {
            key: lq.cluster_queue
            for key, lq in queues.local_queues.items()
        }
        self._generation += 1
        rs = ReadSnapshot(
            generation=self._generation,
            fingerprint=fp,
            published_at=self._clock(),
            cache_view=_FrozenCache(snap, dict(cache.nodes)),
            queues_view=_FrozenQueues(pending, pending_all, lq_to_cq),
            pending_total=fp[-1],
        )
        # Build into the back slot, then flip: readers either see the
        # old front or the fully-built new one, never a partial.
        back = 1 - self._front_idx
        self._buffers[back] = rs
        self._front_idx = back
        self._last_fingerprint = fp
        self._last_capture_t = self._clock()
        if self.metrics is not None:
            m = self.metrics
            m.set_gauge("readplane_snapshot_generation",
                        float(self._generation))
            m.observe("readplane_publish_seconds",
                      max(0.0, self._clock() - t0))

    # -- readers (any thread) -------------------------------------------

    def current(self) -> Optional[ReadSnapshot]:
        """The newest fully-published generation (atomic ref read)."""
        return self._buffers[self._front_idx]

    def to_doc(self) -> dict:
        rs = self.current()
        return {
            "generation": self._generation,
            "minIntervalS": self.min_interval_s,
            "demandWindowS": self.demand_window_s,
            "publishErrors": self.publish_errors,
            "current": rs.to_doc() if rs is not None else None,
        }
