"""The read plane's sweep/search compiler.

High-level queries (ETA forecasts, admission previews, quota sweeps,
drain matrices, starvation bisection) compile down to *scenario lanes*
— rows of the what-if engine's batched K-padded rollout — so the
coalescer can pack many tenants' questions into one device dispatch.
:func:`expand` produces the lanes; :func:`fold` turns the lane
forecasts back into one deterministic answer document per query.

Answers are deterministic on a pinned snapshot generation: no wall-
clock fields survive folding, so the concurrent-coalescer differential
(tests/test_readplane.py) can compare coalesced answers against
solo-issued ones with plain ``==``.

Iterative queries (``starve_search``) fold into a *continuation*: the
bisection bracket narrows by one grid per coalescing window, riding
whatever batch dispatches next, until the bracket closes or the round
budget runs out.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from kueue_tpu.whatif.engine import QuotaDelta, Scenario

_KINDS = ("eta", "preview", "sweep", "drain_matrix", "starve_search")

_ids = itertools.count(1)


@dataclass
class Query:
    """One read-plane query. Build via the constructor helpers below —
    they validate the per-kind field contract."""

    kind: str
    tenant: str = "default"
    cluster_queue: Optional[str] = None
    # eta: extra engine scenarios to evaluate alongside the base lane.
    scenarios: Tuple[Scenario, ...] = ()
    # preview: the hypothetical workload.
    workload: Optional[object] = None
    # sweep / starve_search: the nominal-quota cell under study.
    node: Optional[str] = None
    flavor: Optional[str] = None
    resource: Optional[str] = None
    # sweep: additive deltas to evaluate, one lane each.
    deltas: Tuple[int, ...] = ()
    # drain_matrix: nodes to drain, one lane each.
    drain_nodes: Tuple[str, ...] = ()
    # starve_search: bisection budget.
    max_cut: int = 0
    points: int = 4
    rounds: int = 4
    # starve_search bracket state (mutated by fold): largest cut known
    # safe / smallest known (or assumed) starving. _hi starts one past
    # max_cut as a *virtual* bound; hi_confirmed records whether a probe
    # actually starved there.
    _lo: int = 0
    _hi: int = 0
    _hi_confirmed: bool = False
    _probed: List[int] = field(default_factory=list)
    _round: int = 0
    qid: int = field(default_factory=lambda: next(_ids))

    def cell_doc(self) -> dict:
        return {"node": self.node, "flavor": self.flavor,
                "resource": self.resource}


# -- constructor helpers -----------------------------------------------


def eta_query(cluster_queue: Optional[str] = None,
              scenarios: Tuple[Scenario, ...] = (),
              tenant: str = "default") -> Query:
    return Query(kind="eta", tenant=tenant, cluster_queue=cluster_queue,
                 scenarios=tuple(scenarios))


def preview_query(workload, cluster_queue: Optional[str] = None,
                  tenant: str = "default") -> Query:
    if workload is None:
        raise ValueError("preview_query requires a workload")
    return Query(kind="preview", tenant=tenant,
                 cluster_queue=cluster_queue, workload=workload)


def sweep_query(node: str, flavor: str, resource: str,
                deltas: Tuple[int, ...],
                tenant: str = "default") -> Query:
    if not deltas:
        raise ValueError("sweep_query requires at least one delta")
    return Query(kind="sweep", tenant=tenant, node=node, flavor=flavor,
                 resource=resource,
                 deltas=tuple(int(d) for d in deltas))


def drain_matrix_query(drain_nodes: Tuple[str, ...],
                       tenant: str = "default") -> Query:
    if not drain_nodes:
        raise ValueError("drain_matrix_query requires at least one node")
    return Query(kind="drain_matrix", tenant=tenant,
                 drain_nodes=tuple(drain_nodes))


def starve_search_query(node: str, flavor: str, resource: str,
                        max_cut: int, points: int = 4, rounds: int = 4,
                        tenant: str = "default") -> Query:
    """Binary-search "when does cutting this quota cell starve the
    cohort": finds the largest cut that keeps admitted-within-horizon
    at the base level, probing ``points`` cuts per coalescing window
    for at most ``rounds`` windows."""
    if max_cut < 1:
        raise ValueError("starve_search_query requires max_cut >= 1")
    return Query(kind="starve_search", tenant=tenant, node=node,
                 flavor=flavor, resource=resource, max_cut=int(max_cut),
                 points=max(1, int(points)), rounds=max(1, int(rounds)),
                 _lo=0, _hi=int(max_cut) + 1)


# -- lane expansion ----------------------------------------------------


def _search_grid(q: Query) -> List[int]:
    """Up to ``q.points`` integer cuts strictly inside the (_lo, _hi)
    bracket, evenly spaced, deduplicated, ascending."""
    lo, hi = q._lo, q._hi
    span = hi - lo
    if span <= 1:
        return []
    n = min(q.points, span - 1)
    cuts = sorted({lo + max(1, round(i * span / (n + 1)))
                   for i in range(1, n + 1)})
    return [c for c in cuts if lo < c < hi]


def expand(q: Query) -> List[Scenario]:
    """The scenario lanes this query contributes to the next batch.
    Previews contribute none — they ride the batch as per-workload
    ``preview()`` calls against the same pinned snapshot."""
    if q.kind == "eta":
        return list(q.scenarios)
    if q.kind == "preview":
        return []
    if q.kind == "sweep":
        return [
            Scenario(kind="quota", label=f"sweep:{d}", quota_deltas=(
                QuotaDelta(q.node, q.flavor, q.resource, d),))
            for d in q.deltas
        ]
    if q.kind == "drain_matrix":
        return [Scenario(kind="drain", label=f"drain:{n}", drain_node=n)
                for n in q.drain_nodes]
    if q.kind == "starve_search":
        return [
            Scenario(kind="quota", label=f"starve:{c}", quota_deltas=(
                QuotaDelta(q.node, q.flavor, q.resource, -c),))
            for c in _search_grid(q)
        ]
    raise ValueError(f"unknown query kind {q.kind!r}")


# -- result folding ----------------------------------------------------


def _lane_doc(sf) -> dict:
    """A ScenarioForecast document with the per-workload rows dropped —
    sweep/drain/search answers are aggregate questions."""
    d = sf.to_dict()
    d.pop("workloads", None)
    return d


def _starved(sf, base_sf) -> bool:
    return (not sf.ok) or (
        sf.admitted_within_horizon < base_sf.admitted_within_horizon)


def fold(q: Query, base_sf, lane_sfs: List, basis: str
         ) -> Tuple[Optional[dict], Optional[Query]]:
    """Fold the lane forecasts for ``q`` (ordered as :func:`expand`
    produced them) into ``(answer, continuation)``. Exactly one of the
    two is non-None; a continuation re-enters the coalescer queue."""
    if q.kind == "eta":
        base_doc = base_sf.to_dict()
        if q.cluster_queue is not None:
            base_doc["workloads"] = [
                w for w in base_doc["workloads"]
                if w["clusterQueue"] == q.cluster_queue
            ]
        return ({
            "kind": "eta",
            "basis": basis,
            "base": base_doc,
            "scenarios": [sf.to_dict() for sf in lane_sfs],
        }, None)

    if q.kind == "sweep":
        return ({
            "kind": "sweep",
            "basis": basis,
            "cell": q.cell_doc(),
            "points": [
                dict(_lane_doc(sf), delta=d)
                for d, sf in zip(q.deltas, lane_sfs)
            ],
        }, None)

    if q.kind == "drain_matrix":
        return ({
            "kind": "drain_matrix",
            "basis": basis,
            "rows": [
                dict(_lane_doc(sf), node=n)
                for n, sf in zip(q.drain_nodes, lane_sfs)
            ],
        }, None)

    if q.kind == "starve_search":
        cuts = _search_grid(q)
        q._round += 1
        for c, sf in zip(cuts, lane_sfs):
            q._probed.append(c)
            if _starved(sf, base_sf):
                if c < q._hi:
                    q._hi = c
                    q._hi_confirmed = True
            elif c > q._lo and c < q._hi:
                q._lo = c
        # Safe probes above a starving one are stale bracket-wise; the
        # invariant _lo < _hi is restored by the (c < _hi) filter above.
        if q._hi - q._lo > 1 and q._round < q.rounds and _search_grid(q):
            return (None, q)
        return ({
            "kind": "starve_search",
            "basis": basis,
            "cell": q.cell_doc(),
            "maxSafeCut": q._lo,
            "minStarvingCut": q._hi if q._hi_confirmed else None,
            "probedCuts": sorted(q._probed),
            "rounds": q._round,
        }, None)

    raise ValueError(f"fold() does not handle kind {q.kind!r}")


def fold_preview(q: Query, report) -> dict:
    """Deterministic preview answer: the PreviewReport document minus
    its wall-clock field."""
    d = report.to_dict()
    d.pop("wallS", None)
    return {"kind": "preview", "preview": d}
