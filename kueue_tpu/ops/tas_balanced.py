"""Device primitives for TAS balanced placement (no leaders yet).

Building blocks for the round-4 device balanced kernel (reference
tas_balanced_placement.go; host twin tas/snapshot.py
_find_best_domains_balanced / _select_optimal_domain_set /
_place_slices_balanced). Not yet wired into the admission scan — each
primitive is differential-tested against the host implementation
directly (tests/test_tas_balanced_ops.py).

The optimal-domain-set DP reduces to subset enumeration: for the
no-leader case, the host DP's answer over domains in a given order is
EXACTLY "among subsets of n_sel domains (positive-slice-state members
only, built in rank order) whose total state reaches the target AND
whose every proper prefix stays below it (the DP cannot extend an
exhausted prefix — `before_state <= 0: continue`; since prefix sums are
monotone, only the largest proper prefix binds): minimal total state,
then minimal bitmask" — the insertion-ordered setdefault tie-break
collapses to integer bitmask comparison (smaller highest-set-bit wins
first). Verified against the host DP on random instances INCLUDING
fragmented states that are not slice-size multiples. Subsets enumerate
as one static [2^BMAX, BMAX] bit-matrix contraction — MXU-shaped work;
sibling groups wider than BMAX must stay on the host.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

# Exact int64 math end to end (as in quota_ops): without x64 the module
# constants silently truncate to int32 and the _INF sentinel corrupts.
jax.config.update("jax_enable_x64", True)

BMAX = 14
_INF = jnp.int64(1) << 60

_bits_np = (
    (np.arange(1 << BMAX, dtype=np.int64)[:, None]
     >> np.arange(BMAX)) & 1
).astype(np.int32)
_BITS = jnp.asarray(_bits_np)  # i32[2^BMAX, BMAX]
_POPCNT = jnp.asarray(_bits_np.sum(1))  # i32[2^BMAX]
# Highest set bit per mask (0 for the empty mask): the rank of a
# subset's LAST-inserted member, whose removal gives the largest proper
# prefix.
_HIBIT = jnp.asarray(
    np.maximum(
        np.int64(np.floor(np.log2(np.maximum(
            np.arange(1 << BMAX, dtype=np.float64), 1.0
        )))), 0
    ).astype(np.int32)
)


def greedy_eval(slice_vals, state_vals, cand, target):
    """evaluateGreedyAssignment :28 (no leaders): walk candidates in the
    host BestFit order (-slice_state, state, level_values) — the caller
    must present domains in level_values-sorted index order (the device
    topology encode already sorts each level that way), taking whole
    positive slice states until the target is covered. Returns
    (fits bool, n_selected i32, last_slice i64 — the slice state of the
    last domain taken, 0 when none)."""
    d_n = slice_vals.shape[0]
    iota = jnp.arange(d_n)
    usable = cand & (slice_vals > 0)
    order = jnp.lexsort(
        (iota, state_vals, -slice_vals, jnp.where(usable, 0, 1))
    )
    v = jnp.where(usable, slice_vals, 0)[order]
    prefix_incl = jnp.cumsum(v)
    taken = (prefix_incl - v < target) & (v > 0)
    total = jnp.sum(jnp.where(taken, v, 0))
    fits = total >= target
    n_sel = jnp.sum(taken).astype(jnp.int32)
    last_slice = jnp.min(jnp.where(taken, v, _INF))
    last_slice = jnp.where(n_sel > 0, last_slice, 0)
    return fits, n_sel, last_slice


def seg_greedy_eval(slice_vals, state_vals, cand, grp, target):
    """Per-sibling-group evaluateGreedyAssignment :28 (no leaders):
    every group (label in ``grp``) walks its candidates in the host
    BestFit order (-slice_state, state, index), taking whole positive
    slice states until ``target`` is covered. Returns (fits bool[D],
    n_sel i64[D], last_slice i64[D]) indexed by GROUP id — position g
    holds group g's result; positions that are no group's id hold
    garbage and must be masked by the caller."""
    d_n = slice_vals.shape[0]
    iota = jnp.arange(d_n)
    usable = cand & (slice_vals > 0)
    order = jnp.lexsort(
        (iota, state_vals, -slice_vals, jnp.where(usable, 0, 1), grp)
    )
    v = jnp.where(usable, slice_vals, 0)[order]
    u = usable[order]
    g = grp[order]
    head = jnp.concatenate([jnp.ones(1, bool), g[1:] != g[:-1]])
    excl_glob = jnp.cumsum(v) - v
    seg_head = jax.lax.associative_scan(
        jnp.maximum, jnp.where(head, iota, -1)
    )
    excl = excl_glob - excl_glob[seg_head]
    taken = u & (excl < target)
    total = jnp.zeros(d_n, jnp.int64).at[g].add(
        jnp.where(taken, v, 0), mode="drop"
    )
    nsel = jnp.zeros(d_n, jnp.int64).at[g].add(
        taken.astype(jnp.int64), mode="drop"
    )
    last = jnp.full(d_n, _INF).at[g].min(
        jnp.where(taken, v, _INF), mode="drop"
    )
    last = jnp.where(nsel > 0, last, 0)
    return total >= target, nsel, last


def optimal_subset(state_vals, slice_vals, cand, n_sel, target_state,
                   rank):
    """selectOptimalDomainSetToFit :82 (no leaders) as subset
    enumeration: exactly ``n_sel`` members, every member a candidate
    with positive slice state, total state >= ``target_state``; minimal
    total state wins, ties resolved by minimal bitmask over ``rank``
    (the host's `ordered` position of each domain; rank >= BMAX excludes
    the domain). Returns (found bool, selected bool[D])."""
    d_n = state_vals.shape[0]
    participate = cand & (rank >= 0) & (rank < BMAX)
    rank_c = jnp.clip(rank, 0, BMAX - 1)
    state_by_bit = jnp.zeros(BMAX, jnp.int64).at[rank_c].add(
        jnp.where(participate, state_vals, 0), mode="drop"
    )
    ok_bit = jnp.zeros(BMAX, bool).at[rank_c].max(
        participate & (slice_vals > 0), mode="drop"
    )
    # Subset sums by doubling (mask m's low bit b splits [0, 2^(b+1)) into
    # copies without/with bit b) — BMAX concats replace a [2^BMAX, BMAX]
    # contraction, which XLA compiles and runs far faster under vmap.
    sums = jnp.zeros(1, jnp.int64)
    bad = jnp.zeros(1, bool)
    for b in range(BMAX):
        sums = jnp.concatenate([sums, sums + state_by_bit[b]])
        bad = jnp.concatenate([bad, bad | ~ok_bit[b]])
    # Host-DP reachability: the largest proper prefix (subset minus its
    # highest-rank member) must stay below the target, else the DP would
    # have stopped extending it.
    last_state = state_by_bit[_HIBIT]  # [2^BMAX]
    reachable = (sums - last_state) < target_state
    feas = (
        (_POPCNT == n_sel) & ~bad & (sums >= target_state) & reachable
    )
    mask_iota = jnp.arange(1 << BMAX, dtype=jnp.int64)
    key = jnp.where(feas, sums * (1 << BMAX) + mask_iota, _INF)
    win = jnp.argmin(key)
    found = key[win] < _INF
    selected = participate & (((win >> rank_c) & 1) == 1) & found
    return found, selected


def distribute_extras(slice_vals, selected, threshold, extras):
    """placeSlicesOnDomainsBalanced :150 tail: every selected domain gets
    ``threshold`` slices; the remaining ``extras`` distribute
    front-to-back in the given index order, each domain absorbing up to
    its capacity above the threshold. Returns (takes i64[D] in slices,
    leftover i64)."""
    avail = jnp.where(selected, jnp.maximum(slice_vals - threshold, 0), 0)
    excl = jnp.cumsum(avail) - avail
    take_extra = jnp.clip(extras - excl, 0, avail)
    takes = jnp.where(selected, threshold + take_extra, 0)
    leftover = extras - jnp.sum(take_extra)
    return takes, leftover
