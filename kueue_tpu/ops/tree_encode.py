"""Encode a host QuotaNode tree into padded QuotaTreeArrays.

The encoder is host-side (runs once per snapshot); everything downstream is
jittable. Flavors and resources get dense indices; nodes are laid out in an
arbitrary stable order with parent pointers, depth and cohort height
precomputed.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Tuple

import numpy as np

from kueue_tpu.cache.resource_node import QuotaNode
from kueue_tpu.core.resources import FlavorResource, UNLIMITED
from kueue_tpu.ops.quota_ops import MAX_DEPTH, QuotaTreeArrays

import jax.numpy as jnp


@dataclass
class TreeIndex:
    """Host-side mapping between names and dense indices."""

    node_of: Dict[str, int] = field(default_factory=dict)
    nodes: List[QuotaNode] = field(default_factory=list)
    flavor_of: Dict[str, int] = field(default_factory=dict)
    flavors: List[str] = field(default_factory=list)
    resource_of: Dict[str, int] = field(default_factory=dict)
    resources: List[str] = field(default_factory=list)

    def fr_index(self, fr: FlavorResource) -> Tuple[int, int]:
        return self.flavor_of[fr.flavor], self.resource_of[fr.resource]


def _collect(root: QuotaNode, out: List[QuotaNode]) -> None:
    out.append(root)
    for child in root.children:
        _collect(child, out)


def encode_tree(
    roots: List[QuotaNode],
    n_pad: int = 0,
    f_pad: int = 0,
    r_pad: int = 0,
) -> Tuple[QuotaTreeArrays, "TreeIndex", jnp.ndarray, jnp.ndarray]:
    """Returns (tree_arrays, index, usage[N,F,R], is_cq[N]).

    subtree_quota is filled from the host tree (QuotaNode.subtree_quota,
    already exact after update_tree), and usage includes the cohort
    roll-ups — no device computation is needed to finish the encoding.
    ``quota_ops.compute_subtree`` recomputes both on device when arrays are
    built synthetically.
    """
    idx = TreeIndex()
    order: List[QuotaNode] = []
    for root in roots:
        _collect(root, order)
    for node in order:
        idx.node_of[node.name] = len(idx.nodes)
        idx.nodes.append(node)
        for fr in node.quotas:
            if fr.flavor not in idx.flavor_of:
                idx.flavor_of[fr.flavor] = len(idx.flavors)
                idx.flavors.append(fr.flavor)
            if fr.resource not in idx.resource_of:
                idx.resource_of[fr.resource] = len(idx.resources)
                idx.resources.append(fr.resource)

    n = max(len(idx.nodes), n_pad, 1)
    f = max(len(idx.flavors), f_pad, 1)
    r = max(len(idx.resources), r_pad, 1)

    parent = np.full(n, -1, dtype=np.int32)
    active = np.zeros(n, dtype=bool)
    depth = np.zeros(n, dtype=np.int32)
    height = np.zeros(n, dtype=np.int32)
    is_cq = np.zeros(n, dtype=bool)
    nominal = np.zeros((n, f, r), dtype=np.int64)
    borrow_limit = np.full((n, f, r), UNLIMITED, dtype=np.int64)
    has_borrow = np.zeros((n, f, r), dtype=bool)
    lend_limit = np.full((n, f, r), UNLIMITED, dtype=np.int64)
    has_lend = np.zeros((n, f, r), dtype=bool)
    usage = np.zeros((n, f, r), dtype=np.int64)
    subtree = np.zeros((n, f, r), dtype=np.int64)

    for i, node in enumerate(idx.nodes):
        active[i] = True
        is_cq[i] = node.is_cq
        if node.parent is not None:
            parent[i] = idx.node_of[node.parent.name]
        d = sum(1 for _ in node.path_self_to_root()) - 1
        if d > MAX_DEPTH:
            raise ValueError(
                f"cohort tree depth {d} exceeds MAX_DEPTH={MAX_DEPTH}"
            )
        depth[i] = d
        height[i] = node.height()
        for fr, cell in node.quotas.items():
            fi, ri = idx.fr_index(fr)
            nominal[i, fi, ri] = cell.nominal
            if cell.borrowing_limit is not None:
                borrow_limit[i, fi, ri] = cell.borrowing_limit
                has_borrow[i, fi, ri] = True
            if cell.lending_limit is not None:
                lend_limit[i, fi, ri] = cell.lending_limit
                has_lend[i, fi, ri] = True
        for fr, v in node.usage.items():
            fi, ri = idx.fr_index(fr)
            usage[i, fi, ri] = v
        for fr, v in node.subtree_quota.items():
            fi, ri = idx.fr_index(fr)
            subtree[i, fi, ri] = v

    # Numpy leaves throughout: the cycle encoder ships the finished
    # pytrees to the device in ONE batched transfer (models/encode.py) —
    # per-field transfers cost a round trip each over a remote transport.
    tree = QuotaTreeArrays(
        parent=parent,
        active=active,
        depth=depth,
        height=height,
        nominal=nominal,
        borrow_limit=borrow_limit,
        has_borrow_limit=has_borrow,
        lend_limit=lend_limit,
        has_lend_limit=has_lend,
        subtree_quota=subtree,
    )
    return tree, idx, usage, is_cq


class GroupLayout:
    """Forest grouping: nodes re-indexed as [group, local] where a group is
    one root's tree. Cohort trees share no quota, so the admission scan can
    process one entry per group simultaneously — scan length drops from W to
    max-entries-per-group. Built host-side from the flat arrays (static per
    spec change)."""

    def __init__(
        self, parent: np.ndarray, active: np.ndarray, root_merge=None
    ) -> None:
        """``root_merge`` (optional): root node -> merge label; roots with
        the same label share one group (used when trees share external
        state, e.g. a TAS topology, and must serialize their scans)."""
        n = parent.shape[0]
        root_of = np.arange(n)
        # Resolve roots by pointer-jumping (depth bounded by MAX_DEPTH).
        for _ in range(MAX_DEPTH + 1):
            has_parent = parent[root_of] >= 0
            root_of = np.where(has_parent, parent[root_of], root_of)
        roots = sorted(set(root_of[active].tolist())) if active.any() else [0]
        if root_merge:
            label_of = {r: root_merge.get(r, r) for r in roots}
            labels = sorted(set(label_of.values()))
            g_of_label = {lb: g for g, lb in enumerate(labels)}
            g_of_root = {r: g_of_label[label_of[r]] for r in roots}
            roots = labels
        else:
            g_of_root = {r: g for g, r in enumerate(roots)}
        self.n_groups = max(len(roots), 1)
        self.flat_to_group = np.zeros(n, dtype=np.int32)
        self.flat_to_local = np.zeros(n, dtype=np.int32)
        counts = np.zeros(self.n_groups, dtype=np.int64)
        for i in range(n):
            if not active[i]:
                continue
            g = g_of_root[root_of[i]]
            self.flat_to_group[i] = g
            self.flat_to_local[i] = counts[g]
            counts[g] += 1
        self.n_local = max(int(counts.max()) if len(counts) else 1, 1)
        # node_sel[g, l] = flat node index (or 0, masked by local_valid).
        self.node_sel = np.zeros((self.n_groups, self.n_local), dtype=np.int32)
        self.local_valid = np.zeros((self.n_groups, self.n_local), dtype=bool)
        for i in range(n):
            if active[i]:
                g, l = self.flat_to_group[i], self.flat_to_local[i]
                self.node_sel[g, l] = i
                self.local_valid[g, l] = True
        # Local-id ancestor chains [G, Nm, D+1], padded by repeating the
        # local root (mirrors ops.quota_ops.ancestor_chain semantics).
        self.chain_local = np.zeros(
            (self.n_groups, self.n_local, MAX_DEPTH + 1), dtype=np.int32
        )
        for i in range(n):
            if not active[i]:
                continue
            g, l = self.flat_to_group[i], self.flat_to_local[i]
            cur = i
            for d in range(MAX_DEPTH + 1):
                self.chain_local[g, l, d] = self.flat_to_local[cur]
                if parent[cur] >= 0:
                    cur = parent[cur]

    def as_jax(self):
        return (
            jnp.asarray(self.flat_to_group),
            jnp.asarray(self.flat_to_local),
            jnp.asarray(self.node_sel),
            jnp.asarray(self.local_valid),
            jnp.asarray(self.chain_local),
        )

    def as_numpy(self):
        return (
            self.flat_to_group,
            self.flat_to_local,
            self.node_sel,
            self.local_valid,
            self.chain_local,
        )
