"""Encode a host QuotaNode tree into padded QuotaTreeArrays.

The encoder is host-side (runs once per snapshot); everything downstream is
jittable. Flavors and resources get dense indices; nodes are laid out in an
arbitrary stable order with parent pointers, depth and cohort height
precomputed.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Tuple

import numpy as np

from kueue_tpu.cache.resource_node import QuotaNode
from kueue_tpu.core.resources import FlavorResource, UNLIMITED
from kueue_tpu.ops.quota_ops import MAX_DEPTH, QuotaTreeArrays

import jax.numpy as jnp


@dataclass
class TreeIndex:
    """Host-side mapping between names and dense indices."""

    node_of: Dict[str, int] = field(default_factory=dict)
    nodes: List[QuotaNode] = field(default_factory=list)
    flavor_of: Dict[str, int] = field(default_factory=dict)
    flavors: List[str] = field(default_factory=list)
    resource_of: Dict[str, int] = field(default_factory=dict)
    resources: List[str] = field(default_factory=list)

    def fr_index(self, fr: FlavorResource) -> Tuple[int, int]:
        return self.flavor_of[fr.flavor], self.resource_of[fr.resource]


def _collect(root: QuotaNode, out: List[QuotaNode]) -> None:
    out.append(root)
    for child in root.children:
        _collect(child, out)


def encode_tree(
    roots: List[QuotaNode],
    n_pad: int = 0,
    f_pad: int = 0,
    r_pad: int = 0,
) -> Tuple[QuotaTreeArrays, "TreeIndex", jnp.ndarray, jnp.ndarray]:
    """Returns (tree_arrays, index, cq_usage[N,F,R], is_cq[N]).

    subtree_quota in the returned arrays is zero; callers run
    ``quota_ops.compute_subtree`` (or copy host-computed values) to fill it.
    """
    idx = TreeIndex()
    order: List[QuotaNode] = []
    for root in roots:
        _collect(root, order)
    for node in order:
        idx.node_of[node.name] = len(idx.nodes)
        idx.nodes.append(node)
        for fr in node.quotas:
            if fr.flavor not in idx.flavor_of:
                idx.flavor_of[fr.flavor] = len(idx.flavors)
                idx.flavors.append(fr.flavor)
            if fr.resource not in idx.resource_of:
                idx.resource_of[fr.resource] = len(idx.resources)
                idx.resources.append(fr.resource)

    n = max(len(idx.nodes), n_pad, 1)
    f = max(len(idx.flavors), f_pad, 1)
    r = max(len(idx.resources), r_pad, 1)

    parent = np.full(n, -1, dtype=np.int32)
    active = np.zeros(n, dtype=bool)
    depth = np.zeros(n, dtype=np.int32)
    height = np.zeros(n, dtype=np.int32)
    is_cq = np.zeros(n, dtype=bool)
    nominal = np.zeros((n, f, r), dtype=np.int64)
    borrow_limit = np.full((n, f, r), UNLIMITED, dtype=np.int64)
    has_borrow = np.zeros((n, f, r), dtype=bool)
    lend_limit = np.full((n, f, r), UNLIMITED, dtype=np.int64)
    has_lend = np.zeros((n, f, r), dtype=bool)
    usage = np.zeros((n, f, r), dtype=np.int64)

    for i, node in enumerate(idx.nodes):
        active[i] = True
        is_cq[i] = node.is_cq
        if node.parent is not None:
            parent[i] = idx.node_of[node.parent.name]
        d = sum(1 for _ in node.path_self_to_root()) - 1
        if d > MAX_DEPTH:
            raise ValueError(
                f"cohort tree depth {d} exceeds MAX_DEPTH={MAX_DEPTH}"
            )
        depth[i] = d
        height[i] = node.height()
        for fr, cell in node.quotas.items():
            fi, ri = idx.fr_index(fr)
            nominal[i, fi, ri] = cell.nominal
            if cell.borrowing_limit is not None:
                borrow_limit[i, fi, ri] = cell.borrowing_limit
                has_borrow[i, fi, ri] = True
            if cell.lending_limit is not None:
                lend_limit[i, fi, ri] = cell.lending_limit
                has_lend[i, fi, ri] = True
        for fr, v in node.usage.items():
            fi, ri = idx.fr_index(fr)
            usage[i, fi, ri] = v

    tree = QuotaTreeArrays(
        parent=jnp.asarray(parent),
        active=jnp.asarray(active),
        depth=jnp.asarray(depth),
        height=jnp.asarray(height),
        nominal=jnp.asarray(nominal),
        borrow_limit=jnp.asarray(borrow_limit),
        has_borrow_limit=jnp.asarray(has_borrow),
        lend_limit=jnp.asarray(lend_limit),
        has_lend_limit=jnp.asarray(has_lend),
        subtree_quota=jnp.zeros((n, f, r), dtype=jnp.int64),
    )
    return tree, idx, jnp.asarray(usage), jnp.asarray(is_cq)
