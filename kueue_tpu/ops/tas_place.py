"""Device-side TAS gang placement: the full phase 1/2a/2b pipeline.

Tensor twin of tas/snapshot.py find_topology_assignment (reference
tas_flavor_snapshot.go:943 findTopologyAssignment) for the device-eligible
class: no leaders (encode gates those to the host path; balanced
placement runs on device via ``_balanced_place`` when the DP widths fit
BMAX, and inner slice layers via per-level ``sizes``). Supports required
/ preferred (walk-up + top-level gather) / unconstrained modes and the
outer slice constraint (sliceSize pinned at a sliceRequiredLevel) — the
long-context/ICI-critical case.

Layout: every TAS flavor's topology becomes right-padded per-level arrays
(axis D = max domains per level across flavors, LMAX static levels), with
domains at each level PRE-SORTED by their levelValues tuple so the host's
lexicographic tie-break equals the device index order. The phase-2b greedy
descent ("take domains in BestFit order until one can finish, then pick the
smallest sufficient finisher" — updateCountsToMinimumGeneric :1578) is one
segmented prefix-sum + masked argmin per level, for both the free
slice-redistribution region above the slice level and the per-parent pods
region at/below it.

All level indices (requested, slice, leaf) are traced values, so one
compiled kernel serves every flavor/request shape; the static loops run
LMAX times with masks.
"""

from __future__ import annotations

from typing import List, NamedTuple, Tuple

import jax

# Exact int64 placement math; without this a standalone import silently
# truncates _INF (and every i64 tensor) to int32.
jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp
import numpy as np

LMAX = 8
_INF = jnp.int64(1) << 60


class TASDeviceTopo(NamedTuple):
    """Padded topologies for all TAS flavors (leading axis T).

    The capacity resource axis is the cycle resource index PLUS one trailing
    "implicit pods" column (reference resources.CountIn bounds pod counts by
    the node's "pods" capacity even when unrequested): the per-entry TAS
    request vector carries 1 in that column when "pods" isn't requested,
    reproducing the bound as ordinary division; INF capacity when the fleet
    doesn't track pods."""

    n_levels: jnp.ndarray  # i32[T]
    level_size: jnp.ndarray  # i32[T, LMAX]
    parent_idx: jnp.ndarray  # i32[T, LMAX, D]: level-l domain -> parent pos
    leaf_cap: jnp.ndarray  # i64[T, D, R+1]


def encode_device_topos(
    tas_flavors: dict, flavor_names: List[str], resource_of: dict
) -> Tuple[TASDeviceTopo, List[object], List[List[int]]]:
    """Build TASDeviceTopo from host TASFlavorSnapshots.

    Returns (topo, per-T host snapshots, per-T leaf permutation mapping the
    device leaf position -> host leaf index). Only flavors in
    ``flavor_names`` (device-eligible) are encoded.
    """
    r_n = max(len(resource_of), 1)
    t_n = max(len(flavor_names), 1)
    lmax_sizes = [1]
    per_flavor = []
    for name in flavor_names:
        tas = tas_flavors[name]
        sizes = [len(lvl) for lvl in tas.domains_per_level]
        lmax_sizes.extend(sizes)
        per_flavor.append(tas)
    # Power-of-two bucket (min 8) for the domain axis: every kernel masks
    # the pad rows via level_size (``valid_at``), so padding is inert, and
    # bucketing lets randomized topologies of similar width share one
    # compiled program — the same compile-reuse trick as the W axis.
    d_n = max(8, 1 << (max(lmax_sizes) - 1).bit_length())

    n_levels = np.ones(t_n, np.int32)
    level_size = np.zeros((t_n, LMAX), np.int32)
    parent_idx = np.zeros((t_n, LMAX, d_n), np.int32)
    leaf_cap = np.zeros((t_n, d_n, r_n + 1), np.int64)
    leaf_cap[:, :, r_n] = 1 << 60  # implicit-pods column: INF by default
    leaf_perm: List[List[int]] = []

    for t, tas in enumerate(per_flavor):
        nl = len(tas.level_keys)
        n_levels[t] = nl
        # Sort each level's domains by levelValues (the host tie-break);
        # keep position maps for parent indices.
        sorted_levels = []
        pos_maps = []
        for lvl in tas.domains_per_level:
            s = sorted(range(len(lvl)), key=lambda i: lvl[i].level_values)
            sorted_levels.append([lvl[i] for i in s])
            pos_maps.append({id(lvl[i]): j for j, i in enumerate(s)})
        for l in range(nl):
            level_size[t, l] = len(sorted_levels[l])
            if l >= 1:
                for j, dom in enumerate(sorted_levels[l]):
                    parent_idx[t, l, j] = pos_maps[l - 1][id(dom.parent)]
        host_leaf_index = {leaf.id: i for i, leaf in enumerate(tas.leaves)}
        perm = []
        for j, dom in enumerate(sorted_levels[nl - 1]):
            hi = host_leaf_index[dom.id]
            perm.append(hi)
            for r, ri in tas._res_index.items():
                ci = resource_of.get(r)
                if ci is not None:
                    leaf_cap[t, j, ci] = tas._leaf_cap[hi, ri]
                if r == "pods":
                    leaf_cap[t, j, r_n] = tas._leaf_cap[hi, ri]
        leaf_perm.append(perm)

    return (
        TASDeviceTopo(
            n_levels=np.asarray(n_levels),
            level_size=np.asarray(level_size),
            parent_idx=np.asarray(parent_idx),
            leaf_cap=np.asarray(leaf_cap),
        ),
        per_flavor,
        leaf_perm,
    )


def _seg_excl_cumsum(vals, head):
    c = jnp.cumsum(vals)
    excl = c - vals
    n = head.shape[0]
    head_idx = jnp.where(head, jnp.arange(n), -1)
    seg_head = jax.lax.associative_scan(jnp.maximum, head_idx)
    return excl - excl[seg_head], seg_head


def _seg_min_scan(vals, head):
    """Per-position minimum over the position's WHOLE segment: scatter-min
    into the segment-head slot, then gather back."""
    n = head.shape[0]
    head_idx = jnp.where(head, jnp.arange(n), -1)
    seg_head = jax.lax.associative_scan(jnp.maximum, head_idx)
    seg_total = jnp.full(n, _INF, vals.dtype).at[seg_head].min(vals)
    return seg_total[seg_head]


def segmented_greedy(
    values: jnp.ndarray,  # i64[D] capacity per candidate (in units)
    cand: jnp.ndarray,  # bool[D] candidate mask
    seg: jnp.ndarray,  # i32[D] segment id (monotone grouping key)
    target: jnp.ndarray,  # i64[D] per-position target of its segment
    tiebreak_state: jnp.ndarray,  # i64[D] host BestFit secondary key
    primary_desc: jnp.ndarray,  # i64[D] host BestFit primary key (desc)
) -> jnp.ndarray:
    """One host ``updateCountsToMinimum`` pass per segment: walk candidates
    in (primary desc, state asc, index) order, taking full capacity until a
    candidate can finish the remaining target, then give the remainder to
    the smallest sufficient candidate at/after that point. Returns takes
    [D] in ``values`` units."""
    d_n = values.shape[0]
    iota = jnp.arange(d_n)
    order = jnp.lexsort((
        iota, tiebreak_state, -primary_desc, jnp.where(cand, 0, 1), seg
    )).astype(jnp.int32)
    v = jnp.where(cand, values, 0)[order]
    c = cand[order]
    s = seg[order]
    t_seg = target[order]
    head = jnp.concatenate([jnp.ones(1, bool), s[1:] != s[:-1]])
    prefix, _ = _seg_excl_cumsum(v, head)
    remaining = t_seg - prefix  # target left before this candidate
    can_finish = c & (v >= remaining) & (remaining > 0)
    # First finisher per segment: segment-min of (can_finish ? position : INF).
    pos_key = jnp.where(can_finish, iota, _INF)
    first_fin = _seg_min_scan(pos_key, head)  # per-position segment min
    jstar = first_fin  # i64 position of first finisher (INF if none)
    before_star = iota < jstar
    at_or_after = iota >= jstar
    # remaining at jstar, broadcast per segment: gather via remaining[jstar]
    jstar_c = jnp.clip(jstar, 0, d_n - 1).astype(jnp.int32)
    rem_star = jnp.where(jstar < _INF, remaining[jstar_c], 0)
    # Best-fit winner: min (value, position) among sufficient candidates at
    # or after jstar.
    suff = c & at_or_after & (v >= rem_star) & (rem_star > 0)
    bf_key = jnp.where(suff, v * d_n + iota, _INF)
    bf_min = _seg_min_scan(bf_key, head)
    winner = suff & (bf_key == bf_min)
    takes_sorted = jnp.where(
        winner, rem_star,
        jnp.where(c & before_star & (remaining > 0), v, 0),
    )
    takes = jnp.zeros(d_n, jnp.int64).at[order].set(takes_sorted)
    return takes


def segmented_greedy_leader(
    values: jnp.ndarray,  # i64[D] plain capacity (slice/pod units)
    values_wl: jnp.ndarray,  # i64[D] with-leader capacity
    lead: jnp.ndarray,  # bool[D] domain can host the leader
    cand: jnp.ndarray,  # bool[D]
    seg: jnp.ndarray,  # i32[D]
    target: jnp.ndarray,  # i64[D] per-position segment target
    need_leader: jnp.ndarray,  # bool[D] segment consumes a leader
    tiebreak_state: jnp.ndarray,  # i64[D]
    primary_desc: jnp.ndarray,  # i64[D]
    order_rank: jnp.ndarray = None,  # i64[D] explicit walk order override
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """``updateCountsToMinimumGeneric`` with a leader (host
    _update_counts_to_minimum, snapshot.py:626): the first leader-hosting
    candidate jL in walk order consumes min(with_leader, remaining) and
    keeps the leader; everyone else follows the standard walk
    (full takes until a finisher, then the BestFit winner takes the
    remainder). The leader branch engages only when no standard finisher
    precedes jL — otherwise the walk finishes early and the leader is
    dropped, exactly like the host (the early-return in the non-leader
    branch never checks remaining_leaders).

    Returns (takes i64[D], leader_at bool[D] — one-hot per engaged
    segment marking the domain that kept the leader)."""
    d_n = values.shape[0]
    iota = jnp.arange(d_n)
    if order_rank is None:
        order = jnp.lexsort((
            iota, tiebreak_state, -primary_desc, jnp.where(cand, 0, 1), seg
        )).astype(jnp.int32)
    else:
        order = jnp.lexsort((
            iota, order_rank, jnp.where(cand, 0, 1), seg
        )).astype(jnp.int32)
    v = jnp.where(cand, values, 0)[order]
    vwl = jnp.where(cand, values_wl, 0)[order]
    ld = (lead & cand)[order]
    c = cand[order]
    s = seg[order]
    t_seg = target[order]
    nl = need_leader[order]
    head = jnp.concatenate([jnp.ones(1, bool), s[1:] != s[:-1]])

    prefix, _ = _seg_excl_cumsum(v, head)
    rem0 = t_seg - prefix
    can_fin = c & (v >= rem0) & (rem0 > 0)
    jF = _seg_min_scan(jnp.where(can_fin, iota, _INF), head)
    jL = _seg_min_scan(jnp.where(ld, iota, _INF), head)
    engaged = nl & (jL < _INF) & (jL <= jF)

    # Standard walk (exact segmented_greedy semantics).
    jF_c = jnp.clip(jF, 0, d_n - 1).astype(jnp.int32)
    rem_star = jnp.where(jF < _INF, rem0[jF_c], 0)
    suff = c & (iota >= jF) & (v >= rem_star) & (rem_star > 0)
    bf_key = jnp.where(suff, v * d_n + iota, _INF)
    winner = suff & (bf_key == _seg_min_scan(bf_key, head))
    takes_std = jnp.where(
        winner, rem_star,
        jnp.where(c & (iota < jF) & (rem0 > 0), v, 0),
    )

    # Leader-engaged walk: jL takes min(with_leader, remaining-at-jL);
    # positions after jL see the budget shifted by (v[jL] - takeL)
    # because the standard prefix counted v[jL].
    jL_c = jnp.clip(jL, 0, d_n - 1).astype(jnp.int32)
    remL = jnp.where(jL < _INF, jnp.maximum(rem0[jL_c], 0), 0)
    tL = jnp.minimum(vwl[jL_c], remL)
    rem2 = rem0 + jnp.where(jL < _INF, v[jL_c] - tL, 0)
    can_fin2 = c & (iota > jL) & (v >= rem2) & (rem2 > 0)
    jF2 = _seg_min_scan(jnp.where(can_fin2, iota, _INF), head)
    jF2_c = jnp.clip(jF2, 0, d_n - 1).astype(jnp.int32)
    rem_star2 = jnp.where(jF2 < _INF, rem2[jF2_c], 0)
    suff2 = c & (iota >= jF2) & (v >= rem_star2) & (rem_star2 > 0)
    bf_key2 = jnp.where(suff2, v * d_n + iota, _INF)
    winner2 = suff2 & (bf_key2 == _seg_min_scan(bf_key2, head))
    at_jL = iota == jL
    takes_led = jnp.where(
        at_jL, tL,
        jnp.where(
            winner2, rem_star2,
            jnp.where(
                c & (iota < jL) & (rem0 > 0), v,
                jnp.where(
                    c & (iota > jL) & (iota < jF2) & (rem2 > 0), v, 0
                ),
            ),
        ),
    )

    takes_sorted = jnp.where(engaged, takes_led, takes_std)
    leader_sorted = engaged & at_jL
    takes = jnp.zeros(d_n, jnp.int64).at[order].set(takes_sorted)
    leader_at = jnp.zeros(d_n, bool).at[order].set(leader_sorted)
    return takes, leader_at


def entry_leaf_cap(arrays, t_idx, w=None):
    """Per-entry leaf capacity for placement probes: the entry's filtered
    row (node selector / taint matching) where ``w_tas_has_cap``, else the
    topology's static capacity. ``w`` optionally gathers a subset of
    entries (e.g. the scan step's per-group workload indices)."""
    leaf = arrays.tas_topo.leaf_cap[t_idx]
    if arrays.w_tas_cap is None:
        return leaf
    has = arrays.w_tas_has_cap if w is None else arrays.w_tas_has_cap[w]
    cap = arrays.w_tas_cap if w is None else arrays.w_tas_cap[w]
    return jnp.where(has[:, None, None], cap, leaf)


def _balanced_place(
    topo: TASDeviceTopo,
    t: jnp.ndarray,
    states: jnp.ndarray,  # i64[LMAX, D] phase-1 pod states
    sls: jnp.ndarray,  # i64[LMAX, D] phase-1 slice states
    rl: jnp.ndarray,  # i32 requested level
    sl: jnp.ndarray,  # i32 slice level
    ss: jnp.ndarray,  # i64 slice size (>=1)
    slice_count: jnp.ndarray,  # i64
    count: jnp.ndarray,  # i64
    leaf_l: jnp.ndarray,  # i32
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Device twin of the host balanced-placement pipeline (reference
    tas_balanced_placement.go:232 findBestDomainsForBalancedPlacement +
    :293 applyBalancedPlacementAlgorithm + :150
    placeSlicesOnDomainsBalanced; host tas/snapshot.py
    _find_best_domains_balanced/_apply_balanced_placement), no leaders,
    no inner slice layers (encode gates both to the host).

    Returns (ok bool, leaf_take i64[D] pods). Every sibling group at the
    requested level is evaluated in parallel with segmented reductions;
    the two optimal-domain-set DPs run as 2^BMAX subset enumerations
    (encode guarantees the DP input widths fit in BMAX for balanced
    entries — wider entries stay on the host path)."""
    from kueue_tpu.ops import tas_balanced as _bal

    d_n = states.shape[1]
    iota = jnp.arange(d_n)

    def valid_at(l):
        return iota < topo.level_size[t, jnp.clip(l, 0, LMAX - 1)]

    rl_c = jnp.clip(rl, 0, LMAX - 1)
    rl1_c = jnp.clip(rl + 1, 0, LMAX - 1)
    valid_rl = valid_at(rl)
    has_children = rl < leaf_l
    valid_rl1 = valid_at(rl + 1) & has_children

    # Sibling groups at the requested level: one group per level-(rl-1)
    # parent; the whole level is a single group when rl == 0
    # (findBestDomainsForBalancedPlacement :238-247).
    grp_rl = jnp.where(rl > 0, topo.parent_idx[t, rl_c], 0)
    pidx_rl1 = topo.parent_idx[t, rl1_c]
    grp_rl1 = grp_rl[pidx_rl1]

    # Greedy evaluation runs one level below the request when the slice
    # level is deeper (:255 lowerLevelDomains), else on the group itself.
    lower_is_child = rl < sl
    st_rl = jnp.where(valid_rl, states[rl_c], 0)
    sl_rl = jnp.where(valid_rl, sls[rl_c], 0)
    st_rl1 = jnp.where(valid_rl1, states[rl1_c], 0)
    sl_rl1 = jnp.where(valid_rl1, sls[rl1_c], 0)
    grp_low = jnp.where(lower_is_child, grp_rl1, grp_rl)
    low_valid = jnp.where(lower_is_child, valid_rl1, valid_rl)
    st_low = jnp.where(lower_is_child, st_rl1, st_rl)
    sl_low = jnp.where(lower_is_child, sl_rl1, sl_rl)

    fits_g, nsel_g, last_g = _bal.seg_greedy_eval(
        sl_low, st_low, low_valid, grp_low, slice_count
    )
    # balanceThresholdValue :66 (no leaders).
    thr_g = jnp.minimum(
        slice_count // jnp.maximum(nsel_g, 1), last_g
    )
    thr_g = jnp.where(fits_g & (nsel_g > 0), thr_g, 0)

    # pruneDomainsBelowThreshold :363: drop children below the group's
    # threshold, refill the candidates from the survivors, then drop
    # candidates that fell below the threshold themselves.
    thr_child = thr_g[grp_rl1]
    keep_child = valid_rl1 & (sl_rl1 >= thr_child)
    cand_state2 = jnp.where(
        has_children,
        jnp.zeros(d_n, jnp.int64).at[pidx_rl1].add(
            jnp.where(keep_child, st_rl1, 0), mode="drop"
        ),
        st_rl,
    )
    child_slice_sum = jnp.zeros(d_n, jnp.int64).at[pidx_rl1].add(
        jnp.where(keep_child, sl_rl1, 0), mode="drop"
    )
    cand_sls2 = jnp.where(
        rl == sl, cand_state2 // ss,
        jnp.where(has_children, child_slice_sum, sl_rl),
    )
    keep_cand = valid_rl & (cand_sls2 >= thr_g[grp_rl])
    cand_state3 = jnp.where(keep_cand, cand_state2, 0)
    cand_sls3 = jnp.where(keep_cand, cand_sls2, 0)

    fits2_g, count2_g, _ = _bal.seg_greedy_eval(
        cand_sls3, cand_state3, valid_rl, grp_rl, slice_count
    )

    # Best group: threshold desc, post-prune count asc, group order
    # (:276-287 keeps the first winner on ties).
    ok_g = fits_g & (thr_g >= 1) & fits2_g
    ordg = jnp.lexsort(
        (iota, count2_g, -thr_g, jnp.where(ok_g, 0, 1))
    )
    win_g = ordg[0]
    any_g = ok_g[win_g]
    thr = thr_g[win_g]

    # applyBalancedPlacementAlgorithm :293. When the request sits above
    # the slice level, a first DP (entropy-prioritized ordering,
    # selectOptimalDomainSetToFit :82) picks the minimal candidate set
    # and the placement happens one level down on its children.
    member = valid_rl & (grp_rl == win_g)
    kc_full = keep_child & keep_cand[pidx_rl1]
    s_child = jnp.where(kc_full, st_rl1, 0).astype(jnp.float64)
    log_terms = jnp.where(
        s_child > 0, s_child * jnp.log2(jnp.maximum(s_child, 1.0)), 0.0
    )
    tot_c = jnp.zeros(d_n, jnp.float64).at[pidx_rl1].add(
        s_child, mode="drop"
    )
    sum_t = jnp.zeros(d_n, jnp.float64).at[pidx_rl1].add(
        log_terms, mode="drop"
    )
    entropy = jnp.where(
        tot_c > 0,
        jnp.log2(jnp.maximum(tot_c, 1.0)) - sum_t / jnp.maximum(tot_c, 1.0),
        0.0,
    )
    order1 = jnp.lexsort(
        (iota, -entropy, -cand_sls3, jnp.where(member, 0, 1))
    )
    rank1 = jnp.zeros(d_n, jnp.int32).at[order1].set(
        jnp.arange(d_n, dtype=jnp.int32)
    )
    rank1 = jnp.where(member, rank1, _bal.BMAX)
    n1 = count2_g[win_g].astype(jnp.int32)
    found1, sel1 = _bal.optimal_subset(
        cand_state3, cand_sls3, member, n1, slice_count * ss, rank1
    )

    # The placement set (curr): children of the DP-selected candidates
    # when the request is above the slice level, else the pruned group.
    curr_mask = jnp.where(
        lower_is_child, valid_rl1 & sel1[pidx_rl1], member
    )
    st_low_p = jnp.where(
        lower_is_child, jnp.where(kc_full, st_rl1, 0), cand_state3
    )
    sl_low_p = jnp.where(
        lower_is_child, jnp.where(kc_full, sl_rl1, 0), cand_sls3
    )

    # placeSlicesOnDomainsBalanced :150: second DP in level-values order.
    zero_grp = jnp.zeros(d_n, jnp.int32)
    fits_c_g, n2_g, _ = _bal.seg_greedy_eval(
        sl_low_p, st_low_p, curr_mask, zero_grp, slice_count
    )
    fits_c = fits_c_g[0]
    n2 = n2_g[0].astype(jnp.int32)
    rank2 = jnp.cumsum(curr_mask.astype(jnp.int32)) - 1
    rank2 = jnp.where(curr_mask, rank2, _bal.BMAX)
    found2, sel2 = _bal.optimal_subset(
        st_low_p, sl_low_p, curr_mask, n2, slice_count * ss, rank2
    )

    # Every selected domain gets the threshold; extras distribute
    # front-to-back in (-slice_state, state, level_values) order.
    n_res = jnp.sum(sel2).astype(jnp.int64)
    thr_ok = slice_count >= n_res * thr
    order3 = jnp.lexsort(
        (iota, st_low_p, -sl_low_p, jnp.where(sel2, 0, 1))
    )
    extras = slice_count - n_res * thr
    takes_s, leftover = _bal.distribute_extras(
        sl_low_p[order3], sel2[order3], thr, extras
    )
    take_low = jnp.zeros(d_n, jnp.int64).at[order3].set(takes_s) * ss

    ok = (
        any_g
        & jnp.where(lower_is_child, found1, True)
        & fits_c & found2 & thr_ok & (leftover == 0)
    )

    # Pruned per-level states for the descent: the prune clears whole
    # subtrees, so a domain below the prune level survives iff its
    # ancestor chain does.
    keep_levels = [valid_at(0)]
    for l in range(1, LMAX):
        pidx_l = topo.parent_idx[t, l]
        prev = keep_levels[l - 1][pidx_l]
        at_prune = l == rl + 1
        k_here = (jnp.where(valid_at(l), sls[l], 0) >= thr) \
            & keep_cand[pidx_l]
        keep_levels.append(
            valid_at(l) & jnp.where(at_prune, k_here, prev)
        )
    states_p = jnp.stack([
        jnp.where(keep_levels[l], states[l], 0) for l in range(LMAX)
    ])
    sls_p = jnp.stack([
        jnp.where(keep_levels[l], sls[l], 0) for l in range(LMAX)
    ])

    # Descent: per-parent distribution at every level (the balanced path
    # skips the free slice-redistribution loop — snapshot.py:1132), in
    # OUTER slice units above/at the slice level (reference :1104) and in
    # pods below it. Walk order stays the phase-1 (pruned) slice states;
    # values/targets rescale by the slice size (snapshot.py:1153-1167).
    low_l = jnp.where(lower_is_child, rl + 1, rl)
    take_b = take_low
    cur = low_l
    for _ in range(LMAX - 1):
        child_level = cur + 1
        clc = jnp.clip(child_level, 0, LMAX - 1)
        active = child_level <= leaf_l
        pidx_c = topo.parent_idx[t, clc]
        ptake = take_b[pidx_c]
        cvalid = valid_at(child_level) & (ptake > 0)
        sp = states_p[clc]
        slp = sls_p[clc]
        use_slices = child_level <= sl
        values = jnp.where(use_slices, sp // ss, sp)
        target = jnp.where(use_slices, ptake // ss, ptake)
        nt = segmented_greedy(values, cvalid, pidx_c, target, sp, slp)
        nt = jnp.where(use_slices, nt * ss, nt)
        take_b = jnp.where(active, nt, take_b)
        cur = jnp.where(active, child_level, cur)

    # Under-placement safety net (host snapshot.py:1177-1190): refuse a
    # short gang instead of admitting fewer pods than requested.
    leaf_total = jnp.sum(jnp.where(valid_at(leaf_l), take_b, 0))
    ok = ok & (leaf_total == count)
    return ok, jnp.where(valid_at(leaf_l), take_b, 0)


def place(
    topo: TASDeviceTopo,
    t: jnp.ndarray,  # i32 flavor row
    leaf_usage: jnp.ndarray,  # i64[D, R] current usage (device leaf order)
    req: jnp.ndarray,  # i64[R] per-pod requests
    count: jnp.ndarray,  # i64 pod count
    slice_size: jnp.ndarray,  # i64 (1 when unconstrained)
    slice_level: jnp.ndarray,  # i32 (leaf level when no slice constraint)
    req_level: jnp.ndarray,  # i32 requested level index
    required: jnp.ndarray,  # bool
    unconstrained: jnp.ndarray,  # bool
    cap_override: jnp.ndarray = None,  # i64[D, R] entry's filtered leaf cap
    sizes: jnp.ndarray = None,  # i64[LMAX] inner slice unit per level
    balanced: jnp.ndarray = None,  # bool: balanced placement requested
    leader_req: jnp.ndarray = None,  # i64[R] LWS leader pod requests
    has_leader: jnp.ndarray = None,  # bool (traced; default True)
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Returns (feasible bool, leaf_take i64[D] pods per leaf domain);
    with ``leader_req`` given, (feasible, leaf_take, leader_take bool[D]
    one-hot of the leaf hosting the LWS leader pod).

    ``cap_override`` replaces the topology's static leaf capacity for
    this entry — the per-entry analog of the host's node-selector/
    taint-filtered matching capacity (tas/snapshot.py _matching_capacity):
    capacity comes only from nodes the entry's pods may land on, while
    usage stays the leaf total.

    ``sizes``: multi-layer slice units (reference buildSliceSizeAtLevel +
    tas_flavor_snapshot.go:1100-1132): below the outer slice level, the
    per-parent distribution at level l runs in multiples of ``sizes[l]``
    (domain values = state // size, target = parent take // size, takes
    scale back by size). All-ones when the request has no inner layers."""
    d_n = topo.leaf_cap.shape[1]
    r_n = topo.leaf_cap.shape[2]
    iota = jnp.arange(d_n)
    nl = topo.n_levels[t]
    leaf_l = nl - 1
    ss = jnp.maximum(slice_size, 1)
    slice_count = count // ss

    def valid_at(l):
        return iota < topo.level_size[t, jnp.clip(l, 0, LMAX - 1)]

    # ---- phase 1: leaf fill + roll-up -------------------------------------
    cap = topo.leaf_cap[t] if cap_override is None else cap_override
    free = cap - leaf_usage  # [D,R] (incl. implicit-pods col)
    fits = jnp.full(d_n, _INF, jnp.int64)
    for r in range(r_n):  # static unroll over the resource axis
        fits = jnp.where(
            req[r] > 0,
            jnp.minimum(
                fits, jnp.maximum(free[:, r], 0) // jnp.maximum(req[r], 1)
            ),
            fits,
        )
    state_leaf = jnp.where(fits >= _INF, 0, fits)
    state_leaf = jnp.where(valid_at(leaf_l), state_leaf, 0)

    wl = leader_req is not None  # static: leader planes compiled in
    leaf_lc = jnp.clip(leaf_l, 0, LMAX - 1)
    if wl:
        if has_leader is None:
            has_leader = jnp.asarray(True)
        # Leaf leader planes (host fillLeafCounts + leader block,
        # snapshot.py:366-385): leader_state = one leader pod fits the
        # leaf's free capacity; state_with_leader = worker count on the
        # leader-reduced capacity where the leader fits, else the plain
        # worker count.
        lfits = jnp.full(d_n, _INF, jnp.int64)
        for r in range(r_n):
            lfits = jnp.where(
                leader_req[r] > 0,
                jnp.minimum(
                    lfits,
                    jnp.maximum(free[:, r], 0)
                    // jnp.maximum(leader_req[r], 1),
                ),
                lfits,
            )
        lead_leaf = valid_at(leaf_l) & (
            jnp.where(lfits >= _INF, 0, lfits) > 0
        )
        free2 = free - leader_req[None, :]
        fits2 = jnp.full(d_n, _INF, jnp.int64)
        for r in range(r_n):
            fits2 = jnp.where(
                req[r] > 0,
                jnp.minimum(
                    fits2,
                    jnp.maximum(free2[:, r], 0)
                    // jnp.maximum(req[r], 1),
                ),
                fits2,
            )
        swl_leaf = jnp.where(
            lead_leaf, jnp.where(fits2 >= _INF, 0, fits2), state_leaf
        )
        swl_leaf = jnp.where(valid_at(leaf_l), swl_leaf, 0)
        leads = jnp.zeros((LMAX, d_n), bool).at[leaf_lc].set(lead_leaf)
        states_wl = jnp.zeros((LMAX, d_n), jnp.int64).at[leaf_lc].set(
            swl_leaf
        )

    if sizes is None:
        sizes = jnp.ones(LMAX, jnp.int64)
    states = jnp.zeros((LMAX, d_n), jnp.int64)
    states = states.at[leaf_lc].set(state_leaf)
    for s in range(1, LMAX):
        l = leaf_l - s
        lc = jnp.clip(l, 0, LMAX - 1)
        child_l = jnp.clip(l + 1, 0, LMAX - 1)
        pidx = topo.parent_idx[t, child_l]
        child = jnp.where(valid_at(l + 1), states[child_l], 0)
        # Multi-layer inner constraint at the child level: contributions
        # round down to inner-size multiples (reference
        # fillInCountsHelper :1926), so parent capacity reflects what can
        # actually be grouped.
        inner_c = jnp.maximum(sizes[child_l], 1)
        child = (child // inner_c) * inner_c
        acc = jnp.zeros(d_n, jnp.int64).at[pidx].add(child)
        states = jnp.where(l >= 0, states.at[lc].set(acc), states)
        if wl:
            # Parent with-leader state: total minus the smallest
            # (state - state_with_leader) among leader-hosting children;
            # zero without a leader contributor (host _roll_up_counts
            # with leader_required=True, snapshot.py:426-442).
            c_lead = jnp.where(valid_at(l + 1), leads[child_l], False)
            c_swl = jnp.where(valid_at(l + 1), states_wl[child_l], 0)
            c_swl = (c_swl // inner_c) * inner_c
            diff = jnp.where(c_lead, child - c_swl, _INF)
            min_diff = jnp.full(d_n, _INF, jnp.int64).at[pidx].min(diff)
            has_contrib = jnp.zeros(d_n, bool).at[pidx].max(c_lead)
            p_swl = jnp.where(has_contrib, acc - min_diff, 0)
            states_wl = jnp.where(
                l >= 0, states_wl.at[lc].set(p_swl), states_wl
            )
            leads = jnp.where(l >= 0, leads.at[lc].set(has_contrib), leads)

    sls = jnp.zeros((LMAX, d_n), jnp.int64)
    sl_lc = jnp.clip(slice_level, 0, LMAX - 1)
    sls = sls.at[sl_lc].set(states[sl_lc] // ss)
    if wl:
        sls_wl = jnp.zeros((LMAX, d_n), jnp.int64)
        sls_wl = sls_wl.at[sl_lc].set(states_wl[sl_lc] // ss)
    for s in range(1, LMAX):
        l = slice_level - s
        lc = jnp.clip(l, 0, LMAX - 1)
        child_l = jnp.clip(l + 1, 0, LMAX - 1)
        pidx = topo.parent_idx[t, child_l]
        child = jnp.where(valid_at(l + 1), sls[child_l], 0)
        acc = jnp.zeros(d_n, jnp.int64).at[pidx].add(child)
        sls = jnp.where(l >= 0, sls.at[lc].set(acc), sls)
        if wl:
            c_lead = jnp.where(valid_at(l + 1), leads[child_l], False)
            c_slwl = jnp.where(valid_at(l + 1), sls_wl[child_l], 0)
            sdiff = jnp.where(c_lead, child - c_slwl, _INF)
            min_sdiff = jnp.full(d_n, _INF, jnp.int64).at[pidx].min(sdiff)
            has_contrib = jnp.zeros(d_n, bool).at[pidx].max(c_lead)
            p_slwl = jnp.where(has_contrib, acc - min_sdiff, 0)
            sls_wl = jnp.where(l >= 0, sls_wl.at[lc].set(p_slwl), sls_wl)

    # ---- phase 2a: level search -------------------------------------------
    lvl_iota = jnp.arange(LMAX)
    best = jnp.max(jnp.where(valid_at(lvl_iota[:, None]) &
                             (lvl_iota[:, None] < nl), sls, 0), axis=1)
    total = jnp.sum(jnp.where(valid_at(lvl_iota[:, None]) &
                              (lvl_iota[:, None] < nl), sls, 0), axis=1)
    fits_level = best >= slice_count
    req_lc = jnp.clip(req_level, 0, LMAX - 1)
    walk_cand = fits_level & (lvl_iota <= req_level) & (lvl_iota < nl)
    deepest_fit = jnp.max(jnp.where(walk_cand, lvl_iota, -1))

    single_level = jnp.where(
        required | unconstrained, req_level, deepest_fit
    )
    single_ok = jnp.where(
        required | unconstrained, fits_level[req_lc], deepest_fit >= 0
    )
    gather_level = jnp.where(unconstrained, req_level, 0)
    gather_ok = total[jnp.clip(gather_level, 0, LMAX - 1)] >= slice_count
    use_gather = ~single_ok & ~required
    feasible = single_ok | (use_gather & gather_ok)
    start_level = jnp.where(use_gather, gather_level, single_level)
    start_lc = jnp.clip(start_level, 0, LMAX - 1)

    # ---- phase 2b: initial selection at the start level -------------------
    sl_start = jnp.where(valid_at(start_level), sls[start_lc], 0)
    st_start = jnp.where(valid_at(start_level), states[start_lc], 0)
    # Single-domain: lowest sufficient slice capacity; ties broken by the
    # host sort order (-slice_state, state, values) = rank below.
    order0 = jnp.lexsort((iota, st_start, -sl_start)).astype(jnp.int32)
    rank0 = jnp.zeros(d_n, jnp.int64).at[order0].set(
        jnp.arange(d_n, dtype=jnp.int64)
    )
    suff = (sl_start >= slice_count) & valid_at(start_level)
    bf_key = jnp.where(suff, sl_start * d_n + rank0, _INF)
    dstar = jnp.argmin(bf_key)
    single_take = jnp.zeros(d_n, jnp.int64).at[dstar].set(slice_count)
    gather_take = segmented_greedy(
        sl_start, valid_at(start_level), jnp.zeros(d_n, jnp.int32),
        jnp.full(d_n, slice_count), st_start, sl_start,
    )
    take_slices = jnp.where(use_gather, gather_take, single_take)
    leader_at = jnp.zeros(d_n, bool)

    if wl:
        # ---- phase 2a with a leader (host _find_level_with_fit with
        # leader_count=1, snapshot.py:552-622). A level has a single-fit
        # iff the with-leader sort's top — the leader-hosting domain with
        # the highest slice_state_with_leader — covers the request.
        fits_level_wl = jnp.max(
            jnp.where(
                valid_at(lvl_iota[:, None]) & (lvl_iota[:, None] < nl)
                & leads, sls_wl, 0
            ),
            axis=1,
        ) >= slice_count
        walk_wl = fits_level_wl & (lvl_iota <= req_level) & (lvl_iota < nl)
        deepest_wl = jnp.max(jnp.where(walk_wl, lvl_iota, -1))
        single_level_w = jnp.where(
            required | unconstrained, req_level, deepest_wl
        )
        single_ok_w = jnp.where(
            required | unconstrained, fits_level_wl[req_lc], deepest_wl >= 0
        )
        use_gather_w = ~single_ok_w & ~required
        start_level_w = jnp.where(use_gather_w, gather_level, single_level_w)
        start_w_lc = jnp.clip(start_level_w, 0, LMAX - 1)
        v_s = valid_at(start_level_w)
        sl_s = jnp.where(v_s, sls[start_w_lc], 0)
        st_s = jnp.where(v_s, states[start_w_lc], 0)
        slwl_s = jnp.where(v_s, sls_wl[start_w_lc], 0)
        swl_s = jnp.where(v_s, states_wl[start_w_lc], 0)
        lead_s = v_s & leads[start_w_lc]
        # With-leader sort rank (-leader, -slice_wl, state_wl, values).
        ord_wl = jnp.lexsort(
            (iota, swl_s, -slwl_s, jnp.where(lead_s, 0, 1))
        ).astype(jnp.int32)
        rank_wl = jnp.zeros(d_n, jnp.int64).at[ord_wl].set(
            jnp.arange(d_n, dtype=jnp.int64)
        )
        # Single-domain winner: lowest sufficient slice_state_with_leader
        # over ALL domains (host _best_fit_for_slices get=with-leader; a
        # non-leader winner drops the leader in phase 2b, host-exactly).
        suff_w = v_s & (slwl_s >= slice_count)
        dstar_w = jnp.argmin(
            jnp.where(suff_w, slwl_s * d_n + rank_wl, _INF)
        )
        # Top-gather phase L reduces to ONE pick (see the proof in
        # segmented_greedy_leader's caller tests): if the top leader
        # domain covers the request, the best-fit substitute wins and
        # must itself host a leader or the gather fails ("not enough
        # leader capacity"); otherwise the top leader domain is taken.
        any_lead = jnp.any(lead_s)
        top_suff = jnp.max(jnp.where(lead_s, slwl_s, -1)) >= slice_count
        pickB = jnp.argmin(jnp.where(lead_s, rank_wl, _INF))
        pick = jnp.where(top_suff, dstar_w, pickB)
        ok_L = any_lead & jnp.where(top_suff, lead_s[dstar_w], True)
        remaining_after = slice_count - slwl_s[pick]
        rest_total = jnp.sum(jnp.where(v_s & (iota != pick), sl_s, 0))
        gather_ok_w = ok_L & (rest_total >= remaining_after)
        feasible_w = single_ok_w | (use_gather_w & gather_ok_w)

        # Phase 2b: one leader-aware walk covers both cases — the
        # single-domain winner as a singleton candidate set, or the
        # gather's selection order (leader pick first, then the plain
        # BestFit order).
        cand0 = jnp.where(
            use_gather_w, v_s, iota == dstar_w
        )
        rank_plain = jnp.zeros(d_n, jnp.int64).at[
            jnp.lexsort((iota, st_s, -sl_s)).astype(jnp.int32)
        ].set(jnp.arange(d_n, dtype=jnp.int64))
        ordr0 = jnp.where(
            use_gather_w & (iota == pick), jnp.int64(-1), rank_plain
        )
        takes0_w, lead0 = segmented_greedy_leader(
            sl_s, slwl_s, lead_s, cand0, jnp.zeros(d_n, jnp.int32),
            jnp.full(d_n, slice_count),
            jnp.broadcast_to(has_leader, (d_n,)),
            st_s, sl_s, order_rank=ordr0,
        )
        feasible = jnp.where(has_leader, feasible_w, feasible)
        start_level = jnp.where(has_leader, start_level_w, start_level)
        use_gather = jnp.where(has_leader, use_gather_w, use_gather)
        take_slices = jnp.where(has_leader, takes0_w, take_slices)
        leader_at = jnp.where(has_leader, lead0, leader_at)

    # Convert to pods immediately when the start level IS the slice level
    # (or deeper: start <= slice_level always holds).
    at_slice = start_level == slice_level
    take = jnp.where(at_slice, take_slices * ss, take_slices)
    in_pods = at_slice

    # ---- descent ----------------------------------------------------------
    cur_level = start_level
    for _ in range(LMAX - 1):
        child_level = cur_level + 1
        active = (child_level <= leaf_l) & feasible
        child_lc = jnp.clip(child_level, 0, LMAX - 1)
        pidx = topo.parent_idx[t, child_lc]
        parent_take = take[pidx]
        child_valid = valid_at(child_level) & (parent_take > 0)
        mode_a = child_level <= slice_level  # free slice redistribution
        sl_child = jnp.where(valid_at(child_level), sls[child_lc], 0)
        st_child = jnp.where(valid_at(child_level), states[child_lc], 0)
        # Inner slice layer at the child level: per-parent distribution
        # runs in multiples of its size (host recomputes slice_state =
        # state // inner and sorts/greedy-fills in those units).
        inner = jnp.maximum(sizes[child_lc], 1)
        vals_b = st_child // inner
        values = jnp.where(mode_a, sl_child, vals_b)
        seg = jnp.where(mode_a, jnp.zeros(d_n, jnp.int32), pidx)
        target = jnp.where(
            mode_a, jnp.full(d_n, slice_count), parent_take // inner
        )
        # Primary BestFit key: ALWAYS the phase-1 slice states — the host
        # sorts children before recomputing inner-unit slice states
        # (snapshot.py:1141-1147), so an inner layer changes candidate
        # values/targets but NOT the walk order.
        if wl:
            # Free slice redistribution re-engages the original leader
            # count at every level (host passes the function-level
            # leader_count, snapshot.py:1140); per-parent distribution
            # consumes the parent's kept leader (dom.leader_state,
            # :1166-1171).
            slwl_child = jnp.where(valid_at(child_level), sls_wl[child_lc], 0)
            swl_child = jnp.where(
                valid_at(child_level), states_wl[child_lc], 0
            )
            lead_child = valid_at(child_level) & leads[child_lc]
            values_wl = jnp.where(mode_a, slwl_child, swl_child // inner)
            need = jnp.where(
                mode_a,
                jnp.broadcast_to(has_leader, (d_n,)),
                leader_at[pidx],
            )
            new_take, new_lead = segmented_greedy_leader(
                values, values_wl, lead_child, child_valid, seg, target,
                need, st_child, sl_child,
            )
            leader_at = jnp.where(active, new_lead, leader_at)
        else:
            new_take = segmented_greedy(
                values, child_valid, seg, target, st_child, sl_child
            )
        # Slice->pod conversion when the child level is the slice level;
        # inner-layer units always convert back to pods immediately.
        to_pods = mode_a & (child_level == slice_level)
        new_take = jnp.where(
            to_pods, new_take * ss,
            jnp.where(~mode_a, new_take * inner, new_take),
        )
        take = jnp.where(active, new_take, take)
        in_pods = jnp.where(active, in_pods | to_pods | ~mode_a, in_pods)
        cur_level = jnp.where(active, child_level, cur_level)

    # At the leaf level the take is in pods unless no slice conversion
    # happened (slice_level == leaf and start == leaf handled by at_slice).
    leaf_take = jnp.where(in_pods, take, take * ss)
    leaf_take = jnp.where(feasible & valid_at(leaf_l), leaf_take, 0)
    # Under-placement safety net (host snapshot.py:1177-1190): a gang
    # shorter than requested is a placement failure, not an admission.
    feasible = feasible & (jnp.sum(leaf_take) == count)
    leaf_take = jnp.where(feasible, leaf_take, 0)

    if balanced is not None:
        # Balanced placement wins over the standard path when it succeeds
        # (host snapshot.py:1099-1125); on failure the standard result
        # above stands (reference falls back to BestFit). Balanced with
        # a leader stays on the host path (encode gate).
        bal_ok, bal_take = _balanced_place(
            topo, t, states, sls, req_level, slice_level, ss,
            slice_count, count, leaf_l,
        )
        bal_sel = balanced & ~required & ~unconstrained & bal_ok
        if wl:
            bal_sel = bal_sel & ~has_leader
        feasible = jnp.where(bal_sel, True, feasible)
        leaf_take = jnp.where(bal_sel, bal_take, leaf_take)
    if wl:
        leader_take = leader_at & feasible & has_leader & valid_at(leaf_l)
        return feasible, leaf_take, leader_take
    return feasible, leaf_take


def feasible_only(
    topo: TASDeviceTopo,
    t: jnp.ndarray,
    leaf_usage: jnp.ndarray,
    req: jnp.ndarray,
    count: jnp.ndarray,
    slice_size: jnp.ndarray,
    slice_level: jnp.ndarray,
    req_level: jnp.ndarray,
    required: jnp.ndarray,
    unconstrained: jnp.ndarray,
    cap_override: jnp.ndarray = None,
    sizes: jnp.ndarray = None,
    leader_req: jnp.ndarray = None,
    has_leader: jnp.ndarray = None,
) -> jnp.ndarray:
    """Feasibility-only probe. Deliberately ignores balanced placement:
    a balanced success requires one sibling group to cover the whole
    request, which implies the standard preferred-mode walk-up/top-gather
    covers it too, so entry FEASIBILITY is identical on both paths (the
    host falls back to BestFit on balanced failure, snapshot.py:1119) —
    only the chosen domains differ, which feasibility probes (nominate,
    preemption oracles) never see. The under-placement guard in place()
    does not break this: on the STANDARD path the phase-1 slice states
    are true sums of slice-level counts (no state//sliceSize
    re-derivation above the slice level), so the greedy descent always
    realizes the full count — the host documents its safety net as
    reachable only via the balanced descent (tas/snapshot.py:1177).
    Skipping balanced here keeps the 2^BMAX subset enumeration out of
    the W-wide vmaps."""
    out = place(topo, t, leaf_usage, req, count, slice_size, slice_level,
                req_level, required, unconstrained,
                cap_override=cap_override, sizes=sizes,
                leader_req=leader_req, has_leader=has_leader)
    return out[0]
