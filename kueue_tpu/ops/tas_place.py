"""Device-side TAS gang placement: the full phase 1/2a/2b pipeline.

Tensor twin of tas/snapshot.py find_topology_assignment (reference
tas_flavor_snapshot.go:943 findTopologyAssignment) for the device-eligible
class: no leaders, no balanced placement, no inner slice layers, no
per-workload node selectors/taint filtering (encode gates those to the
host path). Supports required / preferred (walk-up + top-level gather) /
unconstrained modes and the outer slice constraint (sliceSize pinned at a
sliceRequiredLevel) — the long-context/ICI-critical case.

Layout: every TAS flavor's topology becomes right-padded per-level arrays
(axis D = max domains per level across flavors, LMAX static levels), with
domains at each level PRE-SORTED by their levelValues tuple so the host's
lexicographic tie-break equals the device index order. The phase-2b greedy
descent ("take domains in BestFit order until one can finish, then pick the
smallest sufficient finisher" — updateCountsToMinimumGeneric :1578) is one
segmented prefix-sum + masked argmin per level, for both the free
slice-redistribution region above the slice level and the per-parent pods
region at/below it.

All level indices (requested, slice, leaf) are traced values, so one
compiled kernel serves every flavor/request shape; the static loops run
LMAX times with masks.
"""

from __future__ import annotations

from typing import List, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

LMAX = 8
_INF = jnp.int64(1) << 60


class TASDeviceTopo(NamedTuple):
    """Padded topologies for all TAS flavors (leading axis T).

    The capacity resource axis is the cycle resource index PLUS one trailing
    "implicit pods" column (reference resources.CountIn bounds pod counts by
    the node's "pods" capacity even when unrequested): the per-entry TAS
    request vector carries 1 in that column when "pods" isn't requested,
    reproducing the bound as ordinary division; INF capacity when the fleet
    doesn't track pods."""

    n_levels: jnp.ndarray  # i32[T]
    level_size: jnp.ndarray  # i32[T, LMAX]
    parent_idx: jnp.ndarray  # i32[T, LMAX, D]: level-l domain -> parent pos
    leaf_cap: jnp.ndarray  # i64[T, D, R+1]


def encode_device_topos(
    tas_flavors: dict, flavor_names: List[str], resource_of: dict
) -> Tuple[TASDeviceTopo, List[object], List[List[int]]]:
    """Build TASDeviceTopo from host TASFlavorSnapshots.

    Returns (topo, per-T host snapshots, per-T leaf permutation mapping the
    device leaf position -> host leaf index). Only flavors in
    ``flavor_names`` (device-eligible) are encoded.
    """
    r_n = max(len(resource_of), 1)
    t_n = max(len(flavor_names), 1)
    lmax_sizes = [1]
    per_flavor = []
    for name in flavor_names:
        tas = tas_flavors[name]
        sizes = [len(lvl) for lvl in tas.domains_per_level]
        lmax_sizes.extend(sizes)
        per_flavor.append(tas)
    d_n = max(lmax_sizes)

    n_levels = np.ones(t_n, np.int32)
    level_size = np.zeros((t_n, LMAX), np.int32)
    parent_idx = np.zeros((t_n, LMAX, d_n), np.int32)
    leaf_cap = np.zeros((t_n, d_n, r_n + 1), np.int64)
    leaf_cap[:, :, r_n] = 1 << 60  # implicit-pods column: INF by default
    leaf_perm: List[List[int]] = []

    for t, tas in enumerate(per_flavor):
        nl = len(tas.level_keys)
        n_levels[t] = nl
        # Sort each level's domains by levelValues (the host tie-break);
        # keep position maps for parent indices.
        sorted_levels = []
        pos_maps = []
        for lvl in tas.domains_per_level:
            s = sorted(range(len(lvl)), key=lambda i: lvl[i].level_values)
            sorted_levels.append([lvl[i] for i in s])
            pos_maps.append({id(lvl[i]): j for j, i in enumerate(s)})
        for l in range(nl):
            level_size[t, l] = len(sorted_levels[l])
            if l >= 1:
                for j, dom in enumerate(sorted_levels[l]):
                    parent_idx[t, l, j] = pos_maps[l - 1][id(dom.parent)]
        host_leaf_index = {leaf.id: i for i, leaf in enumerate(tas.leaves)}
        perm = []
        for j, dom in enumerate(sorted_levels[nl - 1]):
            hi = host_leaf_index[dom.id]
            perm.append(hi)
            for r, ri in tas._res_index.items():
                ci = resource_of.get(r)
                if ci is not None:
                    leaf_cap[t, j, ci] = tas._leaf_cap[hi, ri]
                if r == "pods":
                    leaf_cap[t, j, r_n] = tas._leaf_cap[hi, ri]
        leaf_perm.append(perm)

    return (
        TASDeviceTopo(
            n_levels=np.asarray(n_levels),
            level_size=np.asarray(level_size),
            parent_idx=np.asarray(parent_idx),
            leaf_cap=np.asarray(leaf_cap),
        ),
        per_flavor,
        leaf_perm,
    )


def _seg_excl_cumsum(vals, head):
    c = jnp.cumsum(vals)
    excl = c - vals
    n = head.shape[0]
    head_idx = jnp.where(head, jnp.arange(n), -1)
    seg_head = jax.lax.associative_scan(jnp.maximum, head_idx)
    return excl - excl[seg_head], seg_head


def _seg_min_scan(vals, head):
    """Per-position minimum over the position's WHOLE segment: scatter-min
    into the segment-head slot, then gather back."""
    n = head.shape[0]
    head_idx = jnp.where(head, jnp.arange(n), -1)
    seg_head = jax.lax.associative_scan(jnp.maximum, head_idx)
    seg_total = jnp.full(n, _INF, vals.dtype).at[seg_head].min(vals)
    return seg_total[seg_head]


def segmented_greedy(
    values: jnp.ndarray,  # i64[D] capacity per candidate (in units)
    cand: jnp.ndarray,  # bool[D] candidate mask
    seg: jnp.ndarray,  # i32[D] segment id (monotone grouping key)
    target: jnp.ndarray,  # i64[D] per-position target of its segment
    tiebreak_state: jnp.ndarray,  # i64[D] host BestFit secondary key
    primary_desc: jnp.ndarray,  # i64[D] host BestFit primary key (desc)
) -> jnp.ndarray:
    """One host ``updateCountsToMinimum`` pass per segment: walk candidates
    in (primary desc, state asc, index) order, taking full capacity until a
    candidate can finish the remaining target, then give the remainder to
    the smallest sufficient candidate at/after that point. Returns takes
    [D] in ``values`` units."""
    d_n = values.shape[0]
    iota = jnp.arange(d_n)
    order = jnp.lexsort((
        iota, tiebreak_state, -primary_desc, jnp.where(cand, 0, 1), seg
    )).astype(jnp.int32)
    v = jnp.where(cand, values, 0)[order]
    c = cand[order]
    s = seg[order]
    t_seg = target[order]
    head = jnp.concatenate([jnp.ones(1, bool), s[1:] != s[:-1]])
    prefix, _ = _seg_excl_cumsum(v, head)
    remaining = t_seg - prefix  # target left before this candidate
    can_finish = c & (v >= remaining) & (remaining > 0)
    # First finisher per segment: segment-min of (can_finish ? position : INF).
    pos_key = jnp.where(can_finish, iota, _INF)
    first_fin = _seg_min_scan(pos_key, head)  # per-position segment min
    jstar = first_fin  # i64 position of first finisher (INF if none)
    before_star = iota < jstar
    at_or_after = iota >= jstar
    # remaining at jstar, broadcast per segment: gather via remaining[jstar]
    jstar_c = jnp.clip(jstar, 0, d_n - 1).astype(jnp.int32)
    rem_star = jnp.where(jstar < _INF, remaining[jstar_c], 0)
    # Best-fit winner: min (value, position) among sufficient candidates at
    # or after jstar.
    suff = c & at_or_after & (v >= rem_star) & (rem_star > 0)
    bf_key = jnp.where(suff, v * d_n + iota, _INF)
    bf_min = _seg_min_scan(bf_key, head)
    winner = suff & (bf_key == bf_min)
    takes_sorted = jnp.where(
        winner, rem_star,
        jnp.where(c & before_star & (remaining > 0), v, 0),
    )
    takes = jnp.zeros(d_n, jnp.int64).at[order].set(takes_sorted)
    return takes


def entry_leaf_cap(arrays, t_idx, w=None):
    """Per-entry leaf capacity for placement probes: the entry's filtered
    row (node selector / taint matching) where ``w_tas_has_cap``, else the
    topology's static capacity. ``w`` optionally gathers a subset of
    entries (e.g. the scan step's per-group workload indices)."""
    leaf = arrays.tas_topo.leaf_cap[t_idx]
    if arrays.w_tas_cap is None:
        return leaf
    has = arrays.w_tas_has_cap if w is None else arrays.w_tas_has_cap[w]
    cap = arrays.w_tas_cap if w is None else arrays.w_tas_cap[w]
    return jnp.where(has[:, None, None], cap, leaf)


def place(
    topo: TASDeviceTopo,
    t: jnp.ndarray,  # i32 flavor row
    leaf_usage: jnp.ndarray,  # i64[D, R] current usage (device leaf order)
    req: jnp.ndarray,  # i64[R] per-pod requests
    count: jnp.ndarray,  # i64 pod count
    slice_size: jnp.ndarray,  # i64 (1 when unconstrained)
    slice_level: jnp.ndarray,  # i32 (leaf level when no slice constraint)
    req_level: jnp.ndarray,  # i32 requested level index
    required: jnp.ndarray,  # bool
    unconstrained: jnp.ndarray,  # bool
    cap_override: jnp.ndarray = None,  # i64[D, R] entry's filtered leaf cap
    sizes: jnp.ndarray = None,  # i64[LMAX] inner slice unit per level
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Returns (feasible bool, leaf_take i64[D] pods per leaf domain).

    ``cap_override`` replaces the topology's static leaf capacity for
    this entry — the per-entry analog of the host's node-selector/
    taint-filtered matching capacity (tas/snapshot.py _matching_capacity):
    capacity comes only from nodes the entry's pods may land on, while
    usage stays the leaf total.

    ``sizes``: multi-layer slice units (reference buildSliceSizeAtLevel +
    tas_flavor_snapshot.go:1100-1132): below the outer slice level, the
    per-parent distribution at level l runs in multiples of ``sizes[l]``
    (domain values = state // size, target = parent take // size, takes
    scale back by size). All-ones when the request has no inner layers."""
    d_n = topo.leaf_cap.shape[1]
    r_n = topo.leaf_cap.shape[2]
    iota = jnp.arange(d_n)
    nl = topo.n_levels[t]
    leaf_l = nl - 1
    ss = jnp.maximum(slice_size, 1)
    slice_count = count // ss

    def valid_at(l):
        return iota < topo.level_size[t, jnp.clip(l, 0, LMAX - 1)]

    # ---- phase 1: leaf fill + roll-up -------------------------------------
    cap = topo.leaf_cap[t] if cap_override is None else cap_override
    free = cap - leaf_usage  # [D,R] (incl. implicit-pods col)
    fits = jnp.full(d_n, _INF, jnp.int64)
    for r in range(r_n):  # static unroll over the resource axis
        fits = jnp.where(
            req[r] > 0,
            jnp.minimum(
                fits, jnp.maximum(free[:, r], 0) // jnp.maximum(req[r], 1)
            ),
            fits,
        )
    state_leaf = jnp.where(fits >= _INF, 0, fits)
    state_leaf = jnp.where(valid_at(leaf_l), state_leaf, 0)

    if sizes is None:
        sizes = jnp.ones(LMAX, jnp.int64)
    states = jnp.zeros((LMAX, d_n), jnp.int64)
    states = states.at[jnp.clip(leaf_l, 0, LMAX - 1)].set(state_leaf)
    for s in range(1, LMAX):
        l = leaf_l - s
        lc = jnp.clip(l, 0, LMAX - 1)
        child_l = jnp.clip(l + 1, 0, LMAX - 1)
        pidx = topo.parent_idx[t, child_l]
        child = jnp.where(valid_at(l + 1), states[child_l], 0)
        # Multi-layer inner constraint at the child level: contributions
        # round down to inner-size multiples (reference
        # fillInCountsHelper :1926), so parent capacity reflects what can
        # actually be grouped.
        inner_c = jnp.maximum(sizes[child_l], 1)
        child = (child // inner_c) * inner_c
        acc = jnp.zeros(d_n, jnp.int64).at[pidx].add(child)
        states = jnp.where(l >= 0, states.at[lc].set(acc), states)

    sls = jnp.zeros((LMAX, d_n), jnp.int64)
    sl_lc = jnp.clip(slice_level, 0, LMAX - 1)
    sls = sls.at[sl_lc].set(states[sl_lc] // ss)
    for s in range(1, LMAX):
        l = slice_level - s
        lc = jnp.clip(l, 0, LMAX - 1)
        child_l = jnp.clip(l + 1, 0, LMAX - 1)
        pidx = topo.parent_idx[t, child_l]
        child = jnp.where(valid_at(l + 1), sls[child_l], 0)
        acc = jnp.zeros(d_n, jnp.int64).at[pidx].add(child)
        sls = jnp.where(l >= 0, sls.at[lc].set(acc), sls)

    # ---- phase 2a: level search -------------------------------------------
    lvl_iota = jnp.arange(LMAX)
    best = jnp.max(jnp.where(valid_at(lvl_iota[:, None]) &
                             (lvl_iota[:, None] < nl), sls, 0), axis=1)
    total = jnp.sum(jnp.where(valid_at(lvl_iota[:, None]) &
                              (lvl_iota[:, None] < nl), sls, 0), axis=1)
    fits_level = best >= slice_count
    req_lc = jnp.clip(req_level, 0, LMAX - 1)
    walk_cand = fits_level & (lvl_iota <= req_level) & (lvl_iota < nl)
    deepest_fit = jnp.max(jnp.where(walk_cand, lvl_iota, -1))

    single_level = jnp.where(
        required | unconstrained, req_level, deepest_fit
    )
    single_ok = jnp.where(
        required | unconstrained, fits_level[req_lc], deepest_fit >= 0
    )
    gather_level = jnp.where(unconstrained, req_level, 0)
    gather_ok = total[jnp.clip(gather_level, 0, LMAX - 1)] >= slice_count
    use_gather = ~single_ok & ~required
    feasible = single_ok | (use_gather & gather_ok)
    start_level = jnp.where(use_gather, gather_level, single_level)
    start_lc = jnp.clip(start_level, 0, LMAX - 1)

    # ---- phase 2b: initial selection at the start level -------------------
    sl_start = jnp.where(valid_at(start_level), sls[start_lc], 0)
    st_start = jnp.where(valid_at(start_level), states[start_lc], 0)
    # Single-domain: lowest sufficient slice capacity; ties broken by the
    # host sort order (-slice_state, state, values) = rank below.
    order0 = jnp.lexsort((iota, st_start, -sl_start)).astype(jnp.int32)
    rank0 = jnp.zeros(d_n, jnp.int64).at[order0].set(
        jnp.arange(d_n, dtype=jnp.int64)
    )
    suff = (sl_start >= slice_count) & valid_at(start_level)
    bf_key = jnp.where(suff, sl_start * d_n + rank0, _INF)
    dstar = jnp.argmin(bf_key)
    single_take = jnp.zeros(d_n, jnp.int64).at[dstar].set(slice_count)
    gather_take = segmented_greedy(
        sl_start, valid_at(start_level), jnp.zeros(d_n, jnp.int32),
        jnp.full(d_n, slice_count), st_start, sl_start,
    )
    take_slices = jnp.where(use_gather, gather_take, single_take)

    # Convert to pods immediately when the start level IS the slice level
    # (or deeper: start <= slice_level always holds).
    at_slice = start_level == slice_level
    take = jnp.where(at_slice, take_slices * ss, take_slices)
    in_pods = at_slice

    # ---- descent ----------------------------------------------------------
    cur_level = start_level
    for _ in range(LMAX - 1):
        child_level = cur_level + 1
        active = (child_level <= leaf_l) & feasible
        child_lc = jnp.clip(child_level, 0, LMAX - 1)
        pidx = topo.parent_idx[t, child_lc]
        parent_take = take[pidx]
        child_valid = valid_at(child_level) & (parent_take > 0)
        mode_a = child_level <= slice_level  # free slice redistribution
        sl_child = jnp.where(valid_at(child_level), sls[child_lc], 0)
        st_child = jnp.where(valid_at(child_level), states[child_lc], 0)
        # Inner slice layer at the child level: per-parent distribution
        # runs in multiples of its size (host recomputes slice_state =
        # state // inner and sorts/greedy-fills in those units).
        inner = jnp.maximum(sizes[child_lc], 1)
        vals_b = st_child // inner
        values = jnp.where(mode_a, sl_child, vals_b)
        seg = jnp.where(mode_a, jnp.zeros(d_n, jnp.int32), pidx)
        target = jnp.where(
            mode_a, jnp.full(d_n, slice_count), parent_take // inner
        )
        # Primary BestFit key: ALWAYS the phase-1 slice states — the host
        # sorts children before recomputing inner-unit slice states
        # (snapshot.py:1141-1147), so an inner layer changes candidate
        # values/targets but NOT the walk order.
        new_take = segmented_greedy(
            values, child_valid, seg, target, st_child, sl_child
        )
        # Slice->pod conversion when the child level is the slice level;
        # inner-layer units always convert back to pods immediately.
        to_pods = mode_a & (child_level == slice_level)
        new_take = jnp.where(
            to_pods, new_take * ss,
            jnp.where(~mode_a, new_take * inner, new_take),
        )
        take = jnp.where(active, new_take, take)
        in_pods = jnp.where(active, in_pods | to_pods | ~mode_a, in_pods)
        cur_level = jnp.where(active, child_level, cur_level)

    # At the leaf level the take is in pods unless no slice conversion
    # happened (slice_level == leaf and start == leaf handled by at_slice).
    leaf_take = jnp.where(in_pods, take, take * ss)
    leaf_take = jnp.where(feasible & valid_at(leaf_l), leaf_take, 0)
    return feasible, leaf_take


def feasible_only(
    topo: TASDeviceTopo,
    t: jnp.ndarray,
    leaf_usage: jnp.ndarray,
    req: jnp.ndarray,
    count: jnp.ndarray,
    slice_size: jnp.ndarray,
    slice_level: jnp.ndarray,
    req_level: jnp.ndarray,
    required: jnp.ndarray,
    unconstrained: jnp.ndarray,
    cap_override: jnp.ndarray = None,
    sizes: jnp.ndarray = None,
) -> jnp.ndarray:
    f, _ = place(topo, t, leaf_usage, req, count, slice_size, slice_level,
                 req_level, required, unconstrained,
                 cap_override=cap_override, sizes=sizes)
    return f
