"""Vectorized TAS capacity math (device twin of tas/snapshot.py phases).

Phase 1 of the reference's placement algorithm (fillInCounts,
tas_flavor_snapshot.go:1760 — per-leaf free capacity -> per-domain
pod/slice counts rolled bottom-up) and the phase-2a feasibility scan
(findLevelWithFitDomains :1380 — which level has a domain fitting the
whole gang) as padded tensor ops:

- topology domains become per-level arrays with child->parent index
  vectors (the same forest layout trick as ops/tree_encode.GroupLayout);
- the per-leaf pod-count fill is an elementwise min of integer divisions
  over the resource axis;
- the roll-up is a per-level segment-sum sweep (depth <= 8);
- level feasibility is a per-level max reduction.

At fleet scale (10k+ hosts) this turns the reference's O(nodes) pointer
walk per workload into a handful of vector ops; the greedy descent
(phase 2b) stays host-side this round.
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

INF32 = jnp.int32(1 << 30)


class TASTopologyArrays(NamedTuple):
    """Padded per-level topology layout. ``level_sizes[l]`` domains exist at
    level l; ``parent_idx[l]`` maps level-l domains to their level-(l-1)
    parents (level 0 has no parents). R = resource axis."""

    level_sizes: Tuple[int, ...]  # static python ints
    parent_idx: Tuple[jnp.ndarray, ...]  # per level >=1: i32[n_l]
    leaf_cap: jnp.ndarray  # i64[L, R] total node capacity per leaf
    # Level index of leaves == len(level_sizes) - 1.


def encode_topology(snapshot) -> Tuple[TASTopologyArrays, List[List[str]]]:
    """Build arrays from a host TASFlavorSnapshot. Returns (arrays,
    per-level domain-id lists for decoding)."""
    levels = snapshot.domains_per_level
    ids: List[List[str]] = [[d.id for d in lvl] for lvl in levels]
    pos = [{d.id: i for i, d in enumerate(lvl)} for lvl in levels]
    parent_idx = []
    for l in range(1, len(levels)):
        parent_idx.append(jnp.asarray(
            [pos[l - 1][d.parent.id] for d in levels[l]], dtype=jnp.int32
        ))
    leaf_cap = jnp.asarray(snapshot._leaf_cap)
    # snapshot.leaves order == domains_per_level[-1] order (tas/snapshot).
    return (
        TASTopologyArrays(
            level_sizes=tuple(len(lvl) for lvl in levels),
            parent_idx=tuple(parent_idx),
            leaf_cap=leaf_cap,
        ),
        ids,
    )


def fill_counts(
    topo: TASTopologyArrays,
    leaf_usage: jnp.ndarray,  # i64[L, R]
    requests: jnp.ndarray,  # i64[R] per-pod
    slice_size: int,
    slice_level: int,
) -> Tuple[Tuple[jnp.ndarray, ...], Tuple[jnp.ndarray, ...]]:
    """Per-domain pod counts (state) and slice counts per level,
    leaves-up (reference fillInCounts + fillInCountsHelper).

    Returns (states, slice_states): tuples indexed by level."""
    free = jnp.maximum(topo.leaf_cap - leaf_usage, 0)  # [L, R]
    fits = jnp.full(free.shape[0], INF32, dtype=jnp.int64)
    r_n = requests.shape[0]
    for r in range(r_n):
        req_r = requests[r]
        fits = jnp.where(
            req_r > 0, jnp.minimum(fits, free[:, r] // jnp.maximum(req_r, 1)),
            fits,
        )
    leaf_state = jnp.where(fits >= INF32, 0, fits)

    n_levels = len(topo.level_sizes)
    states: List[jnp.ndarray] = [None] * n_levels
    states[n_levels - 1] = leaf_state
    for l in range(n_levels - 2, -1, -1):
        acc = jnp.zeros(topo.level_sizes[l], dtype=jnp.int64)
        acc = acc.at[topo.parent_idx[l]].add(states[l + 1])
        states[l] = acc

    slice_states: List[jnp.ndarray] = [None] * n_levels
    # At the slice level: floor-divide; above: sum of children's slices.
    slice_states[slice_level] = states[slice_level] // max(slice_size, 1)
    for l in range(slice_level - 1, -1, -1):
        acc = jnp.zeros(topo.level_sizes[l], dtype=jnp.int64)
        acc = acc.at[topo.parent_idx[l]].add(slice_states[l + 1])
        slice_states[l] = acc
    for l in range(slice_level + 1, n_levels):
        slice_states[l] = jnp.zeros(topo.level_sizes[l], dtype=jnp.int64)
    return tuple(states), tuple(slice_states)


def find_fit_level(
    slice_states: Tuple[jnp.ndarray, ...],
    slice_count: jnp.ndarray,
    requested_level: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Phase-2a feasibility: the deepest level <= requested_level whose best
    domain holds the whole gang (reference findLevelWithFitDomains upward
    fallback). Returns (level, fits_somewhere) — level == requested_level
    when it fits there, walking up otherwise; -1 when nothing fits even at
    the root level."""
    level = jnp.int32(-1)
    found = jnp.bool_(False)
    for l in range(requested_level, -1, -1):
        best = jnp.max(slice_states[l]) if slice_states[l].shape[0] else 0
        ok = (best >= slice_count) & ~found
        level = jnp.where(ok, jnp.int32(l), level)
        found = found | ok
    return level, found


def best_fit_domain(
    slice_states_l: jnp.ndarray, slice_count: jnp.ndarray
) -> jnp.ndarray:
    """BestFit selection at one level: the first domain with the LOWEST
    sufficient slice capacity (reference findBestFitDomainBy)."""
    fits = slice_states_l >= slice_count
    keyed = jnp.where(fits, slice_states_l, jnp.int64(1) << 60)
    return jnp.argmin(keyed).astype(jnp.int32)
