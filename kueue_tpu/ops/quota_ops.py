"""Vectorized hierarchical quota math on padded device tensors.

These are the TPU twins of ``kueue_tpu/cache/resource_node.py`` (which
re-derives reference pkg/cache/scheduler/resource_node.go). The cohort tree
is encoded as parent-pointer arrays; every per-FlavorResource scalar function
becomes an elementwise op over an ``[N, F, R]`` int64 tensor, and the
up/down-tree recursions become depth-bounded loops (depth <= MAX_DEPTH,
unrolled at trace time) of gathers/scatter-adds — XLA-friendly: static
shapes, no data-dependent control flow.

Int64 discipline: quota arithmetic must be exact, so everything here is i64
(``jax_enable_x64`` is flipped on at import). Saturation clamps to
±UNLIMITED = ±2**62, so any two in-range values add without int64 overflow.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp

from kueue_tpu.core.resources import UNLIMITED
from kueue_tpu.metrics import tracing

# Maximum supported cohort-tree depth (root=0). The reference supports
# arbitrary depth; 8 levels is far beyond any practical hierarchy and keeps
# the unrolled tree walks cheap.
MAX_DEPTH = 8

I64 = jnp.int64
CAP = jnp.int64(UNLIMITED)
# int32-mode saturation cap: (1 << 30) - 1 so two in-range values add (and
# subtract) without int32 overflow — the same role CAP plays for int64.
# Bit-exactness of int32 quota math is gated by models.pallas_scan
# fits_int32 (every quantity and worst-case accumulation below CAP32).
CAP32 = jnp.int32((1 << 30) - 1)


def _cap_of(dtype) -> jnp.ndarray:
    return CAP32 if dtype == jnp.int32 else CAP


def sat(v: jnp.ndarray) -> jnp.ndarray:
    cap = _cap_of(jnp.result_type(v))
    return jnp.clip(v, -cap, cap)


def sat_add(a, b):
    return sat(a + b)


def sat_sub(a, b):
    """a - b with Unlimited minuend staying Unlimited."""
    cap = _cap_of(jnp.result_type(a, b))
    return jnp.where(a >= cap, cap, sat(a - b))


_CAP_F = float(UNLIMITED)


def sat_scatter_add(base: jnp.ndarray, idx: jnp.ndarray, deltas: jnp.ndarray) -> jnp.ndarray:
    """base.at[idx].add(deltas) with saturation at ±UNLIMITED.

    A plain int64 scatter-add wraps when several near-UNLIMITED values land on
    one row (2 * 2**62 >= 2**63). A float64 shadow accumulation detects any
    row whose true sum leaves the representable range — float64 is only used
    as an overflow detector, the returned values stay exact int64 below the
    cap.
    """
    # Accept numpy inputs (encoders build host-side and batch the
    # device transfer; eager callers may hand us either kind).
    base = jnp.asarray(base)
    int_sum = base.at[idx].add(deltas, mode="drop")
    f_sum = base.astype(jnp.float64).at[idx].add(
        deltas.astype(jnp.float64), mode="drop"
    )
    return jnp.where(
        f_sum >= _CAP_F, CAP, jnp.where(f_sum <= -_CAP_F, -CAP, sat(int_sum))
    )


class QuotaTreeArrays(NamedTuple):
    """Dense encoding of the CQ/Cohort quota tree.

    N = padded node count (ClusterQueues are leaves, Cohorts internal; node 0
    conventionally unused padding is allowed). F/R = padded flavor/resource
    axes. Quantities are canonical integers (milliCPU, bytes, counts).
    """

    parent: jnp.ndarray  # i32[N], -1 for roots and padding
    active: jnp.ndarray  # bool[N]
    depth: jnp.ndarray  # i32[N], root=0; padding=0
    height: jnp.ndarray  # i32[N], distance to furthest leaf cohort-wise
    nominal: jnp.ndarray  # i64[N,F,R]
    borrow_limit: jnp.ndarray  # i64[N,F,R]; CAP where unset (= unlimited)
    has_borrow_limit: jnp.ndarray  # bool[N,F,R]
    lend_limit: jnp.ndarray  # i64[N,F,R]; CAP where unset
    has_lend_limit: jnp.ndarray  # bool[N,F,R]
    subtree_quota: jnp.ndarray  # i64[N,F,R] (computed; see compute_subtree)

    @property
    def n_nodes(self) -> int:
        return self.parent.shape[0]


def _parent_or_self(tree: QuotaTreeArrays) -> jnp.ndarray:
    """Parent indices with roots/padding redirected to themselves, so gathers
    stay in-bounds."""
    return jnp.where(tree.parent < 0, jnp.arange(tree.n_nodes), tree.parent)


def local_quota(tree: QuotaTreeArrays) -> jnp.ndarray:
    """max(0, subtree_quota - lending_limit) where a lending limit is set
    (resource_node.go:67)."""
    lq = jnp.maximum(0, sat_sub(tree.subtree_quota, tree.lend_limit))
    return jnp.where(tree.has_lend_limit, lq, 0)


def local_available(tree: QuotaTreeArrays, usage: jnp.ndarray) -> jnp.ndarray:
    return jnp.maximum(0, sat_sub(local_quota(tree), usage))


def compute_subtree(
    tree: QuotaTreeArrays, cq_usage: jnp.ndarray, is_cq: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Bottom-up fill of subtree_quota and cohort usage roll-up
    (resource_node.go:190-227).

    Args:
      cq_usage: i64[N,F,R], meaningful on CQ rows (cohort rows are derived).
      is_cq: bool[N].

    Returns (subtree_quota, usage) for all nodes.
    """
    n = tree.n_nodes
    parent = _parent_or_self(tree)
    subtree = tree.nominal
    usage = jnp.where(is_cq[:, None, None], cq_usage, 0)

    # Process levels deepest-first. A node's subtree_quota is final once all
    # deeper levels have contributed, because contributions only flow one
    # level up per iteration.
    for d in range(MAX_DEPTH, 0, -1):
        at_level = (tree.depth == d) & tree.active & (tree.parent >= 0)
        mask = at_level[:, None, None]
        # local_quota depends on the node's *final* subtree quota, available
        # at this iteration since the node's children were already folded in.
        lq = jnp.where(
            tree.has_lend_limit,
            jnp.maximum(0, sat_sub(subtree, tree.lend_limit)),
            0,
        )
        q_delta = jnp.where(mask, sat_sub(subtree, lq), 0)
        u_delta = jnp.where(mask, jnp.maximum(0, sat_sub(usage, lq)), 0)
        subtree = sat_scatter_add(subtree, parent, q_delta)
        usage = sat_scatter_add(usage, parent, u_delta)
    return subtree, usage


def available_all(tree: QuotaTreeArrays, usage: jnp.ndarray) -> jnp.ndarray:
    """available() for every node at once (resource_node.go:106-122), by a
    top-down sweep: roots first, then each level consumes its parent's
    finished value."""
    parent = _parent_or_self(tree)
    lq = local_quota(tree)
    l_avail = jnp.maximum(0, sat_sub(lq, usage))
    stored_in_parent = sat_sub(tree.subtree_quota, lq)
    used_in_parent = jnp.maximum(0, sat_sub(usage, lq))
    with_max_from_parent = sat_add(
        sat_sub(stored_in_parent, used_in_parent), tree.borrow_limit
    )

    root_avail = sat_sub(tree.subtree_quota, usage)
    avail = root_avail  # correct for roots; refined level by level
    for d in range(1, MAX_DEPTH + 1):
        at_level = ((tree.depth == d) & (tree.parent >= 0))[:, None, None]
        parent_avail = avail[parent]
        clamped = jnp.where(
            tree.has_borrow_limit,
            jnp.minimum(with_max_from_parent, parent_avail),
            parent_avail,
        )
        avail = jnp.where(at_level, sat_add(l_avail, clamped), avail)
    return avail


def potential_available_all(tree: QuotaTreeArrays) -> jnp.ndarray:
    """potentialAvailable() for every node (resource_node.go:129-140)."""
    parent = _parent_or_self(tree)
    lq = local_quota(tree)
    max_with_borrowing = sat_add(tree.subtree_quota, tree.borrow_limit)

    pot = tree.subtree_quota  # correct for roots
    for d in range(1, MAX_DEPTH + 1):
        at_level = ((tree.depth == d) & (tree.parent >= 0))[:, None, None]
        val = sat_add(lq, pot[parent])
        val = jnp.where(
            tree.has_borrow_limit, jnp.minimum(max_with_borrowing, val), val
        )
        pot = jnp.where(at_level, val, pot)
    return pot


# Jitted alias: encoders call compute_subtree once per cycle; eager
# execution would issue ~50 small dispatches (very costly over a remote
# device transport). Wrapped for compile-cache / wall-time observability
# (single flag check per call when tracing is off).
compute_subtree_jit = tracing.instrument_jit(
    jax.jit(compute_subtree), "quota/compute_subtree"
)


def ancestor_chain(tree: QuotaTreeArrays, node: jnp.ndarray) -> jnp.ndarray:
    """Indices of node, parent, grandparent, ... padded by repeating the
    root. Returns i32[MAX_DEPTH+1]."""
    parent = _parent_or_self(tree)
    chain = [node]
    for _ in range(MAX_DEPTH):
        chain.append(parent[chain[-1]])
    return jnp.stack(chain)


def ancestor_matrix(tree: QuotaTreeArrays) -> jnp.ndarray:
    """bool[N, N]: entry [b, d] is True when b lies on d's root path
    (b is an ancestor-or-self of d). With no lending limits, usage at b
    includes the full usage of every d with [b, d] set."""
    n = tree.parent.shape[0]
    parent = _parent_or_self(tree)
    cols = [jnp.arange(n)]
    for _ in range(MAX_DEPTH):
        cols.append(parent[cols[-1]])
    chain = jnp.stack(cols, axis=1)  # [N, D+1]
    return jnp.zeros((n, n), bool).at[
        chain.ravel(), jnp.repeat(jnp.arange(n), MAX_DEPTH + 1)
    ].set(True)


def add_usage(
    tree: QuotaTreeArrays, usage: jnp.ndarray, node: jnp.ndarray, delta: jnp.ndarray
) -> jnp.ndarray:
    """Add delta i64[F,R] of usage at ``node``, bubbling the part exceeding
    local availability up the ancestor chain (resource_node.go:144-152).

    Returns the updated usage tensor. Works under jit/scan: the chain walk is
    a fixed MAX_DEPTH-step unrolled loop of gathers + one scatter-add.
    """
    chain = ancestor_chain(tree, node)
    lq = local_quota(tree)
    deltas = jnp.zeros((MAX_DEPTH + 1,) + delta.shape, dtype=I64)
    cur = delta
    for i in range(MAX_DEPTH + 1):
        idx = chain[i]
        local_avail = jnp.maximum(0, sat_sub(lq[idx], usage[idx]))
        deltas = deltas.at[i].set(cur)
        has_parent = tree.parent[idx] >= 0
        # bubble only the excess over (pre-update) local availability
        cur = jnp.where(has_parent, jnp.maximum(0, sat_sub(cur, local_avail)), 0)
        # NOTE: reference bubbles (val - localAvailable) which may go negative
        # only when val < localAvailable, in which case it doesn't recurse at
        # all; max(0, ...) with the has_parent gate reproduces both branches
        # for non-negative val.
    return sat_scatter_add(usage, chain, deltas)


def remove_usage(
    tree: QuotaTreeArrays, usage: jnp.ndarray, node: jnp.ndarray, delta: jnp.ndarray
) -> jnp.ndarray:
    """Inverse of add_usage (resource_node.go:156-165)."""
    chain = ancestor_chain(tree, node)
    lq = local_quota(tree)
    deltas = jnp.zeros((MAX_DEPTH + 1,) + delta.shape, dtype=I64)
    cur = delta
    for i in range(MAX_DEPTH + 1):
        idx = chain[i]
        stored_in_parent = sat_sub(usage[idx], lq[idx])
        deltas = deltas.at[i].set(cur)
        has_parent = tree.parent[idx] >= 0
        cont = has_parent & (stored_in_parent > 0)
        cur = jnp.where(cont, jnp.minimum(cur, stored_in_parent), 0)
    return sat_scatter_add(usage, chain, -deltas)


def borrow_height(
    tree: QuotaTreeArrays,
    usage: jnp.ndarray,
    cq: jnp.ndarray,
    fr_val: jnp.ndarray,
    n_levels: int = MAX_DEPTH + 1,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """FindHeightOfLowestSubtreeThatFits, batched over [F, R]
    (reference hierarchical_preemption.go:221).

    Args:
      cq: scalar node index.
      fr_val: i64[F,R] additional amount per flavor-resource cell.

    Returns (height i32[F,R], proper_subtree bool[F,R]) where proper_subtree
    reports the found subtree being smaller than the whole hierarchy.
    """
    chain = ancestor_chain(tree, cq)
    lq = local_quota(tree)
    l_avail = jnp.maximum(0, sat_sub(lq, usage))

    fshape = fr_val.shape
    height = jnp.zeros(fshape, dtype=jnp.int32)
    proper = jnp.zeros(fshape, dtype=bool)
    done = jnp.zeros(fshape, dtype=bool)

    # Level 0: the CQ itself.
    borrowing0 = sat_add(usage[cq], fr_val) > tree.subtree_quota[cq]
    has_parent0 = tree.parent[cq] >= 0
    fits_here = (~borrowing0) | (~has_parent0)
    height = jnp.where(fits_here, 0, height)
    proper = jnp.where(fits_here, has_parent0, proper)
    done = done | fits_here

    remaining = sat_sub(fr_val, l_avail[cq])
    root_height = tree.height[chain[min(n_levels - 1, MAX_DEPTH)]]
    for i in range(1, n_levels):
        idx = chain[i]
        is_real = idx != chain[i - 1]  # chain pads by repeating the root
        borrowing = sat_add(usage[idx], remaining) > tree.subtree_quota[idx]
        fits = (~borrowing) & is_real & ~done
        height = jnp.where(fits, tree.height[idx], height)
        proper = jnp.where(fits, tree.parent[idx] >= 0, proper)
        done = done | fits
        remaining = jnp.where(done, remaining, sat_sub(remaining, l_avail[idx]))
    # Nothing fit: whole-hierarchy height, not a proper subtree.
    height = jnp.where(done, height, root_height)
    proper = jnp.where(done, proper, False)
    return height, proper
