"""Admission Fair Sharing (AFS): usage-based LocalQueue ordering.

Behavioral surface: reference pkg/util/admissionfairsharing +
pkg/cache/queue/afs — per-LocalQueue consumed resources tracked as an
exponential moving average with a configured half-life, entry penalties
added at admission (alpha x totalRequests), and fair-sharing usage
  usage = sum_r weight_r * (consumed_r + penalty_r) / lqWeight
ordering workloads of CQs whose admissionScope is
UsageBasedAdmissionFairSharing (lowest usage first).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, Optional


@dataclass
class AdmissionFairSharingConfig:
    """reference config admissionFairSharing (configuration_types.go:758)."""

    usage_half_life_s: float = 600.0
    usage_sampling_interval_s: float = 300.0
    resource_weights: Dict[str, float] = field(default_factory=dict)


def _alpha(sampling: float, half_life: float) -> float:
    """calculateAlphaRate (admission_fair_sharing.go:41)."""
    if half_life == 0:
        return 0.0
    return 1.0 - math.pow(0.5, sampling / half_life)


@dataclass
class _Entry:
    consumed: Dict[str, float] = field(default_factory=dict)
    penalty: Dict[str, float] = field(default_factory=dict)
    last_update: float = 0.0


class AfsTracker:
    """Consumed-resources EMA + entry penalties per LocalQueue
    (reference afs/consumed_resources.go + entry_penalties.go)."""

    def __init__(self, config: Optional[AdmissionFairSharingConfig] = None):
        self.config = config or AdmissionFairSharingConfig()
        self._entries: Dict[str, _Entry] = {}
        self._lq_weight: Dict[str, float] = {}

    def set_lq_weight(self, lq_key: str, weight: float) -> None:
        self._lq_weight[lq_key] = weight

    def add_entry_penalty(self, lq_key: str, total_requests: Dict[str, int],
                          ) -> None:
        """CalculateEntryPenalty: alpha x totalRequests on admission."""
        a = _alpha(self.config.usage_sampling_interval_s,
                   self.config.usage_half_life_s)
        e = self._entries.setdefault(lq_key, _Entry())
        for r, v in total_requests.items():
            e.penalty[r] = e.penalty.get(r, 0.0) + a * v

    def sample(self, lq_key: str, running_usage: Dict[str, int],
               now: float) -> None:
        """CalculateDecayedConsumed: EMA of running usage; folds pending
        penalties into consumed (the reference pops penalties on sample)."""
        e = self._entries.setdefault(lq_key, _Entry())
        elapsed = max(0.0, now - e.last_update) if e.last_update else \
            self.config.usage_sampling_interval_s
        a = _alpha(elapsed, self.config.usage_half_life_s)
        merged: Dict[str, float] = {}
        for r in set(e.consumed) | set(running_usage):
            merged[r] = (
                e.consumed.get(r, 0.0) * (1 - a)
                + running_usage.get(r, 0) * a
            )
        for r, v in e.penalty.items():
            merged[r] = merged.get(r, 0.0) + v
        e.consumed = merged
        e.penalty = {}
        e.last_update = now

    def usage(self, lq_key: str) -> float:
        """CalculateUsage (admission_fair_sharing.go:67)."""
        e = self._entries.get(lq_key)
        if e is None:
            return 0.0
        total = 0.0
        for r in sorted(set(e.consumed) | set(e.penalty)):
            v = e.consumed.get(r, 0.0) + e.penalty.get(r, 0.0)
            total += self.config.resource_weights.get(r, 1.0) * v
        return total / self._lq_weight.get(lq_key, 1.0)
