"""Pending-workload queues.

Behavioral surface: reference pkg/cache/queue/{manager,cluster_queue}.go —
per-ClusterQueue priority heaps, one-head-per-CQ cycle heads, the
BestEffortFIFO inadmissible staging area with capacity-event wakeups, and
LocalQueue -> ClusterQueue routing.
"""

from __future__ import annotations

import heapq
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

from kueue_tpu.api.constants import (
    AdmissionScope,
    QueueingStrategy,
    RequeueReason,
)
from kueue_tpu.api.types import ClusterQueue, LocalQueue, Workload
from kueue_tpu.core.workload_info import WorkloadInfo, queue_order_timestamp
from kueue_tpu.metrics import tracing


def _order_key(info: WorkloadInfo) -> Tuple:
    """baseCompareFunc (reference cluster_queue.go): priority desc, then
    queue-order timestamp asc (eviction time if evicted, else creation)."""
    return (-info.priority(), queue_order_timestamp(info.obj), info.obj.uid)


class ClusterQueueHeap:
    """One CQ's pending heap + inadmissible staging
    (reference cluster_queue.go)."""

    def __init__(self, spec: ClusterQueue) -> None:
        self.spec = spec
        self._heap: List[Tuple[Tuple, str]] = []  # (key, wl_key)
        self._items: Dict[str, WorkloadInfo] = {}
        self.inadmissible: Dict[str, WorkloadInfo] = {}
        # Cycle snapshot guard (reference queueInadmissibleCycle): if capacity
        # changed since the last failed attempt, requeue immediately.
        self.queue_inadmissible_cycle = -1
        # Sticky workload (reference cluster_queue.go stickyWorkload): the
        # head currently preempting victims keeps the head slot on
        # BestEffortFIFO until admitted, unschedulable, or deleted — other
        # entries must not race for the capacity its evictions free.
        self.sticky: Optional[str] = None

    @property
    def strategy(self) -> QueueingStrategy:
        return self.spec.queueing_strategy

    def push(self, info: WorkloadInfo) -> None:
        key = info.key
        self.inadmissible.pop(key, None)
        if key not in self._items:
            self._items[key] = info
            heapq.heappush(self._heap, (_order_key(info), key))
        else:
            self._items[key] = info

    def pop_head(self, afs_usage_fn=None) -> Optional[WorkloadInfo]:
        if self.sticky is not None:
            info = self._items.pop(self.sticky, None)
            if info is not None:
                return info
            # Admitted or gone: the sticky entry no longer pends.
            self.sticky = None
        if afs_usage_fn is not None and self._items:
            # Usage-based admission fair sharing: lowest LocalQueue usage
            # first, base order as tiebreak (reference cluster_queue.go
            # queueOrderingFunc with enableAdmissionFs).
            best_key = min(
                self._items,
                key=lambda k: (
                    afs_usage_fn(self._items[k]),
                    _order_key(self._items[k]),
                ),
            )
            info = self._items.pop(best_key)
            return info
        while self._heap:
            _, key = heapq.heappop(self._heap)
            info = self._items.pop(key, None)
            if info is not None:
                return info
        return None

    def delete(self, key: str) -> None:
        self._items.pop(key, None)
        self.inadmissible.pop(key, None)
        if self.sticky == key:
            self.sticky = None

    def requeue_if_not_present(
        self, info: WorkloadInfo, reason: RequeueReason, scheduling_cycle: int
    ) -> bool:
        """reference cluster_queue.go:575 requeueIfNotPresent. Returns True
        when the workload went back to the active heap."""
        key = info.key
        if (
            reason == RequeueReason.PENDING_PREEMPTION
            and self.strategy == QueueingStrategy.BEST_EFFORT_FIFO
        ):
            self.sticky = key
        elif self.sticky == key:
            # Unschedulable for another reason: loses the head pin.
            self.sticky = None
        if key in self._items:
            return False
        immediate = (
            self.strategy == QueueingStrategy.STRICT_FIFO
            or reason == RequeueReason.FAILED_AFTER_NOMINATION
            or reason == RequeueReason.PENDING_PREEMPTION
            or self.queue_inadmissible_cycle >= scheduling_cycle
        )
        if immediate:
            self.push(info)
            return True
        self.inadmissible[key] = info
        return False

    def queue_inadmissible(self, scheduling_cycle: int) -> bool:
        """Move inadmissible workloads back to the heap on a capacity event
        (reference QueueInadmissibleWorkloads)."""
        self.queue_inadmissible_cycle = scheduling_cycle
        if not self.inadmissible:
            return False
        for info in self.inadmissible.values():
            if info.key not in self._items:
                self._items[info.key] = info
                heapq.heappush(self._heap, (_order_key(info), info.key))
        self.inadmissible.clear()
        return True

    def pending(self) -> int:
        return len(self._items) + len(self.inadmissible)

    def pending_active(self) -> int:
        return len(self._items)

    def snapshot_sorted(self) -> List[WorkloadInfo]:
        """All active pending workloads in head order (for the visibility
        API; reference cluster_queue.go Snapshot)."""
        return sorted(self._items.values(), key=_order_key)


class QueueManager:
    """reference pkg/cache/queue/manager.go."""

    def __init__(self) -> None:
        self._lock = threading.Condition()
        self.cluster_queues: Dict[str, ClusterQueueHeap] = {}
        self.local_queues: Dict[str, LocalQueue] = {}  # "ns/name" -> LQ
        self.scheduling_cycle = 0
        # AdmissionFairSharing tracker (None = AFS off).
        self.afs_tracker = None
        # Second-pass queue for workloads with delayed TAS admission
        # (reference second_pass_queue.go).
        self._second_pass: Dict[str, WorkloadInfo] = {}
        # Requeue timestamps for queue_requeue_latency_seconds; only
        # populated while tracing is enabled.
        self._requeue_ts: Dict[str, float] = {}

    # -- configuration ------------------------------------------------------

    def add_cluster_queue(self, spec: ClusterQueue) -> None:
        with self._lock:
            if spec.name in self.cluster_queues:
                self.cluster_queues[spec.name].spec = spec
            else:
                self.cluster_queues[spec.name] = ClusterQueueHeap(spec)
            self._lock.notify_all()

    def delete_cluster_queue(self, name: str) -> None:
        with self._lock:
            self.cluster_queues.pop(name, None)

    def add_local_queue(self, lq: LocalQueue) -> None:
        with self._lock:
            self.local_queues[lq.key] = lq

    def delete_local_queue(self, lq_key: str) -> None:
        with self._lock:
            self.local_queues.pop(lq_key, None)

    def cluster_queue_for(self, wl: Workload) -> Optional[str]:
        lq = self.local_queues.get(f"{wl.namespace}/{wl.queue_name}")
        if lq is None:
            return None
        return lq.cluster_queue or None

    # -- workload flow ------------------------------------------------------

    def add_or_update_workload(self, wl: Workload) -> bool:
        cq_name = self.cluster_queue_for(wl)
        if cq_name is None:
            return False
        with self._lock:
            cqh = self.cluster_queues.get(cq_name)
            if cqh is None:
                return False
            info = WorkloadInfo(wl, cq_name)
            cqh.push(info)
            self._lock.notify_all()
            return True

    def requeue_workload(
        self, info: WorkloadInfo, reason: RequeueReason
    ) -> bool:
        with self._lock:
            cqh = self.cluster_queues.get(info.cluster_queue)
            if cqh is None:
                return False
            added = cqh.requeue_if_not_present(
                info, reason, self.scheduling_cycle
            )
            if tracing.ENABLED:
                tracing.inc(
                    "queue_requeue_total",
                    {"reason": reason.value, "immediate": str(added).lower()},
                )
                if added:
                    self._requeue_ts[info.key] = time.perf_counter()
            if added:
                self._lock.notify_all()
            return added

    def delete_workload(self, wl: Workload) -> None:
        with self._lock:
            for cqh in self.cluster_queues.values():
                cqh.delete(wl.key)
            self._second_pass.pop(wl.key, None)

    def queue_second_pass(self, info: WorkloadInfo) -> None:
        with self._lock:
            self._second_pass[info.key] = info
            self._lock.notify_all()

    def queue_inadmissible_workloads(
        self, cq_names: Optional[Iterable[str]] = None
    ) -> None:
        """Capacity-changed event: wake inadmissible workloads
        (reference manager.go QueueInadmissibleWorkloads)."""
        with self._lock:
            moved = False
            names = (
                list(cq_names) if cq_names is not None
                else list(self.cluster_queues)
            )
            for name in names:
                cqh = self.cluster_queues.get(name)
                if cqh is not None and cqh.queue_inadmissible(
                    self.scheduling_cycle
                ):
                    moved = True
            if moved:
                self._lock.notify_all()

    def heads(self) -> List[WorkloadInfo]:
        """Pop one head per CQ plus all ready second-pass workloads
        (reference manager.go:882,901). Non-blocking variant: returns []
        when nothing is pending."""
        if not tracing.ENABLED:
            return self._heads_impl()
        with tracing.span("queue/heads") as s:
            t0 = time.perf_counter()
            out = self._heads_impl()
            now = time.perf_counter()
            s.set_arg("heads", len(out))
            tracing.observe("queue_heads_duration_seconds", now - t0)
            tracing.inc("queue_heads_popped_total", value=len(out))
            for info in out:
                ts = self._requeue_ts.pop(info.key, None)
                if ts is not None:
                    tracing.observe("queue_requeue_latency_seconds", now - ts)
            return out

    def _heads_impl(self) -> List[WorkloadInfo]:
        with self._lock:
            self.scheduling_cycle += 1
            out: List[WorkloadInfo] = []
            for cqh in self.cluster_queues.values():
                afs_fn = None
                if (
                    self.afs_tracker is not None
                    and cqh.spec.admission_scope
                    == AdmissionScope.USAGE_BASED_FAIR_SHARING
                ):
                    tracker = self.afs_tracker

                    def afs_fn(info, _t=tracker):
                        u = _t.usage(
                            f"{info.obj.namespace}/{info.obj.queue_name}"
                        )
                        info.local_queue_fs_usage = u
                        return u

                head = cqh.pop_head(afs_fn)
                if head is not None:
                    out.append(head)
            out.extend(self._second_pass.values())
            self._second_pass.clear()
            return out

    def heads_blocking(self, timeout: Optional[float] = None) -> List[WorkloadInfo]:
        """Blocking Heads() for the daemon loop."""
        with self._lock:
            while not self._any_pending_locked():
                if not self._lock.wait(timeout):
                    return []
        return self.heads()

    def _any_pending_locked(self) -> bool:
        return bool(self._second_pass) or any(
            cqh.pending_active() for cqh in self.cluster_queues.values()
        )

    # -- introspection (visibility API) -------------------------------------

    def pending_workloads(self, cq_name: str) -> List[WorkloadInfo]:
        with self._lock:
            cqh = self.cluster_queues.get(cq_name)
            if cqh is None:
                return []
            return cqh.snapshot_sorted()

    def pending_count(self, cq_name: str) -> int:
        with self._lock:
            cqh = self.cluster_queues.get(cq_name)
            return cqh.pending() if cqh else 0

    def oldest_pending_creation(self, cq_name: str) -> Optional[float]:
        """Creation timestamp of the oldest pending workload (active or
        inadmissible) in one CQ, or None when nothing is pending — the
        source for the service loop's oldest-pending-age watermark."""
        with self._lock:
            cqh = self.cluster_queues.get(cq_name)
            if cqh is None:
                return None
            times = [
                i.obj.creation_time
                for i in list(cqh._items.values())
                + list(cqh.inadmissible.values())
            ]
            return min(times) if times else None

    def pending_workloads_all(self, cq_name: str) -> List[WorkloadInfo]:
        """Active AND inadmissible pending entries in head order. The
        forecasting view: inadmissible workloads requeue on the next
        capacity event, so a virtual-time rollout must include them."""
        with self._lock:
            cqh = self.cluster_queues.get(cq_name)
            if cqh is None:
                return []
            return sorted(
                list(cqh._items.values()) + list(cqh.inadmissible.values()),
                key=_order_key,
            )
