"""Driver entry-point regression tests: entry() must stay jittable and
dryrun_multichip must compile + execute over a virtual mesh."""

import jax


def test_entry_compiles_and_admits():
    import __graft_entry__ as g

    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    admitted = int((out.outcome == 4).sum())
    assert out.outcome.shape[0] == 16
    assert admitted > 0


def test_dryrun_multichip_8():
    import __graft_entry__ as g

    g.dryrun_multichip(8)
