"""Admission-cycle tracing + metrics observability tests.

Covers the tracing substrate (span nesting, thread safety, Chrome-trace
export), the Prometheus exposition round-trip through a strict text-format
parser, registry fixes (quantile interpolation, get() ambiguity, label
escaping), trace-id propagation across the gRPC worker seam, the
schedule_all() span/series surface, the metric-name allowlist checker, and
the end-to-end acceptance run (one traced perf-harness run producing a
valid Chrome trace with nested + remote spans and a parseable /metrics
exposition with non-zero admission-cycle histograms).
"""

from __future__ import annotations

import json
import re
import threading
import time
import urllib.error
import urllib.request

import pytest

from kueue_tpu.api.types import LocalQueue, PodSet, ResourceQuota, Workload
from kueue_tpu.manager import Manager
from kueue_tpu.metrics import METRIC_NAMES
from kueue_tpu.metrics import tracing
from kueue_tpu.metrics.registry import Histogram, Metrics

from .helpers import make_cq


@pytest.fixture(autouse=True)
def _reset_tracing():
    """Every test starts and ends with tracing off and a fresh
    default-size buffer (a prior test may have installed a small one)."""
    tracing.enable(buffer_len=tracing._DEFAULT_BUFFER_LEN)
    tracing.disable()
    tracing.get_tracer().clear()
    yield
    tracing.enable(buffer_len=tracing._DEFAULT_BUFFER_LEN)
    tracing.disable()
    tracing.get_tracer().clear()


# ----------------------------------------------------------------------
# strict Prometheus text-format parser (the round-trip oracle)
# ----------------------------------------------------------------------

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})? (\S+)$"
)


def _parse_label_pairs(s: str) -> dict:
    """Parse `k="v",k2="v2"` honoring \\\\, \\" and \\n escapes. Raises on
    anything malformed — this parser is deliberately strict."""
    labels = {}
    i = 0
    while i < len(s):
        eq = s.index("=", i)
        key = s[i:eq]
        if not _NAME_RE.match(key):
            raise ValueError(f"bad label name {key!r}")
        if s[eq + 1] != '"':
            raise ValueError(f"label value not quoted at {eq}")
        i = eq + 2
        out = []
        while True:
            if i >= len(s):
                raise ValueError("unterminated label value")
            c = s[i]
            if c == "\\":
                esc = s[i + 1]
                if esc not in ('\\', '"', "n"):
                    raise ValueError(f"invalid escape \\{esc}")
                out.append({"\\": "\\", '"': '"', "n": "\n"}[esc])
                i += 2
            elif c == '"':
                i += 1
                break
            elif c == "\n":
                raise ValueError("raw newline inside label value")
            else:
                out.append(c)
                i += 1
        labels[key] = "".join(out)
        if i < len(s):
            if s[i] != ",":
                raise ValueError(f"expected ',' at {i} in {s!r}")
            i += 1
    return labels


def parse_prometheus_text(text: str):
    """Parse an exposition. Returns (types, samples) where samples maps
    (name, frozenset(labels.items())) -> float. Raises ValueError on any
    line a strict scraper would reject."""
    types = {}
    samples = {}
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            parts = line.split(" ")
            if len(parts) != 4 or not _NAME_RE.match(parts[2]) or \
                    parts[3] not in ("counter", "gauge", "histogram"):
                raise ValueError(f"bad TYPE line: {line!r}")
            types[parts[2]] = parts[3]
            continue
        if line.startswith("#"):
            continue  # comments are legal
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ValueError(f"bad sample line: {line!r}")
        name, raw_labels, raw_value = m.groups()
        labels = _parse_label_pairs(raw_labels) if raw_labels else {}
        if raw_value == "+Inf":
            value = float("inf")
        else:
            value = float(raw_value)  # raises on garbage
        samples[(name, frozenset(labels.items()))] = value
    # Histogram structure: cumulative buckets non-decreasing, +Inf == count.
    for name, typ in types.items():
        if typ != "histogram":
            continue
        by_series = {}
        for (n, lk), v in samples.items():
            if n == f"{name}_bucket":
                rest = frozenset(
                    kv for kv in lk if kv[0] != "le"
                )
                le = dict(lk)["le"]
                by_series.setdefault(rest, []).append(
                    (float("inf") if le == "+Inf" else float(le), v)
                )
        for rest, buckets in by_series.items():
            buckets.sort()
            counts = [c for _, c in buckets]
            if counts != sorted(counts):
                raise ValueError(f"{name}: non-cumulative buckets")
            total = samples.get((f"{name}_count", rest))
            if total is None or buckets[-1][1] != total:
                raise ValueError(f"{name}: +Inf bucket != count")
    return types, samples


# ----------------------------------------------------------------------
# tracer core
# ----------------------------------------------------------------------


def test_span_is_shared_noop_when_disabled():
    s1 = tracing.span("a", x=1)
    s2 = tracing.span("b")
    assert s1 is s2  # shared singleton: no allocation on the disabled path
    with s1 as s:
        s.set_arg("k", "v")  # must be a no-op, not an error
    assert tracing.get_tracer().spans() == []


def test_span_nesting_records_parent_and_trace_id():
    tracing.enable()
    with tracing.span("outer") as outer:
        with tracing.span("inner") as inner:
            assert inner.trace_id == outer.trace_id
            assert tracing.current_trace_id() == outer.trace_id
    assert tracing.current_trace_id() is None  # root span resets
    spans = tracing.get_tracer().spans()
    by_name = {s["name"]: s for s in spans}
    assert by_name["inner"]["parent"] == "outer"
    assert by_name["outer"]["parent"] is None
    assert by_name["inner"]["trace_id"] == by_name["outer"]["trace_id"]
    # Inner closes first and nests inside the outer interval.
    assert by_name["inner"]["ts"] >= by_name["outer"]["ts"]
    assert by_name["inner"]["dur"] <= by_name["outer"]["dur"]


def test_span_thread_safety():
    """Concurrent nested spans in N threads must not cross-contaminate
    parents or trace ids (contextvars give each thread its own stack)."""
    tracing.enable()
    barrier = threading.Barrier(4)

    def work(i: int) -> None:
        barrier.wait()
        for _ in range(50):
            with tracing.span(f"outer-{i}"):
                with tracing.span(f"inner-{i}"):
                    pass

    threads = [threading.Thread(target=work, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    spans = tracing.get_tracer().spans()
    assert len(spans) == 4 * 50 * 2
    for rec in spans:
        if rec["name"].startswith("inner-"):
            i = rec["name"].split("-")[1]
            assert rec["parent"] == f"outer-{i}"
    # Each thread's spans carry its own thread id.
    tids_by_thread = {}
    for rec in spans:
        i = rec["name"].split("-")[1]
        tids_by_thread.setdefault(i, set()).add(rec["tid"])
    for tids in tids_by_thread.values():
        assert len(tids) == 1
    assert len(set().union(*tids_by_thread.values())) == 4


def test_ring_buffer_drops_oldest_and_counts():
    tracer = tracing.enable(buffer_len=8)
    for i in range(20):
        with tracing.span(f"s{i}"):
            pass
    spans = tracer.spans()
    assert len(spans) == 8
    assert [s["name"] for s in spans] == [f"s{i}" for i in range(12, 20)]
    assert tracer.dropped == 12


def test_chrome_trace_export_shape():
    tracing.enable()
    with tracing.span("cycle", n=3):
        with tracing.span("stage"):
            pass
    doc = tracing.export_chrome_trace()
    assert doc["displayTimeUnit"] == "ms"
    # Span events, plus the process_name metadata row naming the client
    # lane (the merged-timeline export labels every process).
    meta = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    assert [m["args"]["name"] for m in meta] == ["client"]
    events = [e for e in doc["traceEvents"] if e["ph"] != "M"]
    assert {e["name"] for e in events} == {"cycle", "stage"}
    for e in events:
        assert e["ph"] == "X"
        assert e["cat"] == "kueue_tpu"
        assert isinstance(e["ts"], float) and isinstance(e["dur"], float)
        assert e["dur"] >= 0
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        assert "trace_id" in e["args"]
    # JSON-serializable end to end (Perfetto loads the dump verbatim).
    json.loads(json.dumps(doc))
    cycle = next(e for e in events if e["name"] == "cycle")
    assert cycle["args"]["n"] == 3


def test_span_durations_land_in_metrics_sink():
    m = Metrics()
    tracing.enable(m)
    with tracing.span("phase-x"):
        pass
    h = m.histograms["trace_span_duration_seconds"]
    (lk, hist), = h.items()
    assert dict(lk) == {"span": "phase-x"}
    assert hist.n == 1


# ----------------------------------------------------------------------
# registry fixes (satellites 1-2)
# ----------------------------------------------------------------------


def test_label_value_escaping_round_trip():
    m = Metrics()
    nasty = 'he said "hi"\nback\\slash'
    m.inc("workloads_created_total", {"queue": nasty})
    m.set_gauge("pending_workloads", 2, {"cluster_queue": 'a"b'})
    m.observe("admission_wait_time_seconds", 0.2, {"q": "x\ny"})
    types, samples = parse_prometheus_text(m.expose())
    assert samples[(
        "kueue_workloads_created_total", frozenset({("queue", nasty)})
    )] == 1.0
    assert samples[(
        "kueue_pending_workloads", frozenset({("cluster_queue", 'a"b')})
    )] == 2.0
    assert ("kueue_admission_wait_time_seconds_count",
            frozenset({("q", "x\ny")})) in samples


def test_histogram_quantile_interpolates_within_bucket():
    h = Histogram()
    # 10 observations all landing in the (0.05, 0.1] bucket.
    for _ in range(10):
        h.observe(0.07)
    # Linear interpolation: lo + (hi-lo) * target/count.
    assert h.quantile(0.5) == pytest.approx(0.075)
    assert h.quantile(1.0) == pytest.approx(0.1)
    assert 0.05 < h.quantile(0.1) < 0.075


def test_histogram_quantile_across_buckets_and_overflow():
    h = Histogram()
    for v in (0.003, 0.003, 0.07, 0.07, 1000.0):
        h.observe(v)
    # q=0.2 -> target 1.0 falls in the (0.001, 0.005] bucket (2 obs).
    assert 0.001 < h.quantile(0.2) <= 0.005
    # q=0.7 -> target 3.5 falls in the (0.05, 0.1] bucket.
    assert 0.05 < h.quantile(0.7) <= 0.1
    # Overflow bucket has no finite upper bound.
    assert h.quantile(0.99) == float("inf")
    assert Histogram().quantile(0.5) == 0.0


def test_metrics_get_rejects_counter_gauge_ambiguity():
    m = Metrics()
    m.inc("pending_workloads")
    m.set_gauge("pending_workloads", 7)
    with pytest.raises(ValueError, match="both counter and gauge"):
        m.get("pending_workloads")
    # Unambiguous reads still work.
    m.inc("workloads_created_total")
    assert m.get("workloads_created_total") == 1.0


def test_full_exposition_is_strictly_parseable():
    mgr = Manager()
    mgr.apply(make_cq("cq-a"))
    mgr.apply(LocalQueue(name="lq", cluster_queue="cq-a"))
    mgr.create_workload(Workload(name="w1", queue_name="lq", pod_sets=[
        PodSet(name="main", count=1, requests={"cpu": 1000})]))
    mgr.schedule_all()
    types, samples = parse_prometheus_text(mgr.metrics.expose())
    assert types["kueue_admission_attempts_total"] == "counter"
    assert types["kueue_admission_attempt_duration_seconds"] == "histogram"


# ----------------------------------------------------------------------
# cross-boundary propagation (gRPC seam)
# ----------------------------------------------------------------------


def test_grpc_trace_id_propagates_to_worker_spans():
    from kueue_tpu.remote import GrpcWorkerClient, serve_worker_grpc

    tracing.enable()
    server, bound = serve_worker_grpc(Manager(), in_thread=True)
    try:
        client = GrpcWorkerClient(bound)
        with tracing.span("caller") as caller:
            client.schedule()
            caller_tid = caller.trace_id
        client.close()
    finally:
        server.stop(0)
    spans = tracing.get_tracer().spans()
    dispatch = [s for s in spans if s["name"] == "remote/dispatch"]
    call = [s for s in spans if s["name"] == "remote/call"]
    assert dispatch and call
    # The worker-side span carries the CALLER's trace id even though it
    # ran on a different (server executor) thread.
    assert dispatch[0]["trace_id"] == caller_tid
    assert call[0]["trace_id"] == caller_tid
    assert dispatch[0]["tid"] != call[0]["tid"]
    assert dispatch[0]["args"]["op"] == "schedule"


def test_socket_client_injects_trace_key():
    """The wire request carries the trace id only while tracing is on."""
    from kueue_tpu.remote.worker import dispatch

    mgr = Manager()
    # Disabled: dispatch of a plain request works and records nothing.
    assert dispatch(mgr, {"op": "ping"})["ok"]
    tracing.enable()
    resp = dispatch(mgr, {"op": "ping", "trace": "feedbeef00000000"})
    assert resp["ok"]
    recs = [s for s in tracing.get_tracer().spans()
            if s["name"] == "remote/dispatch"]
    assert recs and recs[0]["trace_id"] == "feedbeef00000000"


# ----------------------------------------------------------------------
# schedule_all() span/series surface
# ----------------------------------------------------------------------


def _contended_manager() -> Manager:
    from kueue_tpu.api.constants import PreemptionPolicy
    from kueue_tpu.api.types import ClusterQueuePreemption, ResourceFlavor

    mgr = Manager()
    mgr.apply(ResourceFlavor(name="default"))
    mgr.apply(make_cq(
        "cq-a",
        flavors={"default": {"cpu": ResourceQuota(nominal=2000)}},
        preemption=ClusterQueuePreemption(
            within_cluster_queue=PreemptionPolicy.LOWER_PRIORITY,
        ),
    ))
    mgr.apply(LocalQueue(name="lq", cluster_queue="cq-a"))
    for i in range(2):
        mgr.create_workload(Workload(
            name=f"lo-{i}", queue_name="lq", priority=0,
            pod_sets=[PodSet(name="main", count=1,
                             requests={"cpu": 1000})],
        ))
    mgr.schedule_all()
    # Higher-priority arrival forces a preemption search.
    mgr.create_workload(Workload(
        name="hi", queue_name="lq", priority=100,
        pod_sets=[PodSet(name="main", count=1, requests={"cpu": 2000})],
    ))
    return mgr


def test_schedule_all_emits_expected_spans_and_series():
    mgr = _contended_manager()
    tracing.enable(mgr.metrics)
    tracing.get_tracer().clear()
    mgr.schedule_all()
    names = {s["name"] for s in tracing.get_tracer().spans()}
    assert {"scheduler/cycle", "scheduler/snapshot", "scheduler/nominate",
            "scheduler/process", "scheduler/process_entry",
            "scheduler/flavor_assignment", "scheduler/preemption_search",
            "queue/heads"} <= names
    m = mgr.metrics
    assert m.histograms["scheduler_admission_cycle_duration_seconds"]
    stages = {
        dict(lk)["stage"]
        for lk in m.histograms["scheduler_admission_cycle_stage_seconds"]
    }
    assert stages == {"snapshot", "nominate", "process"}
    assert sum(m.counters["queue_heads_popped_total"].values()) > 0
    assert sum(m.counters["flavor_assignment_total"].values()) > 0
    assert sum(m.counters["preemption_search_total"].values()) > 0
    assert m.histograms["queue_heads_duration_seconds"]
    # Requeue latency recorded for the preemptor pinned at the head.
    assert m.counters["queue_requeue_total"]
    # Every emitted series is on the frozen allowlist.
    for store in (m.counters, m.gauges, m.histograms):
        for name in store:
            assert name in METRIC_NAMES, name


def test_untraced_schedule_all_records_nothing():
    mgr = _contended_manager()
    mgr.schedule_all()
    assert tracing.get_tracer().spans() == []
    assert "scheduler_admission_cycle_duration_seconds" not in \
        mgr.metrics.histograms


# ----------------------------------------------------------------------
# metric-name allowlist checker (satellite 5)
# ----------------------------------------------------------------------


def test_metric_names_allowlist_clean():
    import sys
    sys.path.insert(0, "/root/repo/tools")
    import check_metrics_names

    violations = check_metrics_names.run_check()
    assert violations == [], "\n".join(violations)


def test_checker_flags_unknown_and_dynamic_names(tmp_path):
    import sys
    sys.path.insert(0, "/root/repo/tools")
    import check_metrics_names

    bad = tmp_path / "bad.py"
    bad.write_text(
        "def f(m, tracing, name):\n"
        "    m.inc('no_such_series_total')\n"
        "    tracing.observe(name, 1.0)\n"
        "    self.roletracker.observe(True)\n"  # not a metrics receiver
    )
    violations = check_metrics_names.check_file(
        bad, frozenset({"workloads_created_total"})
    )
    assert len(violations) == 2
    assert any("no_such_series_total" in msg for _, msg in violations)
    assert any("not a string literal" in msg for _, msg in violations)


# ----------------------------------------------------------------------
# dashboard endpoints
# ----------------------------------------------------------------------


def test_dashboard_metrics_and_trace_endpoints():
    from kueue_tpu.visibility.dashboard import serve_dashboard

    mgr = _contended_manager()
    tracing.enable(mgr.metrics)
    mgr.schedule_all()
    httpd = serve_dashboard(mgr, port=0)
    try:
        port = httpd.server_address[1]
        raw = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5
        ).read().decode()
        types, samples = parse_prometheus_text(raw)
        assert "kueue_scheduler_admission_cycle_duration_seconds" in types
        doc = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/trace", timeout=5
        ).read())
        assert doc["traceEvents"]
        assert {e["name"] for e in doc["traceEvents"]} >= {
            "scheduler/cycle"
        }
    finally:
        httpd.shutdown()


# ----------------------------------------------------------------------
# acceptance: one traced harness run
# ----------------------------------------------------------------------

ACCEPTANCE_CONFIG = {
    # Topology + preemption so one run exercises the full span tree:
    # cycle -> flavor assignment -> preemption search -> TAS placement.
    "topology": {
        "name": "topo",
        "levels": [
            {"name": "block", "count": 1, "nodeLabel": "topology/block"},
            {"name": "rack", "count": 2, "nodeLabel": "topology/rack"},
            {"name": "node", "count": 2,
             "nodeLabel": "kubernetes.io/hostname",
             "capacity": {"cpu": "8"}},
        ],
    },
    "resourceFlavor": {"name": "tas-flavor"},
    "cohorts": [{
        "className": "c", "count": 1,
        "queuesSets": [{
            "className": "q", "count": 2, "nominalQuota": 16,
            "borrowingLimit": 16,
            "reclaimWithinCohort": "LowerPriority",
            "withinClusterQueue": "LowerPriority",
            "workloadsSets": [{
                "count": 8, "creationIntervalMs": 40,
                "workloads": [
                    {"className": "lo", "priority": 0, "request": 4,
                     "runtimeMs": 400, "tasConstraint": "required",
                     "tasLevel": "topology/rack"},
                    {"className": "hi", "priority": 10, "request": 4,
                     "runtimeMs": 100, "tasConstraint": "preferred",
                     "tasLevel": "topology/block"},
                ],
            }],
        }],
    }],
}


def test_acceptance_traced_harness_run():
    """ISSUE acceptance: ONE perf-harness run yields (a) a valid Chrome
    trace with nested cycle/assignment/preemption/TAS spans including a
    remote-worker span carrying the caller's trace id, and (b) a
    /metrics exposition a strict parser accepts with non-zero
    scheduler_admission_cycle-family histograms."""
    from kueue_tpu.perf import harness

    result = harness.run(ACCEPTANCE_CONFIG, trace=True, trace_remote=True)
    assert result.admitted == result.total_workloads
    assert not tracing.enabled()  # restored after the run

    # (a) Chrome trace: loadable JSON, nested span tree, remote span.
    doc = result.trace
    json.loads(json.dumps(doc))
    events = [e for e in doc["traceEvents"] if e["ph"] != "M"]
    names = {e["name"] for e in events}
    assert {"scheduler/cycle", "scheduler/flavor_assignment",
            "scheduler/preemption_search", "scheduler/tas_placement",
            "remote/call", "remote/dispatch",
            "harness/remote_probe"} <= names
    parents = {}
    for e in events:
        parents.setdefault(e["name"], set()).add(e["args"]["parent"])
    assert parents["scheduler/snapshot"] == {"scheduler/cycle"}
    # Flavor assignment runs under nomination (and under process_entry on
    # in-cycle retries); either way it nests inside the cycle tree.
    assert parents["scheduler/flavor_assignment"] <= {
        "scheduler/nominate", "scheduler/process_entry"
    }
    probe = next(e for e in events if e["name"] == "harness/remote_probe")
    dispatch = next(e for e in events if e["name"] == "remote/dispatch")
    assert dispatch["args"]["trace_id"] == probe["args"]["trace_id"]

    # (b) strict-parseable /metrics with non-zero cycle histograms.
    types, samples = parse_prometheus_text(result.metrics_text)
    assert types["kueue_scheduler_admission_cycle_duration_seconds"] == \
        "histogram"
    assert samples[(
        "kueue_scheduler_admission_cycle_duration_seconds_count",
        frozenset(),
    )] > 0
    stage_counts = [
        v for (n, lk), v in samples.items()
        if n == "kueue_scheduler_admission_cycle_stage_seconds_count"
    ]
    assert stage_counts and all(v > 0 for v in stage_counts)
    # Phase breakdown covers the dominant scheduler phases.
    assert result.phase_breakdown["scheduler/cycle"] > 0


# ----------------------------------------------------------------------
# histogram quantile edges (provenance/SLO PR satellites)
# ----------------------------------------------------------------------


def test_histogram_quantile_degenerate_inputs():
    h = Histogram()
    for v in (0.07, 0.07, 0.07):
        h.observe(v)
    # q=0 -> target 0 is satisfied by the very first (empty) bucket,
    # whose zero count short-circuits to its upper bound — the estimator
    # answers "at most the smallest bucket bound", never a negative.
    assert h.quantile(0.0) == pytest.approx(0.001)
    assert h.quantile(1.0) == pytest.approx(0.1)
    # Empty histogram: every quantile is 0 (and never divides by zero).
    assert Histogram().quantile(0.0) == 0.0
    assert Histogram().quantile(1.0) == 0.0


def test_histogram_quantile_single_bucket_layout():
    h = Histogram(buckets=[1.0])
    for v in (0.2, 0.4, 0.6, 0.8):
        h.observe(v)
    # All mass in the one finite bucket: interpolate within (0, 1.0].
    assert h.quantile(0.5) == pytest.approx(0.5)
    assert h.quantile(1.0) == pytest.approx(1.0)
    h.observe(5.0)  # overflow bucket
    assert h.quantile(0.99) == float("inf")


# ----------------------------------------------------------------------
# checker extensions: never-emitted names, reason-code docs
# ----------------------------------------------------------------------


def _checker():
    import sys
    sys.path.insert(0, "/root/repo/tools")
    import check_metrics_names
    return check_metrics_names


def test_checker_collects_bare_and_conditional_emit_names(tmp_path):
    """The emitted-name collector must see the tracing idiom: bare
    module-level inc()/observe() calls, and a conditional first arg
    (observe("a" if x else "b", ...)) contributing BOTH names."""
    checker = _checker()
    src = tmp_path / "emit.py"
    src.write_text(
        "def f(m, miss):\n"
        "    inc('bare_total')\n"
        "    m.observe('attr_seconds' if miss else 'other_seconds', 1.0)\n"
    )
    names = checker.collect_emitted_names(src)
    assert {"bare_total", "attr_seconds", "other_seconds"} <= names


def test_checker_flags_allowlisted_but_never_emitted():
    checker = _checker()
    violations = checker.check_emitted_coverage(
        frozenset({"this_series_is_never_emitted_total"})
    )
    assert len(violations) == 1
    assert "no call site ever emits it" in violations[0]
    assert "this_series_is_never_emitted_total" in violations[0]
    # The real allowlist has no dead names (also covered by run_check).
    from kueue_tpu.metrics.names import METRIC_NAMES
    assert checker.check_emitted_coverage(METRIC_NAMES) == []


def test_checker_requires_reason_codes_documented():
    checker = _checker()
    assert checker.check_reason_codes_documented() == []


# ----------------------------------------------------------------------
# dashboard history: concurrent samplers vs readers
# ----------------------------------------------------------------------


def test_dashboard_history_snapshot_is_consistent_under_races():
    """Writers append four rings per sample; a reader must never see
    them mid-append with different lengths."""
    from kueue_tpu.visibility.dashboard import _History

    hist = _History()
    stop = threading.Event()
    bad = []

    def writer():
        i = 0
        while not stop.is_set():
            hist.sample(i, i + 1, float(i))
            i += 1

    def reader():
        while not stop.is_set():
            snap = hist.snapshot()
            lengths = {len(v) for v in snap.values()}
            if len(lengths) != 1:
                bad.append(lengths)

    threads = [threading.Thread(target=writer) for _ in range(2)]
    threads += [threading.Thread(target=reader) for _ in range(2)]
    for t in threads:
        t.start()
    time.sleep(0.3)
    stop.set()
    for t in threads:
        t.join()
    assert not bad, bad
    snap = hist.snapshot()
    assert len(snap["pending"]) == len(snap["admitted"]) \
        == len(snap["preempted_total"])


# ----------------------------------------------------------------------
# visibility server robustness: malformed requests -> structured errors
# ----------------------------------------------------------------------


def _obs_server():
    from kueue_tpu.visibility.server import VisibilityServer

    mgr = Manager()
    mgr.apply(make_cq("cq-a"))
    mgr.apply(LocalQueue(name="lq", cluster_queue="cq-a"))
    srv = VisibilityServer(
        mgr.queues, whatif=mgr.whatif(),
        explainer=mgr.explainer(), slo=mgr.slo(),
    )
    httpd = srv.serve(port=0)
    return mgr, httpd, f"http://127.0.0.1:{httpd.server_address[1]}"


def _post(url, body: bytes):
    req = urllib.request.Request(
        url, data=body, headers={"Content-Type": "application/json"}
    )
    try:
        resp = urllib.request.urlopen(req, timeout=10)
        return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as err:
        return err.code, json.loads(err.read())


def test_server_malformed_whatif_returns_structured_400():
    mgr, httpd, base = _obs_server()
    try:
        # Non-JSON body.
        code, doc = _post(f"{base}/whatif/preview", b"{nope")
        assert code == 400 and "error" in doc
        # JSON but not an object.
        code, doc = _post(f"{base}/whatif/preview", b"[1, 2]")
        assert code == 400
        assert doc["detail"] == "JSON body must be an object"
        # Wrong field types inside an otherwise-valid object.
        code, doc = _post(
            f"{base}/whatif/preview",
            json.dumps({"requests": {"cpu": "abc"}}).encode(),
        )
        assert code == 400 and doc["error"] == "bad request"
        assert "detail" in doc
        # Scenarios must be a list of dicts.
        code, doc = _post(
            f"{base}/whatif/eta",
            json.dumps({"scenarios": 42}).encode(),
        )
        assert code == 400 and doc["error"] == "bad request"
    finally:
        httpd.shutdown()


def test_server_unknown_paths_and_workloads_are_structured_404():
    mgr, httpd, base = _obs_server()
    try:
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(f"{base}/no/such/route", timeout=10)
        assert err.value.code == 404
        doc = json.loads(err.value.read())
        assert doc["error"] == "not found" and doc["path"] == "/no/such/route"
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(f"{base}/explain/ghost", timeout=10)
        assert err.value.code == 404
        assert json.loads(err.value.read())["found"] is False
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(f"{base}/explain", timeout=10)
        assert err.value.code == 400
        assert "usage" in json.loads(err.value.read())["detail"]
        code, doc = _post(f"{base}/no/such/route", b"{}")
        assert code == 404 and doc["error"] == "not found"
    finally:
        httpd.shutdown()
