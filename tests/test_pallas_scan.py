"""Differential: Pallas admission-scan kernel vs the XLA grouped scan.

Random no-preempt forests (depths 1-3, borrow/lend limits, initial usage,
multi-flavor fungibility) — the Pallas cycle (interpret mode on CPU) must
produce bit-identical outcomes, flavors, and final usage to
``bs.make_grouped_cycle``. The same scenarios run through ``fits_int32``
to confirm the gate admits them; an oversized scenario must be rejected.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from kueue_tpu.models import batch_scheduler as bs
from kueue_tpu.models.encode import CycleArrays, _order_rank
from kueue_tpu.models.pallas_scan import (
    CAP32,
    fits_int32,
    make_pallas_cycle,
)
from kueue_tpu.ops.quota_ops import QuotaTreeArrays, compute_subtree
from kueue_tpu.ops.tree_encode import GroupLayout


def build_random(seed, big=False):
    rng = np.random.default_rng(seed)
    n_roots = rng.integers(1, 4)
    parent_l = []
    depth_l = []
    is_cq_l = []
    for r in range(n_roots):
        root = len(parent_l)
        parent_l.append(-1)
        depth_l.append(0)
        is_cq_l.append(False)
        mids = []
        for _ in range(rng.integers(0, 3)):
            mids.append(len(parent_l))
            parent_l.append(root)
            depth_l.append(1)
            is_cq_l.append(False)
        for _ in range(rng.integers(1, 5)):
            p = root if (not mids or rng.random() < 0.5) else int(
                rng.choice(mids)
            )
            parent_l.append(p)
            depth_l.append(depth_l[p] + 1)
            is_cq_l.append(True)
    # Lone CQs (their own group).
    for _ in range(rng.integers(0, 3)):
        parent_l.append(-1)
        depth_l.append(0)
        is_cq_l.append(True)
    parent = np.asarray(parent_l, np.int32)
    depth = np.asarray(depth_l, np.int32)
    is_cq = np.asarray(is_cq_l, bool)
    N = len(parent_l)
    height = np.zeros(N, np.int32)
    for i in range(N):
        d, p = 0, i
        while parent[p] >= 0:
            p = parent[p]
            d += 1
        # height = distance to deepest descendant; approximate as max chain
    for i in range(N):
        p, h = parent[i], 1
        while p >= 0:
            height[p] = max(height[p], h)
            p, h = parent[p], h + 1

    F = int(rng.integers(1, 4))
    R = int(rng.integers(1, 3))
    scale = (1 << 24) if big else 10
    nominal = np.zeros((N, F, R), np.int64)
    nominal[is_cq] = rng.integers(0, 20, (is_cq.sum(), F, R)) * scale
    CAPV = 1 << 62
    borrow = np.full((N, F, R), CAPV, np.int64)
    has_borrow = np.zeros((N, F, R), bool)
    lend = np.full((N, F, R), CAPV, np.int64)
    has_lend = np.zeros((N, F, R), bool)
    for i in range(N):
        if parent[i] >= 0 and rng.random() < 0.3:
            has_borrow[i] = True
            borrow[i] = rng.integers(0, 15, (F, R)) * scale
        if parent[i] >= 0 and rng.random() < 0.2:
            has_lend[i] = True
            lend[i] = np.minimum(
                rng.integers(0, 15, (F, R)) * scale, nominal[i]
            )
    tree = QuotaTreeArrays(
        parent=jnp.asarray(parent), active=jnp.ones(N, bool),
        depth=jnp.asarray(depth), height=jnp.asarray(height),
        nominal=jnp.asarray(nominal), borrow_limit=jnp.asarray(borrow),
        has_borrow_limit=jnp.asarray(has_borrow),
        lend_limit=jnp.asarray(lend), has_lend_limit=jnp.asarray(has_lend),
        subtree_quota=jnp.zeros((N, F, R), jnp.int64),
    )
    cq_usage = np.zeros((N, F, R), np.int64)
    cq_usage[is_cq] = rng.integers(0, 6, (is_cq.sum(), F, R)) * scale
    subtree, usage = compute_subtree(
        tree, jnp.asarray(cq_usage), jnp.asarray(is_cq)
    )
    tree = tree._replace(subtree_quota=subtree)

    W = int(rng.integers(20, 120))
    cq_ids = np.flatnonzero(is_cq)
    w_cq = rng.choice(cq_ids, W).astype(np.int32)
    w_req = (rng.integers(1, 8, (W, R)) * scale).astype(np.int64)
    w_prio = (rng.integers(0, 3, W) * 100).astype(np.int64)
    w_ts = np.arange(W, dtype=np.float64)
    w_elig = rng.random((W, F)) < 0.85
    flavor_at = np.tile(np.arange(F, dtype=np.int32), (N, 1))
    arrays = CycleArrays(
        tree=tree, usage=usage,
        flavor_at=jnp.asarray(flavor_at),
        n_flavors=jnp.full(N, F, jnp.int32),
        covered=jnp.asarray(rng.random((N, R)) < 0.95),
        when_can_borrow_try_next=jnp.asarray(rng.random(N) < 0.5),
        when_can_preempt_try_next=jnp.ones(N, bool),
        pref_preempt_over_borrow=jnp.zeros(N, bool),
        can_preempt_while_borrowing=jnp.zeros(N, bool),
        never_preempts=jnp.ones(N, bool),
        can_always_reclaim=jnp.asarray(rng.random(N) < 0.3),
        usage_by_prio=jnp.zeros((N, F, R, 8), jnp.int64),
        prio_cuts=jnp.full(8, (1 << 62), jnp.int64),
        prefilter_valid=jnp.asarray(False),
        policy_within=jnp.zeros(N, jnp.int32),
        policy_reclaim=jnp.zeros(N, jnp.int32),
        nominal_cq=tree.nominal,
        w_cq=jnp.asarray(w_cq),
        w_req=jnp.asarray(w_req),
        w_elig=jnp.asarray(w_elig),
        w_active=jnp.asarray(rng.random(W) < 0.95),
        w_priority=jnp.asarray(w_prio),
        w_timestamp=jnp.asarray(w_ts),
        w_quota_reserved=jnp.zeros(W, bool),
        w_start_flavor=jnp.zeros(W, np.int32),
        w_order_rank=jnp.asarray(_order_rank(w_prio, w_ts)),
    )
    layout = GroupLayout(parent, np.ones(N, bool))
    return arrays, layout


@pytest.mark.parametrize("i32", [False, True])
@pytest.mark.parametrize("seed", range(12))
def test_pallas_matches_grouped_scan(seed, i32):
    arrays, layout = build_random(seed)
    assert fits_int32(arrays)
    ga = bs.GroupArrays(*layout.as_jax())
    n_levels = int(np.asarray(arrays.tree.depth).max()) + 1
    group_of = np.asarray(layout.flat_to_group)[np.asarray(arrays.w_cq)]
    s_exact = int(
        np.bincount(group_of, minlength=layout.n_groups).max()
    )
    ref = bs.make_grouped_cycle(s_exact, n_levels=n_levels)(arrays, ga)
    out = make_pallas_cycle(
        s_exact, n_levels=n_levels, interpret=True, i32=i32
    )(arrays, ga)
    np.testing.assert_array_equal(
        np.asarray(ref.outcome), np.asarray(out.outcome)
    )
    np.testing.assert_array_equal(
        np.asarray(ref.chosen_flavor), np.asarray(out.chosen_flavor)
    )
    np.testing.assert_array_equal(
        np.asarray(ref.usage), np.asarray(out.usage)
    )


def test_fits_int32_rejects_oversized():
    arrays, _ = build_random(0, big=True)
    # 2**24-scale quantities x many workloads overflow the int32 budget.
    big_req = arrays.w_req * (1 << 12)
    arrays = arrays._replace(w_req=big_req)
    assert not fits_int32(arrays)
