"""Differentials for the fair fixed-point rounds kernel.

``cycle_fair_fixedpoint`` reformulates the DRS tournament scan as
monotone-bounds rounds with an internal residual scan for trees the
bounds cannot settle (kueue_tpu/models/fair_fixedpoint.py). It must be
plane-for-plane bit-identical to ``cycle_fair_preempt`` on every cycle —
these tests capture the exact (arrays, admitted) cycles the live driver
dispatches across randomized fair scenarios and replay both kernels.
The slot-layout half of the same PR routes multi-podset heads through
the hybrid's residual scan; those cycles are differentialed against the
grouped scan the same way. Non-convergence must be contained as
``solver_fallback_cycles_total{reason="fixedpoint_rounds"}`` before any
plane read, and the flight recorder must name the deciding kernel.
"""

import random
from typing import Dict

import numpy as np
import pytest

from kueue_tpu.api.constants import PreemptionPolicy
from kueue_tpu.api.types import (
    ClusterQueuePreemption,
    Cohort,
    ResourceQuota,
)
from kueue_tpu.models import batch_scheduler as bs
from kueue_tpu.models import fair_fixedpoint as ffp
from kueue_tpu.models import fair_kernel as fkm
from kueue_tpu.models.driver import DeviceScheduler
from kueue_tpu.obs import recorder as flight
from kueue_tpu.perf import compile_cache

from .helpers import build_env, make_cq, make_wl, submit
from .test_device_multislot import random_scenario as multislot_scenario

pytestmark = pytest.mark.isolated

# Planes that define a cycle's decision set. ``order`` and ``win_step``
# style diagnostics are deliberately excluded: the rounds settle whole
# trees at once, so step numbering differs while every decision (and the
# post-cycle tree state) is identical.
FAIR_PLANES = (
    "outcome", "chosen_flavor", "borrow", "tried_flavor_idx", "usage",
    "victims", "victim_variant",
)
SLOT_PLANES = FAIR_PLANES + ("s_flavor", "s_pmode", "s_tried")


def _assert_planes(out_ref, out_new, planes, ctx):
    for p in planes:
        x, y = getattr(out_ref, p), getattr(out_new, p)
        if x is None or y is None:
            assert x is None and y is None, (ctx, p)
            continue
        np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y), err_msg=f"{ctx} plane {p}"
        )


def _capture(entry, run):
    """Run ``run()`` with a dispatch spy and return the (args, s_max)
    captured for ``entry``."""
    captured = []
    orig = compile_cache.dispatch

    def spy(name, fn, *a, **kw):
        if name == entry:
            captured.append((a, kw.get("static", ())))
        return orig(name, fn, *a, **kw)

    compile_cache.dispatch = spy
    try:
        run()
    finally:
        compile_cache.dispatch = orig
    return captured


# ---------------------------------------------------------------------------
# Randomized fair differentials (>=100 captured cycles).
# ---------------------------------------------------------------------------


def _lending_scenario(rng):
    n_cqs = rng.randint(2, 4)
    cqs = []
    for i in range(n_cqs):
        ll = rng.choice([None, rng.randrange(0, 5) * 1000])
        cqs.append(make_cq(
            f"cq{i}", cohort="co",
            flavors={"default": {"cpu": ResourceQuota(
                nominal=rng.randrange(0, 8) * 1000,
                borrowing_limit=rng.choice(
                    [None, rng.randrange(0, 6) * 1000]
                ),
                lending_limit=ll,
            )}},
            fair_weight=rng.choice([None, 0.5, 2.0]),
        ))
    wls = []
    for i in range(rng.randint(4, 12)):
        wls.append(make_wl(
            f"w{i}", f"lq-cq{rng.randrange(n_cqs)}",
            cpu_m=rng.randint(1, 8) * 1000,
            priority=rng.choice([0, 0, 100]),
            creation_time=float(i + 1),
        ))
    return [Cohort(name="co")], cqs, wls


def _preempt_scenario(rng):
    cohorts = [Cohort(name="co")]
    n_cqs = rng.randint(2, 4)
    cqs = []
    for i in range(n_cqs):
        preemption = None
        if rng.random() < 0.5:
            preemption = ClusterQueuePreemption(
                within_cluster_queue=rng.choice(
                    [PreemptionPolicy.NEVER, PreemptionPolicy.LOWER_PRIORITY]
                ),
                reclaim_within_cohort=rng.choice(
                    [PreemptionPolicy.NEVER, PreemptionPolicy.ANY]
                ),
            )
        cqs.append(make_cq(
            f"cq{i}", cohort="co",
            flavors={"default": {"cpu": ResourceQuota(
                nominal=rng.randint(0, 10) * 1000,
                borrowing_limit=rng.choice(
                    [None, rng.randint(0, 8) * 1000]
                ),
            )}},
            preemption=preemption,
            fair_weight=rng.choice([None, 0.5, 1.0, 2.0]),
        ))
    wls = []
    for i in range(rng.randint(4, 12)):
        wls.append(make_wl(
            f"w{i}", f"lq-cq{rng.randrange(n_cqs)}",
            cpu_m=rng.randint(1, 9) * 1000,
            priority=rng.choice([0, 0, 100]),
            creation_time=float(i + 1),
        ))
    return cohorts, cqs, wls


def _fair_cycles_for_seed(seed):
    rng = random.Random(88_000 + seed)
    maker = _preempt_scenario if seed % 2 else _lending_scenario
    cohorts, cqs, wls = maker(rng)
    cache, queues, _host = build_env(
        cqs, cohorts=cohorts, fair_sharing=True
    )
    sched = DeviceScheduler(cache, queues, fair_sharing=True)

    def run():
        submit(queues, *wls)
        sched.schedule_all(max_cycles=40)

    return _capture("cycle_fair_preempt", run)


def test_fair_rounds_differential_random():
    """>=100 live-captured fair cycles: the fixed-point rounds kernel
    must be plane-for-plane identical to the tournament scan, converge,
    and stay within the probe-scale rounds budget (<= 8)."""
    total = 0
    rounds_max = 0
    for seed in range(24):
        for (args, static) in _fair_cycles_for_seed(seed):
            arrays, adm = args
            s_max = static[1] if static else int(arrays.w_cq.shape[0])
            out_s = fkm.fair_cycle_preempt_for(s_max)(arrays, adm)
            out_f = ffp.fair_fixedpoint_cycle_for(s_max)(arrays, adm)
            _assert_planes(out_s, out_f, FAIR_PLANES, f"seed {seed}")
            assert bool(np.asarray(out_f.converged)), seed
            rounds_max = max(rounds_max, int(np.asarray(out_f.fp_rounds)))
            total += 1
        if total >= 120:
            break
    assert total >= 100, f"only {total} fair cycles captured"
    assert rounds_max <= 8, rounds_max


def test_fair_end_state_matches_host_forced_fp():
    """End-to-end: autoCpuKernel=fixedpoint (fair rounds live, host
    fallback forbidden) reproduces the host trace on random scenarios."""
    for seed in (1, 2, 5, 8):
        rng = random.Random(88_000 + seed)
        maker = _preempt_scenario if seed % 2 else _lending_scenario
        state = rng.getstate()

        def run(device):
            rng.setstate(state)
            cohorts, cqs, wls = maker(rng)
            cache, queues, host = build_env(
                cqs, cohorts=cohorts, fair_sharing=True
            )
            sched = (
                DeviceScheduler(
                    cache, queues, fair_sharing=True,
                    device_kernel="auto", auto_cpu_kernel="fixedpoint",
                )
                if device else host
            )
            submit(queues, *wls)
            trace = []
            for _ in range(40):
                r = sched.schedule()
                trace.append((
                    sorted(r.admitted), sorted(r.preempted),
                    sorted(r.preempting),
                ))
                if not r.admitted and not r.preempted and not r.preempting:
                    break
            admitted = sorted(
                i.obj.name for i in cache.workloads.values()
            )
            return admitted, trace

        assert run(False) == run(True), seed


# ---------------------------------------------------------------------------
# Multislot differentials: slot-layout heads through the hybrid residual.
# ---------------------------------------------------------------------------


def test_multislot_hybrid_differential_random():
    """Slot-layout cycles captured from the live grouped-scan driver are
    replayed through the hybrid fixed-point kernel (slot trees go to its
    residual scan): identical planes whenever the rounds converge."""
    total = 0
    slot_cycles = 0
    for seed in range(10):
        flavor_specs, cohorts, cqs, workloads = multislot_scenario(seed)
        cache, queues, _host = build_env(
            cqs, cohorts=cohorts, flavors=flavor_specs
        )
        sched = DeviceScheduler(cache, queues)

        def run():
            submit(queues, *workloads)
            sched.schedule_all(max_cycles=40)

        for (args, _static) in _capture("cycle_grouped_preempt", run):
            arrays, ga, adm = args
            if arrays.tas_topo is not None:
                continue
            s_b = max(4, int(arrays.w_cq.shape[0]))
            out_s = bs.cycle_grouped_preempt(arrays, ga, adm)
            out_h = bs.fixedpoint_cycle_preempt_for(s_b, 32)(
                arrays, ga, adm
            )
            assert bool(np.asarray(out_h.converged)), seed
            _assert_planes(out_s, out_h, SLOT_PLANES, f"seed {seed}")
            total += 1
            if arrays.s_req is not None:
                slot_cycles += 1
        if total >= 40 and slot_cycles >= 10:
            break
    assert total >= 25, f"only {total} multislot cycles captured"
    assert slot_cycles >= 5, f"only {slot_cycles} slot-layout cycles"


# ---------------------------------------------------------------------------
# Containment: rounds-cap exhaustion must never surface bad planes.
# ---------------------------------------------------------------------------


def _contended_env():
    """Two CQs whose heads are order-dependent: each fits alone by
    borrowing the whole cohort pool, both together never fit — the
    monotone bounds cannot settle either, so both trees' decisions ride
    on the residual scan."""
    cqs = [
        make_cq(
            name, cohort="co",
            flavors={"default": {"cpu": ResourceQuota(
                nominal=4_000, borrowing_limit=4_000,
            )}},
        )
        for name in ("cq-a", "cq-b")
    ]
    cache, queues, host = build_env(
        cqs, cohorts=[Cohort(name="co")], fair_sharing=True
    )
    wa = make_wl("wa", "lq-cq-a", cpu_m=6_000, creation_time=1.0)
    wb = make_wl("wb", "lq-cq-b", cpu_m=6_000, creation_time=2.0)
    return cache, queues, wa, wb


def test_rounds_exhaustion_contained(monkeypatch):
    """A fair fixed-point run whose residual budget is exhausted reports
    converged=False; the driver contains it as a fixedpoint_rounds fault
    before reading any plane and the host path finishes the cycle."""
    starved = ffp.fair_fixedpoint_cycle_for(1)
    monkeypatch.setattr(
        ffp, "fair_fixedpoint_cycle_for", lambda s_max: starved
    )
    cache, queues, wa, wb = _contended_env()
    sched = DeviceScheduler(
        cache, queues, fair_sharing=True,
        device_kernel="auto", auto_cpu_kernel="fixedpoint",
    )
    submit(queues, wa, wb)
    faults = []
    for _ in range(6):
        r = sched.schedule()
        if sched.last_fault is not None:
            faults.append(sched.last_fault[0])
        if not r.admitted and not r.preempted:
            break
    assert "fixedpoint_rounds" in faults, faults
    # Containment, not corruption: the host fallback still admits
    # exactly one of the two contenders.
    admitted = sorted(i.obj.name for i in cache.workloads.values())
    assert len(admitted) == 1, admitted


def test_rounds_exhaustion_kernel_level():
    """Same scenario at the kernel layer: with the residual capped at
    one step the rounds report converged=False (never an exception)."""
    cache, queues, wa, wb = _contended_env()
    sched = DeviceScheduler(cache, queues, fair_sharing=True)

    def run():
        submit(queues, wa, wb)
        sched.schedule()

    captured = _capture("cycle_fair_preempt", run)
    assert captured
    arrays, adm = captured[0][0]
    out = ffp.fair_fixedpoint_cycle_for(1)(arrays, adm)
    assert not bool(np.asarray(out.converged))
    # With the real budget the same cycle settles exactly.
    s_max = captured[0][1][1]
    out_ok = ffp.fair_fixedpoint_cycle_for(s_max)(arrays, adm)
    assert bool(np.asarray(out_ok.converged))
    out_s = fkm.fair_cycle_preempt_for(s_max)(arrays, adm)
    _assert_planes(out_s, out_ok, FAIR_PLANES, "contended")


# ---------------------------------------------------------------------------
# Flight recorder: the deciding fair kernel (and auto reason) is named.
# ---------------------------------------------------------------------------


def test_flight_recorder_names_fair_kernel():
    prev = flight.ENABLED
    rec = flight.enable(capacity=64)
    rec.clear()
    try:
        cache, queues, wa, wb = _contended_env()
        sched = DeviceScheduler(
            cache, queues, fair_sharing=True, device_kernel="auto",
        )
        submit(queues, wa, wb)
        sched.schedule_all(max_cycles=6)
        kernels = {r.kernel for r in rec.records() if r.path == "device"}
        assert kernels == {"cycle_fair_preempt[auto-cpu-scan]"}, kernels

        rec.clear()
        cache, queues, wa, wb = _contended_env()
        sched = DeviceScheduler(
            cache, queues, fair_sharing=True, device_kernel="auto",
            auto_cpu_kernel="fixedpoint",
        )
        submit(queues, wa, wb)
        sched.schedule_all(max_cycles=6)
        kernels = {r.kernel for r in rec.records() if r.path == "device"}
        assert kernels == {"cycle_fair_fixedpoint[auto-cpu-fp]"}, kernels
    finally:
        if prev:
            flight.enable()
        else:
            flight.disable()


# ---------------------------------------------------------------------------
# What-if forecasts pick the fair rounds kernel on fair managers.
# ---------------------------------------------------------------------------


def test_whatif_uses_fair_kernel():
    from kueue_tpu.manager import Manager

    mgr = Manager(fair_sharing=True)
    assert mgr.whatif().kernel == "fair_fixedpoint"
    mgr = Manager()
    assert mgr.whatif().kernel == "fixedpoint"
