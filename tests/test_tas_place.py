"""Differential tests: device TAS placement kernel vs the host engine.

Random topologies / usage / placement requests across required, preferred
(walk-up + top gather), unconstrained and outer-slice-constraint modes; the
device kernel (ops/tas_place.py) must agree with
tas/snapshot.find_topology_assignment on feasibility AND produce the exact
same per-leaf pod counts.
"""

import random

import jax.numpy as jnp
import numpy as np
import pytest

from kueue_tpu.api.types import Topology
from kueue_tpu.ops.tas_place import encode_device_topos, place
from kueue_tpu.tas.snapshot import Node, PlacementRequest, TASFlavorSnapshot

LEVELS3 = ["block", "rack", "kubernetes.io/hostname"]


def random_topology(rng: random.Random):
    n_levels = rng.randint(2, 3)
    levels = LEVELS3[-n_levels:] if rng.random() < 0.5 else \
        LEVELS3[:n_levels]
    if levels[-1] != "kubernetes.io/hostname":
        levels = levels[:-1] + ["kubernetes.io/hostname"]
    topo = Topology(name="t", levels=levels)
    nodes = []
    n_blocks = rng.randint(1, 3)
    for b in range(n_blocks):
        for r in range(rng.randint(1, 3)):
            for h in range(rng.randint(1, 4)):
                labels = {}
                if len(levels) >= 2:
                    labels[levels[0]] = f"b{b}"
                if len(levels) == 3:
                    labels[levels[1]] = f"b{b}-r{r}"
                cap = {
                    "tpu": rng.choice([0, 4, 8, 16]),
                    "memory": rng.choice([0, 1000, 4000]),
                }
                nodes.append(Node(
                    name=f"n-{b}-{r}-{h}", labels=labels, capacity=cap,
                ))
    return topo, nodes


def random_request(rng: random.Random, levels):
    count = rng.choice([1, 2, 3, 4, 6, 8, 12])
    mode = rng.choice(["required", "preferred", "unconstrained"])
    level = rng.choice(levels)
    req = PlacementRequest(
        count=count,
        single_pod_requests={
            "tpu": rng.choice([1, 2, 4]),
            **({"memory": rng.choice([100, 500])}
               if rng.random() < 0.5 else {}),
        },
        required_level=level if mode == "required" else None,
        preferred_level=level if mode == "preferred" else None,
        unconstrained=mode == "unconstrained",
    )
    # Outer slice constraint: pin slices of the gang under a deeper level.
    if rng.random() < 0.4:
        level_idx = levels.index(level) if level in levels else 0
        deeper = [lv for i, lv in enumerate(levels) if i >= level_idx]
        slice_level = rng.choice(deeper)
        for ss in (2, 3, 4, 1):
            if count % ss == 0:
                break
        req.slice_size = ss
        req.slice_required_level = slice_level
    return req


def random_usage(rng: random.Random, tas: TASFlavorSnapshot):
    usage = {}
    for leaf in tas.leaves:
        if rng.random() < 0.4:
            usage[leaf.id] = {
                "tpu": rng.choice([1, 2, 4, 8]),
                "memory": rng.choice([0, 500, 1000]),
            }
    return usage


@pytest.mark.parametrize("seed", range(80))
def test_place_with_leader_matches_host(seed):
    """LWS leader differential: the device kernel's with-leader planes,
    level search, gather pick and leader-aware greedy must reproduce the
    host's worker AND leader assignments (find_topology_assignment with
    leader_requests, reference tas_flavor_snapshot.go:963-1154)."""
    rng = random.Random(41000 + seed)
    topo_spec, nodes = random_topology(rng)
    tas = TASFlavorSnapshot(topo_spec, nodes)
    tas.usage = random_usage(rng, tas)
    req = random_request(rng, topo_spec.levels)
    req.leader_requests = {
        "tpu": rng.choice([1, 2, 4, 8]),
        **({"memory": rng.choice([100, 500, 2000])}
           if rng.random() < 0.5 else {}),
    }

    ta, leader_ta, reason = tas.find_topology_assignment(req)
    host_ok = not reason
    host_counts = {}
    host_leader = {}
    if host_ok:
        for values, cnt in ta.domains:
            leaf_id = tas._canonical_leaf_id("/".join(values))
            host_counts[leaf_id] = host_counts.get(leaf_id, 0) + cnt
        for values, cnt in leader_ta.domains:
            leaf_id = tas._canonical_leaf_id("/".join(values))
            host_leader[leaf_id] = host_leader.get(leaf_id, 0) + cnt

    resource_of = {"tpu": 0, "memory": 1}
    dev_topo, flavors, leaf_perms = encode_device_topos(
        {"f": tas}, ["f"], resource_of
    )
    d_n = dev_topo.leaf_cap.shape[1]
    leaf_usage = np.zeros((d_n, 3), np.int64)
    perm = leaf_perms[0]
    host_leaf_ids = [leaf.id for leaf in tas.leaves]
    for j, hi in enumerate(perm):
        used = tas.usage.get(host_leaf_ids[hi], {})
        leaf_usage[j, 0] = used.get("tpu", 0)
        leaf_usage[j, 1] = used.get("memory", 0)

    levels = topo_spec.levels
    level_key = req.required_level or req.preferred_level
    if req.unconstrained and level_key is None:
        level_key = levels[-1]
    req_level = levels.index(level_key)
    if req.slice_required_level is not None:
        slice_level = levels.index(req.slice_required_level)
        slice_size = req.slice_size
    else:
        slice_level = len(levels) - 1
        slice_size = 1

    feasible, leaf_take, leader_take = place(
        dev_topo, jnp.int32(0), jnp.asarray(leaf_usage),
        jnp.asarray([req.single_pod_requests.get("tpu", 0),
                     req.single_pod_requests.get("memory", 0), 1],
                    dtype=jnp.int64),
        jnp.int64(req.count), jnp.int64(slice_size),
        jnp.int32(slice_level), jnp.int32(req_level),
        jnp.asarray(req.required_level is not None),
        jnp.asarray(req.unconstrained),
        leader_req=jnp.asarray(
            [req.leader_requests.get("tpu", 0),
             req.leader_requests.get("memory", 0), 1], dtype=jnp.int64
        ),
    )
    feasible = bool(feasible)
    assert feasible == host_ok, (
        f"feasibility differs: host={host_ok} ({reason}) device={feasible}"
    )
    if host_ok:
        dev_counts = {}
        dev_leader = {}
        take = np.asarray(leaf_take)
        ltake = np.asarray(leader_take)
        for j, hi in enumerate(perm):
            if take[j]:
                dev_counts[host_leaf_ids[hi]] = int(take[j])
            if ltake[j]:
                dev_leader[host_leaf_ids[hi]] = 1
        assert dev_counts == host_counts, (
            f"placement differs:\n host={host_counts}\n dev ={dev_counts}"
        )
        assert dev_leader == host_leader, (
            f"leader differs:\n host={host_leader}\n dev ={dev_leader}"
        )


@pytest.mark.parametrize("seed", range(120))
def test_place_matches_host(seed):
    rng = random.Random(7000 + seed)
    topo_spec, nodes = random_topology(rng)
    tas = TASFlavorSnapshot(topo_spec, nodes)
    tas.usage = random_usage(rng, tas)
    req = random_request(rng, topo_spec.levels)

    ta, _leader, reason = tas.find_topology_assignment(req)
    host_ok = not reason
    host_counts = {}
    if host_ok:
        for values, cnt in ta.domains:
            leaf_id = "/".join(values) if len(values) > 1 or \
                not tas.lowest_is_node else values[0]
            leaf_id = tas._canonical_leaf_id("/".join(values))
            host_counts[leaf_id] = host_counts.get(leaf_id, 0) + cnt

    resource_of = {"tpu": 0, "memory": 1}
    dev_topo, flavors, leaf_perms = encode_device_topos(
        {"f": tas}, ["f"], resource_of
    )
    d_n = dev_topo.leaf_cap.shape[1]
    leaf_usage = np.zeros((d_n, 3), np.int64)  # + implicit pods column
    perm = leaf_perms[0]
    host_leaf_ids = [leaf.id for leaf in tas.leaves]
    for j, hi in enumerate(perm):
        used = tas.usage.get(host_leaf_ids[hi], {})
        leaf_usage[j, 0] = used.get("tpu", 0)
        leaf_usage[j, 1] = used.get("memory", 0)

    levels = topo_spec.levels
    level_key = req.required_level or req.preferred_level
    if req.unconstrained and level_key is None:
        level_key = levels[-1]
    req_level = levels.index(level_key)
    if req.slice_required_level is not None:
        slice_level = levels.index(req.slice_required_level)
        slice_size = req.slice_size
    else:
        slice_level = len(levels) - 1
        slice_size = 1

    feasible, leaf_take = place(
        dev_topo, jnp.int32(0), jnp.asarray(leaf_usage),
        jnp.asarray([req.single_pod_requests.get("tpu", 0),
                     req.single_pod_requests.get("memory", 0), 1],
                    dtype=jnp.int64),
        jnp.int64(req.count), jnp.int64(slice_size),
        jnp.int32(slice_level), jnp.int32(req_level),
        jnp.asarray(req.required_level is not None),
        jnp.asarray(req.unconstrained),
    )
    feasible = bool(feasible)
    assert feasible == host_ok, (
        f"feasibility differs: host={host_ok} ({reason}) device={feasible}"
    )
    if host_ok:
        dev_counts = {}
        take = np.asarray(leaf_take)
        for j, hi in enumerate(perm):
            if take[j]:
                dev_counts[host_leaf_ids[hi]] = int(take[j])
        assert dev_counts == host_counts, (
            f"placement differs:\n host={host_counts}\n dev ={dev_counts}"
        )
