"""Tiled streaming admission differential (models/driver.py tentpole).

The tiled dispatch mode streams pending heads through the bounded device
arena in fixed-width tiles, carrying quota usage and admitted deltas
across tiles through the arena's event stream: tile N+1 solves against
tile N's post-apply usage. These tests pin the tentpole claim — a tiled
cycle is BIT-IDENTICAL to the monolithic cycle — on randomized
scenarios where cohort trees straddle tile boundaries, preemption
victims land mid-stream, multi-podset TAS gangs share a fused topology
group, and injected per-tile faults reroute single tiles host-exact
without disturbing settled neighbours.

Tile widths here are tiny (3-5) so even small forests split into
several tiles; production widths (auto: 8192) change the packing, not
the math.
"""

import random

import pytest

from kueue_tpu.api.constants import PreemptionPolicy
from kueue_tpu.api.types import ClusterQueuePreemption, ResourceQuota
from kueue_tpu.models.driver import DeviceScheduler
from kueue_tpu.utils import faults

from .helpers import admitted_names, build_env, make_cq, make_wl, submit

# Compile-heavy: run in its own subprocess via tools/run_isolated.py.
pytestmark = pytest.mark.isolated

PREEMPT = ClusterQueuePreemption(
    reclaim_within_cohort=PreemptionPolicy.ANY,
    within_cluster_queue=PreemptionPolicy.LOWER_PRIORITY,
)


def build(seed):
    """Seeded forest: 3-5 cohorts x 2-3 CQs, borrowable quota, a first
    wave of mixed-priority workloads and a second wave of high-priority
    preemptors. Twin builds from the same seed are identical (explicit
    creation_time everywhere)."""
    rng = random.Random(61_000 + seed)
    cqs = []
    for c in range(rng.randint(3, 5)):
        for q in range(rng.randint(2, 3)):
            cqs.append(make_cq(
                f"cq{c}q{q}", cohort=f"co{c}",
                flavors={"default": {"cpu": ResourceQuota(
                    nominal=4000, borrowing_limit=6000)}},
                preemption=PREEMPT,
            ))
    cache, queues, _ = build_env(cqs)
    t = 0.0
    first, second = [], []
    for cq in cqs:
        for i in range(rng.randint(2, 4)):
            t += 1.0
            first.append(make_wl(
                f"{cq.name}-w{i}", queue=f"lq-{cq.name}",
                cpu_m=rng.choice([500, 1000, 2000, 3000]),
                priority=rng.choice([0, 0, 100]),
                creation_time=t,
            ))
        if rng.random() < 0.7:
            t += 1.0
            second.append(make_wl(
                f"{cq.name}-hi", queue=f"lq-{cq.name}",
                cpu_m=rng.choice([2000, 4000]), priority=200,
                creation_time=t,
            ))
    return cache, queues, first, second


def drive(sched, max_cycles=25):
    """Per-cycle (admitted, preempted, skipped) for up to max_cycles.
    Early exit only on true quiescence (two consecutive empty cycles) —
    some reclaim-vs-borrow seeds oscillate forever, and the differential
    claim is over the capped stream either way."""
    out = []
    idle = 0
    for _ in range(max_cycles):
        res = sched.schedule()
        out.append((
            tuple(sorted(res.admitted)),
            tuple(sorted(res.preempted)),
            tuple(sorted(res.skipped)),
        ))
        if res.admitted or res.preempted or res.head_keys:
            idle = 0
        else:
            idle += 1
            if idle >= 2:
                break
    return out


def run(seed, tile_width, fault_plan=None):
    cache, queues, first, second = build(seed)
    sched = DeviceScheduler(cache, queues, tile_width=tile_width)
    if fault_plan is not None:
        faults.install(fault_plan)
    try:
        submit(queues, *first)
        cycles = drive(sched)
        submit(queues, *second)  # preemptors arrive mid-stream
        cycles += drive(sched)
    finally:
        if fault_plan is not None:
            faults.clear()
    return cycles, admitted_names(cache), sched


@pytest.mark.parametrize("seed", range(6))
def test_tiled_matches_monolithic(seed):
    """Randomized forests with preemption: tiled (width 4 — trees of
    2-3 heads straddle every boundary) is bit-identical to monolithic,
    per cycle and in the final admitted set."""
    mono_cycles, mono_final, _ = run(seed, "off")
    tiled_cycles, tiled_final, sched = run(seed, 4)
    assert tiled_cycles == mono_cycles
    assert tiled_final == mono_final
    carry = sched._last_tile_carry
    assert carry is not None and carry.tiles >= 2
    assert carry.faulted_tiles == 0
    assert carry.peak_plane_bytes > 0


@pytest.mark.parametrize("seed", range(3))
def test_tiled_fault_containment_is_invisible(seed):
    """Per-tile faults (solver dispatch raising on a seeded schedule)
    reroute only the faulted tile through the host-exact path; the
    cycle stream still matches an UNFAULTED monolithic run exactly."""
    mono_cycles, mono_final, _ = run(seed, "off")
    plan = faults.FaultPlan(seed=seed)
    plan.add(faults.SOLVER_DISPATCH, mode="raise", rate=0.4, times=3)
    tiled_cycles, tiled_final, sched = run(seed, 4, fault_plan=plan)
    assert plan.counts[(faults.SOLVER_DISPATCH, "raise")] > 0
    assert sched.fault_fallback_cycles > 0
    assert tiled_cycles == mono_cycles
    assert tiled_final == mono_final


def test_tiled_snapshot_fault_falls_back_whole_cycle():
    """A fault in the shared pre-tile snapshot (before any tile runs)
    contains at cycle granularity and still matches monolithic."""
    mono_cycles, mono_final, _ = run(0, "off")
    plan = faults.FaultPlan(seed=0)
    plan.add(faults.CACHE_SNAPSHOT, mode="raise", rate=1.0, times=1)
    tiled_cycles, tiled_final, _ = run(0, 4, fault_plan=plan)
    assert plan.counts[(faults.CACHE_SNAPSHOT, "raise")] == 1
    assert tiled_cycles == mono_cycles
    assert tiled_final == mono_final


def test_tile_width_validation():
    cache, queues, *_ = build(0)
    for bad in (0, -3, True, "sometimes", 2.5):
        with pytest.raises(ValueError):
            DeviceScheduler(cache, queues, tile_width=bad)
    for ok in ("auto", "off", 1, 4096, "16"):
        DeviceScheduler(cache, queues, tile_width=ok)


def test_auto_mode_never_tiles_small_cycles():
    """tile_width='auto' leaves every existing deployment untouched:
    cycles at or below the auto threshold dispatch monolithically."""
    cache, queues, first, _second = build(1)
    sched = DeviceScheduler(cache, queues)  # default: auto
    submit(queues, *first)
    drive(sched)
    assert sched._last_tile_carry is None
    assert sched._resolve_tile_width(DeviceScheduler._TILE_AUTO_MIN) is None
    assert (sched._resolve_tile_width(DeviceScheduler._TILE_AUTO_MIN + 1)
            == DeviceScheduler._TILE_AUTO_WIDTH)


def test_tas_gangs_straddling_tile_boundaries():
    """Cohorts whose CQs share one device-encoded TAS flavor are FUSED
    into a single tile group (topology capacity is physical state the
    monolithic kernel arbitrates in one conflict pass); the remaining
    quota-only cohorts pack around them. Multi-podset TAS gangs ride
    the per-slot planes. Tiled == monolithic, including topology domain
    assignments."""
    from kueue_tpu.api.types import (
        LocalQueue,
        PodSet,
        ResourceFlavor,
        Topology,
        TopologyRequest,
        Workload,
        quota,
    )
    from kueue_tpu.manager import Manager
    from kueue_tpu.tas.snapshot import Node

    LVL = ["tpu.rack", "kubernetes.io/hostname"]

    def build_tas():
        mgr = Manager()
        objs = [
            ResourceFlavor(name="tpu-v5e", topology_name="topo"),
            Topology(name="topo", levels=LVL),
        ]
        # Two TAS cohorts sharing the flavor (fused group) + two plain
        # cohorts (packable around the fused group).
        for c in range(2):
            cq = make_cq(f"tas{c}", cohort=f"tco{c}",
                         flavors={"tpu-v5e": {"tpu": quota(64)}},
                         resources=["tpu"], preemption=PREEMPT)
            objs += [cq, LocalQueue(name=f"lq-tas{c}",
                                    cluster_queue=f"tas{c}")]
        for c in range(2):
            cq = make_cq(f"plain{c}", cohort=f"pco{c}",
                         flavors={"default": {"cpu": ResourceQuota(
                             nominal=6000)}},
                         preemption=PREEMPT)
            objs += [cq, LocalQueue(name=f"lq-plain{c}",
                                    cluster_queue=f"plain{c}")]
        mgr.apply(*objs)
        for r in range(2):
            for h in range(2):
                mgr.apply(Node(
                    name=f"n{r}{h}", labels={"tpu.rack": f"r{r}"},
                    capacity={"tpu": 8},
                ))
        wls = []
        t = 0.0
        for c in range(2):
            for i in range(3):
                t += 1.0
                tr = TopologyRequest(required_level="tpu.rack")
                wls.append(Workload(
                    name=f"gang{c}-{i}", queue_name=f"lq-tas{c}",
                    pod_sets=[
                        PodSet(name="lead", count=1,
                               requests={"tpu": 1},
                               topology_request=tr),
                        PodSet(name="work", count=2 + i,
                               requests={"tpu": 2},
                               topology_request=TopologyRequest(
                                   required_level="tpu.rack")),
                    ],
                    priority=(i % 2) * 100,
                    creation_time=t,
                ))
        for c in range(2):
            for i in range(3):
                t += 1.0
                wls.append(make_wl(
                    f"job{c}-{i}", queue=f"lq-plain{c}",
                    cpu_m=2500, priority=(i % 3) * 100,
                    creation_time=t,
                ))
        return mgr, wls

    def state_of(mgr, wls):
        out = {}
        for wl in wls:
            adm = wl.status.admission
            if adm is None:
                out[wl.name] = None
            else:
                out[wl.name] = [
                    (sorted(psa.flavors.items()),
                     sorted(psa.topology_assignment.domains)
                     if psa.topology_assignment else None)
                    for psa in adm.pod_set_assignments
                ]
        return out

    def run_tas(tile_width):
        mgr, wls = build_tas()
        sched = DeviceScheduler(mgr.cache, mgr.queues,
                                tile_width=tile_width)
        for wl in wls:
            mgr.create_workload(wl)
        drive(sched)
        return state_of(mgr, wls), sched

    mono, _ = run_tas("off")
    tiled, sched = run_tas(3)
    assert tiled == mono
    carry = sched._last_tile_carry
    assert carry is not None and carry.tiles >= 2
