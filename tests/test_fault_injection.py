"""Fault containment: injected device/remote failures must never change
admission outcomes or crash the loop.

Three layers under test (ISSUE 3 tentpole):

- the injection framework itself (``utils/faults.py``): deterministic
  seeded schedules, rate/times gating, plane corruption on copies;
- per-cycle containment in ``models/driver.py``: randomized fault
  schedules (solver raise, corrupted readback planes) asserting admission
  outcomes bit-identical to a fault-free host-only run, plus breaker
  trip / re-probe / arena-reset transitions;
- transport deadlines + breaker on the remote clients: drops at up to
  20% rate are absorbed by retries, a dead worker trips to fast-fail,
  an op-level error does NOT count as a transport failure.

Plus a zero-overhead test pinning the faults-disabled hot path (same
pattern as the tracing zero-cost test: every production call site is
guarded by ``if faults.ENABLED:``).
"""

from __future__ import annotations

import os
import re
import time

import numpy as np
import pytest

from kueue_tpu.models.driver import DeviceScheduler, PlaneValidationError
from kueue_tpu.utils import faults
from kueue_tpu.utils.breaker import (
    CLOSED,
    HALF_OPEN,
    OPEN,
    CircuitBreaker,
)

from .helpers import build_env, make_cq, make_wl, submit
from .test_device_differential import random_scenario, run_host


@pytest.fixture(autouse=True)
def _clear_faults():
    yield
    faults.clear()


# ---------------------------------------------------------------------------
# Framework unit tests


def test_plan_rejects_unknown_point_and_mode():
    plan = faults.FaultPlan()
    with pytest.raises(ValueError):
        plan.add("not.a.point")
    with pytest.raises(ValueError):
        plan.add(faults.SOLVER_DISPATCH, mode="explode")


def test_fire_respects_times_and_counts():
    plan = faults.FaultPlan(seed=1)
    plan.add(faults.SOLVER_DISPATCH, mode="raise", times=2)
    faults.install(plan)
    for _ in range(2):
        with pytest.raises(faults.InjectedFault):
            faults.fire(faults.SOLVER_DISPATCH)
    faults.fire(faults.SOLVER_DISPATCH)  # spent: no raise
    assert plan.fired(faults.SOLVER_DISPATCH) == 2
    assert plan.evaluated[faults.SOLVER_DISPATCH] == 3


def test_fire_rate_is_deterministic_per_seed():
    def fire_pattern(seed):
        plan = faults.FaultPlan(seed=seed)
        plan.add(faults.CACHE_SNAPSHOT, mode="raise", rate=0.3)
        faults.install(plan)
        pattern = []
        for _ in range(50):
            try:
                faults.fire(faults.CACHE_SNAPSHOT)
                pattern.append(0)
            except faults.InjectedFault:
                pattern.append(1)
        faults.clear()
        return pattern

    assert fire_pattern(7) == fire_pattern(7)
    assert fire_pattern(7) != fire_pattern(8)


def test_custom_exception_class():
    plan = faults.FaultPlan()
    plan.add(faults.REMOTE_TRANSPORT, mode="raise", exc=ConnectionError)
    faults.install(plan)
    with pytest.raises(ConnectionError):
        faults.fire(faults.REMOTE_TRANSPORT)


def test_delay_mode_sleeps():
    plan = faults.FaultPlan()
    plan.add(faults.REMOTE_DISPATCH, mode="delay", delay_s=0.05)
    faults.install(plan)
    t0 = time.perf_counter()
    faults.fire(faults.REMOTE_DISPATCH)
    assert time.perf_counter() - t0 >= 0.05


def test_corrupt_plane_copies_and_filters():
    plan = faults.FaultPlan(seed=3)
    plan.add(faults.DEVICE_READBACK, mode="corrupt", planes=("outcome",))
    faults.install(plan)
    original = np.arange(64, dtype=np.int32)
    keep = original.copy()
    out = faults.corrupt_plane(faults.DEVICE_READBACK, "outcome", original)
    assert (original == keep).all(), "caller's array must not be mutated"
    assert not (out == keep).all(), "returned copy must be corrupted"
    other = faults.corrupt_plane(faults.DEVICE_READBACK, "tried", original)
    assert other is original, "plane filter must pass other planes through"
    assert faults.corrupt_plane(faults.DEVICE_READBACK, "outcome",
                                None) is None


def test_default_corrupter_is_out_of_domain():
    rng = __import__("random").Random(0)
    floats = faults.default_corrupt(rng, "x", np.zeros(16, np.float32))
    assert np.isnan(floats).any()
    bools = faults.default_corrupt(rng, "x", np.ones(16, bool))
    assert not bools.any()
    ints = faults.default_corrupt(rng, "x", np.zeros(16, np.int32))
    assert (np.abs(ints) >= (1 << 20)).any()


# ---------------------------------------------------------------------------
# Zero overhead when disabled


def test_faults_disabled_by_default_and_call_sites_guarded():
    """The production contract: ``faults.ENABLED`` is False unless a plan
    is installed, and every production ``faults.fire`` /
    ``faults.corrupt_plane`` call site sits under an ``if faults.ENABLED``
    guard (or inside a helper that is itself only reached under one) — so
    the disabled hot path pays one module-attribute read and nothing
    else. Same pattern as the tracing zero-cost test."""
    assert faults.ENABLED is False
    assert faults.active_plan() is None

    pkg_root = os.path.join(os.path.dirname(__file__), "..", "kueue_tpu")
    offenders = []
    for dirpath, _dirs, files in os.walk(os.path.abspath(pkg_root)):
        for fn in files:
            if not fn.endswith(".py") or fn == "faults.py":
                continue
            path = os.path.join(dirpath, fn)
            src = open(path).read()
            if "faults." not in src:
                continue
            lines = src.splitlines()
            for i, line in enumerate(lines):
                if not re.search(r"faults\.(fire|corrupt_plane)\(", line):
                    continue
                indent = len(line) - len(line.lstrip())
                guarded = False
                for j in range(i - 1, max(-1, i - 40), -1):
                    prev = lines[j]
                    if not prev.strip():
                        continue
                    p_ind = len(prev) - len(prev.lstrip())
                    if p_ind < indent:
                        if "if faults.ENABLED" in prev:
                            guarded = True
                        break
                # _read_planes runs its body unconditionally but is only
                # a readback helper; its internal sites still guard.
                if not guarded:
                    offenders.append(f"{path}:{i + 1}: {line.strip()}")
    assert not offenders, (
        "unguarded fault-injection call sites (wrap in `if "
        f"faults.ENABLED:`): {offenders}"
    )


def test_disabled_fire_is_noop():
    faults.clear()
    # No plan installed: fire() must return without side effects.
    faults.fire(faults.SOLVER_DISPATCH)
    arr = np.arange(4)
    assert faults.corrupt_plane(faults.DEVICE_READBACK, "outcome",
                                arr) is arr


# ---------------------------------------------------------------------------
# Circuit breaker unit tests


def test_breaker_trip_probe_reset_cycle():
    now = [0.0]
    br = CircuitBreaker(threshold=3, backoff_s=1.0, max_backoff_s=8.0,
                        clock=lambda: now[0])
    assert br.state == CLOSED and br.allow()
    br.record_failure()
    br.record_failure()
    assert br.state == CLOSED
    br.record_failure()  # third consecutive: trip
    assert br.state == OPEN and not br.allow()
    now[0] = 0.5
    assert not br.allow()
    now[0] = 1.1  # past backoff: one probe
    assert br.allow()
    assert br.state == HALF_OPEN
    assert not br.allow(), "only one probe in flight"
    br.record_failure()  # probe failed: re-open, backoff doubled
    assert br.state == OPEN
    assert br.last_backoff_s == 2.0
    now[0] = 1.1 + 2.0 + 0.01
    assert br.allow()
    br.record_success()  # probe succeeded: fully closed, backoff reset
    assert br.state == CLOSED and br.trips == 0
    # Next trip sequence starts from the base backoff again.
    for _ in range(3):
        br.record_failure()
    assert br.last_backoff_s == 1.0


def test_breaker_backoff_caps():
    now = [0.0]
    br = CircuitBreaker(threshold=1, backoff_s=1.0, max_backoff_s=4.0,
                        clock=lambda: now[0])
    for _ in range(6):
        # trip, wait out the backoff, fail the probe, repeat
        br.record_failure()
        now[0] += br.last_backoff_s + 0.01
        assert br.allow()
    assert br.last_backoff_s == 4.0


def test_breaker_success_resets_consecutive_count():
    br = CircuitBreaker(threshold=2)
    br.record_failure()
    br.record_success()
    br.record_failure()
    assert br.state == CLOSED, "non-consecutive failures must not trip"


# ---------------------------------------------------------------------------
# Plane validation unit tests


def _fake_idx(w=2, flavors=("f0", "f1"), admitted=()):
    from types import SimpleNamespace

    return SimpleNamespace(
        workloads=[SimpleNamespace() for _ in range(w)],
        flavors=list(flavors),
        admitted=list(admitted),
        slots=None,
    )


def _valid_planes(w=2):
    outcome = np.zeros(w, np.int32)  # OUT_NOFIT
    chosen = np.zeros(w, np.int32)
    tried = np.zeros(w, np.int32)
    return outcome, chosen, tried


def _validate(idx, outcome, chosen, tried, partial=None, victims=None,
              variants=None, s_flavor=None):
    DeviceScheduler._validate_planes(
        None, outcome, chosen, tried, partial, victims, variants,
        s_flavor, idx,
    )


def test_validate_accepts_clean_planes():
    idx = _fake_idx()
    _validate(idx, *_valid_planes())


@pytest.mark.parametrize("case,mutate", [
    ("outcome-domain", lambda o, c, t: o.__setitem__(0, 99)),
    ("outcome-domain", lambda o, c, t: o.__setitem__(1, -5)),
    ("tried-bounds", lambda o, c, t: t.__setitem__(0, 7)),
    ("tried-bounds", lambda o, c, t: t.__setitem__(1, -2)),
])
def test_validate_rejects_domain_garbage(case, mutate):
    idx = _fake_idx()
    outcome, chosen, tried = _valid_planes()
    mutate(outcome, chosen, tried)
    with pytest.raises(PlaneValidationError) as ei:
        _validate(idx, outcome, chosen, tried)
    assert ei.value.check == case


def test_validate_rejects_bad_admitted_flavor():
    from kueue_tpu.models import batch_scheduler as bs

    idx = _fake_idx()
    outcome, chosen, tried = _valid_planes()
    outcome[0] = bs.OUT_ADMITTED
    chosen[0] = 5  # only 2 flavors exist
    with pytest.raises(PlaneValidationError) as ei:
        _validate(idx, outcome, chosen, tried)
    assert ei.value.check == "flavor-bounds"


def test_validate_rejects_nan_and_truncated_planes():
    idx = _fake_idx()
    outcome, chosen, tried = _valid_planes()
    with pytest.raises(PlaneValidationError) as ei:
        _validate(idx, outcome, chosen, tried,
                  partial=np.array([np.nan, 0.0]))
    assert ei.value.check == "nan"
    with pytest.raises(PlaneValidationError) as ei:
        _validate(idx, np.zeros(1, np.int32), chosen, tried)
    assert ei.value.check == "shape"


def test_validate_rejects_empty_and_out_of_range_victims():
    from kueue_tpu.models import batch_scheduler as bs

    idx = _fake_idx(admitted=[object()])  # one admitted row
    outcome, chosen, tried = _valid_planes()
    outcome[0] = bs.OUT_PREEMPTING
    with pytest.raises(PlaneValidationError) as ei:
        _validate(idx, outcome, chosen, tried)
    assert ei.value.check == "victims-missing"
    victims = np.zeros((2, 3), bool)
    with pytest.raises(PlaneValidationError) as ei:
        _validate(idx, outcome, chosen, tried, victims=victims)
    assert ei.value.check == "victims-empty"
    victims[0, 2] = True  # index 2 >= 1 admitted row
    with pytest.raises(PlaneValidationError) as ei:
        _validate(idx, outcome, chosen, tried, victims=victims)
    assert ei.value.check == "victim-bounds"


# ---------------------------------------------------------------------------
# Driver containment differentials: faulty device run == fault-free host run


def run_device_with_faults(seed: int, plan: faults.FaultPlan):
    flavor_specs, cohorts, cqs, workloads = random_scenario(seed)
    cache, queues, _ = build_env(cqs, cohorts=cohorts,
                                 flavors=flavor_specs)
    dsched = DeviceScheduler(cache, queues)
    submit(queues, *workloads)
    faults.install(plan)
    try:
        dsched.schedule_all()
    finally:
        faults.clear()
    admissions = {}
    for key, info in cache.workloads.items():
        adm = info.obj.status.admission
        admissions[info.obj.name] = str(
            sorted(adm.pod_set_assignments[0].flavors.items())
        )
    return admissions, sorted(admissions), dsched


@pytest.mark.parametrize("seed", range(8))
def test_solver_raise_faults_keep_outcomes_bit_identical(seed):
    """20% dispatch raises (+ occasional snapshot/arena faults): contained
    cycles reroute through the host-exact path, so the final admitted set
    and flavor assignments match the fault-free host-only run exactly."""
    host_adm, host_names = run_host(seed)
    plan = faults.FaultPlan(seed=seed)
    plan.add(faults.SOLVER_DISPATCH, mode="raise", rate=0.2)
    plan.add(faults.CACHE_SNAPSHOT, mode="raise", rate=0.05)
    plan.add(faults.ARENA_DELTA_APPLY, mode="raise", rate=0.2)
    dev_adm, dev_names, dsched = run_device_with_faults(seed, plan)
    assert dev_names == host_names
    for name in host_names:
        assert dev_adm[name] == host_adm[name]


@pytest.mark.parametrize("seed", range(8))
def test_corrupted_readback_planes_keep_outcomes_bit_identical(seed):
    """Corrupt result planes at 20%: validation rejects out-of-domain
    garbage BEFORE any admission applies and the cycle replays host-side —
    outcomes stay bit-identical to the fault-free host run."""
    host_adm, host_names = run_host(seed)
    plan = faults.FaultPlan(seed=seed)
    plan.add(faults.DEVICE_READBACK, mode="corrupt", rate=0.2,
             planes=("outcome", "tried", "victims", "partial"))
    dev_adm, dev_names, dsched = run_device_with_faults(seed, plan)
    assert dev_names == host_names
    for name in host_names:
        assert dev_adm[name] == host_adm[name]


def test_corrupted_outcome_plane_is_caught_and_contained():
    """Deterministic corruption (rate 1.0, once): the validator must flag
    the plane, the fallback counter must tick, and outcomes must still
    match the host run."""
    seed = 3
    host_adm, host_names = run_host(seed)

    def smash_row0(rng, plane, a):
        # The default corrupter picks random indices, which can land
        # entirely on padded rows beyond the live W range (harmless by
        # design); pin the corruption to a live row so validation MUST
        # trip.
        a.flat[0] = 99
        return a

    plan = faults.FaultPlan(seed=seed)
    plan.add(faults.DEVICE_READBACK, mode="corrupt", times=1,
             planes=("outcome",), corrupt=smash_row0)
    dev_adm, dev_names, dsched = run_device_with_faults(seed, plan)
    assert plan.fired(faults.DEVICE_READBACK, "corrupt") == 1
    assert dsched.fault_fallback_cycles >= 1
    assert dsched.last_fault is not None
    assert dsched.last_fault[0] == "plane_validation"
    assert dev_names == host_names
    for name in host_names:
        assert dev_adm[name] == host_adm[name]


def test_assertion_errors_are_never_contained():
    """AssertionError is the verify-mode differential signal — containment
    must let it surface, not launder it into a host fallback."""
    cq = make_cq("cq0")
    cache, queues, _ = build_env([cq])
    ds = DeviceScheduler(cache, queues)
    plan = faults.FaultPlan()
    plan.add(faults.SOLVER_DISPATCH, mode="raise", exc=AssertionError)
    submit(queues, make_wl("wl0", queue="lq-cq0", cpu_m=100))
    faults.install(plan)
    with pytest.raises(AssertionError):
        ds.schedule()


def test_containment_off_reraises():
    cq = make_cq("cq0")
    cache, queues, _ = build_env([cq])
    ds = DeviceScheduler(cache, queues, containment=False)
    plan = faults.FaultPlan()
    plan.add(faults.SOLVER_DISPATCH, mode="raise")
    submit(queues, make_wl("wl0", queue="lq-cq0", cpu_m=100))
    faults.install(plan)
    with pytest.raises(faults.InjectedFault):
        ds.schedule()


def test_driver_breaker_trips_reroutes_and_reprobes():
    """K consecutive device failures trip the breaker to all-host cycles;
    past the backoff, one probe re-enters the device path and a success
    closes the breaker — with the arena re-captured from scratch."""
    now = [0.0]

    def clock():
        now[0] += 0.001
        return now[0]

    cq = make_cq("cq0")
    cache, queues, _ = build_env([cq])
    ds = DeviceScheduler(cache, queues, clock=clock, breaker_threshold=2,
                         breaker_backoff_s=10.0)
    plan = faults.FaultPlan()
    plan.add(faults.SOLVER_DISPATCH, mode="raise", times=2)
    faults.install(plan)

    for i in range(2):
        submit(queues, make_wl(f"wl{i}", queue="lq-cq0", cpu_m=100))
        ds.schedule()
    assert ds.fault_fallback_cycles == 2
    assert ds._breaker.state == OPEN
    # Workloads were admitted host-side despite the device failures.
    assert len(cache.workloads) == 2

    # Open breaker: the device path is not consulted at all.
    evaluated = plan.evaluated[faults.SOLVER_DISPATCH]
    submit(queues, make_wl("wl2", queue="lq-cq0", cpu_m=100))
    ds.schedule()
    assert plan.evaluated[faults.SOLVER_DISPATCH] == evaluated
    assert len(cache.workloads) == 3

    # Past the backoff the probe cycle runs the device path again (the
    # raise rule is spent, so it succeeds) and fully closes the breaker.
    now[0] += 10.0
    submit(queues, make_wl("wl3", queue="lq-cq0", cpu_m=100))
    ds.schedule()
    assert plan.evaluated[faults.SOLVER_DISPATCH] == evaluated + 1
    assert ds._breaker.state == CLOSED
    assert len(cache.workloads) == 4
    # The failure invalidated the arena: the probe cycle re-captured from
    # scratch (gate reason "cold"), not from stale device state.
    if ds._arena is not None:
        assert ds._arena.last_stats.get("path") == "full"
        assert ds._arena.last_stats.get("reason") == "cold"


def test_arena_invalidate_clears_committed_state():
    cq = make_cq("cq0")
    cache, queues, _ = build_env([cq])
    ds = DeviceScheduler(cache, queues)
    submit(queues, make_wl("wl0", queue="lq-cq0", cpu_m=100))
    ds.schedule()
    arena = ds._arena
    if arena is None:
        pytest.skip("arena disabled")
    arena.component_cache["sentinel"] = object()
    arena.invalidate("test")
    assert arena._committed is False
    assert arena._pending_events is None
    assert "sentinel" not in arena.component_cache
    assert arena.last_stats == {"path": "invalidated", "reason": "test"}


# ---------------------------------------------------------------------------
# Remote seam: transport drops, deadlines, breaker


def _worker_pair(tmp_path):
    from kueue_tpu.manager import Manager
    from kueue_tpu.remote import RemoteWorkerClient, serve_worker

    mgr = Manager()
    sock = str(tmp_path / "w.sock")
    server = serve_worker(mgr, sock)
    return mgr, server, sock, RemoteWorkerClient


def test_transport_drops_up_to_20pct_are_absorbed(tmp_path):
    """Injected connection drops at 20% per attempt: the retry/backoff
    machinery absorbs them and every logical op still completes."""
    mgr, server, sock, Client = _worker_pair(tmp_path)
    try:
        client = Client(sock, retries=5, backoff_s=0.001)
        plan = faults.FaultPlan(seed=11)
        plan.add(faults.REMOTE_TRANSPORT, mode="raise", rate=0.2,
                 exc=ConnectionError)
        faults.install(plan)
        from .helpers import make_wl

        for i in range(20):
            wl = make_wl(f"wl{i}", queue="lq", cpu_m=100)
            client.create_workload(wl)
            assert client.workloads.get(wl.key) is not None
        assert plan.fired(faults.REMOTE_TRANSPORT) > 0, (
            "the 20% drop schedule never fired — the test exercised "
            "nothing"
        )
        assert len(mgr.workloads) == 20
        assert client.breaker.state == CLOSED
    finally:
        faults.clear()
        server.shutdown()


def test_dead_worker_trips_breaker_to_fast_fail(tmp_path):
    from kueue_tpu.remote.client import RemoteWorkerClient, WorkerUnreachable

    now = [0.0]
    br = CircuitBreaker(threshold=2, backoff_s=5.0, clock=lambda: now[0])
    client = RemoteWorkerClient(str(tmp_path / "nope.sock"), retries=0,
                                backoff_s=0.001, breaker=br)
    for _ in range(2):
        with pytest.raises(WorkerUnreachable):
            client._call({"op": "ping"})
    assert br.state == OPEN
    # Fast-fail: no connect attempt is made while open.
    with pytest.raises(WorkerUnreachable, match="breaker open"):
        client._call({"op": "ping"})
    # Past the backoff a live worker closes the breaker again.
    from kueue_tpu.manager import Manager
    from kueue_tpu.remote import serve_worker

    server = serve_worker(Manager(), client.socket_path)
    try:
        now[0] = 5.1
        assert client.ping() is True
        assert br.state == CLOSED
    finally:
        server.shutdown()


def test_worker_op_error_is_not_a_transport_failure(tmp_path):
    """A raise injected in worker-side dispatch comes back as an error
    RESPONSE: the client surfaces RuntimeError but the transport breaker
    must stay closed (the worker is reachable)."""
    mgr, server, sock, Client = _worker_pair(tmp_path)
    try:
        client = Client(sock, retries=0)
        plan = faults.FaultPlan()
        plan.add(faults.REMOTE_DISPATCH, mode="raise", times=1)
        faults.install(plan)
        with pytest.raises(RuntimeError):
            client.schedule()
        assert client.breaker.state == CLOSED
        assert client.breaker.failures == 0
        faults.clear()
        client.schedule()  # worker healthy again
    finally:
        faults.clear()
        server.shutdown()


def test_slow_worker_hits_op_deadline(tmp_path):
    """A delay injected in worker dispatch beyond the client's op_timeout
    surfaces as WorkerUnreachable via the per-op socket deadline instead
    of wedging the caller."""
    from kueue_tpu.remote.client import WorkerUnreachable

    mgr, server, sock, Client = _worker_pair(tmp_path)
    try:
        client = Client(sock, retries=0, op_timeout=0.2,
                        connect_timeout=0.2)
        plan = faults.FaultPlan()
        plan.add(faults.REMOTE_DISPATCH, mode="delay", delay_s=1.0,
                 times=1)
        faults.install(plan)
        t0 = time.perf_counter()
        with pytest.raises(WorkerUnreachable):
            client._call({"op": "ping"})
        assert time.perf_counter() - t0 < 0.9
        assert client.breaker.failures == 1
    finally:
        faults.clear()
        server.shutdown()


def test_grpc_deadline_and_breaker(tmp_path):
    pytest.importorskip("grpc")
    from kueue_tpu.manager import Manager
    from kueue_tpu.remote.client import WorkerUnreachable
    from kueue_tpu.remote.grpc_transport import (
        GrpcWorkerClient,
        serve_worker_grpc,
    )

    server, bound = serve_worker_grpc(Manager(), "127.0.0.1:0")
    try:
        client = GrpcWorkerClient(bound, retries=0, op_timeout=0.2,
                                  connect_timeout=0.2)
        plan = faults.FaultPlan()
        plan.add(faults.REMOTE_DISPATCH, mode="delay", delay_s=1.0,
                 times=1)
        faults.install(plan)
        with pytest.raises(WorkerUnreachable):
            client._call({"op": "schedule"})
        assert client.breaker.failures == 1
        faults.clear()
        # The timed-out dispatch is still sleeping server-side holding the
        # dispatch lock; a generous deadline lets recovery queue behind it.
        client._call({"op": "ping"}, timeout=10.0)
        assert client.breaker.state == CLOSED
    finally:
        faults.clear()
        server.stop(0)
